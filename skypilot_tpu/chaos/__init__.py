"""Deterministic fault injection for the orchestration layer.

The paper's core promise is that orchestration survives the sky being
unreliable; this package makes that testable. Named injection points
(``chaos.point("provision.run_instances", zone=...)``) are threaded
through the provisioners, the RPC transport, the job queue, the skylet,
serve probes/load balancing, managed-job recovery, and checkpointing
(catalog in :mod:`skypilot_tpu.chaos.plan`). A *fault plan* — JSON, with
a seed — schedules failures against those points: fail-N-times,
fail-with-probability under a seeded PRNG, inject-latency, standing
partitions, capacity stockouts scoped to a zone. The same plan + seed
reproduces the same injection sequence, and every fired fault lands as
a typed ``chaos.injected`` event in the structured event log, so a
trace of a chaos run shows exactly what was injected where.

Activation, in precedence order:

* programmatic — ``chaos.configure(plan_dict)`` (tests);
* ``SKYTPU_CHAOS_PLAN_JSON`` — inline JSON (how a plan crosses process
  boundaries: spawned controllers/skylets inherit the env);
* ``SKYTPU_CHAOS_PLAN`` — path to a plan file.

With no plan configured, ``chaos.point`` is a no-op costing one
attribute check — production paths pay nothing.

Stdlib-only (runtime modules import this under ``python -S``).
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import threading
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.chaos import plan as plan_lib
from skypilot_tpu.chaos.plan import (KNOWN_POINTS, FaultRule, Plan,
                                     load_plan_file, parse_plan,
                                     unknown_points)
from skypilot_tpu.observability import tracing

ENV_PLAN_JSON = "SKYTPU_CHAOS_PLAN_JSON"
ENV_PLAN = "SKYTPU_CHAOS_PLAN"

__all__ = ["ChaosError", "Injector", "KNOWN_POINTS", "FaultRule", "Plan",
           "active", "configure", "deactivate", "injector", "point",
           "load_plan_file", "parse_plan", "unknown_points"]


class ChaosError(exceptions.SkyTpuError):
    """Default injected failure (a rule may name any exception from
    ``skypilot_tpu.exceptions`` or the builtins instead — e.g.
    ``CapacityError`` for a zone stockout, ``ConnectionError`` for a
    partition the transport layer must absorb)."""


def _resolve_error(name: str):
    cls = getattr(exceptions, name, None)
    if isinstance(cls, type) and issubclass(cls, BaseException):
        return cls
    import builtins
    cls = getattr(builtins, name, None)
    if isinstance(cls, type) and issubclass(cls, BaseException):
        return cls
    return ChaosError


class Injector:
    """Runtime half of a plan: matches point hits against rules, fires
    effects, and keeps the bookkeeping tests assert against —
    ``observed`` (every hit per point, fault or not: the cheap way to
    assert "exactly one launch happened") and ``fired`` (the injection
    sequence, reproducible per seed)."""

    def __init__(self, plan: Plan):
        # Private rule copies: hits/fired are runtime counters, and a
        # caller re-running the SAME parsed Plan (the reproducibility
        # workflow) must start from zero, not inherit the last run's.
        self.plan = Plan(seed=plan.seed, rules=[
            dataclasses.replace(r, hits=0, fired=0) for r in plan.rules])
        self.rng = random.Random(plan.seed)
        self.fired: List[Dict[str, Any]] = []         # guarded-by: _lock
        self.observed: Dict[str, int] = {}            # guarded-by: _lock
        self.observations: List[Dict[str, Any]] = []  # guarded-by: _lock
        self._lock = threading.Lock()

    def point(self, name: str, ctx: Dict[str, Any]) -> None:
        sctx = {k: str(v) for k, v in ctx.items()}
        with self._lock:
            self.observed[name] = self.observed.get(name, 0) + 1
            self.observations.append({"point": name, "ctx": sctx})
            rule = self._select(name, sctx)
            if rule is None:
                return
            rule.fired += 1
            rec = {"seq": len(self.fired), "point": name, "ctx": sctx,
                   "effect": rule.effect(), "latency_s": rule.latency_s}
            self.fired.append(rec)
        tracing.add_event(
            "chaos.injected",
            attrs={"point": name, "effect": rec["effect"],
                   "seq": rec["seq"], **{f"ctx.{k}": v
                                         for k, v in sctx.items()}},
            echo=True)
        if rule.latency_s > 0:
            time.sleep(rule.latency_s)
        if rule.error is not None or rule.latency_s <= 0:
            err = _resolve_error(rule.error or "ChaosError")
            msg = rule.message or (
                f"[chaos] injected {rec['effect']} at {name} ({sctx})")
            raise err(msg)

    def _select(self, name: str, sctx: Dict[str, str]
                ) -> Optional[FaultRule]:
        """First rule that fires wins (plan order). The PRNG is drawn
        once per eligible probabilistic hit, in hit order — that is the
        whole determinism contract."""
        for rule in self.plan.rules:
            if rule.point != name:
                continue
            if any(sctx.get(k) != v for k, v in rule.match.items()):
                continue
            rule.hits += 1
            if rule.hits <= rule.after:
                continue
            if rule.times is not None and rule.fired >= rule.times:
                continue
            if (rule.probability is not None
                    and self.rng.random() >= rule.probability):
                continue
            return rule
        return None


# Lazily initialized: None = inactive, _UNSET = env not consulted yet.
_UNSET = object()
_injector: Any = _UNSET
_init_lock = threading.Lock()


def _get() -> Optional[Injector]:
    global _injector
    if _injector is _UNSET:
        with _init_lock:
            if _injector is _UNSET:
                _injector = _from_env()
    return _injector


def _from_env() -> Optional[Injector]:
    inline = os.environ.get(ENV_PLAN_JSON)
    path = os.environ.get(ENV_PLAN)
    try:
        if inline:
            return Injector(parse_plan(json.loads(inline)))
        if path:
            return Injector(load_plan_file(path))
    except (OSError, ValueError) as e:
        # A typo'd plan must not poison production paths: the first
        # chaos.point() sits inside broad handlers (probe loops, the
        # LB's failover) that would misread a ValueError as a component
        # failure. Disable injection and say so loudly (typed event,
        # echoed to stderr) — `skytpu chaos validate` is the preflight.
        tracing.add_event(
            "chaos.plan_invalid",
            attrs={"source": ENV_PLAN_JSON if inline else ENV_PLAN,
                   "error_type": type(e).__name__,
                   "message": str(e)[:500]},
            echo=True)
        return None
    return None


def point(name: str, **ctx: Any) -> None:
    """Declare a fault-injection point. No-op unless a plan is active;
    an active plan may sleep here (latency fault) or raise (failure
    fault) — call sites own surviving exactly the exceptions their
    layer claims to handle."""
    inj = _get()
    if inj is not None:
        inj.point(name, ctx)


def configure(plan: Any) -> Injector:
    """Install a plan programmatically (dict, or a parsed Plan).
    Replaces any active injector; returns the new one."""
    global _injector
    inj = Injector(plan if isinstance(plan, Plan) else parse_plan(plan))
    with _init_lock:
        _injector = inj
    return inj


def deactivate() -> None:
    """Remove the active injector AND stop consulting the env (tests
    that must run chaos-free call this; :func:`_reset_for_tests`
    restores lazy env activation)."""
    global _injector
    with _init_lock:
        _injector = None


def _reset_for_tests() -> None:
    global _injector
    with _init_lock:
        _injector = _UNSET


def active() -> bool:
    return _get() is not None


def injector() -> Optional[Injector]:
    """The live injector (tests read ``.fired`` / ``.observed``)."""
    return _get()
