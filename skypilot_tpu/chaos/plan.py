"""Fault-plan schema: the declarative half of the chaos layer.

A plan is JSON — a seed plus an ordered list of fault rules — so a
chaos run is a *reproducible artifact*: check the plan into a repo,
point ``SKYTPU_CHAOS_PLAN`` at it, and the same seed fires the same
faults in the same order (see ``docs/robustness.md`` for the full
schema and the injection-point catalog).

Stdlib-only: chaos points live inside head-side runtime modules that
run under ``python -S``.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, List, Optional

# Injection-point catalog: every chaos.point() call site in the tree.
# ``skytpu chaos points`` prints this; docs/robustness.md documents it;
# tests/test_chaos.py asserts the code and the catalog agree.
KNOWN_POINTS: Dict[str, str] = {
    "provision.run_instances":
        "instance create/resume, per provider attempt "
        "(ctx: provider, cluster, zone)",
    "provision.stop_instances":
        "instance stop (ctx: provider, cluster, zone)",
    "provision.terminate_instances":
        "instance teardown (ctx: provider, cluster, zone)",
    "provision.query_instances":
        "cloud-side status query (ctx: provider, cluster, zone)",
    "provision.wait_instances":
        "wait-until-ready poll (ctx: provider, cluster, zone)",
    "rpc.transport":
        "cluster RPC transport attempt, client side; ConnectionError "
        "faults ride the transport-failure retry path "
        "(ctx: method, cluster)",
    "jobs.transition":
        "cluster job-queue status write (ctx: status, job_id)",
    "jobs.recovery":
        "managed-job recovery relaunch (ctx: strategy, cluster)",
    "skylet.tick":
        "skylet poll-loop iteration (ctx: cluster)",
    "serve.probe":
        "replica readiness probe; a fault counts as one probe failure "
        "(ctx: service, replica)",
    "serve.lb.forward":
        "load-balancer forward attempt; a fault triggers replica "
        "failover (ctx: backend)",
    "qos.shed":
        "QoS admission decision at the model server and the load "
        "balancer; a fault forces a typed 429 shed for the request "
        "(ctx: tenant, where=server|lb)",
    "adapter.load":
        "adapter-catalog hot-load attempt (checkpoint fetch + device "
        "pool install); a transient fault retries via utils/retry, "
        "exhaustion fails the request typed adapter_load_failed — "
        "never a silent fall-through to the base model (ctx: adapter)",
    "engine.dispatch":
        "inference-engine device dispatch seam (admission wave, "
        "prefill chunk, decode burst, spec verify); a fault surfaces "
        "as a recoverable EngineDispatchError — the server resets the "
        "engine and re-admits every in-flight request through the "
        "preemption resume path, greedy output bit-identical "
        "(ctx: seam=admit|chunk|decode|verify)",
    "kv.alloc":
        "paged KV block allocation (admission claim, lazy per-burst "
        "growth); a fault rides the enclosing dispatch seam's "
        "recovery path (ctx: need)",
    "handoff.transfer":
        "disaggregated prefill->decode KV handoff, per decode-replica "
        "attempt at the load balancer; a fault simulates the decode "
        "replica dying mid-transfer — the export (held in LB memory) "
        "retries on a surviving decode replica, the prefill tier "
        "keeps its refcounted copy, zero requests lost and zero "
        "blocks leaked (ctx: backend)",
    "replica.kill":
        "model-server streaming response mid-flight; a fault drops "
        "the client connection with no terminal chunk — the replica "
        "looks SIGKILLed to the LB, which fails the stream over to a "
        "surviving replica (ctx: route)",
    "train.checkpoint_save":
        "checkpoint save dispatch (ctx: step)",
    "train.checkpoint_restore":
        "checkpoint restore (ctx: step)",
}


@dataclasses.dataclass
class FaultRule:
    """One fault schedule bound to an injection point.

    Selection: a point hit is *eligible* when ``point`` matches and
    every ``match`` key equals the point's context (stringified). The
    first ``after`` eligible hits pass through untouched; then the rule
    fires on each eligible hit — every time by default, with chance
    ``probability`` under the plan's seeded PRNG, at most ``times``
    total. Effect: sleep ``latency_s`` (if set), then raise ``error``
    (unless the rule is latency-only). A rule with neither ``times``
    nor ``probability`` is a standing fault — e.g. a network partition
    of one RPC target — active for the whole run.
    """

    point: str
    match: Dict[str, str] = dataclasses.field(default_factory=dict)
    times: Optional[int] = None       # max fires; None = unlimited
    after: int = 0                    # eligible hits to skip first
    probability: Optional[float] = None   # None = always fire
    latency_s: float = 0.0
    error: Optional[str] = None       # exception name; None + latency
                                      # = latency-only fault
    message: str = ""

    # runtime counters (not part of the schema)
    hits: int = 0
    fired: int = 0

    def effect(self) -> str:
        if self.error is None and self.latency_s > 0:
            return "latency"
        return self.error or "ChaosError"


@dataclasses.dataclass
class Plan:
    seed: int
    rules: List[FaultRule]


_RULE_FIELDS = {"point", "match", "times", "after", "probability",
                "latency_s", "error", "message"}


def parse_plan(raw: Any) -> Plan:
    """Validate a decoded plan dict into a :class:`Plan`; raises
    ``ValueError`` naming the offending rule/field (a typo'd plan must
    fail the run loudly, not silently inject nothing)."""
    if not isinstance(raw, dict):
        raise ValueError(f"chaos plan must be a JSON object, got "
                         f"{type(raw).__name__}")
    unknown_top = set(raw) - {"seed", "faults"}
    if unknown_top:
        raise ValueError(f"chaos plan: unknown keys {sorted(unknown_top)}")
    seed = raw.get("seed", 0)
    if not isinstance(seed, int):
        raise ValueError(f"chaos plan: seed must be an int, got {seed!r}")
    faults = raw.get("faults", [])
    if not isinstance(faults, list):
        raise ValueError("chaos plan: 'faults' must be a list of rules")
    rules: List[FaultRule] = []
    for i, r in enumerate(faults):
        where = f"faults[{i}]"
        if not isinstance(r, dict):
            raise ValueError(f"chaos plan: {where} must be an object")
        unknown = set(r) - _RULE_FIELDS
        if unknown:
            raise ValueError(
                f"chaos plan: {where}: unknown keys {sorted(unknown)}")
        point = r.get("point")
        if not point or not isinstance(point, str):
            raise ValueError(f"chaos plan: {where}: 'point' is required")
        match = r.get("match", {})
        if not isinstance(match, dict):
            raise ValueError(f"chaos plan: {where}: 'match' must be an "
                             f"object of context-key -> value")
        times = r.get("times")
        if times is not None and (not isinstance(times, int) or times < 0):
            raise ValueError(f"chaos plan: {where}: 'times' must be a "
                             f"non-negative int")
        after = r.get("after", 0)
        if not isinstance(after, int) or after < 0:
            raise ValueError(f"chaos plan: {where}: 'after' must be a "
                             f"non-negative int")
        prob = r.get("probability")
        if prob is not None and not (isinstance(prob, (int, float))
                                     and 0.0 <= prob <= 1.0):
            raise ValueError(f"chaos plan: {where}: 'probability' must "
                             f"be in [0, 1]")
        latency = r.get("latency_s", 0.0)
        if not isinstance(latency, (int, float)) or latency < 0:
            raise ValueError(f"chaos plan: {where}: 'latency_s' must be "
                             f"a non-negative number")
        error = r.get("error")
        if error is not None and not isinstance(error, str):
            raise ValueError(f"chaos plan: {where}: 'error' must be an "
                             f"exception class name")
        rules.append(FaultRule(
            point=point, match={k: str(v) for k, v in match.items()},
            times=times, after=after, probability=prob,
            latency_s=float(latency), error=error,
            message=str(r.get("message", ""))))
    return Plan(seed=seed, rules=rules)


def load_plan_file(path: str) -> Plan:
    with open(os.path.expanduser(path), encoding="utf-8") as f:
        return parse_plan(json.load(f))


def unknown_points(plan: Plan) -> List[str]:
    """Rule points absent from the catalog — allowed at runtime (a
    plan may predate a renamed point) but surfaced by ``skytpu chaos
    validate`` because they inject nothing."""
    return sorted({r.point for r in plan.rules
                   if r.point not in KNOWN_POINTS})
