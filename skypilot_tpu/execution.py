"""The launch/exec stage machine.

Reference parity: sky/execution.py (Stage enum :35, _execute:99 —
OPTIMIZE -> PROVISION -> SYNC_WORKDIR -> SYNC_FILE_MOUNTS -> SETUP ->
PRE_EXEC -> EXEC -> DOWN). Setup is folded into the job script (the
reference's detached-setup default), and PRE_EXEC autostop wiring is a
state-DB write consumed by the autostop event loop.
"""

from __future__ import annotations

import enum
import uuid
from typing import Optional, Tuple

from skypilot_tpu import exceptions, state
from skypilot_tpu.backend import ClusterHandle, TpuVmBackend
from skypilot_tpu.task import Task
from skypilot_tpu.usage import usage_lib
from skypilot_tpu.utils import timeline


class Stage(enum.Enum):
    OPTIMIZE = "OPTIMIZE"
    PROVISION = "PROVISION"
    SYNC_WORKDIR = "SYNC_WORKDIR"
    SYNC_FILE_MOUNTS = "SYNC_FILE_MOUNTS"
    PRE_EXEC = "PRE_EXEC"
    EXEC = "EXEC"
    DOWN = "DOWN"


def _generate_cluster_name() -> str:
    return f"sky-{uuid.uuid4().hex[:6]}"


@timeline.event
@usage_lib.entrypoint
def launch(task: Task,
           cluster_name: Optional[str] = None,
           retry_until_up: bool = False,
           idle_minutes_to_autostop: Optional[int] = None,
           down: bool = False,
           detach_run: bool = True,
           dryrun: bool = False) -> Tuple[Optional[int], Optional[ClusterHandle]]:
    """Provision (or reuse) a cluster and run the task on it."""
    cluster_name = cluster_name or _generate_cluster_name()

    # Org-level request mutation/validation hook (reference:
    # execution.py:180 admin_policy_utils.apply).
    from skypilot_tpu import admin_policy, config as config_lib
    task, mutated_config = admin_policy.apply(
        task, admin_policy.RequestOptions(
            cluster_name=cluster_name,
            idle_minutes_to_autostop=idle_minutes_to_autostop,
            down=down, dryrun=dryrun))

    with config_lib.replace_config(mutated_config), \
            config_lib.override_config(getattr(task, "config_overrides",
                                               None)):
        return _launch_with_config(
            task, cluster_name, retry_until_up, idle_minutes_to_autostop,
            down, detach_run, dryrun)


def _launch_with_config(task, cluster_name, retry_until_up,
                        idle_minutes_to_autostop, down, detach_run,
                        dryrun) -> Tuple[Optional[int], Optional[ClusterHandle]]:
    backend = TpuVmBackend()

    if dryrun:
        from skypilot_tpu import optimizer
        # quiet=False: print the reference-style plan comparison table
        # (sky/optimizer.py:717) alongside the decision.
        launchable = optimizer.optimize_task(task, quiet=False)
        print(f"Dryrun: would launch {cluster_name} with {launchable}")
        return None, None

    handle = backend.provision(task, cluster_name,
                               retry_until_up=retry_until_up)

    if task.workdir:
        backend.sync_workdir(handle, task.workdir)
    if task.file_mounts:
        backend.sync_file_mounts(handle, task.file_mounts)
    if task.storage_mounts:
        backend.sync_storage_mounts(handle, task.storage_mounts)

    if idle_minutes_to_autostop is not None:
        backend.set_autostop(handle, idle_minutes_to_autostop, down)
        state.set_autostop(cluster_name, idle_minutes_to_autostop, down)

    job_id = None
    if task.run is not None or task.setup is not None:
        job_id = backend.execute(handle, task, detach_run=detach_run)

    if down and idle_minutes_to_autostop is None:
        if job_id is not None:
            # No deadline: --down must tear down after the job however
            # long it runs.
            backend.wait_job(handle, job_id, timeout=float("inf"))
        backend.teardown(handle)
    return job_id, handle


@timeline.event
@usage_lib.entrypoint
def exec(task: Task,  # noqa: A001 — mirrors the public API name
         cluster_name: str,
         detach_run: bool = True) -> Tuple[int, ClusterHandle]:
    """Run a task on an existing cluster, skipping provisioning."""
    from skypilot_tpu import admin_policy, config as config_lib
    task, mutated_config = admin_policy.apply(
        task, admin_policy.RequestOptions(cluster_name=cluster_name))
    with config_lib.replace_config(mutated_config), \
            config_lib.override_config(getattr(task, "config_overrides",
                                               None)):
        return _exec_with_config(task, cluster_name, detach_run)


def _exec_with_config(task: Task, cluster_name: str,
                      detach_run: bool) -> Tuple[int, ClusterHandle]:
    from skypilot_tpu.backend import check_owner_identity
    check_owner_identity(cluster_name)
    rec = state.get_cluster(cluster_name)
    if rec is None:
        raise exceptions.ClusterNotUpError(
            f"cluster {cluster_name!r} does not exist; use launch")
    if rec["status"] != state.ClusterStatus.UP:
        raise exceptions.ClusterNotUpError(
            f"cluster {cluster_name!r} is {rec['status'].value}")
    backend = TpuVmBackend()
    handle = ClusterHandle(rec["handle"])
    backend.check_resources_fit(task, handle)
    if task.workdir:
        backend.sync_workdir(handle, task.workdir)
    job_id = backend.execute(handle, task, detach_run=detach_run)
    return job_id, handle
