"""Cloud credential checking + enabled-cloud cache.

Reference parity: sky/check.py (check:23 validates credentials per
cloud; get_cached_enabled_clouds_or_refresh:172 caches the enabled
list). Providers here are the provision modules; each may export
``check_credentials() -> (bool, str)``. The enabled set is cached in
``$SKYPILOT_TPU_HOME/enabled_clouds.json`` and consulted by the
optimizer via get_cached_enabled_clouds_or_refresh.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

from skypilot_tpu import exceptions
from skypilot_tpu.utils import paths

# Known providers, in display order. 'local' is the in-process fake
# cloud used by tests and demos; it is always credentialed.
CLOUDS = ("gcp", "aws", "azure", "kubernetes", "local")


def _cache_path() -> str:
    return os.path.join(paths.home(), "enabled_clouds.json")


def _check_one(cloud: str) -> Tuple[bool, str]:
    if cloud == "local":
        return True, "local fake cloud (always enabled)"
    if cloud == "gcp":
        from skypilot_tpu.provision import gcp_auth
        return gcp_auth.check_credentials()
    if cloud == "aws":
        from skypilot_tpu.provision import aws_auth
        return aws_auth.check_credentials()
    if cloud == "azure":
        from skypilot_tpu.provision import azure_auth
        return azure_auth.check_credentials()
    if cloud == "kubernetes":
        try:
            from skypilot_tpu.provision import kubernetes as k8s
            return k8s.check_credentials()
        except ImportError:
            return False, "kubernetes provider not available"
    return False, f"unknown cloud {cloud!r}"


def check(quiet: bool = False,
          clouds: Optional[List[str]] = None) -> List[str]:
    """Validate credentials per cloud; merge into + return the enabled list.

    A subset check (``clouds=['gcp']``) only updates the checked clouds'
    entries in the cache — previously enabled clouds stay enabled
    (reference behavior: sky/check.py merges subset results).
    """
    to_check = list(clouds) if clouds else list(CLOUDS)
    prior = (cached_enabled_clouds() or []) if clouds else []
    enabled = [c for c in prior if c not in to_check]
    reasons: Dict[str, str] = {}
    for cloud in to_check:
        ok, reason = _check_one(cloud)
        reasons[cloud] = reason
        if ok:
            enabled.append(cloud)
    enabled = sorted(enabled, key=lambda c: (CLOUDS + (c,)).index(c))
    if not quiet:
        for cloud in to_check:
            mark = "enabled" if cloud in enabled else "disabled"
            print(f"  {cloud}: {mark} — {reasons[cloud]}")
    with open(_cache_path(), "w") as f:
        json.dump({"enabled": enabled}, f)
    if not enabled:
        raise exceptions.NoCloudAccessError(
            "no cloud is enabled; run `skytpu check` after configuring "
            "credentials (gcloud auth application-default login)")
    return enabled


_cache_memo: dict = {}


def cached_enabled_clouds() -> Optional[List[str]]:
    """The enabled list IF a check has ever run, else None (no probe).

    The optimizer consults this to restrict catalog candidates to
    enabled clouds (reference: optimizer candidates come only from
    enabled clouds, sky/optimizer.py via check.py:172) — but only once
    the user has actually run a check; with no cache, every catalog
    cloud stays a candidate so offline planning/dryruns work
    credential-free. Memoized on file mtime: launchables() sits on the
    optimizer's per-resource path and must not re-parse an unchanged
    file every call."""
    path = _cache_path()
    try:
        mtime = os.path.getmtime(path)
    except OSError:
        return None
    key = (path, mtime)
    if key not in _cache_memo:
        try:
            with open(path) as f:
                value = list(json.load(f)["enabled"])
        except (json.JSONDecodeError, KeyError, TypeError, OSError):
            # Unreadable/malformed cache == "no check has run".
            value = None
        _cache_memo.clear()
        _cache_memo[key] = value
    return _cache_memo[key]


def get_cached_enabled_clouds_or_refresh(
        raise_if_no_cloud_access: bool = False) -> List[str]:
    cached = cached_enabled_clouds()
    if cached is not None:
        return cached
    try:
        return check(quiet=True)
    except exceptions.NoCloudAccessError:
        if raise_if_no_cloud_access:
            raise
        return []
