"""Multi-LoRA adapter catalog: one base model, a fleet of fine-tunes.

The serving engine graduates from "serves a model" to "serves a
CATALOG of fine-tunes" (ROADMAP item 5 — the vLLM-style multi-LoRA
pattern, TPU-native): rank-R adapters from ``train/lora.py`` /
``train/qlora.py`` live in a device-resident STACKED pool
(``[L, n_adapters, d_in, r]`` / ``[L, n_adapters, r, d_out]`` per
target projection) and every decode/verify/chunk/wave program gathers
each slot's (A, B) pair into the batched matmul — one gather per
layer per target, rank fixed, so requests for *different* fine-tunes
batch into ONE device dispatch.

Retrace discipline (the ROADMAP item 5 watch item): the pool's
capacity is an engine constant and the per-slot adapter id rides as a
DEVICE ARRAY next to the block table — adapter *count* and *identity*
never enter program identity (compile watch + ``warm_programs()`` are
the guard; tests/test_adapters.py gates zero unexpected compiles while
adapters hot-load mid-traffic). Pool slot 0 is pinned to the all-zeros
BASE adapter: ``x @ A`` with ``A == 0`` contributes an exact-zero
delta, so "no adapter" runs the same compiled program and its greedy
output is bit-identical to an adapterless engine's.

Host-side bookkeeping mirrors the paged-KV design:

* checkpoints are CONTENT-ADDRESSED (blake2b-128 over the stacked
  weight bytes) — two names registering identical bytes share one
  pool slot;
* hot-load/evict is LRU over resident, UNPINNED slots. A slot is
  pinned while any decode slot references it (in-flight refcounts,
  bumped at claim and dropped at retire/preemption) — an adapter a
  resident request is mid-generation on is never evicted under it;
* a load failure (the ``adapter.load`` chaos point; transient faults
  retry via ``utils/retry``) fails the REQUEST typed
  (``adapter.load_failed`` event + ``{"type": "adapter_load_failed"}``
  body) — it never silently falls through to the base model's weights.

The ``alpha / rank`` LoRA scale folds into B at load time, so the
device path is a pure pair of einsums and adapters with different
alphas coexist in one pool; an adapter whose rank is below the pool's
zero-pads (extra rank columns contribute exact zeros).

See docs/serving.md §Adapter catalog for pool layout, the parity
guarantee, eviction/pinning semantics and the knob table.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from skypilot_tpu import chaos
from skypilot_tpu.observability import metrics, tracing
from skypilot_tpu.utils import retry

# The request header naming a fine-tune (the body's ``model`` field is
# the SDK path) — shared by the model server and the LB so the two
# tiers can never disagree on where the name rides.
MODEL_HEADER = "x-skytpu-model"

# Targets must match train/lora.py's geometry table: per target, the
# base weight's (input dims, output dims) after the layer axis, derived
# from the model config at pool init.
TARGETS = ("wq", "wk", "wv", "wo")

ADAPTER_LOADS = metrics.counter(
    "skytpu_adapter_loads_total",
    "Adapter checkpoints hot-loaded into the device-resident pool "
    "(a prefix-cache-style demand load: the first request naming a "
    "non-resident adapter pays it, later ones gather warm)")
ADAPTER_EVICTIONS = metrics.counter(
    "skytpu_adapter_evictions_total",
    "Resident adapters evicted (LRU over unpinned pool slots) to "
    "hot-load another — an adapter pinned by an in-flight request "
    "is never evicted")
ADAPTER_ACTIVE = metrics.gauge(
    "skytpu_adapter_active",
    "Adapters currently resident in the device pool (the base "
    "all-zeros slot 0 is not counted)")
ADAPTER_SLOTS = metrics.gauge(
    "skytpu_adapter_slots",
    "Adapter-pool capacity: fine-tune slots available per engine "
    "(pool slot 0 is reserved for the all-zeros base adapter)")


class UnknownAdapterError(ValueError):
    """Request names a fine-tune the catalog has never heard of. A
    CLIENT error — HTTP 404 with a typed body at both the LB and the
    model server (``{"type": "unknown_adapter"}``) — never a 500."""

    http_status = 404

    def __init__(self, name: str, known: Optional[List[str]] = None):
        super().__init__(f"unknown adapter {name!r}")
        self.adapter = name
        self.typed_error = {
            "type": "unknown_adapter",
            "adapter": name,
            "message": str(self),
        }
        if known is not None:
            self.typed_error["known"] = sorted(known)[:32]


class AdapterLoadError(RuntimeError):
    """Hot-loading a registered adapter's checkpoint failed (after
    retries). The REQUEST fails typed with this body — falling through
    to the base model's weights would silently serve the wrong
    model."""

    http_status = 503

    def __init__(self, name: str, reason: str):
        super().__init__(f"adapter {name!r} failed to load: {reason}")
        self.adapter = name
        self.typed_error = {
            "type": "adapter_load_failed",
            "adapter": name,
            "message": str(self),
        }


@dataclasses.dataclass
class _Entry:
    """One registered adapter. ``params``/``path`` is the checkpoint
    (host arrays, or an .npz on disk loaded on first demand);
    ``digest`` is the content address, computed at registration for
    in-memory params and at first load for paths."""

    name: str
    params: Optional[Dict[str, Any]] = None
    path: Optional[str] = None
    alpha: float = 32.0
    rank: Optional[int] = None
    digest: Optional[bytes] = None


def _dims(cfg, axes: Tuple[str, ...]) -> Tuple[int, ...]:
    m = {"embed": cfg.d_model, "heads": cfg.n_heads,
         "kv_heads": cfg.n_kv_heads, "head_dim": cfg.head_dim}
    return tuple(m[a] for a in axes)


def target_shapes(cfg, rank: int) -> Dict[str, Tuple[Tuple[int, ...],
                                                     Tuple[int, ...]]]:
    """Per target, the (a, b) shapes AFTER the leading [L, N] pool
    dims — the single geometry definition (mirrors train/lora.py
    ``_TARGETS``)."""
    geo = {
        "wq": (("embed",), ("heads", "head_dim")),
        "wk": (("embed",), ("kv_heads", "head_dim")),
        "wv": (("embed",), ("kv_heads", "head_dim")),
        "wo": (("heads", "head_dim"), ("embed",)),
    }
    out = {}
    for t, (in_axes, out_axes) in geo.items():
        out[t] = (_dims(cfg, in_axes) + (rank,),
                  (rank,) + _dims(cfg, out_axes))
    return out


def init_adapter_pool(cfg, n_adapters: int, rank: int,
                      dtype=None) -> Dict[str, Dict[str, Any]]:
    """The device-resident stacked pool: per target
    ``{"a": [L, N, d_in..., r], "b": [L, N, r, d_out...]}`` zeros.
    The layer axis LEADS so pool slices ride the decoder's
    ``lax.scan`` as ordinary xs; slot 0 stays all-zeros forever (the
    base adapter — an exact-zero delta)."""
    import jax.numpy as jnp
    dtype = dtype if dtype is not None else cfg.dtype
    L = cfg.n_layers
    pool: Dict[str, Dict[str, Any]] = {}
    for t, (sa, sb) in target_shapes(cfg, rank).items():
        pool[t] = {
            "a": jnp.zeros((L, n_adapters) + sa, dtype),
            "b": jnp.zeros((L, n_adapters) + sb, dtype),
        }
    return pool


def pool_install(pool: Dict[str, Dict[str, Any]], slot,
                 weights: Dict[str, Dict[str, Any]]
                 ) -> Dict[str, Dict[str, Any]]:
    """Scatter one adapter's stacked weights into pool slot ``slot``
    (device program — the engine jits + donates the pool and wraps it
    in the compile watch). ``weights``: per target
    ``{"a": [L, d_in..., r], "b": [L, r, d_out...]}`` with the
    alpha/rank scale already folded into ``b``."""
    from jax import lax
    out = {}
    for t, ab in pool.items():
        out[t] = {
            "a": lax.dynamic_update_index_in_dim(
                ab["a"], weights[t]["a"].astype(ab["a"].dtype), slot, 1),
            "b": lax.dynamic_update_index_in_dim(
                ab["b"], weights[t]["b"].astype(ab["b"].dtype), slot, 1),
        }
    return out


def save_adapter(path: str, params: Dict[str, Any],
                 alpha: float = 32.0) -> None:
    """Write a trained adapter tree (train/lora.py layout: per target
    ``{"a": [L, ..., r], "b": [L, r, ...]}``) as the small .npz
    checkpoint the serve controller distributes to replicas."""
    flat = {"__alpha__": np.asarray(alpha, np.float64)}
    for t, ab in params.items():
        flat[f"{t}.a"] = np.asarray(ab["a"])
        flat[f"{t}.b"] = np.asarray(ab["b"])
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **flat)
    os.replace(tmp, path)


def load_adapter_file(path: str) -> Tuple[Dict[str, Any], float]:
    """Read a ``save_adapter`` checkpoint -> (params tree, alpha)."""
    with np.load(os.path.expanduser(path)) as z:
        alpha = float(z["__alpha__"]) if "__alpha__" in z else 32.0
        params: Dict[str, Any] = {}
        for key in z.files:
            if key == "__alpha__":
                continue
            t, leaf = key.rsplit(".", 1)
            params.setdefault(t, {})[leaf] = z[key]
    return params, alpha


def _content_digest(params: Dict[str, Any], alpha: float) -> bytes:
    """blake2b-128 over alpha + the stacked weight bytes,
    target-ordered — the content address (a Python ``hash`` could
    collide and silently serve the wrong fine-tune). ``alpha`` is part
    of the identity: it folds into B at install time, so identical raw
    weights under different alphas are DIFFERENT effective models and
    must never share a pool slot."""
    h = hashlib.blake2b(digest_size=16)
    h.update(np.float64(alpha).tobytes())
    for t in sorted(params):
        for leaf in ("a", "b"):
            arr = np.ascontiguousarray(
                np.asarray(params[t][leaf], np.float32))
            h.update(t.encode())
            h.update(leaf.encode())
            h.update(arr.tobytes())
    return h.digest()


class AdapterCatalog:
    """Host-side catalog over the device-resident adapter pool.

    Registration (``register``) may run from any thread — the registry
    dict rides ``_lock``. Residency/pin/pool state is LOOP-THREAD ONLY
    (the engine claims and retires there), mirroring the engine's
    block-table ownership model; the engine binds the compile-watched
    install program via :meth:`bind_loader` before first use.
    """

    def __init__(self, cfg, n_adapters: int = 8, rank: int = 16,
                 dtype=None):
        if n_adapters < 2:
            raise ValueError(
                f"adapter pool needs >= 2 slots (slot 0 is the base "
                f"adapter), got {n_adapters}")
        if rank <= 0:
            raise ValueError(f"adapter rank must be positive, got {rank}")
        self.cfg = cfg
        self.rank = rank
        self.n_adapters = n_adapters
        self.pool = init_adapter_pool(cfg, n_adapters, rank, dtype)
        self._lock = threading.Lock()
        # name -> registered entry. guarded-by: _lock
        self._registry: Dict[str, _Entry] = {}
        # Loop-thread-only residency state (the engine's claim/retire
        # path is the sole mutator, exactly like the block table):
        self._resident: Dict[bytes, int] = {}      # digest -> pool slot
        self._slot_digest: Dict[int, bytes] = {}
        self._slot_name: Dict[int, str] = {}       # display only
        self._pins: Dict[int, int] = {}            # slot -> refcount
        self._used: Dict[int, int] = {}            # slot -> LRU tick
        self._tick = 0
        self._free: List[int] = list(range(n_adapters - 1, 0, -1))
        self._loader: Optional[Callable] = None
        self.loads = 0
        self.evictions = 0
        ADAPTER_SLOTS.set(n_adapters - 1)
        ADAPTER_ACTIVE.set(0)

    # -- registration (any thread) -----------------------------------------

    def register(self, name: str, params: Optional[Dict] = None,
                 path: Optional[str] = None,
                 alpha: float = 32.0) -> None:
        """Make a fine-tune KNOWN (routable). Loading to device stays
        lazy — the first request naming it pays the hot-load. In-memory
        ``params`` are content-addressed immediately; a ``path``
        checkpoint hashes at first load."""
        if not name or not isinstance(name, str):
            raise ValueError(f"adapter name must be a non-empty string, "
                             f"got {name!r}")
        if (params is None) == (path is None):
            raise ValueError("register() needs exactly one of "
                             "params= or path=")
        ent = _Entry(name=name, params=params, path=path, alpha=alpha)
        if params is not None:
            self._validate(name, params)
            ent.rank = next(iter(params.values()))["a"].shape[-1]
            ent.digest = _content_digest(params, alpha)
        with self._lock:
            self._registry[name] = ent

    def register_entries(self, raw: str) -> int:
        """Register every adapter in a JSON ``{name: checkpoint
        path}`` catalog (how the serve controller hands a replica its
        catalog — ``SKYTPU_ADAPTERS`` / ``--adapters``). Returns how
        many registered; a malformed value registers nothing and a bad
        ENTRY skips that entry, loudly, so one typo cannot take the
        rest of the catalog down with it."""
        try:
            entries = json.loads(raw)
            if not isinstance(entries, dict):
                raise ValueError("expected a JSON object")
        except (ValueError, TypeError):
            tracing.add_event("adapter.env_invalid",
                              {"raw": raw[:200]}, echo=True)
            return 0
        n = 0
        for name, path in entries.items():
            try:
                self.register(str(name), path=str(path))
                n += 1
            except ValueError:
                tracing.add_event("adapter.env_invalid",
                                  {"adapter": str(name)[:64]}, echo=True)
        return n

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._registry)

    def check(self, name: Optional[str]) -> None:
        """Submit-time guard (handler threads): an unregistered name is
        a clean typed 404 BEFORE the request rides the inbox."""
        if name is None:
            return
        with self._lock:
            if name not in self._registry:
                raise UnknownAdapterError(name, list(self._registry))

    def _validate(self, name: str, params: Dict) -> None:
        shapes = target_shapes(self.cfg, self.rank)
        for t, ab in params.items():
            if t not in shapes:
                raise ValueError(f"adapter {name!r}: unknown target "
                                 f"{t!r}; supported: {sorted(shapes)}")
            r = ab["a"].shape[-1]
            if r > self.rank:
                raise ValueError(
                    f"adapter {name!r}: rank {r} exceeds the pool's "
                    f"rank {self.rank} (lower ranks zero-pad)")
            if ab["b"].shape[1] != r:
                raise ValueError(
                    f"adapter {name!r}: A rank {r} != B rank "
                    f"{ab['b'].shape[1]} on target {t!r}")

    # -- residency (engine loop thread only) -------------------------------

    def bind_loader(self, loader: Callable) -> None:
        """The engine's compile-watched jitted install program:
        ``loader(pool, slot, weights) -> pool'`` (donating the pool)."""
        self._loader = loader

    def resident_count(self) -> int:
        return len(self._resident)

    def slot_names(self) -> Dict[int, str]:
        """pool slot -> adapter name for resident slots (flight-record
        and debug surfaces)."""
        return dict(self._slot_name)

    def pins(self, slot: int) -> int:
        return self._pins.get(slot, 0)

    def slot_content(self, slot: int) -> bytes:
        """The resident adapter's content digest (b"" for the base
        slot) — the engine's prefix-cache key salt, so warm prefixes
        follow the adapter's CONTENT across evict/reload cycles and
        across aliases."""
        return self._slot_digest.get(slot, b"")

    def acquire(self, name: Optional[str]) -> Optional[int]:
        """The pool slot serving ``name``, hot-loading (and evicting an
        LRU unpinned resident) when non-resident; the slot's in-flight
        refcount is bumped — :meth:`release` drops it at retirement or
        preemption. ``None`` (base model) is slot 0, never refcounted.

        Returns None — the STALL signal, mirroring the dry block
        pool — when every pool slot is pinned by an in-flight request:
        the engine re-queues the request and retries once a
        retirement unpins a slot. Raises :class:`UnknownAdapterError`
        for unregistered names and :class:`AdapterLoadError` when the
        checkpoint cannot load (after retries) — the caller fails the
        request typed, never falls through to the base weights."""
        if name is None:
            return 0
        with self._lock:
            ent = self._registry.get(name)
        if ent is None:
            raise UnknownAdapterError(name, self.names())
        if ent.digest is not None:
            slot = self._resident.get(ent.digest)
            if slot is not None:
                self._tick += 1
                self._used[slot] = self._tick
                self._pins[slot] = self._pins.get(slot, 0) + 1
                return slot
        slot = self._grab_slot()
        if slot is None:
            return None                     # all pinned: stall
        self._hot_load(ent, slot)
        self.loads += 1
        ADAPTER_LOADS.inc()
        # A path checkpoint's digest is only known AFTER the first
        # load: if it resolved to content that is ALREADY resident (a
        # path alias), keep the original slot — one digest must never
        # map two slots, or evicting either would pop the mapping out
        # from under the survivor. The freshly installed copy goes
        # back to the free list (its bytes are unreachable garbage
        # until the next install overwrites them).
        dup = self._resident.get(ent.digest)
        if dup is not None and dup != slot:
            self._free.append(slot)
            slot = dup
        else:
            self._resident[ent.digest] = slot
            self._slot_digest[slot] = ent.digest
            self._slot_name[slot] = ent.name
        self._tick += 1
        self._used[slot] = self._tick
        self._pins[slot] = self._pins.get(slot, 0) + 1
        ADAPTER_ACTIVE.set(len(self._resident))
        return slot

    def release(self, slot: Optional[int]) -> None:
        """Drop one in-flight reference (retirement / preemption).
        Slot 0 (base) carries no refcount; a slot at zero pins stays
        RESIDENT (warm for the next request) but becomes evictable."""
        if not slot:
            return
        n = self._pins.get(slot, 0) - 1
        if n > 0:
            self._pins[slot] = n
        else:
            self._pins.pop(slot, None)

    def _grab_slot(self) -> Optional[int]:
        """A free pool slot, else the LRU resident UNPINNED slot
        evicted; None when everything is pinned by in-flight
        requests (slot 0 never participates — the base adapter is
        pinned by construction)."""
        if self._free:
            return self._free.pop()
        victims = [s for s in self._used if not self._pins.get(s, 0)]
        if not victims:
            return None
        victim = min(victims, key=self._used.get)
        digest = self._slot_digest.pop(victim, None)
        if digest is not None:
            self._resident.pop(digest, None)
        self._slot_name.pop(victim, None)
        self._used.pop(victim, None)
        self.evictions += 1
        ADAPTER_EVICTIONS.inc()
        ADAPTER_ACTIVE.set(len(self._resident))
        # The evicted slot's pool weights stay as garbage until the
        # install below overwrites them; nothing maps an adapter id to
        # this slot until residency is re-recorded.
        return victim

    def _hot_load(self, ent: _Entry, slot: int) -> None:
        """Fetch + install one checkpoint into ``slot``. Each attempt
        rides the ``adapter.load`` chaos point; transient faults retry
        (utils/retry, capped backoff); exhaustion emits the typed
        ``adapter.load_failed`` event and raises — the caller fails
        the request typed instead of serving base weights."""
        if self._loader is None:
            raise AdapterLoadError(ent.name, "no loader bound")

        def attempt():
            chaos.point("adapter.load", adapter=ent.name)
            params = ent.params
            alpha = ent.alpha
            if params is None:
                params, alpha = load_adapter_file(ent.path)
                self._validate(ent.name, params)
            if ent.digest is None:
                ent.digest = _content_digest(params, alpha)
                ent.rank = next(iter(params.values()))["a"].shape[-1]
            weights = self._stack(params, alpha,
                                  next(iter(params.values()))
                                  ["a"].shape[-1])
            self.pool = self._loader(self.pool, slot, weights)

        try:
            retry.call(
                attempt, name="adapter_load",
                policy=retry.RetryPolicy(
                    max_attempts=2, backoff_base_s=0.05,
                    backoff_max_s=0.25,
                    retry_on=(OSError, ConnectionError, RuntimeError),
                    give_up_on=(UnknownAdapterError, ValueError)))
        except Exception as e:  # noqa: BLE001 — typed terminal failure
            self._free.append(slot)     # slot never became resident
            tracing.add_event(
                "adapter.load_failed",
                {"adapter": ent.name, "error": str(e)[:200]},
                echo=True)
            raise AdapterLoadError(ent.name, str(e)) from e

    def _stack(self, params: Dict, alpha: float,
               rank: int) -> Dict[str, Dict[str, Any]]:
        """Checkpoint tree -> install-shaped weights: the alpha/rank
        scale folds into B (the device path stays a pure einsum pair),
        missing targets and rank columns zero-pad (exact-zero
        deltas)."""
        import jax.numpy as jnp
        scale = alpha / rank
        shapes = target_shapes(self.cfg, self.rank)
        out: Dict[str, Dict[str, Any]] = {}
        L = self.cfg.n_layers
        for t, (sa, sb) in shapes.items():
            if t in params:
                a = np.asarray(params[t]["a"], np.float32)
                b = np.asarray(params[t]["b"], np.float32) * scale
                if rank < self.rank:
                    pad_a = np.zeros((L,) + sa, np.float32)
                    pad_a[..., :rank] = a
                    pad_b = np.zeros((L,) + sb, np.float32)
                    pad_b[:, :rank] = b
                    a, b = pad_a, pad_b
            else:
                a = np.zeros((L,) + sa, np.float32)
                b = np.zeros((L,) + sb, np.float32)
            out[t] = {"a": jnp.asarray(a), "b": jnp.asarray(b)}
        return out

    def zero_weights(self) -> Dict[str, Dict[str, Any]]:
        """An all-zero install-shaped weight tree (the warm-grid sweep
        installs it into the base slot — values unchanged, program
        compiled)."""
        return self._stack({}, 1.0, self.rank)

    def reset(self) -> None:
        """Drop all residency/pin state (the engine's reset path —
        a mid-load failure may have left pins inconsistent). The pool
        arrays stay; nothing maps to them until re-acquired."""
        self._resident.clear()
        self._slot_digest.clear()
        self._slot_name.clear()
        self._pins.clear()
        self._used.clear()
        self._free = list(range(self.n_adapters - 1, 0, -1))
        ADAPTER_ACTIVE.set(0)


def catalog_from_env(cfg, adapters_json: Optional[str] = None,
                     slots: Optional[int] = None,
                     rank: Optional[int] = None
                     ) -> Optional[AdapterCatalog]:
    """The engine's adapter catalog, or None when no catalog is
    configured (the zero-cost adapterless path). THE bootstrap — the
    server's CLI flags pass through the explicit arguments and the
    serve controller's env distribution rides the defaults, so the
    two paths cannot drift: ``SKYTPU_ADAPTERS`` (JSON name->path)
    names the fine-tunes, ``SKYTPU_ADAPTER_SLOTS`` (default 8) the
    pool capacity and ``SKYTPU_ADAPTER_RANK`` (default 16) the pool
    rank."""
    raw = (adapters_json if adapters_json is not None
           else os.environ.get("SKYTPU_ADAPTERS", "").strip())
    if not raw:
        return None
    if slots is None:
        slots = int(os.environ.get("SKYTPU_ADAPTER_SLOTS", "8") or 8)
    if rank is None:
        rank = int(os.environ.get("SKYTPU_ADAPTER_RANK", "16") or 16)
    cat = AdapterCatalog(cfg, n_adapters=max(slots, 2), rank=rank)
    cat.register_entries(raw)
    return cat
