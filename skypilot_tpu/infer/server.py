"""HTTP model server: continuous-batching engine behind a stdlib server.

This is what a SkyServe replica runs (see llm/serve-llama.yaml): the
load balancer probes ``/health`` and proxies ``/generate``; the engine
thread batches concurrent requests into shared decode bursts.

Endpoints:
  GET  /health              -> 200 {"status": "ok"} once warm
  GET  /metrics             -> Prometheus text exposition of the
                               process registry (engine TTFT/TPOT
                               histograms, slot occupancy, queue depth,
                               HTTP latencies; docs/observability.md)
  POST /generate            {"tokens": [...], "max_new_tokens": N}
                            -> {"tokens": [...], "ttft_ms": ..., ...}
  POST /generate + "stream": true
                            -> Transfer-Encoding: chunked, one JSON
                               line per emission ({"tokens": [...]}),
                               closing line {"done": true, "ttft_ms":.}
                               Tokens stream AS DECODED — TTFT is one
                               prefill away, not one full generation.

Reference parity: the reference's serving recipes wrap external engines
(reference: llm/vllm/serve.yaml, JetStream in examples/tpu/v6e) — this
is the in-tree TPU-native equivalent; streaming mirrors what the
JetStream benchmark measures (examples/tpu/v6e/README.md TTFT).
"""

from __future__ import annotations

import argparse
import json
import os
import queue
import threading
import time
from http.server import BaseHTTPRequestHandler, HTTPServer
from socketserver import ThreadingMixIn
from typing import Dict, Optional

from skypilot_tpu import chaos
from skypilot_tpu.infer import qos as qos_lib
from skypilot_tpu.observability import flight as flight_lib
from skypilot_tpu.observability import health as health_lib
from skypilot_tpu.observability import metrics, tracing
from skypilot_tpu.utils import timeline

HTTP_SECONDS = metrics.histogram(
    "skytpu_http_request_seconds",
    "Model-server HTTP request latency (streaming requests span the "
    "full generation)", labelnames=("route",),
    buckets=metrics.latency_buckets())
HTTP_REQUESTS = metrics.counter(
    "skytpu_http_requests_total",
    "Model-server HTTP requests by route and status code",
    labelnames=("route", "code"))
INBOX_DEPTH = metrics.gauge(
    "skytpu_server_inbox_depth",
    "Requests accepted by handler threads, not yet drained into the "
    "engine (queue depth ahead of admission)")
PENDING_REQUESTS = metrics.gauge(
    "skytpu_server_pending_requests",
    "Requests in flight in the serving loop (drained, not finished)")
BURST_FLUSHES = metrics.counter(
    "skytpu_server_burst_flushes_total",
    "Async decode bursts landed (fetched + streamed) by the loop")
WAVE_FLUSH_SECONDS = metrics.histogram(
    "skytpu_server_wave_flush_seconds",
    "Post-admission-wave flush (stream first tokens + re-drain inbox)")
SERVER_DRAINING = metrics.gauge(
    "skytpu_server_draining",
    "1 while this replica is draining (POST /drain received: new "
    "admissions get a typed 503, in-flight requests finish, /healthz "
    "reports 'draining' so the LB and controller stop routing here)")


class _Pending:
    def __init__(self, req=None):
        self.event = threading.Event()
        self.result: Optional[Dict] = None
        self.enqueued_s = time.time()
        self.stream = False
        # Streaming: the engine loop pushes token batches as decoded
        # ({"tokens": [...]}); a {"done"/"error"} dict terminates.
        self.req = req            # engine Request (tokens grow in place)
        self.cursor = 0           # tokens already pushed to the stream
        self.chunks: queue.Queue = queue.Queue()
        # Disaggregated prefill tier: when set, the finished-request
        # pass attaches the retired request's stored-prefix export
        # (block contents + lengths) to the result for the /prefill
        # response — the payload the LB hands to a decode replica.
        self.export_prefix = False


class ModelServer:
    """Engine + request queue + batching loop.

    Ownership model: the step loop thread is the ONLY thread that
    touches the engine. Handler threads drop (tokens, pending) into an
    inbox under a tiny lock and wait on their pending's event/queue.
    (An earlier design guarded the engine with one big lock; the
    busy loop re-acquired it back-to-back and barge-starved admissions
    on a single core — concurrent TTFTs collapsed to full-batch wall.)
    """

    def __init__(self, engine, max_burst: int = 8,
                 open_burst: int = 4, open_window_s: float = 1.0,
                 coalesce_s: float = 0.012,
                 qos: Optional[qos_lib.AdmissionController] = None):
        self.engine = engine
        self.max_burst = max_burst
        # Multi-tenant QoS admission (docs/serving.md §Multi-tenant
        # QoS): handler threads run the token-bucket + overload check
        # BEFORE a request ever touches the inbox; None (the default)
        # is the zero-cost path.
        self.qos = qos
        # Admission coalescing: when the inbox yields less than a full
        # wave but a request arrived within the last ``coalesce_s``,
        # wait a beat (in 2 ms slices, re-draining) before dispatching.
        # Burst arrivals land over several ms — on a single-core host
        # the handler threads need the GIL the loop thread is holding —
        # and an eager dispatch sends a 1-row wave padded to max_wave
        # rows of FULL-bucket prefill: measured 7 waves instead of 6
        # for a 24-request burst at wave 4, one entirely wasted 8B
        # prefill program per run. The sleep slices also yield the GIL,
        # which is exactly what lets the stragglers enqueue.
        self.coalesce_s = coalesce_s
        # Burst size while the admission window is OPEN (free slots
        # exist AND traffic is arriving): a late HTTP arrival waits at
        # most one short burst before its prefill, instead of a full
        # max_burst decode (JetStream's prefill-over-generate priority;
        # r3 driver bench showed 5x TTFT variance from arrivals
        # stranded behind full bursts). Full bursts run when every
        # slot is busy — admission is impossible then — and ALSO when
        # no request has arrived for ``open_window_s``: free slots
        # alone must not pin the burst short, or a partially loaded
        # server pays per-burst dispatch forever (measured 359 vs 748
        # tok/s at 24 requests on 32 slots). An unlucky arrival after
        # a quiet spell waits at most one long burst, and the very
        # next burst is short again.
        self.open_burst = min(open_burst, max_burst)
        self.open_window_s = open_window_s
        # Monotonic: an NTP step must not pin the window open (short
        # bursts forever) or spuriously slam it shut.
        self._last_arrival = 0.0     # guarded-by: _inbox_lock
        # Double-buffered decode (engines exposing the async pair):
        # burst k+1 is dispatched BEFORE burst k's tokens are fetched
        # and streamed, so the TPU decodes k+1 while this thread does
        # k's JSON framing + socket writes + LB hop. Fake/simple
        # engines without the pair fall back to sync decode_burst.
        # Speculative engines (spec_k > 0) also run the sync path:
        # verify FETCHES can't double-buffer — the next round's window
        # depends on the tokens this one commits — and decode_burst
        # itself routes to the verify program there. The overlap spec
        # mode used to forfeit now lives INSIDE the round: with a
        # model drafter + spec_pipeline, the next round's draft
        # rollout dispatches while the verify is in flight
        # (engine.spec_decode_burst), so the draft model's work rides
        # the verify wall instead of serializing after it.
        self._burst = None
        self._async_decode = (hasattr(engine, "dispatch_decode_burst")
                              and not getattr(engine, "spec_k", 0))
        # Component health detail behind GET /healthz: "" while
        # serving; a reason string while warming or after a failed
        # engine reset (the two _ready-unset states a probe must tell
        # apart — one recovers by waiting, one needs replacement).
        self.health_reason = "warming"
        self._inbox_lock = threading.Lock()
        self._inbox: list = []        # guarded-by: _inbox_lock
        self._pending: Dict[int, _Pending] = {}   # loop-thread only
        self._ready = threading.Event()
        self._stop = threading.Event()
        # Graceful drain (docs/robustness.md §Replica loss & rolling
        # update): once draining, new admissions get a typed 503 and
        # in-flight requests run to completion; past the deadline the
        # replica self-reports DEGRADED so `skytpu status --health`
        # exits 2. Flags are written by handler threads and read
        # everywhere — benign un-locked reads, same as queue_depth().
        self._draining = False
        self._drain_deadline_s = 0.0
        # Engine crash-recovery storm guard: recover at most
        # ``_storm_limit`` times per ``_storm_window_s`` rolling
        # window, then fall back to fail-all + reset (a device that
        # keeps crashing needs replacement, not an infinite
        # recover/crash loop that never fails a request visibly).
        self._storm_limit = int(os.environ.get(
            "SKYTPU_RECOVERY_STORM_LIMIT", "3"))
        self._storm_window_s = float(os.environ.get(
            "SKYTPU_RECOVERY_STORM_WINDOW_S", "30"))
        self._recover_times: list = []    # loop-thread only
        # Off-thread event-log heartbeat: engine spans become durable
        # (visible to a separate-process `skytpu trace`) within ~5s of
        # recording, and the O(ring) flush serialization never runs on
        # the serving loop between decode waves. The flight recorder
        # gets the same durability heartbeat (visible to a separate-
        # process `skytpu flight --local`).
        tracing.ensure_flush_thread()
        flight_lib.ensure_flush_thread()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def queue_depth(self) -> int:
        """Inbox + in-flight requests — the overload-shed input.
        Benign racy len() reads from handler threads: a threshold
        check needs no exactness, and taking the loop's locks here
        would serialize admission behind decode."""
        return len(self._inbox) + len(self._pending)

    # -- graceful drain ----------------------------------------------------

    def start_drain(self, grace_s: float = 30.0) -> Dict:
        """Enter (or re-poll) the draining state: idempotent — the
        first call stamps the deadline, repeats just report progress,
        so the controller polls `POST /drain` until ``drained``."""
        if not self._draining:
            self._draining = True
            self._drain_deadline_s = time.time() + max(grace_s, 0.0)
            SERVER_DRAINING.set(1)
            tracing.add_event(
                "server.draining",
                {"in_flight": self.queue_depth(),
                 "grace_s": grace_s}, echo=True)
        return self.drain_status()

    def draining(self) -> bool:
        return self._draining

    def drain_status(self) -> Dict:
        depth = self.queue_depth()
        return {
            "draining": self._draining,
            "in_flight": depth,
            "drained": self._draining and depth == 0,
            "deadline_s": round(self._drain_deadline_s, 3),
        }

    def _add(self, tokens, max_new_tokens: int,
             stream: bool = False, trace_ctx=None,
             tenant: str = qos_lib.DEFAULT_TENANT,
             priority: int = 0,
             adapter: Optional[str] = None,
             export_prefix: bool = False,
             handoff: Optional[Dict] = None) -> _Pending:
        from skypilot_tpu.infer import engine as eng
        # Validate eagerly (oversized prompt / unsatisfiable KV quota /
        # unknown adapter -> clean 400/404) without touching the
        # engine's mutable state from this thread — an exception
        # raised later on the loop thread could reach no client.
        eng._bucket(len(tokens), self.engine.buckets)
        check = getattr(self.engine, "check_kv_quota", None)
        if check is not None:
            check(tenant, len(tokens), max_new_tokens)
        if adapter is not None:
            check_ad = getattr(self.engine, "check_adapter", None)
            if check_ad is not None:
                check_ad(adapter)
        p = _Pending()
        p.stream = stream
        p.export_prefix = export_prefix
        with self._inbox_lock:
            # The caller's trace context rides the inbox tuple: the
            # loop thread (which has no ambient context) hands it to
            # add_request so the engine's per-request spans join the
            # HTTP caller's trace.
            self._inbox.append((list(tokens), max_new_tokens, p,
                                trace_ctx, tenant, priority, adapter,
                                handoff))
            self._last_arrival = time.monotonic()
            INBOX_DEPTH.set(len(self._inbox))
        return p

    def submit(self, tokens, max_new_tokens: int, trace_ctx=None,
               tenant: str = qos_lib.DEFAULT_TENANT,
               priority: int = 0, adapter: Optional[str] = None,
               export_prefix: bool = False,
               handoff: Optional[Dict] = None) -> Dict:
        p = self._add(tokens, max_new_tokens, trace_ctx=trace_ctx,
                      tenant=tenant, priority=priority, adapter=adapter,
                      export_prefix=export_prefix, handoff=handoff)
        t0 = time.time()
        p.event.wait()
        out = dict(p.result or {})
        out["total_ms"] = round((time.time() - t0) * 1e3, 2)
        return out

    def submit_stream(self, tokens, max_new_tokens: int, trace_ctx=None,
                      tenant: str = qos_lib.DEFAULT_TENANT,
                      priority: int = 0, adapter: Optional[str] = None,
                      handoff: Optional[Dict] = None):
        """Iterator of chunk dicts: {"tokens": [...]} as decoded, then
        one {"done": true, "ttft_ms": ...} (or {"error": ...}).

        Admission validation happens EAGERLY (before any bytes are
        written), so an oversized prompt — or an unknown adapter
        name — raises here as a clean 400/404, not mid-stream after a
        200 went out.
        """
        p = self._add(tokens, max_new_tokens, stream=True,
                      trace_ctx=trace_ctx, tenant=tenant,
                      priority=priority, adapter=adapter,
                      handoff=handoff)

        def gen():
            while True:
                chunk = p.chunks.get()
                yield chunk
                if "done" in chunk or "error" in chunk:
                    return

        return gen()

    def _loop(self) -> None:
        # Warm the compile path before /health flips: the load balancer
        # must not route traffic into a cold XLA compile. The warmup
        # runs the fully instrumented path and the compile dominates
        # it — observed, that one sample would skew the serving
        # histograms' (TTFT/prefill/decode-step) sums and means for the
        # life of the process, so it records nothing (the trainer skips
        # its compile step for the same reason).
        try:
            with metrics.suppress():
                self.engine.generate([[1]], max_new_tokens=2)
            self.engine.finished.clear()
        except Exception as e:  # noqa: BLE001
            tracing.add_event("server.warmup_failed",
                              {"error": str(e)}, echo=True)
        self.health_reason = ""
        self._ready.set()
        while not self._stop.is_set():
            try:
                busy = self._step()
            except Exception as e:  # noqa: BLE001 — fail the in-flight
                # requests loudly; never let the serving thread die
                # while /health reports ok.
                self._burst = None   # poisoned in-flight burst, if any
                # Crash RECOVERY first (docs/robustness.md): a typed
                # recoverable dispatch failure resets the engine and
                # re-queues every in-flight request through the
                # preemption resume path — the _pending entries (and
                # their Request objects) survive, so open streams
                # continue gapless and greedy output stays
                # bit-identical. The storm guard keeps a persistently
                # dying device from recover-looping forever.
                if self._try_recover(e):
                    continue
                # Unrecoverable (or storming): fail the in-flight
                # requests. The engine's waiting/slot_req still hold
                # the poisoned requests — left in place, every
                # subsequent step would re-drive them and fail all
                # future traffic with the same error (advisor r3).
                # Reset the slot state; if even that fails the device
                # is gone: flip /health to 503 so the LB stops routing
                # here. Health flips BEFORE the pending events fire: a
                # client reacting to its failed request must not race
                # a still-green /health.
                try:
                    self.engine.reset()
                except Exception as e2:  # noqa: BLE001
                    tracing.add_event("server.engine_reset_failed",
                                      {"error": str(e2)}, echo=True)
                    self.health_reason = "engine reset failed"
                    self._ready.clear()
                for p in self._pending.values():
                    p.result = {"error": f"engine failure: {e}"}
                    if p.stream:
                        p.chunks.put({"error": p.result["error"]})
                    p.event.set()
                self._pending.clear()
                # The gauge tracks _pending; left stale it would report
                # the pre-failure in-flight count for the whole outage
                # window — exactly when an operator reads it.
                PENDING_REQUESTS.set(0)
                busy = False
            if not busy:
                time.sleep(0.002)

    def _try_recover(self, e: BaseException) -> bool:
        """Attempt engine crash recovery for a typed recoverable
        dispatch failure. Loop-thread only. Returns True when the
        engine reset and re-queued its in-flight requests (the step
        loop just continues); False routes to the fail-all path."""
        if not (getattr(e, "recoverable", False)
                and hasattr(self.engine, "recover")):
            return False
        now = time.monotonic()
        self._recover_times = [
            t for t in self._recover_times
            if now - t < self._storm_window_s]
        if len(self._recover_times) >= self._storm_limit:
            tracing.add_event(
                "server.recovery_storm",
                {"recoveries": len(self._recover_times),
                 "window_s": self._storm_window_s,
                 "error": str(e)}, echo=True)
            return False
        self._recover_times.append(now)
        try:
            n = self.engine.recover(e)
        except Exception as e2:  # noqa: BLE001 — reset itself failed;
            # the fail-all path will retry it and flip health.
            tracing.add_event("server.engine_recover_failed",
                              {"error": str(e2)}, echo=True)
            return False
        tracing.add_event(
            "server.engine_recovered",
            {"seam": getattr(e, "seam", None), "victims": n,
             "error": str(e)}, echo=True)
        return True

    def _drain_inbox(self) -> None:
        with self._inbox_lock:
            new, self._inbox = self._inbox, []
            INBOX_DEPTH.set(0)
        for tokens, max_new, p, trace_ctx, tenant, priority, adapter, \
                handoff in new:
            # Optional kwargs only when they carry signal: simple
            # engine doubles (and older engines) without the kwargs
            # keep working.
            kwargs = {}
            if trace_ctx is not None:
                kwargs["trace_ctx"] = trace_ctx
            if tenant != qos_lib.DEFAULT_TENANT:
                kwargs["tenant"] = tenant
            if priority:
                kwargs["priority"] = priority
            if adapter is not None:
                kwargs["adapter"] = adapter
            if handoff is not None:
                # Disaggregated decode tier: install the prefill
                # tier's exported KV blocks into this engine's prefix
                # cache (loop thread — the only engine toucher), then
                # admit prompt + committed through the ordinary
                # preemption-resume path. A failed/skipped import
                # (dry pool, geometry mismatch) is a COLD resume, not
                # an error: the output is bit-identical either way.
                committed = list(handoff.get("committed") or [])
                export = handoff.get("export")
                imp = getattr(self.engine, "import_prefix", None)
                if export is not None and imp is not None:
                    try:
                        imp(list(tokens) + committed, export,
                            salt=export.get("salt", b""))
                    except Exception as e:  # noqa: BLE001 — cold
                        # resume; the request must still run.
                        tracing.add_event(
                            "server.handoff_import_failed",
                            {"error": str(e)}, echo=True)
                kwargs["committed"] = committed
            rid = self.engine.add_request(tokens, max_new, **kwargs)
            # add_request appends to engine.waiting; keep the Request so
            # emitted tokens can be diffed without a rid->req search.
            p.req = self.engine.waiting[-1]
            assert p.req.rid == rid
            # TTFT counts from when the handler enqueued the request,
            # not when the loop got around to admitting it.
            p.req.submit_s = p.enqueued_s
            self._pending[rid] = p
        if new:
            PENDING_REQUESTS.set(len(self._pending))

    def _flush_streams(self) -> None:
        """Push newly decoded tokens to every pending stream. Works for
        admission-time first tokens and burst tokens alike — it diffs
        req.tokens against the cursor. Blocking requests skip the chunk
        queue entirely (nobody drains it)."""
        for p in self._pending.values():
            if p.req is None or not p.stream:
                continue
            new = p.req.tokens[p.cursor:]
            if new:
                p.cursor += len(new)
                p.chunks.put({"tokens": list(new)})

    @timeline.event(name="skytpu_server_wave_flush_seconds",
                    histogram=WAVE_FLUSH_SECONDS)
    def _on_wave(self) -> None:
        # After each admission wave: stream its first tokens, then pull
        # any requests that arrived DURING the wave's prefill into this
        # same admission pass (engine._admit keeps looping while
        # waiting+free slots exist) — they'd otherwise sit through a
        # decode burst first.
        self._flush_streams()
        self._drain_inbox()

    def _complete_burst(self) -> None:
        """Land the outstanding async burst: fetch its tokens (host
        sync), run retire bookkeeping, stream what it decoded."""
        if self._burst is not None:
            handle, self._burst = self._burst, None
            self.engine.complete_decode_burst(handle)
            BURST_FLUSHES.inc()
            self._flush_streams()

    def _step(self) -> bool:
        self._drain_inbox()
        eng = self.engine
        chunking = getattr(eng, "chunking", None)
        if not (eng.waiting or eng.slot_req or chunking
                or self._burst is not None):
            return False
        # Coalesce a filling wave: more arrivals are in flight when the
        # last one is only milliseconds old. Never waits when the wave
        # is already full, slots are exhausted, or traffic has gone
        # quiet — and the wait is bounded by one coalesce_s total.
        if eng.waiting and eng.free_slots:
            target = min(getattr(eng, "max_wave", None)
                         or len(eng.free_slots),
                         len(eng.free_slots))
            deadline = time.monotonic() + self.coalesce_s
            while (len(eng.waiting) < target
                   and time.monotonic() < deadline
                   and time.monotonic() - self._last_arrival
                       < self.coalesce_s):
                time.sleep(0.002)
                self._drain_inbox()
        # Admission has strict priority over decode — but it needs
        # accurate slot state, so the outstanding burst lands first
        # (retirements there may free the very slots admission wants).
        if eng.waiting:
            self._complete_burst()
            admit = bool(eng.free_slots)
            if (not admit and eng.slot_req
                    and getattr(eng, "qos", None) is not None):
                # Saturated replica: admission is the only path into
                # the engine's priority-preemption pass, so it must
                # still run when a queued request outranks a resident —
                # otherwise the priority lanes are dead exactly when
                # every slot is held, the one situation they exist for.
                floor = min(r.priority for r in eng.slot_req.values())
                admit = any(w.priority > floor for w in eng.waiting)
            if eng.waiting and admit:
                eng.admit(on_wave=self._on_wave)
                self._flush_streams()
        if chunking:
            # Interference scheduler: land the outstanding burst, run
            # ONE prefill chunk, then fall through to dispatch the next
            # decode burst — chunk -> decode alternation, so a long
            # prompt's prefill never stalls decode slots for more than
            # one chunk and TPOT stops spiking during admission waves.
            self._complete_burst()
            eng.prefill_chunk_step()
            self._flush_streams()   # final chunk emits a first token
        if eng.slot_req:
            quiet = (time.monotonic() - self._last_arrival
                     > self.open_window_s)
            # While a chunked prefill is in flight, bursts stay short
            # regardless of slot pressure: the alternation granularity
            # IS the chunked-prefill TTFT bound. ``chunking`` is the
            # engine's live deque — its truthiness reflects claims made
            # by the admit call above.
            k = (self.max_burst
                 if (not eng.free_slots or quiet) and not chunking
                 else self.open_burst)
            if self._async_decode:
                # Dispatch the NEXT burst before fetching the previous
                # one: the device decodes while this thread streams.
                nxt = eng.dispatch_decode_burst(max_burst=k)
                self._complete_burst()
                self._burst = nxt
            else:
                eng.decode_burst(max_burst=k)
                self._flush_streams()
        else:
            self._complete_burst()
        for req in self.engine.finished:
            p = self._pending.pop(req.rid, None)
            if p is None:
                continue
            err = getattr(req, "error", None)
            if err is not None:
                # Typed per-request failure (adapter load failed): the
                # body rides verbatim with the error's HTTP status —
                # the engine never substituted base-model output.
                err = dict(err)
                status = err.pop("http_status", 500)
                p.result = {"error": err, "http_status": status}
                if p.stream:
                    p.chunks.put({"error": err})
                p.event.set()
                continue
            ttft = ((req.first_token_s - req.submit_s) * 1e3
                    if req.first_token_s is not None else None)
            ttft = round(ttft, 2) if ttft is not None else None
            cached = getattr(req, "cached_len", 0)
            p.result = {
                "tokens": req.tokens,
                "ttft_ms": ttft,
                # Per-request prefix-cache stats (the response
                # trailer): how much prefill this request skipped.
                "cache_hit": bool(cached),
                "cached_tokens": cached,
                "prefill_chunks": getattr(req, "n_chunks", 0),
                # Speculative-decode stats: how much of the decode this
                # request's drafts covered (accepted / drafted), and
                # which drafter rung served it last (model|ngram|off —
                # the acceptance-collapse ladder's resting place).
                "spec_drafted": getattr(req, "spec_drafted", 0),
                "spec_accepted": getattr(req, "spec_accepted", 0),
                "drafter": getattr(req, "spec_mode", None) or "off",
                # QoS: how often this request was preempted-by-
                # eviction and resumed (0 on the single-tenant path).
                "preemptions": getattr(req, "preemptions", 0),
                # Fault tolerance: engine crash recoveries this
                # request rode through (re-admitted via the same
                # resume path, output bit-identical).
                "recoveries": getattr(req, "recoveries", 0),
                # Adapter catalog: which fine-tune generated this
                # (None = the base model).
                "model": getattr(req, "adapter", None),
            }
            if p.export_prefix:
                # Disaggregated prefill tier: snapshot the stored
                # prefix's blocks for the /prefill response. Runs on
                # the loop thread (one fixed-shape gather + host
                # fetch); the entry stays a ref-counted LRU resident
                # here, so a lost handoff leaks nothing. None when no
                # prefix is resident (evicted under pool pressure
                # between store and retire) — the LB falls back to
                # single-tier.
                exp_fn = getattr(self.engine, "export_prefix_for",
                                 None)
                p.result["export"] = (exp_fn(req)
                                      if exp_fn is not None else None)
            if p.stream:
                p.chunks.put({"done": True, "ttft_ms": ttft,
                              "n_tokens": len(req.tokens),
                              "cache_hit": bool(cached),
                              "cached_tokens": cached,
                              "spec_drafted":
                                  getattr(req, "spec_drafted", 0),
                              "spec_accepted":
                                  getattr(req, "spec_accepted", 0),
                              "drafter":
                                  getattr(req, "spec_mode", None)
                                  or "off",
                              "preemptions":
                                  getattr(req, "preemptions", 0),
                              "recoveries":
                                  getattr(req, "recoveries", 0)})
            p.event.set()
        if self.engine.finished:
            PENDING_REQUESTS.set(len(self._pending))
        self.engine.finished.clear()
        return True

    def shutdown(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)


class _Threading(ThreadingMixIn, HTTPServer):
    daemon_threads = True
    # A burst of concurrent clients (the LB fan-in) overflows the
    # default listen backlog of 5 -> connection resets under load.
    request_queue_size = 128


_KNOWN_ROUTES = frozenset({"/health", "/healthz", "/metrics",
                           "/generate", "/prefill", "/handoff",
                           "/drain", "/debug/flight",
                           "/debug/forensics"})


def encode_export(export: Dict) -> Dict:
    """JSON-safe wire form of an engine prefix export (the /prefill
    response body's ``export`` field): block tensors as base64 raw
    bytes + shape/dtype, the adapter salt as base64. bfloat16 scale
    planes widen to float32 on the wire (exact, and the receiver's
    scatter casts back), so every wire dtype is plain numpy."""
    import base64

    import numpy as np
    tensors = {}
    for name, arr in export["tensors"].items():
        arr = np.ascontiguousarray(arr)
        if str(arr.dtype) == "bfloat16":
            arr = arr.astype(np.float32)
        tensors[name] = {
            "shape": list(arr.shape), "dtype": str(arr.dtype),
            "data": base64.b64encode(arr.tobytes()).decode()}
    return {"cached_len": int(export["cached_len"]),
            "kv_block": int(export["kv_block"]),
            "n_blocks": int(export["n_blocks"]),
            "salt": base64.b64encode(export.get("salt")
                                     or b"").decode(),
            "tensors": tensors}


def decode_export(wire: Dict) -> Dict:
    """Inverse of :func:`encode_export` — the dict
    ``InferenceEngine.import_prefix`` consumes. Raises ValueError /
    KeyError / TypeError on malformed wire payloads (the /handoff
    handler maps those to a 400)."""
    import base64

    import numpy as np
    tensors = {}
    for name, spec in wire["tensors"].items():
        arr = np.frombuffer(
            base64.b64decode(spec["data"]),
            dtype=np.dtype(str(spec["dtype"]))).reshape(
                [int(d) for d in spec["shape"]])
        tensors[str(name)] = arr
    return {"cached_len": int(wire["cached_len"]),
            "kv_block": int(wire["kv_block"]),
            "n_blocks": int(wire["n_blocks"]),
            "salt": base64.b64decode(wire.get("salt") or ""),
            "tensors": tensors}


def make_handler(model: ModelServer):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def _observe(self, code: int) -> None:
            route = self.path.split("?", 1)[0]
            if route not in _KNOWN_ROUTES:
                # Label children are never evicted; arbitrary scanner
                # paths must not mint unbounded series.
                route = "other"
            HTTP_REQUESTS.labels(route=route, code=str(code)).inc()
            t0 = getattr(self, "_t0", None)
            if t0 is not None:
                HTTP_SECONDS.labels(route=route).observe(
                    time.monotonic() - t0)
                self._t0 = None

        def _json(self, code, obj, headers=None):
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)
            self._observe(code)

        def do_GET(self):
            self._t0 = time.monotonic()
            if self.path == "/health":
                if model._draining:
                    # 503 stops the LB/controller routing here — the
                    # point of the drain; in-flight work continues.
                    return self._json(503, {"status": "draining"},
                                      headers={"Retry-After": "1"})
                if model._ready.is_set():
                    return self._json(200, {"status": "ok"})
                return self._json(503, {"status": "warming"})
            if self.path == "/healthz":
                # The fleet health model's shape: always 200 (the
                # probe succeeded), status carries the verdict.
                if model._draining:
                    depth = model.queue_depth()
                    past = (depth > 0
                            and time.time() > model._drain_deadline_s)
                    health_lib.write_healthz(
                        self,
                        health_lib.DEGRADED if past
                        else health_lib.DRAINING,
                        reason=(f"draining past deadline "
                                f"({depth} in flight)" if past
                                else f"draining ({depth} in flight)"))
                    return self._observe(200)
                ready = model._ready.is_set()
                health_lib.write_healthz(
                    self,
                    health_lib.HEALTHY if ready else health_lib.DEGRADED,
                    reason=model.health_reason)
                return self._observe(200)
            if self.path == "/metrics":
                metrics.write_exposition(self)
                return self._observe(200)
            if self.path.split("?", 1)[0] == "/debug/flight":
                # Burst-level introspection: the engine's in-process
                # flight ring + compile-watch registry (no flush
                # needed — this reads live state). ?n= caps the
                # record tail (default 128).
                n = 128
                since = None
                if "?" in self.path:
                    from urllib.parse import parse_qs
                    qs = parse_qs(self.path.split("?", 1)[1])
                    try:
                        n = max(int(qs.get("n", ["128"])[0]), 1)
                    except ValueError:
                        pass
                    try:
                        if "since" in qs:
                            since = int(qs["since"][0])
                    except ValueError:
                        pass
                eng = model.engine
                fl = getattr(eng, "flight", None)
                watch = getattr(eng, "compile_watch", None)
                # Device-truth attribution (PR 16): the calibrated
                # per-program device-time EWMAs and the HBM ledger
                # ride the same live-state read — skytpu flight
                # renders host-vs-device and headroom without a
                # second endpoint.
                devtime = getattr(eng, "devtime", None)
                ledger = getattr(eng, "hbm_ledger", None)
                # ?since=<seq> is the incremental cursor: only records
                # the recorder stamped AFTER that sequence number come
                # back (``skytpu flight --follow`` tails the ring by
                # re-sending the returned "seq" instead of refetching
                # 8192 records per poll).
                if fl is None:
                    records: list = []
                elif since is not None:
                    records = fl.since(since)
                else:
                    records = fl.tail(n)
                return self._json(200, {
                    "records": records,
                    "seq": fl.seq() if fl is not None else 0,
                    "enabled": bool(fl is not None and fl.enabled),
                    "programs": (watch.summary()
                                 if watch is not None else {}),
                    "warm": bool(watch is not None and watch.warm),
                    "unexpected": (watch.unexpected
                                   if watch is not None else []),
                    "devtime": (devtime.summary()
                                if devtime is not None else {}),
                    "hbm": (ledger.snapshot()
                            if ledger is not None else {}),
                })
            if self.path.split("?", 1)[0] == "/debug/forensics":
                # Request forensics: bare — the engine's streaming
                # tail estimates + pinned-exemplar summaries;
                # ?rid=<id> — that request's critical-path ledger
                # assembled from the live flight ring (falling back
                # to a pinned exemplar once the ring rolled over),
                # what `skytpu why <rid>` renders.
                rid = None
                if "?" in self.path:
                    from urllib.parse import parse_qs
                    qs = parse_qs(self.path.split("?", 1)[1])
                    try:
                        if "rid" in qs:
                            rid = int(qs["rid"][0])
                    except ValueError:
                        return self._json(400, {"error": "bad rid"})
                eng = model.engine
                fl = getattr(eng, "flight", None)
                tail = getattr(eng, "tail", None)
                store = getattr(eng, "exemplars", None)
                if rid is None:
                    return self._json(200, {
                        "enabled": bool(getattr(eng, "forensics",
                                                False)),
                        "tail": (tail.snapshot()
                                 if tail is not None else {}),
                        "exemplars": (store.list()
                                      if store is not None else []),
                    })
                from skypilot_tpu.observability import (
                    forensics as forensics_lib)
                recs = fl.tail() if fl is not None else []
                ledger = forensics_lib.ledger_from_records(rid, recs)
                records = forensics_lib.records_for(rid, recs)
                exemplar = (store.get(rid)
                            if store is not None else None)
                if ledger is None and exemplar is not None:
                    # Ring rolled over; the pinned evidence is the
                    # whole point of the exemplar store.
                    ledger = exemplar.get("ledger")
                    records = exemplar.get("records") or []
                if ledger is None:
                    return self._json(404, {
                        "error": f"no retired request {rid} in the "
                                 f"flight ring or exemplar store"})
                return self._json(200, {
                    "rid": rid, "ledger": ledger,
                    "records": records,
                    "exemplar": exemplar is not None,
                })
            return self._json(404, {"error": "not found"})

        def _stream(self, chunks):
            """Chunked NDJSON: tokens flow as the engine decodes them."""
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()

            def write_chunk(data: bytes) -> None:
                # ONE write per chunk: the handler's wfile is unbuffered
                # (http.server wbufsize=0), so separate size/data/CRLF
                # writes would be three syscalls — and three chances for
                # the kernel to emit small segments — per streamed token
                # batch.
                self.wfile.write(b"%x\r\n%s\r\n" % (len(data), data))

            code = 200
            try:
                for chunk in chunks:
                    # Chaos: a replica.kill fault here drops the
                    # connection mid-stream with NO terminal chunk —
                    # to the LB this replica just got SIGKILLed, which
                    # is exactly what the mid-stream failover path
                    # must recover from.
                    chaos.point("replica.kill", route="/generate")
                    write_chunk(json.dumps(chunk).encode() + b"\n")
            except chaos.ChaosError:
                code = 500
                self.close_connection = True
                return
            except ConnectionError:
                # Client went away mid-stream (broken pipe OR a reset —
                # flaky LBs produce both): count it as 499 (client
                # closed request), not a success.
                code = 499
                return
            finally:
                self._observe(code)
            try:
                self.wfile.write(b"0\r\n\r\n")
            except ConnectionError:
                pass

        def do_POST(self):
            self._t0 = time.monotonic()
            # Chunked request bodies have no Content-Length; reading
            # them is unimplemented, and NOT reading them would leave
            # unread bytes on a keep-alive socket — the next request
            # on the connection would parse the stale body as its
            # request line. 411 + close is the honest answer.
            if "chunked" in (self.headers.get("Transfer-Encoding")
                             or "").lower():
                self.close_connection = True
                return self._json(411, {"error": {
                    "type": "length_required",
                    "message": "chunked request bodies are not "
                               "supported; send Content-Length"}})
            if self.path == "/drain":
                length = int(self.headers.get("Content-Length") or 0)
                try:
                    body = json.loads(self.rfile.read(length)
                                      or b"{}")
                    grace = float(body.get("grace_s", 30.0))
                except (ValueError, TypeError, AttributeError):
                    return self._json(
                        400, {"error": "bad drain request"})
                return self._json(200, model.start_drain(grace))
            if self.path not in ("/generate", "/prefill", "/handoff"):
                return self._json(404, {"error": "not found"})
            if model._draining:
                # Typed drain shed: the LB treats the 503 as a
                # connection-level failure and retries the request on
                # a surviving replica; direct clients back off per
                # Retry-After. Consume the body first — an unread
                # body on a keep-alive socket corrupts the NEXT
                # request on the connection.
                self.rfile.read(
                    int(self.headers.get("Content-Length") or 0))
                return self._json(
                    503,
                    {"error": {
                        "type": "draining",
                        "message": "replica is draining; retry "
                                   "against another replica"}},
                    headers={"Retry-After": "1"})
            length = int(self.headers.get("Content-Length") or 0)
            try:
                body = json.loads(self.rfile.read(length) or b"{}")
                tokens = [int(t) for t in body["tokens"]]
                max_new = int(body.get("max_new_tokens", 64))
                stream = bool(body.get("stream", False))
                # Adapter catalog: the fine-tune this request targets.
                # HEADER FIRST, body ``model`` (the SDK path) as the
                # fallback — the LB resolves in exactly this order
                # (it never parses the body when the header is
                # present), and the two tiers must agree or a request
                # carrying both would route/validate under one
                # adapter and be served under another. None/"" = the
                # base model.
                from skypilot_tpu.infer import adapters as ad_lib
                model_name = (self.headers.get(ad_lib.MODEL_HEADER)
                              or body.get("model"))
                # `or None` AFTER the strip: a whitespace-only header
                # must read as the base model at BOTH tiers (the LB
                # normalizes the same way) — not 404 here while the
                # LB routed it as base.
                model_name = (str(model_name).strip()[:128] or None
                              if model_name else None)
            except (ValueError, TypeError, KeyError) as e:
                return self._json(400, {"error": f"bad request: {e}"})
            trace_ctx = tracing.parse_traceparent(
                self.headers.get("traceparent"))
            # Multi-tenant QoS: identity from header/body, then the
            # token-bucket + overload check BEFORE any engine state is
            # touched. A shed is a typed client signal (429
            # rate_limited / 503 overloaded with Retry-After), never
            # a 500 — the LB runs the same check one hop earlier.
            tenant, priority = qos_lib.request_identity(
                self.headers, body,
                cfg=model.qos.cfg if model.qos is not None else None)
            if model.qos is not None:
                try:
                    model.qos.admit(tenant, depth=model.queue_depth())
                except qos_lib.ShedError as e:
                    return self._json(
                        e.http_status, {"error": e.typed_error},
                        headers={"Retry-After": e.retry_after_header()})
            # Client errors carry a typed body when the engine minted
            # one (PromptTooLongError.typed_error — a prompt past the
            # largest bucket is the caller's fault, never a 500; an
            # unknown adapter name rides its 404 the same way).
            def _bad_request(e):
                return self._json(
                    getattr(e, "http_status", 400),
                    {"error": getattr(e, "typed_error", None) or str(e)})

            if self.path == "/prefill":
                # Disaggregated prefill tier (docs/serving.md
                # §Disaggregated serving): run chunked admission to
                # completion (ONE committed token), export the stored
                # prefix's blocks, and return both — the LB hands them
                # to a decode replica. Blocking JSON only; the decode
                # tier owns streaming. An ineligible request (or a
                # prefix evicted under pool pressure before export) is
                # a typed 409 the LB answers by falling back to
                # ordinary single-tier routing — never an error the
                # client sees.
                elig = getattr(model.engine, "handoff_eligible", None)
                if elig is None or not elig(tokens, max_new):
                    return self._json(409, {"error": {
                        "type": "handoff_ineligible",
                        "message": "request cannot hand off (prompt "
                                   "shorter than one prefill chunk, "
                                   "single-token budget, or prefix "
                                   "cache off); route single-tier"}})
                try:
                    out = model.submit(tokens, 1, trace_ctx=trace_ctx,
                                       tenant=tenant,
                                       priority=priority,
                                       adapter=model_name,
                                       export_prefix=True)
                except ValueError as e:
                    return _bad_request(e)
                if "error" in out:
                    return self._json(out.pop("http_status", 500), out)
                export = out.pop("export", None)
                if export is None:
                    return self._json(409, {"error": {
                        "type": "handoff_ineligible",
                        "message": "prefix evicted before export "
                                   "(pool pressure); route "
                                   "single-tier"}})
                out["committed"] = out.pop("tokens")
                out["export"] = encode_export(export)
                return self._json(200, out)

            if self.path == "/handoff":
                # Disaggregated decode tier: import the prefill tier's
                # exported blocks, then resume prompt + committed
                # through the ordinary prefix-resume path — a
                # preemption with a network hop. The committed tokens
                # stream immediately (cursor starts at 0), so the
                # client's TTFT is the prefill tier's.
                try:
                    committed = [int(t) for t in
                                 body.get("committed") or []]
                    export = (decode_export(body["export"])
                              if body.get("export") else None)
                except (ValueError, TypeError, KeyError) as e:
                    return self._json(
                        400, {"error": f"bad handoff: {e}"})
                handoff = {"committed": committed, "export": export}
                if stream:
                    try:
                        chunks = model.submit_stream(
                            tokens, max_new, trace_ctx=trace_ctx,
                            tenant=tenant, priority=priority,
                            adapter=model_name, handoff=handoff)
                    except ValueError as e:
                        return _bad_request(e)
                    return self._stream(chunks)
                try:
                    out = model.submit(tokens, max_new,
                                       trace_ctx=trace_ctx,
                                       tenant=tenant,
                                       priority=priority,
                                       adapter=model_name,
                                       handoff=handoff)
                except ValueError as e:
                    return _bad_request(e)
                if "error" in out:
                    return self._json(out.pop("http_status", 500), out)
                return self._json(200, out)

            if stream:
                try:
                    chunks = model.submit_stream(tokens, max_new,
                                                 trace_ctx=trace_ctx,
                                                 tenant=tenant,
                                                 priority=priority,
                                                 adapter=model_name)
                except ValueError as e:  # oversized prompt, 404 etc.
                    return _bad_request(e)
                return self._stream(chunks)
            try:
                out = model.submit(tokens, max_new, trace_ctx=trace_ctx,
                                   tenant=tenant, priority=priority,
                                   adapter=model_name)
            except ValueError as e:      # oversized prompt, 404 etc.
                return _bad_request(e)
            if "error" in out:
                return self._json(out.pop("http_status", 500), out)
            return self._json(200, out)

        def log_message(self, *a):
            pass

    return Handler


def serve(engine, host: str = "0.0.0.0", port: int = 8080,
          max_burst: int = 8, open_burst: int = 4,
          open_window_s: float = 1.0, coalesce_s: float = 0.012,
          qos: Optional[qos_lib.AdmissionController] = None):
    model = ModelServer(engine, max_burst=max_burst,
                        open_burst=open_burst,
                        open_window_s=open_window_s,
                        coalesce_s=coalesce_s, qos=qos)
    httpd = _Threading((host, port), make_handler(model))
    return model, httpd


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default=None)
    ap.add_argument("--port", type=int, default=8080)
    ap.add_argument("--slots", type=int, default=16)
    ap.add_argument("--max-len", type=int, default=1024)
    ap.add_argument("--kv-int8", action="store_true",
                    help="int8 KV cache: half the HBM per token")
    ap.add_argument("--weights-int8", action="store_true",
                    help="w8a8 decode: int8 weights + activations")
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--max-burst", type=int, default=8,
                    help="decode tokens per device call (streaming "
                         "granularity vs dispatch amortization)")
    ap.add_argument("--open-burst", type=int, default=4,
                    help="decode burst while free slots remain AND "
                         "traffic arrived within --open-window — keeps "
                         "late arrivals from waiting out a full burst "
                         "before their prefill")
    ap.add_argument("--open-window", type=float, default=1.0,
                    help="seconds since the last arrival during which "
                         "bursts stay short when slots are free; after "
                         "a quiet spell bursts go long (dispatch "
                         "amortization on a partially loaded server)")
    ap.add_argument("--admit-wave", type=int, default=8,
                    help="admission wave cap: early waves' first "
                         "tokens stream while later waves prefill "
                         "(0 = uncapped)")
    ap.add_argument("--coalesce", type=float, default=0.012,
                    help="seconds to wait for a filling admission wave "
                         "when the newest arrival is fresher than this "
                         "(prevents 1-row padded waves on bursts)")
    ap.add_argument("--prefill-chunk", type=int, default=None,
                    help="chunked prefill: prompts longer than this "
                         "prefill in fixed chunks interleaved with "
                         "decode bursts (0 disables; default env "
                         "SKYTPU_PREFILL_CHUNK or 512)")
    ap.add_argument("--prefix-pool", type=int, default=None,
                    help="prefix KV cache: resident prompt prefixes "
                         "for suffix-only prefill on shared system "
                         "prompts (paged: ref-counted shared blocks; "
                         "contiguous: reserved pool rows; 0 disables; "
                         "default env SKYTPU_PREFIX_POOL or 8)")
    ap.add_argument("--kv-block", type=int, default=None,
                    help="paged KV cache block length: slots rent "
                         "blocks for rows they actually use instead "
                         "of a contiguous max-len row, so slot count "
                         "is bounded by tokens, not worst-case length "
                         "(0 = contiguous layout; default env "
                         "SKYTPU_KV_BLOCK or 256)")
    ap.add_argument("--kv-blocks", type=int, default=None,
                    help="paged KV pool size in blocks (default env "
                         "SKYTPU_KV_BLOCKS, or the contiguous-"
                         "equivalent HBM: (slots+1)*max_len/block)")
    ap.add_argument("--span-buckets", default=None,
                    help="span-bucketed decode attention: comma-"
                         "separated ladder of KV-row spans (each "
                         "decode/verify/chunk program compiles per "
                         "rung and reads only that many rows, so "
                         "decode bandwidth tracks the active span, "
                         "not --max-len). "
                         "Default: max_len/8,/4,/2 ladder "
                         "(env SKYTPU_SPAN_BUCKETS); 0 disables "
                         "(full-view reads only)")
    ap.add_argument("--kv-kernel", action="store_true",
                    default=None,
                    help="Pallas paged decode-attention kernel: "
                         "decode/verify/chunk big-cache reads walk "
                         "each slot's block table in-kernel instead "
                         "of materializing the gathered logical view "
                         "per layer (paged layouts only; contiguous "
                         "falls back to the gather, which also stays "
                         "the greedy-parity oracle). Default env "
                         "SKYTPU_KV_KERNEL=1")
    ap.add_argument("--kv-lazy", action="store_true",
                    default=None,
                    help="lazy paged-KV growth: admission reserves "
                         "prompt + one burst of blocks instead of "
                         "the full max_new_tokens worst case; the "
                         "rest allocates at burst dispatch (dry pool "
                         "= the slot sits a burst out). Default env "
                         "SKYTPU_KV_LAZY; eager reservation is the "
                         "default")
    ap.add_argument("--spec-k", type=int, default=None,
                    help="speculative decoding: draft up to K tokens "
                         "per slot per burst and verify them in one "
                         "device call — up to K+1 committed tokens "
                         "per decode dispatch, greedy output "
                         "bit-preserved (0 disables; forced off under "
                         "--temperature > 0; default env SKYTPU_SPEC_K "
                         "or 4)")
    ap.add_argument("--draft-model", default=None,
                    help="model-backed speculative drafter: 'self:N' "
                         "(truncated-layer draft sharing the target's "
                         "first N blocks — zero extra weights) or a "
                         "llama config name (e.g. llama3-400m; a "
                         "distilled checkpoint's config). The draft "
                         "model runs the engine's own staged-burst "
                         "program on its own paged KV, advanced/"
                         "rolled-back in lockstep with the verifier; "
                         "unset = the n-gram drafter only (env "
                         "SKYTPU_DRAFT_MODEL)")
    ap.add_argument("--spec-pipeline", type=int, default=None,
                    help="async draft/verify pipeline (model drafter "
                         "only): 1 = dispatch the next round's draft "
                         "rollout while the verify is in flight, "
                         "reconciling on fetch; 0 = synchronous "
                         "draft-then-verify (default env "
                         "SKYTPU_SPEC_PIPELINE or 1)")
    ap.add_argument("--tp", type=int, default=1,
                    help="tensor-parallel degree: shard weights + KV "
                         "cache over the first N local devices "
                         "(Megatron head/mlp/vocab split — serves "
                         "models bigger than one chip's HBM)")
    ap.add_argument("--adapters", default=None,
                    help="multi-LoRA adapter catalog: JSON object of "
                         "{name: checkpoint path} (adapters.save_"
                         "adapter .npz files). Requests pick a "
                         "fine-tune via the body's 'model' field or "
                         "the x-skytpu-model header; unknown names "
                         "get a typed 404. Default env "
                         "SKYTPU_ADAPTERS (how the serve controller "
                         "hands a replica its catalog)")
    ap.add_argument("--adapter-slots", type=int, default=None,
                    help="device adapter-pool capacity (fine-tunes "
                         "resident at once; LRU hot-load/evict past "
                         "it; default env SKYTPU_ADAPTER_SLOTS or 8)")
    ap.add_argument("--adapter-rank", type=int, default=None,
                    help="adapter-pool LoRA rank (lower-rank "
                         "checkpoints zero-pad; default env "
                         "SKYTPU_ADAPTER_RANK or 16)")
    ap.add_argument("--warm-grid", action="store_true",
                    default=os.environ.get("SKYTPU_WARM_GRID") == "1",
                    help="pre-compile the engine's whole program grid "
                         "at startup and arm the compile watch: any "
                         "later XLA compile is a mid-traffic stall "
                         "and raises the typed "
                         "engine.unexpected_compile alarm + "
                         "skytpu_unexpected_compiles_total (env "
                         "SKYTPU_WARM_GRID=1). Off by default: "
                         "startup pays the full compile sweep")
    args = ap.parse_args()

    # Long-lived serving daemon: sever any inherited trace root. A
    # server launched as a task inherits SKYTPU_TRACEPARENT from the
    # launch request's rpc chain — without this, every headerless
    # /generate for the life of the server would attach its engine
    # spans to that ONE launch trace (the same spawn-time-root
    # misattribution the skylet avoids via the persisted arm context).
    # Requests that carry their own traceparent are unaffected.
    os.environ.pop(tracing.ENV_VAR, None)
    tracing.set_process_name("model-server")

    import jax

    from skypilot_tpu.infer import engine as eng, sampling
    from skypilot_tpu.models import llama

    on_cpu = jax.default_backend() == "cpu"
    cfg = llama.CONFIGS[args.config or
                        ("llama3-tiny" if on_cpu else "llama3-400m")]
    mesh = None
    if args.tp > 1:
        import numpy as np
        from jax.sharding import Mesh
        devices = jax.devices()
        if len(devices) < args.tp:
            raise SystemExit(f"--tp {args.tp} needs {args.tp} devices, "
                             f"found {len(devices)}")
        mesh = Mesh(np.array(devices[:args.tp]), ("tp",))
        # Sharded-at-init: each device materializes only its shards —
        # a plain init_params would build the full fp tree on device 0
        # and OOM exactly the bigger-than-one-chip models --tp exists
        # for.
        params = eng.InferenceEngine.sharded_init(cfg, mesh)
    else:
        params = llama.init_params(jax.random.key(0), cfg)
    # "--span-buckets 0" disables bucketing; a comma list is an
    # explicit ladder; unset falls through to the engine default /
    # SKYTPU_SPAN_BUCKETS.
    span_buckets = None
    if args.span_buckets is not None:
        rungs = [int(t) for t in
                 args.span_buckets.replace(",", " ").split()]
        span_buckets = [r for r in rungs if r > 0] or 0
    # Multi-LoRA adapter catalog (docs/serving.md §Adapter catalog):
    # a JSON {name: checkpoint path} names the replica's fine-tunes;
    # loading to device is on demand (the first request naming one
    # pays the hot-load). None = the zero-cost adapterless engine.
    from skypilot_tpu.infer import adapters as ad_lib
    catalog = ad_lib.catalog_from_env(cfg, adapters_json=args.adapters,
                                      slots=args.adapter_slots,
                                      rank=args.adapter_rank)
    # Model-backed drafter (docs/serving.md §Speculative decoding):
    # built BEFORE the engine slims the fp tree (a 'self:N' draft
    # shares the target's first N blocks by reference). None = the
    # n-gram drafter stays the only rung.
    from skypilot_tpu.infer import draft as draft_lib
    draft_engine = draft_lib.draft_engine_from_env(
        params, cfg, n_slots=args.slots, max_len=args.max_len,
        spec=args.draft_model, kv_int8=args.kv_int8)
    engine = eng.InferenceEngine(
        params, cfg, n_slots=args.slots, max_len=args.max_len,
        mesh=mesh,
        prompt_buckets=(128, min(512, args.max_len),
                        args.max_len),
        sampling_params=sampling.SamplingParams(
            temperature=args.temperature),
        kv_int8=args.kv_int8, weights_int8=args.weights_int8,
        max_wave=args.admit_wave,
        prefill_chunk=args.prefill_chunk,
        kv_block=args.kv_block, kv_blocks=args.kv_blocks,
        span_buckets=span_buckets, kv_lazy=args.kv_lazy,
        kv_kernel=args.kv_kernel,
        # Serving default: prefix reuse ON (repeated system prompts are
        # the common serving workload); the engine-level default stays
        # 0 so library users opt in.
        prefix_pool=(args.prefix_pool
                     if args.prefix_pool is not None
                     else int(os.environ.get("SKYTPU_PREFIX_POOL",
                                             "8") or 0)),
        # Serving default: speculation ON at K=4 (greedy serving is the
        # common case and a missed draft costs one empty verify slot);
        # the engine-level default stays 0 so library users opt in.
        spec_k=(args.spec_k
                if args.spec_k is not None
                else int(os.environ.get("SKYTPU_SPEC_K", "4") or 0)),
        draft_engine=draft_engine,
        spec_pipeline=(bool(args.spec_pipeline)
                       if args.spec_pipeline is not None else None),
        # One compiled prefill program per bucket: an odd wave size
        # must never hit a mid-traffic XLA compile on a live replica.
        pad_waves=True,
        # Multi-tenant QoS (SKYTPU_QOS=1): WFQ + priority lanes in the
        # engine's waiting deque. All host-side — tenant count never
        # enters program identity (the compile watch is the gate).
        qos=qos_lib.scheduler_from_env(),
        adapters=catalog)
    # The engine slims its own tree under weights_int8; drop main()'s
    # reference too or the fp block weights stay resident for the whole
    # server lifetime and the memory halving never happens.
    del params
    if args.warm_grid:
        # Compile the whole program grid BEFORE /health can flip, then
        # arm the compile watch: from here on, a new program compiling
        # under live traffic is an alarm, not tens of silent seconds
        # of TPOT (docs/observability.md §Flight recorder).
        t0 = time.time()
        n = engine.warm_programs(max_burst=args.max_burst)
        engine.declare_warmup_complete()
        tracing.add_event(
            "server.programs_warmed",
            {"programs": n,
             "warm_s": round(time.time() - t0, 2)}, echo=True)
    model, httpd = serve(engine, port=args.port,
                         max_burst=args.max_burst,
                         open_burst=args.open_burst,
                         open_window_s=args.open_window,
                         coalesce_s=args.coalesce,
                         qos=qos_lib.admission_from_env("server"))
    tracing.add_event("server.listening", {"port": args.port},
                      echo=True)
    try:
        httpd.serve_forever()
    finally:
        model.shutdown()


if __name__ == "__main__":
    main()
