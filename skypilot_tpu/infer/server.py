"""HTTP model server: continuous-batching engine behind a stdlib server.

This is what a SkyServe replica runs (see llm/serve-llama.yaml): the
load balancer probes ``/health`` and proxies ``/generate``; the engine
thread batches concurrent requests into shared decode bursts.

Endpoints:
  GET  /health              -> 200 {"status": "ok"} once warm
  POST /generate            {"tokens": [...], "max_new_tokens": N}
                            -> {"tokens": [...], "ttft_ms": ..., ...}

Reference parity: the reference's serving recipes wrap external engines
(reference: llm/vllm/serve.yaml, JetStream in examples/tpu/v6e) — this
is the in-tree TPU-native equivalent.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, HTTPServer
from socketserver import ThreadingMixIn
from typing import Dict, Optional


class _Pending:
    def __init__(self):
        self.event = threading.Event()
        self.result: Optional[Dict] = None


class ModelServer:
    """Engine + request queue + batching loop."""

    def __init__(self, engine):
        self.engine = engine
        self._lock = threading.Lock()
        self._pending: Dict[int, _Pending] = {}
        self._ready = threading.Event()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def submit(self, tokens, max_new_tokens: int) -> Dict:
        p = _Pending()
        t0 = time.time()
        with self._lock:
            rid = self.engine.add_request(list(tokens), max_new_tokens)
            self._pending[rid] = p
        p.event.wait()
        out = dict(p.result or {})
        out["total_ms"] = round((time.time() - t0) * 1e3, 2)
        return out

    def _loop(self) -> None:
        # Warm the compile path before /health flips: the load balancer
        # must not route traffic into a cold XLA compile.
        try:
            self.engine.generate([[1]], max_new_tokens=2)
            self.engine.finished.clear()
        except Exception as e:  # noqa: BLE001
            print(f"model server warmup failed: {e}", file=sys.stderr)
        self._ready.set()
        while not self._stop.is_set():
            try:
                busy = self._step()
            except Exception as e:  # noqa: BLE001 — fail the in-flight
                # requests loudly; never let the serving thread die
                # while /health reports ok.
                with self._lock:
                    for p in self._pending.values():
                        p.result = {"error": f"engine failure: {e}"}
                        p.event.set()
                    self._pending.clear()
                busy = False
            if not busy:
                time.sleep(0.002)

    def _step(self) -> bool:
        with self._lock:
            busy = bool(self.engine.waiting or self.engine.slot_req)
            if not busy:
                return False
            self.engine.step_burst(max_burst=8)
            for req in self.engine.finished:
                p = self._pending.pop(req.rid, None)
                if p is None:
                    continue
                ttft = ((req.first_token_s - req.submit_s) * 1e3
                        if req.first_token_s is not None else None)
                p.result = {
                    "tokens": req.tokens,
                    "ttft_ms": (round(ttft, 2)
                                if ttft is not None else None),
                }
                p.event.set()
            self.engine.finished.clear()
        return True

    def shutdown(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)


class _Threading(ThreadingMixIn, HTTPServer):
    daemon_threads = True


def make_handler(model: ModelServer):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def _json(self, code, obj):
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/health":
                if model._ready.is_set():
                    return self._json(200, {"status": "ok"})
                return self._json(503, {"status": "warming"})
            return self._json(404, {"error": "not found"})

        def do_POST(self):
            if self.path != "/generate":
                return self._json(404, {"error": "not found"})
            length = int(self.headers.get("Content-Length") or 0)
            try:
                body = json.loads(self.rfile.read(length) or b"{}")
                tokens = [int(t) for t in body["tokens"]]
                max_new = int(body.get("max_new_tokens", 64))
            except (ValueError, TypeError, KeyError) as e:
                return self._json(400, {"error": f"bad request: {e}"})
            try:
                out = model.submit(tokens, max_new)
            except ValueError as e:      # oversized prompt etc.
                return self._json(400, {"error": str(e)})
            if "error" in out:
                return self._json(500, out)
            return self._json(200, out)

        def log_message(self, *a):
            pass

    return Handler


def serve(engine, host: str = "0.0.0.0", port: int = 8080):
    model = ModelServer(engine)
    httpd = _Threading((host, port), make_handler(model))
    return model, httpd


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default=None)
    ap.add_argument("--port", type=int, default=8080)
    ap.add_argument("--slots", type=int, default=16)
    ap.add_argument("--max-len", type=int, default=1024)
    ap.add_argument("--kv-int8", action="store_true",
                    help="int8 KV cache: half the HBM per token")
    ap.add_argument("--weights-int8", action="store_true",
                    help="w8a8 decode: int8 weights + activations")
    ap.add_argument("--temperature", type=float, default=0.0)
    args = ap.parse_args()

    import jax

    from skypilot_tpu.infer import engine as eng, sampling
    from skypilot_tpu.models import llama

    on_cpu = jax.default_backend() == "cpu"
    cfg = llama.CONFIGS[args.config or
                        ("llama3-tiny" if on_cpu else "llama3-400m")]
    params = llama.init_params(jax.random.key(0), cfg)
    engine = eng.InferenceEngine(
        params, cfg, n_slots=args.slots, max_len=args.max_len,
        prompt_buckets=(128, min(512, args.max_len),
                        args.max_len),
        sampling_params=sampling.SamplingParams(
            temperature=args.temperature),
        kv_int8=args.kv_int8, weights_int8=args.weights_int8)
    # The engine slims its own tree under weights_int8; drop main()'s
    # reference too or the fp block weights stay resident for the whole
    # server lifetime and the memory halving never happens.
    del params
    model, httpd = serve(engine, port=args.port)
    print(f"serving on :{args.port}", file=sys.stderr, flush=True)
    try:
        httpd.serve_forever()
    finally:
        model.shutdown()


if __name__ == "__main__":
    main()
