"""Model-backed speculative drafter: a small model riding the engine's
own program machinery (docs/serving.md §Speculative decoding).

PR 8's n-gram drafter is pure host work but only pays on repetitive
text. :class:`DraftEngine` serves a REAL draft model (a llama3-400m-
class config in production; a truncated-layer draft of the target is
the zero-training starting point — :func:`truncated_draft`) and plugs
into the engine's ``spec_decode_burst`` as a *batched* drafter: K
greedy tokens per active slot per round from ONE device dispatch
(``kvcache.decode_burst_staged`` on the draft config — the identical
staged-burst program the main engine runs, at the draft model's size).

Design rules (the PAPER.md contract, restated for two models):

* **Static shapes, bounded programs.** The drafter compiles the same
  bounded grid the main engine does: one staged rollout program per
  (k, span-rung), one chunked ingest program per span rung, one
  batched sync program. Its own :class:`~skypilot_tpu.observability.
  flight.CompileWatch` guards the surface — ``warm_programs`` +
  ``declare_warmup_complete`` make a mid-traffic draft-model compile
  the same typed alarm a main-engine compile is.
* **Paged KV in lockstep.** The drafter owns a paged block-pool cache
  (same ``kvcache`` layout, block table + sentinel column). Slot ``s``
  of the drafter mirrors slot ``s`` of the main engine; its rows
  advance as the drafter rolls out and ROLL BACK exactly as the
  verifier's do — a length non-advance (``kvcache.sync_slots``), never
  a row copy or block move. Rows are content-tracked host-side
  (``_SlotState.toks``: the token backing each resident row), so after
  a verify commits ``n_commit`` tokens the longest valid row prefix is
  found by comparison and everything past it is dead by bookkeeping.
* **Correctness never depends on the draft.** The verifier is
  greedy-exact and unchanged; a bad draft only wastes verify
  positions. The drafter therefore keeps NO invariant the engine
  could violate: any state mismatch resolves to rollback + re-ingest.

The async pipeline (engine ``spec_pipeline``): while the main model's
verify dispatch is in flight, the engine calls :meth:`rollout` to run
the NEXT round's draft program against the drafter's committed-so-far
state — the drafter speculates on its own speculation (it assumes the
current draft fully accepts and predicts the verifier's bonus token as
its own next greedy token). The rollout's tokens are fetched LAZILY at
the next round's :meth:`draft_batch`, which validates them against
what the verifier actually committed: a full match serves the next
draft with zero new device work; a mispredicted round is discarded
host-side (rollback = length non-advance, free under paged blocks).
"""

from __future__ import annotations

import dataclasses
import functools
import os
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from skypilot_tpu.infer import kvcache, sampling
from skypilot_tpu.models import llama
from skypilot_tpu.observability import attribution as attribution_lib
from skypilot_tpu.observability import flight as flight_lib
from skypilot_tpu.observability import metrics


@dataclasses.dataclass
class _SlotState:
    """Host mirror of one draft slot's device state. ``toks[i]`` is
    the token whose K/V occupies row ``i`` (committed AND speculative
    rollout rows — validity is decided by comparison against the
    verifier's committed context, never trusted); ``last`` is the
    pending token the next rollout step consumes (device
    ``last_token``); ``confirmed`` bounds how far the committed
    context has already been matched, so a steady-state sync compares
    O(new tokens), not O(context)."""
    toks: List[int]
    last: Optional[int]
    confirmed: int = 0


class DraftEngine:
    """A small model + paged KV cache + the three draft programs.

    Not a request scheduler: the MAIN engine owns admission, slots and
    retirement, and drives this through three calls —
    :meth:`draft_batch` (K draft tokens per slot, syncing the draft KV
    to the verifier's committed state first), :meth:`rollout` (the
    async predraft while a verify is in flight), and :meth:`release`
    (slot retired/preempted: blocks free, state drops). Single-thread
    contract: all calls come from the engine loop thread, exactly like
    the engine's own block management.
    """

    def __init__(self, params: llama.Params, cfg: llama.LlamaConfig,
                 n_slots: int, max_len: int, kv_int8: bool = False,
                 qweights=None, kv_block: Optional[int] = None,
                 kv_blocks: Optional[int] = None, span_buckets=None,
                 ingest_chunk: Optional[int] = None, seed: int = 1):
        from skypilot_tpu.infer.engine import _span_ladder
        self.params = params
        self.cfg = cfg
        self.n_slots = n_slots
        self.max_len = max_len
        self.qweights = qweights
        # Paged block-pool layout, the engine's exact idiom: block
        # length clamped to a divisor of max_len, host-authoritative
        # table with a dirty-tracked device copy, sentinel last
        # column. The pool defaults to one full-length allocation per
        # slot (+ spare) — the draft model's KV is small, and a
        # drafter must never become the admission limiter.
        if kv_block is None:
            kv_block = int(os.environ.get("SKYTPU_DRAFT_KV_BLOCK",
                                          "256") or 0)
        self.paged = kv_block > 0
        if self.paged:
            b = min(kv_block, max_len)
            while max_len % b:
                b -= 1
            self.kv_block = b
            nb = max_len // b
            self.blocks_per_slot = nb
            self.n_kv_blocks = (kv_blocks if kv_blocks and kv_blocks > 0
                                else (n_slots + 1) * nb)
            self.allocator = kvcache.BlockAllocator(self.n_kv_blocks)
            self.block_table = np.full(
                (n_slots + 1, nb + 1), self.n_kv_blocks, np.int32)
            self._table_dev = None
            self._table_dirty = True
            self.cache = kvcache.init_paged_cache(
                cfg, n_slots + 1, self.n_kv_blocks, self.kv_block,
                kv_int8=kv_int8)
        else:
            self.kv_block = None
            self.blocks_per_slot = 0
            self.n_kv_blocks = 0
            self.allocator = None
            self.block_table = None
            self._table_dev = None
            self._table_dirty = False
            self.cache = kvcache.init_cache(cfg, n_slots + 1, max_len,
                                            kv_int8=kv_int8)
        self.span_ladder = _span_ladder(span_buckets, max_len)
        # One compiled ingest program per span rung: ``ingest_chunk``
        # is its static width (catch-up rows land in chunks of this).
        self.ingest_chunk = min(int(ingest_chunk or 256), max_len)
        self.rng = jax.random.key(seed)
        self._state: Dict[int, _SlotState] = {}
        # The one deferred rollout (async predraft): (device toks,
        # slots, k). At most one outstanding — the engine runs one
        # verify round at a time.
        self._pending_roll: Optional[
            Tuple[jax.Array, List[int], int]] = None
        # Introspection counters (tests + bench structure asserts).
        self.rollouts = 0            # rollout programs dispatched
        self.ingest_chunks = 0       # catch-up chunk programs
        self.rollbacks = 0           # speculative rows discarded
        self.reuse_hits = 0          # rounds served from a predraft
        self.decode_programs: set = set()
        self.compile_watch = flight_lib.CompileWatch()
        # Device-time calibration for the DRAFT model's programs: the
        # engine's "draft" flight records look their dev_ms_est up in
        # THIS calibrator (draft program identity is drafter-scoped,
        # exactly like its compile watch).
        self.devtime = attribution_lib.DeviceTimeCalibrator()
        self.compile_watch.calibrator = self.devtime

        sp = sampling.SamplingParams()     # drafting is argmax-only

        # The draft rollout: k greedy steps with on-device token
        # feedback — kvcache.decode_burst_staged on the DRAFT config,
        # the literal program the main engine bursts with. RNG rides
        # the signature (greedy sampling ignores it) so the program
        # shape matches the engine's; the drafter's stream is its own.
        @functools.partial(jax.jit, donate_argnums=(1, 2),
                           static_argnames=("k", "span"))
        def _rollout(params, cache, rng, active, table=None, *, k,
                     span=None, qweights=None):
            return kvcache.decode_burst_staged(
                params, cache, rng, active, k, cfg, sp,
                qweights=qweights, table=table, span=span)

        # Catch-up ingest: one chunk of committed tokens into a draft
        # slot — kvcache.prefill_chunk with ``final=False`` (no
        # sampling, no RNG split), stamping the running row count.
        @functools.partial(jax.jit, donate_argnums=(1,),
                           static_argnames=("final", "span"))
        def _ingest(params, cache, tokens_c, start, n_valid, slot,
                    new_len, rng, table=None, *, final=False,
                    span=None, qweights=None):
            return kvcache.prefill_chunk(
                params, cache, tokens_c, start, n_valid, slot,
                new_len, rng, cfg, sp, final=final, qweights=qweights,
                table=table, span=span)

        # Lockstep/rollback: batched (length, last_token) sync — a
        # mispredicted rollout's rows die by this bookkeeping write
        # alone (kvcache.sync_slots).
        @functools.partial(jax.jit, donate_argnums=(0,))
        def _sync(cache, active, lengths, tokens):
            return kvcache.sync_slots(cache, active, lengths, tokens)

        watch = self.compile_watch.wrap
        self._rollout_fn = watch("draft_rollout", _rollout,
                                 ("k", "span"))
        self._ingest_fn = watch("draft_ingest", _ingest,
                                ("final", "span"))
        self._sync_fn = watch("draft_sync", _sync)

    # -- paged table (the engine's dirty-tracked device copy idiom) --------

    def table_device(self):
        if not self.paged:
            return None
        if self._table_dirty or self._table_dev is None:
            self._table_dev = jnp.asarray(self.block_table)
            self._table_dirty = False
        return self._table_dev

    @property
    def blocks_used(self) -> int:
        return self.allocator.used if self.paged else 0

    # -- span buckets ------------------------------------------------------

    def _span_for(self, rows: int) -> int:
        for s in self.span_ladder:
            if rows <= s:
                return s
        return self.span_ladder[-1]

    def _span_arg(self, span: int) -> Optional[int]:
        return None if span >= self.max_len else span

    # -- slot lifecycle ----------------------------------------------------

    def claimed(self, slot: int) -> bool:
        return slot in self._state

    def _acquire(self, slot: int) -> Optional[_SlotState]:
        """Fresh state + a full-length block allocation for a slot the
        engine started drafting on. Returns None when the draft pool
        is dry (custom-undersized pool): the slot simply gets an empty
        draft — the drafter degrades, it never stalls admission."""
        if self.paged:
            if self.allocator.available < self.blocks_per_slot:
                return None
            row = self.block_table[slot]
            row[:] = self.n_kv_blocks
            blocks = [self.allocator.alloc()
                      for _ in range(self.blocks_per_slot)]
            row[:len(blocks)] = blocks
            self._table_dirty = True
        st = _SlotState(toks=[], last=None, confirmed=0)
        self._state[slot] = st
        return st

    def release(self, slot: int) -> None:
        """Slot retired/preempted on the main engine: free its draft
        blocks and drop state. Rows a released slot leaves behind are
        dead by construction — its table row goes all-sentinel (other
        slots' rollout garbage writes for it drop) and a re-acquire
        starts from zero rows, re-ingesting everything it will read."""
        st = self._state.pop(slot, None)
        if st is None:
            return
        if self.paged:
            row = self.block_table[slot]
            for b in row[row < self.n_kv_blocks].tolist():
                self.allocator.decref(b)
            row[:] = self.n_kv_blocks
            self._table_dirty = True

    def reset(self) -> None:
        """Engine reset: drop all state (counts may be mid-failure
        inconsistent — wholesale, like the engine's allocator reset)."""
        self._state.clear()
        self._pending_roll = None
        if self.paged:
            self.allocator.reset()
            self.block_table[:] = self.n_kv_blocks
            self._table_dirty = True
        self.cache["length"] = jnp.zeros_like(self.cache["length"])

    def hbm_bytes(self) -> int:
        """Device-resident bytes the drafter holds (draft weights +
        its KV pool) — the engine's HBM ledger publishes this as the
        ``draft_pool`` component. Metadata reads only (nbytes), never
        a device fetch."""
        return (attribution_lib.tensor_bytes(self.params)
                + attribution_lib.tensor_bytes(self.qweights)
                + attribution_lib.tensor_bytes(self.cache))

    # -- drafting ----------------------------------------------------------

    def draft_batch(self, ctxs: Dict[int, Sequence[int]],
                    k: int) -> Dict[int, List[int]]:
        """Up to ``k`` draft tokens per slot, syncing each slot's
        draft KV to the verifier's committed context first.

        ``ctxs``: slot -> the request's committed context (prompt +
        committed tokens). Lockstep sync per slot: the longest row
        prefix backed by committed tokens stays (an accepted round's
        rows — and a matching predraft's — are valid by content);
        everything past it is discarded by a batched length/pending
        rollback; missing rows ingest through the chunk program. When
        a deferred predraft (:meth:`rollout`) matched what the
        verifier committed, the round is served with ZERO new device
        work — the async pipeline's win.
        """
        self._apply_pending()
        k = max(k, 1)
        preds: Dict[int, List[int]] = {}
        fix: Dict[int, Tuple[int, int]] = {}
        ctx_by_slot: Dict[int, List[int]] = {}
        need_roll: List[int] = []
        for slot, ctx in ctxs.items():
            # The caller hands a fresh per-round list (engine._ctx);
            # no defensive copy — the sync path is per slot per round
            # and an O(context) copy here is pure waste (the PR 11
            # _ctx_len lesson).
            if not isinstance(ctx, list):
                ctx = list(ctx)
            if not ctx:
                preds[slot] = []
                continue
            ctx_by_slot[slot] = ctx
            st = self._state.get(slot)
            if st is None:
                st = self._acquire(slot)
                if st is None:          # draft pool dry: degrade
                    preds[slot] = []
                    continue
            p = self._sync_slot(slot, st, ctx, fix)
            preds[slot] = p
            if len(p) >= k:
                self.reuse_hits += 1
            elif len(st.toks) + k <= self.max_len:
                need_roll.append(slot)
        if fix:
            self._dispatch_sync(fix)
        if need_roll:
            toks = self._dispatch_rollout(need_roll, k)
            # The draft path's completion fetch: the next verify
            # window needs these token VALUES host-side.
            arr = np.asarray(toks)
            self._apply_rollout(arr, need_roll, k)
            for slot in need_roll:
                st = self._state[slot]
                M = len(ctx_by_slot[slot])
                # Predictions beyond the context: O(k), never a full
                # toks+[last] concat (O(rows)) per round.
                preds[slot] = st.toks[M:] + [st.last]
        return {s: p[:k] for s, p in preds.items()}

    def rollout(self, slots: Sequence[int], k: int) -> bool:
        """Async predraft: dispatch one ``k``-step rollout for the
        given slots WITHOUT fetching (the engine calls this while its
        verify dispatch is in flight; the tokens are fetched — and
        validated against what the verify actually committed — at the
        next :meth:`draft_batch`). Slots without state or row headroom
        are skipped. Returns whether anything dispatched."""
        self._apply_pending()
        live = [s for s in slots
                if s in self._state
                and self._state[s].last is not None
                and len(self._state[s].toks) + k <= self.max_len]
        if not live or k <= 0:
            return False
        toks = self._dispatch_rollout(live, k)
        self._pending_roll = (toks, live, k)
        return True

    # -- internals ---------------------------------------------------------

    def _apply_pending(self) -> None:
        if self._pending_roll is None:
            return
        toks, slots, k = self._pending_roll
        self._pending_roll = None
        # Deferred fetch: the device finished this while the verify
        # round's fetch + commit bookkeeping ran.
        arr = np.asarray(toks)
        self._apply_rollout(arr, slots, k)

    def _apply_rollout(self, arr: np.ndarray, slots: Sequence[int],
                       k: int) -> None:
        for slot in slots:
            st = self._state.get(slot)
            if st is None:           # released mid-flight: rows dead
                continue
            p = [int(arr[j, slot]) for j in range(k)]
            st.toks.append(st.last)
            st.toks.extend(p[:-1])
            st.last = p[-1]

    def _sync_slot(self, slot: int, st: _SlotState, ctx: List[int],
                   fix: Dict[int, Tuple[int, int]]) -> List[int]:
        """Sync one slot to the committed context; returns the
        still-valid predictions beyond it ([] after a rollback)."""
        M = len(ctx)
        n = len(st.toks)
        have = n + (1 if st.last is not None else 0)
        if have >= M:
            # Compare WITHOUT materializing toks+[last] (O(rows) per
            # slot per round): seq[i] is toks[i] below n, last at n.
            i = st.confirmed
            while i < M and (st.toks[i] if i < n
                             else st.last) == ctx[i]:
                i += 1
            if i == M:
                # Full match: rows 0..M-2 are committed-backed, the
                # tail is the drafter's own consistent chain — its
                # outputs beyond the context are the live predictions
                # (O(k), the spare tail).
                st.confirmed = M - 1
                preds = st.toks[M:]
                if st.last is not None and n >= M:
                    # ``last`` sits at chain index n: a prediction
                    # only when it lies BEYOND the context (n >= M) —
                    # at n == M-1 it IS the committed pending token.
                    preds = preds + [st.last]
                return preds
        # Mismatch (or a fresh/short slot): roll back to the longest
        # committed-backed row prefix — a pure bookkeeping write, the
        # rows themselves never move (kvcache.sync_slots docstring).
        v = st.confirmed
        limit = min(len(st.toks), M - 1)
        while v < limit and st.toks[v] == ctx[v]:
            v += 1
        if v < len(st.toks):
            self.rollbacks += len(st.toks) - v
            del st.toks[v:]
        st.last = None
        if v < M - 1:
            self._ingest(slot, ctx, v, M - 1)
            st.toks.extend(ctx[v:M - 1])
        st.last = ctx[M - 1]
        st.confirmed = M - 1
        fix[slot] = (M - 1, ctx[M - 1])
        return []

    def _ingest(self, slot: int, ctx: List[int], start: int,
                upto: int) -> None:
        """Rows [start, upto) for tokens ctx[start:upto], in chunks of
        the static ingest width (one compiled program per span rung)."""
        C = self.ingest_chunk
        pos = start
        while pos < upto:
            n = min(C, upto - pos)
            chunk = np.zeros((C,), np.int32)
            chunk[:n] = ctx[pos:pos + n]
            sarg = self._span_arg(self._span_for(pos))
            self.decode_programs.add(("ingest", False, sarg))
            self.cache, self.rng, _ = self._ingest_fn(
                self.params, self.cache, jnp.asarray(chunk),
                jnp.asarray(pos, jnp.int32),
                jnp.asarray(n, jnp.int32),
                jnp.asarray(slot, jnp.int32),
                jnp.asarray(pos + n, jnp.int32), self.rng,
                self.table_device(), final=False, span=sarg,
                qweights=self.qweights)
            self.ingest_chunks += 1
            pos += n

    def _dispatch_sync(self, fix: Dict[int, Tuple[int, int]]) -> None:
        active = np.zeros((self.n_slots + 1,), bool)
        lengths = np.zeros((self.n_slots + 1,), np.int32)
        tokens = np.zeros((self.n_slots + 1,), np.int32)
        for slot, (ln, tok) in fix.items():
            active[slot] = True
            lengths[slot] = ln
            tokens[slot] = tok
        self.cache = self._sync_fn(
            self.cache, jnp.asarray(active), jnp.asarray(lengths),
            jnp.asarray(tokens))

    def _dispatch_rollout(self, slots: Sequence[int],
                          k: int) -> jax.Array:
        active = np.zeros((self.n_slots + 1,), bool)
        rows_max = 1
        for s in slots:
            active[s] = True
            rows_max = max(rows_max, len(self._state[s].toks))
        sarg = self._span_arg(self._span_for(rows_max))
        self.decode_programs.add(("rollout", k, sarg))
        self.cache, self.rng, toks = self._rollout_fn(
            self.params, self.cache, self.rng, jnp.asarray(active),
            self.table_device(), k=k, span=sarg,
            qweights=self.qweights)
        self.rollouts += 1
        return toks

    # -- warmup ------------------------------------------------------------

    def warm_programs(self, k: int) -> int:
        """Pre-compile the drafter's reachable grid against the spare
        slot (its table row is all-sentinel, writes drop) — same
        contract as the engine's sweep: run under metrics.suppress,
        scrub lengths after, republish compile metrics from the watch
        registry. Covers rollouts at k AND k+1 (the pipelined predraft
        width) per span rung, the ingest program per rung, and the
        sync program. Returns programs compiled."""
        before = self.compile_watch.count
        pre_keys = set(self.compile_watch.summary())
        k = max(int(k), 1)
        spare = self.n_slots
        active = np.zeros((self.n_slots + 1,), bool)
        active[spare] = True
        active_dev = jnp.asarray(active)
        with metrics.suppress():
            for span in self.span_ladder:
                sarg = self._span_arg(span)
                for kk in sorted({k, k + 1}):
                    self.cache, self.rng, _ = self._rollout_fn(
                        self.params, self.cache, self.rng, active_dev,
                        self.table_device(), k=kk, span=sarg,
                        qweights=self.qweights)
                chunk = jnp.zeros((self.ingest_chunk,), jnp.int32)
                self.cache, self.rng, _ = self._ingest_fn(
                    self.params, self.cache, chunk,
                    jnp.asarray(0, jnp.int32),
                    jnp.asarray(1, jnp.int32),
                    jnp.asarray(spare, jnp.int32),
                    jnp.asarray(0, jnp.int32), self.rng,
                    self.table_device(), final=False, span=sarg,
                    qweights=self.qweights)
            zeros = jnp.zeros((self.n_slots + 1,), jnp.int32)
            self.cache = self._sync_fn(
                self.cache, jnp.zeros((self.n_slots + 1,), bool),
                zeros, zeros)
            self.cache["length"] = jnp.zeros_like(self.cache["length"])
        self.compile_watch.drain_new()
        summ = self.compile_watch.summary()
        for key in summ:
            if key not in pre_keys:
                flight_lib.COMPILE_SECONDS.labels(
                    program=key).observe(summ[key])
                flight_lib.PROGRAMS_COMPILED.inc()
        return self.compile_watch.count - before

    def declare_warmup_complete(self) -> None:
        self.compile_watch.declare_warm()

    def stats(self) -> Dict[str, int]:
        return {
            "rollouts": self.rollouts,
            "ingest_chunks": self.ingest_chunks,
            "rollbacks": self.rollbacks,
            "reuse_hits": self.reuse_hits,
            "slots": len(self._state),
            "blocks_used": self.blocks_used,
            "pending": 1 if self._pending_roll is not None else 0,
        }


# ---------------------------------------------------------------------------
# Draft-model construction helpers.

def truncated_draft(params: llama.Params, cfg: llama.LlamaConfig,
                    n_layers: int) -> Tuple[llama.Params,
                                            llama.LlamaConfig]:
    """The zero-training draft model: the target's first ``n_layers``
    decoder blocks + its embedding/norm/head, sliced from the stacked
    per-layer tensors (no copies beyond the slice). Residual-stream
    models degrade gracefully under layer truncation, so this is the
    standard no-checkpoint starting point; a self-distilled draft
    (train/qlora on the target's outputs) slots into the same seam."""
    n_layers = max(1, min(int(n_layers), cfg.n_layers))
    dcfg = dataclasses.replace(cfg, n_layers=n_layers)
    blocks = {name: w[:n_layers] for name, w in params["blocks"].items()}
    return dict(params, blocks=blocks), dcfg


def self_distilled_pair(params: llama.Params, cfg: llama.LlamaConfig,
                        draft_layers: int):
    """(target_params, draft_params, draft_cfg) at the distillation
    ENDPOINT: the target's residual blocks past ``draft_layers`` get
    zeroed output projections (wo, w_down), so they pass the residual
    stream through unchanged and the truncated-layer draft agrees with
    the target exactly — the regime a finished self-distillation run
    converges toward. The bench and tests use it to exercise the
    draft/verify machinery at high acceptance without a training run;
    the zeroed layers still pay their full matmul cost, so the
    TARGET's decode cost is unchanged and the comparison stays honest.
    """
    draft_layers = max(1, min(int(draft_layers), cfg.n_layers))
    blocks = dict(params["blocks"])
    blocks["wo"] = blocks["wo"].at[draft_layers:].set(0)
    blocks["w_down"] = blocks["w_down"].at[draft_layers:].set(0)
    target = dict(params, blocks=blocks)
    draft, dcfg = truncated_draft(target, cfg, draft_layers)
    return target, draft, dcfg


def draft_engine_from_env(params: llama.Params, cfg: llama.LlamaConfig,
                          n_slots: int, max_len: int,
                          spec: Optional[str] = None,
                          kv_int8: bool = False,
                          seed: int = 1) -> Optional[DraftEngine]:
    """Build the serving drafter from ``--draft-model`` /
    ``SKYTPU_DRAFT_MODEL``:

    * ``self:N`` — truncated-layer draft sharing the target's first N
      blocks (zero extra weights, zero extra checkpoints);
    * a ``llama.CONFIGS`` name (e.g. ``llama3-400m``) — a separate
      draft config, randomly initialized (the repo's serving scaffold
      initializes the target the same way; a distilled checkpoint
      loads over it);
    * unset/empty — no model drafter (n-gram stays the default).
    """
    spec = (spec if spec is not None
            else os.environ.get("SKYTPU_DRAFT_MODEL", "")).strip()
    if not spec:
        return None
    if spec.startswith("self:"):
        n = int(spec.split(":", 1)[1])
        dparams, dcfg = truncated_draft(params, cfg, n)
    elif spec in llama.CONFIGS:
        dcfg = llama.CONFIGS[spec]
        if dcfg.vocab_size != cfg.vocab_size:
            dcfg = dataclasses.replace(dcfg,
                                       vocab_size=cfg.vocab_size)
        dparams = llama.init_params(jax.random.key(seed), dcfg)
    else:
        raise ValueError(
            f"SKYTPU_DRAFT_MODEL={spec!r}: expected 'self:N' or one "
            f"of {sorted(llama.CONFIGS)}")
    return DraftEngine(dparams, dcfg, n_slots=n_slots,
                       max_len=max_len, kv_int8=kv_int8, seed=seed)
