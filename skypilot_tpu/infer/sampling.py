"""Token sampling: greedy / temperature / top-k, batched and jittable."""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    temperature: float = 0.0      # 0 => greedy
    top_k: Optional[int] = None   # None => full vocab


def sample(logits: jax.Array, rng: jax.Array,
           params: SamplingParams) -> jax.Array:
    """logits: [..., vocab] fp32 -> token ids [...]."""
    if params.temperature <= 0.0:
        return argmax_tokens(logits)
    logits = logits / params.temperature
    if params.top_k is not None and params.top_k > 0:
        top_vals, _ = jax.lax.top_k(logits, params.top_k)
        cutoff = top_vals[..., -1:]
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


def argmax_tokens(logits: jax.Array) -> jax.Array:
    """Greedy token choice: deterministic argmax over the vocab axis.

    Speculative verification calls this directly (never ``sample``):
    draft-and-verify is exactly output-preserving only under greedy
    decoding, and the verify program must not consume RNG — the greedy
    path's RNG stream has to stay identical spec-on vs spec-off so the
    two are comparable token-for-token even in mixed workloads."""
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)
