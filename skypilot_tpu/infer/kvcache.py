"""KV-cache prefill / decode steps for the Llama family.

TPU-first design notes
----------------------
* Everything is **static-shape**: the decode cache is a pre-allocated
  ``[L, slots, max_len, kv_heads, head_dim]`` buffer; per-slot lengths
  mask attention instead of resizing anything. One compiled prefill per
  prompt bucket, one compiled decode step, reused for the whole serving
  lifetime — no retracing, ever.
* Prefill is the plain causal forward (right-padded to a bucket length)
  that additionally emits each layer's post-rope K/V rows; padding rows
  never poison the cache because causal attention keeps positions
  < true_len independent of them, and decode masks rows >= length.
* Decode processes *all slots together*: [slots, 1] tokens through the
  stacked-layer ``lax.scan``, one scatter per layer to append K/V. This
  is the JetStream-style generate step — MXU-batched across requests.
* Sharding composes with serving TP: cache kv-head dim maps to ``tp``,
  slot dim to (``dp``, ``fsdp``) via the standard rule table.

Reference parity: the reference serves LLMs only through external
engines (reference: llm/vllm/serve.yaml, examples/tpu/v6e/README.md
JetStream section). This module is the in-tree TPU-native engine core.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from skypilot_tpu.models import llama

Cache = Dict[str, jax.Array]


def _ffn(cfg: llama.LlamaConfig, h: jax.Array, layer: Dict) -> jax.Array:
    """Post-norm FFN: dense SwiGLU, or the sparse expert FFN when the
    config is an MoE (aux loss is irrelevant at inference and dropped).
    h: [B, S, D].

    MoE + right-padded prefill is safe: capacity assignment is
    position-ordered, so padding rows (after true_len) can never evict
    a real token from an expert's buffer; decode steps see S=1 where
    top-k choices always fit.
    """
    if hasattr(cfg, "n_experts"):
        from skypilot_tpu.models import moe
        out, _ = moe.moe_ffn(cfg, h, layer)
        return out
    g = jnp.einsum("bsd,df->bsf", h, layer["w_gate"].astype(cfg.dtype))
    u = jnp.einsum("bsd,df->bsf", h, layer["w_up"].astype(cfg.dtype))
    return jnp.einsum("bsf,fd->bsd", jax.nn.silu(g) * u,
                      layer["w_down"].astype(cfg.dtype))


def init_cache(cfg: llama.LlamaConfig, n_slots: int,
               max_len: int, kv_int8: bool = False) -> Cache:
    """Pre-allocated decode state for ``n_slots`` concurrent requests.

    ``kv_int8``: store K/V rows as int8 with a per-(row, kv-head) absmax
    scale. Decode is HBM-bandwidth-bound on cache reads, so halving the
    bytes raises decode throughput AND doubles the requests that fit —
    the standard TPU serving trade (XLA fuses the dequant multiply into
    the attention einsums).
    """
    L, G, hd = cfg.n_layers, cfg.n_kv_heads, cfg.head_dim
    cache: Cache = {
        # Tokens generated + prompt rows present, per slot (0 = free).
        "length": jnp.zeros((n_slots,), jnp.int32),
        "last_token": jnp.zeros((n_slots,), jnp.int32),
    }
    if kv_int8:
        cache["k"] = jnp.zeros((L, n_slots, max_len, G, hd), jnp.int8)
        cache["v"] = jnp.zeros((L, n_slots, max_len, G, hd), jnp.int8)
        cache["k_scale"] = jnp.zeros((L, n_slots, max_len, G),
                                     jnp.float32)
        cache["v_scale"] = jnp.zeros((L, n_slots, max_len, G),
                                     jnp.float32)
    else:
        cache["k"] = jnp.zeros((L, n_slots, max_len, G, hd), cfg.dtype)
        cache["v"] = jnp.zeros((L, n_slots, max_len, G, hd), cfg.dtype)
    return cache


def quantize_rows(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """[..., G, hd] -> (int8 values, [..., G] absmax scales)."""
    absmax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale[..., None]),
                 -127, 127).astype(jnp.int8)
    return q, scale


def dequantize_rows(q: jax.Array, scale: jax.Array,
                    dtype=jnp.float32) -> jax.Array:
    return (q.astype(jnp.float32) * scale[..., None]).astype(dtype)


def cache_logical_axes(cache: Cache | None = None) -> Dict[str, Tuple]:
    """Axes for the given cache's keys (quantization is derived from the
    cache itself, like insert/decode_step do; None = fp layout)."""
    axes = {
        "k": ("layer", "batch", "seq_cache", "kv_heads", "head_dim"),
        "v": ("layer", "batch", "seq_cache", "kv_heads", "head_dim"),
        "length": ("batch",),
        "last_token": ("batch",),
    }
    if cache is not None and "k_scale" in cache:
        axes["k_scale"] = ("layer", "batch", "seq_cache", "kv_heads")
        axes["v_scale"] = ("layer", "batch", "seq_cache", "kv_heads")
    return axes


# ---------------------------------------------------------------------------
# Prefill
# ---------------------------------------------------------------------------

def prefill(params: llama.Params, tokens: jax.Array, true_len: jax.Array,
            cfg: llama.LlamaConfig,
            constrain=None) -> Tuple[Cache, jax.Array]:
    """Causal forward over a right-padded prompt.

    tokens: [S_bucket] int32 (single request), true_len: scalar int32.
    Returns ({"k","v"}: [L, S_bucket, G, hd] post-rope rows, logits at
    the last real position [vocab] fp32).
    """
    if constrain is None:
        constrain = lambda x, axes: x
    tokens = tokens[None]                                     # [1, S]
    S = tokens.shape[1]
    x = params["embed"].astype(cfg.dtype)[tokens]
    positions = jnp.arange(S)
    cos, sin = llama.rope_frequencies(cfg, positions)

    def body(carry, layer):
        x = carry
        h = llama.rms_norm(x, layer["ln1"], cfg.norm_eps)
        q = jnp.einsum("bsd,dhk->bshk", h, layer["wq"].astype(cfg.dtype))
        k = jnp.einsum("bsd,dhk->bshk", h, layer["wk"].astype(cfg.dtype))
        v = jnp.einsum("bsd,dhk->bshk", h, layer["wv"].astype(cfg.dtype))
        q = llama.apply_rope(q, cos, sin)
        k = llama.apply_rope(k, cos, sin)
        from skypilot_tpu.ops import attention as attn_ops
        o = attn_ops.gqa_attention(q, k, v, causal=True)
        o = jnp.einsum("bshk,hkd->bsd", o, layer["wo"].astype(cfg.dtype))
        x = x + o
        h = llama.rms_norm(x, layer["ln2"], cfg.norm_eps)
        return x + _ffn(cfg, h, layer), (k[0], v[0])

    x, (ks, vs) = lax.scan(body, x, params["blocks"])
    x = llama.rms_norm(x, params["final_norm"], cfg.norm_eps)
    last = x[0, true_len - 1]                                  # [D]
    head = (params["embed"].T if cfg.tie_embeddings else params["lm_head"])
    logits = (last @ head.astype(cfg.dtype)).astype(jnp.float32)
    return {"k": ks, "v": vs}, logits


def insert(cache: Cache, prefix: Cache, slot: jax.Array,
           true_len: jax.Array, first_token: jax.Array) -> Cache:
    """Install a prefilled prompt into a decode slot.

    prefix k/v: [L, S_bucket, G, hd]; rows >= true_len are padding but
    harmless — decode masks by ``length``.
    """
    out = dict(cache)
    pk, pv = prefix["k"], prefix["v"]
    if "k_scale" in cache:
        pk, ks = quantize_rows(pk)
        pv, vs = quantize_rows(pv)
        out["k_scale"] = lax.dynamic_update_slice(
            cache["k_scale"], ks[:, None], (0, slot, 0, 0))
        out["v_scale"] = lax.dynamic_update_slice(
            cache["v_scale"], vs[:, None], (0, slot, 0, 0))
    out["k"] = lax.dynamic_update_slice(
        cache["k"], pk[:, None], (0, slot, 0, 0, 0))
    out["v"] = lax.dynamic_update_slice(
        cache["v"], pv[:, None], (0, slot, 0, 0, 0))
    out["length"] = cache["length"].at[slot].set(true_len)
    out["last_token"] = cache["last_token"].at[slot].set(first_token)
    return out


# ---------------------------------------------------------------------------
# Decode
# ---------------------------------------------------------------------------

def decode_step(params: llama.Params, cache: Cache,
                cfg: llama.LlamaConfig,
                constrain=None) -> Tuple[Cache, jax.Array]:
    """One token for every slot. Returns (cache', logits [slots, vocab])."""
    if constrain is None:
        constrain = lambda x, axes: x
    B = cache["length"].shape[0]
    M = cache["k"].shape[2]
    G, hd = cfg.n_kv_heads, cfg.head_dim
    rep = cfg.n_heads // G

    tokens = cache["last_token"][:, None]                     # [B, 1]
    # ``length`` counts rows already in the cache (prompt + committed
    # tokens); the pending token's K/V row is written at index length.
    pos = cache["length"]                                     # [B]
    x = params["embed"].astype(cfg.dtype)[tokens]             # [B, 1, D]
    cos, sin = llama.rope_frequencies(cfg, pos[:, None])      # [B,1,hd/2]

    # Rows <= length are valid (the just-written current row included).
    valid = (jnp.arange(M)[None, :] <= cache["length"][:, None])  # [B, M]
    neg = jnp.asarray(-1e30, jnp.float32)
    scale = hd ** -0.5
    batch_ix = jnp.arange(B)

    quant = "k_scale" in cache

    def body(carry, layer_kv):
        x = carry
        if quant:
            layer, ck, cv, cks, cvs = layer_kv              # ck int8
        else:
            layer, ck, cv = layer_kv                        # ck [B,M,G,hd]
            cks = cvs = None
        h = llama.rms_norm(x, layer["ln1"], cfg.norm_eps)
        q = jnp.einsum("bsd,dhk->bshk", h, layer["wq"].astype(cfg.dtype))
        k = jnp.einsum("bsd,dhk->bshk", h, layer["wk"].astype(cfg.dtype))
        v = jnp.einsum("bsd,dhk->bshk", h, layer["wv"].astype(cfg.dtype))
        q = llama.apply_rope(q, cos, sin)
        k = llama.apply_rope(k, cos, sin)
        if quant:
            kq, ks = quantize_rows(k[:, 0])
            vq, vs = quantize_rows(v[:, 0])
            ck = ck.at[batch_ix, pos].set(kq)
            cv = cv.at[batch_ix, pos].set(vq)
            cks = cks.at[batch_ix, pos].set(ks)
            cvs = cvs.at[batch_ix, pos].set(vs)
            # Dequant fuses into the einsums: HBM reads stay int8.
            ck_f = dequantize_rows(ck, cks)
            cv_f = dequantize_rows(cv, cvs)
        else:
            ck = ck.at[batch_ix, pos].set(k[:, 0])
            cv = cv.at[batch_ix, pos].set(v[:, 0])
            ck_f = ck.astype(jnp.float32)
            cv_f = cv.astype(jnp.float32)
        qh = q[:, 0].reshape(B, G, rep, hd)
        s = jnp.einsum("bgrk,bmgk->bgrm", qh.astype(jnp.float32),
                       ck_f) * scale
        s = jnp.where(valid[:, None, None, :], s, neg)
        w = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bgrm,bmgk->bgrk", w, cv_f)
        o = o.reshape(B, 1, cfg.n_heads, hd).astype(cfg.dtype)
        o = jnp.einsum("bshk,hkd->bsd", o, layer["wo"].astype(cfg.dtype))
        x = x + o
        h = llama.rms_norm(x, layer["ln2"], cfg.norm_eps)
        out_kv = (ck, cv, cks, cvs) if quant else (ck, cv)
        return x + _ffn(cfg, h, layer), out_kv

    if quant:
        xs = (params["blocks"], cache["k"], cache["v"],
              cache["k_scale"], cache["v_scale"])
    else:
        xs = (params["blocks"], cache["k"], cache["v"])
    x, new_kv = lax.scan(body, x, xs)
    x = llama.rms_norm(x, params["final_norm"], cfg.norm_eps)
    head = (params["embed"].T if cfg.tie_embeddings else params["lm_head"])
    logits = jnp.einsum("bsd,dv->bsv", x,
                        head.astype(cfg.dtype))[:, 0].astype(jnp.float32)
    out = dict(cache)
    if quant:
        out["k"], out["v"], out["k_scale"], out["v_scale"] = new_kv
    else:
        out["k"], out["v"] = new_kv
    return out, logits


def commit_tokens(cache: Cache, tokens: jax.Array,
                  active: jax.Array) -> Cache:
    """Append sampled tokens on active slots: bump lengths, set last."""
    return dict(
        cache,
        length=cache["length"] + active.astype(jnp.int32),
        last_token=jnp.where(active, tokens, cache["last_token"]))
