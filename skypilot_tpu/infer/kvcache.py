"""KV-cache prefill / decode steps for the Llama family.

TPU-first design notes
----------------------
* Everything is **static-shape**: the decode cache is a pre-allocated
  ``[L, slots, max_len, kv_heads, head_dim]`` buffer; per-slot lengths
  mask attention instead of resizing anything. One compiled prefill per
  prompt bucket, one compiled decode step, reused for the whole serving
  lifetime — no retracing, ever.
* Prefill is the plain causal forward (right-padded to a bucket length)
  that additionally emits each layer's post-rope K/V rows; padding rows
  never poison the cache because causal attention keeps positions
  < true_len independent of them, and decode masks rows >= length.
* Decode processes *all slots together*: [slots, 1] tokens through the
  stacked-layer ``lax.scan``, one scatter per layer to append K/V. This
  is the JetStream-style generate step — MXU-batched across requests.
* Sharding composes with serving TP: cache kv-head dim maps to ``tp``,
  slot dim to (``dp``, ``fsdp``) via the standard rule table.
* Two storage layouts share ONE implementation of every program:
  the original contiguous ``[L, slots, max_len, ...]`` cache, and the
  **paged** block pool (``[L, n_blocks, block_len, ...]`` + a per-slot
  block table — see the "Paged block-pool layout" section) that decouples
  slot count from worst-case length. Each program takes an optional
  ``table``; reads/writes route through it, so paged-vs-contiguous
  outputs are bit-identical by construction.

Reference parity: the reference serves LLMs only through external
engines (reference: llm/vllm/serve.yaml, examples/tpu/v6e/README.md
JetStream section). This module is the in-tree TPU-native engine core.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from skypilot_tpu.infer import sampling as sampling_mod
from skypilot_tpu.models import llama
from skypilot_tpu.ops import paged_attention as paged_attn_ops

Cache = Dict[str, jax.Array]


def _ffn(cfg: llama.LlamaConfig, h: jax.Array, layer: Dict) -> jax.Array:
    """Post-norm FFN: dense SwiGLU, or the sparse expert FFN when the
    config is an MoE (aux loss is irrelevant at inference and dropped).
    h: [B, S, D].

    MoE + right-padded prefill is safe: capacity assignment is
    position-ordered, so padding rows (after true_len) can never evict
    a real token from an expert's buffer; decode steps see S=1 where
    top-k choices always fit.
    """
    if hasattr(cfg, "n_experts"):
        from skypilot_tpu.models import moe
        out, _ = moe.moe_ffn(cfg, h, layer)
        return out
    g = jnp.einsum("bsd,df->bsf", h, layer["w_gate"].astype(cfg.dtype))
    u = jnp.einsum("bsd,df->bsf", h, layer["w_up"].astype(cfg.dtype))
    return jnp.einsum("bsf,fd->bsd", jax.nn.silu(g) * u,
                      layer["w_down"].astype(cfg.dtype))


def init_cache(cfg: llama.LlamaConfig, n_slots: int,
               max_len: int, kv_int8: bool = False) -> Cache:
    """Pre-allocated decode state for ``n_slots`` concurrent requests.

    ``kv_int8``: store K/V rows as int8 with a per-(row, kv-head) absmax
    scale. Decode is HBM-bandwidth-bound on cache reads, so halving the
    bytes raises decode throughput AND doubles the requests that fit —
    the standard TPU serving trade (XLA fuses the dequant multiply into
    the attention einsums).
    """
    L, G, hd = cfg.n_layers, cfg.n_kv_heads, cfg.head_dim
    cache: Cache = {
        # Tokens generated + prompt rows present, per slot (0 = free).
        "length": jnp.zeros((n_slots,), jnp.int32),
        "last_token": jnp.zeros((n_slots,), jnp.int32),
    }
    if kv_int8:
        cache["k"] = jnp.zeros((L, n_slots, max_len, G, hd), jnp.int8)
        cache["v"] = jnp.zeros((L, n_slots, max_len, G, hd), jnp.int8)
        # Scales: [..., G, max_len] (row dim last) in BF16. Both choices
        # fight TPU tile padding: XLA lays the G=8 dim minormost
        # whatever the logical order, and an f32 minormost dim of 8
        # pads 8->128 — a 16x expansion that was 2x730 MB of HBM at 32
        # slots (per the XLA OOM allocation dump). bf16 tiles (16,128)
        # cap the waste at 2x, and scale precision is irrelevant at
        # absmax/127 granularity.
        cache["k_scale"] = jnp.zeros((L, n_slots, G, max_len),
                                     jnp.bfloat16)
        cache["v_scale"] = jnp.zeros((L, n_slots, G, max_len),
                                     jnp.bfloat16)
    else:
        cache["k"] = jnp.zeros((L, n_slots, max_len, G, hd), cfg.dtype)
        cache["v"] = jnp.zeros((L, n_slots, max_len, G, hd), cfg.dtype)
    return cache


# ---------------------------------------------------------------------------
# int8 weights (w8a8 decode)
# ---------------------------------------------------------------------------
# Decode reads EVERY weight once per token: int8 storage halves that HBM
# traffic and the s8xs8->s32 MXU path doubles matmul throughput
# (measured ~1.9x on a [16,2048]x[2048,8192] v5e matmul). Weights are
# quantized per OUTPUT channel once at engine init; activations per
# token inside the step; the products rescale by (ax * aw) / 127^2.
# Prefill runs the same w8a8 path, which is what lets the engine drop
# the fp weight copies entirely (slim_params) — the memory halving.

def quantize_weight(w: jax.Array, contract_ndim: int
                    ) -> Dict[str, jax.Array]:
    """Per-output-channel absmax int8. ``contract_ndim``: how many
    LEADING dims (after any layer dim handled by the caller) are
    contracted in the consuming einsum; the rest are output channels."""
    axes = tuple(range(contract_ndim))
    absmax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=axes)
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -127,
                 127).astype(jnp.int8)
    return {"w": q, "s": scale}


# How many leading dims (after the layer dim) each block weight
# contracts in its consuming einsum. SINGLE definition: quantization
# and the sharding axes derive from the same map, so a new quantized
# weight can never get int8 data without sharding axes (it would
# silently replicate under --tp).
QUANT_CONTRACT = {"wq": 1, "wk": 1, "wv": 1, "wo": 2,
                  "w_gate": 1, "w_up": 1, "w_down": 1}


def quantize_block_weights(params: llama.Params) -> Dict[str, Dict]:
    """int8 copies of the stacked per-layer matmul weights (norms and
    the embedding table stay fp)."""
    blocks = params["blocks"]

    def per_layer(name, w):
        nd = QUANT_CONTRACT[name]
        # vmap over the leading layer dim.
        return jax.vmap(lambda x: quantize_weight(x, nd))(w)

    return {name: per_layer(name, blocks[name])
            for name in QUANT_CONTRACT}


def quantize_head(params: llama.Params,
                  cfg: llama.LlamaConfig) -> Dict[str, jax.Array]:
    head = (params["embed"].T if cfg.tie_embeddings
            else params["lm_head"])
    return quantize_weight(head, 1)


def qweight_logical_axes(cfg: llama.LlamaConfig) -> Dict[str, Dict]:
    """Logical axes for the ``{"blocks": ..., "head": ...}`` qweights
    tree (same names the fp params use, so one TP rule set shards
    both): ``w`` mirrors its fp tensor; ``s`` (per-output-channel
    scales) keeps ("layer",) + the NON-contracted output axes."""
    full = llama.param_logical_axes(cfg)["blocks"]
    blocks = {}
    for name, nd in QUANT_CONTRACT.items():
        axes = full[name]            # ("layer", <contracted...>, <out...>)
        blocks[name] = {"w": axes, "s": ("layer",) + axes[1 + nd:]}
    return {"blocks": blocks,
            "head": {"w": ("embed", "vocab"), "s": ("vocab",)}}


def _act_quant(x: jax.Array, n_contract: int
               ) -> Tuple[jax.Array, jax.Array]:
    """Per-token int8: absmax over the TRAILING n_contract dims."""
    axes = tuple(range(x.ndim - n_contract, x.ndim))
    absmax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=axes)
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(x.astype(jnp.float32)
                           / scale[(...,) + (None,) * n_contract]),
                 -127, 127).astype(jnp.int8)
    return q, scale


def qeinsum(eq: str, x: jax.Array, qw: Dict[str, jax.Array],
            n_contract: int, out_dtype) -> jax.Array:
    """w8a8 einsum: quantize x per token, s8xs8->s32 MXU matmul,
    rescale. ``n_contract``: contracted dims at x's tail (= qw's
    head)."""
    xq, sx = _act_quant(x, n_contract)
    acc = jnp.einsum(eq, xq, qw["w"],
                     preferred_element_type=jnp.int32).astype(jnp.float32)
    n_out = qw["s"].ndim
    scale = (sx[(...,) + (None,) * n_out]
             * qw["s"][(None,) * (acc.ndim - n_out) + (...,)])
    return (acc * scale).astype(out_dtype)


def proj(eq: str, x: jax.Array, layer: Dict, qlayer, name: str,
         n_contract: int, dtype) -> jax.Array:
    """One weight matmul, int8 (w8a8) when ``qlayer`` provides the
    weight, fp otherwise. Shared by prefill and decode so a fully
    quantized engine needs NO fp copy of the seven block matrices —
    that memory halving is what fits an 8B-class model on a 16 GB
    chip."""
    if qlayer is not None and name in qlayer:
        return qeinsum(eq, x, qlayer[name], n_contract, dtype)
    return jnp.einsum(eq, x, layer[name].astype(dtype))


# ---------------------------------------------------------------------------
# Multi-LoRA adapter gathers (infer/adapters.py)
# ---------------------------------------------------------------------------
# Per-slot LoRA: every program below takes an optional ``lora`` pool
# (per target {"a": [L, N, d_in..., r], "b": [L, N, r, d_out...]},
# layer axis leading so slices ride the decoder scan as xs) plus an
# ``aid`` vector of per-row adapter-pool slots. The delta is the
# factored pair x @ A[aid] @ B[aid] (alpha/rank already folded into B
# at load) added to the base projection — ONE gather per layer per
# target, rank static, so requests for different fine-tunes batch in
# one dispatch and adapter identity is pure device DATA (never program
# identity). Pool slot 0 is all zeros: base-model rows add an
# exact-zero delta, which is what makes an adapter-capable engine's
# base output bit-identical to an adapterless engine's.


def _layer_parts(layer_q, wq8: bool, has_lora: bool):
    """Unpack one scan step's xs slice into (layer, qlayer, llayer) —
    the single decoder between fp, w8a8 and adapter-pool variants."""
    if wq8 and has_lora:
        layer, qlayer, llayer = layer_q
    elif wq8:
        (layer, qlayer), llayer = layer_q, None
    elif has_lora:
        layer, llayer = layer_q
        qlayer = None
    else:
        layer, qlayer, llayer = layer_q, None, None
    return layer, qlayer, llayer


def _scan_xs(params, qweights, lora):
    """The decoder scan's xs: blocks (+ int8 blocks) (+ the adapter
    pool). A lora-less call builds the identical structure it always
    did — the adapterless trace is unchanged."""
    if qweights is not None and lora is not None:
        return (params["blocks"], qweights["blocks"], lora)
    if qweights is not None:
        return (params["blocks"], qweights["blocks"])
    if lora is not None:
        return (params["blocks"], lora)
    return params["blocks"]


def _lora_in_delta(h, ab, aid):
    """Per-slot delta for an embed->heads/kv target. h: [B, S, D];
    ab: ONE layer's pool slice {"a": [N, D, r], "b": [N, r, H, hd]};
    aid: [B] int32 pool slots (one gather per layer per target)."""
    a = ab["a"][aid].astype(h.dtype)               # [B, D, r]
    b = ab["b"][aid].astype(h.dtype)               # [B, r, H, hd]
    u = jnp.einsum("bsd,bdr->bsr", h, a)
    return jnp.einsum("bsr,brhk->bshk", u, b)


def _lora_out_delta(o, ab, aid):
    """Per-slot delta for the wo target. o (pre-projection attention
    output): [B, S, H, hd]; a: [N, H, hd, r]; b: [N, r, D]."""
    a = ab["a"][aid].astype(o.dtype)               # [B, H, hd, r]
    b = ab["b"][aid].astype(o.dtype)               # [B, r, D]
    u = jnp.einsum("bshk,bhkr->bsr", o, a)
    return jnp.einsum("bsr,brd->bsd", u, b)


def slim_params(params: llama.Params) -> llama.Params:
    """Drop the fp copies of quantized weights: blocks keep only the
    norms; lm_head is covered by the quantized head."""
    return {
        "embed": params["embed"],
        "final_norm": params["final_norm"],
        "blocks": {"ln1": params["blocks"]["ln1"],
                   "ln2": params["blocks"]["ln2"]},
    }


def random_quantized_params(cfg: llama.LlamaConfig, seed: int = 0):
    """(slim fp params, qweights) with random int8 weights, built
    WITHOUT ever materializing the fp tree — how an 8B-class benchmark
    fits a 16 GB chip (the fp init alone would be 32 GB). Every leaf is
    generated ON DEVICE (jax.random): a host-side numpy tree would ship
    ~8 GB through PCIe — or a tunneled relay, where that transfer
    stalls for tens of minutes."""
    d, ff, v, L = cfg.d_model, cfg.d_ff, cfg.vocab_size, cfg.n_layers
    hd, nh, nkv = cfg.head_dim, cfg.n_heads, cfg.n_kv_heads
    keys = iter(jax.random.split(jax.random.key(seed), 16))

    def q(shape, out_ndim):
        w = jax.random.randint(next(keys), shape, -127, 128,
                               dtype=jnp.int8)
        sshape = ((shape[0],) + tuple(shape[-out_ndim:])
                  if len(shape) > out_ndim + 1
                  else tuple(shape[-out_ndim:]))
        return {"w": w, "s": jnp.full(sshape, 0.02 / 127.0, jnp.float32)}

    blocks = {
        "wq": q((L, d, nh, hd), 2),
        "wk": q((L, d, nkv, hd), 2),
        "wv": q((L, d, nkv, hd), 2),
        "wo": q((L, nh, hd, d), 1),
        "w_gate": q((L, d, ff), 1),
        "w_up": q((L, d, ff), 1),
        "w_down": q((L, ff, d), 1),
    }
    head = {"w": jax.random.randint(next(keys), (d, v), -127, 128,
                                    dtype=jnp.int8),
            "s": jnp.full((v,), 0.02 / 127.0, jnp.float32)}
    params = {
        "embed": (jax.random.normal(next(keys), (v, d), jnp.bfloat16)
                  * 0.02),
        "final_norm": jnp.ones((d,), jnp.float32),
        "blocks": {"ln1": jnp.ones((L, d), jnp.float32),
                   "ln2": jnp.ones((L, d), jnp.float32)},
    }
    return params, {"blocks": blocks, "head": head}


def quantize_rows(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """[..., G, hd] -> (int8 values, [..., G] absmax scales)."""
    absmax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale[..., None]),
                 -127, 127).astype(jnp.int8)
    return q, scale


def dequantize_rows(q: jax.Array, scale: jax.Array,
                    dtype=jnp.float32) -> jax.Array:
    return (q.astype(jnp.float32) * scale[..., None]).astype(dtype)


def cache_logical_axes(cache: Cache | None = None) -> Dict[str, Tuple]:
    """Axes for the given cache's keys (quantization is derived from the
    cache itself, like insert/decode_step do; None = fp layout). The
    paged layout reuses the same names: its block dim takes "batch" and
    its block_len dim takes "seq_cache", so one TP rule set shards both
    layouts (kv_heads is dim 3 either way)."""
    axes = {
        "k": ("layer", "batch", "seq_cache", "kv_heads", "head_dim"),
        "v": ("layer", "batch", "seq_cache", "kv_heads", "head_dim"),
        "length": ("batch",),
        "last_token": ("batch",),
    }
    if cache is not None and "k_scale" in cache:
        axes["k_scale"] = ("layer", "batch", "kv_heads", "seq_cache")
        axes["v_scale"] = ("layer", "batch", "kv_heads", "seq_cache")
    return axes


# ---------------------------------------------------------------------------
# Paged block-pool layout
# ---------------------------------------------------------------------------
# The contiguous layout above charges every slot max_len rows of HBM
# rent regardless of actual length. The paged layout allocates
# fixed-size BLOCKS from one shared pool ([L, n_blocks, block_len, ...]
# per tensor) and gives each slot a BLOCK TABLE mapping logical block
# j -> physical block id. Shapes stay fully static — attention gathers
# a slot's blocks in logical order (same row ordering, same masked
# score set as the contiguous read, so the softmax sums are identical)
# and writes scatter through the table. The table carries one EXTRA
# column pinned to the sentinel (== n_blocks): any logical row past the
# slot's allocation maps there, and JAX scatter DROPS out-of-bounds
# updates — the same garbage-write safety net the contiguous layout
# gets from row indices >= max_len (gathers CLAMP, but clamped garbage
# rows are masked by `length` exactly as contiguous garbage rows are).
#
# Host-side bookkeeping (which blocks a slot owns, ref counts for
# prefix sharing) lives in BlockAllocator + the engine; a stored prefix
# is just ref-counted shared blocks mapped into a new slot's table —
# no row copies. Copy-on-write happens only when a shared block is
# PARTIAL (block_len does not divide the stored prefix length): the
# writer gets a fresh copy (`copy_block`) before its first write.


def init_paged_cache(cfg: llama.LlamaConfig, n_slots: int,
                     n_blocks: int, block_len: int,
                     kv_int8: bool = False) -> Cache:
    """Block-pool decode state: ``n_blocks`` physical blocks of
    ``block_len`` rows shared by ``n_slots`` slots. Per-slot
    length/last_token bookkeeping is identical to the contiguous
    layout; only the K/V storage is pooled."""
    L, G, hd = cfg.n_layers, cfg.n_kv_heads, cfg.head_dim
    cache: Cache = {
        "length": jnp.zeros((n_slots,), jnp.int32),
        "last_token": jnp.zeros((n_slots,), jnp.int32),
    }
    if kv_int8:
        cache["k"] = jnp.zeros((L, n_blocks, block_len, G, hd), jnp.int8)
        cache["v"] = jnp.zeros((L, n_blocks, block_len, G, hd), jnp.int8)
        # Same minormost-row-dim trade as init_cache's scales.
        cache["k_scale"] = jnp.zeros((L, n_blocks, G, block_len),
                                     jnp.bfloat16)
        cache["v_scale"] = jnp.zeros((L, n_blocks, G, block_len),
                                     jnp.bfloat16)
    else:
        cache["k"] = jnp.zeros((L, n_blocks, block_len, G, hd),
                               cfg.dtype)
        cache["v"] = jnp.zeros((L, n_blocks, block_len, G, hd),
                               cfg.dtype)
    return cache


class BlockAllocator:
    """Host-side ref-counted allocator over the paged block pool.

    Pure bookkeeping — no device state. Invariants (property-tested in
    tests/test_paged_kv.py): a block is FREE xor referenced; alloc
    hands out ref==1 blocks in ascending id order (deterministic);
    incref requires a live block; decref of a free block raises
    (double-free guard); a block is writable only at ref==1 — the
    engine must COW before writing a shared block.
    """

    def __init__(self, n_blocks: int):
        self.n_blocks = n_blocks
        self.reset()

    def reset(self) -> None:
        # Popped from the end: blocks hand out in ascending id order.
        self._free = list(range(self.n_blocks - 1, -1, -1))
        self._ref = [0] * self.n_blocks

    @property
    def used(self) -> int:
        return self.n_blocks - len(self._free)

    @property
    def available(self) -> int:
        return len(self._free)

    def alloc(self) -> int:
        if not self._free:
            raise RuntimeError("KV block pool exhausted")
        b = self._free.pop()
        self._ref[b] = 1
        return b

    def incref(self, block: int) -> None:
        if self._ref[block] <= 0:
            raise RuntimeError(f"incref of free block {block}")
        self._ref[block] += 1

    def decref(self, block: int) -> None:
        if self._ref[block] <= 0:
            raise RuntimeError(f"double free of block {block}")
        self._ref[block] -= 1
        if self._ref[block] == 0:
            self._free.append(block)

    def ref(self, block: int) -> int:
        return self._ref[block]

    def writable(self, block: int) -> bool:
        """Safe to scatter into: exactly one owner."""
        return self._ref[block] == 1


def copy_block(cache: Cache, src: jax.Array, dst: jax.Array) -> Cache:
    """Copy-on-write: duplicate one physical block's rows (and scales)
    into a freshly allocated block. All ``block_len`` rows copy (static
    shape); rows past the shared prefix are garbage in BOTH blocks and
    stay unreadable until the new owner overwrites them."""
    out = dict(cache)
    for name in ("k", "v", "k_scale", "v_scale"):
        if name not in cache:
            continue
        rows = lax.dynamic_index_in_dim(cache[name], src, 1,
                                        keepdims=False)
        out[name] = lax.dynamic_update_index_in_dim(cache[name], rows,
                                                    dst, 1)
    return out


def _logical_rows(cache: Cache, table) -> int:
    """Rows a slot's attention spans: max_len (contiguous) or
    blocks_per_slot * block_len (paged; the table's last column is the
    sentinel and holds no rows)."""
    if table is None:
        return cache["k"].shape[2]
    return (table.shape[1] - 1) * cache["k"].shape[2]


def _phys(cache: Cache, table, slots, idx):
    """(slot, logical row) -> scatter coordinates on the cache's two
    row-addressing dims: identity for contiguous, block-table lookup
    for paged. Overflow logical rows index the table's sentinel column
    (gathers clamp into it), resolving to block id == n_blocks, where
    scatter drops the write."""
    if table is None:
        return slots, idx
    bl = cache["k"].shape[2]
    return table[slots, idx // bl], idx % bl


def _gather_kv_layer(cache: Cache, i, table, span=None):
    """Layer ``i``'s K/V (+ scales when int8) arranged per slot:
    k/v [B, M, G, hd], scales [B, G, M]. Contiguous reads the
    slot-major layout directly; paged gathers each slot's blocks in
    logical order — identical row ordering, so the attention sums
    match the contiguous layout bit-for-bit.

    ``span`` (static int): gather only the first ``span`` logical
    rows — the span-bucketed read. The rows kept are a PREFIX of the
    full view in the same order, and every row the caller's validity
    mask admits lies below the span by construction (the engine picks
    the bucket covering the longest active slot), so the masked score
    set — and the attention output — is bit-identical to the full
    gather while the materialized K/V transient (the decode-bandwidth
    cost) shrinks from max_len to span rows per slot. Paged: the
    gather covers ceil(span / block_len) whole blocks of the table
    prefix, then slices to the span — sub-block spans still pay one
    block of gather but only span rows of attention."""
    ck = lax.dynamic_index_in_dim(cache["k"], i, 0, keepdims=False)
    cv = lax.dynamic_index_in_dim(cache["v"], i, 0, keepdims=False)
    cks = cvs = None
    if "k_scale" in cache:
        cks = lax.dynamic_index_in_dim(cache["k_scale"], i, 0,
                                       keepdims=False)
        cvs = lax.dynamic_index_in_dim(cache["v_scale"], i, 0,
                                       keepdims=False)
    if table is not None:
        bl = ck.shape[1]
        nb = table.shape[1] - 1              # sentinel column: no rows
        if span is not None:
            nb = -(-span // bl)              # block-table prefix
        tbl = table[:, :nb]
        B = tbl.shape[0]
        G = ck.shape[2]
        ck = ck[tbl].reshape(B, nb * bl, *ck.shape[2:])
        cv = cv[tbl].reshape(B, nb * bl, *cv.shape[2:])
        if cks is not None:
            cks = cks[tbl].transpose(0, 2, 1, 3).reshape(B, G, nb * bl)
            cvs = cvs[tbl].transpose(0, 2, 1, 3).reshape(B, G, nb * bl)
    if span is not None:
        ck = ck[:, :span]
        cv = cv[:, :span]
        if cks is not None:
            cks = cks[..., :span]
            cvs = cvs[..., :span]
    return ck, cv, cks, cvs


def _gather_slot_kv_layer(cache: Cache, i, slot, table, span=None):
    """One slot's rows for layer ``i``: k/v [M, G, hd], scales [G, M]
    (the prefill_chunk read path). ``span``: first ``span`` logical
    rows only — same prefix semantics as :func:`_gather_kv_layer`."""
    ck = lax.dynamic_index_in_dim(cache["k"], i, 0, keepdims=False)
    cv = lax.dynamic_index_in_dim(cache["v"], i, 0, keepdims=False)
    cks = cvs = None
    if "k_scale" in cache:
        cks = lax.dynamic_index_in_dim(cache["k_scale"], i, 0,
                                       keepdims=False)
        cvs = lax.dynamic_index_in_dim(cache["v_scale"], i, 0,
                                       keepdims=False)
    if table is None:
        ck = lax.dynamic_index_in_dim(ck, slot, 0, keepdims=False)
        cv = lax.dynamic_index_in_dim(cv, slot, 0, keepdims=False)
        if cks is not None:
            cks = lax.dynamic_index_in_dim(cks, slot, 0, keepdims=False)
            cvs = lax.dynamic_index_in_dim(cvs, slot, 0, keepdims=False)
        if span is not None:
            ck, cv = ck[:span], cv[:span]
            if cks is not None:
                cks, cvs = cks[:, :span], cvs[:, :span]
        return ck, cv, cks, cvs
    bl = ck.shape[1]
    nb = table.shape[1] - 1                  # sentinel column: no rows
    if span is not None:
        nb = -(-span // bl)
    tblk = table[slot, :nb]                  # [nb]
    G = ck.shape[2]
    ck = ck[tblk].reshape(nb * bl, *ck.shape[2:])
    cv = cv[tblk].reshape(nb * bl, *cv.shape[2:])
    if cks is not None:
        cks = cks[tblk].transpose(1, 0, 2).reshape(G, nb * bl)
        cvs = cvs[tblk].transpose(1, 0, 2).reshape(G, nb * bl)
    if span is not None:
        ck, cv = ck[:span], cv[:span]
        if cks is not None:
            cks, cvs = cks[:, :span], cvs[:, :span]
    return ck, cv, cks, cvs


def _paged_attn_stats(cache: Cache, i, table, qh, lengths, span):
    """Big-cache attention stats via the Pallas paged-attention kernel
    (``SKYTPU_KV_KERNEL=1``): per (slot, kv-head) the kernel walks the
    slot's block table and streams its physical blocks through an
    online-softmax accumulator — the ``[slots, span, G, hd]`` logical
    view the gather path materializes per layer simply never exists.

    qh: [B, G, R, hd] query rows; lengths: [B] the per-slot validity
    bound (the same ``col < length`` rule the gather path's mask
    encodes); ``span`` (static) bounds the block sweep to the span
    rung's table prefix, exactly like the gather path. Returns the
    unnormalized stats ``(acc, m, l)`` for :func:`_merge_attn_parts`.
    """
    bl = cache["k"].shape[2]
    M = span if span is not None else (table.shape[1] - 1) * bl
    return paged_attn_ops.paged_attention(
        qh, cache["k"], cache["v"],
        cache.get("k_scale"), cache.get("v_scale"),
        table, lengths, i, span_blocks=-(-M // bl))


def _merge_attn_parts(acc, m, l, ss):
    """Two-block online-softmax combine: fold the staged-columns block
    into the kernel's big-cache stats. ``ss``: masked staged scores
    [..., W] (masked columns at -1e30). Returns (alpha, w_s, l_tot)
    where the final output is ``(acc * alpha + w_s @ v_staged) /
    l_tot`` — the same score set the one-shot softmax over
    [cache | staged] sees, summed in online order (greedy parity, not
    bit parity, vs the gather oracle). A slot with NO valid cache rows
    reports m == -1e30 and ``alpha`` underflows to exactly 0 — its
    (garbage) acc/l never contribute."""
    m_tot = jnp.maximum(m, jnp.max(ss, axis=-1))
    alpha = jnp.exp(m - m_tot)
    w_s = jnp.exp(ss - m_tot[..., None])
    l_tot = jnp.maximum(l * alpha + jnp.sum(w_s, axis=-1), 1e-30)
    return alpha, w_s, l_tot


# ---------------------------------------------------------------------------
# Prefill
# ---------------------------------------------------------------------------

def prefill(params: llama.Params, tokens: jax.Array, true_len: jax.Array,
            cfg: llama.LlamaConfig,
            constrain=None, qweights=None) -> Tuple[Cache, jax.Array]:
    """Causal forward over ONE right-padded prompt ([S_bucket] int32);
    see :func:`prefill_batch` for the batched core. Returns
    ({"k","v"}: [L, S_bucket, G, hd], logits [vocab] fp32)."""
    prefix, logits = prefill_batch(params, tokens[None], true_len[None],
                                   cfg, constrain=constrain,
                                   qweights=qweights)
    return {"k": prefix["k"][:, 0], "v": prefix["v"][:, 0]}, logits[0]


def prefill_batch(params: llama.Params, tokens: jax.Array,
                  true_lens: jax.Array, cfg: llama.LlamaConfig,
                  constrain=None, qweights=None, lora=None,
                  aid=None) -> Tuple[Cache, jax.Array]:
    """Causal forward over a WAVE of right-padded prompts.

    tokens: [W, S_bucket] int32, true_lens: [W] int32.
    Returns ({"k","v"}: [L, W, S_bucket, G, hd] post-rope rows, logits
    at each request's last real position [W, vocab] fp32). One batched
    program per wave: the W requests share every weight read and the
    matmuls run at W x S rows — admission cost per request drops vs a
    scan of W single-request prefills. With ``qweights`` the block
    matmuls + head run w8a8 int8, so params may omit the fp matrices
    entirely (slim tree: embed + norms only). ``lora``/``aid``: the
    adapter pool + per-wave-row pool slots — each row's (A, B) pair
    gathers into the batched matmuls (dummy rows ride slot 0, the
    all-zeros base).
    """
    if constrain is None:
        constrain = lambda x, axes: x
    wq8 = qweights is not None
    S = tokens.shape[1]
    x = params["embed"].astype(cfg.dtype)[tokens]
    positions = jnp.arange(S)
    cos, sin = llama.rope_frequencies(cfg, positions)

    def body(carry, layer_q):
        x = carry
        layer, qlayer, llayer = _layer_parts(layer_q, wq8,
                                             lora is not None)
        h = llama.rms_norm(x, layer["ln1"], cfg.norm_eps)
        q = proj("bsd,dhk->bshk", h, layer, qlayer, "wq", 1, cfg.dtype)
        k = proj("bsd,dhk->bshk", h, layer, qlayer, "wk", 1, cfg.dtype)
        v = proj("bsd,dhk->bshk", h, layer, qlayer, "wv", 1, cfg.dtype)
        if llayer is not None:
            q = q + _lora_in_delta(h, llayer["wq"], aid)
            k = k + _lora_in_delta(h, llayer["wk"], aid)
            v = v + _lora_in_delta(h, llayer["wv"], aid)
        q = llama.apply_rope(q, cos, sin)
        k = llama.apply_rope(k, cos, sin)
        from skypilot_tpu.ops import attention as attn_ops
        o = attn_ops.gqa_attention(q, k, v, causal=True)
        y = proj("bshk,hkd->bsd", o, layer, qlayer, "wo", 2, cfg.dtype)
        if llayer is not None:
            y = y + _lora_out_delta(o, llayer["wo"], aid)
        x = x + y
        h = llama.rms_norm(x, layer["ln2"], cfg.norm_eps)
        if wq8 and not hasattr(cfg, "n_experts"):
            g = proj("bsd,df->bsf", h, layer, qlayer, "w_gate", 1,
                     cfg.dtype)
            u = proj("bsd,df->bsf", h, layer, qlayer, "w_up", 1,
                     cfg.dtype)
            x = x + proj("bsf,fd->bsd", jax.nn.silu(g) * u, layer,
                         qlayer, "w_down", 1, cfg.dtype)
        else:
            x = x + _ffn(cfg, h, layer)
        return x, (k, v)

    xs = _scan_xs(params, qweights, lora)
    x, (ks, vs) = lax.scan(body, x, xs)        # ks: [L, W, S, G, hd]
    x = llama.rms_norm(x, params["final_norm"], cfg.norm_eps)
    last = jnp.take_along_axis(
        x, (true_lens - 1)[:, None, None], axis=1)[:, 0]       # [W, D]
    if wq8:
        logits = qeinsum("wd,dv->wv", last, qweights["head"], 1,
                         jnp.float32)
    else:
        head = (params["embed"].T if cfg.tie_embeddings
                else params["lm_head"])
        logits = (last @ head.astype(cfg.dtype)).astype(jnp.float32)
    return {"k": ks, "v": vs}, logits


def insert(cache: Cache, prefix: Cache, slot: jax.Array,
           true_len: jax.Array, first_token: jax.Array,
           table=None) -> Cache:
    """Install a prefilled prompt into a decode slot.

    prefix k/v: [L, S_bucket, G, hd]; rows >= true_len are padding but
    harmless — decode masks by ``length``. With a block ``table`` the
    rows scatter through the slot's table instead (values identical to
    the contiguous write, which is what makes paged-vs-contiguous
    generation bit-identical); the spare slot's all-sentinel row drops
    dummy-wave writes entirely.
    """
    out = dict(cache)
    pk, pv = prefix["k"], prefix["v"]
    quant = "k_scale" in cache
    if quant:
        pk, ks = quantize_rows(pk)          # ks/vs: [L, S, G]
        pv, vs = quantize_rows(pv)
        sdt = cache["k_scale"].dtype
        ks, vs = ks.astype(sdt), vs.astype(sdt)
    if table is None:
        if quant:
            out["k_scale"] = lax.dynamic_update_slice(
                cache["k_scale"], ks.transpose(0, 2, 1)[:, None],
                (0, slot, 0, 0))
            out["v_scale"] = lax.dynamic_update_slice(
                cache["v_scale"], vs.transpose(0, 2, 1)[:, None],
                (0, slot, 0, 0))
        out["k"] = lax.dynamic_update_slice(
            cache["k"], pk[:, None], (0, slot, 0, 0, 0))
        out["v"] = lax.dynamic_update_slice(
            cache["v"], pv[:, None], (0, slot, 0, 0, 0))
    else:
        S = pk.shape[1]
        blk, off = _phys(cache, table, slot, jnp.arange(S))
        out["k"] = cache["k"].at[:, blk, off].set(pk)
        out["v"] = cache["v"].at[:, blk, off].set(pv)
        if quant:
            # Non-adjacent advanced indices put the broadcast dim
            # first: update shape is [S, L, G].
            out["k_scale"] = cache["k_scale"].at[:, blk, :, off].set(
                ks.transpose(1, 0, 2))
            out["v_scale"] = cache["v_scale"].at[:, blk, :, off].set(
                vs.transpose(1, 0, 2))
    out["length"] = cache["length"].at[slot].set(true_len)
    out["last_token"] = cache["last_token"].at[slot].set(first_token)
    return out


# ---------------------------------------------------------------------------
# Prefix KV pool + chunked prefill
# ---------------------------------------------------------------------------
# Prefix reuse: a reserved pool of K/V rows holds prompt prefixes (one
# row = one prefix, full max_len rows) in a SEPARATE tensor from the
# decode cache, so decode programs never pay compute or scatter traffic
# for pool rows. Host-side bookkeeping (which prefix lives in which
# row, LRU) stays in the engine; the device side is two gather/scatter
# copy programs (slot->row to store, row->slot to load) plus the
# chunked-prefill program below, which prefills ONLY the suffix after a
# prefix hit — the same program that chunks long cold prompts.


def init_prefix_pool(cfg: llama.LlamaConfig, rows: int, max_len: int,
                     kv_int8: bool = False) -> Cache:
    """K/V rows reserved for the prefix cache (``rows`` resident
    prefixes). Same per-row layout (and int8 scales) as the decode
    cache so a row copy is a pure gather/scatter — no requantization,
    which is what makes cached-vs-cold generation bit-identical."""
    L, G, hd = cfg.n_layers, cfg.n_kv_heads, cfg.head_dim
    pool: Cache = {}
    if kv_int8:
        pool["k"] = jnp.zeros((L, rows, max_len, G, hd), jnp.int8)
        pool["v"] = jnp.zeros((L, rows, max_len, G, hd), jnp.int8)
        pool["k_scale"] = jnp.zeros((L, rows, G, max_len), jnp.bfloat16)
        pool["v_scale"] = jnp.zeros((L, rows, G, max_len), jnp.bfloat16)
    else:
        pool["k"] = jnp.zeros((L, rows, max_len, G, hd), cfg.dtype)
        pool["v"] = jnp.zeros((L, rows, max_len, G, hd), cfg.dtype)
    return pool


def pool_logical_axes(pool: Cache) -> Dict[str, Tuple]:
    """Sharding axes for the prefix pool: identical names to the decode
    cache's (row dim = "batch") so ONE TP rule set shards both and the
    row-copy programs stay layout-compatible under a mesh."""
    axes = {
        "k": ("layer", "batch", "seq_cache", "kv_heads", "head_dim"),
        "v": ("layer", "batch", "seq_cache", "kv_heads", "head_dim"),
    }
    if "k_scale" in pool:
        axes["k_scale"] = ("layer", "batch", "kv_heads", "seq_cache")
        axes["v_scale"] = ("layer", "batch", "kv_heads", "seq_cache")
    return axes


def pool_store(pool: Cache, cache: Cache, slot: jax.Array,
               row: jax.Array) -> Cache:
    """Copy a slot's K/V rows (all max_len of them — static shape) into
    a pool row. Rows past the prompt are garbage but harmless: the host
    index records the cached prefix length and a load's suffix prefill
    overwrites everything past it before decode can read it."""
    out = dict(pool)
    for name in pool:
        src = lax.dynamic_index_in_dim(cache[name], slot, 1,
                                       keepdims=False)
        out[name] = lax.dynamic_update_index_in_dim(pool[name], src,
                                                    row, 1)
    return out


def pool_load(cache: Cache, pool: Cache, row: jax.Array,
              slot: jax.Array, claim_len: jax.Array) -> Cache:
    """Copy a pool row into a decode slot AND claim the slot for an
    in-progress chunked prefill: length is stamped to ``claim_len``
    (= max_len) so interleaved decode bursts — which scatter a garbage
    row for EVERY slot at index ``length``, active or not — write out
    of bounds and get dropped instead of corrupting rows a finished
    chunk already wrote (see the engine's chunk scheduler)."""
    out = dict(cache)
    for name in pool:
        src = lax.dynamic_index_in_dim(pool[name], row, 1,
                                       keepdims=False)
        out[name] = lax.dynamic_update_index_in_dim(cache[name], src,
                                                    slot, 1)
    out["length"] = cache["length"].at[slot].set(claim_len)
    return out


def claim_slot(cache: Cache, slot: jax.Array,
               claim_len: jax.Array) -> Cache:
    """Claim a slot for a cold chunked prefill (no pool row to copy):
    same length stamp as :func:`pool_load`, same reason."""
    return dict(cache,
                length=cache["length"].at[slot].set(claim_len))


def export_blocks(cache: Cache, idx: jax.Array) -> Dict[str, jax.Array]:
    """Gather ``idx``-selected physical blocks' K/V rows (+ scales) out
    of the paged pool: [L, NB, block_len, G, hd] per tensor, the
    device half of a cross-replica KV handoff. ``idx`` is a FIXED-width
    [NB] vector (NB = blocks per slot) padded with the sentinel
    (== n_blocks); gathers CLAMP out-of-bounds indices, so padding rows
    come back as garbage the host masks by the true block count — one
    compiled program regardless of how many blocks transfer."""
    return {name: cache[name][:, idx]
            for name in ("k", "v", "k_scale", "v_scale")
            if name in cache}


def import_blocks(cache: Cache, idx: jax.Array,
                  vals: Dict[str, jax.Array]) -> Cache:
    """Scatter exported block rows into freshly allocated physical
    blocks — the receive half of a cross-replica KV handoff. Same
    fixed-width padded ``idx`` as :func:`export_blocks`: sentinel
    positions scatter out of bounds and DROP (the block-table garbage
    net), so padding never corrupts the pool."""
    out = dict(cache)
    for name, v in vals.items():
        out[name] = cache[name].at[:, idx].set(
            v.astype(cache[name].dtype))
    return out


def sync_slots(cache: Cache, active: jax.Array, lengths: jax.Array,
               tokens: jax.Array) -> Cache:
    """Force selected slots' (length, last_token) bookkeeping to
    host-given values in ONE batched program — the draft engine's
    lockstep/rollback seam (infer/draft.py).

    The drafter's KV rows for a mispredicted rollout sit PAST the
    committed length by construction (the same free-rollback property
    the verifier's window rows have), so rolling a draft slot back to
    the verifier's commit point — or re-pointing its pending token at
    the correction token — is purely this bookkeeping write: no K/V
    row moves, no block moves. ``active`` masks which slots sync;
    inactive slots are untouched (the commit_tokens idiom)."""
    return dict(
        cache,
        length=jnp.where(active, lengths.astype(jnp.int32),
                         cache["length"]),
        last_token=jnp.where(active, tokens.astype(jnp.int32),
                             cache["last_token"]))


def prefill_chunk(params: llama.Params, cache: Cache,
                  tokens_c: jax.Array, start: jax.Array,
                  n_valid: jax.Array, slot: jax.Array,
                  new_len: jax.Array, rng: jax.Array,
                  cfg: llama.LlamaConfig, sp, *, final: bool,
                  qweights=None, table=None, span=None,
                  kv_kernel=False, lora=None, aid=None
                  ) -> Tuple[Cache, jax.Array, jax.Array]:
    """One chunk of an incremental prefill into a decode slot.

    tokens_c: [C] int32 right-padded chunk; start: row offset of this
    chunk in the slot's sequence (rows < start — a reused prefix and/or
    earlier chunks — are already in the cache); n_valid: real tokens in
    this chunk; new_len: length to stamp (max_len mid-prefill, the true
    total on the final chunk — see :func:`pool_load`). ``final`` is
    static: the final variant samples the request's first token from
    the last valid position (and is the only one that splits the RNG,
    so cached and cold paths consume identical RNG streams).

    Chunk attention = big-cache dot over the slot's rows masked to
    ``col < start`` ++ causal intra-chunk dot — the decode_burst_staged
    formulation at C query rows. ONE compiled program (two with
    ``final``) serves every bucket and every suffix offset, replacing
    the per-bucket O(S^2) prefill monoliths above the chunk size.
    Numerics match the monolithic prefill up to summation order (same
    score set, softmaxed with the chunk block concatenated after the
    cache block); cached-vs-cold CHUNKED runs are bit-identical because
    both read/write the same rows with the same program. int8 KV path
    included: chunk rows quantize exactly as ``insert`` would.

    With ``table`` the slot's rows live in pool blocks: reads gather
    the blocks in logical order (same score set, same summation order
    as the contiguous read) and writes scatter through the table —
    paged-vs-contiguous chunk prefills are bit-identical.

    ``span`` (static): the big-cache dot reads only the first ``span``
    logical rows — sufficient whenever span >= ``start`` (the mask
    admits no row past ``start``), so the engine picks the span bucket
    covering this chunk's offset and a long-max_len engine stops
    paying max_len rows of reads per chunk. Same masked score set,
    same summation order: bit-identical to the full-view chunk.

    ``kv_kernel`` (static, paged only): the big-cache block runs
    through the Pallas paged-attention kernel over this slot's block
    table (queries batched as ``C * rep`` rows per kv-head) and merges
    with the intra-chunk block via the online-softmax combine — same
    score set, online summation order, greedy parity vs the gather
    oracle (this function with the flag off).

    Returns (cache', rng', first_token — 0 unless ``final``).
    """
    C = tokens_c.shape[0]
    M = span if span is not None else _logical_rows(cache, table)
    G, hd = cfg.n_kv_heads, cfg.head_dim
    rep = cfg.n_heads // G
    scale = hd ** -0.5
    neg = jnp.asarray(-1e30, jnp.float32)
    quant = "k_scale" in cache
    wq8 = qweights is not None
    sdt = cache["k_scale"].dtype if quant else None
    kdt = cache["k"].dtype

    x = params["embed"].astype(cfg.dtype)[tokens_c][None]   # [1, C, D]
    positions = start + jnp.arange(C)
    cos, sin = llama.rope_frequencies(cfg, positions)
    col = jnp.arange(M)
    j = jnp.arange(C)
    # The chunk program runs ONE slot: its adapter id is the single
    # entry of aid_b ([1], aligned with x's batch dim).
    aid_b = aid[slot][None] if lora is not None else None
    # Padding columns (>= n_valid) are masked out of the intra-chunk
    # scores; padding ROWS compute garbage that lands past the prompt's
    # true length, where decode's validity mask never reads.
    intra_mask = (j[None, :] <= j[:, None]) & (j[None, :] < n_valid)

    def body(carry, layer_q):
        x, i = carry
        layer, qlayer, llayer = _layer_parts(layer_q, wq8,
                                             lora is not None)
        h = llama.rms_norm(x, layer["ln1"], cfg.norm_eps)
        q = proj("bsd,dhk->bshk", h, layer, qlayer, "wq", 1, cfg.dtype)
        k = proj("bsd,dhk->bshk", h, layer, qlayer, "wk", 1, cfg.dtype)
        v = proj("bsd,dhk->bshk", h, layer, qlayer, "wv", 1, cfg.dtype)
        if llayer is not None:
            q = q + _lora_in_delta(h, llayer["wq"], aid_b)
            k = k + _lora_in_delta(h, llayer["wk"], aid_b)
            v = v + _lora_in_delta(h, llayer["wv"], aid_b)
        q = llama.apply_rope(q, cos, sin)
        k = llama.apply_rope(k, cos, sin)
        kr, vr = k[0], v[0]                       # [C, G, hd]
        if quant:
            kq, ksc = quantize_rows(kr)
            vq, vsc = quantize_rows(vr)
            ys = (kq, vq, ksc.astype(sdt), vsc.astype(sdt))
        else:
            ys = (kr.astype(kdt), vr.astype(kdt))
        # bf16 dots, fp32 accumulation — int8 converts to bf16 exactly
        # (see decode_step's note).
        qh = q[0].reshape(C, G, rep, hd).astype(jnp.bfloat16)
        ss = jnp.einsum("cgrk,jgk->cgrj", qh, kr.astype(jnp.bfloat16),
                        preferred_element_type=jnp.float32) * scale
        ss = jnp.where(intra_mask[:, None, None, :], ss, neg)
        if kv_kernel and table is not None:
            # Kernel big-cache block over THIS slot's table row: the
            # chunk's C * rep query rows batch into one (slot,
            # kv-head) grid cell each; the mask bound is ``start``
            # (rows below this chunk are the resident prefix).
            q_k = qh.transpose(1, 0, 2, 3).reshape(1, G, C * rep, hd)
            acc, m, l = _paged_attn_stats(
                cache, i, lax.dynamic_slice_in_dim(table, slot, 1, 0),
                q_k, jnp.reshape(start, (1,)), span)
            acc = acc.reshape(G, C, rep, hd).transpose(1, 0, 2, 3)
            m = m.reshape(G, C, rep).transpose(1, 0, 2)
            l = l.reshape(G, C, rep).transpose(1, 0, 2)
            alpha, w_s, l_tot = _merge_attn_parts(acc, m, l, ss)
            o = acc * alpha[..., None] + jnp.einsum(
                "cgrj,jgk->cgrk", w_s.astype(jnp.bfloat16),
                vr.astype(jnp.bfloat16),
                preferred_element_type=jnp.float32)
            o = o / l_tot[..., None]
        else:
            ck, cv, cks, cvs = _gather_slot_kv_layer(cache, i, slot,
                                                     table, span)
            sm = jnp.einsum("cgrk,mgk->cgrm", qh,
                            ck.astype(jnp.bfloat16),
                            preferred_element_type=jnp.float32) * scale
            if quant:
                sm = sm * cks[None, :, None, :]
            sm = jnp.where(col[None, None, None, :] < start, sm, neg)
            w = jax.nn.softmax(jnp.concatenate([sm, ss], axis=-1),
                               axis=-1)
            wm, ws = w[..., :M], w[..., M:]
            if quant:
                wm = wm * cvs[None, :, None, :]
            o = jnp.einsum("cgrm,mgk->cgrk", wm.astype(jnp.bfloat16),
                           cv.astype(jnp.bfloat16),
                           preferred_element_type=jnp.float32)
            o = o + jnp.einsum("cgrj,jgk->cgrk",
                               ws.astype(jnp.bfloat16),
                               vr.astype(jnp.bfloat16),
                               preferred_element_type=jnp.float32)
        o = o.reshape(1, C, cfg.n_heads, hd).astype(cfg.dtype)
        y = proj("bshk,hkd->bsd", o, layer, qlayer, "wo", 2, cfg.dtype)
        if llayer is not None:
            y = y + _lora_out_delta(o, llayer["wo"], aid_b)
        x = x + y
        h = llama.rms_norm(x, layer["ln2"], cfg.norm_eps)
        if wq8 and not hasattr(cfg, "n_experts"):
            g = proj("bsd,df->bsf", h, layer, qlayer, "w_gate", 1,
                     cfg.dtype)
            u = proj("bsd,df->bsf", h, layer, qlayer, "w_up", 1,
                     cfg.dtype)
            x = x + proj("bsf,fd->bsd", jax.nn.silu(g) * u, layer,
                         qlayer, "w_down", 1, cfg.dtype)
        else:
            x = x + _ffn(cfg, h, layer)
        return (x, i + 1), ys

    xs = _scan_xs(params, qweights, lora)
    (x, _), ys = lax.scan(body, (x, jnp.int32(0)), xs)

    if final:
        x = llama.rms_norm(x, params["final_norm"], cfg.norm_eps)
        last = lax.dynamic_index_in_dim(x[0], n_valid - 1, 0,
                                        keepdims=False)      # [D]
        if wq8:
            logits = qeinsum("d,dv->v", last, qweights["head"], 1,
                             jnp.float32)
        else:
            head = (params["embed"].T if cfg.tie_embeddings
                    else params["lm_head"])
            logits = (last @ head.astype(cfg.dtype)).astype(jnp.float32)
        rng, sub = jax.random.split(rng)
        tok = sampling_mod.sample(logits, sub, sp)
    else:
        tok = jnp.zeros((), jnp.int32)

    # Chunk rows land at logical [slot, start:start+C]. Scatter (not
    # dynamic_update_slice): a final partial chunk's window may poke
    # past max_len, and scatter DROPS out-of-bounds indices instead of
    # clamping the whole window backwards over valid rows (paged: the
    # overflow maps to the sentinel block, dropped the same way).
    idx = start + jnp.arange(C)
    blk, off = _phys(cache, table, slot, idx)
    out = dict(cache)
    if quant:
        kq_l, vq_l, ks_l, vs_l = ys       # [L,C,G,hd] / [L,C,G]
        out["k"] = cache["k"].at[:, blk, off].set(kq_l)
        out["v"] = cache["v"].at[:, blk, off].set(vq_l)
        # Non-adjacent advanced indices put the broadcast dim first:
        # update shape is [C, L, G].
        out["k_scale"] = cache["k_scale"].at[:, blk, :, off].set(
            ks_l.transpose(1, 0, 2))
        out["v_scale"] = cache["v_scale"].at[:, blk, :, off].set(
            vs_l.transpose(1, 0, 2))
    else:
        k_l, v_l = ys
        out["k"] = cache["k"].at[:, blk, off].set(k_l)
        out["v"] = cache["v"].at[:, blk, off].set(v_l)
    out["length"] = cache["length"].at[slot].set(new_len)
    if final:
        out["last_token"] = cache["last_token"].at[slot].set(tok)
    return out, rng, tok


# ---------------------------------------------------------------------------
# Decode
# ---------------------------------------------------------------------------

def _decode_qkv(cfg, layer, qlayer, x, cos, sin, llayer=None,
                aid=None):
    """Shared decode-layer front half: norm + q/k/v projections + rope
    (used by decode_step AND decode_burst_staged so quantization or
    projection changes land in ONE place). ``llayer``/``aid``: one
    layer's adapter-pool slice + per-slot pool ids — the per-slot
    (A, B) gather adds its delta before rope, exactly as a merged
    weight would."""
    h = llama.rms_norm(x, layer["ln1"], cfg.norm_eps)
    q = proj("bsd,dhk->bshk", h, layer, qlayer, "wq", 1, cfg.dtype)
    k = proj("bsd,dhk->bshk", h, layer, qlayer, "wk", 1, cfg.dtype)
    v = proj("bsd,dhk->bshk", h, layer, qlayer, "wv", 1, cfg.dtype)
    if llayer is not None:
        q = q + _lora_in_delta(h, llayer["wq"], aid)
        k = k + _lora_in_delta(h, llayer["wk"], aid)
        v = v + _lora_in_delta(h, llayer["wv"], aid)
    q = llama.apply_rope(q, cos, sin)
    k = llama.apply_rope(k, cos, sin)
    return q, k, v


def _decode_out_ffn(cfg, layer, qlayer, wq8, x, o, llayer=None,
                    aid=None):
    """Shared decode-layer back half: output projection + residual +
    FFN (w8a8 dense when quantized weights are present, the model's
    own _ffn — incl. MoE experts — otherwise)."""
    B = x.shape[0]
    o = o.reshape(B, 1, cfg.n_heads, cfg.head_dim).astype(cfg.dtype)
    y = proj("bshk,hkd->bsd", o, layer, qlayer, "wo", 2, cfg.dtype)
    if llayer is not None:
        y = y + _lora_out_delta(o, llayer["wo"], aid)
    x = x + y
    h = llama.rms_norm(x, layer["ln2"], cfg.norm_eps)
    if wq8 and not hasattr(cfg, "n_experts"):
        g = proj("bsd,df->bsf", h, layer, qlayer, "w_gate", 1,
                 cfg.dtype)
        u = proj("bsd,df->bsf", h, layer, qlayer, "w_up", 1, cfg.dtype)
        m = proj("bsf,fd->bsd", jax.nn.silu(g) * u, layer, qlayer,
                 "w_down", 1, cfg.dtype)
        return x + m
    return x + _ffn(cfg, h, layer)


def _decode_head(cfg, params, qweights, x):
    """Shared final-norm + LM head (fp or w8a8)."""
    x = llama.rms_norm(x, params["final_norm"], cfg.norm_eps)
    if qweights is not None:
        return qeinsum("bsd,dv->bsv", x, qweights["head"], 1,
                       jnp.float32)[:, 0]
    head = (params["embed"].T if cfg.tie_embeddings
            else params["lm_head"])
    return jnp.einsum("bsd,dv->bsv", x,
                      head.astype(cfg.dtype))[:, 0].astype(jnp.float32)


def decode_step(params: llama.Params, cache: Cache,
                cfg: llama.LlamaConfig,
                constrain=None, qweights=None,
                table=None, span=None, lora=None,
                aid=None) -> Tuple[Cache, jax.Array]:
    """One token for every slot. Returns (cache', logits [slots, vocab]).

    ``qweights`` (from ``quantize_block_weights``/``quantize_head``):
    run the seven block matmuls + the LM head as w8a8 int8 — half the
    weight HBM reads and the 2x int8 MXU path, the decode bottleneck.
    ``table`` ([slots, blocks_per_slot + 1] int32): paged layout —
    reads gather each slot's blocks in logical order, the pending-row
    scatter maps through the table (sentinel -> dropped).
    ``span`` (static): attention reads only the first ``span`` logical
    rows — valid whenever every active slot's length <= span (the
    engine's span-bucket selection guarantees it); the pending-row
    scatter still routes through the FULL table, so writes are
    untouched. Bit-identical to the full view: the rows dropped were
    all masked to exact-zero softmax weight.
    """
    if constrain is None:
        constrain = lambda x, axes: x
    B = cache["length"].shape[0]
    M = span if span is not None else _logical_rows(cache, table)
    G, hd = cfg.n_kv_heads, cfg.head_dim
    rep = cfg.n_heads // G

    tokens = cache["last_token"][:, None]                     # [B, 1]
    # ``length`` counts rows already in the cache (prompt + committed
    # tokens); the pending token's K/V row is written at index length.
    pos = cache["length"]                                     # [B]
    x = params["embed"].astype(cfg.dtype)[tokens]             # [B, 1, D]
    cos, sin = llama.rope_frequencies(cfg, pos[:, None])      # [B,1,hd/2]

    # Stored rows are STRICTLY below ``length``; the pending token
    # joins attention as an explicit SELF-TERM (one extra logit per
    # head) and its K/V rows are scattered into the cache ONCE — for
    # all layers together — after the layer scan. Keeping the cache a
    # scan INVARIANT (read-only inside the loop) instead of a carry is
    # what the decode-step's HBM budget lives on: the carried version
    # round-tripped each layer's 82 MB K/V slice through
    # dynamic-slice/row-update/dynamic-update (~330 MB of copy traffic
    # per layer, ~12 ms of a 31 ms 8B step), and even the scatter-into-
    # carry variant paid 4 serialized scatters x 32 layers of fixed op
    # overhead. Self-term math is identical: the pending row's score
    # uses the SAME quantized values a read-back would see, and the
    # softmax simply sees that logit at the end of the row instead of
    # at index ``length``.
    valid = (jnp.arange(M)[None, :] < cache["length"][:, None])   # [B, M]
    neg = jnp.asarray(-1e30, jnp.float32)
    scale = hd ** -0.5
    batch_ix = jnp.arange(B)

    quant = "k_scale" in cache
    wq8 = qweights is not None
    sdt = cache["k_scale"].dtype if quant else None

    def body(carry, layer_q):
        x, i = carry
        layer, qlayer, llayer = _layer_parts(layer_q, wq8,
                                             lora is not None)
        q, k, v = _decode_qkv(cfg, layer, qlayer, x, cos, sin,
                              llayer, aid)
        if quant:
            kq, ks = quantize_rows(k[:, 0])     # ks/vs: [B, G]
            vq, vs = quantize_rows(v[:, 0])
            ks, vs = ks.astype(sdt), vs.astype(sdt)
            k_new = kq.astype(jnp.bfloat16)     # exact: int8 fits bf16
            v_new = vq.astype(jnp.float32) * vs.astype(jnp.float32)[..., None]
            ys = (kq, vq, ks, vs)
        else:
            kq, vq = k[:, 0], v[:, 0]
            ks = vs = None
            k_new = kq.astype(jnp.bfloat16)
            v_new = vq.astype(jnp.float32)
            ys = (kq, vq)
        ck, cv, cks, cvs = _gather_kv_layer(cache, i, table, span)
        # The attention dots run in bf16 with fp32 ACCUMULATION. The
        # int8 cache converts to bf16 EXACTLY (integers <= 127 carry no
        # rounding in an 8-bit mantissa) and each bf16xbf16 product is
        # exact in the fp32 accumulator, so the scores match a full
        # fp32 dot while the materialized cache-sized intermediate is
        # half the size. Per-row scales stay linear in the contraction:
        # K's scale applies to the SCORES and V's folds into the
        # softmax weights — nothing dequantized at cache shape ever
        # hits fp32.
        qh = q[:, 0].reshape(B, G, rep, hd).astype(jnp.bfloat16)
        s = jnp.einsum("bgrk,bmgk->bgrm", qh, ck.astype(jnp.bfloat16),
                       preferred_element_type=jnp.float32) * scale
        s_self = jnp.einsum("bgrk,bgk->bgr", qh, k_new,
                            preferred_element_type=jnp.float32) * scale
        if quant:
            s = s * cks[:, :, None, :]
            s_self = s_self * ks.astype(jnp.float32)[:, :, None]
        s = jnp.where(valid[:, None, None, :], s, neg)
        w = jax.nn.softmax(jnp.concatenate([s, s_self[..., None]], -1),
                           axis=-1)
        wm, w_self = w[..., :M], w[..., M]
        if quant:
            wm = wm * cvs[:, :, None, :]
        o = jnp.einsum("bgrm,bmgk->bgrk", wm.astype(jnp.bfloat16),
                       cv.astype(jnp.bfloat16),
                       preferred_element_type=jnp.float32)
        o = o + w_self[..., None] * v_new[:, :, None, :]
        x = _decode_out_ffn(cfg, layer, qlayer, wq8, x, o, llayer, aid)
        return (x, i + 1), ys

    xs = _scan_xs(params, qweights, lora)
    (x, _), ys = lax.scan(body, (x, jnp.int32(0)), xs)
    logits = _decode_head(cfg, params, qweights, x)
    # One batched scatter per cache array: every layer's pending row
    # lands at logical [l, b, pos[b]] (the ys stacks are megabyte-scale
    # next to the gigabyte-scale cache, and the donated cache aliases
    # through).
    blk, off = _phys(cache, table, batch_ix, pos)
    out = dict(cache)
    if quant:
        kq_l, vq_l, ks_l, vs_l = ys           # [L,B,G,hd] / [L,B,G]
        out["k"] = cache["k"].at[:, blk, off].set(kq_l)
        out["v"] = cache["v"].at[:, blk, off].set(vq_l)
        # Non-adjacent advanced indices put the broadcast dim first:
        # update shape is [B, L, G].
        out["k_scale"] = cache["k_scale"].at[:, blk, :, off].set(
            ks_l.transpose(1, 0, 2))
        out["v_scale"] = cache["v_scale"].at[:, blk, :, off].set(
            vs_l.transpose(1, 0, 2))
    else:
        k_l, v_l = ys
        out["k"] = cache["k"].at[:, blk, off].set(k_l)
        out["v"] = cache["v"].at[:, blk, off].set(v_l)
    return out, logits


def commit_tokens(cache: Cache, tokens: jax.Array,
                  active: jax.Array) -> Cache:
    """Append sampled tokens on active slots: bump lengths, set last."""
    return dict(
        cache,
        length=cache["length"] + active.astype(jnp.int32),
        last_token=jnp.where(active, tokens, cache["last_token"]))


def _staged_attn_layer(cfg, cache, table, layer, qlayer, x, cos, sin,
                       i, s, sk, sv, sks, svs, valid_cache,
                       stage_valid, batch_ix, span=None, pos0=None,
                       kv_kernel=False, llayer=None, aid=None):
    """One decoder layer of a staged-burst step: the current step's
    K/V rows land in the staging buffers, attention runs as big-cache
    dot (rows masked by ``valid_cache``) ++ staged-columns dot
    (columns masked by ``stage_valid``), and the big cache stays a
    pure invariant. Shared VERBATIM by :func:`decode_burst_staged` and
    :func:`verify_draft_staged` — the speculative parity guarantee is
    precisely that both programs run THIS math, so an edit here can
    never drift one without the other. ``span`` (static) bounds the
    big-cache read to the first ``span`` logical rows; the caller's
    ``valid_cache`` mask must already be span-shaped.

    ``kv_kernel`` (static): run the big-cache block through the Pallas
    paged-attention kernel instead of the gather — the kernel walks
    the block table per (slot, kv-head) and the logical-view transient
    never materializes. Requires a ``table`` (the kernel is
    block-table-native; contiguous callers keep the gather) and
    ``pos0`` (the burst-start lengths the kernel masks by — the same
    rule ``valid_cache`` encodes). The staged-columns block is
    UNCHANGED either way; the two blocks merge via the online-softmax
    combine (:func:`_merge_attn_parts`) — same score set, online
    summation order, greedy parity vs the gather oracle.
    Returns (x', sk, sv, sks, svs).
    """
    quant = "k_scale" in cache
    wq8 = qlayer is not None
    kdt = cache["k"].dtype
    sdt = cache["k_scale"].dtype if quant else None
    B = x.shape[0]
    G, hd = cfg.n_kv_heads, cfg.head_dim
    rep = cfg.n_heads // G
    M = span if span is not None else _logical_rows(cache, table)
    scale = hd ** -0.5
    neg = jnp.asarray(-1e30, jnp.float32)

    q, kk, v = _decode_qkv(cfg, layer, qlayer, x, cos, sin, llayer,
                           aid)
    if quant:
        kq, ksc = quantize_rows(kk[:, 0])
        vq, vsc = quantize_rows(v[:, 0])
        ksc, vsc = ksc.astype(sdt), vsc.astype(sdt)
        sk = sk.at[i, batch_ix, s].set(kq)
        sv = sv.at[i, batch_ix, s].set(vq)
        sks = sks.at[i, batch_ix, s].set(ksc)
        svs = svs.at[i, batch_ix, s].set(vsc)
    else:
        sk = sk.at[i, batch_ix, s].set(kk[:, 0].astype(kdt))
        sv = sv.at[i, batch_ix, s].set(v[:, 0].astype(kdt))
    lk = lax.dynamic_index_in_dim(sk, i, 0, False)
    lv = lax.dynamic_index_in_dim(sv, i, 0, False)
    # bf16 dots, fp32 accumulation — int8 converts to bf16 exactly
    # (see decode_step's note).
    qh = q[:, 0].reshape(B, G, rep, hd).astype(jnp.bfloat16)
    ss = jnp.einsum("bgrk,bjgk->bgrj", qh,
                    lk.astype(jnp.bfloat16),
                    preferred_element_type=jnp.float32) * scale
    lvs = None
    if quant:
        lks = lax.dynamic_index_in_dim(sks, i, 0, False)
        lvs = lax.dynamic_index_in_dim(svs, i, 0, False)
        ss = ss * lks.transpose(0, 2, 1)[:, :, None, :]
    ss = jnp.where(stage_valid[:, None, None, :], ss, neg)
    if kv_kernel and table is not None:
        acc, m, l = _paged_attn_stats(cache, i, table, qh, pos0, span)
        alpha, w_s, l_tot = _merge_attn_parts(acc, m, l, ss)
        if quant:
            w_s = w_s * lvs.transpose(0, 2, 1)[:, :, None, :]
        o = acc * alpha[..., None] + jnp.einsum(
            "bgrj,bjgk->bgrk", w_s.astype(jnp.bfloat16),
            lv.astype(jnp.bfloat16),
            preferred_element_type=jnp.float32)
        o = o / l_tot[..., None]
    else:
        ck, cv, cks, cvs = _gather_kv_layer(cache, i, table, span)
        sm = jnp.einsum("bgrk,bmgk->bgrm", qh,
                        ck.astype(jnp.bfloat16),
                        preferred_element_type=jnp.float32) * scale
        if quant:
            sm = sm * cks[:, :, None, :]
        sm = jnp.where(valid_cache[:, None, None, :], sm, neg)
        w = jax.nn.softmax(jnp.concatenate([sm, ss], axis=-1), axis=-1)
        wm, ws = w[..., :M], w[..., M:]
        if quant:
            wm = wm * cvs[:, :, None, :]
            ws = ws * lvs.transpose(0, 2, 1)[:, :, None, :]
        o = jnp.einsum("bgrm,bmgk->bgrk", wm.astype(jnp.bfloat16),
                       cv.astype(jnp.bfloat16),
                       preferred_element_type=jnp.float32)
        o = o + jnp.einsum("bgrj,bjgk->bgrk",
                           ws.astype(jnp.bfloat16),
                           lv.astype(jnp.bfloat16),
                           preferred_element_type=jnp.float32)
    x = _decode_out_ffn(cfg, layer, qlayer, wq8, x, o, llayer, aid)
    return x, sk, sv, sks, svs


def _flush_staged_rows(cache: Cache, table, pos0, batch_ix,
                       sk, sv, sks, svs) -> Cache:
    """One batched scatter per cache array: every staged window row
    lands at logical [b, pos0[b] + j] (through the block table when
    paged — sentinel/overflow rows drop). Shared by the burst and
    verify programs; the caller updates length/last_token."""
    W = sk.shape[2]
    idx = pos0[:, None] + jnp.arange(W)[None, :]           # [B, W]
    blk, off = _phys(cache, table, batch_ix[:, None], idx)
    out = dict(cache)
    out["k"] = cache["k"].at[:, blk, off].set(sk)
    out["v"] = cache["v"].at[:, blk, off].set(sv)
    if "k_scale" in cache:
        # Non-adjacent advanced indices lead with the broadcast [B, W]
        # dims: updates are [B, W, L, G].
        out["k_scale"] = cache["k_scale"].at[
            :, blk, :, off].set(sks.transpose(1, 2, 0, 3))
        out["v_scale"] = cache["v_scale"].at[
            :, blk, :, off].set(svs.transpose(1, 2, 0, 3))
    return out


def decode_burst_staged(params: llama.Params, cache: Cache,
                        rng: jax.Array, active: jax.Array, k: int,
                        cfg: llama.LlamaConfig, sp,
                        qweights=None, table=None, span=None,
                        kv_kernel=False, lora=None, aid=None
                        ) -> Tuple[Cache, jax.Array, jax.Array]:
    """k decode steps with a per-BURST cache flush (the engine's burst
    program; trace under jit with cache+rng donated).

    Within the burst, each step's K/V rows land in a small STAGING
    buffer ([L, slots, k, G, hd] — megabytes) and attention runs as
    big-cache dot (rows < the burst-start lengths, a CONSTANT mask) ++
    staged-columns dot (cols <= step). The big cache is therefore a
    pure scan INVARIANT: one batched scatter flushes all k rows after
    the step loop. The previous formulation scattered into the carried
    cache every step — XLA couldn't keep those fully in place, costing
    ~2.3 ms of a 24.9 ms 8B step, and carried-cache reads fuse worse
    than invariant reads (measured: this version decodes the same
    burst in ~18-20 ms/step, ~25% faster end to end).

    Logits equal the per-step formulation's up to summation order
    (the same score set, softmaxed with staged columns concatenated
    after the cache block instead of interleaved at their cache
    positions), so greedy tokens can differ on near-ties exactly as
    any kernel reorganization allows.

    Dead slots (inactive, or retired mid-burst) write rows past their
    logical end; flush indices beyond the buffer are DROPPED by JAX
    scatter OOB semantics, and reused slots are fully re-stamped by
    ``insert``. With a block ``table``, cache reads gather each slot's
    blocks in logical order and the flush scatters through the table
    (cleared/dead slot rows map to the sentinel block and drop).

    ``span`` (static): the big-cache read covers only the first
    ``span`` logical rows. Correct whenever every ACTIVE slot's
    burst-start length <= span (the engine's bucket selection); an
    inactive slot whose length exceeds the span computes garbage that
    is never committed, exactly like any other dead-slot row. The
    flush scatters through the FULL table, so writes are unchanged.

    ``kv_kernel`` (static): route the big-cache read through the
    Pallas paged-attention kernel (paged only — see
    :func:`_staged_attn_layer`); greedy parity vs this function with
    the flag off, which stays the oracle.
    Returns (cache', rng', toks [k, slots]).
    """
    B = cache["length"].shape[0]
    M = span if span is not None else _logical_rows(cache, table)
    G, hd = cfg.n_kv_heads, cfg.head_dim
    L = cfg.n_layers
    quant = "k_scale" in cache
    wq8 = qweights is not None
    sdt = cache["k_scale"].dtype if quant else None
    kdt = cache["k"].dtype

    pos0 = cache["length"]                           # burst-start rows
    valid_cache = jnp.arange(M)[None, :] < pos0[:, None]   # [B, M]
    batch_ix = jnp.arange(B)

    rng, sub = jax.random.split(rng)
    keys = jax.random.split(sub, k)

    stage_k = jnp.zeros((L, B, k, G, hd), kdt)
    stage_v = jnp.zeros((L, B, k, G, hd), kdt)
    zero = jnp.zeros((), jnp.float32)
    stage_ks = jnp.zeros((L, B, k, G), sdt) if quant else zero
    stage_vs = jnp.zeros((L, B, k, G), sdt) if quant else zero

    def step(carry, key_s):
        key, s = key_s
        last, sk, sv, sks, svs = carry
        x = params["embed"].astype(cfg.dtype)[last[:, None]]
        pos = pos0 + s
        cos, sin = llama.rope_frequencies(cfg, pos[:, None])
        stage_valid = jnp.arange(k)[None, :] <= s     # [1, k]

        def body(carry2, layer_q):
            x, i, sk, sv, sks, svs = carry2
            layer, qlayer, llayer = _layer_parts(layer_q, wq8,
                                                 lora is not None)
            x, sk, sv, sks, svs = _staged_attn_layer(
                cfg, cache, table, layer, qlayer, x, cos, sin, i, s,
                sk, sv, sks, svs, valid_cache, stage_valid, batch_ix,
                span, pos0, kv_kernel, llayer, aid)
            return (x, i + 1, sk, sv, sks, svs), None

        xs = _scan_xs(params, qweights, lora)
        (x, _, sk, sv, sks, svs), _ = lax.scan(
            body, (x, jnp.int32(0), sk, sv, sks, svs), xs)
        logits = _decode_head(cfg, params, qweights, x)
        tok = sampling_mod.sample(logits, key, sp)
        last = jnp.where(active, tok, last)
        return (last, sk, sv, sks, svs), tok

    init = (cache["last_token"], stage_k, stage_v, stage_ks, stage_vs)
    (last, sk, sv, sks, svs), toks = lax.scan(
        step, init, (keys, jnp.arange(k)))

    out = _flush_staged_rows(cache, table, pos0, batch_ix,
                             sk, sv, sks, svs)
    out["length"] = cache["length"] + k * active.astype(jnp.int32)
    out["last_token"] = last
    return out, rng, toks


def verify_draft_staged(params: llama.Params, cache: Cache,
                        draft: jax.Array, n_draft: jax.Array,
                        active: jax.Array, k: int,
                        cfg: llama.LlamaConfig,
                        qweights=None, table=None, span=None,
                        kv_kernel=False, lora=None, aid=None
                        ) -> Tuple[Cache, jax.Array, jax.Array]:
    """Speculative-decode verify: score ``k`` drafted tokens per slot
    plus the correction position in ONE device call (the engine's
    verify program; trace under jit with the cache donated, ``k``
    static — one compiled program for the whole serving lifetime).

    draft: [B, k] int32 host-proposed tokens per slot (n-gram /
    prompt-lookup — the drafter never touches the device); n_draft:
    [B] int32 real draft tokens per slot (slots that drafted fewer
    than ``k`` pad and mask, exactly like a partial prefill chunk).

    The window is ``k + 1`` positions: position 0 consumes the slot's
    pending ``last_token`` (the same token a plain decode step would
    consume) and positions 1..k consume the draft. Structurally this
    is :func:`decode_burst_staged` with the sampled-token feedback
    replaced by the given window tokens and greedy argmax outputs —
    same big-cache dot over rows < the burst-start lengths, same
    staged intra-window dot, same single per-burst flush — so an
    ACCEPTED position's logits are computed from exactly the inputs
    the plain decode path would have fed it.

    Greedy-exact acceptance, ON DEVICE (no RNG anywhere — the greedy
    path's stream must stay untouched): out[s] = argmax after
    consuming window position s; the accepted prefix length is the
    longest run of out[s] == draft[s] over real (< n_draft) draft
    positions, and ``n_commit = n_match + 1`` committed tokens per
    active slot — the matched draft tokens plus the first correction
    (or bonus) token from the same pass. Committed outputs depend only
    on real tokens: out[s] for s <= n_match attends to window columns
    0..s, all of which are the pending token or MATCHED draft tokens.

    Rollback is free by construction: all ``k + 1`` window rows are
    scattered at logical rows length..length+k, but ``length`` only
    advances by ``n_commit`` — rejected rows sit past the committed
    length, invisible to the validity mask (contiguous) or sitting in
    already-allocated blocks (paged: a block-table length decrement,
    no block ever moves), and the next burst overwrites them. A slot
    without k + 1 rows of headroom below max_len rides the burst with
    an empty draft (the engine zeroes it): its correction row at
    ``length`` is always in bounds for an active request, and spare
    window rows past max_len drop via scatter-OOB (contiguous) or the
    sentinel block (paged).

    ``span`` (static): same bounded big-cache read as
    :func:`decode_burst_staged` — accepted positions see exactly the
    score set the plain decode path at the same span would, so the
    spec parity guarantee extends to every span bucket.

    Returns (cache', toks [B, k+1] — the window's argmax outputs, the
    first ``n_commit[b]`` of row b are the committed tokens —
    n_commit [B] int32, 0 for inactive slots).
    """
    B = cache["length"].shape[0]
    W = k + 1
    M = span if span is not None else _logical_rows(cache, table)
    G, hd = cfg.n_kv_heads, cfg.head_dim
    L = cfg.n_layers
    quant = "k_scale" in cache
    wq8 = qweights is not None
    sdt = cache["k_scale"].dtype if quant else None
    kdt = cache["k"].dtype

    pos0 = cache["length"]                           # burst-start rows
    valid_cache = jnp.arange(M)[None, :] < pos0[:, None]   # [B, M]
    batch_ix = jnp.arange(B)

    # Window tokens: the pending token then the draft — the exact
    # sequence sequential decode would consume while every draft
    # position matches.
    window = jnp.concatenate(
        [cache["last_token"][:, None], draft.astype(jnp.int32)],
        axis=1)                                      # [B, W]

    stage_k = jnp.zeros((L, B, W, G, hd), kdt)
    stage_v = jnp.zeros((L, B, W, G, hd), kdt)
    zero = jnp.zeros((), jnp.float32)
    stage_ks = jnp.zeros((L, B, W, G), sdt) if quant else zero
    stage_vs = jnp.zeros((L, B, W, G), sdt) if quant else zero

    def step(carry, tok_s):
        tok, s = tok_s
        sk, sv, sks, svs = carry
        x = params["embed"].astype(cfg.dtype)[tok[:, None]]
        pos = pos0 + s
        cos, sin = llama.rope_frequencies(cfg, pos[:, None])
        stage_valid = jnp.arange(W)[None, :] <= s     # [1, W]

        def body(carry2, layer_q):
            x, i, sk, sv, sks, svs = carry2
            layer, qlayer, llayer = _layer_parts(layer_q, wq8,
                                                 lora is not None)
            x, sk, sv, sks, svs = _staged_attn_layer(
                cfg, cache, table, layer, qlayer, x, cos, sin, i, s,
                sk, sv, sks, svs, valid_cache, stage_valid, batch_ix,
                span, pos0, kv_kernel, llayer, aid)
            return (x, i + 1, sk, sv, sks, svs), None

        xs = _scan_xs(params, qweights, lora)
        (x, _, sk, sv, sks, svs), _ = lax.scan(
            body, (x, jnp.int32(0), sk, sv, sks, svs), xs)
        logits = _decode_head(cfg, params, qweights, x)
        out_tok = sampling_mod.argmax_tokens(logits)
        return (sk, sv, sks, svs), out_tok

    init = (stage_k, stage_v, stage_ks, stage_vs)
    (sk, sv, sks, svs), toks = lax.scan(
        step, init, (window.T, jnp.arange(W)))
    toks = toks.T                                     # [B, W]

    # Accepted prefix: out[s] must reproduce draft position s, and
    # padding positions (>= n_draft) never match — a pad token that
    # happened to equal the argmax must not commit a token computed
    # from garbage input.
    match = ((toks[:, :k] == draft)
             & (jnp.arange(k)[None, :] < n_draft[:, None]))
    n_match = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1),
                      axis=1)                          # [B]
    n_commit = jnp.where(active, n_match + 1, 0).astype(jnp.int32)

    out = _flush_staged_rows(cache, table, pos0, batch_ix,
                             sk, sv, sks, svs)
    out["length"] = cache["length"] + n_commit
    out["last_token"] = jnp.where(active, toks[batch_ix, n_match],
                                  cache["last_token"])
    return out, toks, n_commit
