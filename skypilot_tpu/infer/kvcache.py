"""KV-cache prefill / decode steps for the Llama family.

TPU-first design notes
----------------------
* Everything is **static-shape**: the decode cache is a pre-allocated
  ``[L, slots, max_len, kv_heads, head_dim]`` buffer; per-slot lengths
  mask attention instead of resizing anything. One compiled prefill per
  prompt bucket, one compiled decode step, reused for the whole serving
  lifetime — no retracing, ever.
* Prefill is the plain causal forward (right-padded to a bucket length)
  that additionally emits each layer's post-rope K/V rows; padding rows
  never poison the cache because causal attention keeps positions
  < true_len independent of them, and decode masks rows >= length.
* Decode processes *all slots together*: [slots, 1] tokens through the
  stacked-layer ``lax.scan``, one scatter per layer to append K/V. This
  is the JetStream-style generate step — MXU-batched across requests.
* Sharding composes with serving TP: cache kv-head dim maps to ``tp``,
  slot dim to (``dp``, ``fsdp``) via the standard rule table.

Reference parity: the reference serves LLMs only through external
engines (reference: llm/vllm/serve.yaml, examples/tpu/v6e/README.md
JetStream section). This module is the in-tree TPU-native engine core.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from skypilot_tpu.models import llama

Cache = Dict[str, jax.Array]


def _ffn(cfg: llama.LlamaConfig, h: jax.Array, layer: Dict) -> jax.Array:
    """Post-norm FFN: dense SwiGLU, or the sparse expert FFN when the
    config is an MoE (aux loss is irrelevant at inference and dropped).
    h: [B, S, D].

    MoE + right-padded prefill is safe: capacity assignment is
    position-ordered, so padding rows (after true_len) can never evict
    a real token from an expert's buffer; decode steps see S=1 where
    top-k choices always fit.
    """
    if hasattr(cfg, "n_experts"):
        from skypilot_tpu.models import moe
        out, _ = moe.moe_ffn(cfg, h, layer)
        return out
    g = jnp.einsum("bsd,df->bsf", h, layer["w_gate"].astype(cfg.dtype))
    u = jnp.einsum("bsd,df->bsf", h, layer["w_up"].astype(cfg.dtype))
    return jnp.einsum("bsf,fd->bsd", jax.nn.silu(g) * u,
                      layer["w_down"].astype(cfg.dtype))


def init_cache(cfg: llama.LlamaConfig, n_slots: int,
               max_len: int) -> Cache:
    """Pre-allocated decode state for ``n_slots`` concurrent requests."""
    L, G, hd = cfg.n_layers, cfg.n_kv_heads, cfg.head_dim
    return {
        "k": jnp.zeros((L, n_slots, max_len, G, hd), cfg.dtype),
        "v": jnp.zeros((L, n_slots, max_len, G, hd), cfg.dtype),
        # Tokens generated + prompt rows present, per slot (0 = free).
        "length": jnp.zeros((n_slots,), jnp.int32),
        "last_token": jnp.zeros((n_slots,), jnp.int32),
    }


def cache_logical_axes() -> Dict[str, Tuple]:
    return {
        "k": ("layer", "batch", "seq_cache", "kv_heads", "head_dim"),
        "v": ("layer", "batch", "seq_cache", "kv_heads", "head_dim"),
        "length": ("batch",),
        "last_token": ("batch",),
    }


# ---------------------------------------------------------------------------
# Prefill
# ---------------------------------------------------------------------------

def prefill(params: llama.Params, tokens: jax.Array, true_len: jax.Array,
            cfg: llama.LlamaConfig,
            constrain=None) -> Tuple[Cache, jax.Array]:
    """Causal forward over a right-padded prompt.

    tokens: [S_bucket] int32 (single request), true_len: scalar int32.
    Returns ({"k","v"}: [L, S_bucket, G, hd] post-rope rows, logits at
    the last real position [vocab] fp32).
    """
    if constrain is None:
        constrain = lambda x, axes: x
    tokens = tokens[None]                                     # [1, S]
    S = tokens.shape[1]
    x = params["embed"].astype(cfg.dtype)[tokens]
    positions = jnp.arange(S)
    cos, sin = llama.rope_frequencies(cfg, positions)

    def body(carry, layer):
        x = carry
        h = llama.rms_norm(x, layer["ln1"], cfg.norm_eps)
        q = jnp.einsum("bsd,dhk->bshk", h, layer["wq"].astype(cfg.dtype))
        k = jnp.einsum("bsd,dhk->bshk", h, layer["wk"].astype(cfg.dtype))
        v = jnp.einsum("bsd,dhk->bshk", h, layer["wv"].astype(cfg.dtype))
        q = llama.apply_rope(q, cos, sin)
        k = llama.apply_rope(k, cos, sin)
        from skypilot_tpu.ops import attention as attn_ops
        o = attn_ops.gqa_attention(q, k, v, causal=True)
        o = jnp.einsum("bshk,hkd->bsd", o, layer["wo"].astype(cfg.dtype))
        x = x + o
        h = llama.rms_norm(x, layer["ln2"], cfg.norm_eps)
        return x + _ffn(cfg, h, layer), (k[0], v[0])

    x, (ks, vs) = lax.scan(body, x, params["blocks"])
    x = llama.rms_norm(x, params["final_norm"], cfg.norm_eps)
    last = x[0, true_len - 1]                                  # [D]
    head = (params["embed"].T if cfg.tie_embeddings else params["lm_head"])
    logits = (last @ head.astype(cfg.dtype)).astype(jnp.float32)
    return {"k": ks, "v": vs}, logits


def insert(cache: Cache, prefix: Cache, slot: jax.Array,
           true_len: jax.Array, first_token: jax.Array) -> Cache:
    """Install a prefilled prompt into a decode slot.

    prefix k/v: [L, S_bucket, G, hd]; rows >= true_len are padding but
    harmless — decode masks by ``length``.
    """
    k = lax.dynamic_update_slice(
        cache["k"], prefix["k"][:, None], (0, slot, 0, 0, 0))
    v = lax.dynamic_update_slice(
        cache["v"], prefix["v"][:, None], (0, slot, 0, 0, 0))
    return {
        "k": k,
        "v": v,
        "length": cache["length"].at[slot].set(true_len),
        "last_token": cache["last_token"].at[slot].set(first_token),
    }


# ---------------------------------------------------------------------------
# Decode
# ---------------------------------------------------------------------------

def decode_step(params: llama.Params, cache: Cache,
                cfg: llama.LlamaConfig,
                constrain=None) -> Tuple[Cache, jax.Array]:
    """One token for every slot. Returns (cache', logits [slots, vocab])."""
    if constrain is None:
        constrain = lambda x, axes: x
    B = cache["length"].shape[0]
    M = cache["k"].shape[2]
    G, hd = cfg.n_kv_heads, cfg.head_dim
    rep = cfg.n_heads // G

    tokens = cache["last_token"][:, None]                     # [B, 1]
    # ``length`` counts rows already in the cache (prompt + committed
    # tokens); the pending token's K/V row is written at index length.
    pos = cache["length"]                                     # [B]
    x = params["embed"].astype(cfg.dtype)[tokens]             # [B, 1, D]
    cos, sin = llama.rope_frequencies(cfg, pos[:, None])      # [B,1,hd/2]

    # Rows <= length are valid (the just-written current row included).
    valid = (jnp.arange(M)[None, :] <= cache["length"][:, None])  # [B, M]
    neg = jnp.asarray(-1e30, jnp.float32)
    scale = hd ** -0.5
    batch_ix = jnp.arange(B)

    def body(carry, layer_kv):
        x = carry
        layer, ck, cv = layer_kv                              # ck [B,M,G,hd]
        h = llama.rms_norm(x, layer["ln1"], cfg.norm_eps)
        q = jnp.einsum("bsd,dhk->bshk", h, layer["wq"].astype(cfg.dtype))
        k = jnp.einsum("bsd,dhk->bshk", h, layer["wk"].astype(cfg.dtype))
        v = jnp.einsum("bsd,dhk->bshk", h, layer["wv"].astype(cfg.dtype))
        q = llama.apply_rope(q, cos, sin)
        k = llama.apply_rope(k, cos, sin)
        ck = ck.at[batch_ix, pos].set(k[:, 0])
        cv = cv.at[batch_ix, pos].set(v[:, 0])
        qh = q[:, 0].reshape(B, G, rep, hd)
        s = jnp.einsum("bgrk,bmgk->bgrm", qh.astype(jnp.float32),
                       ck.astype(jnp.float32)) * scale
        s = jnp.where(valid[:, None, None, :], s, neg)
        w = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bgrm,bmgk->bgrk", w, cv.astype(jnp.float32))
        o = o.reshape(B, 1, cfg.n_heads, hd).astype(cfg.dtype)
        o = jnp.einsum("bshk,hkd->bsd", o, layer["wo"].astype(cfg.dtype))
        x = x + o
        h = llama.rms_norm(x, layer["ln2"], cfg.norm_eps)
        return x + _ffn(cfg, h, layer), (ck, cv)

    x, (new_k, new_v) = lax.scan(
        body, x, (params["blocks"], cache["k"], cache["v"]))
    x = llama.rms_norm(x, params["final_norm"], cfg.norm_eps)
    head = (params["embed"].T if cfg.tie_embeddings else params["lm_head"])
    logits = jnp.einsum("bsd,dv->bsv", x,
                        head.astype(cfg.dtype))[:, 0].astype(jnp.float32)
    return {
        "k": new_k,
        "v": new_v,
        "length": cache["length"],
        "last_token": cache["last_token"],
    }, logits


def commit_tokens(cache: Cache, tokens: jax.Array,
                  active: jax.Array) -> Cache:
    """Append sampled tokens on active slots: bump lengths, set last."""
    return {
        "k": cache["k"],
        "v": cache["v"],
        "length": cache["length"] + active.astype(jnp.int32),
        "last_token": jnp.where(active, tokens, cache["last_token"]),
    }
