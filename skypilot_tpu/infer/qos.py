"""Multi-tenant QoS: admission control, weighted fair queueing,
priority lanes.

Three host-side pieces protect a serving fleet from a hot tenant
(ROADMAP item 4 — nothing here touches a device program, so tenant
count can never enter program identity):

* :class:`AdmissionController` — per-tenant token-bucket rate limits
  plus a queue-depth overload check, shared by the model server and
  the load balancer. A shed is TYPED: :class:`RateLimitedError` maps
  to HTTP 429 (``{"type": "rate_limited", "retry_after_ms": ...}``),
  :class:`OverloadedError` to HTTP 503 (``{"type": "overloaded"}``) —
  clients back off deterministically instead of parsing prose. The
  decision rides the ``qos.shed`` chaos point, so a fault plan can
  force sheds deterministically (tests/test_chaos.py).

* :class:`FairScheduler` — deficit-round-robin over per-tenant
  subqueues of the engine's ``waiting`` deque, weighted by configured
  tenant weight and costed in TOKENS (prompt + committed + budget), so
  one tenant's hundred queued requests cannot starve a neighbor's one.
  Priority lanes sort strictly above the DRR interleave; WFQ applies
  within a lane. The scheduler only REORDERS the deque before an
  admission pass — bucketed waves, chunked claims and span regrouping
  downstream are untouched.

* Priority preemption-by-eviction lives in the engine
  (:meth:`InferenceEngine.preempt_slot`): the scheduler here just puts
  the outranking request at the head so admission finds it first.

Tenant identity comes from a request header (``SKYTPU_TENANT_HEADER``,
default ``x-skytpu-tenant``) or the request body's ``tenant`` field
(the SDK path); priority from ``x-skytpu-priority`` / ``priority``.
Tenants are client-supplied strings, so every metric label rides
:func:`tenant_label`, which caps the live label set and collapses the
overflow into ``other`` — a scanner must not mint unbounded series.

Config (env; see docs/serving.md §Multi-tenant QoS for the knob
table): ``SKYTPU_QOS=1`` enables, ``SKYTPU_QOS_RATE`` /
``SKYTPU_QOS_BURST`` set the default per-tenant bucket,
``SKYTPU_QOS_MAX_WAITING`` the overload shed depth,
``SKYTPU_QOS_QUANTUM`` the DRR quantum (tokens), and
``SKYTPU_QOS_TENANTS`` a JSON object of per-tenant overrides
(``{"free-tier": {"rate": 2, "burst": 4, "weight": 1,
"priority": -1}}``).
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Any, Deque, Dict, List, Optional, Tuple

from skypilot_tpu import chaos
from skypilot_tpu.observability import metrics

DEFAULT_TENANT = "default"
TENANT_HEADER_ENV = "SKYTPU_TENANT_HEADER"
DEFAULT_TENANT_HEADER = "x-skytpu-tenant"
PRIORITY_HEADER = "x-skytpu-priority"

QOS_REQUESTS = metrics.counter(
    "skytpu_qos_requests_total",
    "Requests admitted past QoS admission control, by tenant "
    "(label set capped; overflow tenants collapse into 'other') and "
    "tier — LB-admitted requests are admitted AGAIN at the server, so "
    "fleet req/s must read one tier, not the sum",
    labelnames=("tenant", "where"))
QOS_SHED = metrics.counter(
    "skytpu_qos_shed_total",
    "Requests load-shed by QoS admission control, by tenant, reason "
    "(rate_limited | overloaded | injected) and tier (server | lb)",
    labelnames=("tenant", "reason", "where"))
QOS_PREEMPTIONS = metrics.counter(
    "skytpu_qos_preemptions_total",
    "Decode slots preempted-by-eviction for a higher-priority "
    "request, by the VICTIM's tenant",
    labelnames=("tenant",))
QOS_TENANTS = metrics.gauge(
    "skytpu_qos_tenants",
    "Distinct tenant label values currently tracked (capped — the "
    "cap, not the true tenant cardinality, bounds this)")

# Metric-label cap: tenants are client-supplied strings and label
# children are never evicted — past the cap everything reads 'other'.
_MAX_TENANT_LABELS = 32
_label_lock = threading.Lock()
_labels_seen: set = set()        # guarded-by: _label_lock

# Bucket-table key for post-cap strangers: a sentinel OBJECT, not the
# string "other" — a real tenant named "other" must keep its own
# bucket, not pool quota with every overflow stranger.
_OVERFLOW_BUCKET_KEY = object()


def retry_after_header(retry_after_s: float) -> str:
    """The ``Retry-After`` header value (integer seconds, ceiling,
    min 1) — one implementation so the LB and the model server cannot
    drift apart on the same shed."""
    return str(max(int(retry_after_s + 0.999), 1))


def tenant_label(tenant: str, cfg: Optional["QosConfig"] = None) -> str:
    """The metric-label value for a tenant: itself while the live
    label set is under the cap, ``other`` past it. A CONFIGURED
    tenant bypasses the cap for the same reason it bypasses the
    bucket-table cap: the cap defends against scanner-minted names,
    and config — not scanners — bounds real tenants. Without the
    bypass, 32 throwaway names seen at startup would permanently
    collapse the operator's own tenants into ``other``."""
    with _label_lock:
        if tenant in _labels_seen:
            return tenant
        if (len(_labels_seen) >= _MAX_TENANT_LABELS
                and not (cfg is not None and tenant in cfg.tenants)):
            return "other"
        _labels_seen.add(tenant)
        QOS_TENANTS.set(len(_labels_seen))
        return tenant


def _reset_labels_for_tests() -> None:
    with _label_lock:
        _labels_seen.clear()


class ShedError(Exception):
    """Base of the typed load-shed family: carries the HTTP status and
    the ``typed_error`` body the server/LB return verbatim (the
    PromptTooLongError idiom — a shed is the caller's signal to back
    off, never a 500)."""

    http_status = 503

    def __init__(self, message: str, typed_error: Dict[str, Any],
                 retry_after_s: float = 1.0):
        super().__init__(message)
        self.typed_error = typed_error
        self.retry_after_s = retry_after_s

    def retry_after_header(self) -> str:
        return retry_after_header(self.retry_after_s)


class RateLimitedError(ShedError):
    """Tenant over its token-bucket rate -> HTTP 429."""

    http_status = 429

    def __init__(self, tenant: str, retry_after_s: float,
                 reason: str = "rate_limited"):
        msg = (f"tenant {tenant!r} over its request rate; retry in "
               f"{retry_after_s:.2f}s")
        super().__init__(msg, {
            "type": "rate_limited",
            "tenant": tenant,
            "retry_after_ms": int(retry_after_s * 1000),
            "message": msg,
        }, retry_after_s=retry_after_s)
        self.tenant = tenant
        self.reason = reason


class OverloadedError(ShedError):
    """Queue depth past the shed threshold -> HTTP 503."""

    def __init__(self, depth: int, max_waiting: int):
        msg = (f"server overloaded: {depth} queued requests "
               f"(shed threshold {max_waiting})")
        super().__init__(msg, {
            "type": "overloaded",
            "queued": depth,
            "max_waiting": max_waiting,
            "message": msg,
        }, retry_after_s=1.0)


class TokenBucket:
    """Classic token bucket; not thread-safe (the owner holds the
    lock). ``take`` returns 0.0 when a token was consumed, else the
    seconds until one accrues (the typed 429's Retry-After)."""

    def __init__(self, rate: float, burst: float,
                 now: Optional[float] = None):
        self.rate = float(rate)
        self.burst = max(float(burst), 1.0)
        self.tokens = self.burst
        self.last_s = time.monotonic() if now is None else now

    def take(self, now: Optional[float] = None) -> float:
        now = time.monotonic() if now is None else now
        # max(..., 0): a caller-supplied clock must never bank debt.
        self.tokens = min(self.burst, self.tokens
                          + max(now - self.last_s, 0.0) * self.rate)
        self.last_s = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return 0.0
        if self.rate <= 0.0:
            return 1.0
        return (1.0 - self.tokens) / self.rate


@dataclasses.dataclass
class TenantSpec:
    """Per-tenant QoS knobs. ``rate`` 0 = unlimited; ``weight`` scales
    the tenant's DRR share; ``priority`` is the default lane for the
    tenant's requests (a per-request header may override);
    ``max_kv_blocks`` caps the paged KV blocks the tenant's resident
    slots may reference at once (0 = unlimited) — the engine stalls
    the tenant's admissions at the cap (typed ``qos.kv_quota_stall``,
    never a 503) so a hot tenant cannot hog the block pool via long
    contexts while rate-limited."""

    rate: float = 0.0
    burst: float = 0.0           # 0 -> max(2 * rate, 4)
    weight: int = 1
    priority: int = 0
    max_kv_blocks: int = 0

    def bucket_burst(self) -> float:
        return self.burst if self.burst > 0 else max(2 * self.rate, 4.0)


@dataclasses.dataclass
class QosConfig:
    """The env-derived QoS policy shared by server, LB and engine."""

    enabled: bool = False
    default_rate: float = 0.0        # req/s per tenant; 0 = unlimited
    default_burst: float = 0.0
    max_waiting: int = 0             # queued requests before 503; 0 = off
    quantum: int = 256               # DRR quantum, in tokens
    tenants: Dict[str, TenantSpec] = dataclasses.field(
        default_factory=dict)

    @classmethod
    def from_env(cls) -> "QosConfig":
        def _f(name, default):
            try:
                return float(os.environ.get(name, "") or default)
            except ValueError:
                return default
        tenants: Dict[str, TenantSpec] = {}
        raw = os.environ.get("SKYTPU_QOS_TENANTS", "").strip()
        if raw:
            try:
                for name, spec in json.loads(raw).items():
                    tenants[str(name)] = TenantSpec(
                        rate=float(spec.get("rate", 0.0)),
                        burst=float(spec.get("burst", 0.0)),
                        weight=max(int(spec.get("weight", 1)), 1),
                        priority=int(spec.get("priority", 0)),
                        max_kv_blocks=max(
                            int(spec.get("max_kv_blocks", 0)), 0))
            except (ValueError, TypeError, AttributeError):
                # A typo'd override must not silently disable QoS for
                # every tenant; fall back to the defaults, loudly.
                from skypilot_tpu.observability import tracing
                tracing.add_event("qos.tenants_invalid",
                                  {"raw": raw[:200]}, echo=True)
                tenants = {}
        return cls(
            enabled=os.environ.get("SKYTPU_QOS", "") == "1",
            default_rate=_f("SKYTPU_QOS_RATE", 0.0),
            default_burst=_f("SKYTPU_QOS_BURST", 0.0),
            max_waiting=int(_f("SKYTPU_QOS_MAX_WAITING", 0)),
            quantum=max(int(_f("SKYTPU_QOS_QUANTUM", 256)), 1),
            tenants=tenants)

    def tenant(self, name: str) -> TenantSpec:
        spec = self.tenants.get(name)
        if spec is not None:
            return spec
        return TenantSpec(rate=self.default_rate,
                          burst=self.default_burst)


def tenant_header() -> str:
    return (os.environ.get(TENANT_HEADER_ENV, "").strip().lower()
            or DEFAULT_TENANT_HEADER)


def request_identity(headers, body: Optional[Dict[str, Any]] = None,
                     cfg: Optional[QosConfig] = None
                     ) -> Tuple[str, int]:
    """(tenant, priority) for one request: header first, then the
    body's ``tenant``/``priority`` fields (the SDK path), then the
    tenant's configured default lane. Tenant strings are capped at 64
    chars; priority clamps to [-9, 9]. Whenever a QoS config is in
    force the tenant's lane (configured spec, else the default spec)
    is also a ceiling — a request may deprioritize itself, but a
    client-supplied header must never outrank the operator's lane
    (priority gates preemption rights; the hostile hot tenant this
    module defends against must not control them, and minting a fresh
    unconfigured tenant name must not be the escape hatch)."""
    tenant = None
    prio_raw = None
    if headers is not None:
        tenant = headers.get(tenant_header())
        prio_raw = headers.get(PRIORITY_HEADER)
    if not tenant and isinstance(body, dict):
        tenant = body.get("tenant")
    if prio_raw is None and isinstance(body, dict):
        prio_raw = body.get("priority")
    # Strip BEFORE the emptiness check: a whitespace-only header value
    # must read as the default tenant, not mint a tenant="" series,
    # bucket and DRR lane of its own.
    tenant = (str(tenant).strip()[:64] if tenant else "") or DEFAULT_TENANT
    if prio_raw is None and cfg is not None:
        priority = cfg.tenant(tenant).priority
    else:
        try:
            priority = int(prio_raw) if prio_raw is not None else 0
        except (TypeError, ValueError):
            priority = 0
        if cfg is not None:
            priority = min(priority, cfg.tenant(tenant).priority)
    return tenant, max(-9, min(priority, 9))


class AdmissionController:
    """Token-bucket admission + overload shed; thread-safe (handler
    threads call :meth:`admit` concurrently)."""

    def __init__(self, cfg: QosConfig, where: str = "server"):
        self.cfg = cfg
        self.where = where
        self._lock = threading.Lock()
        self._buckets: Dict[Any, TokenBucket] = {}  # guarded-by: _lock

    def _shed(self, tenant: str, reason: str, err: ShedError):
        QOS_SHED.labels(tenant=tenant_label(tenant, self.cfg),
                        reason=reason, where=self.where).inc()
        raise err

    def admit(self, tenant: str, depth: Optional[int] = None) -> None:
        """Admit one request or raise the typed shed. ``depth`` is the
        caller's queue depth (inbox + in-flight) for the overload
        check; None skips it (the LB has no queue)."""
        try:
            chaos.point("qos.shed", tenant=tenant, where=self.where)
        except Exception:  # noqa: BLE001 — an injected fault IS a shed
            self._shed(tenant, "injected",
                       RateLimitedError(tenant, 1.0, reason="injected"))
        if (self.cfg.max_waiting and depth is not None
                and depth >= self.cfg.max_waiting):
            self._shed(tenant, "overloaded",
                       OverloadedError(depth, self.cfg.max_waiting))
        spec = self.cfg.tenant(tenant)
        if spec.rate > 0:
            with self._lock:
                bucket = self._buckets.get(tenant)
                if bucket is None:
                    # Bucket table is bounded like the label set: past
                    # the cap, UNCONFIGURED tenants share one 'other'
                    # bucket at the default spec (they already share
                    # its metric label). Explicitly configured tenants
                    # always get their own bucket — the config, not a
                    # scanner minting throwaway names, bounds those —
                    # so a paid tenant first seen past the cap is never
                    # throttled to the strangers' shared quota.
                    if (tenant not in self.cfg.tenants
                            and len(self._buckets) >= _MAX_TENANT_LABELS):
                        bucket = self._buckets.get(_OVERFLOW_BUCKET_KEY)
                        if bucket is None:
                            bucket = TokenBucket(spec.rate,
                                                 spec.bucket_burst())
                            self._buckets[_OVERFLOW_BUCKET_KEY] = bucket
                    else:
                        bucket = TokenBucket(spec.rate,
                                             spec.bucket_burst())
                        self._buckets[tenant] = bucket
                wait_s = bucket.take()
            if wait_s > 0:
                self._shed(tenant, "rate_limited",
                           RateLimitedError(tenant, wait_s))
        QOS_REQUESTS.labels(tenant=tenant_label(tenant, self.cfg),
                            where=self.where).inc()


class FairScheduler:
    """Deficit-round-robin reorder of the engine's ``waiting`` deque.

    Called by the engine at the top of each admission pass (loop
    thread only — no locking needed). Requests split into
    ``(priority, tenant)`` lanes preserving per-tenant FIFO; lanes
    emit highest priority first, and within a priority level tenants
    interleave by DRR — each round a tenant's deficit grows by
    ``min(quantum, cheapest queued head) * weight`` tokens and it
    releases queued requests while the head request's token cost fits
    (the cap keeps rotation request-granular when the configured
    quantum dwarfs the workload's request cost). Cost is the request's KV
    footprint (prompt + committed tokens + remaining budget), so
    fairness is over the resource requests actually consume, not
    request count. The rotation start follows SERVICE: each call
    observes which requests left the queue since the last one (the
    claim loop consumes the head, so a missing request was admitted)
    and starts the next round at the tenant after the last one
    served. A pass that admits nothing must not advance the rotation
    — admission capacity frees on the engine's schedule, and a
    counter that ticks per CALL can land the same tenant at the
    front on exactly the passes that claim, starving the other lane
    deterministically.
    """

    def __init__(self, cfg: Optional[QosConfig] = None,
                 quantum: Optional[int] = None):
        self.cfg = cfg or QosConfig(enabled=True)
        self.quantum = int(quantum if quantum is not None
                           else self.cfg.quantum)
        # Last reorder's output as (rid, priority, tenant), head
        # first; diffed against the live deque to observe admissions.
        self._prev_order: List[Tuple[int, int, str]] = []
        self._last_served: Dict[int, str] = {}   # priority -> tenant

    def weight(self, tenant: str) -> int:
        w = self.cfg.tenant(tenant).weight      # already an int (config)
        return w if w > 1 else 1

    def request_cost(self, req) -> int:
        """Token footprint of one queued request (its DRR cost)."""
        return max(len(req.prompt) + len(req.tokens)
                   + req.max_new_tokens, 1)

    def reorder(self, waiting: Deque) -> None:
        """Rebuild ``waiting`` in (priority lane, DRR) order, in
        place. Pure host bookkeeping over request lists."""
        # Observe service since the last pass: a request gone from the
        # deque was claimed off the head — iterating the previous
        # output head-first leaves the LAST tenant served per lane,
        # which the rotation below starts after.
        if self._prev_order:
            present = {r.rid for r in waiting}
            for rid, prio, tenant in self._prev_order:
                if rid not in present:
                    self._last_served[prio] = tenant
        if len(waiting) < 2:
            self._prev_order = [(r.rid, r.priority, r.tenant)
                                for r in waiting]
            return
        lanes: Dict[Tuple[int, str], List] = {}
        tenant_order: Dict[int, List[str]] = {}
        for r in waiting:
            key = (r.priority, r.tenant)
            if key not in lanes:
                lanes[key] = []
                tenant_order.setdefault(r.priority, []).append(r.tenant)
            lanes[key].append(r)
        if len(lanes) < 2:
            self._prev_order = [(r.rid, r.priority, r.tenant)
                                for r in waiting]
            return                      # one lane: FIFO already fair
        out: List = []
        for prio in sorted(tenant_order, reverse=True):
            tenants = tenant_order[prio]
            last = self._last_served.get(prio)
            start = ((tenants.index(last) + 1) % len(tenants)
                     if last in tenants else 0)
            tenants = tenants[start:] + tenants[:start]
            queues = {t: lanes[(prio, t)] for t in tenants}
            heads = {t: 0 for t in tenants}
            deficit = {t: 0 for t in tenants}
            remaining = sum(len(q) for q in queues.values())
            while remaining:
                # Per-round top-up: the configured quantum capped at the
                # cheapest head still queued this round. A fleet quantum
                # sized for production prompts must not let one lane's
                # first top-up drain its whole queue ahead of a small
                # workload's other tenants; the cap keeps rotation
                # request-granular at any cost scale while weights stay
                # token-proportional, and it guarantees the cheapest
                # head's lane releases every round (the loop is O(n)
                # rounds, not cost-ratio-many).
                step = min([self.quantum]
                           + [self.request_cost(queues[t][heads[t]])
                              for t in tenants
                              if heads[t] < len(queues[t])])
                for t in tenants:
                    q, i = queues[t], heads[t]
                    if i >= len(q):
                        deficit[t] = 0
                        continue
                    deficit[t] += step * self.weight(t)
                    while i < len(q) and \
                            self.request_cost(q[i]) <= deficit[t]:
                        deficit[t] -= self.request_cost(q[i])
                        out.append(q[i])
                        i += 1
                        remaining -= 1
                    heads[t] = i
        waiting.clear()
        waiting.extend(out)
        self._prev_order = [(r.rid, r.priority, r.tenant) for r in out]


def admission_from_env(where: str = "server"
                       ) -> Optional[AdmissionController]:
    """The process's admission controller, or None when QoS is off
    (``SKYTPU_QOS`` != 1) — a None policy is the zero-cost path."""
    cfg = QosConfig.from_env()
    if not cfg.enabled:
        return None
    return AdmissionController(cfg, where=where)


def scheduler_from_env() -> Optional[FairScheduler]:
    """The engine's fair scheduler, or None when QoS is off."""
    cfg = QosConfig.from_env()
    if not cfg.enabled:
        return None
    return FairScheduler(cfg)
