"""Serving benchmark: TTFT + token throughput on the local accelerator.

Prints ONE JSON line, same contract as the repo-root bench.py:
  {"metric": "serve_median_ttft", "value": ..., "unit": "ms",
   "vs_baseline": ...}

vs_baseline compares against the reference's JetStream anchor on TPU
(reference: examples/tpu/v6e/README.md — median TTFT 1829.33 ms,
2147.98 output tok/s for Llama-2-7B on v6e; BASELINE.md). Ratio > 1
means faster than baseline (baseline_ttft / our_ttft).

Usage: python -m skypilot_tpu.infer.bench_serve [--config llama3-400m]
       [--requests 16] [--slots 8] [--prompt-len 96] [--new-tokens 64]
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def log(*a):
    print(*a, file=sys.stderr, flush=True)


REF_TTFT_MS = 1829.33
REF_TOK_S = 2147.98


def run(config=None, requests=16, slots=16, prompt_len=96,
        new_tokens=64, max_burst=32, kv_int8=False,
        weights_int8=False, admit_wave=None) -> dict:
    """Run the serving benchmark; returns the metrics dict (also usable
    by the repo-root bench.py to fold serving numbers into its single
    JSON artifact)."""
    import jax
    import numpy as np

    on_cpu = jax.default_backend() == "cpu"
    if config is None:
        config = "llama3-tiny" if on_cpu else "llama3-400m"
    cfg, e = _build_engine(config, slots, prompt_len, new_tokens,
                           kv_int8, weights_int8, max_wave=admit_wave)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, cfg.vocab_size, prompt_len).tolist()
               for _ in range(requests)]

    # Warmup: compile the full-wave admission program and the burst
    # decode programs at the measured run's own burst size.
    for p in [prompts[0]] * slots:
        e.add_request(p, max_new_tokens=new_tokens)
    e.run_to_completion(max_burst=max_burst)
    e.finished.clear()

    t0 = time.time()
    for p in prompts:
        e.add_request(p, max_new_tokens=new_tokens)
    done = e.run_to_completion(max_burst=max_burst)
    # Force a host sync so the wall clock is honest (axon relay:
    # block_until_ready does not synchronize; a host fetch does).
    float(e.cache["length"][0])
    wall = time.time() - t0

    ttfts = sorted((r.first_token_s - r.submit_s) * 1e3 for r in done)
    med_ttft = ttfts[len(ttfts) // 2]
    total_tokens = sum(len(r.tokens) for r in done)
    tok_s = total_tokens / wall
    req_s = len(done) / wall

    log(f"requests={len(done)} wall={wall:.2f}s median_ttft={med_ttft:.1f}ms "
        f"tok/s={tok_s:.1f} req/s={req_s:.2f}")
    return {
        "median_ttft_ms": round(med_ttft, 2),
        "out_tok_s": round(tok_s, 2),
        "req_per_s": round(req_s, 3),
        "vs_baseline_ttft": round(REF_TTFT_MS / max(med_ttft, 1e-9), 3),
        "config": config,
        "kv_int8": kv_int8,
        "weights_int8": weights_int8,
    }


def _build_engine(config, slots, prompt_len, new_tokens, kv_int8,
                  weights_int8, max_wave=None):
    import jax

    from skypilot_tpu.infer import engine as eng
    from skypilot_tpu.models import llama
    cfg = llama.CONFIGS[config]
    log(f"serve bench: {config} on {jax.devices()[0].device_kind}")
    max_len = prompt_len + new_tokens + 8
    if weights_int8:
        # Build int8 weights directly — the fp init of an 8B-class
        # config (32 GB) would never fit the chip that the int8 model
        # (8 GB) serves from.
        from skypilot_tpu.infer import kvcache
        params, qw = kvcache.random_quantized_params(cfg)
        return cfg, eng.InferenceEngine(
            params, cfg, n_slots=slots, max_len=max_len,
            prompt_buckets=(prompt_len,), kv_int8=kv_int8, qweights=qw,
            max_wave=max_wave)
    params = llama.init_params(jax.random.key(0), cfg)
    return cfg, eng.InferenceEngine(
        params, cfg, n_slots=slots, max_len=max_len,
        prompt_buckets=(prompt_len,), kv_int8=kv_int8,
        max_wave=max_wave)


def run_http(config=None, requests=16, slots=16, prompt_len=96,
             new_tokens=64, max_burst=8, kv_int8=False,
             weights_int8=False, admit_wave=None) -> dict:
    """End-to-end streaming bench: requests go over HTTP through a REAL
    load balancer to the model server, and TTFT is the wall time to the
    FIRST STREAMED BYTE of each response — the JetStream comparison
    (reference: examples/tpu/v6e/README.md measures streaming TTFT),
    not an engine-internal timestamp.
    """
    import json as _json
    import os
    import socket
    import tempfile
    import threading
    import urllib.request

    import jax

    on_cpu = jax.default_backend() == "cpu"
    if config is None:
        config = "llama3-tiny" if on_cpu else "llama3-400m"

    home = tempfile.mkdtemp(prefix="skytpu-bench-serve-")
    os.environ["SKYPILOT_TPU_HOME"] = home

    from skypilot_tpu.infer import server as srv
    from skypilot_tpu.serve import load_balancer, serve_state
    from skypilot_tpu.serve.serve_state import ReplicaStatus

    cfg, engine = _build_engine(config, slots, prompt_len, new_tokens,
                                kv_int8, weights_int8,
                                max_wave=admit_wave)

    def free_port():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    model_port, lb_port = free_port(), free_port()
    model, httpd = srv.serve(engine, host="127.0.0.1", port=model_port,
                             max_burst=max_burst)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    assert model._ready.wait(timeout=600), "model warmup timed out"

    serve_state.add_service("bench", {}, {}, lb_port)
    serve_state.upsert_replica("bench", 1, "bench-replica",
                               ReplicaStatus.READY,
                               f"http://127.0.0.1:{model_port}")
    lb = load_balancer._ThreadingServer(
        ("127.0.0.1", lb_port),
        load_balancer.make_handler("bench",
                                   load_balancer.LeastLoadPolicy()))
    threading.Thread(target=lb.serve_forever, daemon=True).start()
    endpoint = f"http://127.0.0.1:{lb_port}/generate"

    import numpy as np
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, cfg.vocab_size, prompt_len).tolist()
               for _ in range(requests)]

    results = {}

    def one(i, record):
        body = _json.dumps({"tokens": prompts[i],
                            "max_new_tokens": new_tokens,
                            "stream": True}).encode()
        req = urllib.request.Request(
            endpoint, data=body,
            headers={"Content-Type": "application/json"})
        t0 = time.time()
        first = None
        n_tok = 0
        buf = b""
        with urllib.request.urlopen(req, timeout=600) as r:
            while True:
                piece = r.read1(65536)
                if not piece:
                    break
                if first is None:
                    first = time.time()
                buf += piece
        for line in buf.split(b"\n"):
            if line.strip():
                n_tok += len(_json.loads(line).get("tokens", []))
        if record:
            results[i] = ((first - t0) * 1e3, n_tok, time.time() - t0)

    # Warmup wave: compile admission/burst programs at the measured
    # shapes, outside the timed window.
    warm = [threading.Thread(target=one, args=(i % len(prompts), False))
            for i in range(min(slots, requests))]
    for t in warm:
        t.start()
    for t in warm:
        t.join(timeout=600)

    t0 = time.time()
    threads = [threading.Thread(target=one, args=(i, True))
               for i in range(requests)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=600)
    wall = time.time() - t0

    lb.shutdown()
    httpd.shutdown()
    model.shutdown()

    assert len(results) == requests, f"only {len(results)} completed"
    ttfts = sorted(v[0] for v in results.values())
    med_ttft = ttfts[len(ttfts) // 2]
    p99_ttft = ttfts[min(len(ttfts) - 1, int(len(ttfts) * 0.99))]
    total_tokens = sum(v[1] for v in results.values())
    tok_s = total_tokens / wall
    req_s = requests / wall
    log(f"http/lb streaming: requests={requests} wall={wall:.2f}s "
        f"median_ttft={med_ttft:.1f}ms p99={p99_ttft:.1f}ms "
        f"tok/s={tok_s:.1f}")
    return {
        "median_ttft_ms": round(med_ttft, 2),
        "p99_ttft_ms": round(p99_ttft, 2),
        "out_tok_s": round(tok_s, 2),
        "req_per_s": round(req_s, 3),
        "vs_baseline_ttft": round(REF_TTFT_MS / max(med_ttft, 1e-9), 3),
        "config": config,
        "kv_int8": kv_int8,
        "weights_int8": weights_int8,
        "transport": "http_lb_streaming",
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default=None)
    ap.add_argument("--requests", type=int, default=16)
    ap.add_argument("--slots", type=int, default=16)
    ap.add_argument("--prompt-len", type=int, default=96)
    ap.add_argument("--new-tokens", type=int, default=64)
    ap.add_argument("--max-burst", type=int, default=32)
    ap.add_argument("--kv-int8", action="store_true")
    ap.add_argument("--weights-int8", action="store_true")
    ap.add_argument("--admit-wave", type=int, default=None,
                    help="cap admission waves: early waves' first "
                         "tokens stream (HTTP) / stamp TTFT (engine) "
                         "while later waves prefill")
    ap.add_argument("--engine-only", action="store_true",
                    help="bench the engine directly (no HTTP/LB; "
                         "engine-internal TTFT)")
    args = ap.parse_args()
    if args.engine_only:
        r = run(config=args.config, requests=args.requests,
                slots=args.slots, prompt_len=args.prompt_len,
                new_tokens=args.new_tokens, max_burst=args.max_burst,
                kv_int8=args.kv_int8, weights_int8=args.weights_int8,
                admit_wave=args.admit_wave)
    else:
        r = run_http(config=args.config, requests=args.requests,
                     slots=args.slots, prompt_len=args.prompt_len,
                     new_tokens=args.new_tokens,
                     max_burst=args.max_burst, kv_int8=args.kv_int8,
                     weights_int8=args.weights_int8,
                     admit_wave=args.admit_wave)
    out = {
        "metric": "serve_median_ttft",
        "value": r["median_ttft_ms"],
        "unit": "ms",
        "vs_baseline": r["vs_baseline_ttft"],
        "output_tok_per_s": r["out_tok_s"],
        "req_per_s": r["req_per_s"],
        "config": r["config"],
        "kv_int8": r["kv_int8"],
        "weights_int8": r["weights_int8"],
    }
    if "p99_ttft_ms" in r:
        out["p99_ttft_ms"] = r["p99_ttft_ms"]
        out["transport"] = r["transport"]
    print(json.dumps(out))


if __name__ == "__main__":
    main()
