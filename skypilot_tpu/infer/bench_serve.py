"""Serving benchmark: TTFT + token throughput on the local accelerator.

Prints ONE JSON line, same contract as the repo-root bench.py:
  {"metric": "serve_median_ttft", "value": ..., "unit": "ms",
   "vs_baseline": ...}

vs_baseline compares against the reference's JetStream anchor on TPU
(reference: examples/tpu/v6e/README.md — median TTFT 1829.33 ms,
2147.98 output tok/s for Llama-2-7B on v6e; BASELINE.md). Ratio > 1
means faster than baseline (baseline_ttft / our_ttft).

Usage: python -m skypilot_tpu.infer.bench_serve [--config llama3-400m]
       [--requests 16] [--slots 8] [--prompt-len 96] [--new-tokens 64]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def log(*a):
    print(*a, file=sys.stderr, flush=True)


REF_TTFT_MS = 1829.33
REF_TOK_S = 2147.98
# Anchor's per-token latency (reference: examples/tpu/v6e/README.md
# §Serve — median TPOT for the same JetStream Llama-2-7B run).
REF_TPOT_MS = 18.88


def run(config=None, requests=16, slots=16, prompt_len=96,
        new_tokens=64, max_burst=32, kv_int8=False,
        weights_int8=False, admit_wave=None) -> dict:
    """Run the serving benchmark; returns the metrics dict (also usable
    by the repo-root bench.py to fold serving numbers into its single
    JSON artifact)."""
    import jax
    import numpy as np

    on_cpu = jax.default_backend() == "cpu"
    if config is None:
        config = "llama3-tiny" if on_cpu else "llama3-400m"
    cfg, e = _build_engine(config, slots, prompt_len, new_tokens,
                           kv_int8, weights_int8, max_wave=admit_wave)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, cfg.vocab_size, prompt_len).tolist()
               for _ in range(requests)]

    # Warmup: compile the full-wave admission program and the burst
    # decode programs at the measured run's own burst size.
    for p in [prompts[0]] * slots:
        e.add_request(p, max_new_tokens=new_tokens)
    e.run_to_completion(max_burst=max_burst)
    e.finished.clear()

    t0 = time.time()
    for p in prompts:
        e.add_request(p, max_new_tokens=new_tokens)
    done = e.run_to_completion(max_burst=max_burst)
    # Force a host sync so the wall clock is honest (axon relay:
    # block_until_ready does not synchronize; a host fetch does).
    float(e.cache["length"][0])
    wall = time.time() - t0

    ttfts = sorted((r.first_token_s - r.submit_s) * 1e3 for r in done)
    med_ttft = ttfts[len(ttfts) // 2]
    total_tokens = sum(len(r.tokens) for r in done)
    tok_s = total_tokens / wall
    req_s = len(done) / wall

    log(f"requests={len(done)} wall={wall:.2f}s median_ttft={med_ttft:.1f}ms "
        f"tok/s={tok_s:.1f} req/s={req_s:.2f}")
    return {
        "median_ttft_ms": round(med_ttft, 2),
        "out_tok_s": round(tok_s, 2),
        "req_per_s": round(req_s, 3),
        "vs_baseline_ttft": round(REF_TTFT_MS / max(med_ttft, 1e-9), 3),
        "config": config,
        "kv_int8": kv_int8,
        "weights_int8": weights_int8,
    }


def _build_engine(config, slots, prompt_len, new_tokens, kv_int8,
                  weights_int8, max_wave=None, buckets=None,
                  pad_waves=False, prefill_chunk=None,
                  prefix_pool=None):
    import jax

    from skypilot_tpu.infer import engine as eng
    from skypilot_tpu.models import llama
    cfg = llama.CONFIGS[config]
    log(f"serve bench: {config} on {jax.devices()[0].device_kind}")
    max_len = prompt_len + new_tokens + 8
    if buckets is None:
        buckets = (prompt_len,)
    kw = dict(n_slots=slots, max_len=max_len, prompt_buckets=buckets,
              kv_int8=kv_int8, max_wave=max_wave, pad_waves=pad_waves,
              prefill_chunk=prefill_chunk, prefix_pool=prefix_pool)
    if weights_int8:
        # Build int8 weights directly — the fp init of an 8B-class
        # config (32 GB) would never fit the chip that the int8 model
        # (8 GB) serves from.
        from skypilot_tpu.infer import kvcache
        params, qw = kvcache.random_quantized_params(cfg)
        return cfg, eng.InferenceEngine(params, cfg, qweights=qw, **kw)
    params = llama.init_params(jax.random.key(0), cfg)
    return cfg, eng.InferenceEngine(params, cfg, **kw)


def _mixed_prompts(rng, vocab, requests, lo=512, hi=1024):
    """Realistic prompt-length mix, every prompt >= ``lo`` tokens: half
    at exactly ``lo`` (short-bucket), half uniform in (3/4*hi, hi] —
    including full ``hi``-token prompts. Returns (prompts, buckets)."""
    lens = []
    for i in range(requests):
        if i % 2 == 0:
            lens.append(lo)
        else:
            lens.append(int(rng.integers(hi - hi // 4 + 1, hi + 1)))
    prompts = [rng.integers(1, vocab, n).tolist() for n in lens]
    return prompts, (lo, hi)


def _client_wave(host, port, payloads, timeout=600.0, stagger_s=0.0,
                 bodies=None):
    """Fire every payload concurrently from ONE thread (raw sockets +
    a selector). A thread-per-request client adds GIL scheduling jitter
    that rivals the TTFTs being measured on a single-core host — the
    r3 driver artifact showed 5x run-to-run TTFT variance.

    ``stagger_s`` paces arrivals: request i is sent at i*stagger_s —
    an open-ish workload instead of one instantaneous burst, so
    admission overlaps decode the way production traffic does.

    Returns [(ttft_s, n_tokens, total_s)] aligned with payloads.
    TTFT is wall time from request send to the first BODY byte (the
    response headers go out before any token and don't count).
    ``bodies``, if a list, collects each raw response body (chunked
    framing included) in payload order — the failover gate parses the
    NDJSON token lines out of it for bit-identity checks.
    """
    import re
    import selectors
    import socket

    sel = selectors.DefaultSelector()
    conns = []
    t_start = time.time()
    unsent = []
    for i, body in enumerate(payloads):
        s = socket.create_connection((host, port))
        head = (f"POST /generate HTTP/1.1\r\nHost: {host}\r\n"
                "Content-Type: application/json\r\n"
                f"Content-Length: {len(body)}\r\n\r\n").encode()
        st = {"sock": s, "t0": None, "buf": b"", "first": None,
              "hdr_end": None, "done": None}
        conns.append(st)
        unsent.append((t_start + i * stagger_s, s, head + body, st))

    def send_due():
        while unsent and time.time() >= unsent[0][0]:
            _, s, data, st = unsent.pop(0)
            s.sendall(data)            # still blocking: full send
            st["t0"] = time.time()
            s.setblocking(False)
            sel.register(s, selectors.EVENT_READ, st)

    send_due()
    deadline = time.time() + timeout
    live = len(conns)
    while live and time.time() < deadline:
        wait = 1.0
        if unsent:
            wait = max(min(wait, unsent[0][0] - time.time()), 0.0)
        events = sel.select(timeout=wait)
        send_due()
        for key, _ in events:
            st = key.data
            try:
                piece = st["sock"].recv(1 << 16)
            except BlockingIOError:
                continue
            now = time.time()
            if not piece:   # server closed early — treat as done
                sel.unregister(st["sock"])
                st["done"] = st["done"] or now
                live -= 1
                continue
            st["buf"] += piece
            if st["hdr_end"] is None:
                pos = st["buf"].find(b"\r\n\r\n")
                if pos >= 0:
                    st["hdr_end"] = pos + 4
                    hdrs = st["buf"][:pos].lower()
                    # Error paths (400/500, LB 503) respond with
                    # Content-Length over the same keep-alive socket —
                    # no chunked terminator, no close; completion must
                    # come from the framed length.
                    m = re.search(rb"content-length:\s*(\d+)", hdrs)
                    if m:
                        st["clen"] = int(m.group(1))
            if (st["first"] is None and st["hdr_end"] is not None
                    and len(st["buf"]) > st["hdr_end"]):
                st["first"] = now
            done = False
            if st["hdr_end"] is not None and st.get("clen") is not None:
                done = (len(st["buf"]) - st["hdr_end"] >= st["clen"])
            # Chunked body ends with the zero-length chunk.
            elif st["buf"].endswith(b"0\r\n\r\n"):
                done = True
            if done:
                sel.unregister(st["sock"])
                st["done"] = now
                live -= 1
    sel.close()
    out = []
    for st in conns:
        st["sock"].close()
        status = st["buf"].split(b"\r\n", 1)[0]
        if st["done"] is None or st["first"] is None:
            raise AssertionError(
                f"request did not complete (status line {status!r})")
        if b" 200 " not in status + b" ":
            raise AssertionError(f"non-200 response: {status!r} "
                                 f"{st['buf'][:300]!r}")
        body = st["buf"][st["hdr_end"]:]
        if re.search(rb'"error"\s*:', body):
            # A mid-stream engine failure ends the 200 stream with an
            # {"error": ...} line — counting it as a 0-token success
            # would silently corrupt the bench numbers.
            raise AssertionError(f"engine error mid-stream: "
                                 f"{body[:300]!r}")
        m = re.search(rb'"n_tokens":\s*(\d+)', st["buf"])
        n_tok = int(m.group(1)) if m else 0
        out.append((st["first"] - st["t0"], n_tok,
                    st["done"] - st["t0"]))
        if bodies is not None:
            bodies.append(body)
    return out


def run_http(config=None, requests=16, slots=16, prompt_len=None,
             new_tokens=64, max_burst=8, kv_int8=False,
             weights_int8=False, admit_wave=None, open_burst=4,
             repeats=1, prompt_lo=512, prompt_hi=1024,
             stagger_s=0.0, coalesce_s=0.012, full_load=False) -> dict:
    """End-to-end streaming bench: requests go over HTTP through a REAL
    load balancer to the model server, and TTFT is the wall time to the
    FIRST STREAMED BYTE of each response — the JetStream comparison
    (reference: examples/tpu/v6e/README.md measures streaming TTFT),
    not an engine-internal timestamp.

    ``prompt_len=None`` uses a realistic length mix in
    [prompt_lo, prompt_hi] (every prompt >= prompt_lo; see
    :func:`_mixed_prompts`); an int pins every prompt to that length.
    ``repeats`` runs the timed wave N times back-to-back on the warm
    server and reports the median-of-runs AND the worst run — a
    serving number is only real if the worst run clears the bar too.
    """
    import json as _json
    import os
    import socket
    import tempfile
    import threading

    import jax
    import numpy as np

    on_cpu = jax.default_backend() == "cpu"
    if config is None:
        config = "llama3-tiny" if on_cpu else "llama3-400m"
    if admit_wave is None:
        # pad_waves below needs a wave cap: without one the engine
        # silently falls back to power-of-two padding and a novel
        # (bucket, rows) pair can hit a mid-measurement XLA compile.
        admit_wave = 4

    home = tempfile.mkdtemp(prefix="skytpu-bench-serve-")
    os.environ["SKYPILOT_TPU_HOME"] = home

    from skypilot_tpu.infer import server as srv
    from skypilot_tpu.models import llama
    from skypilot_tpu.serve import load_balancer, serve_state
    from skypilot_tpu.serve.serve_state import ReplicaStatus

    cfg = llama.CONFIGS[config]
    rng = np.random.default_rng(0)
    if prompt_len is None:
        prompts, (lo, hi) = _mixed_prompts(rng, cfg.vocab_size,
                                           requests, prompt_lo,
                                           prompt_hi)
        if on_cpu:   # keep CPU CI fast; shape behavior is identical
            prompts = [p[:max(len(p) // 8, 4)] for p in prompts]
            lo, hi = lo // 8, hi // 8
        buckets = (lo, hi)
        max_prompt = hi
    else:
        prompts = [rng.integers(1, cfg.vocab_size, prompt_len).tolist()
                   for _ in range(requests)]
        buckets = (prompt_len,)
        max_prompt = prompt_len
    mean_len = sum(len(p) for p in prompts) / len(prompts)

    _, engine = _build_engine(config, slots, max_prompt, new_tokens,
                              kv_int8, weights_int8,
                              max_wave=admit_wave, buckets=buckets,
                              pad_waves=True)

    def free_port():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    model_port, lb_port = free_port(), free_port()
    model, httpd = srv.serve(engine, host="127.0.0.1", port=model_port,
                             max_burst=max_burst,
                             open_burst=open_burst,
                             coalesce_s=coalesce_s)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    assert model._ready.wait(timeout=600), "model warmup timed out"

    serve_state.add_service("bench", {}, {}, lb_port)
    serve_state.upsert_replica("bench", 1, "bench-replica",
                               ReplicaStatus.READY,
                               f"http://127.0.0.1:{model_port}")
    lb = load_balancer._ThreadingServer(
        ("127.0.0.1", lb_port),
        load_balancer.make_handler("bench",
                                   load_balancer.LeastLoadPolicy()))
    threading.Thread(target=lb.serve_forever, daemon=True).start()

    payloads = [_json.dumps({"tokens": p, "max_new_tokens": new_tokens,
                             "stream": True}).encode()
                for p in prompts]

    # Warmup: the same concurrent wave as the measurement — compiles
    # every admission program (pad_waves: one per bucket) and both
    # decode burst sizes (open_burst while slots drain in, max_burst
    # once full) outside the timed window.
    _client_wave("127.0.0.1", lb_port, payloads)

    def _tpots(res):
        # Per-request TPOT: stream time after the first byte, averaged
        # over the remaining tokens (chunk-granular at the burst size,
        # honest over ~190 intervals). The anchor reports the same
        # decode-side per-token latency (REF_TPOT_MS).
        return [(tot - ttft) / max(n - 1, 1) * 1e3
                for (ttft, n, tot) in res if n > 1]

    runs = []
    all_ttfts = []
    all_tpots = []
    for rep in range(max(repeats, 1)):
        t0 = time.time()
        res = _client_wave("127.0.0.1", lb_port, payloads,
                           stagger_s=stagger_s)
        wall = time.time() - t0
        ttfts = sorted(r[0] * 1e3 for r in res)
        all_ttfts.extend(ttfts)
        all_tpots.extend(_tpots(res))
        total_tokens = sum(r[1] for r in res)
        runs.append({
            "median_ttft_ms": round(ttfts[len(ttfts) // 2], 2),
            "max_ttft_ms": round(ttfts[-1], 2),
            "out_tok_s": round(total_tokens / wall, 2),
            "wall_s": round(wall, 3),
        })
        log(f"run {rep + 1}/{repeats}: median_ttft="
            f"{runs[-1]['median_ttft_ms']:.1f}ms "
            f"max={runs[-1]['max_ttft_ms']:.1f}ms "
            f"tok/s={runs[-1]['out_tok_s']:.1f}")

    # Second phase on the SAME warm server: every slot filled
    # (throughput-optimal load, vs the headroom load above that the
    # TTFT numbers use). Engine-only decode at 32 full slots measures
    # ~1.4k tok/s on v5e (staged burst); this reports what survives
    # HTTP + LB (~1.24k).
    full = None
    if full_load and requests >= slots:
        log(f"full-load phase skipped: requests ({requests}) already "
            f">= slots ({slots}) — the headline phase IS full load")
    if full_load and requests < slots:
        if prompt_len is None:
            fl_prompts, _ = _mixed_prompts(rng, cfg.vocab_size, slots,
                                           prompt_lo, prompt_hi)
            if on_cpu:
                fl_prompts = [p[:max(len(p) // 8, 4)]
                              for p in fl_prompts]
        else:
            # Pinned-length benches must stay inside the engine's
            # buckets — the mixed draw would exceed max_prompt.
            fl_prompts = [rng.integers(1, cfg.vocab_size,
                                       prompt_len).tolist()
                          for _ in range(slots)]
        fl_payloads = [_json.dumps({"tokens": p,
                                    "max_new_tokens": new_tokens,
                                    "stream": True}).encode()
                       for p in fl_prompts]
        _client_wave("127.0.0.1", lb_port, fl_payloads)   # warm shapes
        fl_runs = []
        fl_tpots = []
        for rep in range(3):
            t0 = time.time()
            res = _client_wave("127.0.0.1", lb_port, fl_payloads)
            wall = time.time() - t0
            ttfts = sorted(r[0] * 1e3 for r in res)
            fl_tpots.extend(_tpots(res))
            fl_runs.append({
                "median_ttft_ms": round(ttfts[len(ttfts) // 2], 2),
                "out_tok_s": round(sum(r[1] for r in res) / wall, 2),
                "wall_s": round(wall, 3),
            })
            log(f"full-load run {rep + 1}/3: "
                f"median_ttft={fl_runs[-1]['median_ttft_ms']:.1f}ms "
                f"tok/s={fl_runs[-1]['out_tok_s']:.1f}")
        # Median across runs — same reporting discipline as the
        # headline phase (a lucky run must not become the record).
        toks_sorted = sorted(r["out_tok_s"] for r in fl_runs)
        ttft_sorted = sorted(r["median_ttft_ms"] for r in fl_runs)
        fl_tpots.sort()
        full = {
            "requests": slots,
            "out_tok_s": toks_sorted[len(toks_sorted) // 2],
            "median_ttft_ms": ttft_sorted[len(ttft_sorted) // 2],
            "tpot_ms": (round(fl_tpots[len(fl_tpots) // 2], 2)
                        if fl_tpots else None),
            # Full-load TTFT clears the anchor by only ~15% historically
            # (r4: 1557 ms vs 1829) — a separate guard so a small
            # regression here is loud too.
            "regressed": bool(ttft_sorted[len(ttft_sorted) // 2]
                              >= REF_TTFT_MS),
            "runs": fl_runs,
        }

    lb.shutdown()
    httpd.shutdown()
    model.shutdown()

    medians = sorted(r["median_ttft_ms"] for r in runs)
    med_ttft = medians[len(medians) // 2]
    worst_ttft = medians[-1]
    all_ttfts.sort()
    p99_ttft = all_ttfts[min(len(all_ttfts) - 1,
                             int(len(all_ttfts) * 0.99))]
    toks = sorted(r["out_tok_s"] for r in runs)
    tok_s = toks[len(toks) // 2]
    wall_total = sum(r["wall_s"] for r in runs)
    req_s = requests * len(runs) / wall_total
    all_tpots.sort()
    tpot = all_tpots[len(all_tpots) // 2] if all_tpots else None
    log(f"http/lb streaming x{len(runs)}: median-of-runs "
        f"{med_ttft:.1f}ms worst-run {worst_ttft:.1f}ms "
        f"p99(all) {p99_ttft:.1f}ms tok/s {tok_s:.1f} "
        f"tpot {tpot if tpot is None else round(tpot, 2)}ms")
    return {
        "median_ttft_ms": round(med_ttft, 2),
        "worst_run_median_ttft_ms": round(worst_ttft, 2),
        "p99_ttft_ms": round(p99_ttft, 2),
        "out_tok_s": round(tok_s, 2),
        "req_per_s": round(req_s, 3),
        "tpot_ms": round(tpot, 2) if tpot is not None else None,
        "vs_baseline_tpot": (round(REF_TPOT_MS / tpot, 3)
                             if tpot else None),
        "vs_baseline_ttft": round(REF_TTFT_MS / max(med_ttft, 1e-9), 3),
        "worst_run_vs_baseline_ttft": round(
            REF_TTFT_MS / max(worst_ttft, 1e-9), 3),
        # r5 gate: serving changes must keep the WORST run at least
        # 1.2x faster than the anchor, not just the median.
        "worst_run_below_1p2x": bool(
            worst_ttft * 1.2 > REF_TTFT_MS),
        # The headline guard keys on the MEDIAN of runs (the anchor
        # comparison the r3 verdict set); the worst run is reported and
        # separately flagged — on a shared/loaded host it can absorb
        # scheduler noise a median shrugs off (measured: a concurrent
        # test suite on the same core moved worst runs ~30%).
        "regressed": bool(med_ttft >= REF_TTFT_MS),
        "worst_run_regressed": bool(worst_ttft >= REF_TTFT_MS),
        "runs": runs,
        "prompt_mean_len": round(mean_len, 1),
        "prompt_max_len": max(len(p) for p in prompts),
        "new_tokens": new_tokens,
        "stagger_s": stagger_s,
        "config": config,
        "kv_int8": kv_int8,
        "weights_int8": weights_int8,
        "transport": "http_lb_streaming",
        **({"full_load": full} if full else {}),
    }


def _median(xs):
    xs = sorted(xs)
    return xs[len(xs) // 2] if xs else None


def _p99(xs):
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(len(xs) * 0.99))] if xs else None


def _interference(engine, fillers, longs, burst, idle_bursts=8):
    """Decode-interference report: per-token decode cadence while long
    prompts are being admitted vs idle decode.

    ``fillers`` (short prompts, long generations) occupy slots and keep
    decoding; once steady, ``longs`` (long prompts) are injected and
    the scheduler runs the server's alternation (one prefill chunk —
    or, chunk-disabled, the whole monolith wave — between decode
    bursts). TPOT here is the REQUEST-experienced cadence: the wall
    interval between consecutive burst completions divided by the burst
    size, so time decode spent stalled behind prefill is charged to it.
    Returns stats in ms plus the admission-vs-idle p99 ratio.
    """
    import time as _time

    for p in fillers:
        engine.add_request(p, max_new_tokens=engine.max_len)
    engine.admit()
    engine.decode_burst(burst)            # warm the cadence
    idle = []
    for _ in range(idle_bursts):
        t0 = _time.time()
        engine.decode_burst(burst)
        idle.append(_time.time() - t0)
    for p in longs:
        engine.add_request(p, max_new_tokens=4)
    intervals, stalls = [], []
    t_last = _time.time()
    while engine.waiting or engine.chunking:
        engine.admit()
        if engine.chunking:
            t0 = _time.time()
            engine.prefill_chunk_step()
            stalls.append(_time.time() - t0)
        engine.decode_burst(burst)
        now = _time.time()
        intervals.append(now - t_last)
        t_last = now
    # Drain and reset so the caller gets a quiet engine back.
    engine.reset()
    idle_tpot = _median(idle) / burst * 1e3
    adm_p99 = (_p99(intervals) / burst * 1e3 if intervals
               else idle_tpot)
    return {
        "idle_tpot_ms": round(idle_tpot, 3),
        "admission_tpot_p99_ms": round(adm_p99, 3),
        "tpot_admission_ratio": round(adm_p99 / max(idle_tpot, 1e-9),
                                      3),
        "decode_stall_p99_ms": (round(_p99(stalls) * 1e3, 3)
                                if stalls else 0.0),
        "admission_bursts": len(intervals),
    }


def run_prefix_share(config=None, requests=12, slots=16,
                     system_len=None, tail_len=None, new_tokens=None,
                     max_burst=16, prefill_chunk=None, prefix_pool=8,
                     kv_int8=False, weights_int8=False,
                     smoke=False) -> dict:
    """Prefix-share workload: every prompt = one shared system prompt +
    a unique tail (the dominant production shape). Measures cold
    (empty prefix cache) vs warm (system prompt resident) TTFT on the
    same engine, asserts greedy token parity between the two passes,
    and appends the decode-interference report (chunked scheduler vs
    the per-bucket monolith). ``smoke=True`` shrinks everything to a
    CPU-CI-sized regression guard (run_smoke)."""
    import jax
    import numpy as np

    on_cpu = jax.default_backend() == "cpu"
    if config is None:
        config = "llama3-tiny" if on_cpu else "llama3-400m"
    small = smoke or on_cpu
    if system_len is None:
        system_len = 24 if small else 768
    if tail_len is None:
        tail_len = 6 if small else 48
    if new_tokens is None:
        new_tokens = 6 if small else 48
    if prefill_chunk is None:
        prefill_chunk = 8 if small else 256
    if small:
        requests = min(requests, 4)
        slots = min(slots, 4)
        max_burst = min(max_burst, 4)
        prefix_pool = min(prefix_pool, 4)
    requests = min(requests, slots)   # one admission pass => all cold
    bucket = system_len + tail_len
    short_bucket = min(32, bucket)
    # Row headroom so the interference phase's filler requests never
    # push the burst cap below the measured burst size — a shrunken k
    # would compile a fresh decode program mid-measurement.
    iburst = min(max_burst, 4 if small else 8)
    headroom = 48 if small else 0
    cfg, e = _build_engine(config, slots, bucket,
                           new_tokens + headroom, kv_int8,
                           weights_int8, buckets=(short_bucket, bucket),
                           prefill_chunk=prefill_chunk,
                           prefix_pool=prefix_pool)
    rng = np.random.default_rng(0)
    system = rng.integers(1, cfg.vocab_size, system_len).tolist()

    def make_prompts(salt):
        return [system + rng.integers(1, cfg.vocab_size,
                                      tail_len).tolist()
                for _ in range(requests)]

    prompts = make_prompts(0)

    # Warmup: compile claim/chunk/pool-store/decode programs (first
    # request, cold) AND the pool-load path (second, identical request
    # hits the prefix just stored) — the warm timed pass must not pay
    # a first-sight XLA compile.
    e.add_request(prompts[0], max_new_tokens=2)
    e.run_to_completion(max_burst=max_burst)
    e.add_request(prompts[0], max_new_tokens=2)
    e.run_to_completion(max_burst=max_burst)
    e.finished.clear()
    e.clear_prefix_cache()

    def timed_pass(ps):
        for p in ps:
            e.add_request(p, max_new_tokens=new_tokens)
        done = e.run_to_completion(max_burst=max_burst)
        float(e.cache["length"][0])     # honest host sync
        ttfts = [(r.first_token_s - r.submit_s) * 1e3 for r in done]
        out = {tuple(r.prompt): list(r.tokens) for r in done}
        hits = sum(1 for r in done if r.cached_len > 0)
        chunks = sum(r.n_chunks for r in done)
        e.finished.clear()
        return _median(ttfts), out, hits, chunks

    cold_ttft, cold_out, cold_hits, cold_chunks = timed_pass(prompts)
    warm_ttft, warm_out, warm_hits, warm_chunks = timed_pass(prompts)
    parity_ok = all(warm_out[k] == cold_out[k] for k in cold_out)

    log(f"prefix-share: cold={cold_ttft:.1f}ms warm={warm_ttft:.1f}ms "
        f"hits {warm_hits}/{requests} parity={parity_ok}")

    n_f = max(slots // 2, 1)
    fillers = [rng.integers(1, cfg.vocab_size, 4).tolist()
               for _ in range(n_f)]
    longs = [rng.integers(1, cfg.vocab_size, bucket).tolist()
             for _ in range(min(slots - n_f, n_f, 4))]
    interference = _interference(e, fillers, longs, burst=iburst,
                                 idle_bursts=4 if small else 8)
    # Free the chunked engine BEFORE building the monolith comparison:
    # two live 8B-class weight sets would not fit the 16 GB chip the
    # engine is sized for (the OOM would silently eat this phase's
    # numbers via bench.py's guard).
    del e, timed_pass          # timed_pass's closure also pins the engine
    import gc
    gc.collect()
    # The same workload against the per-bucket monolith: the
    # interference chunked prefill removes.
    _, e_mono = _build_engine(config, slots, bucket,
                              new_tokens + headroom, kv_int8,
                              weights_int8,
                              buckets=(short_bucket, bucket),
                              prefill_chunk=0, prefix_pool=0)
    # Warm the exact wave shapes the measured window will admit (the
    # monolith's long-bucket wave would otherwise compile mid-window).
    for p in longs:
        e_mono.add_request(p, max_new_tokens=2)
    e_mono.run_to_completion(max_burst=iburst)
    e_mono.generate([fillers[0]], max_new_tokens=2)
    e_mono.finished.clear()
    mono = _interference(e_mono, fillers, longs, burst=iburst,
                         idle_bursts=4 if small else 8)
    interference["monolith_tpot_p99_ms"] = mono["admission_tpot_p99_ms"]
    interference["monolith_ratio"] = mono["tpot_admission_ratio"]
    log(f"interference: idle {interference['idle_tpot_ms']}ms/tok, "
        f"admission p99 {interference['admission_tpot_p99_ms']} "
        f"(x{interference['tpot_admission_ratio']}), monolith "
        f"x{interference['monolith_ratio']}")

    return {
        "cold_ttft_ms": round(cold_ttft, 2),
        "warm_ttft_ms": round(warm_ttft, 2),
        "warm_speedup": round(cold_ttft / max(warm_ttft, 1e-9), 3),
        # Acceptance bar: warm-prefix median TTFT >= 30% below cold.
        "warm_below_70pct_of_cold": bool(warm_ttft <= 0.7 * cold_ttft),
        "hit_rate": round(warm_hits / max(requests, 1), 3),
        "cold_hits": cold_hits,
        "parity_ok": bool(parity_ok),
        "prefix_hits": warm_hits,
        # Structural (timing-independent) evidence of reuse: chunk
        # programs run per pass — the warm pass prefills suffixes only.
        "cold_chunks": cold_chunks,
        "warm_chunks": warm_chunks,
        "decode_stall_p99_ms": interference["decode_stall_p99_ms"],
        "interference": interference,
        "requests": requests,
        "system_len": system_len,
        "tail_len": tail_len,
        "prefill_chunk": prefill_chunk,
        "prefix_pool": prefix_pool,
        "config": config,
        "kv_int8": kv_int8,
        "weights_int8": weights_int8,
    }


def run_smoke() -> dict:
    """CI-sized prefix-share + interference pass (tier-1 regression
    guard for the chunk scheduler; see tests/test_prefix_cache.py)."""
    return run_prefix_share(smoke=True)


class _OracleDrafter:
    """Replays a known-correct continuation as the draft — the
    acceptance CEILING for the verify path: every burst accepts the
    full draft, so the measured speedup is what the fixed-K verify
    program delivers when drafts are right, independent of how
    n-gram-predictable the (random-weight) bench model's output is."""

    def __init__(self, out):
        self.out = list(out)
        self._gen = 0

    def catch_up(self, prompt, generated):
        self._gen = len(generated)

    def draft(self, k):
        return self.out[self._gen:self._gen + k]


def run_spec(config=None, spec_k=4, requests=None, prompt_len=16,
             new_tokens=None, max_burst=8, kv_int8=False,
             weights_int8=False, smoke=False,
             draft_layers=None) -> dict:
    """Speculative-decoding bench, two workloads on two engines.

    **Phase A — non-repetitive (the headline, the honest one).**
    Random prompts at the config's FULL vocabulary: the random-weight
    target's greedy trajectories don't cycle, so prompt-lookup has
    nothing to look up — n-gram speculation is a wash here by design,
    and any win must come from the MODEL drafter. The draft model is
    the truncated-layer draft of a self-distilled target
    (``draft.self_distilled_pair``: the target's upper residual blocks
    carry zeroed output projections — the distillation endpoint — so
    the half-cost draft agrees with the target and acceptance is
    near-1.0 without a training run; the zeroed layers still pay their
    full matmul cost, so the baseline TPOT is honest). Five decode
    passes on ONE engine (same weights, same compiled programs — only
    routing flips): spec-off, model-draft pipelined (the shipped
    default), model-draft synchronous (isolates the async pipeline's
    contribution), n-gram (the honest wash column), plus the
    structural overlap check (flight records must show a draft
    dispatch INSIDE a verify's dispatch->fetch window).

    **Phase B — repetition-heavy (the secondary n-gram column).**
    PR 8's original workload verbatim — vocab 16 so the random
    model's trajectories cycle within a few dozen tokens, the regime
    prompt-lookup pays in — with the n-gram and oracle-draft-ceiling
    passes unchanged (the old keys keep their meanings release over
    release).

    TTFT is out of scope by construction: speculation only replaces
    decode bursts — admission, chunking and prefill are untouched (the
    --prefix-share and full-load benches guard TTFT).

    ``smoke=True``: CI-sized (tier-1 wiring in tests/test_spec_decode
    .py + tests/test_draft_model.py) — asserts parity, acceptance and
    overlap STRUCTURE, never wall-clock (a compute-bound CPU cannot
    show a memory-bandwidth win; the speedup gates bind on TPU).
    """
    import dataclasses
    import time as _time

    import jax
    import numpy as np

    from skypilot_tpu.infer import draft as draft_lib
    from skypilot_tpu.infer import engine as eng
    from skypilot_tpu.models import llama
    from skypilot_tpu.observability import flight as flight_lib

    on_cpu = jax.default_backend() == "cpu"
    if config is None:
        config = "llama3-tiny" if on_cpu else "llama3-400m"
    small = smoke or on_cpu
    if requests is None:
        requests = 4 if small else 8
    if new_tokens is None:
        new_tokens = 96 if small else 256
    spec_k = max(int(spec_k), 1)
    slots = requests
    max_len = 128 if small else 512
    assert prompt_len + new_tokens + spec_k + 1 <= max_len
    # Separate streams: phase B keeps PR 8's exact prompts (seed 0) so
    # its columns stay comparable release over release.
    rng_a = np.random.default_rng(1)
    rng_b = np.random.default_rng(0)

    def decode_pass(e, prompts, spec_on, factory=None,
                    ngram_factory=None, draft_engine=None,
                    pipeline=False):
        """One admit-then-decode pass; TPOT measured over the decode
        loop only (admission/prefill excluded — spec does not touch
        them). Returns (outputs, tpot_s, drafted, accepted, bursts)."""
        e.spec_k = spec_k if spec_on else 0
        e._spec_drafter_factory = factory or ngram_factory
        e.draft_engine = draft_engine
        e.spec_pipeline = bool(pipeline) and draft_engine is not None
        d0, a0 = e._spec_drafted_total, e._spec_accepted_total
        ids = [e.add_request(p, max_new_tokens=new_tokens)
               for p in prompts]
        e.admit()
        t0 = _time.time()
        bursts = 0
        while e.slot_req:
            e.decode_burst(max_burst)
            bursts += 1
        float(e.cache["length"][0])     # honest host sync
        wall = _time.time() - t0
        by_rid = {r.rid: list(r.tokens) for r in e.finished}
        outs = [by_rid[i] for i in ids]
        e.finished.clear()
        # First tokens came from admission; TPOT charges decode only.
        dtoks = sum(len(o) for o in outs) - len(outs)
        return (outs, wall / max(dtoks, 1),
                e._spec_drafted_total - d0,
                e._spec_accepted_total - a0, bursts)

    # -- Phase A: non-repetitive workload, model drafter ------------------
    cfg_a = llama.CONFIGS[config]
    if draft_layers is None:
        draft_layers = max(cfg_a.n_layers // 2, 1)
    params_a = llama.init_params(jax.random.key(0), cfg_a)
    target, dparams, dcfg = draft_lib.self_distilled_pair(
        params_a, cfg_a, draft_layers)
    del params_a
    qw_t = qw_d = None
    if weights_int8:
        # w8a8 phase A (the production serving config the gate must
        # describe): quantize the distilled target's blocks + head
        # ONCE; the draft's quantized tree is the literal layer slice
        # of the target's — the zeroed upper blocks quantize to exact
        # zeros, so the agreement regime survives quantization (both
        # models read the SAME int8 weights for the shared layers).
        from skypilot_tpu.infer import kvcache
        qw_t = jax.jit(lambda p: {
            "blocks": kvcache.quantize_block_weights(p),
            "head": kvcache.quantize_head(p, cfg_a)})(target)
        qw_d = {"blocks": {
                    name: {k: v[:draft_layers]
                           for k, v in qw_t["blocks"][name].items()}
                    for name in qw_t["blocks"]},
                "head": qw_t["head"]}
        dparams = kvcache.slim_params(dparams)
    log(f"spec bench A: {config} (vocab {cfg_a.vocab_size}, "
        f"non-repetitive) K={spec_k} draft={draft_layers}/"
        f"{cfg_a.n_layers} layers requests={requests} "
        f"new_tokens={new_tokens} w8a8={bool(weights_int8)}")
    fl = flight_lib.FlightRecorder()
    e_a = eng.InferenceEngine(
        target, cfg_a, n_slots=slots, max_len=max_len,
        prompt_buckets=(prompt_len,), kv_int8=kv_int8,
        qweights=qw_t,
        prefill_chunk=0, prefix_pool=0, max_wave=slots,
        pad_waves=True, spec_k=spec_k, flight_recorder=fl)
    ngram_factory_a = e_a._spec_drafter_factory
    de = draft_lib.DraftEngine(dparams, dcfg, n_slots=slots,
                               max_len=max_len, kv_int8=kv_int8,
                               qweights=qw_d)
    prompts_a = [rng_a.integers(1, cfg_a.vocab_size,
                                prompt_len).tolist()
                 for _ in range(requests)]

    def pass_a(spec_on, draft_engine=None, pipeline=False):
        return decode_pass(e_a, prompts_a, spec_on,
                           ngram_factory=ngram_factory_a,
                           draft_engine=draft_engine,
                           pipeline=pipeline)

    # Warmups: the off pass covers the plain bursts; the pipelined
    # model pass covers verify + the drafter's rollout (k AND k+1)
    # and steady-state sync programs; the SYNC model pass additionally
    # reaches the per-round bonus-row ingest at every span rung it
    # crosses (pipelined steady state never ingests) — without it the
    # sync column pays mid-window compiles and the pipeline ratio
    # overstates. The n-gram pass dispatches a subset of the above.
    pass_a(False)
    pass_a(True, draft_engine=de, pipeline=True)
    de.reset()
    pass_a(True, draft_engine=de, pipeline=False)
    de.reset()

    out_off_a, tpot_off_a, _, _, bursts_off_a = pass_a(False)
    seq0 = fl.seq()
    out_m, tpot_m, dr_m, ac_m, bursts_m = pass_a(
        True, draft_engine=de, pipeline=True)
    recs = fl.since(seq0)
    reuse_hits, rollouts = de.reuse_hits, de.rollouts
    de.reset()
    out_ms, tpot_ms, dr_ms, ac_ms, bursts_ms = pass_a(
        True, draft_engine=de, pipeline=False)
    de.reset()
    out_ng, tpot_ng, dr_ng, ac_ng, bursts_ng = pass_a(True)

    # Structural overlap evidence: a "draft" record whose dispatch
    # landed INSIDE a verify record's dispatch->fetch window — the
    # pipeline's whole point, timing-free.
    verify_recs = [r for r in recs if r.get("burst") == "verify"]
    draft_recs = [r for r in recs if r.get("burst") == "draft"]
    overlapped = 0
    for d in draft_recs:
        for v in verify_recs:
            if (v["ts_s"] <= d["ts_s"]
                    <= v["ts_s"] + float(v.get("dur_s", 0.0))):
                overlapped += 1
                break
    overlap_ok = bool(draft_recs) and overlapped == len(draft_recs)

    model_parity = out_m == out_off_a
    sync_parity = out_ms == out_off_a
    ngram_parity = out_ng == out_off_a
    rate_m = ac_m / max(dr_m, 1)
    rate_ng = ac_ng / max(dr_ng, 1)
    log(f"spec A: off {tpot_off_a * 1e3:.2f}ms/tok "
        f"model(pipe) {tpot_m * 1e3:.2f}ms (accept {rate_m:.2f}, "
        f"{overlapped}/{len(draft_recs)} draft dispatches "
        f"overlapped, {reuse_hits} rounds predraft-served) "
        f"model(sync) {tpot_ms * 1e3:.2f}ms "
        f"ngram {tpot_ng * 1e3:.2f}ms (accept {rate_ng:.2f}) "
        f"parity={model_parity}/{sync_parity}/{ngram_parity}")

    # -- Phase B: repetition-heavy workload, n-gram + oracle (PR 8) -------
    # Small vocab => the random model's greedy decode cycles quickly
    # (the repetition-heavy regime); block weights — the decode cost —
    # keep the config's full size.
    cfg_b = dataclasses.replace(llama.CONFIGS[config], vocab_size=16)
    log(f"spec bench B: {config} (vocab 16, repetition-heavy) "
        f"K={spec_k}")
    kw = dict(n_slots=slots, max_len=max_len,
              prompt_buckets=(prompt_len,), kv_int8=kv_int8,
              prefill_chunk=0, prefix_pool=0, max_wave=slots,
              pad_waves=True, spec_k=spec_k)
    if weights_int8:
        from skypilot_tpu.infer import kvcache
        params_b, qw = kvcache.random_quantized_params(cfg_b)
        e_b = eng.InferenceEngine(params_b, cfg_b, qweights=qw, **kw)
    else:
        params_b = llama.init_params(jax.random.key(0), cfg_b)
        e_b = eng.InferenceEngine(params_b, cfg_b, **kw)
    ngram_factory_b = e_b._spec_drafter_factory
    prompts_b = [rng_b.integers(1, cfg_b.vocab_size,
                                prompt_len).tolist()
                 for _ in range(requests)]

    def pass_b(spec_on, factory=None):
        return decode_pass(e_b, prompts_b, spec_on, factory=factory,
                           ngram_factory=ngram_factory_b)

    # Warmup: compile the admission program, the plain burst at the
    # measured size AND the verify program outside any timed window.
    pass_b(False)
    pass_b(True)

    out_off, tpot_off, _, _, bursts_off = pass_b(False)
    out_on, tpot_on, drafted, accepted, bursts_on = pass_b(True)
    oracle = {tuple(p): o for p, o in zip(prompts_b, out_off)}
    out_or, tpot_or, dr_or, ac_or, bursts_or = pass_b(
        True,
        factory=lambda req: _OracleDrafter(oracle[tuple(req.prompt)]))

    parity_ok = out_on == out_off
    oracle_parity_ok = out_or == out_off
    rate = accepted / max(drafted, 1)
    oracle_rate = ac_or / max(dr_or, 1)
    dtoks = sum(len(o) for o in out_off) - len(out_off)
    log(f"spec B: off {tpot_off * 1e3:.2f}ms/tok ({bursts_off} bursts) "
        f"ngram {tpot_on * 1e3:.2f}ms ({bursts_on} bursts, "
        f"accept {rate:.2f}) oracle {tpot_or * 1e3:.2f}ms "
        f"({bursts_or} bursts, accept {oracle_rate:.2f}) "
        f"parity={parity_ok}/{oracle_parity_ok}")
    return {
        # -- Phase A (non-repetitive, model drafter): the headline.
        "backend": jax.default_backend(),
        "model_tpot_off_ms": round(tpot_off_a * 1e3, 3),
        "tpot_model_ms": round(tpot_m * 1e3, 3),
        "tpot_model_sync_ms": round(tpot_ms * 1e3, 3),
        "tpot_ngram_nonrep_ms": round(tpot_ng * 1e3, 3),
        # Wall-clock ratios: bench.py binds the >=1.5x gate on TPU
        # runs only (the kernel-bench precedent — a compute-bound CPU
        # cannot show a memory-bandwidth win); parity and overlap
        # structure gate everywhere.
        "model_speedup": round(tpot_off_a / max(tpot_m, 1e-9), 3),
        "model_sync_speedup": round(tpot_off_a / max(tpot_ms, 1e-9),
                                    3),
        "pipeline_ratio": round(tpot_ms / max(tpot_m, 1e-9), 3),
        "ngram_nonrep_speedup": round(tpot_off_a / max(tpot_ng, 1e-9),
                                      3),
        "model_accept_rate": round(rate_m, 3),
        "model_sync_accept_rate": round(ac_ms / max(dr_ms, 1), 3),
        "ngram_nonrep_accept_rate": round(rate_ng, 3),
        "model_parity_ok": bool(model_parity),
        "model_sync_parity_ok": bool(sync_parity),
        "ngram_nonrep_parity_ok": bool(ngram_parity),
        "overlap_ok": bool(overlap_ok),
        "draft_records": len(draft_recs),
        "draft_reuse_hits": int(reuse_hits),
        "draft_rollouts": int(rollouts),
        "draft_layers": int(draft_layers),
        "bursts_model": int(bursts_m),
        "bursts_model_sync": int(bursts_ms),
        # -- Phase B (repetition-heavy, n-gram + oracle): the PR 8
        # keys, meanings unchanged release over release.
        "tpot_off_ms": round(tpot_off * 1e3, 3),
        "tpot_spec_ms": round(tpot_on * 1e3, 3),
        "tpot_oracle_ms": round(tpot_or * 1e3, 3),
        "speedup": round(tpot_off / max(tpot_on, 1e-9), 3),
        "oracle_speedup": round(tpot_off / max(tpot_or, 1e-9), 3),
        "accept_rate": round(rate, 3),
        "oracle_accept_rate": round(oracle_rate, 3),
        "drafted": int(drafted),
        "accepted": int(accepted),
        "parity_ok": bool(parity_ok),
        "oracle_parity_ok": bool(oracle_parity_ok),
        # Structural (timing-free) evidence the verify path carried
        # the decode: device dispatches per pass.
        "bursts_off": int(bursts_off),
        "bursts_spec": int(bursts_on),
        "bursts_oracle": int(bursts_or),
        "decode_tokens": int(dtoks),
        "spec_k": spec_k,
        "requests": requests,
        "new_tokens": new_tokens,
        "config": config,
        "kv_int8": kv_int8,
        "weights_int8": weights_int8,
    }


def run_spec_smoke() -> dict:
    """CI-sized spec pass (tier-1 wiring: tests/test_spec_decode.py +
    tests/test_draft_model.py assert parity on every column, oracle
    acceptance == 1.0, model-draft acceptance structure and the
    pipeline-overlap records; wall-clock is reported, never gated, on
    CPU)."""
    return run_spec(smoke=True)


def run_occupancy(config=None, smoke=False, kv_int8=False,
                  weights_int8=False, factor=8, max_burst=4,
                  kv_kernel=False) -> dict:
    """High-occupancy decode sweep: max concurrent decode slots at the
    SAME KV HBM bytes, paged block-table cache vs the contiguous
    layout.

    The workload is the shape paging exists for: requests needing
    max_len/8 rows each (prompt + full token budget) against an engine
    sized for max_len worst cases. The contiguous engine's slot count
    is pinned by HBM/max_len; the paged engine gets the IDENTICAL pool
    bytes ((slots+1) * max_len rows worth of blocks) and ``factor`` x
    the slots — admission itself proves the blocks suffice, and the
    greedy outputs must match the contiguous engine token-for-token
    (the paged-vs-contiguous parity gate, at full occupancy).
    ``serve_blocks_per_token`` reports allocated-block rows per
    resident token at peak (eager allocation: the over-reservation a
    lazy allocator would shave).
    """
    import jax
    import numpy as np

    from skypilot_tpu.infer import engine as eng
    from skypilot_tpu.models import llama

    on_cpu = jax.default_backend() == "cpu"
    if config is None:
        config = "llama3-tiny" if on_cpu else "llama3-400m"
    small = smoke or on_cpu
    cfg = llama.CONFIGS[config]
    max_len = 64 if small else 4096
    kv_block = 8 if small else 256
    plen = 4 if small else 256
    new_tokens = max_len // 8 - plen
    slots_c = 2 if small else 8
    requests = slots_c * factor
    log(f"occupancy bench: {config} max_len={max_len} "
        f"block={kv_block} need={plen + new_tokens} rows/req")

    if weights_int8:
        from skypilot_tpu.infer import kvcache
        params, qw = kvcache.random_quantized_params(cfg)
    else:
        params, qw = llama.init_params(jax.random.key(0), cfg), None
    kw = dict(max_len=max_len, prompt_buckets=(plen,),
              kv_int8=kv_int8, qweights=qw, prefill_chunk=0,
              prefix_pool=0, max_wave=8, pad_waves=True)
    nb = max_len // kv_block
    # kv_kernel: the paged engine reads through the Pallas kernel; the
    # contiguous twin has no block table and falls back to the gather
    # — the parity assert below then spans kernel-vs-gather AND
    # paged-vs-contiguous at once (the PR 9 composition re-run).
    e_paged = eng.InferenceEngine(params, cfg,
                                  n_slots=slots_c * factor,
                                  kv_block=kv_block,
                                  kv_blocks=(slots_c + 1) * nb,
                                  kv_kernel=kv_kernel, **kw)
    e_contig = eng.InferenceEngine(params, cfg, n_slots=slots_c,
                                   kv_block=0, **kw)

    def kv_bytes(e):
        return sum(int(e.cache[n].nbytes)
                   for n in ("k", "v", "k_scale", "v_scale")
                   if n in e.cache)

    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, cfg.vocab_size, plen).tolist()
               for _ in range(requests)]

    def drive(e):
        ids = [e.add_request(p, max_new_tokens=new_tokens)
               for p in prompts]
        peak, bpt = 0, None
        while e.waiting or e.chunking or e.slot_req:
            # Occupancy is sampled right after admission — before the
            # decode burst can retire short requests — so the peak is
            # the number of requests the cache actually held at once.
            e.admit()
            while e.chunking:
                e.prefill_chunk_step()
            occ = len(e.slot_req)
            if occ >= peak:
                peak = occ
                if e.paged:
                    toks = sum(len(r.prompt) + len(r.tokens)
                               for r in e.slot_req.values())
                    bpt = (e.blocks_used * e.kv_block
                           / max(toks, 1))
            e.decode_burst(max_burst=max_burst)
        by_rid = {r.rid: r.tokens for r in e.finished}
        e.finished.clear()
        return [by_rid[i] for i in ids], peak, bpt

    out_c, peak_c, _ = drive(e_contig)
    out_p, peak_p, bpt = drive(e_paged)
    parity_ok = out_p == out_c
    leak_free = e_paged.blocks_used == 0
    bytes_p, bytes_c = kv_bytes(e_paged), kv_bytes(e_contig)
    occupancy_x = peak_p / max(peak_c, 1)
    log(f"occupancy: contiguous {peak_c} slots vs paged {peak_p} "
        f"at {bytes_p / 1e6:.1f} MB KV ({occupancy_x:.1f}x, "
        f"parity={parity_ok})")
    return {
        "kv_hbm_bytes": bytes_p,
        "kv_hbm_bytes_contiguous": bytes_c,
        "same_hbm": bool(bytes_p == bytes_c),
        "paged_slots": peak_p,
        "contiguous_slots": peak_c,
        "occupancy_x": round(occupancy_x, 2),
        "blocks_per_token": round(bpt, 3) if bpt else None,
        "kv_block": kv_block,
        "parity_ok": bool(parity_ok),
        "leak_free": bool(leak_free),
        # Acceptance bar: >= 4x concurrent slots at equal KV HBM.
        "occupancy_regressed": bool(occupancy_x < 4 or not parity_ok
                                    or bytes_p != bytes_c),
        "requests": requests,
        "max_len": max_len,
        "new_tokens": new_tokens,
        "config": config,
        "kv_int8": kv_int8,
        "weights_int8": weights_int8,
        "kv_kernel": bool(kv_kernel),
    }


def run_span(config=None, requests=None, prompt_len=None,
             new_tokens=None, max_burst=8, kv_int8=False,
             weights_int8=False, spec_k=0, smoke=False,
             kv_kernel=False) -> dict:
    """Span-bucketed decode attention bench: span-on vs full-view
    decode TPOT on the SAME engine (same weights, same block pool —
    the ladder is host-side dispatch state, so toggling it only
    routes bursts to differently-sliced compiled programs), greedy
    parity asserted.

    Workload: the shape span bucketing exists for — SHORT active
    conversations on a LONG-max_len engine. Every request needs
    <= max_len/8 rows; the full-view baseline still gathers max_len
    rows per slot per layer per burst step, the span path gathers the
    active bucket. TTFT is out of scope: span selection touches only
    the decode/verify/chunk big-cache read (admission waves are
    span-free).

    ``spec_k``: run the comparison through the verify path instead of
    plain bursts (the span x spec composition). ``smoke=True``:
    CI-sized — parity and dispatch structure are asserted in tier-1
    (tests/test_span_attn.py); wall-clock is reported, gated only by
    bench.py on hardware.
    """
    import time as _time

    import jax
    import numpy as np

    from skypilot_tpu.infer import engine as eng
    from skypilot_tpu.models import llama

    on_cpu = jax.default_backend() == "cpu"
    if config is None:
        config = "llama3-tiny" if on_cpu else "llama3-400m"
    small = smoke or on_cpu
    cfg = llama.CONFIGS[config]
    max_len = 2048 if small else 4096
    kv_block = 64 if small else 256
    if requests is None:
        requests = 8
    if prompt_len is None:
        prompt_len = 16 if small else 128
    if new_tokens is None:
        new_tokens = 96 if small else 256
    slots = requests
    need = prompt_len + new_tokens + (spec_k + 1 if spec_k else 0)
    assert need <= max_len // 8, "workload must fit the smallest rungs"
    log(f"span bench: {config} max_len={max_len} block={kv_block} "
        f"active<={need} rows/req requests={requests}")

    kw = dict(n_slots=slots, max_len=max_len,
              prompt_buckets=(prompt_len,), kv_int8=kv_int8,
              prefill_chunk=0, prefix_pool=0, max_wave=slots,
              pad_waves=True, kv_block=kv_block, spec_k=spec_k,
              kv_kernel=kv_kernel)
    if weights_int8:
        from skypilot_tpu.infer import kvcache
        params, qw = kvcache.random_quantized_params(cfg)
        e = eng.InferenceEngine(params, cfg, qweights=qw, **kw)
    else:
        params = llama.init_params(jax.random.key(0), cfg)
        e = eng.InferenceEngine(params, cfg, **kw)
    ladder = e.span_ladder
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, cfg.vocab_size, prompt_len).tolist()
               for _ in range(requests)]

    def decode_pass(span_on):
        """One admit-then-decode pass; TPOT over the decode loop only
        (admission is span-free). Returns (outputs, tpot_s, rows)
        where rows is the largest span actually dispatched."""
        e.span_ladder = ladder if span_on else (e.max_len,)
        e.decode_programs.clear()
        ids = [e.add_request(p, max_new_tokens=new_tokens)
               for p in prompts]
        e.admit()
        t0 = _time.time()
        while e.slot_req:
            e.decode_burst(max_burst)
        float(e.cache["length"][0])     # honest host sync
        wall = _time.time() - t0
        by_rid = {r.rid: list(r.tokens) for r in e.finished}
        outs = [by_rid[i] for i in ids]
        e.finished.clear()
        rows = max((s if s is not None else e.max_len)
                   for _, _, s in e.decode_programs)
        dtoks = sum(len(o) for o in outs) - len(outs)
        return outs, wall / max(dtoks, 1), rows

    # Warmup compiles both modes' programs outside the timed window.
    decode_pass(False)
    decode_pass(True)

    out_full, tpot_full, rows_full = decode_pass(False)
    out_span, tpot_span, rows_span = decode_pass(True)
    e.span_ladder = ladder
    parity_ok = out_span == out_full
    # Dispatch structure (timing-free): the span pass must actually
    # have read a fraction of the full view, with a ladder-bounded
    # program count.
    n_programs = len(e.decode_programs)
    log(f"span: full {tpot_full * 1e3:.2f}ms/tok ({rows_full} rows) "
        f"span {tpot_span * 1e3:.2f}ms ({rows_span} rows, "
        f"{n_programs} programs) parity={parity_ok}")
    return {
        "tpot_full_ms": round(tpot_full * 1e3, 3),
        "tpot_span_ms": round(tpot_span * 1e3, 3),
        # Wall-clock decode ratio — the regression gate input
        # (bench.py gates >= 1.5x on hardware; the tentpole target
        # is 2x for active lengths <= max_len/8).
        "speedup": round(tpot_full / max(tpot_span, 1e-9), 3),
        "rows_full": int(rows_full),
        "rows_span": int(rows_span),
        "rows_ratio": round(rows_full / max(rows_span, 1), 2),
        "span_ladder": list(ladder),
        "n_span_programs": int(n_programs),
        "parity_ok": bool(parity_ok),
        "max_len": max_len,
        "kv_block": kv_block,
        "requests": requests,
        "new_tokens": new_tokens,
        "spec_k": spec_k,
        "config": config,
        "kv_int8": kv_int8,
        "weights_int8": weights_int8,
        "kv_kernel": bool(kv_kernel),
    }


def run_span_smoke() -> dict:
    """CI-sized span pass (tier-1 wiring: tests/test_span_attn.py
    asserts parity and the rows/program structure; wall-clock is
    reported, never gated, on CPU)."""
    return run_span(smoke=True)


def run_kernel(config=None, requests=None, prompt_len=None,
               new_tokens=None, max_burst=8, kv_int8=False,
               weights_int8=False, spec_k=0, smoke=False) -> dict:
    """Pallas paged decode-attention kernel bench: kernel-vs-gather
    decode TPOT on the SAME engine (the kernel flag is a static jit
    argument — flipping it routes bursts to the other compiled
    program; weights, block pool and RNG stream are shared), greedy
    parity asserted against the gather oracle.

    Workload: LOW occupancy-utilization — a few active requests on an
    engine sized for many slots. The gather path materializes the
    [slots, span, G, hd] logical view per layer per burst step
    REGARDLESS of how many slots are active, so its fixed per-burst
    transient cost is amortized over the fewest tokens exactly here;
    the kernel never builds the view, which is the whole win.

    ``smoke=True`` / CPU: the kernel runs in Pallas interpret mode —
    parity and program identity (compile-watch keys carry
    ``kernel=True``) are the asserts; wall-clock is reported but
    MEANINGLESS on interpret (gated only by bench.py on real TPU
    runs). Full (hardware) mode additionally re-runs the span and
    occupancy benches under the kernel, confirming the PR 9 gates
    still hold on the kernel path.
    """
    import time as _time

    import jax
    import numpy as np

    from skypilot_tpu.infer import engine as eng
    from skypilot_tpu.models import llama

    on_cpu = jax.default_backend() == "cpu"
    if config is None:
        config = "llama3-tiny" if on_cpu else "llama3-400m"
    small = smoke or on_cpu
    cfg = llama.CONFIGS[config]
    max_len = 256 if small else 4096
    kv_block = 32 if small else 256
    slots = 8 if small else 16
    if requests is None:
        requests = 2 if small else 4
    if prompt_len is None:
        prompt_len = 8 if small else 128
    if new_tokens is None:
        new_tokens = 16 if small else 256
    log(f"kernel bench: {config} max_len={max_len} block={kv_block} "
        f"slots={slots} active={requests} (low occupancy)")

    kw = dict(n_slots=slots, max_len=max_len,
              prompt_buckets=(prompt_len,), kv_int8=kv_int8,
              prefill_chunk=0, prefix_pool=0, max_wave=slots,
              pad_waves=True, kv_block=kv_block, spec_k=spec_k,
              kv_kernel=True)
    if weights_int8:
        from skypilot_tpu.infer import kvcache
        params, qw = kvcache.random_quantized_params(cfg)
        e = eng.InferenceEngine(params, cfg, qweights=qw, **kw)
    else:
        params = llama.init_params(jax.random.key(0), cfg)
        e = eng.InferenceEngine(params, cfg, **kw)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, cfg.vocab_size, prompt_len).tolist()
               for _ in range(requests)]

    def decode_pass(kernel_on):
        """One admit-then-decode pass; TPOT over the decode loop only
        (admission is kernel-free: prefill waves never read the big
        cache)."""
        e.kv_kernel = kernel_on
        ids = [e.add_request(p, max_new_tokens=new_tokens)
               for p in prompts]
        e.admit()
        t0 = _time.time()
        while e.slot_req:
            e.decode_burst(max_burst)
        float(e.cache["length"][0])     # honest host sync
        wall = _time.time() - t0
        by_rid = {r.rid: list(r.tokens) for r in e.finished}
        outs = [by_rid[i] for i in ids]
        e.finished.clear()
        dtoks = sum(len(o) for o in outs) - len(outs)
        return outs, wall / max(dtoks, 1)

    # Warmup compiles both modes' programs outside the timed window.
    decode_pass(False)
    decode_pass(True)

    out_gather, tpot_gather = decode_pass(False)
    out_kernel, tpot_kernel = decode_pass(True)
    e.kv_kernel = True
    parity_ok = out_kernel == out_gather
    # Program identity: the kernel flag must live in the compile-watch
    # keys (never a retrace surface — both values were warmed above).
    keys = e.compile_watch.summary()
    kernel_programs_ok = (
        any("kernel=True" in k for k in keys)
        and any("kernel=False" in k for k in keys))
    speedup = tpot_gather / max(tpot_kernel, 1e-9)
    log(f"kernel: gather {tpot_gather * 1e3:.2f}ms/tok kernel "
        f"{tpot_kernel * 1e3:.2f}ms/tok ({speedup:.2f}x, "
        f"parity={parity_ok}, backend={jax.default_backend()})")
    out = {
        "tpot_gather_ms": round(tpot_gather * 1e3, 3),
        "tpot_kernel_ms": round(tpot_kernel * 1e3, 3),
        # Informational on CPU (interpret mode); gated on TPU runs.
        "speedup": round(speedup, 3),
        "parity_ok": bool(parity_ok),
        "kernel_programs_ok": bool(kernel_programs_ok),
        "backend": jax.default_backend(),
        "active_requests": requests,
        "slots": slots,
        "max_len": max_len,
        "kv_block": kv_block,
        "span_ladder": list(e.span_ladder),
        "new_tokens": new_tokens,
        "spec_k": spec_k,
        "config": config,
        "kv_int8": kv_int8,
        "weights_int8": weights_int8,
    }
    if not small:
        # The PR 9 gates, re-run on the kernel path (hardware only:
        # interpret-mode wall-clock would drown the comparison).
        sa = run_span(config=config, kv_int8=kv_int8,
                      weights_int8=weights_int8, kv_kernel=True)
        out["span_under_kernel_speedup"] = sa["speedup"]
        out["span_under_kernel_parity_ok"] = sa["parity_ok"]
        oc = run_occupancy(config=config, kv_int8=kv_int8,
                           weights_int8=weights_int8, kv_kernel=True)
        out["occupancy_under_kernel_x"] = oc["occupancy_x"]
        out["occupancy_under_kernel_ok"] = (
            not oc["occupancy_regressed"])
    return out


def run_kernel_smoke() -> dict:
    """CI-sized kernel pass (tier-1 wiring: tests/test_paged_attention
    .py asserts parity and program identity; interpret-mode wall-clock
    is reported, never gated, on CPU)."""
    return run_kernel(smoke=True)


def run_adapters(config=None, n_adapters=8, requests=None,
                 prompt_len=None, new_tokens=None, max_burst=8,
                 kv_int8=False, weights_int8=False, spec_k=0,
                 smoke=False) -> dict:
    """Multi-LoRA adapter-catalog bench (docs/serving.md §Adapter
    catalog): N-adapters-vs-1 decode TPOT overhead on the SAME engine.

    Three phases, one engine:

    1. BASELINE — every request generates under ONE fine-tune
       (decode gathers one pool slot's (A, B) per layer).
    2. MIXED — the same requests spread over ``n_adapters``
       fine-tunes in one continuous batch. The gather indexes differ;
       the program is IDENTICAL (adapter id is slot data, exactly like
       the span rung), so the overhead gate (bench.py:
       ``serve_adapter_overhead`` <= 1.15x) is pure gather cost.
       Greedy parity is asserted against per-request sequential runs
       — a mixed batch must emit exactly what each fine-tune emits
       alone.
    3. HOT-LOAD CHURN — more fine-tunes than pool slots cycle through
       traffic under ``declare_warmup_complete``: every demand load is
       an LRU evict + install DISPATCH, and the compile watch gates
       ZERO unexpected compiles (adapter count/identity never enters
       program identity — the ROADMAP item 5 watch item).

    ``smoke=True`` / CPU: CI-sized; wall-clock is reported, the 1.15x
    gate binds via bench.py (structure/parity/compile gates bind
    everywhere).
    """
    import time as _time

    import jax
    import numpy as np

    from skypilot_tpu.infer import adapters as ad_lib
    from skypilot_tpu.infer import engine as eng
    from skypilot_tpu.models import llama

    on_cpu = jax.default_backend() == "cpu"
    if config is None:
        config = "llama3-tiny" if on_cpu else "llama3-400m"
    small = smoke or on_cpu
    cfg = llama.CONFIGS[config]
    rank = 4 if small else 16
    if requests is None:
        requests = n_adapters if small else 2 * n_adapters
    if prompt_len is None:
        prompt_len = 16 if small else 128
    if new_tokens is None:
        new_tokens = 32 if small else 256
    slots = requests
    max_len = 256 if small else 2048
    log(f"adapter bench: {config} rank={rank} n_adapters={n_adapters} "
        f"requests={requests}")

    catalog = ad_lib.AdapterCatalog(cfg, n_adapters=n_adapters + 1,
                                    rank=rank)
    shapes = ad_lib.target_shapes(cfg, rank)
    L = cfg.n_layers
    # Registered fine-tunes: n_adapters for the mixed phase plus as
    # many again for the churn phase (they cannot all be resident).
    names = [f"ft-{i}" for i in range(2 * n_adapters)]
    for i, name in enumerate(names):
        r = np.random.default_rng(100 + i)
        catalog.register(name, params={
            t: {"a": r.normal(size=(L,) + sa).astype(np.float32) * 0.02,
                "b": r.normal(size=(L,) + sb).astype(np.float32) * 0.02}
            for t, (sa, sb) in shapes.items()})

    kw = dict(n_slots=slots, max_len=max_len,
              prompt_buckets=(prompt_len,), kv_int8=kv_int8,
              prefill_chunk=0, prefix_pool=0, max_wave=slots,
              pad_waves=True, spec_k=spec_k, adapters=catalog)
    if weights_int8:
        from skypilot_tpu.infer import kvcache
        params, qw = kvcache.random_quantized_params(cfg)
        e = eng.InferenceEngine(params, cfg, qweights=qw, **kw)
    else:
        params = llama.init_params(jax.random.key(0), cfg)
        e = eng.InferenceEngine(params, cfg, **kw)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, cfg.vocab_size, prompt_len).tolist()
               for _ in range(requests)]

    # Production startup: pre-compile the grid (incl. the adapter
    # gather + hot-load programs), then arm the compile watch — every
    # phase below runs under the zero-unexpected-compiles contract.
    e.warm_programs(max_burst=max_burst)
    e.declare_warmup_complete()

    def decode_pass(adapter_names):
        ids = [e.add_request(p, max_new_tokens=new_tokens, adapter=a)
               for p, a in zip(prompts, adapter_names)]
        e.admit()
        t0 = _time.time()
        while e.slot_req:
            e.decode_burst(max_burst)
        float(e.cache["length"][0])     # honest host sync
        wall = _time.time() - t0
        by_rid = {r.rid: list(r.tokens) for r in e.finished}
        outs = [by_rid[i] for i in ids]
        e.finished.clear()
        dtoks = sum(len(o) for o in outs) - len(outs)
        return outs, wall / max(dtoks, 1)

    single = [names[0]] * requests
    mixed = [names[i % n_adapters] for i in range(requests)]

    # Warm both gather patterns' caches/adapters outside the window.
    decode_pass(single)
    decode_pass(mixed)

    out_single, tpot_single = decode_pass(single)
    out_mixed, tpot_mixed = decode_pass(mixed)

    # Greedy parity: the mixed batch must emit exactly what each
    # fine-tune emits alone (sequential single-request passes).
    parity_ok = True
    for p, a, want in zip(prompts, mixed, out_mixed):
        rid = e.add_request(p, max_new_tokens=new_tokens, adapter=a)
        e.admit()
        while e.slot_req:
            e.decode_burst(max_burst)
        got = {r.rid: list(r.tokens) for r in e.finished}[rid]
        e.finished.clear()
        if got != want:
            parity_ok = False
            break

    # Hot-load churn: cycle through 2x the pool's fine-tunes under
    # live decode — every wave demand-loads (LRU evict + install),
    # and nothing may compile.
    loads_before = catalog.loads
    for i in range(0, len(names), n_adapters):
        batch = [names[(i + j) % len(names)]
                 for j in range(min(n_adapters, requests))]
        for p, a in zip(prompts, batch):
            e.add_request(p, max_new_tokens=4, adapter=a)
        e.run_to_completion()
        e.finished.clear()
    churn_loads = catalog.loads - loads_before
    unexpected = list(e.compile_watch.unexpected)

    overhead = tpot_mixed / max(tpot_single, 1e-9)
    log(f"adapters: single {tpot_single * 1e3:.2f}ms/tok mixed "
        f"{tpot_mixed * 1e3:.2f}ms/tok (x{overhead:.3f}) "
        f"parity={parity_ok} churn_loads={churn_loads} "
        f"evictions={catalog.evictions} unexpected={len(unexpected)}")
    return {
        "tpot_single_ms": round(tpot_single * 1e3, 3),
        "tpot_mixed_ms": round(tpot_mixed * 1e3, 3),
        # The regression-gate input: bench.py gates <= 1.15x.
        "overhead_ratio": round(overhead, 3),
        "parity_ok": bool(parity_ok),
        "hot_loads": int(churn_loads),
        "evictions": int(catalog.evictions),
        "unexpected_compiles": len(unexpected),
        "n_adapters": n_adapters,
        "rank": rank,
        "requests": requests,
        "new_tokens": new_tokens,
        "spec_k": spec_k,
        "backend": jax.default_backend(),
        "config": config,
        "kv_int8": kv_int8,
        "weights_int8": weights_int8,
    }


def run_adapters_smoke() -> dict:
    """CI-sized adapter-catalog pass (tier-1 wiring:
    tests/test_adapters.py asserts parity, churn and the
    zero-compile contract; CPU wall-clock is reported, the 1.15x
    TPOT gate binds via bench.py)."""
    return run_adapters(smoke=True, n_adapters=4)


def run_flight(config=None, requests=None, new_tokens=None,
               max_burst=8, spec_k=4, kv_int8=False,
               weights_int8=False, smoke=False) -> dict:
    """Flight recorder + compile watch bench over the FULL mixed
    workload: chunked admission with prefix reuse + speculative decode
    + span regrouping, on a paged engine AND a contiguous twin.

    Per layout:

      1. ``warm_programs()`` sweeps the program grid, one untimed
         workload pass covers anything workload-specific, then the
         engine declares warmup complete — the production startup
         sequence (`--warm-grid`).
      2. The TIMED window runs the same mixed workload and asserts
         the introspection contract: ``unexpected_compiles == 0``
         (nothing compiled mid-traffic), and every decode/verify
         program the engine selected (``decode_programs``) has flight
         records whose program identity matches — and vice versa
         (records never claim a program the engine didn't dispatch).
      3. Recorder-on vs recorder-off passes measure the no-op-guard
         overhead (``overhead_ratio``; greedy outputs must be
         identical — recording can never perturb generation).

    ``smoke=True``: CI-sized — structure and the zero-unexpected gate
    are asserted in tier-1 (tests/test_flight.py); the <1% overhead
    bound is gated only by bench.py on hardware (CPU wall-clock noise
    swamps it).
    """
    import dataclasses
    import time as _time

    import jax
    import numpy as np

    from skypilot_tpu.infer import engine as eng
    from skypilot_tpu.models import llama
    from skypilot_tpu.observability import flight as flight_lib

    on_cpu = jax.default_backend() == "cpu"
    if config is None:
        config = "llama3-tiny" if on_cpu else "llama3-400m"
    small = smoke or on_cpu
    if requests is None:
        requests = 6 if small else 16
    if new_tokens is None:
        new_tokens = 24 if small else 128
    max_len = 256 if small else 2048
    chunk = 24 if small else 256
    kv_block = 32 if small else 128   # not dividing chunk-aligned
    #                                   prefixes cleanly -> COW runs
    short_len, long_a, long_b = (12, 60, 72) if small \
        else (96, 640, 768)
    shared = 2 * chunk                # chunk-aligned shared prefix
    slots = requests
    # Small vocab: the random model's greedy decode cycles, so the
    # n-gram drafter actually drafts (the run_spec regime).
    cfg = dataclasses.replace(llama.CONFIGS[config], vocab_size=16)
    rng = np.random.default_rng(0)
    base = rng.integers(1, cfg.vocab_size, shared).tolist()
    prompts = (
        [rng.integers(1, cfg.vocab_size, short_len).tolist()
         for _ in range(requests - 4)]
        + [base + rng.integers(1, cfg.vocab_size,
                               long_a - shared).tolist(),
           base + rng.integers(1, cfg.vocab_size,
                               long_b - shared).tolist()] * 2)
    log(f"flight bench: {config} (vocab 16) max_len={max_len} "
        f"chunk={chunk} block={kv_block} K={spec_k} "
        f"requests={len(prompts)}")

    def build(paged):
        kw = dict(n_slots=slots, max_len=max_len,
                  prompt_buckets=(16 if small else 128, max_len),
                  kv_int8=kv_int8, prefill_chunk=chunk,
                  prefix_pool=4, max_wave=slots, pad_waves=True,
                  spec_k=spec_k, kv_block=kv_block if paged else 0,
                  flight_recorder=flight_lib.FlightRecorder())
        if weights_int8:
            from skypilot_tpu.infer import kvcache
            params, qw = kvcache.random_quantized_params(cfg)
            return eng.InferenceEngine(params, cfg, qweights=qw, **kw)
        params = llama.init_params(jax.random.key(0), cfg)
        return eng.InferenceEngine(params, cfg, **kw)

    def workload(e):
        ids = [e.add_request(p, max_new_tokens=new_tokens)
               for p in prompts]
        t0 = _time.time()
        e.run_to_completion(max_burst)
        wall = _time.time() - t0
        by_rid = {r.rid: list(r.tokens) for r in e.finished}
        outs = [by_rid[i] for i in ids]
        e.finished.clear()
        toks = sum(len(o) for o in outs)
        return outs, wall / max(toks, 1)

    layouts = {}
    for paged in (True, False):
        e = build(paged)
        rec = e.flight
        # Production startup: grid sweep + one untimed workload pass,
        # then arm the watch.
        warmed = e.warm_programs(max_burst=max_burst)
        workload(e)
        warm_compile_s = e.compile_watch.total_compile_s()
        e.declare_warmup_complete()
        # Timed window.
        e.decode_programs.clear()
        seq0 = rec.seq()
        out_on, tpot_on = workload(e)
        window = rec.since(seq0)
        unexpected = list(e.compile_watch.unexpected)
        # Coverage: flight-record program identity <-> the programs
        # the engine actually selected, both directions.
        rec_dv = {(r["program"]["k"], r["program"]["span"])
                  for r in window if r["burst"] in ("decode",
                                                    "verify")}
        eng_dv = {(k, s) for kind, k, s in e.decode_programs
                  if kind in ("burst", "verify")}
        n_chunks = sum(1 for r in window if r["burst"] == "chunk")
        n_waves = sum(1 for r in window if r["burst"] == "wave")
        coverage_ok = (rec_dv == eng_dv and n_chunks > 0
                       and n_waves > 0)
        # Recorder-off guard: same workload, recorder disabled —
        # identical greedy output, best-of TPOT for the ratio.
        rec.enabled = False
        out_off, tpot_off = workload(e)
        rec.enabled = True
        _, tpot_on2 = workload(e)
        rec.enabled = False
        _, tpot_off2 = workload(e)
        rec.enabled = True
        tpot_on = min(tpot_on, tpot_on2)
        tpot_off = min(tpot_off, tpot_off2)
        # Calibration parity: every pass above ran with the device-time
        # calibrator at its default cadence (the bracket rides the
        # compile-watch hit path whether or not the recorder is on), so
        # overhead_ratio already prices calibration into BOTH sides.
        # Here the off-switch itself is gated: SKYTPU_DEVTIME_EVERY=0
        # must produce bit-identical greedy tokens — the bracket only
        # ever observes, never perturbs.
        cal_samples = e.devtime.samples
        prev_every = os.environ.get("SKYTPU_DEVTIME_EVERY")
        os.environ["SKYTPU_DEVTIME_EVERY"] = "0"
        try:
            out_nocal, _ = workload(e)
        finally:
            if prev_every is None:
                os.environ.pop("SKYTPU_DEVTIME_EVERY", None)
            else:
                os.environ["SKYTPU_DEVTIME_EVERY"] = prev_every
        # Forensics guard: the request-ledger machinery (stall-episode
        # bookkeeping, the retire record, the P^2 tail observe) rides
        # the retire path. The timed window above ran forensics-ON (the
        # default), so measure the off side the same best-of-two way.
        # Off must be bit-identical greedy output — forensics observes
        # retirement, it never steers scheduling.
        e.forensics = False
        out_foff, tpot_foff = workload(e)
        e.forensics = True
        _, tpot_fon = workload(e)
        e.forensics = False
        _, tpot_foff2 = workload(e)
        e.forensics = True
        tpot_fon = min(tpot_on, tpot_fon)
        tpot_foff = min(tpot_foff, tpot_foff2)
        layouts["paged" if paged else "contig"] = {
            "programs_warmed": warmed,
            "warmup_compile_s": round(warm_compile_s, 3),
            "unexpected_compiles": len(unexpected),
            "unexpected": unexpected,
            "coverage_ok": bool(coverage_ok),
            "parity_ok": bool(out_on == out_off),
            "calibration_parity_ok": bool(out_nocal == out_on),
            "calibration_samples": int(cal_samples),
            "n_records": len(window),
            "n_chunk_records": n_chunks,
            "n_wave_records": n_waves,
            "tpot_on_ms": round(tpot_on * 1e3, 3),
            "tpot_off_ms": round(tpot_off * 1e3, 3),
            "overhead_ratio": round(tpot_on / max(tpot_off, 1e-9), 4),
            "forensics_parity_ok": bool(out_foff == out_on),
            "tpot_forensics_on_ms": round(tpot_fon * 1e3, 3),
            "tpot_forensics_off_ms": round(tpot_foff * 1e3, 3),
            "forensics_overhead_ratio": round(
                tpot_fon / max(tpot_foff, 1e-9), 4),
        }
        log(f"flight {'paged' if paged else 'contig'}: "
            f"{layouts['paged' if paged else 'contig']}")
    agg = {
        "warmup_compile_s": round(
            sum(v["warmup_compile_s"] for v in layouts.values()), 3),
        "unexpected_compiles": sum(v["unexpected_compiles"]
                                   for v in layouts.values()),
        "coverage_ok": all(v["coverage_ok"] for v in layouts.values()),
        "parity_ok": all(v["parity_ok"] for v in layouts.values()),
        "calibration_parity_ok": all(v["calibration_parity_ok"]
                                     for v in layouts.values()),
        "calibration_samples": sum(v["calibration_samples"]
                                   for v in layouts.values()),
        "n_records": sum(v["n_records"] for v in layouts.values()),
        # Worst layout: the gate must catch a recorder change that
        # slows only one of the two decode paths.
        "overhead_ratio": max(v["overhead_ratio"]
                              for v in layouts.values()),
        "forensics_parity_ok": all(v["forensics_parity_ok"]
                                   for v in layouts.values()),
        "forensics_overhead_ratio": max(v["forensics_overhead_ratio"]
                                        for v in layouts.values()),
        "layouts": layouts,
        "config": config,
        "spec_k": spec_k,
        "kv_int8": kv_int8,
        "weights_int8": weights_int8,
    }
    return agg


def run_flight_smoke() -> dict:
    """CI-sized flight pass (tier-1 wiring: tests/test_flight.py
    asserts the zero-unexpected + coverage structure; overhead is
    reported, never gated, on CPU)."""
    return run_flight(smoke=True)


def run_qos(config=None, slots=None, bg_requests=None,
            hot_requests=None, new_tokens=None, max_burst=8,
            kv_int8=False, weights_int8=False, smoke=False) -> dict:
    """Multi-tenant QoS bench: weighted-fair-queueing isolation under a
    hot tenant, and preemption-by-eviction greedy parity.

    Two phases on CI-sized engines (docs/serving.md §Multi-tenant
    QoS):

    1. **Fairness** — a background tenant's requests run (a) alone
       (idle), (b) behind a hot tenant's flood under WFQ, and (c) the
       same flood under plain FIFO (the control). Gates: background
       TPOT p99 under contention <= 1.3x idle while the hot tenant
       queues, and — the structural win — WFQ admits the background
       tenant ahead of the flood while FIFO strands it
       (``bg_ttft_fifo_ratio`` shows the damage WFQ undoes).

    2. **Preemption parity** — a low-priority request is evicted
       mid-decode by a high-priority arrival (1-slot engine: eviction
       is the only way in), resumes warm from the prefix cache, and
       must produce BIT-IDENTICAL greedy output to an unpreempted run
       — across {fp32, int8 KV} x {spec-on, spec-off} on the paged
       layout (``smoke=True`` runs the fp32 pair only; tests/test_qos
       .py covers the full matrix). Zero leaked blocks after retire +
       cache clear (allocator audit) is asserted, not reported.
    """
    import dataclasses
    import time as _time

    import jax
    import numpy as np

    from skypilot_tpu.infer import engine as eng
    from skypilot_tpu.infer import qos as qos_lib
    from skypilot_tpu.models import llama

    on_cpu = jax.default_backend() == "cpu"
    if config is None:
        config = "llama3-tiny" if on_cpu else "llama3-400m"
    small = smoke or on_cpu
    slots = slots or (4 if small else 8)
    bg_requests = bg_requests or 2
    hot_requests = hot_requests or (3 * slots)
    new_tokens = new_tokens or (16 if small else 64)
    prompt_len = 12
    max_len = 64 if small else 256
    cfg = llama.CONFIGS[config]
    log(f"qos bench: {config} slots={slots} bg={bg_requests} "
        f"hot={hot_requests} new_tokens={new_tokens}")

    def build(n_slots, qos=None, spec_k=0, chunk=0, pool=0,
              buckets=None, kv_int8=kv_int8):
        kw = dict(n_slots=n_slots, max_len=max_len,
                  prompt_buckets=buckets or (prompt_len,),
                  kv_int8=kv_int8, prefill_chunk=chunk,
                  prefix_pool=pool, max_wave=n_slots, pad_waves=True,
                  spec_k=spec_k, qos=qos)
        if weights_int8:
            from skypilot_tpu.infer import kvcache
            params, qw = kvcache.random_quantized_params(cfg)
            return eng.InferenceEngine(params, cfg, qweights=qw, **kw)
        params = llama.init_params(jax.random.key(0), cfg)
        return eng.InferenceEngine(params, cfg, **kw)

    rng = np.random.default_rng(0)
    bg_prompts = [rng.integers(1, cfg.vocab_size, prompt_len).tolist()
                  for _ in range(bg_requests)]
    hot_prompts = [rng.integers(1, cfg.vocab_size, prompt_len).tolist()
                   for _ in range(hot_requests)]

    def fairness_pass(e, with_hot):
        """Hot flood enqueued FIRST (worst case for the background
        tenant), then background; per-background-request TTFT and
        TPOT collected at retirement."""
        ids = []
        if with_hot:
            for p in hot_prompts:
                e.add_request(p, max_new_tokens=new_tokens,
                              tenant="hot")
        for p in bg_prompts:
            ids.append(e.add_request(p, max_new_tokens=new_tokens,
                                     tenant="background"))
        done_s: dict = {}
        while e.waiting or e.chunking or e.slot_req:
            e.step_burst(max_burst)
            now = _time.time()
            for r in e.finished:
                done_s.setdefault(r.rid, now)
        by_rid = {r.rid: r for r in e.finished}
        ttfts, tpots = [], []
        for rid in ids:
            r = by_rid[rid]
            ttfts.append(r.first_token_s - r.submit_s)
            if len(r.tokens) > 1:
                tpots.append((done_s[rid] - r.first_token_s)
                             / (len(r.tokens) - 1))
        outs = [by_rid[i].tokens for i in ids]
        e.finished.clear()
        return ttfts, tpots, outs

    # Warmup compiles, then idle / WFQ-contended / FIFO-contended on
    # fresh schedulers (bucket state must not leak between passes).
    e = build(slots, qos=qos_lib.FairScheduler())
    fairness_pass(e, with_hot=False)
    idle_ttft, idle_tpot, idle_out = fairness_pass(e, with_hot=False)
    e.qos = qos_lib.FairScheduler()
    wfq_ttft, wfq_tpot, wfq_out = fairness_pass(e, with_hot=True)
    e.qos = None
    fifo_ttft, _fifo_tpot, fifo_out = fairness_pass(e, with_hot=True)

    # Scheduling must never change tokens: same engine, same greedy
    # stream per request.
    sched_parity = (idle_out == wfq_out == fifo_out)
    fairness_ratio = _p99(wfq_tpot) / max(_p99(idle_tpot), 1e-9)
    ttft_wfq_ratio = _p99(wfq_ttft) / max(_p99(idle_ttft), 1e-9)
    ttft_fifo_ratio = _p99(fifo_ttft) / max(_p99(idle_ttft), 1e-9)
    log(f"qos fairness: bg TPOT p99 x{fairness_ratio:.2f} vs idle "
        f"(bg TTFT p99 x{ttft_wfq_ratio:.1f} wfq / "
        f"x{ttft_fifo_ratio:.1f} fifo), sched parity={sched_parity}")

    # Phase 2: preemption-by-eviction parity. 1-slot engine, chunked
    # prefill + prefix cache on (the warm-resume path), high-priority
    # arrival evicts the low-priority resident mid-decode.
    # The full run sweeps the kv dtype too — {fp32, int8} x
    # {spec-off, spec-on}, the acceptance matrix; smoke (and a run
    # pinned by --kv-int8, whose fairness phase already chose its
    # dtype) runs only that dtype's spec pair.
    dtypes = [kv_int8] if (smoke or kv_int8) else [False, True]
    combos = [(k, i8) for i8 in dtypes for k in (0, 4)]
    parity_ok = True
    preemptions = 0
    resumed_rows = 0
    low_prompt = list(range(5, 5 + prompt_len))
    hi_prompt = [3, 1, 4]
    for spec_k, i8 in combos:
        ref = build(1, chunk=8, pool=4, spec_k=spec_k, kv_int8=i8,
                    buckets=(prompt_len + new_tokens + 8,))
        want = ref.generate([low_prompt],
                            max_new_tokens=new_tokens)[0]
        e2 = build(1, qos=qos_lib.FairScheduler(), chunk=8, pool=4,
                   spec_k=spec_k, kv_int8=i8,
                   buckets=(prompt_len + new_tokens + 8,))
        rid_low = e2.add_request(low_prompt,
                                 max_new_tokens=new_tokens,
                                 priority=0)
        while not e2.slot_req:
            e2.step_burst(max_burst=2)
        for _ in range(2):
            e2.decode_burst(max_burst=2)
        e2.add_request(hi_prompt, max_new_tokens=4, priority=1)
        e2.run_to_completion(max_burst=2)
        by_rid = {r.rid: r for r in e2.finished}
        low = by_rid[rid_low]
        parity_ok = parity_ok and (low.tokens == want
                                   and low.preemptions >= 1)
        preemptions += low.preemptions
        resumed_rows += low.resumed_len
        e2.clear_prefix_cache()
        assert e2.allocator.used == 0, (
            f"block leak after preemption cycle: {e2.allocator.used}")
    log(f"qos preempt: parity={parity_ok} preemptions={preemptions} "
        f"resumed_rows={resumed_rows}")

    return {
        "fairness_ratio": round(fairness_ratio, 3),
        "bg_tpot_idle_p99_ms": round(_p99(idle_tpot) * 1e3, 3),
        "bg_tpot_contended_p99_ms": round(_p99(wfq_tpot) * 1e3, 3),
        "bg_ttft_wfq_ratio": round(ttft_wfq_ratio, 3),
        "bg_ttft_fifo_ratio": round(ttft_fifo_ratio, 3),
        "sched_parity_ok": bool(sched_parity),
        "preempt_parity_ok": bool(parity_ok),
        "preemptions": int(preemptions),
        "preempt_resumed_rows": int(resumed_rows),
        "slots": slots,
        "bg_requests": bg_requests,
        "hot_requests": hot_requests,
        "new_tokens": new_tokens,
        "config": config,
        "kv_int8": kv_int8,
        "weights_int8": weights_int8,
    }


def run_qos_smoke() -> dict:
    """CI-sized QoS pass (tier-1 wiring: tests/test_qos.py asserts
    scheduling + preemption parity and the fairness structure;
    wall-clock ratios are reported, gated only on hardware)."""
    return run_qos(smoke=True)


def _ndjson_objs(body):
    """The NDJSON objects in a raw chunked response body. The server
    writes one JSON line per chunk, so splitting on newlines recovers
    the lines; the hex chunk-size framing lines are dropped (some hex
    strings parse as JSON numbers — only dicts survive)."""
    objs = []
    for line in body.split(b"\n"):
        line = line.strip()
        if not line.startswith(b"{"):
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        if isinstance(obj, dict):
            objs.append(obj)
    return objs


def run_failover(config=None, requests=None, slots=4, new_tokens=None,
                 max_burst=8, kv_int8=False, weights_int8=False,
                 smoke=False) -> dict:
    """Serving fault-tolerance gate, chaos-verified end to end over
    HTTP through the real LB against two live replicas
    (docs/robustness.md §Replica loss & rolling update):

    1. **Engine crash recovery** — a seeded ``engine.dispatch`` fault
       (seam=decode) crashes one replica's engine mid-wave; the model
       server resets the engine and re-admits every in-flight request
       through the resume path. Gates: every stream completes cleanly,
       tokens BIT-IDENTICAL to the fault-free control, and >= 1
       recovery observed (``skytpu_engine_recoveries_total`` plus the
       done-line ``recoveries`` trailer).

    2. **Mid-stream failover** — a seeded ``replica.kill`` fault drops
       one stream's connection with no terminal chunk (to the LB that
       replica was SIGKILLed mid-stream); the LB replays
       prompt + committed tokens on the surviving replica with the
       budget reduced by what already streamed. Gates: the client sees
       ONE gapless duplicate-free stream bit-identical to the control,
       and >= 1 failover counted (``skytpu_lb_failovers_total``).

    Zero lost requests is asserted structurally: :func:`_client_wave`
    raises on any non-200, in-stream error line, or unterminated
    stream, so a passing wave IS the zero-shed/zero-truncation gate.
    """
    import json as _json
    import socket
    import tempfile
    import threading

    import jax
    import numpy as np

    from skypilot_tpu import chaos

    on_cpu = jax.default_backend() == "cpu"
    if config is None:
        config = "llama3-tiny" if on_cpu else "llama3-400m"
    small = smoke or on_cpu
    requests = requests or (6 if small else 16)
    new_tokens = new_tokens or (12 if small else 32)
    prompt_len = 12
    # A failover replay's prompt is prompt + committed (up to one token
    # short of the full budget): the bucket must fit the longest
    # replay, not just the original prompts.
    max_prompt = prompt_len + new_tokens
    buckets = (max_prompt,)
    log(f"failover gate: {config} replicas=2 slots={slots} "
        f"requests={requests} new_tokens={new_tokens}")

    home = tempfile.mkdtemp(prefix="skytpu-bench-failover-")
    os.environ["SKYPILOT_TPU_HOME"] = home

    from skypilot_tpu.infer import engine as eng_mod
    from skypilot_tpu.infer import server as srv
    from skypilot_tpu.models import llama
    from skypilot_tpu.serve import load_balancer, serve_state
    from skypilot_tpu.serve.serve_state import ReplicaStatus

    cfg = llama.CONFIGS[config]
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, cfg.vocab_size, prompt_len).tolist()
               for _ in range(requests)]

    chaos.deactivate()   # warmup + control must run fault-free

    def free_port():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    lb_port = free_port()
    serve_state.add_service("bench-failover", {}, {}, lb_port)
    models, httpds = [], []
    for i in range(2):
        # Same seed -> identical weights: a resumed suffix from the
        # surviving replica must be what the dead one would have
        # produced. Chunked prefill + a prefix pool put the crash
        # resume on the warm path (contexts stay > prefill_chunk, the
        # parity-covered regime).
        _, engine = _build_engine(config, slots, max_prompt,
                                  new_tokens, kv_int8, weights_int8,
                                  max_wave=4, buckets=buckets,
                                  pad_waves=True, prefill_chunk=8,
                                  prefix_pool=8)
        port = free_port()
        model, httpd = srv.serve(engine, host="127.0.0.1", port=port,
                                 max_burst=max_burst, open_burst=4,
                                 coalesce_s=0.0)
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()
        models.append(model)
        httpds.append(httpd)
        serve_state.upsert_replica("bench-failover", i + 1,
                                   f"bench-failover-{i + 1}",
                                   ReplicaStatus.READY,
                                   f"http://127.0.0.1:{port}")
    for model in models:
        assert model._ready.wait(timeout=600), "model warmup timed out"
    lb = load_balancer._ThreadingServer(
        ("127.0.0.1", lb_port),
        load_balancer.make_handler("bench-failover",
                                   load_balancer.LeastLoadPolicy()))
    threading.Thread(target=lb.serve_forever, daemon=True).start()

    payloads = [_json.dumps({"tokens": p, "max_new_tokens": new_tokens,
                             "stream": True}).encode()
                for p in prompts]

    def wave():
        """One concurrent wave; returns (token sequences, done-line
        trailers), both in payload order."""
        bodies = []
        _client_wave("127.0.0.1", lb_port, payloads, bodies=bodies)
        seqs, trailers = [], []
        for body in bodies:
            objs = _ndjson_objs(body)
            toks = []
            for o in objs:
                toks.extend(int(t) for t in o.get("tokens") or [])
            done = [o for o in objs if o.get("done")]
            assert done, f"stream ended without a done line: {objs!r}"
            seqs.append(toks)
            trailers.append(done[-1])
        return seqs, trailers

    def _total(metric):
        return sum(child.value for _, child in metric.children())

    try:
        wave()                        # warm: compiles outside the gate
        want, _ = wave()              # fault-free control
        assert all(len(s) == new_tokens for s in want), (
            f"control wave short: {[len(s) for s in want]}")

        # Phase 1: engine crash recovery. One decode dispatch fault;
        # the wave must come back bit-identical with >= 1 recovery.
        rec0 = _total(eng_mod.ENGINE_RECOVERIES)
        chaos.configure({"seed": 7, "faults": [
            {"point": "engine.dispatch", "match": {"seam": "decode"},
             "times": 1}]})
        crash_seqs, crash_trailers = wave()
        crash_fired = len(chaos.injector().fired)
        chaos.deactivate()
        recoveries = _total(eng_mod.ENGINE_RECOVERIES) - rec0
        trailer_recoveries = sum(t.get("recoveries", 0)
                                 for t in crash_trailers)
        crash_parity = crash_seqs == want
        log(f"failover phase 1 (engine crash): parity={crash_parity} "
            f"fired={crash_fired} recoveries={recoveries} "
            f"rode_through={trailer_recoveries}")

        # Phase 2: replica death mid-stream. The kill fires on the 3rd
        # chunk write (after=2: past connect, tokens committed); the
        # LB stitches the suffix from the surviving replica.
        fo0 = _total(load_balancer.LB_FAILOVERS)
        chaos.configure({"seed": 11, "faults": [
            {"point": "replica.kill", "times": 1, "after": 2}]})
        kill_seqs, kill_trailers = wave()
        kill_fired = len(chaos.injector().fired)
        chaos.deactivate()
        failovers = _total(load_balancer.LB_FAILOVERS) - fo0
        trailer_failovers = sum(t.get("failovers", 0)
                                for t in kill_trailers)
        kill_parity = kill_seqs == want
        log(f"failover phase 2 (replica kill): parity={kill_parity} "
            f"fired={kill_fired} failovers={failovers} "
            f"stitched={trailer_failovers}")
    finally:
        chaos.deactivate()
        lb.shutdown()
        for httpd in httpds:
            httpd.shutdown()
        for model in models:
            model.shutdown()

    gate_ok = (crash_parity and kill_parity
               and crash_fired >= 1 and recoveries >= 1
               and kill_fired >= 1 and failovers >= 1)
    return {
        "gate_ok": bool(gate_ok),
        "crash_parity_ok": bool(crash_parity),
        "kill_parity_ok": bool(kill_parity),
        "recoveries": int(recoveries),
        "trailer_recoveries": int(trailer_recoveries),
        "failovers": int(failovers),
        "trailer_failovers": int(trailer_failovers),
        # Structural: _client_wave raised on any lost/short stream.
        "lost_requests": 0,
        "requests": requests,
        "new_tokens": new_tokens,
        "config": config,
        "kv_int8": kv_int8,
        "weights_int8": weights_int8,
    }


def run_failover_smoke() -> dict:
    """CI-sized fault-tolerance pass (tier-1 wiring: tests/
    test_serve_recovery.py asserts gate_ok; wall-clock is never
    gated on CPU)."""
    return run_failover(smoke=True)


def _free_port():
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _wave_token_seqs(port, payloads, ttfts=None):
    """Fire ``payloads`` concurrently at the LB and return each
    response's token sequence in payload order (blocking JSON and
    NDJSON stream bodies both parse through _ndjson_objs). ``ttfts``,
    if a list, collects per-request TTFT seconds."""
    bodies = []
    res = _client_wave("127.0.0.1", port, payloads, bodies=bodies)
    if ttfts is not None:
        ttfts.extend(r[0] for r in res)
    seqs = []
    for body in bodies:
        toks = []
        for o in _ndjson_objs(body):
            toks.extend(int(t) for t in o.get("tokens") or [])
        seqs.append(toks)
    return seqs


def _stream_token_times(port, payload, timeout=600.0):
    """One streaming request; returns (tokens, arrival times) with one
    wall-clock stamp PER TOKEN (a multi-token chunk stamps all its
    tokens at the chunk's arrival). Mean TPOT over the stream is
    (t_last - t_first) / (n - 1) — per-gap medians would undercount
    when the server coalesces tokens into one write."""
    import socket

    s = socket.create_connection(("127.0.0.1", port), timeout=timeout)
    head = ("POST /generate HTTP/1.1\r\nHost: 127.0.0.1\r\n"
            "Content-Type: application/json\r\n"
            f"Content-Length: {len(payload)}\r\n\r\n").encode()
    s.sendall(head + payload)
    buf = b""
    toks, times = [], []
    deadline = time.time() + timeout
    try:
        while time.time() < deadline:
            piece = s.recv(1 << 16)
            if not piece:
                break
            now = time.time()
            buf += piece
            objs = _ndjson_objs(buf)
            fresh = []
            for o in objs:
                fresh.extend(int(t) for t in o.get("tokens") or [])
            while len(toks) < len(fresh):
                toks.append(fresh[len(toks)])
                times.append(now)
            if any(o.get("done") or o.get("error") for o in objs):
                break
    finally:
        s.close()
    assert toks, f"stream produced no tokens: {buf[:300]!r}"
    return toks, times


def _mean_tpot_ms(times):
    if len(times) < 2 or times[-1] <= times[0]:
        return 0.0
    return (times[-1] - times[0]) * 1e3 / (len(times) - 1)


def run_affinity(config=None, families=None, per_family=None,
                 slots=None, new_tokens=None, kv_int8=False,
                 weights_int8=False, smoke=False) -> dict:
    """Fleet prefix-affinity gate: N replicas behind the real LB, the
    prefix-share workload (shared system prompts + unique tails) fired
    THROUGH the LB.

    The claim under test: consistent-hash routing on the chunk-aligned
    prefix digest turns N per-replica prefix caches into one fleet
    cache. With plain least-load routing a family's requests spread —
    only the ~1/N that happen to land on the replica holding the
    prefix hit. With affinity every family pins to its rendezvous
    replica: after one cold request per family the measured wave is
    all hits.

    Phases (fleet shared, families fresh per phase so each starts
    cold): (A) affinity OFF control — seed one request per family,
    then the full wave; fleet hit rate lands near 1/N. (B) affinity
    ON — same shape; gate: hit rate >= 0.8. (C) affinity ON cold-vs-
    warm TTFT on a third family set — the same payload wave twice;
    gates: warm median TTFT >= 30% below cold, tokens bit-identical
    between the passes. Hit rates are read from the engines' own
    prefix tallies (the replicas live in-process), so the gate
    measures real cache behavior, not routing bookkeeping.

    Load spill is pinned OFF (SKYTPU_LB_SPILL high) for the measured
    waves: this bench isolates PLACEMENT; the spill rule has its own
    tier-1 coverage (tests/test_disagg.py).
    """
    import json as _json
    import tempfile
    import threading

    import jax
    import numpy as np

    on_cpu = jax.default_backend() == "cpu"
    if config is None:
        config = "llama3-tiny" if on_cpu else "llama3-400m"
    small = smoke or on_cpu
    n_replicas = 3
    families = families or (3 if small else 6)
    per_family = per_family or (4 if small else 8)
    slots = slots or (per_family if small else 16)
    new_tokens = new_tokens or (4 if small else 32)
    # The system prompt must dwarf the fixed per-request cost (HTTP +
    # admission + dispatch, ~tens of ms on a CPU host): the 30%-below-
    # cold TTFT gate measures prefill compute SAVED, and a too-short
    # prefix would drown the saving in constant overhead.
    system_len = 120 if small else 768
    tail_len = 4 if small else 48
    chunk = 8 if small else 256
    bucket = system_len + tail_len
    log(f"affinity gate: {config} replicas={n_replicas} "
        f"families={families} per_family={per_family} "
        f"system_len={system_len} chunk={chunk}")

    home = tempfile.mkdtemp(prefix="skytpu-bench-affinity-")
    os.environ["SKYPILOT_TPU_HOME"] = home
    env_prev = {k: os.environ.get(k)
                for k in ("SKYTPU_PREFILL_CHUNK", "SKYTPU_LB_SPILL",
                          "SKYTPU_LB_PREFIX_AFFINITY")}
    os.environ["SKYTPU_PREFILL_CHUNK"] = str(chunk)
    os.environ["SKYTPU_LB_SPILL"] = str(4096)

    from skypilot_tpu import chaos
    from skypilot_tpu.infer import server as srv
    from skypilot_tpu.serve import load_balancer, serve_state
    from skypilot_tpu.serve.serve_state import ReplicaStatus

    chaos.deactivate()
    load_balancer._adapter_cache.clear()
    load_balancer._disagg_cache.clear()

    rng = np.random.default_rng(0)
    cfg = None
    engines, models, httpds = [], [], []
    lb_port = _free_port()
    serve_state.add_service("bench-affinity", {}, {}, lb_port)
    for i in range(n_replicas):
        cfg, engine = _build_engine(config, slots, bucket, new_tokens,
                                    kv_int8, weights_int8,
                                    buckets=(bucket,),
                                    prefill_chunk=chunk,
                                    prefix_pool=4 * families)
        port = _free_port()
        model, httpd = srv.serve(engine, host="127.0.0.1", port=port,
                                 max_burst=slots, open_burst=4,
                                 coalesce_s=0.0)
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()
        engines.append(engine)
        models.append(model)
        httpds.append(httpd)
        serve_state.upsert_replica("bench-affinity", i + 1,
                                   f"bench-affinity-{i + 1}",
                                   ReplicaStatus.READY,
                                   f"http://127.0.0.1:{port}")
    for model in models:
        assert model._ready.wait(timeout=600), "model warmup timed out"
    lb = load_balancer._ThreadingServer(
        ("127.0.0.1", lb_port),
        load_balancer.make_handler("bench-affinity",
                                   load_balancer.LeastLoadPolicy()))
    threading.Thread(target=lb.serve_forever, daemon=True).start()

    def mk_families(n):
        """n fresh prefix families: a shared system prompt + unique
        tails per member; every prompt exactly ``bucket`` tokens."""
        out = []
        for _ in range(n):
            system = rng.integers(1, cfg.vocab_size,
                                  system_len).tolist()
            out.append([system + rng.integers(1, cfg.vocab_size,
                                              tail_len).tolist()
                        for _ in range(per_family)])
        return out

    def payload(p):
        return _json.dumps({"tokens": p,
                            "max_new_tokens": new_tokens}).encode()

    def fleet_hits():
        return (sum(e._prefix_hit_n for e in engines),
                sum(e._prefix_miss_n for e in engines))

    def measured_wave(fam_set):
        """Seed one request per family (fleet warms), then the full
        interleaved wave; returns the wave's fleet hit rate."""
        _wave_token_seqs(lb_port, [payload(f[0]) for f in fam_set])
        wave = [payload(f[i]) for i in range(1, per_family)
                for f in fam_set]
        h0, m0 = fleet_hits()
        _wave_token_seqs(lb_port, wave)
        h1, m1 = fleet_hits()
        seen = (h1 - h0) + (m1 - m0)
        return (h1 - h0) / max(seen, 1)

    try:
        # Warmup: compile every program the measured waves reach —
        # cold store, warm pool-load, and the concurrent wave shapes —
        # on every replica (direct, bypassing routing).
        warm_fams = mk_families(1)
        for url in serve_state.ready_urls("bench-affinity"):
            port = int(url.rsplit(":", 1)[1])
            for _ in range(2):
                _wave_token_seqs(port, [payload(p)
                                        for p in warm_fams[0]])

        os.environ["SKYTPU_LB_PREFIX_AFFINITY"] = "0"
        control_hit_rate = measured_wave(mk_families(families))
        os.environ["SKYTPU_LB_PREFIX_AFFINITY"] = "1"
        affinity_hit_rate = measured_wave(mk_families(families))
        log(f"affinity: fleet hit rate {affinity_hit_rate:.2f} "
            f"(control {control_hit_rate:.2f}, ~1/{n_replicas} "
            f"expected)")

        # Cold-vs-warm TTFT + parity: one request per fresh family,
        # the identical wave twice. Streaming: _client_wave stamps
        # TTFT at the first BODY byte, which for a blocking response
        # is the whole JSON (TTFT would absorb every decode token).
        ttft_fams = mk_families(max(families, 3))
        ttft_wave = [_json.dumps({"tokens": f[0],
                                  "max_new_tokens": new_tokens,
                                  "stream": True}).encode()
                     for f in ttft_fams]
        cold_ttfts, warm_ttfts = [], []
        cold_seqs = _wave_token_seqs(lb_port, ttft_wave,
                                     ttfts=cold_ttfts)
        warm_seqs = _wave_token_seqs(lb_port, ttft_wave,
                                     ttfts=warm_ttfts)
        cold_ttft = _median(cold_ttfts) * 1e3
        warm_ttft = _median(warm_ttfts) * 1e3
        parity_ok = warm_seqs == cold_seqs
        log(f"affinity TTFT: cold={cold_ttft:.1f}ms "
            f"warm={warm_ttft:.1f}ms parity={parity_ok}")
    finally:
        lb.shutdown()
        for httpd in httpds:
            httpd.shutdown()
        for model in models:
            model.shutdown()
        for k, v in env_prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    gate_ok = (affinity_hit_rate >= 0.8 and parity_ok
               and warm_ttft <= 0.7 * cold_ttft)
    return {
        "gate_ok": bool(gate_ok),
        "affinity_hit_rate": round(affinity_hit_rate, 3),
        "control_hit_rate": round(control_hit_rate, 3),
        "cold_ttft_ms": round(cold_ttft, 2),
        "warm_ttft_ms": round(warm_ttft, 2),
        "warm_below_70pct_of_cold": bool(warm_ttft <= 0.7 * cold_ttft),
        "parity_ok": bool(parity_ok),
        "replicas": n_replicas,
        "families": families,
        "per_family": per_family,
        "system_len": system_len,
        "prefill_chunk": chunk,
        "config": config,
        "kv_int8": kv_int8,
        "weights_int8": weights_int8,
    }


def run_affinity_smoke() -> dict:
    """CI-sized prefix-affinity pass (tier-1 wiring in
    tests/test_disagg.py covers the routing pieces; this gates the
    fleet-cache economics end to end)."""
    return run_affinity(smoke=True)


def run_disagg(config=None, requests=None, slots=4, new_tokens=None,
               smoke=False) -> dict:
    """Disaggregated prefill/decode serving gate, end to end over HTTP
    through the real LB (docs/serving.md §Disaggregated serving).

    **Parity sweep** — for each of {fp32, int8 KV} x {spec on/off}: a
    1-prefill + 2-decode fleet; every request through the LB runs
    chunked admission on the prefill tier, hands its paged KV blocks
    to a decode replica, and must return tokens BIT-IDENTICAL to the
    same prompt served single-tier (direct to a decode replica). The
    handoff counter must account for every request.

    **Isolation** — on the fp32 fleet: decode-tier streaming TPOT
    while the prefill tier chews a continuous heavy prefill load,
    vs the same engines' idle TPOT, vs a single-tier fleet (same 3
    replicas, no tiers) interleaving both workloads. Gate (TPU only —
    CPU wall-clock is reported, never gated): loaded/idle <= 1.1x.

    **Introspection** — after warmup the fleet's compile watches are
    armed: the measured phases (streams, prefill load, chaos retries)
    must compile NOTHING on either tier.

    **Fault tolerance** — a seeded ``handoff.transfer`` fault kills a
    decode replica's transfer mid-stream; the LB retries the export on
    the survivor. Gates: every stream completes bit-identical to the
    fault-free control (zero lost requests — _client_wave raises on
    any short/errored stream), and the prefill tier ends with its
    block pool exactly equal to its resident refcounted prefixes
    (zero leaked blocks).
    """
    import gc
    import json as _json
    import tempfile
    import threading

    import jax
    import numpy as np

    on_cpu = jax.default_backend() == "cpu"
    if config is None:
        config = "llama3-tiny" if on_cpu else "llama3-400m"
    small = smoke or on_cpu
    requests = requests or (4 if small else 12)
    new_tokens = new_tokens or (6 if small else 32)
    probe_tokens = 24 if small else 64
    prompt_len = 12 if small else 256
    load_len = 48 if small else 1024
    chunk = 8 if small else 256
    buckets = (prompt_len + new_tokens, load_len)
    max_prompt = load_len
    log(f"disagg gate: {config} tiers=1p+2d slots={slots} "
        f"requests={requests} new_tokens={new_tokens}")

    home = tempfile.mkdtemp(prefix="skytpu-bench-disagg-")
    os.environ["SKYPILOT_TPU_HOME"] = home
    env_prev = {k: os.environ.get(k)
                for k in ("SKYTPU_PREFILL_CHUNK", "SKYTPU_LB_SPILL")}
    os.environ["SKYTPU_PREFILL_CHUNK"] = str(chunk)

    from skypilot_tpu import chaos
    from skypilot_tpu.infer import engine as eng_mod
    from skypilot_tpu.infer import server as srv
    from skypilot_tpu.models import llama
    from skypilot_tpu.serve import load_balancer, serve_state
    from skypilot_tpu.serve.serve_state import ReplicaStatus

    chaos.deactivate()
    load_balancer._adapter_cache.clear()
    cfg = llama.CONFIGS[config]
    rng = np.random.default_rng(0)

    def build_fleet(tag, kv_int8_v, spec_k):
        """1 prefill + 2 decode replicas behind a fresh LB, registered
        as a disaggregated service."""
        params = llama.init_params(jax.random.key(0), cfg)
        engines, models, httpds, urls = [], [], [], []
        for _ in range(3):
            engine = eng_mod.InferenceEngine(
                params, cfg, n_slots=slots,
                max_len=max_prompt + probe_tokens + 8,
                prompt_buckets=buckets, kv_int8=kv_int8_v,
                prefill_chunk=chunk, prefix_pool=8 * requests,
                spec_k=spec_k)
            port = _free_port()
            model, httpd = srv.serve(engine, host="127.0.0.1",
                                     port=port, max_burst=slots,
                                     open_burst=4, coalesce_s=0.0)
            threading.Thread(target=httpd.serve_forever,
                             daemon=True).start()
            engines.append(engine)
            models.append(model)
            httpds.append(httpd)
            urls.append(f"http://127.0.0.1:{port}")
        for model in models:
            assert model._ready.wait(timeout=600), \
                "model warmup timed out"
        service = f"bench-disagg-{tag}"
        serve_state.add_service(
            service, {"disaggregation": {"prefill_replicas": 1,
                                         "decode_replicas": 2}},
            {}, 0)
        for i, tier in enumerate(("prefill", "decode", "decode")):
            serve_state.upsert_replica(service, i + 1,
                                       f"{service}-{i + 1}",
                                       ReplicaStatus.READY, urls[i],
                                       tier=tier)
        load_balancer._disagg_cache.clear()
        lb_port = _free_port()
        lb = load_balancer._ThreadingServer(
            ("127.0.0.1", lb_port),
            load_balancer.make_handler(
                service, load_balancer.LeastLoadPolicy()))
        threading.Thread(target=lb.serve_forever, daemon=True).start()
        return {"engines": engines, "models": models, "httpds": httpds,
                "urls": urls, "lb": lb, "lb_port": lb_port,
                "service": service}

    def teardown(fleet):
        fleet["lb"].shutdown()
        for httpd in fleet["httpds"]:
            httpd.shutdown()
        for model in fleet["models"]:
            model.shutdown()
        serve_state.remove_service(fleet["service"])

    def payload(p, n, stream=False):
        d = {"tokens": p, "max_new_tokens": n}
        if stream:
            d["stream"] = True
        return _json.dumps(d).encode()

    def handoff_ok_count():
        return load_balancer.LB_HANDOFFS.labels(result="ok").value

    prompts = [rng.integers(1, cfg.vocab_size, prompt_len).tolist()
               for _ in range(requests)]

    def parity_pass(fleet):
        """Via-LB wave vs single-tier direct (one decode replica);
        returns (parity_ok, handoffs) for this fleet."""
        decode_port = int(fleet["urls"][1].rsplit(":", 1)[1])
        _wave_token_seqs(fleet["lb_port"],
                         [payload(p, new_tokens) for p in prompts])
        ref = _wave_token_seqs(decode_port,
                               [payload(p, new_tokens)
                                for p in prompts])
        h0 = handoff_ok_count()
        got = _wave_token_seqs(fleet["lb_port"],
                               [payload(p, new_tokens)
                                for p in prompts])
        handoffs = handoff_ok_count() - h0
        return got == ref, int(handoffs)

    variants = [("fp32", False, 0), ("int8kv", True, 0),
                ("spec", False, 3), ("int8kv_spec", True, 3)]
    variant_parity = {}
    for tag, kv_v, spec_v in variants[1:]:
        fleet = build_fleet(tag, kv_v, spec_v)
        try:
            ok, handoffs = parity_pass(fleet)
            variant_parity[tag] = {"parity_ok": bool(ok),
                                   "handoffs": handoffs}
            log(f"disagg parity [{tag}]: parity={ok} "
                f"handoffs={handoffs}/{requests}")
        finally:
            teardown(fleet)
        gc.collect()

    # Main fp32 fleet: parity + isolation + compile watch + chaos.
    fleet = build_fleet("fp32", False, 0)
    engines = fleet["engines"]
    lb_port = fleet["lb_port"]
    try:
        ok, handoffs = parity_pass(fleet)
        variant_parity["fp32"] = {"parity_ok": bool(ok),
                                  "handoffs": handoffs}
        log(f"disagg parity [fp32]: parity={ok} "
            f"handoffs={handoffs}/{requests}")

        probe_prompt = rng.integers(1, cfg.vocab_size,
                                    prompt_len).tolist()
        probe = payload(probe_prompt, probe_tokens, stream=True)
        load_prompts = [rng.integers(1, cfg.vocab_size,
                                     load_len).tolist()
                        for _ in range(max(requests, 4))]
        load_wave = [payload(p, 2) for p in load_prompts]

        # Single-tier baseline fleet state: the SAME replicas, no
        # tiers — decode streams and heavy prefill interleave on the
        # same engines (registered second so its warm caches don't
        # perturb the disagg measurements, which run first).
        serve_state.add_service("bench-disagg-single", {}, {}, 0)
        for i, url in enumerate(fleet["urls"]):
            serve_state.upsert_replica("bench-disagg-single", i + 1,
                                       f"bds-{i + 1}",
                                       ReplicaStatus.READY, url)
        single_lb_port = _free_port()
        single_lb = load_balancer._ThreadingServer(
            ("127.0.0.1", single_lb_port),
            load_balancer.make_handler(
                "bench-disagg-single",
                load_balancer.LeastLoadPolicy()))
        threading.Thread(target=single_lb.serve_forever,
                         daemon=True).start()

        # Warm every program the measured phases reach — stream +
        # handoff paths on both decode replicas, the heavy-prefill
        # shapes, and the single-tier stream — then arm the watches:
        # anything compiling after this line is a gate failure.
        for _ in range(2):
            _stream_token_times(lb_port, probe)
            _wave_token_seqs(lb_port, load_wave)
            _stream_token_times(single_lb_port, probe)
            _wave_token_seqs(single_lb_port, load_wave)
        chaos.configure({"seed": 5, "faults": [
            {"point": "handoff.transfer", "times": 1}]})
        _stream_token_times(lb_port, probe)
        chaos.deactivate()
        for e in engines:
            e.compile_watch.declare_warm()

        def measured_stream(port, background):
            """Stream TPOT while (optionally) a thread keeps the fleet
            under continuous heavy prefill load."""
            stop = threading.Event()

            def pump():
                n = 0
                while not stop.is_set() and n < 50:
                    _wave_token_seqs(port, load_wave)
                    n += 1

            t = None
            if background:
                t = threading.Thread(target=pump, daemon=True)
                t.start()
                time.sleep(0.05)   # load in flight before the probe
            try:
                _, times = _stream_token_times(port, probe)
            finally:
                stop.set()
                if t is not None:
                    t.join(timeout=600)
            return _mean_tpot_ms(times)

        idle_tpot = measured_stream(lb_port, background=False)
        loaded_tpot = measured_stream(lb_port, background=True)
        single_idle_tpot = measured_stream(single_lb_port,
                                           background=False)
        single_loaded_tpot = measured_stream(single_lb_port,
                                             background=True)
        isolation_ratio = loaded_tpot / max(idle_tpot, 1e-9)
        single_ratio = single_loaded_tpot / max(single_idle_tpot,
                                                1e-9)
        log(f"disagg isolation: decode TPOT idle={idle_tpot:.2f}ms "
            f"loaded={loaded_tpot:.2f}ms (x{isolation_ratio:.2f}); "
            f"single-tier x{single_ratio:.2f}")

        # Chaos: a decode replica dies mid-handoff; the export retries
        # on the survivor. Streams must come back bit-identical.
        chaos_wave = [payload(p, new_tokens, stream=True)
                      for p in prompts]
        want = _wave_token_seqs(lb_port, chaos_wave)
        retry0 = load_balancer.LB_HANDOFFS.labels(
            result="retry").value
        chaos.configure({"seed": 3, "faults": [
            {"point": "handoff.transfer", "times": 1}]})
        got = _wave_token_seqs(lb_port, chaos_wave)
        chaos_fired = len(chaos.injector().fired)
        chaos.deactivate()
        chaos_retries = load_balancer.LB_HANDOFFS.labels(
            result="retry").value - retry0
        chaos_parity = got == want
        log(f"disagg chaos: parity={chaos_parity} "
            f"fired={chaos_fired} retries={chaos_retries}")

        unexpected = [k for e in engines
                      for k in e.compile_watch.unexpected]
        # Donor audit: every prefill-tier block is owned by a resident
        # refcounted prefix — handoffs (including the chaos-retried
        # one) left nothing dangling.
        pf = engines[0]
        resident = (sum(len(p) for p in pf._prefix_index.payloads())
                    if pf._prefix_index else 0)
        leaked = pf.blocks_used - resident
        single_lb.shutdown()
        serve_state.remove_service("bench-disagg-single")
    finally:
        chaos.deactivate()
        teardown(fleet)
        for k, v in env_prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    parity_all = all(v["parity_ok"] for v in variant_parity.values())
    handoffs_all = all(v["handoffs"] == requests
                      for v in variant_parity.values())
    gate_ok = (parity_all and handoffs_all and chaos_parity
               and chaos_fired >= 1 and chaos_retries >= 1
               and not unexpected and leaked == 0
               and (on_cpu or isolation_ratio <= 1.1))
    return {
        "gate_ok": bool(gate_ok),
        "parity_ok": bool(parity_all),
        "variants": variant_parity,
        "handoffs_accounted": bool(handoffs_all),
        "idle_tpot_ms": round(idle_tpot, 3),
        "loaded_tpot_ms": round(loaded_tpot, 3),
        "isolation_ratio": round(isolation_ratio, 3),
        "single_tier_ratio": round(single_ratio, 3),
        # The <= 1.1x isolation gate binds on TPU only (CPU decode is
        # compute-bound: the probe stream and the prefill pump share
        # cores, so wall-clock there measures the host, not the tier
        # split); the ratio is still reported for the record.
        "isolation_gated": bool(not on_cpu),
        "chaos_parity_ok": bool(chaos_parity),
        "chaos_fired": int(chaos_fired),
        "chaos_retries": int(chaos_retries),
        "lost_requests": 0,   # structural: _client_wave raises
        "leaked_blocks": int(leaked),
        "unexpected_compiles": len(unexpected),
        "unexpected": unexpected,
        "requests": requests,
        "new_tokens": new_tokens,
        "config": config,
    }


def run_disagg_smoke() -> dict:
    """CI-sized disaggregation pass (tier-1 wiring in
    tests/test_disagg.py covers the protocol; this gates the fleet
    behavior — parity sweep, compile watch, chaos — end to end)."""
    return run_disagg(smoke=True)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default=None)
    ap.add_argument("--requests", type=int, default=16)
    ap.add_argument("--slots", type=int, default=16)
    ap.add_argument("--prompt-len", type=int, default=None,
                    help="pin every prompt to this length (default: "
                         "realistic 512-1024 mix for HTTP runs, 96 "
                         "for --engine-only)")
    ap.add_argument("--new-tokens", type=int, default=64)
    ap.add_argument("--max-burst", type=int, default=32)
    ap.add_argument("--open-burst", type=int, default=4)
    ap.add_argument("--repeats", type=int, default=1,
                    help="timed runs on the warm server; the summary "
                         "reports median-of-runs and the worst run")
    ap.add_argument("--stagger", type=float, default=0.0,
                    help="seconds between request arrivals (0 = one "
                         "instantaneous burst)")
    ap.add_argument("--kv-int8", action="store_true")
    ap.add_argument("--weights-int8", action="store_true")
    ap.add_argument("--admit-wave", type=int, default=None,
                    help="cap admission waves: early waves' first "
                         "tokens stream (HTTP) / stamp TTFT (engine) "
                         "while later waves prefill")
    ap.add_argument("--engine-only", action="store_true",
                    help="bench the engine directly (no HTTP/LB; "
                         "engine-internal TTFT)")
    ap.add_argument("--prefix-share", action="store_true",
                    help="prefix-share workload (shared system prompt "
                         "+ unique tails): warm-vs-cold TTFT, greedy "
                         "parity, and the decode-interference report")
    ap.add_argument("--prefix-pool", type=int, default=8)
    ap.add_argument("--prefill-chunk", type=int, default=None)
    ap.add_argument("--smoke", action="store_true",
                    help="CI-sized prefix-share pass (tier-1 "
                         "regression guard for the chunk scheduler)")
    ap.add_argument("--occupancy", action="store_true",
                    help="high-occupancy sweep: max concurrent slots "
                         "at equal KV HBM, paged vs contiguous, with "
                         "greedy parity (the paged-cache headline)")
    ap.add_argument("--spec", action="store_true",
                    help="speculative-decoding bench: the NON-"
                         "repetitive workload with the model-backed "
                         "drafter (pipelined + sync + the honest "
                         "n-gram wash column) as the headline, plus "
                         "the repetition-heavy secondary n-gram "
                         "column and the oracle-draft ceiling; greedy "
                         "parity asserted everywhere (combine with "
                         "--smoke for the CI-sized pass)")
    ap.add_argument("--spec-k", type=int, default=4,
                    help="draft length K for --spec")
    ap.add_argument("--span", action="store_true",
                    help="span-bucketed decode attention bench: "
                         "span-on vs full-view decode TPOT on the "
                         "same engine (short active conversations on "
                         "a long-max_len engine), greedy parity "
                         "asserted (combine with --smoke for the "
                         "CI-sized pass)")
    ap.add_argument("--kernel", action="store_true",
                    help="Pallas paged decode-attention kernel bench: "
                         "kernel-vs-gather decode TPOT on the same "
                         "engine at low occupancy (where the gather "
                         "transient dominates), greedy parity "
                         "asserted; combined with --span/--occupancy "
                         "it re-runs THOSE benches with the kernel "
                         "enabled instead (combine with --smoke for "
                         "the CI-sized pass)")
    ap.add_argument("--qos", action="store_true",
                    help="multi-tenant QoS bench: background-tenant "
                         "TPOT/TTFT isolation under a hot tenant "
                         "(WFQ vs FIFO control) and preemption-by-"
                         "eviction greedy parity with the allocator "
                         "audit (combine with --smoke for the "
                         "CI-sized pass)")
    ap.add_argument("--adapters", action="store_true",
                    help="multi-LoRA adapter-catalog bench: N-adapter "
                         "mixed-workload decode TPOT vs a single-"
                         "adapter baseline on the same engine, greedy "
                         "parity vs per-adapter sequential runs, and "
                         "zero unexpected compiles while adapters "
                         "hot-load/evict mid-traffic (combine with "
                         "--smoke for the CI-sized pass)")
    ap.add_argument("--n-adapters", type=int, default=8,
                    help="fine-tunes in the mixed workload for "
                         "--adapters (pool sized to hold them; the "
                         "churn phase registers 2x as many)")
    ap.add_argument("--flight", action="store_true",
                    help="flight recorder + compile watch bench: the "
                         "full mixed workload (chunked admission + "
                         "spec decode + span regrouping, paged + "
                         "contiguous) with warm-grid startup — gates "
                         "zero unexpected compiles in the timed "
                         "window, per-burst record coverage, and the "
                         "recorder-off no-op guard (combine with "
                         "--smoke for the CI-sized pass)")
    ap.add_argument("--failover", action="store_true",
                    help="serving fault-tolerance gate: two live "
                         "replicas behind the real LB; a seeded "
                         "engine.dispatch fault (crash -> reset -> "
                         "bit-identical resume) then a seeded "
                         "replica.kill mid-stream (LB failover -> "
                         "gapless stitched stream) — gates parity "
                         "with the fault-free control and zero lost "
                         "requests (combine with --smoke for the "
                         "CI-sized pass)")
    ap.add_argument("--affinity", action="store_true",
                    help="fleet prefix-affinity gate: N replicas "
                         "behind the real LB, prefix families routed "
                         "by consistent hash on the chunk-aligned "
                         "prefix digest — gates fleet prefix hit-rate "
                         ">= 0.8 (vs the ~1/N least-load control), "
                         "warm TTFT >= 30% below cold, and greedy "
                         "parity (combine with --smoke for the "
                         "CI-sized pass)")
    ap.add_argument("--disagg", action="store_true",
                    help="disaggregated prefill/decode gate: 1-prefill"
                         " + 2-decode fleet behind the real LB — "
                         "gates two-tier output bit-identical to "
                         "single-tier across {fp32, int8 KV} x {spec "
                         "on/off}, decode-tier TPOT isolation under "
                         "heavy prefill (<= 1.1x idle, TPU only), "
                         "zero unexpected compiles on either tier, "
                         "and the handoff.transfer chaos retry with "
                         "zero lost requests / zero leaked blocks "
                         "(combine with --smoke for the CI-sized "
                         "pass)")
    args = ap.parse_args()
    if args.affinity:
        r = run_affinity(config=args.config, kv_int8=args.kv_int8,
                         weights_int8=args.weights_int8,
                         smoke=args.smoke)
        print(json.dumps({
            "metric": "serve_affinity_hit_rate",
            "value": r["affinity_hit_rate"],
            "unit": "fleet_prefix_hit_rate",
            **{k: r[k] for k in (
                "gate_ok", "control_hit_rate", "cold_ttft_ms",
                "warm_ttft_ms", "warm_below_70pct_of_cold",
                "parity_ok", "replicas", "families", "per_family",
                "config")},
        }))
        if not r["gate_ok"]:
            sys.exit(1)
        return
    if args.disagg:
        r = run_disagg(config=args.config, smoke=args.smoke)
        print(json.dumps({
            "metric": "serve_disagg_isolation_ratio",
            "value": r["isolation_ratio"],
            "unit": "x_decode_tpot_loaded_vs_idle",
            **{k: r[k] for k in (
                "gate_ok", "parity_ok", "variants",
                "handoffs_accounted", "single_tier_ratio",
                "isolation_gated", "chaos_parity_ok", "chaos_fired",
                "chaos_retries", "lost_requests", "leaked_blocks",
                "unexpected_compiles", "requests", "config")},
        }))
        if not r["gate_ok"]:
            sys.exit(1)
        return
    if args.failover:
        r = run_failover(config=args.config, kv_int8=args.kv_int8,
                         weights_int8=args.weights_int8,
                         smoke=args.smoke)
        print(json.dumps({
            "metric": "serve_failover_gate",
            "value": 1.0 if r["gate_ok"] else 0.0,
            "unit": "bool",
            **{k: r[k] for k in (
                "crash_parity_ok", "kill_parity_ok", "recoveries",
                "trailer_recoveries", "failovers",
                "trailer_failovers", "lost_requests", "requests",
                "new_tokens", "config")},
        }))
        if not r["gate_ok"]:
            sys.exit(1)
        return
    if args.adapters:
        r = run_adapters(config=args.config,
                         n_adapters=args.n_adapters,
                         kv_int8=args.kv_int8,
                         weights_int8=args.weights_int8,
                         spec_k=(args.spec_k if args.spec else 0),
                         smoke=args.smoke)
        print(json.dumps({
            "metric": "serve_adapter_overhead",
            "value": r["overhead_ratio"],
            "unit": "x_mixed_decode_tpot_vs_single",
            **{k: r[k] for k in (
                "tpot_single_ms", "tpot_mixed_ms", "parity_ok",
                "hot_loads", "evictions", "unexpected_compiles",
                "n_adapters", "rank", "backend", "config")},
        }))
        return
    if args.qos:
        r = run_qos(config=args.config, kv_int8=args.kv_int8,
                    weights_int8=args.weights_int8, smoke=args.smoke)
        print(json.dumps({
            "metric": "serve_qos_fairness_ratio",
            "value": r["fairness_ratio"],
            "unit": "x_bg_tpot_p99_vs_idle",
            **{k: r[k] for k in (
                "bg_tpot_idle_p99_ms", "bg_tpot_contended_p99_ms",
                "bg_ttft_wfq_ratio", "bg_ttft_fifo_ratio",
                "sched_parity_ok", "preempt_parity_ok",
                "preemptions", "preempt_resumed_rows", "config")},
        }))
        return
    if args.flight:
        r = run_flight(config=args.config, kv_int8=args.kv_int8,
                       weights_int8=args.weights_int8,
                       smoke=args.smoke)
        print(json.dumps({
            "metric": "serve_unexpected_compiles",
            "value": r["unexpected_compiles"],
            "unit": "programs_compiled_in_timed_window",
            **{k: r[k] for k in (
                "warmup_compile_s", "coverage_ok", "parity_ok",
                "calibration_parity_ok", "calibration_samples",
                "n_records", "overhead_ratio", "layouts", "config")},
        }))
        return
    if args.span:
        r = run_span(config=args.config, kv_int8=args.kv_int8,
                     weights_int8=args.weights_int8,
                     smoke=args.smoke, kv_kernel=args.kernel)
        print(json.dumps({
            "metric": "serve_span_speedup",
            "value": r["speedup"],
            "unit": "x_decode_tok_s_vs_full_view",
            **{k: r[k] for k in (
                "tpot_full_ms", "tpot_span_ms", "rows_full",
                "rows_span", "rows_ratio", "span_ladder",
                "n_span_programs", "parity_ok", "kv_kernel",
                "config")},
        }))
        return
    if args.kernel and not args.occupancy:
        # --kernel alone = the kernel-vs-gather bench; combined with
        # --span/--occupancy those branches run THEIR bench with the
        # kernel enabled instead (--span is dispatched above,
        # --occupancy below).
        r = run_kernel(config=args.config, kv_int8=args.kv_int8,
                       weights_int8=args.weights_int8,
                       spec_k=(args.spec_k if args.spec else 0),
                       smoke=args.smoke)
        print(json.dumps({
            "metric": "serve_kernel_speedup",
            "value": r["speedup"],
            "unit": "x_decode_tok_s_vs_gather",
            **{k: r[k] for k in (
                "tpot_gather_ms", "tpot_kernel_ms", "parity_ok",
                "kernel_programs_ok", "backend", "active_requests",
                "slots", "span_ladder", "config")},
        }))
        return
    if args.spec:
        r = run_spec(config=args.config, spec_k=args.spec_k,
                     kv_int8=args.kv_int8,
                     weights_int8=args.weights_int8,
                     smoke=args.smoke)
        print(json.dumps({
            "metric": "serve_spec_model_speedup",
            "value": r["model_speedup"],
            "unit": "x_decode_tok_s_vs_spec_off",
            **{k: r[k] for k in (
                "model_tpot_off_ms", "tpot_model_ms",
                "tpot_model_sync_ms", "pipeline_ratio",
                "model_accept_rate", "model_parity_ok",
                "overlap_ok", "draft_reuse_hits", "draft_layers",
                "ngram_nonrep_speedup", "ngram_nonrep_accept_rate",
                "tpot_off_ms", "tpot_spec_ms", "tpot_oracle_ms",
                "speedup", "oracle_speedup", "accept_rate",
                "oracle_accept_rate", "parity_ok",
                "oracle_parity_ok", "spec_k", "config", "backend")},
        }))
        return
    if args.occupancy:
        r = run_occupancy(config=args.config, kv_int8=args.kv_int8,
                          weights_int8=args.weights_int8,
                          kv_kernel=args.kernel)
        print(json.dumps({
            "metric": "serve_occupancy_x",
            "value": r["occupancy_x"],
            "unit": "x_slots_at_equal_hbm",
            **{k: r[k] for k in (
                "kv_hbm_bytes", "paged_slots", "contiguous_slots",
                "blocks_per_token", "kv_block", "parity_ok",
                "occupancy_regressed", "kv_kernel", "config")},
        }))
        return
    if args.smoke or args.prefix_share:
        if args.smoke:
            r = run_smoke()
        else:
            r = run_prefix_share(
                config=args.config, requests=args.requests,
                slots=args.slots, new_tokens=args.new_tokens,
                max_burst=args.max_burst,
                prefill_chunk=args.prefill_chunk,
                prefix_pool=args.prefix_pool,
                kv_int8=args.kv_int8, weights_int8=args.weights_int8)
        print(json.dumps({
            "metric": "serve_prefix_warm_ttft",
            "value": r["warm_ttft_ms"],
            "unit": "ms",
            "cold_ttft_ms": r["cold_ttft_ms"],
            "warm_speedup": r["warm_speedup"],
            "parity_ok": r["parity_ok"],
            "hit_rate": r["hit_rate"],
            "decode_stall_p99_ms": r["decode_stall_p99_ms"],
            "interference": r["interference"],
            "config": r["config"],
        }))
        return
    if args.engine_only:
        r = run(config=args.config, requests=args.requests,
                slots=args.slots, prompt_len=args.prompt_len or 96,
                new_tokens=args.new_tokens, max_burst=args.max_burst,
                kv_int8=args.kv_int8, weights_int8=args.weights_int8,
                admit_wave=args.admit_wave)
    else:
        r = run_http(config=args.config, requests=args.requests,
                     slots=args.slots, prompt_len=args.prompt_len,
                     new_tokens=args.new_tokens,
                     max_burst=args.max_burst, kv_int8=args.kv_int8,
                     weights_int8=args.weights_int8,
                     admit_wave=args.admit_wave,
                     open_burst=args.open_burst,
                     repeats=args.repeats, stagger_s=args.stagger)
    out = {
        "metric": "serve_median_ttft",
        "value": r["median_ttft_ms"],
        "unit": "ms",
        "vs_baseline": r["vs_baseline_ttft"],
        "output_tok_per_s": r["out_tok_s"],
        "req_per_s": r["req_per_s"],
        "config": r["config"],
        "kv_int8": r["kv_int8"],
        "weights_int8": r["weights_int8"],
    }
    if "p99_ttft_ms" in r:
        out["p99_ttft_ms"] = r["p99_ttft_ms"]
        out["transport"] = r["transport"]
    print(json.dumps(out))


if __name__ == "__main__":
    main()
