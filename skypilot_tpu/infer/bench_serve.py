"""Serving benchmark: TTFT + token throughput on the local accelerator.

Prints ONE JSON line, same contract as the repo-root bench.py:
  {"metric": "serve_median_ttft", "value": ..., "unit": "ms",
   "vs_baseline": ...}

vs_baseline compares against the reference's JetStream anchor on TPU
(reference: examples/tpu/v6e/README.md — median TTFT 1829.33 ms,
2147.98 output tok/s for Llama-2-7B on v6e; BASELINE.md). Ratio > 1
means faster than baseline (baseline_ttft / our_ttft).

Usage: python -m skypilot_tpu.infer.bench_serve [--config llama3-400m]
       [--requests 16] [--slots 8] [--prompt-len 96] [--new-tokens 64]
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def log(*a):
    print(*a, file=sys.stderr, flush=True)


REF_TTFT_MS = 1829.33
REF_TOK_S = 2147.98


def run(config=None, requests=16, slots=16, prompt_len=96,
        new_tokens=64, max_burst=32, kv_int8=False,
        weights_int8=False) -> dict:
    """Run the serving benchmark; returns the metrics dict (also usable
    by the repo-root bench.py to fold serving numbers into its single
    JSON artifact)."""
    import jax
    import numpy as np

    from skypilot_tpu.infer import engine as eng
    from skypilot_tpu.models import llama

    on_cpu = jax.default_backend() == "cpu"
    if config is None:
        config = "llama3-tiny" if on_cpu else "llama3-400m"
    cfg = llama.CONFIGS[config]
    log(f"serve bench: {config} on {jax.devices()[0].device_kind}")

    max_len = prompt_len + new_tokens + 8
    if weights_int8:
        # Build int8 weights directly — the fp init of an 8B-class
        # config (32 GB) would never fit the chip that the int8 model
        # (8 GB) serves from.
        from skypilot_tpu.infer import kvcache
        params, qw = kvcache.random_quantized_params(cfg)
        e = eng.InferenceEngine(params, cfg, n_slots=slots,
                                max_len=max_len,
                                prompt_buckets=(prompt_len,),
                                kv_int8=kv_int8, qweights=qw)
    else:
        params = llama.init_params(jax.random.key(0), cfg)
        e = eng.InferenceEngine(params, cfg, n_slots=slots,
                                max_len=max_len,
                                prompt_buckets=(prompt_len,),
                                kv_int8=kv_int8)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, cfg.vocab_size, prompt_len).tolist()
               for _ in range(requests)]

    # Warmup: compile the full-wave admission program and the burst
    # decode programs at the measured run's own burst size.
    for p in [prompts[0]] * slots:
        e.add_request(p, max_new_tokens=new_tokens)
    e.run_to_completion(max_burst=max_burst)
    e.finished.clear()

    t0 = time.time()
    for p in prompts:
        e.add_request(p, max_new_tokens=new_tokens)
    done = e.run_to_completion(max_burst=max_burst)
    # Force a host sync so the wall clock is honest (axon relay:
    # block_until_ready does not synchronize; a host fetch does).
    float(e.cache["length"][0])
    wall = time.time() - t0

    ttfts = sorted((r.first_token_s - r.submit_s) * 1e3 for r in done)
    med_ttft = ttfts[len(ttfts) // 2]
    total_tokens = sum(len(r.tokens) for r in done)
    tok_s = total_tokens / wall
    req_s = len(done) / wall

    log(f"requests={len(done)} wall={wall:.2f}s median_ttft={med_ttft:.1f}ms "
        f"tok/s={tok_s:.1f} req/s={req_s:.2f}")
    return {
        "median_ttft_ms": round(med_ttft, 2),
        "out_tok_s": round(tok_s, 2),
        "req_per_s": round(req_s, 3),
        "vs_baseline_ttft": round(REF_TTFT_MS / max(med_ttft, 1e-9), 3),
        "config": config,
        "kv_int8": kv_int8,
        "weights_int8": weights_int8,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default=None)
    ap.add_argument("--requests", type=int, default=16)
    ap.add_argument("--slots", type=int, default=16)
    ap.add_argument("--prompt-len", type=int, default=96)
    ap.add_argument("--new-tokens", type=int, default=64)
    ap.add_argument("--max-burst", type=int, default=32)
    ap.add_argument("--kv-int8", action="store_true")
    ap.add_argument("--weights-int8", action="store_true")
    args = ap.parse_args()
    r = run(config=args.config, requests=args.requests, slots=args.slots,
            prompt_len=args.prompt_len, new_tokens=args.new_tokens,
            max_burst=args.max_burst, kv_int8=args.kv_int8,
            weights_int8=args.weights_int8)
    print(json.dumps({
        "metric": "serve_median_ttft",
        "value": r["median_ttft_ms"],
        "unit": "ms",
        "vs_baseline": r["vs_baseline_ttft"],
        "output_tok_per_s": r["out_tok_s"],
        "req_per_s": r["req_per_s"],
        "config": r["config"],
        "kv_int8": r["kv_int8"],
        "weights_int8": r["weights_int8"],
    }))


if __name__ == "__main__":
    main()
