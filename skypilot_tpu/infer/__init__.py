"""TPU-native inference: KV-cache decode + continuous-batching engine."""
