"""Continuous-batching inference engine (JetStream-equivalent).

Slot-based serving: a fixed pool of decode slots advances one token per
``step()`` for every active request, while new requests prefill into free
slots between steps. All device programs are compiled once per prompt
bucket — admission/eviction is host-side bookkeeping only; no shape ever
changes on device.

TTFT = one bucketed prefill (+ queue wait); steady-state throughput =
slots x decode rate. The orchestration mirrors JetStream's
prefill-insert-generate loop, which is what the reference benchmarks on
TPU (reference: examples/tpu/v6e/README.md §Serve — 11.42 req/s,
1829 ms median TTFT on v6e; BASELINE.md).
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from skypilot_tpu.infer import kvcache, sampling
from skypilot_tpu.models import llama


@dataclasses.dataclass
class Request:
    rid: int
    prompt: List[int]
    max_new_tokens: int
    tokens: List[int] = dataclasses.field(default_factory=list)
    slot: Optional[int] = None
    submit_s: float = 0.0
    first_token_s: Optional[float] = None
    done: bool = False
    eos_id: Optional[int] = None


def _bucket(n: int, buckets) -> int:
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"prompt length {n} exceeds max bucket {buckets[-1]}")


class InferenceEngine:
    """Single-model continuous-batching engine.

    Parameters live wherever the caller put them (replicated or
    TP-sharded under a mesh); the engine only compiles and schedules.
    """

    def __init__(self, params: llama.Params, cfg: llama.LlamaConfig,
                 n_slots: int = 8, max_len: int = 1024,
                 prompt_buckets: Tuple[int, ...] = (128, 512, 1024),
                 sampling_params: sampling.SamplingParams = sampling.SamplingParams(),
                 eos_id: Optional[int] = None, seed: int = 0):
        self.params = params
        self.cfg = cfg
        self.n_slots = n_slots
        self.max_len = max_len
        self.buckets = tuple(b for b in prompt_buckets if b <= max_len)
        self.sampling_params = sampling_params
        self.eos_id = eos_id
        self.cache = kvcache.init_cache(cfg, n_slots, max_len)
        self.rng = jax.random.key(seed)

        self.free_slots = list(range(n_slots))
        self.slot_req: Dict[int, Request] = {}
        self.waiting: List[Request] = []
        self.finished: List[Request] = []
        self._next_rid = 0

        sp = self.sampling_params

        @functools.partial(jax.jit, static_argnames=("bucket",))
        def _prefill(params, tokens, true_len, rng, *, bucket):
            del bucket
            prefix, logits = kvcache.prefill(params, tokens, true_len, cfg)
            tok = sampling.sample(logits, rng, sp)
            return prefix, tok

        # Donate the cache: the engine reassigns self.cache from the
        # output every call, so XLA can update the [L, slots, max_len,
        # G, hd] buffers in place instead of copying them per token.
        @functools.partial(jax.jit, donate_argnums=(0,))
        def _insert(cache, prefix, slot, true_len, first_token):
            return kvcache.insert(cache, prefix, slot, true_len, first_token)

        @functools.partial(jax.jit, donate_argnums=(1,))
        def _decode(params, cache, rng, active):
            cache, logits = kvcache.decode_step(params, cache, cfg)
            toks = sampling.sample(logits, rng, sp)
            cache = kvcache.commit_tokens(cache, toks, active)
            return cache, toks

        self._prefill_fn = _prefill
        self._insert_fn = _insert
        self._decode_fn = _decode

    # -- admission ---------------------------------------------------------

    def add_request(self, prompt: List[int],
                    max_new_tokens: int = 128) -> int:
        req = Request(rid=self._next_rid, prompt=list(prompt),
                      max_new_tokens=max_new_tokens, submit_s=time.time(),
                      eos_id=self.eos_id)
        self._next_rid += 1
        self.waiting.append(req)
        return req.rid

    def _admit(self) -> None:
        while self.waiting and self.free_slots:
            req = self.waiting.pop(0)
            slot = self.free_slots.pop(0)
            bucket = _bucket(len(req.prompt), self.buckets)
            padded = np.zeros((bucket,), np.int32)
            padded[:len(req.prompt)] = req.prompt
            self.rng, sub = jax.random.split(self.rng)
            prefix, tok = self._prefill_fn(
                self.params, jnp.asarray(padded),
                jnp.asarray(len(req.prompt), jnp.int32), sub, bucket=bucket)
            self.cache = self._insert_fn(
                self.cache, prefix, jnp.asarray(slot, jnp.int32),
                jnp.asarray(len(req.prompt), jnp.int32), tok)
            first = int(tok)
            req.slot = slot
            req.tokens.append(first)
            req.first_token_s = time.time()
            self.slot_req[slot] = req
            if self._req_finished(req, first):
                self._retire(req)

    # -- stepping ----------------------------------------------------------

    def _req_finished(self, req: Request, tok: int) -> bool:
        if req.eos_id is not None and tok == req.eos_id:
            return True
        if len(req.tokens) >= req.max_new_tokens:
            return True
        return len(req.prompt) + len(req.tokens) >= self.max_len

    def _retire(self, req: Request) -> None:
        req.done = True
        self.finished.append(req)
        if req.slot is not None:
            self.slot_req.pop(req.slot, None)
            self.free_slots.append(req.slot)
            self.cache["length"] = self.cache["length"].at[req.slot].set(0)
            req.slot = None

    def step(self) -> Dict[int, int]:
        """Admit waiting requests, decode one token per active slot.

        Returns {rid: token} emitted this step.
        """
        self._admit()
        if not self.slot_req:
            return {}
        active = np.zeros((self.n_slots,), bool)
        for s in self.slot_req:
            active[s] = True
        self.rng, sub = jax.random.split(self.rng)
        self.cache, toks = self._decode_fn(self.params, self.cache, sub,
                                           jnp.asarray(active))
        toks = np.asarray(toks)
        out: Dict[int, int] = {}
        for slot, req in list(self.slot_req.items()):
            tok = int(toks[slot])
            req.tokens.append(tok)
            out[req.rid] = tok
            if self._req_finished(req, tok):
                self._retire(req)
        return out

    def run_to_completion(self) -> List[Request]:
        """Drain all waiting + active requests; returns finished list."""
        while self.waiting or self.slot_req:
            self.step()
        return self.finished

    # -- convenience -------------------------------------------------------

    def generate(self, prompts: List[List[int]],
                 max_new_tokens: int = 128) -> List[List[int]]:
        ids = [self.add_request(p, max_new_tokens) for p in prompts]
        self.run_to_completion()
        by_rid = {r.rid: r for r in self.finished}
        return [by_rid[i].tokens for i in ids]
