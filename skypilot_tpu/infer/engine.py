"""Continuous-batching inference engine (JetStream-equivalent).

Slot-based serving: a fixed pool of decode slots advances one token per
``step()`` for every active request, while new requests prefill into free
slots between steps. All device programs are compiled once per prompt
bucket — admission/eviction is host-side bookkeeping only; no shape ever
changes on device.

TTFT = one bucketed prefill (+ queue wait); steady-state throughput =
slots x decode rate. The orchestration mirrors JetStream's
prefill-insert-generate loop, which is what the reference benchmarks on
TPU (reference: examples/tpu/v6e/README.md §Serve — 11.42 req/s,
1829 ms median TTFT on v6e; BASELINE.md).
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import functools
import hashlib
import os
import time
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from skypilot_tpu import chaos
from skypilot_tpu.infer import adapters as adapters_lib
from skypilot_tpu.infer import kvcache, sampling
from skypilot_tpu.infer import qos as qos_lib
from skypilot_tpu.models import llama
from skypilot_tpu.observability import attribution as attribution_lib
from skypilot_tpu.observability import flight as flight_lib
from skypilot_tpu.observability import forensics as forensics_lib
from skypilot_tpu.observability import metrics, tracing
from skypilot_tpu.utils import timeline

# Live serving metrics (docs/observability.md). Span names match the
# histogram names exactly, so a Perfetto trace and a /metrics scrape
# describe the same instrumentation points.
PREFILL_SECONDS = metrics.histogram(
    "skytpu_prefill_seconds",
    "Admission-wave prefill latency, dispatch to first-token fetch, "
    "by prompt bucket", labelnames=("bucket",))
PREFILL_REQUESTS = metrics.counter(
    "skytpu_prefill_requests_total",
    "Requests prefilled, by prompt bucket", labelnames=("bucket",))
WAVE_SIZE = metrics.histogram(
    "skytpu_admission_wave_size",
    "Real (pre-padding) requests per admission wave",
    buckets=(1, 2, 4, 8, 16, 32, 64))
DECODE_STEP_SECONDS = metrics.histogram(
    "skytpu_decode_step_seconds",
    "Decode device-call latency, dispatch to token fetch (one call "
    "decodes a burst of k tokens per active slot)")
DECODE_TOKENS = metrics.counter(
    "skytpu_decode_tokens_total",
    "Output tokens committed to requests by decode")
TTFT_SECONDS = metrics.histogram(
    "skytpu_ttft_seconds",
    "Per-request time to first token (submit/enqueue to first token)",
    buckets=metrics.latency_buckets())
TPOT_SECONDS = metrics.histogram(
    "skytpu_tpot_seconds",
    "Per-request mean time per output token after the first",
    buckets=metrics.latency_buckets())
SLOTS_ACTIVE = metrics.gauge(
    "skytpu_slots_active", "Decode slots currently serving a request")
SLOTS_TOTAL = metrics.gauge(
    "skytpu_slots_total", "Configured decode slot pool size")
ENGINE_WAITING = metrics.gauge(
    "skytpu_engine_waiting",
    "Requests accepted by the engine but not yet prefilled")
REQUESTS_FINISHED = metrics.counter(
    "skytpu_requests_finished_total", "Requests fully generated")
PREFIX_HITS = metrics.counter(
    "skytpu_prefix_cache_hits_total",
    "Admissions that reused a resident prompt-prefix's KV rows "
    "(suffix-only prefill)")
PREFIX_MISSES = metrics.counter(
    "skytpu_prefix_cache_misses_total",
    "Admissions eligible for prefix reuse (pool enabled, prompt longer "
    "than one chunk) that found no resident prefix")
PREFIX_EVICTIONS = metrics.counter(
    "skytpu_prefix_cache_evictions_total",
    "Prefix-pool rows evicted (LRU) to admit a new prefix")
PREFIX_HIT_RATIO = metrics.gauge(
    "skytpu_prefix_cache_hit_ratio",
    "Lifetime fraction of prefix-eligible admissions that reused a "
    "resident prefix (hits / (hits + misses); 0 until the first "
    "eligible admission) — a gauge so fleet aggregation keeps the "
    "per-replica spread affinity routing is supposed to close")
PREFILL_CHUNKS = metrics.counter(
    "skytpu_prefill_chunks_total",
    "Chunked-prefill device calls (one fixed-size chunk each, "
    "interleaved with decode bursts)")
DECODE_STALL_SECONDS = metrics.histogram(
    "skytpu_decode_stall_seconds",
    "Time active decode slots waited on a prefill device call (one "
    "chunk or one admission wave) — the interference chunked prefill "
    "bounds",
    buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
             0.5, 1.0, 2.5, 5.0))
KV_BLOCKS_TOTAL = metrics.gauge(
    "skytpu_kv_blocks_total",
    "Paged KV cache: physical blocks in the pool (0 when the engine "
    "runs the contiguous layout)")
KV_BLOCKS_USED = metrics.gauge(
    "skytpu_kv_blocks_used",
    "Paged KV cache: blocks currently referenced by decode slots "
    "and/or resident prefix-cache entries")
KV_COW_COPIES = metrics.counter(
    "skytpu_kv_cow_copies_total",
    "Paged KV cache copy-on-write block copies (partial shared blocks "
    "duplicated on prefix store/hit before a writer touches them)")
SPEC_DRAFTED = metrics.counter(
    "skytpu_spec_drafted_total",
    "Speculative-decode draft tokens proposed (n-gram/prompt-lookup) "
    "and scored by a verify burst")
SPEC_ACCEPTED = metrics.counter(
    "skytpu_spec_accepted_total",
    "Speculative-decode draft tokens accepted (matched the model's "
    "greedy argmax and were committed)")
SPEC_ROLLBACKS = metrics.counter(
    "skytpu_spec_rollbacks_total",
    "Speculative-decode draft tokens not committed — rejected by "
    "verification, or discarded when the request retired mid-run "
    "(their KV rows sit past the committed length and are never read)")
SPEC_ACCEPT_RATE = metrics.gauge(
    "skytpu_spec_acceptance_rate",
    "Speculative-decode lifetime acceptance rate "
    "(accepted / drafted; 0 until the first draft)")
SPEC_DRAFT_TOKENS = metrics.counter(
    "skytpu_spec_draft_tokens_total",
    "Speculative-decode draft tokens proposed, by drafter kind: "
    "'model' = the draft-model engine (infer/draft.py), 'ngram' = "
    "the host prompt-lookup drafter (also the demotion fallback) — "
    "the fallback ladder model -> ngram -> off is observable per "
    "window", labelnames=("drafter",))
SPEC_VERIFY_WALL = metrics.counter(
    "skytpu_spec_verify_wall_seconds_total",
    "Host wall seconds spent per verify round, dispatch to fetch — "
    "the window the async draft pipeline overlaps draft work into")
SPEC_OVERLAP_WALL = metrics.counter(
    "skytpu_spec_overlap_wall_seconds_total",
    "Host wall seconds spent dispatching the NEXT round's draft "
    "rollout while the current verify was in flight (the pipelined "
    "predraft); overlap ratio = this over "
    "skytpu_spec_verify_wall_seconds_total")
DECODE_ATTN_ROWS = metrics.histogram(
    "skytpu_decode_attn_rows",
    "Span bucket (logical KV rows gathered per slot) actually "
    "dispatched for a decode/verify burst — decode attention "
    "bandwidth tracks this, not max_len (the full-view fallback)",
    buckets=(32, 64, 128, 256, 512, 1024, 2048, 4096, 8192, 16384,
             32768))
KV_LAZY_GROWS = metrics.counter(
    "skytpu_kv_lazy_grows_total",
    "Paged KV blocks allocated by lazy per-burst growth "
    "(SKYTPU_KV_LAZY=1: admission reserves prompt + one burst of "
    "rows; the rest allocates at burst dispatch)")
DECODE_ATTN_PATH = metrics.counter(
    "skytpu_decode_attn_bursts_total",
    "Decode-family bursts (decode, verify, single-step) by big-cache "
    "attention read path: 'kernel' = the Pallas paged-attention "
    "kernel (SKYTPU_KV_KERNEL=1), 'gather' = the XLA logical-view "
    "gather (the parity oracle and contiguous/fallback path) — the "
    "kernel rollout is observable per burst",
    labelnames=("path",))
QOS_KV_QUOTA_STALLS = metrics.counter(
    "skytpu_qos_kv_quota_stalls_total",
    "Admissions stalled because the request's tenant is at its "
    "per-tenant KV-block quota (qos tenant spec max_kv_blocks) — a "
    "typed wait for the tenant's own retirements, never a 503; other "
    "tenants keep admitting",
    labelnames=("tenant",))
QOS_KV_BLOCKS = metrics.gauge(
    "skytpu_qos_kv_blocks_used",
    "Paged KV blocks currently charged to each tenant (table "
    "references, shared prefix blocks charged to every referencing "
    "tenant) — the quantity max_kv_blocks caps",
    labelnames=("tenant",))
ENGINE_RECOVERIES = metrics.counter(
    "skytpu_engine_recoveries_total",
    "Engine crash recoveries: a device dispatch seam raised, the "
    "engine reset (allocator/table/index wiped) and every in-flight "
    "request was re-admitted through the preemption resume path, "
    "by the seam that failed", labelnames=("seam",))


@dataclasses.dataclass
class Request:
    rid: int
    prompt: List[int]
    max_new_tokens: int
    tokens: List[int] = dataclasses.field(default_factory=list)
    slot: Optional[int] = None
    submit_s: float = 0.0
    first_token_s: Optional[float] = None
    done: bool = False
    eos_id: Optional[int] = None
    # Identity of this request's trace span ("engine.request", recorded
    # at retirement): queue-wait/prefill/decode child spans parent to
    # it. parent_id links it into an external trace (the HTTP caller's
    # traceparent) when one rode in with the request.
    span_ctx: Optional[tracing.SpanContext] = None
    parent_id: Optional[str] = None
    # Prefix-cache / chunked-prefill stats (surfaced in the server's
    # response trailer and the prefill span's attrs).
    cached_len: int = 0
    n_chunks: int = 0
    prefill_begin_s: float = 0.0
    # Speculative-decode stats (surfaced next to the cache stats in
    # the response trailer) + per-request drafter state. ``spec_off``
    # flips when this request's acceptance collapses — it keeps riding
    # verify bursts with an empty draft (or plain bursts when every
    # active request collapsed), never paying wasted verify compute.
    spec_drafted: int = 0
    spec_accepted: int = 0
    spec_off: bool = False
    drafter: Optional[Any] = None
    # Drafter kind this request is currently riding ("model" when the
    # engine has a DraftEngine, else "ngram"; "off" once collapsed).
    # The acceptance-collapse fallback DEMOTES down the ladder
    # model -> ngram -> off, with a fresh acceptance window per rung
    # (spec_mode_drafted/accepted reset on demotion — the lifetime
    # spec_drafted/accepted keep feeding the trailer).
    spec_mode: Optional[str] = None
    spec_mode_drafted: int = 0
    spec_mode_accepted: int = 0
    # Multi-tenant QoS (docs/serving.md §Multi-tenant QoS): tenant
    # feeds the fair scheduler and flight attribution; priority picks
    # the lane (higher preempts lower); ``preemptions`` counts how
    # often this request was evicted mid-decode and resumed (surfaced
    # in the response trailer); ``resumed_len`` is the KV rows the
    # LAST resume reused warm from the prefix cache (0 = cold resume).
    tenant: str = qos_lib.DEFAULT_TENANT
    priority: int = 0
    preemptions: int = 0
    resumed_len: int = 0
    # Engine crash recoveries this request survived: each one is an
    # involuntary preemption — the request was re-admitted through the
    # same prompt+committed-tokens resume path eviction uses, so the
    # greedy output stays bit-identical (surfaced in the trailer).
    recoveries: int = 0
    # Per-tenant KV-block quota: True while this request sits queued
    # because its tenant is at max_kv_blocks — the typed stall event
    # and counter fire once per episode, not once per admission pass.
    kv_quota_stalled: bool = False
    # Multi-LoRA adapter catalog (docs/serving.md §Adapter catalog):
    # ``adapter`` names the fine-tune this request generates under
    # (None = the base model); ``adapter_slot`` is the device pool
    # slot serving it (0 = the all-zeros base adapter), assigned at
    # claim; ``adapter_pinned`` tracks the catalog's in-flight
    # refcount so release happens exactly once per acquire; ``error``
    # carries a typed failure body (adapter load failure) the server
    # returns instead of generated tokens — a failed adapter load
    # must NEVER silently fall through to the base model's weights.
    adapter: Optional[str] = None
    adapter_slot: int = 0
    adapter_pinned: bool = False
    error: Optional[Dict[str, Any]] = None
    # Request forensics (observability/forensics.py): admission-stall
    # episode accounting. ``stall_ms`` accumulates closed episodes by
    # cause (pool_dry / kv_quota / adapter_pin); an OPEN episode is
    # (stall_cause, stall_begin_s) and closes — idempotently — at the
    # successful claim. The retirement record carries the totals, and
    # the ledger's queue-wait gaps consume them into named stall
    # phases.
    stall_ms: Dict[str, float] = dataclasses.field(default_factory=dict)
    stall_cause: Optional[str] = None
    stall_begin_s: float = 0.0


@dataclasses.dataclass
class BurstHandle:
    """A dispatched-but-unfetched decode burst (see
    :meth:`InferenceEngine.dispatch_decode_burst`). One handle covers
    the whole burst round: span regrouping may split it over several
    device programs — ``parts`` pairs each program's token array with
    the slots it decoded for."""
    parts: List[Tuple[jax.Array, List[int]]]  # [(toks [k, slots+1], slots)]
    k: int
    slot_req: Dict[int, "Request"]    # slot->request snapshot at dispatch
    # Span opened at dispatch, closed when the tokens are fetched —
    # double-records into skytpu_decode_step_seconds.
    span: Optional[timeline.Event] = None
    # Per-part span rungs (parallel to ``parts``; None = full view):
    # the flight record written at completion carries each part's
    # program identity.
    spans: List[Optional[int]] = dataclasses.field(default_factory=list)
    # Per-part compile-watch program keys (parallel to ``parts``) —
    # the completion record's dev_ms_est looks each part's calibrated
    # device-time EWMA up by this identity.
    keys: List[Optional[str]] = dataclasses.field(default_factory=list)
    # Wall clock when the last part's dispatch returned: the
    # completion record splits its host wall into dispatch vs fetch
    # at this stamp.
    dispatch_done_s: Optional[float] = None


class PromptTooLongError(ValueError):
    """Prompt exceeds the engine's largest prompt bucket. A client
    error, not an engine failure: the server maps it to HTTP 400 with a
    typed body (``typed_error``) instead of a 500."""

    def __init__(self, prompt_len: int, max_prompt_len: int):
        super().__init__(
            f"prompt length {prompt_len} exceeds max bucket "
            f"{max_prompt_len}")
        self.prompt_len = prompt_len
        self.max_prompt_len = max_prompt_len
        self.typed_error = {
            "type": "prompt_too_long",
            "message": str(self),
            "prompt_len": prompt_len,
            "max_prompt_len": max_prompt_len,
        }


class KvQuotaUnsatisfiableError(ValueError):
    """The request's own worst-case KV-block need exceeds its tenant's
    ``max_kv_blocks`` quota, so no amount of the tenant's retirements
    could ever admit it — stalling would hang the client forever. A
    client error (HTTP 400, typed body), never a stall or a 500."""

    def __init__(self, tenant: str, need: int, quota: int):
        super().__init__(
            f"request needs {need} KV blocks but tenant "
            f"{tenant!r} is capped at max_kv_blocks={quota}")
        self.typed_error = {
            "type": "kv_quota_unsatisfiable",
            "message": str(self),
            "tenant": tenant,
            "need_blocks": need,
            "max_kv_blocks": quota,
        }


class EngineDispatchError(RuntimeError):
    """A device dispatch seam (admission wave, prefill chunk, decode
    burst, spec verify) raised. The engine's host bookkeeping may
    disagree with device state, so the only safe move is a full
    ``reset()`` — but every in-flight request is recoverable through
    the preemption resume path (``recover()``): a crash is just an
    involuntary preemption. ``recoverable`` is the duck-typed flag the
    server loop keys recovery on."""

    recoverable = True

    def __init__(self, seam: str, cause: BaseException):
        super().__init__(f"engine dispatch failed at {seam}: {cause}")
        self.seam = seam
        self.cause = cause
        self.typed_error = {
            "type": "engine_dispatch_failed",
            "message": str(self),
            "seam": seam,
        }


class KvPoolWedgedError(RuntimeError):
    """The paged KV pool is exhausted and nothing can make progress:
    every block is held by an active slot (lazy growth has no victim
    to evict). Admission sizing should make this unreachable — hitting
    it means the pool is undersized for the configured slot count, an
    operator error, not a transient."""

    def __init__(self, detail: str):
        super().__init__(detail)
        self.typed_error = {
            "type": "kv_pool_wedged",
            "message": detail,
        }


@contextlib.contextmanager
def _dispatch_boundary(seam: str):
    """Typed failure boundary around one device dispatch seam.

    Chaos point ``engine.dispatch`` fires inside the try so an injected
    fault takes the same wrap path a real device error would. Typed
    client errors (prompt too long, unsatisfiable quota) pass through
    unwrapped — they are the caller's fault, not a crash — as do
    already-wrapped dispatch errors from a nested seam."""
    try:
        chaos.point("engine.dispatch", seam=seam)
        yield
    except (EngineDispatchError, PromptTooLongError,
            KvQuotaUnsatisfiableError):
        raise
    except Exception as e:
        raise EngineDispatchError(seam, e) from e


def _bucket(n: int, buckets) -> int:
    for b in buckets:
        if n <= b:
            return b
    raise PromptTooLongError(n, buckets[-1])


def _span_ladder(buckets, max_len: int) -> Tuple[int, ...]:
    """The span-bucket ladder: ascending rungs, largest always
    ``max_len`` (the full view — also the only rung when bucketing is
    disabled). ``buckets``: None -> the default power-of-two ladder
    (max_len/8, /4, /2, max_len — same idiom as the prefill prompt
    buckets); an explicit iterable -> its positive rungs clamped
    below max_len; empty/0 -> disabled. Rungs need no block
    alignment: the paged gather covers whole blocks and slices to
    the span. Every decode/verify/chunk program compiles once per
    rung it is dispatched at, so the ladder size bounds the compile
    count."""
    if buckets is None:
        ladder = [max_len // d for d in (8, 4, 2)]
    elif isinstance(buckets, int):
        ladder = [buckets] if buckets > 0 else []    # 0 = disabled
    else:
        ladder = [int(b) for b in buckets if int(b) > 0]
    rungs = {s for s in ladder if 0 < s < max_len}
    rungs.add(max_len)
    return tuple(sorted(rungs))


class PrefixIndex:
    """Host-side index over resident prompt prefixes.

    Hash granularity is the prefill chunk: a prompt's prefix is
    cacheable at every multiple of ``block`` tokens, keyed by a
    blake2b-128 digest of the token bytes (content-addressed — a
    Python ``hash`` collision would silently serve the wrong prefix).
    ``salt`` prefixes every digest: the engine feeds the request's
    ADAPTER identity through it, because stored K/V rows carry the
    fine-tune's wk/wv deltas — without the salt, two adapters sharing
    a prompt prefix would share cached K/V computed under whichever
    stored first (silently serving the wrong model).
    One ENTRY holds one stored prefix; every chunk-multiple key of
    that prefix points at the entry, so a shorter shared prefix hits
    it too. Eviction is LRU over entries (a hit or a store bumps the
    entry); evicting drops all of its keys.

    An entry's *payload* is storage-layout specific: the contiguous
    engine stores a pool ROW id (int, allocated via ``acquire_row``);
    the paged engine stores a TUPLE of ref-counted block ids
    (``insert_entry`` — the caller owns the ref-count bookkeeping and
    decrefs whatever ``evict_lru``/``clear``/``insert_entry`` report
    as evicted). ``rows`` caps resident entries either way.
    """

    def __init__(self, rows: int, block: int):
        self.rows = rows
        self.block = block
        self.clear()

    def clear(self) -> None:
        self._tick = 0
        self._keys: Dict[bytes, Tuple[Any, int]] = {}  # -> (payload, n)
        self._ent_keys: Dict[Any, set] = {}
        self._ent_used: Dict[Any, int] = {}            # payload -> LRU

    def _digest(self, prompt: List[int], n: int,
                salt: bytes = b"") -> bytes:
        return hashlib.blake2b(
            salt + np.asarray(prompt[:n], np.int64).tobytes(),
            digest_size=16).digest()

    def eligible(self, prompt: List[int]) -> bool:
        # The shortest cacheable prefix is one block, and at least one
        # suffix token must remain to produce the first-token logits.
        return len(prompt) > self.block

    def payloads(self) -> List[Any]:
        return list(self._ent_used)

    def lookup(self, prompt: List[int],
               salt: bytes = b"") -> Optional[Tuple[Any, int]]:
        """Longest resident chunk-aligned proper prefix of ``prompt``
        (under ``salt`` — the adapter-identity namespace); returns
        (payload, cached_len) and bumps the entry's LRU stamp."""
        for k in range((len(prompt) - 1) // self.block, 0, -1):
            ent = self._keys.get(
                self._digest(prompt, k * self.block, salt))
            if ent is not None:
                self._tick += 1
                self._ent_used[ent[0]] = self._tick
                return ent
        return None

    def _drop(self, payload) -> None:
        for key in self._ent_keys.pop(payload, ()):
            del self._keys[key]
        self._ent_used.pop(payload, None)

    def evict_lru(self) -> Optional[Any]:
        """Drop the least-recently-used entry; returns its payload (the
        caller releases the storage) or None when empty."""
        if not self._ent_used:
            return None
        payload = min(self._ent_used, key=self._ent_used.get)
        self._drop(payload)
        return payload

    def payloads_lru(self) -> List[Any]:
        """Resident payloads, least-recently-used first."""
        return sorted(self._ent_used, key=self._ent_used.get)

    def evict_entry(self, payload) -> None:
        """Drop one specific entry (the caller releases its storage)."""
        self._drop(payload)

    def acquire_row(self) -> Tuple[int, bool]:
        """Contiguous-pool payloads: a free row in [0, rows), or the
        LRU row evicted (its keys dropped). Returns (row, evicted)."""
        evicted = False
        free = [r for r in range(self.rows) if r not in self._ent_used]
        if free:
            row = free[0]
        else:
            row = min(self._ent_used, key=self._ent_used.get)
            self._drop(row)
            evicted = True
        self._tick += 1
        self._ent_used[row] = self._tick
        return row, evicted

    def insert_entry(self, prompt: List[int], n_tokens: int,
                     payload, salt: bytes = b"") -> List[Any]:
        """Paged payloads: admit a new entry, evicting LRU entries past
        the ``rows`` cap. Returns the evicted payloads (caller decrefs
        their blocks)."""
        evicted: List[Any] = []
        while len(self._ent_used) >= self.rows:
            p = self.evict_lru()
            if p is None:
                break
            evicted.append(p)
        self._tick += 1
        self._ent_used[payload] = self._tick
        self.register(prompt, n_tokens, payload, salt)
        return evicted

    def register(self, prompt: List[int], n_tokens: int,
                 payload, salt: bytes = b"") -> None:
        """Point every not-yet-resident chunk multiple <= n_tokens at
        ``payload`` (shorter multiples already resident keep their
        entry — both copies hold identical bytes)."""
        for k in range(1, n_tokens // self.block + 1):
            d = self._digest(prompt, k * self.block, salt)
            if d not in self._keys:
                self._keys[d] = (payload, k * self.block)
                self._ent_keys.setdefault(payload, set()).add(d)


class NGramDrafter:
    """Prompt-lookup speculative drafter (host-side, zero device work).

    The request's context (prompt + committed tokens) is indexed by
    trailing n-gram: ``_index`` maps each n-gram to the START of its
    most recent occurrence that already has a continuation. Drafting
    looks up the context's last n tokens and proposes the up-to-k
    tokens that followed that earlier occurrence — the prompt-lookup
    heuristic: repeated spans (shared boilerplate, quoted input, a
    generation that has entered a cycle) verify at near-full
    acceptance, and a miss costs nothing (empty draft).

    No second model, no trained weights: correctness never depends on
    draft quality because verification is greedy-exact — a bad draft
    only wastes the verify burst's spare positions.
    """

    def __init__(self, tokens: List[int], n: int = 2):
        self.n = max(int(n), 1)
        self.tokens: List[int] = []
        self._index: Dict[Tuple[int, ...], int] = {}
        self.extend(tokens)

    def extend(self, toks) -> None:
        """Append committed tokens, indexing each n-gram the moment it
        gains a continuation (the trailing n-gram itself is never
        indexed — it has nothing after it to draft)."""
        for t in toks:
            self.tokens.append(int(t))
            j = len(self.tokens) - self.n - 1
            if j >= 0:
                self._index[tuple(self.tokens[j:j + self.n])] = j

    def catch_up(self, prompt: List[int], generated: List[int]) -> None:
        """Sync with the request after tokens committed through any
        path (verify bursts, a plain-decode fallback burst, the
        admission first token)."""
        missing = len(prompt) + len(generated) - len(self.tokens)
        if missing > 0:
            self.extend(generated[len(generated) - missing:])

    def draft(self, k: int) -> List[int]:
        """Up to ``k`` proposed continuation tokens ([] on a miss or a
        context shorter than one n-gram — degenerate prompts draft
        nothing rather than guessing).

        Self-extending: when the matched continuation runs into the
        end of the context (the most recent occurrence is near the
        tail — ALWAYS the case once generation enters a cycle), the
        lookup continues from the draft's own tail n-gram, which by
        construction re-matches an earlier occurrence. A tight loop
        therefore drafts the full K instead of the 1-2 tokens left
        after the nearest match."""
        if k <= 0 or len(self.tokens) < self.n:
            return []
        out: List[int] = []
        # Only the trailing n tokens ever feed the key — keep the
        # lookup O(n + k), not O(context): drafting runs per slot per
        # burst on the verify hot path.
        tail = self.tokens[-self.n:]
        while len(out) < k:
            key = tuple((tail + out)[-self.n:])
            j = self._index.get(key)
            if j is None:
                break
            take = self.tokens[j + self.n:j + self.n + k - len(out)]
            if not take:
                break
            out.extend(take)
        return out


@dataclasses.dataclass
class _ChunkState:
    """A request mid-chunked-prefill: slot claimed, rows [0, pos)
    resident (reused prefix and/or completed chunks), first token not
    yet produced (or, on a preemption resume, the NEXT token not yet
    produced). ``ctx`` is the admission-time context snapshot — the
    prompt for a fresh request, prompt + committed tokens for a
    preempted request resuming."""
    req: Request
    pos: int            # next row offset to prefill
    total: int          # len(ctx)
    ctx: Optional[List[int]] = None


class InferenceEngine:
    """Single-model continuous-batching engine.

    Parameters live wherever the caller put them (replicated or
    TP-sharded under a mesh); the engine only compiles and schedules.
    """

    def __init__(self, params: llama.Params, cfg: llama.LlamaConfig,
                 n_slots: int = 8, max_len: int = 1024,
                 prompt_buckets: Tuple[int, ...] = (128, 512, 1024),
                 sampling_params: sampling.SamplingParams = sampling.SamplingParams(),
                 eos_id: Optional[int] = None, seed: int = 0,
                 kv_int8: bool = False, weights_int8: bool = False,
                 qweights=None, max_wave: Optional[int] = None,
                 pad_waves: bool = False, mesh=None, shard_rules=None,
                 prefill_chunk: Optional[int] = None,
                 prefix_pool: Optional[int] = None,
                 kv_block: Optional[int] = None,
                 kv_blocks: Optional[int] = None,
                 spec_k: Optional[int] = None,
                 spec_drafter: Optional[Callable] = None,
                 draft_engine: Optional[Any] = None,
                 spec_pipeline: Optional[bool] = None,
                 span_buckets=None, kv_lazy: Optional[bool] = None,
                 kv_kernel: Optional[bool] = None,
                 flight_recorder: Optional[
                     flight_lib.FlightRecorder] = None,
                 qos: Optional[qos_lib.FairScheduler] = None,
                 adapters: Optional[
                     adapters_lib.AdapterCatalog] = None,
                 forensics: Optional[bool] = None,
                 exemplar_store: Optional[
                     forensics_lib.ExemplarStore] = None):
        self.params = params
        # Multi-tenant QoS: a FairScheduler reorders ``waiting`` into
        # priority lanes + DRR interleave before each admission pass
        # and arms priority preemption-by-eviction (preempt_slot).
        # None (the default) is the zero-cost single-tenant path —
        # admission order stays pure FIFO and nothing ever preempts.
        self.qos = qos
        self.cfg = cfg
        self.n_slots = n_slots
        self.max_len = max_len
        self.buckets = tuple(b for b in prompt_buckets if b <= max_len)
        # Chunked prefill: prompts longer than ``prefill_chunk`` are
        # prefilled in fixed-size chunks interleaved with decode bursts
        # (one compiled chunk program for every bucket and offset)
        # instead of one per-bucket O(S^2) monolith that stalls every
        # decode slot for the whole prompt. 0 disables. Budget knob:
        # SKYTPU_PREFILL_CHUNK (ctor arg wins).
        if prefill_chunk is None:
            prefill_chunk = int(
                os.environ.get("SKYTPU_PREFILL_CHUNK", "512") or 0)
        self.prefill_chunk = (prefill_chunk
                              if prefill_chunk and prefill_chunk > 0
                              else None)
        # Prefix KV reuse: up to ``prefix_pool`` resident prompt
        # prefixes at chunk granularity; a request whose prompt shares
        # a resident prefix prefills only the suffix. Paged engines
        # store a prefix as ref-counted shared blocks (near-zero cost);
        # the contiguous layout reserves ``prefix_pool`` pool rows in a
        # separate tensor and copies rows on store/hit. Requires
        # chunking (the suffix runs through the chunk program). Budget
        # knob: SKYTPU_PREFIX_POOL. 0 disables.
        if prefix_pool is None:
            prefix_pool = int(
                os.environ.get("SKYTPU_PREFIX_POOL", "0") or 0)
        self.prefix_pool = (max(prefix_pool, 0)
                            if self.prefill_chunk else 0)
        # Admission wave cap: a burst of N requests prefills as
        # ceil(N/max_wave) device calls instead of one. Each wave's
        # first tokens can then stream out (step_burst's on_wave hook)
        # while later waves are still prefilling — early requests'
        # TTFT stops paying for the whole burst's prefill.
        # <= 0 means uncapped (a 0 cap would otherwise spin _admit
        # forever on empty waves).
        self.max_wave = max_wave if max_wave and max_wave > 0 else None
        # pad_waves: every admission wave is padded to exactly max_wave
        # rows (dummy rows -> spare slot), so ONE compiled program per
        # bucket serves every wave. A straggler wave pays dummy prefill
        # compute; in exchange no mid-traffic XLA compile can ever
        # stall a request (a fresh (bucket, rows) pair otherwise
        # compiles on first sight — tens of seconds on an 8B model).
        self.pad_waves = bool(pad_waves and self.max_wave)
        self.sampling_params = sampling_params
        self.eos_id = eos_id
        # Speculative decoding: a host-side drafter proposes up to K
        # tokens per slot per burst and ONE compiled verify program
        # scores the K+1 window positions in a single forward pass —
        # K is static, so no new retrace surface. Greedy-exact: spec
        # is forced off under temperature sampling (verification is
        # only output-preserving for argmax, and the RNG stream must
        # stay untouched). Budget knob: SKYTPU_SPEC_K (ctor arg wins;
        # 0 = off — the library default; the server defaults to 4).
        # Clamped to [0, 16]: each K compiles its own program and
        # acceptance past a handful of tokens is workload fantasy.
        if spec_k is None:
            spec_k = int(os.environ.get("SKYTPU_SPEC_K", "0") or 0)
        spec_k = max(0, min(int(spec_k), 16))
        if sampling_params.temperature > 0.0:
            spec_k = 0
        self.spec_k = spec_k
        # Pluggable drafter factory (request -> drafter with the
        # NGramDrafter protocol: catch_up/draft). The per-request seam
        # PR 8 built; default is prompt-lookup. It is ALSO the
        # demotion target: a request whose model-draft acceptance
        # collapses falls back to this factory's drafter.
        self._spec_drafter_factory = (
            spec_drafter
            if spec_drafter is not None
            else (lambda req: NGramDrafter(req.prompt)))
        # Model-backed batched drafter (infer/draft.py DraftEngine):
        # when present, requests start in "model" mode — K tokens per
        # slot per round from the draft model's own staged-burst
        # program, its paged KV advanced/rolled-back in lockstep with
        # the verifier's commits. The n-gram factory above stays the
        # zero-cost fallback rung.
        self.draft_engine = draft_engine
        # Async draft/verify pipeline: while a verify dispatch is in
        # flight, the drafter runs the NEXT round's rollout (its
        # prediction of the bonus token + the following K drafts) and
        # the fetch reconciles — a matched predraft serves the next
        # round with zero new draft work, a miss is discarded
        # host-side (drafter rollback = length non-advance). Only
        # meaningful with a model drafter (n-gram drafting is pure
        # host work with nothing to overlap). Knob:
        # SKYTPU_SPEC_PIPELINE (default on; ctor arg wins).
        if spec_pipeline is None:
            spec_pipeline = (
                os.environ.get("SKYTPU_SPEC_PIPELINE", "1") != "0")
        self.spec_pipeline = bool(spec_pipeline) \
            and draft_engine is not None
        # Per-request acceptance-collapse fallback: once a request has
        # drafted >= spec_min_drafted tokens at an acceptance rate
        # below spec_min_rate IN ITS CURRENT MODE, it demotes down the
        # drafter ladder (model -> ngram -> off) — verify compute
        # stops being wasted on a workload the current drafter can't
        # predict, and the burst degrades to plain decode when every
        # active request has collapsed.
        self.spec_min_drafted = 16
        self.spec_min_rate = 0.2
        self._spec_drafted_total = 0
        self._spec_accepted_total = 0
        # Paged KV cache: the default storage layout. Fixed-size blocks
        # from one shared pool + a per-slot block table decouple slot
        # count from worst-case length — a slot's HBM rent is its
        # ACTUAL rows (rounded up to a block), not max_len, so slot
        # count grows ~max_len/need x at the same HBM. Knobs:
        # SKYTPU_KV_BLOCK (block length, default 256; 0 = contiguous
        # layout) and SKYTPU_KV_BLOCKS (pool size in blocks, default
        # the contiguous-equivalent HBM: (slots+1) * max_len / block).
        if kv_block is None:
            kv_block = int(os.environ.get("SKYTPU_KV_BLOCK", "256")
                           or 0)
        self.paged = kv_block > 0
        if self.paged:
            # Largest divisor of max_len <= the requested block: the
            # block axis must tile max_len exactly for the logical->
            # physical row map to stay a static reshape.
            b = min(kv_block, max_len)
            while max_len % b:
                b -= 1
            self.kv_block = b
            nb = max_len // b
            if kv_blocks is None:
                kv_blocks = int(
                    os.environ.get("SKYTPU_KV_BLOCKS", "0") or 0)
            self.n_kv_blocks = kv_blocks if kv_blocks > 0 \
                else (n_slots + 1) * nb
            if self.n_kv_blocks < nb:
                raise ValueError(
                    f"kv_blocks={self.n_kv_blocks} cannot hold one "
                    f"max_len request ({nb} blocks of {b})")
            self.blocks_per_slot = nb
            self.allocator = kvcache.BlockAllocator(self.n_kv_blocks)
            # Per-slot block table (+ spare). One extra column pinned
            # to the sentinel (== n_kv_blocks): logical rows past the
            # slot's allocation scatter out of bounds and drop. Host
            # numpy is authoritative; a cached device copy rides into
            # every program (_table_device).
            self.block_table = np.full(
                (n_slots + 1, nb + 1), self.n_kv_blocks, np.int32)
            self._table_dev = None
            self._table_dirty = True
        else:
            self.kv_block = None
            self.n_kv_blocks = 0
            self.blocks_per_slot = 0
            self.allocator = None
            self.block_table = None
            self._table_dev = None
            self._table_dirty = False
        # Span-bucketed decode attention: decode/verify/chunk programs
        # compile per SPAN BUCKET (a power-of-two ladder whose largest
        # rung is max_len — the full view) and gather only the first
        # span logical rows, so decode KV bandwidth tracks the ACTIVE
        # span of the burst, not the engine's worst-case length. The
        # ladder is the entire new retrace surface: selection, and the
        # regrouping that keeps one long slot from pinning everyone to
        # its bucket, are host-side. Knob: SKYTPU_SPAN_BUCKETS (ctor
        # arg wins) — a comma-separated explicit ladder, or 0 to
        # disable (full view only).
        if span_buckets is None:
            env = os.environ.get("SKYTPU_SPAN_BUCKETS", "").strip()
            if env:
                span_buckets = [int(t) for t in
                                env.replace(",", " ").split()]
        self.span_ladder = _span_ladder(span_buckets, max_len)
        # Pallas paged-attention kernel (SKYTPU_KV_KERNEL=1 /
        # --kv-kernel, ctor arg wins): decode/verify/chunk big-cache
        # reads walk each slot's block table in-kernel instead of
        # materializing the gathered logical view per layer. Paged
        # layouts only — a contiguous engine falls back to the gather
        # path (typed event, not an error) which also remains the
        # greedy-parity oracle and is selectable at runtime by leaving
        # the flag off. The flag is a STATIC jit argument on every
        # kernel-capable entry point, so it is part of compile-watch
        # program identity and can never be a retrace surface (it is
        # engine-constant).
        if kv_kernel is None:
            kv_kernel = os.environ.get("SKYTPU_KV_KERNEL", "") == "1"
        self.kv_kernel = bool(kv_kernel) and self.paged
        if kv_kernel and not self.paged:
            tracing.add_event(
                "engine.kv_kernel_fallback",
                {"reason": "contiguous_layout"}, echo=True)
        # Decode-side program keys actually dispatched ((kind, width,
        # span) tuples; span None = the full view): the retrace-
        # discipline tests assert this stays bounded by the ladder —
        # never one program per observed length.
        self.decode_programs: set = set()
        # Flight recorder: one record per device burst (wave/chunk/
        # decode/verify), program identity + group composition + host
        # timing, zero device fetches. Injectable so tests/bench can
        # observe an isolated window; None/disabled is a no-op guard.
        self.flight = (flight_recorder if flight_recorder is not None
                       else flight_lib.RECORDER)
        # Compile watch: program registry over the jit entry points
        # below — first-dispatch compile cost, and the mid-traffic
        # unexpected-compile alarm once warmup is declared complete.
        self.compile_watch = flight_lib.CompileWatch()
        # Device-time calibration: every Nth hit dispatch of a program
        # key (SKYTPU_DEVTIME_EVERY; 0 = off) is timed synchronously
        # through the calibrator's bracket, maintaining a per-program
        # EWMA of pure device seconds — the dev_ms_est column flight
        # records carry next to host wall.
        self.devtime = attribution_lib.DeviceTimeCalibrator()
        self.compile_watch.calibrator = self.devtime
        # Per-burst attribution accumulators for the flight record
        # (loop-thread only): COW copies / prefix evictions / lazy
        # grows since the previous record.
        self._fl_cow = 0
        self._fl_evictions = 0
        self._fl_lazy_grows = 0
        # Lifetime prefix-cache hit/miss tallies (loop-thread only)
        # backing the skytpu_prefix_cache_hit_ratio gauge — a gauge,
        # not two counters, so the fleet aggregator can show the
        # per-replica min/max spread that makes affinity skew visible
        # (counters are summed across instances; gauges keep theirs).
        self._prefix_hit_n = 0
        self._prefix_miss_n = 0
        # Request forensics (observability/forensics.py): one
        # retirement record per request (the ledger's anchor) plus
        # streaming P2 tail detection on TTFT/TPOT that pins crossing
        # requests' full evidence into the exemplar store. A plain
        # runtime-flippable flag, exactly like the recorder's — the
        # off path is bit-identical and the bench gates the on path
        # at <= 1.01x.
        if forensics is None:
            forensics = forensics_lib.forensics_enabled()
        self.forensics = bool(forensics)
        self.tail = forensics_lib.TailDetector()
        self.exemplars = (exemplar_store if exemplar_store is not None
                          else forensics_lib.EXEMPLARS)
        # Lazy per-burst block growth (paged only): admission reserves
        # the prompt plus ONE burst of rows instead of the full
        # max_new_tokens worst case; the rest allocates at burst
        # dispatch through the same dry-pool evict/stall path
        # admission uses. Eager stays the default: lazy trades the
        # no-mid-flight-fault guarantee for tighter reservations (a
        # slot the pool cannot grow sits a burst out until
        # retirements free blocks). Knob: SKYTPU_KV_LAZY=1.
        if kv_lazy is None:
            kv_lazy = os.environ.get("SKYTPU_KV_LAZY", "") == "1"
        self.kv_lazy = bool(kv_lazy) and self.paged
        self._lazy_headroom = max(16, self.spec_k + 1)
        # One hidden spare slot (index n_slots): batched admission pads
        # its wave with dummy prefills targeting the spare, so one
        # compiled program serves every wave size. (Paged: the spare's
        # table row stays all-sentinel — dummy writes drop, zero block
        # cost.)
        if self.paged:
            self.cache = kvcache.init_paged_cache(
                cfg, n_slots + 1, self.n_kv_blocks, self.kv_block,
                kv_int8=kv_int8)
        else:
            self.cache = kvcache.init_cache(cfg, n_slots + 1, max_len,
                                            kv_int8=kv_int8)
        # Contiguous layout only: the separate prefix-pool tensor.
        # Paged engines need no pool — a stored prefix is just shared
        # ref-counted blocks mapped into the new slot's table.
        self.pool = (kvcache.init_prefix_pool(cfg, self.prefix_pool,
                                              max_len, kv_int8=kv_int8)
                     if self.prefix_pool and not self.paged else None)
        self._prefix_index = (PrefixIndex(self.prefix_pool,
                                          self.prefill_chunk)
                              if self.prefix_pool else None)
        KV_BLOCKS_TOTAL.set(self.n_kv_blocks)
        # w8a8 serving: int8 weights for BOTH prefill and decode, so no
        # fp copy of the seven block matrices (or the head) is kept —
        # the memory halving that fits an 8B-class model on a 16 GB
        # chip. ``qweights`` may be passed pre-built (with a slim
        # params tree: embed + norms only). Not wired for MoE experts.
        self.qweights = qweights
        if weights_int8 and qweights is None:
            if hasattr(cfg, "n_experts"):
                raise NotImplementedError(
                    "weights_int8 is not supported for MoE configs yet")
            self.qweights = jax.jit(lambda prm: {
                "blocks": kvcache.quantize_block_weights(prm),
                "head": kvcache.quantize_head(prm, cfg),
            })(params)
        if self.qweights is not None:
            self.params = params = kvcache.slim_params(params)
        # Tensor-parallel serving: shard params/qweights/cache over the
        # mesh's tp axis (Megatron head/mlp/vocab split; the KV cache
        # shards its kv_heads dim, so each device holds its heads' KV).
        # The jitted prefill/decode programs need NO changes — XLA SPMD
        # partitions them from the input shardings, inserting the
        # all-reduces where wo/w_down contract (verified token-exact vs
        # a single-device engine in tests/test_infer_tp.py). Multi-chip
        # 70B-class serving is this + enough chips.
        self.mesh = mesh
        if mesh is not None:
            from skypilot_tpu.models import llama as llama_mod
            from skypilot_tpu.parallel import sharding as sh
            rules = shard_rules or sh.INFER_TP_RULES
            self._shard_rules = rules
            self.params = params = sh.shard_tree_subset(
                params, llama_mod.param_logical_axes(cfg), mesh, rules)
            if self.qweights is not None:
                self.qweights = sh.shard_tree_subset(
                    self.qweights, kvcache.qweight_logical_axes(cfg),
                    mesh, rules)
            self.cache = sh.shard_tree_subset(
                self.cache, kvcache.cache_logical_axes(self.cache),
                mesh, rules)
            if self.pool is not None:
                self.pool = sh.shard_tree_subset(
                    self.pool, kvcache.pool_logical_axes(self.pool),
                    mesh, rules)
        self.rng = jax.random.key(seed)

        # Multi-LoRA adapter catalog (docs/serving.md §Adapter
        # catalog): a device-resident stacked (A, B) pool + host LRU
        # hot-load/evict. Per-slot adapter ids live in a host numpy
        # array with a dirty-tracked device copy — EXACTLY the block-
        # table idiom — and ride every program as data, so adapter
        # count/identity never enters program identity (the compile
        # watch is the guard). None (the default) is the zero-cost
        # adapterless path: every program traces exactly as before.
        self.adapters = adapters
        if adapters is not None:
            self.adapter_ids = np.zeros((n_slots + 1,), np.int32)
            self._aid_dev = None
            self._aid_dirty = True
        else:
            self.adapter_ids = None
            self._aid_dev = None
            self._aid_dirty = False

        # HBM ledger + roofline model (observability/attribution.py):
        # analytical byte accounting of every device-resident tensor
        # family this engine owns, refreshed from host bookkeeping at
        # every _update_gauges, and the per-record FLOPs/bytes cost
        # model behind the MFU / bandwidth-utilization columns. KV
        # bytes-per-token is computed from the ACTUAL cache dtypes
        # (int8 KV counts its fp32 scales).
        itemsize = self.cache["k"].dtype.itemsize
        G, hd, L = cfg.n_kv_heads, cfg.head_dim, cfg.n_layers
        self._kv_token_bytes = 2 * L * G * hd * itemsize \
            + (2 * L * G * 4 if "k_scale" in self.cache else 0)
        self._kv_block_bytes = (self._kv_token_bytes * self.kv_block
                                if self.paged else 0)
        weight_bytes = (attribution_lib.tensor_bytes(self.params)
                        + attribution_lib.tensor_bytes(self.qweights))
        self.hbm_ledger = attribution_lib.HbmLedger()
        self._weight_bytes = weight_bytes
        self.roofline = attribution_lib.Roofline(
            param_count=cfg.num_params(), weight_bytes=weight_bytes,
            kv_token_bytes=self._kv_token_bytes, d_model=cfg.d_model,
            n_layers=L, n_heads=cfg.n_heads, head_dim=hd,
            max_len=max_len, chunk_tokens=self.prefill_chunk)
        # The draft model's rollouts attribute at ITS scale, not the
        # verifier's — a second roofline on the draft config.
        self._draft_roofline = None
        if draft_engine is not None:
            dcfg = draft_engine.cfg
            d_itemsize = draft_engine.cache["k"].dtype.itemsize
            d_kvt = 2 * dcfg.n_layers * dcfg.n_kv_heads \
                * dcfg.head_dim * d_itemsize \
                + (2 * dcfg.n_layers * dcfg.n_kv_heads * 4
                   if "k_scale" in draft_engine.cache else 0)
            self._draft_roofline = attribution_lib.Roofline(
                param_count=dcfg.num_params(),
                weight_bytes=(
                    attribution_lib.tensor_bytes(draft_engine.params)
                    + attribution_lib.tensor_bytes(
                        draft_engine.qweights)),
                kv_token_bytes=d_kvt, d_model=dcfg.d_model,
                n_layers=dcfg.n_layers, n_heads=dcfg.n_heads,
                head_dim=dcfg.head_dim, max_len=draft_engine.max_len)
        peak_flops, peak_bw = attribution_lib.device_peaks()
        attribution_lib.ROOFLINE_PEAK_FLOPS.set(peak_flops)
        attribution_lib.ROOFLINE_PEAK_BW.set(peak_bw)
        # Per-tenant KV-block quotas (qos tenant spec max_kv_blocks):
        # blocks a slot's table references are charged to its tenant
        # at claim/growth and refunded when the slot's blocks free.
        # Shared prefix blocks charge EVERY referencing tenant — a
        # reference holds the block live, so each referencing tenant
        # pays. Host bookkeeping only (loop thread).
        self._slot_kv_charge: Dict[int, Tuple[str, int]] = {}
        self._tenant_kv: Dict[str, int] = {}
        self.free_slots = list(range(n_slots))
        self.slot_req: Dict[int, Request] = {}
        self.waiting: Deque[Request] = collections.deque()
        self.chunking: Deque[_ChunkState] = collections.deque()
        self.finished: List[Request] = []
        # Requests a crashed admission pass was holding in locals
        # (crash safety; see _rescue_admit_limbo).
        self._admit_limbo: List[Request] = []
        self._next_rid = 0
        # Tokens dispatched to the device but not yet committed
        # host-side (one outstanding async burst at a time is the
        # expected pattern; the count caps the next burst).
        self._inflight_tokens = 0
        # Static ledger components once; the dynamic ones (kv_used,
        # prefix_pinned) refresh with the slot gauges, so the ledger
        # init must follow the slot bookkeeping above. The runtime
        # cross-check fills bytes_in_use / the true bytes_limit where
        # the backend reports memory_stats (CPU: typed fallback event,
        # analytical-only).
        self._init_hbm_ledger()
        SLOTS_TOTAL.set(n_slots)
        self._update_gauges()

        sp = self.sampling_params

        # The cache is donated everywhere: the engine reassigns
        # self.cache from the output every call, so XLA updates the
        # [L, slots, max_len, G, hd] buffers in place, never copying.

        # RNG lives on device and every program splits it INTERNALLY,
        # returning the successor key: a host-side jax.random.split per
        # call would be an extra eagerly-dispatched device program on
        # the hot path (per decode burst / admission wave) — material
        # when dispatch rides a relayed TPU link.

        # Batched admission: ONE batched prefill for the whole wave (the
        # W requests share every weight read; matmuls run at W x S
        # rows), then a scan of per-request cache inserts (cheap
        # scatters). Dummy rows target the spare slot; its length
        # bookkeeping is zeroed HERE (last row of the length vector)
        # rather than by a follow-up eager scatter per wave.
        @functools.partial(jax.jit, donate_argnums=(1, 5),
                           static_argnames=("bucket",))
        def _admit_wave(params, cache, tokens_b, true_lens, slots, rng,
                        table=None, lora=None, aid=None, *, bucket,
                        qweights=None):
            del bucket
            from jax import lax as _lax
            rng, sub = jax.random.split(rng)
            prefix, logits = kvcache.prefill_batch(
                params, tokens_b, true_lens, cfg, qweights=qweights,
                lora=lora, aid=aid)
            first = sampling.sample(logits, sub, sp)      # [W]

            def ins(c, w):
                pk = _lax.dynamic_index_in_dim(prefix["k"], w, 1,
                                               keepdims=False)
                pv = _lax.dynamic_index_in_dim(prefix["v"], w, 1,
                                               keepdims=False)
                c = kvcache.insert(c, {"k": pk, "v": pv}, slots[w],
                                   true_lens[w], first[w], table=table)
                return c, None

            cache, _ = _lax.scan(ins, cache,
                                 jnp.arange(tokens_b.shape[0]))
            cache["length"] = cache["length"].at[-1].set(0)  # spare
            return cache, rng, first

        @functools.partial(jax.jit, donate_argnums=(1, 2),
                           static_argnames=("span",))
        def _decode(params, cache, rng, active, table=None,
                    lora=None, aid=None, qweights=None, *, span=None):
            rng, sub = jax.random.split(rng)
            cache, logits = kvcache.decode_step(params, cache, cfg,
                                                qweights=qweights,
                                                table=table, span=span,
                                                lora=lora, aid=aid)
            toks = sampling.sample(logits, sub, sp)
            cache = kvcache.commit_tokens(cache, toks, active)
            return cache, rng, toks

        # Burst decode: k steps in one device program -> one host round
        # trip per k tokens. Crucial when dispatch latency rivals the
        # per-token compute (small models, remote/relayed TPUs). The
        # program is the STAGED formulation — in-burst rows accumulate
        # in a small staging buffer and flush to the cache once per
        # burst, keeping the big cache a loop invariant (see
        # kvcache.decode_burst_staged; ~25% faster than a scan of
        # per-step cache updates on an 8B model).
        @functools.partial(jax.jit, donate_argnums=(1, 2),
                           static_argnames=("k", "span", "kernel"))
        def _decode_burst(params, cache, rng, active, table=None,
                          lora=None, aid=None, *, k,
                          qweights=None, span=None, kernel=False):
            return kvcache.decode_burst_staged(
                params, cache, rng, active, k, cfg, sp,
                qweights=qweights, table=table, span=span,
                kv_kernel=kernel, lora=lora, aid=aid)

        # Speculative verify: the decode_burst_staged formulation with
        # the sampled-token feedback replaced by the host's draft
        # window and greedy argmax outputs + on-device acceptance. No
        # RNG argument at all — the greedy stream stays untouched, so
        # spec-on and spec-off runs consume identical RNG.
        @functools.partial(jax.jit, donate_argnums=(1,),
                           static_argnames=("k", "span", "kernel"))
        def _verify(params, cache, draft, n_draft, active, table=None,
                    lora=None, aid=None, *, k, qweights=None,
                    span=None, kernel=False):
            return kvcache.verify_draft_staged(
                params, cache, draft, n_draft, active, k, cfg,
                qweights=qweights, table=table, span=span,
                kv_kernel=kernel, lora=lora, aid=aid)

        # Chunked-prefill programs: ONE chunk program (two traces: the
        # ``final`` variant samples the first token and splits the RNG)
        # serves every bucket and every suffix offset; the claim/copy
        # programs are trivial gathers/scatters.
        @functools.partial(jax.jit, donate_argnums=(1,),
                           static_argnames=("final", "span", "kernel"))
        def _prefill_chunk(params, cache, tokens_c, start, n_valid,
                           slot, new_len, rng, table=None, lora=None,
                           aid=None, *, final,
                           qweights=None, span=None, kernel=False):
            return kvcache.prefill_chunk(
                params, cache, tokens_c, start, n_valid, slot, new_len,
                rng, cfg, sp, final=final, qweights=qweights,
                table=table, span=span, kv_kernel=kernel, lora=lora,
                aid=aid)

        @functools.partial(jax.jit, donate_argnums=(0,))
        def _claim(cache, slot, claim_len):
            return kvcache.claim_slot(cache, slot, claim_len)

        @functools.partial(jax.jit, donate_argnums=(0,))
        def _pool_load(cache, pool, row, slot, claim_len):
            return kvcache.pool_load(cache, pool, row, slot, claim_len)

        @functools.partial(jax.jit, donate_argnums=(0,))
        def _pool_store(pool, cache, slot, row):
            return kvcache.pool_store(pool, cache, slot, row)

        @functools.partial(jax.jit, donate_argnums=(0,))
        def _copy_block(cache, src, dst):
            return kvcache.copy_block(cache, src, dst)

        # Cross-replica KV handoff (docs/serving.md §Disaggregated
        # serving): gather a stored prefix's physical blocks to host,
        # scatter them into a receiving replica's pool. The index
        # vector is FIXED-width (blocks_per_slot, sentinel-padded), so
        # each direction is one compiled program for the engine's
        # lifetime — a handoff can never hit a mid-traffic compile.
        @jax.jit
        def _export_blocks(cache, idx):
            return kvcache.export_blocks(cache, idx)

        @functools.partial(jax.jit, donate_argnums=(0,))
        def _import_blocks(cache, idx, vals):
            return kvcache.import_blocks(cache, idx, vals)

        # Adapter hot-load: scatter one fine-tune's stacked (A, B)
        # weights into a pool slot (pool donated — the install is in
        # place). Weight shapes are pool constants, so ONE program
        # serves every adapter for the engine's lifetime; it rides the
        # compile watch and the warm grid like every other entry point,
        # which is what makes mid-traffic hot-loads compile-free.
        @functools.partial(jax.jit, donate_argnums=(0,))
        def _adapter_install(pool, slot, weights):
            return adapters_lib.pool_install(pool, slot, weights)

        # Every jit entry point rides the compile watch: a program key
        # is (entry point, static args) — plus the wave's ROW COUNT,
        # which is shape-derived identity jit recompiles on even under
        # an unchanged static key. First dispatch records the compile
        # wall; post-warmup new keys raise the unexpected-compile
        # alarm. The wrappers are transparent pass-throughs (donation
        # and async dispatch semantics unchanged).
        watch = self.compile_watch.wrap
        self._admit_wave_fn = watch(
            "admit_wave", _admit_wave, ("bucket",),
            key_fn=lambda a, kw: (("rows", a[2].shape[0]),))
        self._decode_fn = watch("decode1", _decode, ("span",))
        self._decode_burst_fn = watch("decode_burst", _decode_burst,
                                      ("k", "span", "kernel"))
        self._verify_fn = watch("verify", _verify,
                                ("k", "span", "kernel"))
        self._prefill_chunk_fn = watch("prefill_chunk", _prefill_chunk,
                                       ("final", "span", "kernel"))
        self._claim_fn = watch("claim", _claim)
        self._pool_load_fn = watch("pool_load", _pool_load)
        self._pool_store_fn = watch("pool_store", _pool_store)
        self._copy_block_fn = watch("copy_block", _copy_block)
        self._export_blocks_fn = watch("export_blocks", _export_blocks)
        self._import_blocks_fn = watch("import_blocks", _import_blocks)
        self._adapter_install_fn = watch("adapter_load",
                                         _adapter_install)
        if self.adapters is not None:
            self.adapters.bind_loader(
                lambda pool, slot, weights: self._adapter_install_fn(
                    pool, jnp.asarray(slot, jnp.int32), weights))

    # -- admission ---------------------------------------------------------

    # -- sharded init ------------------------------------------------------
    @staticmethod
    def sharded_init(cfg, mesh, rules=None, seed: int = 0):
        """Initialize params DIRECTLY onto the mesh (jit with
        out_shardings): each device materializes only its own weight
        shards, so a model bigger than one chip's HBM can be built at
        all — init-then-shard would OOM device 0 before the engine's
        device_put ever ran. Pass the result + the same mesh to
        InferenceEngine (its device_put then no-ops)."""
        from skypilot_tpu.models import llama as llama_mod
        from skypilot_tpu.parallel import sharding as sh
        rules = rules or sh.INFER_TP_RULES
        abstract = jax.eval_shape(
            lambda k: llama_mod.init_params(k, cfg), jax.random.key(0))
        shardings = sh.logical_to_sharding(
            llama_mod.param_logical_axes(cfg), mesh, rules,
            shapes=abstract)
        return jax.jit(lambda k: llama_mod.init_params(k, cfg),
                       out_shardings=shardings)(jax.random.key(seed))

    def add_request(self, prompt: List[int],
                    max_new_tokens: int = 128,
                    trace_ctx: Optional[tracing.SpanContext] = None,
                    tenant: str = qos_lib.DEFAULT_TENANT,
                    priority: int = 0,
                    adapter: Optional[str] = None,
                    committed: Optional[List[int]] = None) -> int:
        _bucket(len(prompt), self.buckets)   # validate length up front
        self.check_kv_quota(tenant, len(prompt), max_new_tokens)
        self.check_adapter(adapter)          # unknown name -> typed 404
        req = Request(rid=self._next_rid, prompt=list(prompt),
                      max_new_tokens=max_new_tokens, submit_s=time.time(),
                      eos_id=self.eos_id, tenant=tenant,
                      priority=priority, adapter=adapter)
        if committed:
            # Disaggregated handoff: tokens another replica already
            # committed (and streamed) ride in pre-seeded, so this
            # request admits through the SAME prompt+committed resume
            # path preemption and crash recovery use — the suffix it
            # decodes is bit-identical to finishing on the origin
            # replica, and max_new_tokens keeps its original meaning
            # (the budget counts the committed tokens).
            req.tokens = [int(t) for t in committed]
        # Per-request span identity, minted at submit so child spans
        # recorded before retirement can already parent to it. The
        # parent comes from the caller's explicit context (the HTTP
        # handler's traceparent — admission runs on the loop thread,
        # which has no ambient context) or the ambient one.
        parent = trace_ctx if trace_ctx is not None else tracing.current()
        req.span_ctx = tracing.SpanContext(
            parent.trace_id if parent else tracing.new_trace_id(),
            tracing.new_span_id())
        req.parent_id = parent.span_id if parent else None
        self._next_rid += 1
        self.waiting.append(req)
        ENGINE_WAITING.set(len(self.waiting))
        return req.rid

    def _update_gauges(self) -> None:
        SLOTS_ACTIVE.set(len(self.slot_req))
        ENGINE_WAITING.set(len(self.waiting))
        seen = self._prefix_hit_n + self._prefix_miss_n
        if seen:
            PREFIX_HIT_RATIO.set(self._prefix_hit_n / seen)
        if self.paged:
            KV_BLOCKS_USED.set(self.allocator.used)
        self._refresh_hbm_ledger()

    # -- HBM ledger --------------------------------------------------------

    def _init_hbm_ledger(self) -> None:
        """Static ledger components: resident capacity each tensor
        family holds for the engine's lifetime (array nbytes are
        metadata reads — no device fetch). The workspace entry is the
        per-program activation ESTIMATE for the widest admission wave
        (rows x bucket x (ff + 2d) fp32 plus the wave logits), the one
        family with no host-authoritative array to read."""
        led = self.hbm_ledger
        led.set_bytes("weights", self._weight_bytes)
        led.set_bytes("kv_pool",
                      attribution_lib.tensor_bytes(self.cache))
        led.set_bytes("prefix_pool",
                      attribution_lib.tensor_bytes(self.pool))
        led.set_bytes("draft_pool",
                      self.draft_engine.hbm_bytes()
                      if self.draft_engine is not None else 0)
        led.set_bytes("adapter_pool",
                      attribution_lib.tensor_bytes(self.adapters.pool)
                      if self.adapters is not None else 0)
        rows = (self.max_wave if self.pad_waves else self.n_slots) + 1
        widest = max(self.buckets) if self.buckets else self.max_len
        cfg = self.cfg
        workspace = rows * widest * (cfg.d_ff + 2 * cfg.d_model) * 4 \
            + rows * cfg.vocab_size * 4
        led.set_bytes("workspace", workspace)
        stats = led.cross_check()
        if stats is None or "bytes_limit" not in stats:
            # No backend truth: the alarmable limit comes from the
            # operator (env) or defaults to the analytical total plus
            # slack — headroom stays a meaningful ratio either way.
            env = os.environ.get("SKYTPU_HBM_LIMIT_BYTES", "")
            try:
                limit = int(env) if env else 0
            except ValueError:
                limit = 0
            led.set_limit(limit if limit > 0
                          else int(led.total() * 1.25))
        self._refresh_hbm_ledger()

    def _refresh_hbm_ledger(self) -> None:
        """Dynamic (occupancy) components, recomputed from the SAME
        host bookkeeping the engine admits against — allocator block
        counts and prefix payloads — so a ledger leak IS a structure
        leak. Occupancy views overlap the capacity components
        (kv_used is resident inside kv_pool); the headroom SLO rule
        sums capacity components only."""
        led = self.hbm_ledger
        if self.paged:
            led.set_bytes("kv_used",
                          self.allocator.used * self._kv_block_bytes)
            pinned = 0
            if self._prefix_index is not None:
                for payload in self._prefix_index.payloads():
                    if isinstance(payload, (list, tuple)):
                        pinned += len(payload) * self._kv_block_bytes
            led.set_bytes("prefix_pinned", pinned)
        else:
            led.set_bytes("kv_used",
                          len(self.slot_req) * self.max_len
                          * self._kv_token_bytes)
            led.set_bytes(
                "prefix_pinned",
                (len(self._prefix_index.payloads()) * self.max_len
                 * self._kv_token_bytes)
                if self._prefix_index is not None else 0)

    # -- flight recorder + compile watch -----------------------------------

    def _record_flight(self, burst: str, begin_s: float, end_s: float,
                       program: Dict[str, Any], slots, reqs,
                       toks: int, stall: bool = False,
                       drafted: int = 0, accepted: int = 0,
                       drafter: Optional[str] = None,
                       overlap_ms: float = 0.0,
                       dispatch_s: Optional[float] = None,
                       dev_keys: Optional[List[Optional[str]]] = None,
                       calibrator: Optional[
                           attribution_lib.DeviceTimeCalibrator]
                       = None) -> None:
        """Append one burst record to the flight recorder. HOST
        bookkeeping only — every value here already lives on the host
        (request lists, ints, floats); a device fetch on this path
        would stall the dispatch pipeline the recorder exists to
        observe. COW/eviction/lazy-grow attribution: whatever
        accumulated since the previous record rides this one (claims
        run just before the wave/chunk record they belong to; lazy
        growth happens inside the burst being recorded)."""
        cow, self._fl_cow = self._fl_cow, 0
        evs, self._fl_evictions = self._fl_evictions, 0
        lazy, self._fl_lazy_grows = self._fl_lazy_grows, 0
        compiled = self.compile_watch.drain_new()
        # Big-cache read path this burst rode: the kernel covers the
        # burst/verify/chunk programs; decode1 (the classic single-
        # step fallback) stays on the gather even with the flag on.
        attn = None
        if burst in ("decode", "verify", "chunk", "decode1"):
            attn = ("kernel" if self.kv_kernel and burst != "decode1"
                    else "gather")
            if burst != "chunk":
                DECODE_ATTN_PATH.labels(path=attn).inc()
        fl = self.flight
        if fl is None or not fl.enabled:
            return
        program = dict(program)
        program["layout"] = "paged" if self.paged else "contig"
        if attn is not None:
            program["attn"] = attn
        extra: Dict[str, Any] = {}
        if stall:
            extra["stall"] = True
        if drafted:
            extra["drafted"] = drafted
            extra["accepted"] = accepted
        if drafter:
            # Which drafter kind fed this burst (verify bursts:
            # model|ngram|mixed group composition; "draft" records:
            # the pipelined predraft dispatch itself).
            extra["drafter"] = drafter
        if overlap_ms:
            # Host wall the round spent dispatching next-round draft
            # work INSIDE the verify's dispatch->fetch window — the
            # pipeline-overlap attribution skytpu flight/--perfetto
            # render as overlapping spans.
            extra["overlap_ms"] = overlap_ms
        if cow:
            extra["cow"] = cow
        if evs:
            extra["evictions"] = evs
        if lazy:
            extra["lazy_grows"] = lazy
        if compiled:
            extra["compiled"] = compiled
        # Device-truth attribution (observability/attribution.py).
        # dur_s stays the dispatch->fetch host wall for render/test
        # compat; the split names where it went (enqueueing vs
        # waiting), and dev_ms_est is the calibrated EWMA of pure
        # device time for the program(s) this record dispatched.
        dur_ms = max(end_s - begin_s, 0.0) * 1e3
        if dispatch_s is not None:
            disp_ms = min(max((dispatch_s - begin_s) * 1e3, 0.0),
                          dur_ms)
            extra["dispatch_wall_ms"] = round(disp_ms, 4)
            extra["fetch_wall_ms"] = round(dur_ms - disp_ms, 4)
        cal = calibrator if calibrator is not None else self.devtime
        if dev_keys:
            ests = [cal.estimate(k) for k in dev_keys]
            ests = [e for e in ests if e is not None]
            if ests:
                dev_ms = sum(ests) * 1e3
                extra["dev_ms_est"] = round(dev_ms, 4)
                attribution_lib.DEVICE_SECONDS.inc(dev_ms / 1e3)
        rl = (self._draft_roofline if burst == "draft"
              else self.roofline)
        if rl is not None:
            flops, hbm = rl.record_cost(burst, program,
                                        len(slots), toks)
            if flops:
                extra["flops"] = flops
                extra["hbm_bytes"] = hbm
                attribution_lib.DEVICE_FLOPS.inc(flops)
                attribution_lib.DEVICE_HBM_MOVED.inc(hbm)
        if self.adapters is not None and reqs:
            # Per-burst adapter composition (host dict over the
            # request list): `skytpu flight` and the bench read which
            # fine-tunes shared each dispatch straight off records.
            ads: Dict[str, int] = {}
            for r in reqs:
                if r.adapter:
                    ads[r.adapter] = ads.get(r.adapter, 0) + 1
            if ads:
                extra["adapters"] = ads
        if self.qos is not None and reqs:
            # Per-burst tenant/priority composition (host dict builds
            # over the request list): the chaos fairness scenario and
            # `skytpu flight` read group make-up straight off records.
            tenants: Dict[str, int] = {}
            for r in reqs:
                tenants[r.tenant] = tenants.get(r.tenant, 0) + 1
            extra["tenants"] = tenants
            if any(r.priority for r in reqs):
                prios: Dict[str, int] = {}
                for r in reqs:
                    key = str(r.priority)
                    prios[key] = prios.get(key, 0) + 1
                extra["priorities"] = prios
        fl.record(
            burst, ts_s=begin_s, dur_s=max(end_s - begin_s, 0.0),
            program=program, slots=list(slots),
            rids=[r.rid for r in reqs],
            traces=[r.span_ctx.trace_id for r in reqs
                    if r.span_ctx is not None],
            toks=toks, **extra)

    def declare_warmup_complete(self) -> None:
        """Arm the compile watch: every program the live workload can
        reach is believed compiled, so any later compile is the
        mid-traffic stall the static-shape design forbids — a typed
        ``engine.unexpected_compile`` event plus
        ``skytpu_unexpected_compiles_total`` (the SLO watchdog's
        ``unexpected-compiles`` rule alarms on it)."""
        self.compile_watch.declare_warm()
        if self.draft_engine is not None:
            # The drafter's programs are part of this replica's live
            # surface: a mid-traffic draft-model compile stalls the
            # spec path exactly like a main-engine one.
            self.draft_engine.declare_warmup_complete()

    def warm_programs(self, max_burst: int = 8) -> int:
        """Pre-compile the engine's reachable program grid so no XLA
        compile can stall live traffic (call once at startup, then
        :meth:`declare_warmup_complete`).

        Every (kind, static-args) variant dispatches once against the
        hidden SPARE slot, whose writes are garbage by construction
        (paged: the spare's table row is all-sentinel so writes drop;
        contiguous: they land in the spare's own dead rows), and the
        length bookkeeping is zeroed afterwards. Greedy output is
        unaffected — argmax sampling ignores the RNG stream this
        consumes. Runs under ``metrics.suppress`` so the compile-
        dominated sweep stays out of the serving histograms, then
        republishes the sweep's compile metrics (skytpu_compile_
        seconds / skytpu_programs_compiled_total) from the watch
        registry — "programs compiled on this replica" must stay
        truthful on warm-grid fleets. Returns the number of programs
        compiled."""
        before = self.compile_watch.count
        pre_keys = set(self.compile_watch.summary())
        spare = self.n_slots
        active = np.zeros((self.n_slots + 1,), bool)
        active[spare] = True
        active_dev = jnp.asarray(active)
        spans = [self._span_arg(s) for s in self.span_ladder]
        lora_kw = self._lora_args()
        with metrics.suppress():
            for sarg in spans:
                self.cache, self.rng, _ = self._decode_fn(
                    self.params, self.cache, self.rng, active_dev,
                    self.table_device(), qweights=self.qweights,
                    span=sarg, **lora_kw)
                k = 1
                while k <= max_burst:
                    self.cache, self.rng, _ = self._decode_burst_fn(
                        self.params, self.cache, self.rng, active_dev,
                        self.table_device(), k=k,
                        qweights=self.qweights, span=sarg,
                        kernel=self.kv_kernel, **lora_kw)
                    k *= 2
                if self.spec_k:
                    draft = jnp.zeros((self.n_slots + 1, self.spec_k),
                                      jnp.int32)
                    n_draft = jnp.zeros((self.n_slots + 1,), jnp.int32)
                    self.cache, _, _ = self._verify_fn(
                        self.params, self.cache, draft, n_draft,
                        active_dev, self.table_device(), k=self.spec_k,
                        qweights=self.qweights, span=sarg,
                        kernel=self.kv_kernel, **lora_kw)
                if self.prefill_chunk:
                    chunk = jnp.zeros((self.prefill_chunk,), jnp.int32)
                    for final in (False, True):
                        self.cache, self.rng, _ = \
                            self._prefill_chunk_fn(
                                self.params, self.cache, chunk,
                                jnp.asarray(0, jnp.int32),
                                jnp.asarray(1, jnp.int32),
                                jnp.asarray(spare, jnp.int32),
                                jnp.asarray(self.max_len, jnp.int32),
                                self.rng, self.table_device(),
                                final=final, qweights=self.qweights,
                                span=sarg, kernel=self.kv_kernel,
                                **lora_kw)
            # Admission waves: pad_waves pins every wave at max_wave
            # rows, so one program per bucket suffices. Unpadded
            # engines pad each wave to the next power of two of its
            # size — warm that whole ladder, or declaring warmup
            # complete would false-page on the first 2-row wave.
            if self.pad_waves:
                rows_ladder = [self.max_wave]
            else:
                cap = self.max_wave or self.n_slots
                rows_ladder = [1]
                r = 2
                while r <= (1 << (cap - 1).bit_length()):
                    rows_ladder.append(r)
                    r <<= 1
            for bucket in self.buckets:
                for rows in rows_ladder:
                    tokens_b = np.ones((rows, bucket), np.int32)
                    true_lens = np.ones((rows,), np.int32)
                    slot_ids = np.full((rows,), spare, np.int32)
                    wave_lora = {}
                    if self.adapters is not None:
                        wave_lora = {
                            "lora": self.adapters.pool,
                            "aid": jnp.zeros((rows,), jnp.int32)}
                    self.cache, self.rng, _ = self._admit_wave_fn(
                        self.params, self.cache, jnp.asarray(tokens_b),
                        jnp.asarray(true_lens),
                        jnp.asarray(slot_ids), self.rng,
                        self.table_device(), bucket=bucket,
                        qweights=self.qweights, **wave_lora)
            # The admission path's small gather/scatter programs.
            claim_len = jnp.asarray(self.max_len, jnp.int32)
            self.cache = self._claim_fn(
                self.cache, jnp.asarray(spare, jnp.int32), claim_len)
            if self.pool is not None:
                self.cache = self._pool_load_fn(
                    self.cache, self.pool, jnp.asarray(0, jnp.int32),
                    jnp.asarray(spare, jnp.int32), claim_len)
                self.pool = self._pool_store_fn(
                    self.pool, self.cache,
                    jnp.asarray(spare, jnp.int32),
                    jnp.asarray(0, jnp.int32))
            if self.paged:
                self.cache = self._copy_block_fn(
                    self.cache, jnp.asarray(0, jnp.int32),
                    jnp.asarray(0, jnp.int32))
                # Handoff export/import: warm against an all-sentinel
                # index — the gather clamps (garbage nobody reads), the
                # scatter drops every write (out of bounds), so the
                # sweep leaves the pool untouched.
                ids = jnp.full((self.blocks_per_slot,),
                               self.n_kv_blocks, jnp.int32)
                vals = self._export_blocks_fn(self.cache, ids)
                self.cache = self._import_blocks_fn(self.cache, ids,
                                                    vals)
            if self.adapters is not None:
                # Warm the hot-load program by installing the all-zero
                # weights into the base slot (values unchanged): a
                # demand load mid-traffic must dispatch, not compile.
                self.adapters.pool = self._adapter_install_fn(
                    self.adapters.pool, jnp.asarray(0, jnp.int32),
                    self.adapters.zero_weights())
            # Scrub: zero the length bookkeeping — the sweep's data
            # rows are dead without a length exposing them.
            self.cache["length"] = jnp.zeros_like(self.cache["length"])
        self.compile_watch.drain_new()   # not any burst's to claim
        # Republish the sweep's compile metrics OUTSIDE suppress: the
        # wrapper's increments were discarded inside it, but "programs
        # compiled on this replica" must mirror the watch registry —
        # or a warm-grid fleet would read `compiles 0` on skytpu top.
        summ = self.compile_watch.summary()
        for key in summ:
            if key not in pre_keys:
                flight_lib.COMPILE_SECONDS.labels(
                    program=key).observe(summ[key])
                flight_lib.PROGRAMS_COMPILED.inc()
        n = self.compile_watch.count - before
        if self.spec_k and self.draft_engine is not None:
            # The drafter's grid (rollouts at K and K+1 per span rung,
            # ingest, sync) is reachable the moment the first request
            # drafts — warm it with the engine's, or a live replica's
            # first spec round pays a draft-model compile.
            n += self.draft_engine.warm_programs(self.spec_k)
        return n

    # -- paged block management --------------------------------------------

    @property
    def blocks_used(self) -> int:
        """Physical blocks currently referenced (0 when contiguous)."""
        return self.allocator.used if self.paged else 0

    def table_device(self):
        """The block table as a device array (None when contiguous).
        Cached between calls — claims/retires mark it dirty — so a
        steady decode stream pays no per-burst host->device copy."""
        if not self.paged:
            return None
        if self._table_dirty or self._table_dev is None:
            self._table_dev = jnp.asarray(self.block_table)
            self._table_dirty = False
        return self._table_dev

    # -- adapter catalog ---------------------------------------------------

    def aid_device(self):
        """The per-slot adapter-id vector as a device array (None when
        no catalog). Cached between calls — claims/retires mark it
        dirty — so a steady decode stream pays no per-burst
        host->device copy (the block-table idiom)."""
        if self.adapters is None:
            return None
        if self._aid_dirty or self._aid_dev is None:
            self._aid_dev = jnp.asarray(self.adapter_ids)
            self._aid_dirty = False
        return self._aid_dev

    def _lora_args(self) -> Dict[str, Any]:
        """kwargs routing the adapter pool + per-slot ids into a
        decode-family dispatch ({} on the adapterless path — the
        programs then trace exactly as before)."""
        if self.adapters is None:
            return {}
        return {"lora": self.adapters.pool, "aid": self.aid_device()}

    def check_adapter(self, name: Optional[str]) -> None:
        """Submit-time guard (server handler threads, the _bucket
        idiom): an unknown fine-tune is a clean typed 404 before the
        request ever rides the inbox. An engine with NO catalog knows
        no adapters at all."""
        if name is None:
            return
        if self.adapters is None:
            raise adapters_lib.UnknownAdapterError(name, [])
        self.adapters.check(name)

    def _acquire_adapter(self, req: Request) -> str:
        """Pin the request's fine-tune into the device pool at claim
        time. Returns "ok" (adapter_slot assigned, pin counted),
        "stall" (every pool slot pinned by in-flight requests — the
        caller re-queues and retries once a retirement unpins), or
        "failed" (checkpoint load failed after retries / unknown name:
        the request has been FAILED TYPED and consumed — it must never
        silently fall through to the base model's weights)."""
        if self.adapters is None or req.adapter is None:
            req.adapter_slot = 0
            return "ok"
        try:
            slot = self.adapters.acquire(req.adapter)
        except (adapters_lib.AdapterLoadError,
                adapters_lib.UnknownAdapterError) as e:
            self._fail_request(req, e)
            return "failed"
        if slot is None:
            return "stall"
        req.adapter_slot = slot
        req.adapter_pinned = slot > 0
        return "ok"

    def _release_adapter(self, req: Request) -> None:
        """Drop the request's in-flight adapter pin (exactly once per
        acquire: retirement, preemption, or an abandoned claim)."""
        if req.adapter_pinned and self.adapters is not None:
            self.adapters.release(req.adapter_slot)
            req.adapter_pinned = False

    def _set_slot_adapter(self, slot: int, pool_slot: int) -> None:
        if self.adapters is None:
            return
        if self.adapter_ids[slot] != pool_slot:
            self.adapter_ids[slot] = pool_slot
            self._aid_dirty = True

    def _prefix_salt(self, req: Request) -> bytes:
        """The request's prefix-cache key namespace. Stored K/V rows
        carry the fine-tune's wk/wv deltas, so cached prefixes are
        ADAPTER-SPECIFIC: without the salt, two adapters sharing a
        prompt prefix would hit cached K/V computed under whichever
        stored first — silently serving the wrong model. Keyed by the
        adapter's CONTENT digest (warm prefixes survive evict/reload
        and alias names); base-model requests keep the empty salt
        (the pre-adapter key space, bit-compatible)."""
        if self.adapters is None or not req.adapter_slot:
            return b""
        return self.adapters.slot_content(req.adapter_slot)

    def _fail_request(self, req: Request, exc: Exception) -> None:
        """Retire a request with a typed error instead of tokens (the
        adapter-load failure path). The server returns the body with
        the error's HTTP status; the engine never substitutes base-
        model output for a named fine-tune."""
        req.error = getattr(exc, "typed_error", None) or {
            "type": "error", "message": str(exc)}
        if getattr(exc, "http_status", None):
            req.error = dict(req.error,
                             http_status=exc.http_status)
        req.done = True
        self.finished.append(req)

    def _need_blocks(self, req: Request,
                     ctx_len: Optional[int] = None) -> int:
        """Blocks to reserve at admission. Eager (default): the
        worst case — prompt plus the full token budget, capped by
        max_len — so decode can never run out of backing mid-flight;
        the pool, not a mid-decode fault path, is the admission
        limiter. (The formula is already total-shaped, so a preempted
        request resuming with committed tokens reserves the identical
        worst case.) Lazy (SKYTPU_KV_LAZY=1): just the admission
        context plus one burst of headroom; the rest allocates per
        burst in :meth:`_ensure_headroom` through the same dry-pool
        evict/stall path."""
        need = min(len(req.prompt) + req.max_new_tokens, self.max_len)
        if self.kv_lazy:
            base = ctx_len if ctx_len is not None else len(req.prompt)
            need = min(base + self._lazy_headroom, need)
        return -(-need // self.kv_block)

    def _ensure_headroom(self, slot: int, req: Request,
                         need_rows: int) -> bool:
        """Lazy mode: grow the slot's block allocation to back
        ``need_rows`` cache rows before a burst writes them (eager
        engines reserved the worst case at admission and always pass).
        Growth rides admission's dry-pool path — LRU prefix entries
        evict first, and a pool that stays dry returns False: the
        slot sits this burst out and retries after retirements free
        blocks."""
        if not self.kv_lazy:
            return True
        cap = min(len(req.prompt) + req.max_new_tokens, self.max_len)
        need_rows = min(need_rows, cap)
        row = self.block_table[slot]
        have = len(row[row < self.n_kv_blocks])
        grow = -(-need_rows // self.kv_block) - have
        if grow <= 0:
            return True
        blocks = self._alloc_blocks(grow)
        if blocks is None:
            return False
        row[have:have + len(blocks)] = blocks
        self._table_dirty = True
        self._sync_kv_charge(slot, req.tenant)
        KV_LAZY_GROWS.inc(len(blocks))
        self._fl_lazy_grows += len(blocks)
        return True

    # -- span buckets ------------------------------------------------------

    def _span_for(self, rows: int) -> int:
        """Smallest ladder rung covering ``rows`` cache rows (the full
        view for anything past the ladder — callers' row counts are
        already capped by max_len)."""
        for s in self.span_ladder:
            if rows <= s:
                return s
        return self.span_ladder[-1]

    def _span_arg(self, span: int) -> Optional[int]:
        """The static ``span`` argument for a dispatch: None selects
        the unsliced full-view program — the identical trace the
        pre-span engine compiled, so a disabled ladder costs
        nothing."""
        return None if span >= self.max_len else span

    def _slot_rows(self, req: Request) -> int:
        """Cache rows the slot holds at the next burst's start as the
        DEVICE will see it: host-committed tokens plus every token
        still in flight (dispatched bursts commit on device before
        the next program runs)."""
        return (len(req.prompt) + len(req.tokens)
                + self._inflight_tokens)

    def _span_groups(self, width: int
                     ) -> List[Tuple[int, List[int]]]:
        """Active slots grouped by the span bucket covering their
        rows — the REGROUPING step: one mixed-length burst would
        otherwise ride the longest slot's bucket, so a single long
        conversation would drag every short neighbor back to
        worst-case reads. Each group dispatches its own burst at its
        own span (programs chain on the donated cache; a group's
        garbage writes for other groups' slots land past their
        committed lengths and are overwritten before any read, the
        standard dead-row net). ``width``: rows the burst will write
        per slot — lazy growth must back them; a slot the pool cannot
        grow is left out and retries once retirements free blocks.
        Returns [(span, [slot, ...])], ascending spans."""
        groups: Dict[int, List[int]] = {}
        for slot, req in self.slot_req.items():
            rows = self._slot_rows(req)
            if not self._ensure_headroom(slot, req, rows + width):
                continue
            groups.setdefault(self._span_for(rows), []).append(slot)
        return sorted(groups.items())

    def _alloc_blocks(self, n: int) -> Optional[List[int]]:
        """n fresh blocks, evicting LRU prefix-cache entries on a dry
        pool (their blocks free unless still shared with live slots).
        None when the pool stays too dry — the caller leaves the
        request queued; retirements free blocks and admission retries
        next pass."""
        chaos.point("kv.alloc", need=n)
        alloc = self.allocator
        idx = self._prefix_index
        while alloc.available < n and idx is not None:
            # Evict the LRU entry that would actually FREE blocks.
            # Entries whose blocks are all still shared with live
            # slots (or pinned by the claim in progress) free nothing
            # — dropping them would wipe the warm cache for zero
            # capacity, turning one transient dry-pool moment into a
            # fleet-wide cold-prefill regression.
            victim = None
            for p in idx.payloads_lru():
                if any(alloc.ref(b) == 1 for b in p):
                    victim = p
                    break
            if victim is None:
                break
            idx.evict_entry(victim)
            PREFIX_EVICTIONS.inc()
            self._fl_evictions += 1
            for b in victim:
                alloc.decref(b)
        if alloc.available < n:
            return None
        return [alloc.alloc() for _ in range(n)]

    # -- per-tenant KV-block quotas (qos max_kv_blocks) --------------------

    def _kv_quota(self, tenant: str) -> int:
        """The tenant's ``max_kv_blocks`` quota (0 = unlimited):
        paged engines with a QoS config only."""
        if not self.paged or self.qos is None:
            return 0
        return max(self.qos.cfg.tenant(tenant).max_kv_blocks, 0)

    def check_kv_quota(self, tenant: str, prompt_len: int,
                       max_new_tokens: int) -> None:
        """Submit-time guard: a request whose OWN worst-case block
        need exceeds its tenant's ``max_kv_blocks`` quota can never
        admit (the need formula is total-shaped and never shrinks), so
        stalling it would hang the client forever — raise the typed
        error instead. Reads only engine constants, so the server's
        handler threads call it eagerly (the ``_bucket`` idiom: a
        clean 400 before the request ever rides the inbox — an
        exception on the loop thread could reach no client)."""
        quota = self._kv_quota(tenant)
        if not quota:
            return
        need = min(prompt_len + max_new_tokens, self.max_len)
        if self.kv_lazy:
            need = min(prompt_len + self._lazy_headroom, need)
        need = -(-need // self.kv_block)
        if need > quota:
            raise KvQuotaUnsatisfiableError(tenant, need, quota)

    def _kv_quota_blocked(self, req: Request) -> bool:
        """Admission-time per-tenant KV-block quota check: True holds
        THIS request back (typed ``qos.kv_quota_stall`` event +
        counter, once per episode) while other tenants keep admitting
        — a hot tenant can no longer hog the paged pool via long
        contexts even while rate-limited. The quota gates ADMISSION
        only: in-flight lazy growth is never blocked, so an admitted
        request always runs to completion (growth is still charged,
        which holds the tenant's NEXT admission)."""
        quota = self._kv_quota(req.tenant)
        if not quota:
            return False
        need = self._need_blocks(req, self._ctx_len(req))
        used = self._tenant_kv.get(req.tenant, 0)
        if used + need <= quota:
            req.kv_quota_stalled = False
            if req.stall_cause == "kv_quota":
                self._end_stall(req)
            return False
        self._mark_stall(req, "kv_quota")
        if not req.kv_quota_stalled:
            req.kv_quota_stalled = True
            QOS_KV_QUOTA_STALLS.labels(
                tenant=qos_lib.tenant_label(req.tenant,
                                            self.qos.cfg)).inc()
            tracing.add_event(
                "qos.kv_quota_stall",
                {"tenant": req.tenant, "rid": req.rid,
                 "used_blocks": used, "need_blocks": need,
                 "max_kv_blocks": quota})
        return True

    def _set_tenant_kv(self, tenant: str, n: int) -> None:
        # Entries pop at zero: tenant names are client-supplied, so a
        # scanner minting one name per request must not grow the dict
        # for the engine's lifetime.
        if n > 0:
            self._tenant_kv[tenant] = n
        else:
            self._tenant_kv.pop(tenant, None)
        # The gauge is absolute and its label CAP collapses overflow
        # tenants into "other" — publish the label's SUM, not this
        # tenant's count, or collapsed tenants would overwrite each
        # other (a counter tolerates collapse; a .set() gauge only
        # does summed).
        cfg = self.qos.cfg if self.qos is not None else None
        label = qos_lib.tenant_label(tenant, cfg)
        total = sum(v for t, v in self._tenant_kv.items()
                    if qos_lib.tenant_label(t, cfg) == label)
        QOS_KV_BLOCKS.labels(tenant=label).set(total)

    def _sync_kv_charge(self, slot: int,
                        tenant: Optional[str] = None) -> None:
        """Re-point the tenant KV-block accounting at the slot's
        CURRENT table occupancy (called at claim, growth and free):
        the charge is the number of blocks the slot's table
        references, so shared prefix blocks charge every referencing
        tenant and the refund at :meth:`_free_slot_blocks` is exact by
        construction — no leak path exists that does not also leak the
        table row itself."""
        if not self.paged:
            return
        old_tenant, old_n = self._slot_kv_charge.get(slot, (None, 0))
        tenant = tenant if tenant is not None else old_tenant
        row = self.block_table[slot]
        have = len(row[row < self.n_kv_blocks])
        if old_tenant is not None and old_n:
            self._set_tenant_kv(
                old_tenant, self._tenant_kv.get(old_tenant, 0) - old_n)
        if tenant is not None and have:
            self._slot_kv_charge[slot] = (tenant, have)
            self._set_tenant_kv(
                tenant, self._tenant_kv.get(tenant, 0) + have)
        else:
            self._slot_kv_charge.pop(slot, None)

    def _wave_claim(self, req: Request
                    ) -> Tuple[str, Optional[int]]:
        """Claim a slot (+ its KV blocks when paged, + the adapter
        pool pin when the request names a fine-tune) for a wave-path
        request. Returns (status, slot): ("ok", slot); ("dry", None)
        — the block pool is too dry, the caller re-queues and stalls
        admission globally; ("held", None) — every adapter-pool slot
        is pinned by in-flight requests, the caller steps THIS request
        aside (the quota-held idiom — a per-resource limit must not
        head-of-line-block base-model traffic); ("failed", None) —
        the adapter failed to load and the request has been FAILED
        TYPED and consumed."""
        st = self._acquire_adapter(req)
        if st == "failed":
            return "failed", None
        if st == "stall":
            self._mark_stall(req, "adapter_pin")
            return "held", None
        if not self.paged:
            slot = self.free_slots.pop(0)
            self._set_slot_adapter(slot, req.adapter_slot)
            self._end_stall(req)
            return "ok", slot
        blocks = self._alloc_blocks(
            self._need_blocks(req, self._ctx_len(req)))
        if blocks is None:
            # The adapter pin must not leak across the re-queue: the
            # next pass re-acquires (resident slots are warm hits).
            self._release_adapter(req)
            self._mark_stall(req, "pool_dry")
            return "dry", None
        slot = self.free_slots.pop(0)
        row = self.block_table[slot]
        row[:] = self.n_kv_blocks
        row[:len(blocks)] = blocks
        self._table_dirty = True
        self._sync_kv_charge(slot, req.tenant)
        self._set_slot_adapter(slot, req.adapter_slot)
        self._end_stall(req)
        return "ok", slot

    def _free_slot_blocks(self, slot: int) -> None:
        """Release a slot's block references and clear its table row to
        the sentinel: bursts dispatched after the retirement drop their
        garbage writes for the dead slot. A burst already in flight
        rode the OLD device table and still writes the old blocks —
        safely: device programs execute in dispatch order, so a re-
        allocated block's every readable row is overwritten by its new
        owner's (later-dispatched) prefill/decode writes before the
        owner's length ever exposes it."""
        if not self.paged:
            return
        row = self.block_table[slot]
        for b in row[row < self.n_kv_blocks].tolist():
            self.allocator.decref(b)
        row[:] = self.n_kv_blocks
        self._table_dirty = True
        self._sync_kv_charge(slot)      # refund the tenant's charge

    # -- QoS: re-queue, fair scheduling, preemption-by-eviction ------------

    def _mark_stall(self, req: Request, cause: str) -> None:
        """Open (or re-assert) an admission-stall episode on ``req``.
        Idempotent per cause while the episode stays open — one
        episode spans every admission pass that re-hits the same
        blocker; a cause CHANGE closes the old episode into
        ``stall_ms`` and opens the new one. Host floats/dicts only,
        on paths that only run when admission is already blocked."""
        now = time.time()
        if req.stall_cause is not None:
            if req.stall_cause == cause:
                return
            req.stall_ms[req.stall_cause] = (
                req.stall_ms.get(req.stall_cause, 0.0)
                + (now - req.stall_begin_s) * 1e3)
        req.stall_cause = cause
        req.stall_begin_s = now

    def _end_stall(self, req: Request) -> None:
        """Close an open stall episode (successful claim, quota
        unblock, retirement). No-op when none is open."""
        if req.stall_cause is None:
            return
        req.stall_ms[req.stall_cause] = (
            req.stall_ms.get(req.stall_cause, 0.0)
            + max(time.time() - req.stall_begin_s, 0.0) * 1e3)
        req.stall_cause = None
        req.stall_begin_s = 0.0

    def _requeue(self, req: Request) -> None:
        """THE re-queue path: every request going back to the queue
        head (dry-pool admission stall, chunk-claim stall, preemption
        eviction) passes through here, so the queue-depth gauge
        updates with the deque in one place and ``skytpu_engine_
        waiting`` can never go stale on a re-queue branch."""
        self.waiting.appendleft(req)
        ENGINE_WAITING.set(len(self.waiting))

    def _ctx(self, req: Request) -> List[int]:
        """A queued request's admission context: its prompt, extended
        by committed tokens when it was preempted mid-decode — the
        resume prefills (or prefix-cache-reuses) the full committed
        sequence and the final chunk's sample IS the next token the
        unpreempted run would have decoded (greedy-exact)."""
        if not req.tokens:
            return req.prompt
        return req.prompt + req.tokens

    def _ctx_len(self, req: Request) -> int:
        """``len(self._ctx(req))`` without materializing the concat —
        the admission loop asks for queued requests' context lengths
        every pass, and a long preempted conversation stuck behind a
        dry pool must not re-build a multi-KB list each time."""
        return len(req.prompt) + len(req.tokens)

    def _resumable(self, ctx_len: int) -> bool:
        """Whether a context of this length could be re-admitted after
        eviction THROUGH THE CHUNK PATH — the only resume the
        bit-identical parity matrix covers. A wave re-admission would
        re-sample the victim's next token from the wave program's
        logits where an unpreempted run used the decode program's;
        rather than extend the parity surface across programs, a slot
        whose context still fits a wave simply isn't preempted yet
        (one more burst makes it eligible)."""
        if ctx_len >= self.max_len:
            return False
        return (self.prefill_chunk is not None
                and ctx_len > self.prefill_chunk)

    def preempt_slot(self, slot: int) -> bool:
        """Preemption-by-eviction of one decode slot — the priority
        lanes' primitive (ROADMAP items 1/4, shared by items 3/5).

        The victim's committed KV rows [0, prompt+tokens-1) are
        exactly the bytes prefill/decode wrote; the chunk-aligned
        prefix retires into the prefix cache as ref-counted shared
        blocks (paged: increfs only — the dying slot never writes
        again, so even a trailing partial block is shared without the
        COW copy a live donor would need). The request re-queues with
        its tokens intact and resumes through the ORDINARY prefix-hit
        admission path over its extended context, re-prefilling only
        the sub-chunk tail; greedy output is bit-identical to an
        unpreempted run (tests/test_qos.py asserts it across
        {fp32, int8} x {spec-on, spec-off}).

        Refuses while a dispatched burst is un-fetched (its completion
        would commit tokens into a request already back in the queue)
        and for contexts the engine could not re-admit. Host-side
        bookkeeping only — a block-table edit, never a device copy.
        """
        req = self.slot_req.get(slot)
        if req is None or self._inflight_tokens:
            return False
        ctx = req.prompt + req.tokens
        if not self._resumable(len(ctx)):
            return False
        retired_rows = 0
        if (self.paged and self._prefix_index is not None
                and req.n_chunks):
            # Committed rows stop one short of the context: the last
            # token's KV row is written by the burst that decodes its
            # successor, which never ran. Only a CHUNK-admitted
            # victim's rows may enter the shared cache — the cache
            # promises chunk-origin bytes to every later sharer
            # (_store_prefix's parity rule), and a wave-admitted
            # victim's prompt rows came from the wave program. Such a
            # victim still evicts; it just resumes cold.
            self._store_prefix(ctx, slot, len(ctx) - 1,
                               donor_live=False,
                               salt=self._prefix_salt(req))
            # The flight record reports what the RESUME will read
            # warm: the cached rows covering the victim's context
            # after the store (admission may have stored the prompt's
            # prefix already — still warm; a dry-pool or sub-chunk
            # skip with no prior entry — cold, 0). Never the raw
            # context length.
            covered = self._prefix_index.lookup(
                ctx, self._prefix_salt(req))
            if covered is not None:
                retired_rows = covered[1]
        self.slot_req.pop(slot, None)
        self.free_slots.append(slot)
        self._free_slot_blocks(slot)
        self._set_slot_adapter(slot, 0)
        if self.draft_engine is not None:
            self.draft_engine.release(slot)
        self._release_adapter(req)
        req.slot = None
        req.preemptions += 1
        qos_lib.QOS_PREEMPTIONS.labels(
            tenant=qos_lib.tenant_label(
                req.tenant,
                self.qos.cfg if self.qos is not None else None)).inc()
        fl = self.flight
        if fl is not None and fl.enabled:
            fl.record(
                "preempt", ts_s=time.time(), dur_s=0.0,
                program={"layout": "paged" if self.paged else "contig"},
                slots=[slot], rids=[req.rid], toks=0,
                tenants={req.tenant: 1}, priority=req.priority,
                retired_rows=retired_rows)
        self._requeue(req)
        self._update_gauges()
        return True

    def _preempt_for_waiting(self) -> bool:
        """Give the priority lanes teeth: for each queued request that
        outranks a running one and cannot get a free slot, evict the
        lowest-priority active slot (ties: the youngest — least sunk
        decode work). Runs before admission claims slots; the evicted
        victims re-queue behind the high-priority lane on the next
        reorder. Returns whether anything was evicted."""
        if self._inflight_tokens or not self.slot_req:
            return False
        evicted_any = False
        avail = len(self.free_slots)
        for w in list(self.waiting)[:self.n_slots]:
            if avail > 0:
                avail -= 1          # a free slot already covers it
                continue
            # Outranked residents, best victim first (lowest priority,
            # then youngest = least sunk decode). preempt_slot can
            # refuse a candidate (un-resumable context) — fall through
            # to the next one rather than strand an evictable victim
            # in another slot behind the refusal.
            candidates = sorted(
                (r.priority, -r.rid, slot)
                for slot, r in self.slot_req.items()
                if r.priority < w.priority)
            for _, _, slot in candidates:
                if self.preempt_slot(slot):
                    evicted_any = True
                    break
            else:
                break               # nothing outranked (or evictable)
        return evicted_any

    def _admit(self, on_wave=None) -> None:
        """Admission pass behind the ``admit`` dispatch boundary: a
        device error anywhere in wave dispatch/completion or a chunk
        claim's block allocation surfaces as a recoverable
        :class:`EngineDispatchError` (typed client errors pass
        through). Exception-safe: requests the pass had popped off
        ``waiting`` but not yet landed in ``chunking``/``slot_req``
        (mid-claim, mid-wave, quota-held) go back to the queue head
        BEFORE the error crosses the boundary — otherwise
        :meth:`recover`'s snapshot cannot see them and a crash would
        silently drop in-flight requests."""
        self._admit_limbo = []
        try:
            with _dispatch_boundary("admit"):
                self._admit_impl(on_wave)
        except EngineDispatchError:
            self._rescue_admit_limbo()
            raise

    def _rescue_admit_limbo(self) -> None:
        """Re-queue every request the crashed admission pass was
        holding in locals. Membership by rid (Request __eq__ is
        field-wise): anything already reachable from ``waiting``,
        ``chunking``, ``slot_req``, or ``finished`` stays put — limbo
        restore must never duplicate a request."""
        reachable = {r.rid for r in self.waiting}
        reachable.update(st.req.rid for st in self.chunking)
        reachable.update(r.rid for r in self.slot_req.values())
        reachable.update(r.rid for r in self.finished)
        lost = [r for r in self._admit_limbo
                if r.rid not in reachable]
        self._admit_limbo = []
        for r in reversed(lost):     # earliest pop back at the head
            self.waiting.appendleft(r)
        ENGINE_WAITING.set(len(self.waiting))

    def _admit_impl(self, on_wave=None) -> None:
        # Waves are grouped by prompt bucket (prefill is O(S^2): one
        # long prompt must not drag every co-admitted short prompt up
        # to its bucket) and capped at max_wave, then padded to the
        # next power-of-two row count (dummy rows -> spare slot) so
        # each (bucket, rows) pair compiles exactly once. ``on_wave``
        # fires as each wave's first tokens LAND (fetch order = device
        # order) — the server streams them while later, already
        # dispatched waves are still prefilling; requests on_wave
        # drains into ``waiting`` join the next outer-loop pass.
        #
        # PIPELINED: all waves' device programs are dispatched first
        # (JAX dispatch is async; the programs chain on the donated
        # cache and execute back-to-back), THEN each wave's first
        # tokens are fetched in order. Fetching inside the build loop
        # would serialize a full host round trip per wave — measured
        # ~200 ms fixed cost per wave on a relayed chip, the dominant
        # TTFT term for every wave after the first.
        if self.qos is not None and self.waiting:
            # WFQ + priority lanes: reorder the deque (DRR across
            # per-tenant subqueues, high priority first), then evict
            # outranked decode slots for queued high-priority work.
            # Both are host bookkeeping; wave building below is
            # unchanged and span regrouping downstream never sees
            # tenants.
            self.qos.reorder(self.waiting)
            if self._preempt_for_waiting() and self.waiting:
                # Evicted victims re-queued at the head; put them back
                # behind the lanes that outrank them. Back-to-back
                # reorders are otherwise idempotent — the DRR rotation
                # advances only when a request actually LEAVES the
                # queue, never per call, so a pass that admits nothing
                # cannot shift which tenant owns the front.
                self.qos.reorder(self.waiting)
        stalled = False
        # Requests held by a PER-REQUEST resource limit this pass —
        # their tenant's KV-block quota, or a fully-pinned adapter
        # pool: such limits must not stall the whole queue the way
        # the (global) dry-block-pool stall does. Held requests step
        # aside, everyone behind them gets their shot, and they
        # re-queue at the head for the next pass (a retirement
        # unblocks them: it frees the tenant's blocks / unpins an
        # adapter slot).
        quota_held: List[Request] = []
        limbo = self._admit_limbo

        def pop_waiting() -> Request:
            # Every admission pop is limbo-tracked until the request
            # lands somewhere recover() can see (crash safety; see
            # _rescue_admit_limbo).
            req = self.waiting.popleft()
            limbo.append(req)
            return req

        while self.waiting and self.free_slots and not stalled:
            dispatched = []
            while self.waiting and self.free_slots and not stalled:
                if self._kv_quota_blocked(self.waiting[0]):
                    quota_held.append(pop_waiting())
                    continue
                # Chunk-path requests (prompt longer than the chunk —
                # which also covers every possible prefix-cache hit)
                # claim a slot and join the chunk queue; they never
                # ride a bucketed wave. "stall" means the paged block
                # pool is dry: the request went back to the queue head
                # and admission stops until retirements free blocks
                # (the pool, not the slot count, is then the admission
                # limiter); "held" means its fine-tune's pool is fully
                # pinned — it steps aside and everyone behind it keeps
                # admitting.
                if self._use_chunked(self.waiting[0]):
                    req = pop_waiting()
                    cst = self._claim_chunked(req)
                    if cst == "stall":
                        stalled = True
                    elif cst == "held":
                        quota_held.append(req)
                    continue
                bucket = _bucket(self._ctx_len(self.waiting[0]),
                                 self.buckets)
                wave: List[Request] = []
                slots: List[int] = []
                rest: List[Request] = []
                while self.waiting and self.free_slots and \
                        not stalled and \
                        (self.max_wave is None
                         or len(wave) < self.max_wave):
                    req = pop_waiting()
                    if self._kv_quota_blocked(req):
                        quota_held.append(req)
                    elif self._use_chunked(req):
                        cst = self._claim_chunked(req)
                        if cst == "stall":
                            stalled = True
                        elif cst == "held":
                            quota_held.append(req)
                    elif _bucket(self._ctx_len(req),
                                 self.buckets) == bucket:
                        st, slot = self._wave_claim(req)
                        if st == "ok":
                            wave.append(req)
                            slots.append(slot)
                        elif st == "held":
                            # Adapter pool fully pinned: step aside —
                            # base-model and resident-adapter traffic
                            # behind it keeps admitting.
                            quota_held.append(req)
                        elif st == "dry":    # block pool dry
                            self._requeue(req)
                            stalled = True
                        # "failed": consumed (failed typed)
                    else:
                        rest.append(req)
                self.waiting.extendleft(reversed(rest))
                if wave:
                    dispatched.append(
                        (wave, slots, bucket) + self._dispatch_wave(
                            wave, slots, bucket))
            for wave, slots, bucket, first_dev, span, stall, disp_s, \
                    dev_key in dispatched:
                self._complete_wave(wave, slots, first_dev, span,
                                    bucket, stall, dispatch_s=disp_s,
                                    dev_key=dev_key)
                if on_wave is not None:
                    on_wave()
            # on_wave may have drained fresh arrivals into ``waiting``
            # — the outer loop admits them while slots remain.
        if quota_held:
            self.waiting.extendleft(reversed(quota_held))
            ENGINE_WAITING.set(len(self.waiting))

    def _use_chunked(self, req: Request) -> bool:
        return (self.prefill_chunk is not None
                and self._ctx_len(req) > self.prefill_chunk)

    def _claim_chunked(self, req: Request) -> str:
        """Claim a slot for an incremental prefill: look up the prefix
        cache, reuse a hit's rows (suffix-only prefill), and queue the
        remaining chunks. The claim stamps the slot's cache length to
        max_len so interleaved decode bursts' garbage writes for this
        (inactive) slot land out of bounds and are dropped — they must
        never corrupt rows a finished chunk already wrote.

        Paged: a hit maps the stored prefix's ref-counted blocks into
        the slot's table — NO row copies. A partially-filled shared
        block (block_len not dividing the cached length) is copied on
        write first (`skytpu_kv_cow_copies_total`): this slot's suffix
        prefill writes into it at offset cached%block. Contiguous: the
        hit copies the pool row on-device as before. Returns "ok"
        (claimed), "failed" (adapter load failed — the request was
        consumed, failed typed), "held" (adapter pool fully pinned —
        the caller steps this request aside, everyone behind it keeps
        admitting), or "stall" (paged block pool dry — the request was
        re-queued at the head and admission pauses).
        """
        st = self._acquire_adapter(req)
        if st == "failed":
            return "failed"  # consumed (failed typed); keep admitting
        if st == "stall":
            self._mark_stall(req, "adapter_pin")
            return "held"    # adapter pool pinned: step aside
        ctx = self._ctx(req)
        idx = self._prefix_index
        hit = (idx.lookup(ctx, self._prefix_salt(req))
               if idx is not None else None)
        payload = cached = None
        n_shared = partial = 0
        shared: List[int] = []
        new_blocks: Optional[List[int]] = None
        if self.paged:
            if hit is not None:
                payload, cached = hit
                n_shared, partial = divmod(cached, self.kv_block)
                # PIN the shared blocks BEFORE any dry-pool eviction:
                # _alloc_blocks may evict the hit's own entry, and an
                # unpinned payload block could be freed and handed
                # straight back as a fresh block — one physical block
                # aliased at two table positions, silently corrupting
                # the cached prefix the request is about to read.
                shared = list(payload[:n_shared])
                for b in shared:
                    self.allocator.incref(b)
            # Lazy reservations can be SMALLER than the shared prefix
            # rounds to; never ask for a negative count.
            new_blocks = self._alloc_blocks(
                max(self._need_blocks(req, len(ctx)) - n_shared, 0))
            if new_blocks is None:
                for b in shared:          # unpin; retry next pass
                    self.allocator.decref(b)
                self._release_adapter(req)
                self._mark_stall(req, "pool_dry")
                self._requeue(req)
                return "stall"
        slot = self.free_slots.pop(0)
        self._set_slot_adapter(slot, req.adapter_slot)
        req.slot = slot
        self._end_stall(req)
        req.prefill_begin_s = time.time()
        tracing.record_span(
            "engine.queue_wait", req.submit_s, req.prefill_begin_s,
            parent=req.span_ctx, attrs={"rid": req.rid})
        claim_len = jnp.asarray(self.max_len, jnp.int32)
        reused = 0
        if self.paged:
            row = self.block_table[slot]
            row[:] = self.n_kv_blocks
            if hit is not None:
                reused = cached
                PREFIX_HITS.inc()
                self._prefix_hit_n += 1
                row[:n_shared] = shared   # pinned above
                if partial:
                    # COW the partial shared block BEFORE the suffix
                    # prefill writes into it (its owner keeps ref > 1,
                    # so nothing else may scatter there).
                    self.cache = self._copy_block_fn(
                        self.cache,
                        jnp.asarray(payload[n_shared], jnp.int32),
                        jnp.asarray(new_blocks[0], jnp.int32))
                    KV_COW_COPIES.inc()
                    self._fl_cow += 1
            elif idx is not None and idx.eligible(ctx):
                PREFIX_MISSES.inc()
                self._prefix_miss_n += 1
            row[n_shared:n_shared + len(new_blocks)] = new_blocks
            self._table_dirty = True
            self._sync_kv_charge(slot, req.tenant)
            self.cache = self._claim_fn(
                self.cache, jnp.asarray(slot, jnp.int32), claim_len)
        elif hit is not None:
            payload, cached = hit
            reused = cached
            PREFIX_HITS.inc()
            self._prefix_hit_n += 1
            self.cache = self._pool_load_fn(
                self.cache, self.pool, jnp.asarray(payload, jnp.int32),
                jnp.asarray(slot, jnp.int32), claim_len)
        else:
            if idx is not None and idx.eligible(ctx):
                PREFIX_MISSES.inc()
                self._prefix_miss_n += 1
            self.cache = self._claim_fn(
                self.cache, jnp.asarray(slot, jnp.int32), claim_len)
        if req.tokens:
            # Preemption resume: the trailer's cached_len keeps the
            # ORIGINAL admission's prompt-prefix story; warm-resume
            # reuse is its own stat.
            req.resumed_len = reused
        else:
            req.cached_len = reused
        self.chunking.append(_ChunkState(req=req, pos=reused,
                                         total=len(ctx), ctx=ctx))
        # The request left ``waiting``; without this the queue-depth
        # gauge overreports by one per claim for the whole (possibly
        # multi-second) chunked prefill.
        self._update_gauges()
        return "ok"

    def prefill_chunk_step(self) -> bool:
        """Run ONE chunk of the head chunked prefill (host-synced: the
        scheduler deliberately alternates chunk -> decode burst, so the
        chunk's device time is the decode stall it causes — recorded
        into skytpu_decode_stall_seconds when slots were decoding).
        Returns True if a chunk ran. Runs behind the ``chunk`` dispatch
        boundary: a device failure mid-chunk surfaces as a recoverable
        :class:`EngineDispatchError`."""
        if not self.chunking:
            return False
        with _dispatch_boundary("chunk"):
            return self._prefill_chunk_impl()

    def _prefill_chunk_impl(self) -> bool:
        st = self.chunking[0]
        req = st.req
        ctx = st.ctx if st.ctx is not None else req.prompt
        C = self.prefill_chunk
        start = st.pos
        n_valid = min(C, st.total - start)
        final = start + n_valid >= st.total
        chunk = np.zeros((C,), np.int32)
        chunk[:n_valid] = ctx[start:start + n_valid]
        new_len = st.total if final else self.max_len
        decode_active = bool(self.slot_req)
        # The big-cache dot reads only rows below this chunk's offset:
        # the span bucket covering ``start`` suffices, and because the
        # span is a pure function of the offset, warm (suffix-only)
        # and cold runs of the same chunk pick the same program —
        # the cached-vs-cold parity guarantee extends to spans.
        attn_span = self._span_arg(self._span_for(start))
        self.decode_programs.add(("chunk", final, attn_span))
        t0 = time.time()
        self.cache, self.rng, tok_dev = self._prefill_chunk_fn(
            self.params, self.cache, jnp.asarray(chunk),
            jnp.asarray(start, jnp.int32),
            jnp.asarray(n_valid, jnp.int32),
            jnp.asarray(req.slot, jnp.int32),
            jnp.asarray(new_len, jnp.int32), self.rng,
            self.table_device(), final=final, qweights=self.qweights,
            span=attn_span, kernel=self.kv_kernel,
            **self._lora_args())
        t_disp = time.time()             # dispatch returned; fetch next
        chunk_key = self.compile_watch.last_key
        tok = int(tok_dev)               # host sync (garbage unless final)
        dt = time.time() - t0
        PREFILL_CHUNKS.inc()
        req.n_chunks += 1
        if decode_active:
            DECODE_STALL_SECONDS.observe(dt)
        self._record_flight(
            "chunk", begin_s=t0, end_s=t0 + dt,
            program={"span": attn_span, "final": final},
            slots=[req.slot], reqs=[req], toks=1 if final else 0,
            stall=decode_active, dispatch_s=t_disp,
            dev_keys=[chunk_key])
        st.pos += n_valid
        if not final:
            return True
        self.chunking.popleft()
        now = time.time()
        tracing.record_span(
            "engine.prefill", req.prefill_begin_s, now,
            parent=req.span_ctx,
            attrs={"rid": req.rid, "bucket": "chunked",
                   "cached_len": req.cached_len,
                   "chunks": req.n_chunks})
        req.tokens.append(tok)
        if req.first_token_s is None:
            # A preemption resume already served its first token —
            # TTFT is a once-per-request truth.
            req.first_token_s = now
            TTFT_SECONDS.observe(max(now - req.submit_s, 0.0))
        PREFILL_SECONDS.labels(bucket="chunked").observe(
            max(now - req.prefill_begin_s, 0.0))
        PREFILL_REQUESTS.labels(bucket="chunked").inc()
        self.slot_req[req.slot] = req
        self._store_prefix(ctx, req.slot, len(ctx),
                           salt=self._prefix_salt(req))
        if self._req_finished(req, tok):
            self._retire(req)
        self._update_gauges()
        return True

    def _store_prefix(self, ctx: List[int], slot: Optional[int],
                      rows: int, donor_live: bool = True,
                      salt: bytes = b"") -> int:
        """Install ``ctx``'s chunk-aligned prefix (over the slot's
        first ``rows`` resident rows) into the prefix cache unless it
        is already resident. Returns the number of rows actually
        installed — 0 on every skip path (no index, sub-chunk prefix,
        already covered, dry pool, contiguous dead donor) — so a
        caller can tell a real install from a no-op. Only chunk-path sequences are stored:
        their rows came from the chunk program, so a later cached run
        replays bit-identical state (the parity guarantee) — and a
        preempted slot's rows are the literal bytes decode committed,
        which is exactly what its resume must read back.

        Paged: storing is (mostly) FREE — the slot's full blocks over
        the prefix are increfed and recorded as the entry's payload, no
        row copies. A trailing partial block is copied-on-share while
        the donor LIVES (it keeps writing into its own copy past the
        prefix; `skytpu_kv_cow_copies_total`); a dying donor
        (preemption-by-eviction) shares the partial block by incref
        alone — no writer remains, so eviction stays a pure table
        edit. Contiguous: the slot's rows copy into a pool row as
        before (live donors only; a contiguous eviction resumes
        cold)."""
        idx = self._prefix_index
        if idx is None or slot is None:
            return 0
        n = (rows // idx.block) * idx.block
        if n < idx.block:
            return 0
        covered = idx.lookup(ctx, salt)
        if covered is not None and covered[1] >= n:
            return 0
        if self.paged:
            n_full, partial = divmod(n, self.kv_block)
            nb = n_full + (1 if partial else 0)
            blocks = self.block_table[slot, :nb].tolist()
            if partial and donor_live:
                cow = self._alloc_blocks(1)
                if cow is None:      # pool dry: skip storing
                    return 0
                self.cache = self._copy_block_fn(
                    self.cache,
                    jnp.asarray(blocks[n_full], jnp.int32),
                    jnp.asarray(cow[0], jnp.int32))
                KV_COW_COPIES.inc()
                self._fl_cow += 1
                blocks[n_full] = cow[0]
            for b in blocks[:n_full]:
                self.allocator.incref(b)
            if partial and not donor_live:
                self.allocator.incref(blocks[n_full])
            for payload in idx.insert_entry(ctx, n, tuple(blocks),
                                            salt):
                PREFIX_EVICTIONS.inc()
                self._fl_evictions += 1
                for b in payload:
                    self.allocator.decref(b)
            self._update_gauges()
            return n
        if not donor_live:
            return 0
        row, evicted = idx.acquire_row()
        if evicted:
            PREFIX_EVICTIONS.inc()
            self._fl_evictions += 1
        self.pool = self._pool_store_fn(
            self.pool, self.cache, jnp.asarray(slot, jnp.int32),
            jnp.asarray(row, jnp.int32))
        idx.register(ctx, n, row, salt)
        return n

    def clear_prefix_cache(self) -> None:
        """Drop every resident prefix. Paged: the entries' block refs
        are released (blocks still mapped into live slots stay until
        those retire). Contiguous: host index only — the pool rows
        become unreachable. Benchmarks use this to measure a cold pass
        against a warm one on the same engine."""
        idx = self._prefix_index
        if idx is None:
            return
        if self.paged:
            for payload in idx.payloads():
                for b in payload:
                    self.allocator.decref(b)
        idx.clear()
        self._update_gauges()

    # -- cross-replica KV handoff (disaggregated serving) ------------------

    def handoff_eligible(self, prompt: List[int],
                         max_new_tokens: int) -> bool:
        """Whether a request prefilled HERE can hand its KV off to
        another replica: paged layout + prefix cache on, and the
        resumed context (prompt + the one committed token) must take
        the chunk-path resume on the receiving tier — the same
        ``_resumable`` conditions preemption requires, because a
        handoff IS a preemption with a network hop. Single-token
        budgets stay single-tier: there is nothing left to decode."""
        return (self.paged
                and self._prefix_index is not None
                and self._prefix_index.eligible(prompt)
                and max_new_tokens > 1
                and self._resumable(len(prompt) + 1))

    def export_prefix_for(self, req: Request) -> Optional[Dict[str, Any]]:
        """Host-side snapshot of the retired request's stored prefix —
        block contents + lengths — for transfer to a decode-tier
        replica. The chunk path stored the prefix at final-chunk
        completion (:meth:`_store_prefix`), so this is a PrefixIndex
        lookup plus ONE fixed-shape device gather; the entry's blocks
        stay ref-counted LRU residents here (nothing to leak — a
        handoff leaves the donor exactly as warm as any cached serve).
        Returns None when no chunk-aligned prefix is resident (the
        caller falls back to single-tier)."""
        idx = self._prefix_index
        if not self.paged or idx is None:
            return None
        ctx = self._ctx(req)
        salt = self._prefix_salt(req)
        hit = idx.lookup(ctx, salt)
        if hit is None:
            return None
        payload, cached = hit
        nb = len(payload)
        ids = np.full((self.blocks_per_slot,), self.n_kv_blocks,
                      np.int32)
        ids[:nb] = payload
        vals = self._export_blocks_fn(self.cache, jnp.asarray(ids))
        tensors = {}
        for name, v in vals.items():
            arr = np.ascontiguousarray(np.asarray(v)[:, :nb])
            tensors[name] = arr
        # The salt rides the export: an adapter-scoped prefix must be
        # re-inserted on the decode tier under the SAME content digest
        # its claim-time lookup will use (the fleet shares one catalog,
        # so the decode replica's hot-load reproduces the digest).
        return {"cached_len": cached, "kv_block": self.kv_block,
                "n_blocks": nb, "salt": salt, "tensors": tensors}

    def import_prefix(self, ctx: List[int], export: Dict[str, Any],
                      salt: bytes = b"") -> int:
        """Install another replica's exported prefix into this
        engine's pool + PrefixIndex so the handed-off request resumes
        through the ordinary prefix-hit suffix prefill. Returns the
        cached rows now resident for ``ctx`` (0 = nothing imported —
        layout/geometry mismatch or a dry pool; the caller's request
        still runs correctly, just cold). Loop-thread only: allocates
        blocks and swaps the donated cache."""
        idx = self._prefix_index
        if not self.paged or idx is None:
            return 0
        if export.get("kv_block") != self.kv_block:
            return 0            # geometry mismatch: resume cold
        cached = int(export["cached_len"])
        nb = int(export["n_blocks"])
        tensors = export["tensors"]
        for name in ("k", "v"):
            want = self.cache[name]
            have = tensors.get(name)
            # The wire widens sub-fp32 float planes to float32 (exact;
            # the scatter casts back), so a float32 payload matches a
            # bfloat16 pool; int8-vs-float is a REAL quant-config
            # mismatch and resumes cold.
            ok_dtype = (str(have.dtype) == str(want.dtype)
                        if have is not None else False) or (
                have is not None
                and str(have.dtype) == "float32"
                and jnp.issubdtype(want.dtype, jnp.floating))
            if (have is None or have.shape[0] != want.shape[0]
                    or have.shape[2:] != want.shape[2:]
                    or not ok_dtype):
                return 0        # model/dtype mismatch: resume cold
        if ("k_scale" in self.cache) != ("k_scale" in tensors):
            return 0
        covered = idx.lookup(ctx, salt)
        if covered is not None and covered[1] >= cached:
            return covered[1]   # already at least as warm
        blocks = self._alloc_blocks(nb)
        if blocks is None:
            return 0            # pool dry: resume cold
        ids = np.full((self.blocks_per_slot,), self.n_kv_blocks,
                      np.int32)
        ids[:nb] = blocks
        pad = self.blocks_per_slot - nb
        vals = {}
        for name, arr in tensors.items():
            if pad:
                arr = np.concatenate(
                    [arr, np.zeros((arr.shape[0], pad) + arr.shape[2:],
                                   arr.dtype)], axis=1)
            vals[name] = jnp.asarray(arr)
        self.cache = self._import_blocks_fn(
            self.cache, jnp.asarray(ids), vals)
        for payload in idx.insert_entry(ctx, cached, tuple(blocks),
                                        salt):
            PREFIX_EVICTIONS.inc()
            self._fl_evictions += 1
            for b in payload:
                self.allocator.decref(b)
        self._update_gauges()
        return cached

    def _dispatch_wave(self, wave: List["Request"], slots: List[int],
                       bucket: int
                       ) -> Tuple[jax.Array, timeline.Event, bool,
                                  float, Optional[str]]:
        """Enqueue one wave's prefill+insert program; returns the
        (device) first-token array without forcing a host sync, the
        open prefill span (closed at completion — the span covers
        dispatch THROUGH first-token fetch, the latency a request
        actually experiences), and whether decode slots were active at
        dispatch (the wave then also counts as decode stall)."""
        WAVE_SIZE.observe(len(wave))
        span = timeline.Event(
            "skytpu_prefill_seconds",
            histogram=PREFILL_SECONDS.labels(bucket=str(bucket)))
        span.begin()
        for req in wave:
            # Queue wait ends where the prefill dispatch begins.
            tracing.record_span(
                "engine.queue_wait", req.submit_s, span.begin_s,
                parent=req.span_ctx, attrs={"rid": req.rid})
        if self.pad_waves:
            n = self.max_wave
        else:
            n = 1 << (len(wave) - 1).bit_length() if len(wave) > 1 else 1
        tokens_b = np.zeros((n, bucket), np.int32)
        true_lens = np.ones((n,), np.int32)
        slot_ids = np.full((n,), self.n_slots, np.int32)  # spare
        for i, (req, slot) in enumerate(zip(wave, slots)):
            ctx = self._ctx(req)
            tokens_b[i, :len(ctx)] = ctx
            true_lens[i] = len(ctx)
            slot_ids[i] = slot
        decode_active = bool(self.slot_req)
        wave_lora = {}
        if self.adapters is not None:
            # Per-wave-row adapter ids (dummy rows ride the all-zeros
            # base slot): the wave's rows each gather their own
            # fine-tune — mixed-adapter admission is one dispatch.
            aid_w = np.zeros((n,), np.int32)
            for i, req in enumerate(wave):
                aid_w[i] = req.adapter_slot
            wave_lora = {"lora": self.adapters.pool,
                         "aid": jnp.asarray(aid_w)}
        self.cache, self.rng, first = self._admit_wave_fn(
            self.params, self.cache, jnp.asarray(tokens_b),
            jnp.asarray(true_lens), jnp.asarray(slot_ids), self.rng,
            self.table_device(), bucket=bucket, qweights=self.qweights,
            **wave_lora)
        return (first, span, decode_active, time.time(),
                self.compile_watch.last_key)

    def _complete_wave(self, wave: List["Request"], slots: List[int],
                       first_dev: jax.Array, span: timeline.Event,
                       bucket: int, decode_active: bool = False,
                       dispatch_s: Optional[float] = None,
                       dev_key: Optional[str] = None) -> None:
        first = np.asarray(first_dev)          # host sync for THIS wave
        span.end()
        now = time.time()
        if decode_active:
            DECODE_STALL_SECONDS.observe(max(now - span.begin_s, 0.0))
        self._record_flight(
            "wave", begin_s=span.begin_s, end_s=now,
            program={"bucket": bucket, "rows": first.shape[0]},
            slots=slots, reqs=wave, toks=len(wave),
            stall=decode_active, dispatch_s=dispatch_s,
            dev_keys=[dev_key])
        for req in wave:
            # The latency the request experienced: dispatch through
            # first-token fetch (same window as the histogram span).
            tracing.record_span(
                "engine.prefill", span.begin_s, now,
                parent=req.span_ctx,
                attrs={"rid": req.rid, "bucket": bucket,
                       "cached_len": 0, "chunks": 0})
        for i, (req, slot) in enumerate(zip(wave, slots)):
            tok = int(first[i])
            req.slot = slot
            req.tokens.append(tok)
            if req.first_token_s is None:      # not a preemption resume
                req.first_token_s = now
                TTFT_SECONDS.observe(max(now - req.submit_s, 0.0))
            PREFILL_REQUESTS.labels(bucket=str(bucket)).inc()
            self.slot_req[slot] = req
            if self._req_finished(req, tok):
                self._retire(req)
        self._update_gauges()


    # -- stepping ----------------------------------------------------------

    def _req_finished(self, req: Request, tok: int) -> bool:
        if req.eos_id is not None and tok == req.eos_id:
            return True
        if len(req.tokens) >= req.max_new_tokens:
            return True
        return len(req.prompt) + len(req.tokens) >= self.max_len

    def _retire(self, req: Request) -> None:
        # No cache-length scrub: ``insert`` stamps the slot's length on
        # reuse, decode's commit mask skips non-active slots, and a
        # dead slot's attention output is never read — an eager
        # per-retirement scatter here was pure hygiene at one device
        # dispatch per finished request (reset() still zeroes all).
        req.done = True
        self.finished.append(req)
        REQUESTS_FINISHED.inc()
        now = time.time()
        decoded = req.first_token_s is not None and len(req.tokens) > 1
        if req.span_ctx is not None:
            if decoded:
                # ONE decode span per request (first token ->
                # retirement): a span per slot per burst floods the
                # flight-recorder ring at high occupancy — 64 slots at
                # ~100 bursts/s would leave only seconds of history.
                # Device-call timing stays on the
                # skytpu_decode_step_seconds histogram/timeline span.
                tracing.record_span(
                    "engine.decode", req.first_token_s, now,
                    parent=req.span_ctx,
                    attrs={"rid": req.rid,
                           "tokens": len(req.tokens) - 1})
            tracing.record_span(
                "engine.request", req.submit_s, now,
                ctx=req.span_ctx, parent_id=req.parent_id,
                attrs={"rid": req.rid, "prompt_len": len(req.prompt),
                       "n_tokens": len(req.tokens)})
        if decoded:
            TPOT_SECONDS.observe(
                max(now - req.first_token_s, 0.0)
                / (len(req.tokens) - 1))
        if self.forensics:
            # Request forensics: ONE retirement record anchors the
            # critical-path ledger (submit/first-token/end stamps +
            # closed stall episodes — `skytpu why` reassembles the
            # request's bursts around it), then the streaming tail
            # detector decides whether this request's evidence is
            # worth pinning past ring rollover. Host bookkeeping
            # only, once per request, off the burst path.
            self._end_stall(req)
            fl = self.flight
            if fl is not None and fl.enabled:
                fl.record(
                    "retire", ts_s=now, dur_s=0.0,
                    program={"layout":
                             "paged" if self.paged else "contig"},
                    slots=[req.slot] if req.slot is not None else [],
                    rids=[req.rid],
                    traces=[req.span_ctx.trace_id]
                    if req.span_ctx is not None else [],
                    toks=0, submit_s=req.submit_s,
                    first_token_s=req.first_token_s, end_s=now,
                    prompt_len=len(req.prompt),
                    n_toks=len(req.tokens),
                    cached_len=req.cached_len,
                    resumed_len=req.resumed_len,
                    n_chunks=req.n_chunks,
                    spec_drafted=req.spec_drafted,
                    spec_accepted=req.spec_accepted,
                    preemptions=req.preemptions,
                    stalls={k: round(v, 4)
                            for k, v in req.stall_ms.items()},
                    tenants={req.tenant: 1}, adapter=req.adapter)
            self._observe_tail(req, now)
        if req.slot is not None:
            self.slot_req.pop(req.slot, None)
            self.free_slots.append(req.slot)
            self._free_slot_blocks(req.slot)
            self._set_slot_adapter(req.slot, 0)
            if self.draft_engine is not None:
                # Drafter lifecycle rides the slot's: the mirrored
                # draft slot frees its blocks with the main slot (a
                # reused slot's next occupant re-ingests from zero).
                self.draft_engine.release(req.slot)
            req.slot = None
        self._release_adapter(req)
        SLOTS_ACTIVE.set(len(self.slot_req))
        if self.paged:
            KV_BLOCKS_USED.set(self.allocator.used)

    def _observe_tail(self, req: Request, now: float) -> None:
        """Streaming tail detection at retirement: fold this request's
        TTFT/TPOT into the P2 estimators (O(1) host floats), and when
        it crosses the configured quantile pin its FULL evidence —
        retirement record, every flight record it rode, its assembled
        ledger — into the exemplar store. The ring scan happens only
        on a crossing (~1 in 10^3 at the default p99.9), never on the
        ordinary retire path."""
        if metrics.suppressed():      # warmup must not skew the tail
            return
        hits = []
        if req.first_token_s is not None:
            ttft_ms = max(req.first_token_s - req.submit_s, 0.0) * 1e3
            crossed, thr = self.tail.observe("ttft", ttft_ms)
            if crossed:
                hits.append(("ttft", ttft_ms, thr))
            if len(req.tokens) > 1:
                tpot_ms = (max(now - req.first_token_s, 0.0) * 1e3
                           / (len(req.tokens) - 1))
                crossed, thr = self.tail.observe("tpot", tpot_ms)
                if crossed:
                    hits.append(("tpot", tpot_ms, thr))
        if not hits:
            return
        fl = self.flight
        recs: List[Dict[str, Any]] = []
        retire = None
        if fl is not None and fl.enabled:
            for r in fl.tail():
                if req.rid in (r.get("rids") or ()):
                    recs.append(r)
                    if r.get("burst") == "retire":
                        retire = r
        ledger = (forensics_lib.build_ledger(retire, recs)
                  if retire is not None else None)
        for metric, value, thr in hits:
            forensics_lib.TAIL_EXEMPLARS_PINNED.labels(
                metric=metric).inc()
            self.exemplars.pin({
                "rid": req.rid, "metric": metric,
                "value_ms": round(value, 4),
                "threshold_ms": (round(thr, 4)
                                 if thr is not None else None),
                "ts_s": now,
                "trace_id": (req.span_ctx.trace_id
                             if req.span_ctx is not None else None),
                "tenant": req.tenant, "adapter": req.adapter,
                "retire": retire, "records": recs, "ledger": ledger})

    def step(self) -> Dict[int, int]:
        """Admit waiting requests (draining any chunked prefills to
        completion — single-step callers want classic semantics),
        decode one token per active slot.

        Returns {rid: token} emitted this step.
        """
        self._admit()
        while self.chunking:
            self.prefill_chunk_step()
        return self.step_decode_once()

    def admit(self, on_wave=None) -> None:
        """Prefill+insert every admissible waiting request (public
        wrapper: the server calls this separately from decode so it can
        size decode bursts AFTER admission — full bursts only when the
        slots are full and admission is impossible anyway)."""
        self._admit(on_wave)

    def reset(self) -> None:
        """Drop every queued and in-flight request and zero the slot
        state. After an engine failure the server must not re-drive
        poisoned slots — stale waiting/slot_req would re-raise the same
        error for every future request (advisor r3)."""
        self.waiting.clear()
        self.chunking.clear()
        self.finished.clear()
        self.slot_req.clear()
        self.free_slots = list(range(self.n_slots))
        self._inflight_tokens = 0
        self.cache["length"] = jnp.zeros_like(self.cache["length"])
        # A mid-copy/mid-chunk failure may have left pool rows (or
        # block refcounts) in an unknown state; drop the index rather
        # than serve them.
        if self.paged:
            # The index entries' refs die with the wholesale pool
            # reset below — clear WITHOUT per-block decrefs (a failure
            # mid-claim may have left counts inconsistent; decref
            # could double-free).
            if self._prefix_index is not None:
                self._prefix_index.clear()
            self.allocator.reset()
            self.block_table[:] = self.n_kv_blocks
            self._table_dirty = True
            self._slot_kv_charge.clear()
            for t in list(self._tenant_kv):
                self._set_tenant_kv(t, 0)
        else:
            self.clear_prefix_cache()
        if self.adapters is not None:
            # A failure mid-hot-load may have left pins inconsistent;
            # drop all residency (pool arrays stay — nothing maps to
            # them until re-acquired).
            self.adapters.reset()
            self.adapter_ids[:] = 0
            self._aid_dirty = True
        if self.draft_engine is not None:
            # Drafter state mirrors the slots just wiped; a failure
            # mid-rollout may have left its counts inconsistent too.
            self.draft_engine.reset()
        self._update_gauges()

    def recover(self, exc: Optional[BaseException] = None) -> int:
        """Crash recovery: full :meth:`reset` (device/host bookkeeping
        may disagree after a failed dispatch — nothing narrower is
        safe), then re-admit every request that was queued or in
        flight through the preemption resume path. A crash is an
        involuntary preemption of EVERY resident at once: each victim
        re-queues with its prompt + committed tokens, re-prefills that
        context via the ordinary (now-cold) chunk admission path, and
        its greedy continuation is bit-identical to an uncrashed run
        (same guard rail as :meth:`preempt_slot` — contexts that still
        fit a wave re-admit through the wave program, which the parity
        matrix does not cover).

        Returns the number of requests re-queued. Requests already
        retired with output stay finished; the server keeps streaming
        the SAME Request objects, so open streams continue gapless.
        """
        # Snapshot before the wipe: residents (decode slots), chunkers
        # (mid-chunked-prefill — disjoint from residents until the
        # final chunk), and the untouched queue. Order within each
        # class is deterministic (rid = arrival order) so a recovered
        # engine admits in the same order every time.
        residents = sorted(self.slot_req.values(), key=lambda r: r.rid)
        chunkers = [st.req for st in self.chunking]
        chunker_rids = {r.rid for r in chunkers}
        queued = list(self.waiting)
        finished = list(self.finished)
        self.reset()
        self.finished.extend(finished)   # retired output survives
        seam = getattr(exc, "seam", None) or "unknown"
        now = time.time()
        victims: List[Request] = []
        seen = set()
        for req in residents + chunkers + queued:
            if req.done or req.rid in seen:
                continue
            seen.add(req.rid)
            victims.append(req)
        for req in victims:
            in_flight = (req.slot is not None
                         or req.rid in chunker_rids)
            # reset() wiped the tables/pins wholesale — scrub the
            # per-request mirrors WITHOUT the release paths (a decref
            # or unpin now would double-free against the wiped state).
            req.slot = None
            req.adapter_pinned = False
            req.adapter_slot = 0
            if in_flight:
                req.recoveries += 1
                # The re-prefill wait is a named stall episode: the
                # ledger's queue-ish gaps consume it into the
                # ``stall_recover`` phase, closed by the next claim.
                self._mark_stall(req, "recover")
            self._requeue(req)
        self.waiting.reverse()           # _requeue prepends; restore order
        ENGINE_RECOVERIES.labels(seam=seam).inc()
        fl = self.flight
        if fl is not None and fl.enabled:
            fl.record(
                "recover", ts_s=now, dur_s=0.0,
                program={"layout": "paged" if self.paged else "contig",
                         "seam": seam},
                slots=[], rids=[r.rid for r in victims],
                toks=0, n_victims=len(victims))
        self._update_gauges()
        return len(victims)

    def step_burst(self, max_burst: int = 8,
                   on_wave=None) -> Dict[int, List[int]]:
        """Admit, run ONE prefill chunk if any are queued (chunk ->
        decode-burst alternation: long prompts prefill without stalling
        decode for their whole length), then decode up to ``max_burst``
        tokens per slot in one device call. Tokens past a request's
        EOS/limit are discarded host-side (their cache rows die with
        the slot). Returns {rid: [tokens...]} emitted this call.
        ``on_wave`` fires after each admission wave (streaming flush
        hook)."""
        self._admit(on_wave)
        if self.chunking:
            self.prefill_chunk_step()
        return self.decode_burst(max_burst)

    def decode_burst(self, max_burst: int = 8) -> Dict[int, List[int]]:
        """Decode up to ``max_burst`` tokens per active slot in one
        device call — NO admission (callers that interleave admission
        and decode use :meth:`admit` + this).

        With speculation enabled (``spec_k > 0``) a verify burst
        REPLACES the plain decode burst: one device call scores K
        drafted tokens + the correction position per slot and commits
        the accepted run. Falls back to a plain burst only for the
        rounds where NO active slot drafted (all missed, collapsed,
        or out of row headroom — a tight slot alone just rides the
        verify burst with an empty draft)."""
        if self.spec_k:
            out = self.spec_decode_burst()
            if out is not None:
                return out
        handle = self.dispatch_decode_burst(max_burst)
        if handle is None:
            return {}
        return self.complete_decode_burst(handle)

    def _spec_mode(self, req: Request) -> str:
        """Resolve (and advance) this request's drafter rung. Requests
        start at "model" when the engine has a DraftEngine, else
        "ngram" (the factory seam — custom test drafters ride it too).
        Acceptance collapse in the CURRENT mode (>= spec_min_drafted
        drafted below spec_min_rate accepted since the last demotion)
        demotes one rung: model -> ngram (fresh window, fresh factory
        drafter, draft-engine slot released) -> off."""
        if req.spec_off:
            return "off"
        if req.spec_mode is None:
            req.spec_mode = ("model" if self.draft_engine is not None
                             else "ngram")
        if req.spec_mode == "model" and self.draft_engine is None:
            # The drafter was detached mid-flight (tests/bench toggle
            # routing between passes): fall to the factory rung with a
            # fresh window rather than dereference a gone engine.
            req.spec_mode = "ngram"
            req.spec_mode_drafted = 0
            req.spec_mode_accepted = 0
        if (req.spec_mode_drafted >= self.spec_min_drafted
                and req.spec_mode_accepted
                < self.spec_min_rate * req.spec_mode_drafted):
            if req.spec_mode == "model":
                req.spec_mode = "ngram"
                req.spec_mode_drafted = 0
                req.spec_mode_accepted = 0
                req.drafter = None       # factory rebuilds on demand
                if self.draft_engine is not None \
                        and req.slot is not None:
                    self.draft_engine.release(req.slot)
            else:
                req.spec_mode = "off"
                req.spec_off = True
        return req.spec_mode

    def _draft_for(self, req: Request) -> List[int]:
        """This request's draft through the per-request factory seam
        (n-gram by default; the demotion rung below the model
        drafter). Host-only: builds the drafter lazily and syncs it
        with tokens committed through any path."""
        if req.drafter is None:
            req.drafter = self._spec_drafter_factory(req)
            if req.drafter is None:          # factory opted this one out
                req.spec_off = True
                req.spec_mode = "off"
                return []
        req.drafter.catch_up(req.prompt, req.tokens)
        return req.drafter.draft(self.spec_k)

    def spec_decode_burst(self) -> Optional[Dict[int, List[int]]]:
        """One draft-and-verify burst for every active slot: the host
        drafter proposes up to K tokens per slot, ONE compiled verify
        program scores the K+1 window positions, and the accepted run
        (+ the correction token) commits — up to K+1 tokens per slot
        per device call instead of 1.

        The verify FETCH is synchronous (the next round's window needs
        these tokens), but with a model drafter and ``spec_pipeline``
        the round is internally overlapped: the NEXT round's draft
        rollout dispatches while the verify program is in flight (the
        device chews on it behind the verify; the host fetches it
        lazily next round), so neither model waits on the other — the
        overlap PR 8's spec engines forfeited by skipping the async
        double-buffer. A mispredicted predraft is discarded host-side
        at the next ``draft_batch`` (drafter rollback = length
        non-advance, free under paged blocks).

        Returns None when the spec path can't run this round and the
        caller should fall back to a plain decode burst: no active
        slot produced a draft (all missed, collapsed, or out of row
        headroom — a K+1-wide verify would then be strictly worse
        than a plain burst).
        """
        K = self.spec_k
        if not self.slot_req or K <= 0:
            return None
        with _dispatch_boundary("verify"):
            return self._spec_decode_burst_impl()

    def _spec_decode_burst_impl(self) -> Optional[Dict[int, List[int]]]:
        K = self.spec_k
        draft = np.zeros((self.n_slots + 1, K), np.int32)
        n_draft = np.zeros((self.n_slots + 1,), np.int32)
        dlen: Dict[int, int] = {}
        model_reqs: Dict[int, Request] = {}
        for slot, req in self.slot_req.items():
            # A slot within K+1 rows of max_len drafts NOTHING instead
            # of disabling speculation engine-wide: its single
            # correction row (at length <= max_len-1, guaranteed for
            # any active request) is in bounds, its spare window rows
            # past max_len drop via the same OOB-scatter net every
            # dead-slot write rides, and every other slot keeps its
            # draft. (Budget needs no check: an active request always
            # has >= 1 token remaining — every commit path retires at
            # the cap via _req_finished.)
            if len(req.prompt) + len(req.tokens) + K + 1 > self.max_len:
                continue
            mode = self._spec_mode(req)
            if mode == "off":
                continue
            if mode == "model":
                # Model-mode slots draft BATCHED below: one draft-
                # model dispatch covers every such slot (the whole
                # point of a DraftEngine over per-request drafters).
                model_reqs[slot] = req
                continue
            d = self._draft_for(req)
            if d:
                n_draft[slot] = len(d)
                draft[slot, :len(d)] = d
                dlen[slot] = len(d)
        if model_reqs:
            batch = self.draft_engine.draft_batch(
                {s: self._ctx(r) for s, r in model_reqs.items()}, K)
            for slot, d in batch.items():
                if d:
                    n_draft[slot] = len(d)
                    draft[slot, :len(d)] = d
                    dlen[slot] = len(d)
        if not dlen:
            return None
        # Span regrouping, exactly as the plain burst: one verify
        # program per span bucket present among the active slots —
        # a slot verifies at ITS group's span, so a long conversation
        # never drags short neighbors back to worst-case reads.
        groups = self._span_groups(K + 1)
        drafted = sum(dlen.get(s, 0)
                      for _, slots in groups for s in slots)
        if not drafted:
            # Every drafting slot was kept out (lazy dry pool): a
            # K+1-wide verify for the rest would be strictly worse
            # than the plain burst the caller falls back to.
            return None
        span = timeline.Event("skytpu_decode_step_seconds",
                              histogram=DECODE_STEP_SECONDS)
        span.begin()
        parts = []
        part_spans: List[Optional[int]] = []
        part_keys: List[Optional[str]] = []
        for attn_span, slots in groups:
            active = np.zeros((self.n_slots + 1,), bool)
            for s in slots:
                active[s] = True
            sarg = self._span_arg(attn_span)
            self.decode_programs.add(("verify", K, sarg))
            DECODE_ATTN_ROWS.observe(attn_span)
            self.cache, toks_dev, commit_dev = self._verify_fn(
                self.params, self.cache, jnp.asarray(draft),
                jnp.asarray(n_draft), jnp.asarray(active),
                self.table_device(), k=K, qweights=self.qweights,
                span=sarg, kernel=self.kv_kernel,
                **self._lora_args())
            parts.append((slots, toks_dev, commit_dev))
            part_spans.append(sarg)
            part_keys.append(self.compile_watch.last_key)
        dispatch_done_s = time.time()   # verify programs all enqueued
        # Pipelined predraft: with the verify program(s) now in
        # flight, roll the draft model forward K+1 steps for the
        # model-drafting slots — its prediction of the verifier's
        # bonus/correction token plus the NEXT round's K drafts. The
        # dispatch is async (the device runs it behind the verify;
        # the tokens fetch lazily at the next draft_batch, which
        # validates them against what the verify actually committed),
        # so the draft model's work overlaps the verify wall instead
        # of serializing after the fetch.
        overlap_s = 0.0
        pre_slots = [s for s in dlen if s in model_reqs]
        if self.spec_pipeline and pre_slots:
            t_d0 = time.time()
            if self.draft_engine.rollout(pre_slots, K + 1):
                t_d1 = time.time()
                overlap_s = t_d1 - t_d0
                SPEC_OVERLAP_WALL.inc(overlap_s)
                self._record_flight(
                    "draft", begin_s=t_d0, end_s=t_d1,
                    program={"k": K + 1, "span": None},
                    slots=pre_slots,
                    reqs=[model_reqs[s] for s in pre_slots], toks=0,
                    drafter="model",
                    dev_keys=[self.draft_engine.compile_watch.last_key],
                    calibrator=getattr(self.draft_engine, "devtime",
                                       None) or self.devtime)
        # THE completion fetch: the verify tokens are this round's
        # output (the next round's window input), so this is the one
        # deliberate sync of the spec path — same role as
        # complete_decode_burst's.
        fetched = [(slots, np.asarray(t), np.asarray(c))
                   for slots, t, c in parts]       # [B, K+1] / [B]
        span.end()
        end_s = time.time()
        SPEC_VERIFY_WALL.inc(max(end_s - span.begin_s, 0.0))
        out: Dict[int, List[int]] = {}
        n_emitted = accepted = 0
        model_drafted = ngram_drafted = 0
        for part_i, ((slots, toks, n_commit), sarg) in enumerate(
                zip(fetched, part_spans)):
            grp_emitted = grp_drafted = grp_accepted = 0
            grp_reqs: List[Request] = []
            grp_kinds = set()
            for slot in slots:
                req = self.slot_req.get(slot)
                if req is None or req.done:
                    continue
                nd = dlen.get(slot, 0)
                nc = int(n_commit[slot])
                emitted: List[int] = []
                for i in range(nc):
                    tok = int(toks[slot, i])
                    emitted.append(tok)
                    req.tokens.append(tok)
                    if self._req_finished(req, tok):
                        self._retire(req)
                        break
                # Accepted = matched draft tokens the request actually
                # emitted: the first nc-1 outputs are the matched run,
                # the nc-th the correction/bonus — an early EOS/budget
                # retire discards the tail, and counting the full run
                # would inflate the trailer stats and the acceptance
                # gauge on EOS-heavy workloads.
                acc = min(len(emitted), nc - 1)
                req.spec_drafted += nd
                req.spec_accepted += acc
                req.spec_mode_drafted += nd
                req.spec_mode_accepted += acc
                if nd:
                    if slot in model_reqs:
                        model_drafted += nd
                        grp_kinds.add("model")
                    else:
                        ngram_drafted += nd
                        grp_kinds.add("ngram")
                accepted += acc
                out[req.rid] = emitted
                n_emitted += len(emitted)
                grp_emitted += len(emitted)
                grp_drafted += nd
                grp_accepted += acc
                grp_reqs.append(req)
            self._record_flight(
                "verify", begin_s=span.begin_s, end_s=end_s,
                program={"k": K, "span": sarg},
                slots=slots, reqs=grp_reqs, toks=grp_emitted,
                drafted=grp_drafted, accepted=grp_accepted,
                drafter=("mixed" if len(grp_kinds) > 1
                         else next(iter(grp_kinds), None)),
                overlap_ms=round(overlap_s * 1e3, 3),
                dispatch_s=dispatch_done_s,
                dev_keys=[part_keys[part_i]] if part_i < len(part_keys)
                else None)
        if model_drafted:
            SPEC_DRAFT_TOKENS.labels(drafter="model").inc(model_drafted)
        if ngram_drafted:
            SPEC_DRAFT_TOKENS.labels(drafter="ngram").inc(ngram_drafted)
        SPEC_DRAFTED.inc(drafted)
        if accepted:
            SPEC_ACCEPTED.inc(accepted)
        if drafted > accepted:
            SPEC_ROLLBACKS.inc(drafted - accepted)
        self._spec_drafted_total += drafted
        self._spec_accepted_total += accepted
        SPEC_ACCEPT_RATE.set(self._spec_accepted_total
                             / self._spec_drafted_total)
        if n_emitted:
            DECODE_TOKENS.inc(n_emitted)
        return out

    def dispatch_decode_burst(self, max_burst: int = 8
                              ) -> Optional["BurstHandle"]:
        """Enqueue one decode-burst program WITHOUT fetching its tokens;
        pass the handle to :meth:`complete_decode_burst` later.

        This is the TPU-idle killer for streaming servers: dispatch
        burst k+1, THEN fetch/stream burst k's tokens — the device
        chews on k+1 (programs chain on the donated cache) while the
        host does JSON framing, socket writes and LB hops for k. The
        burst cap accounts for tokens still in flight, and slots whose
        request retires at k's completion simply waste rows in k+1
        (their tokens are discarded; OOB cache writes clamp into the
        dead slot's own rows).

        Returns ``None`` when there is nothing to decode — no active
        slot, or every active request's remaining budget is already
        covered by in-flight tokens.
        """
        if not self.slot_req:
            return None
        with _dispatch_boundary("decode"):
            return self._dispatch_decode_burst_impl(max_burst)

    def _dispatch_decode_burst_impl(self, max_burst: int
                                    ) -> Optional["BurstHandle"]:
        # Cap the burst so no active slot's cache can overflow (counting
        # dispatched-but-uncommitted tokens), then round down to a power
        # of two: each distinct k compiles its own program, so the
        # k-space must stay tiny. (Tokens a request doesn't need are
        # discarded host-side — cheaper than a recompile.)
        k = max_burst
        need = 0
        for req in self.slot_req.values():
            rows = (len(req.prompt) + len(req.tokens)
                    + self._inflight_tokens)
            k = min(k, self.max_len - rows)
            need = max(need, req.max_new_tokens - len(req.tokens)
                       - self._inflight_tokens)
        if k < 1 or need < 1:
            return None
        k = 1 << (k.bit_length() - 1)
        # Span regrouping: one program per span bucket present among
        # the active slots, so a single long conversation promotes
        # only ITS group to the big gather (lazy mode also grows each
        # slot's blocks here; unbackable slots sit the round out).
        groups = self._span_groups(k)
        if not groups:
            return None            # lazy: pool dry — retry next round
        ev = timeline.Event("skytpu_decode_step_seconds",
                            histogram=DECODE_STEP_SECONDS)
        ev.begin()
        parts: List[Tuple[jax.Array, List[int]]] = []
        part_spans: List[Optional[int]] = []
        part_keys: List[Optional[str]] = []
        for attn_span, slots in groups:
            active = np.zeros((self.n_slots + 1,), bool)
            for s in slots:
                active[s] = True
            sarg = self._span_arg(attn_span)
            self.decode_programs.add(("burst", k, sarg))
            DECODE_ATTN_ROWS.observe(attn_span)
            self.cache, self.rng, toks = self._decode_burst_fn(
                self.params, self.cache, self.rng, jnp.asarray(active),
                self.table_device(), k=k, qweights=self.qweights,
                span=sarg, kernel=self.kv_kernel,
                **self._lora_args())
            parts.append((toks, slots))
            part_spans.append(sarg)
            part_keys.append(self.compile_watch.last_key)
        self._inflight_tokens += k
        return BurstHandle(parts=parts, k=k,
                           slot_req=dict(self.slot_req), span=ev,
                           spans=part_spans, keys=part_keys,
                           dispatch_done_s=time.time())

    def complete_decode_burst(self, handle: "BurstHandle"
                              ) -> Dict[int, List[int]]:
        """Fetch a dispatched burst's tokens (host sync) and do the
        bookkeeping: append/retire per request, using the slot->request
        snapshot taken at dispatch. Requests retired by an earlier
        completion are skipped (their surplus tokens are discarded);
        slots a lazy dry pool kept out of the burst simply have no
        part and emit nothing this round."""
        with _dispatch_boundary("decode"):
            return self._complete_decode_burst_impl(handle)

    def _complete_decode_burst_impl(self, handle: "BurstHandle"
                                    ) -> Dict[int, List[int]]:
        fetched = [(np.asarray(toks_dev), slots)
                   for toks_dev, slots in handle.parts]
        if handle.span is not None:
            handle.span.end()
        end_s = time.time()
        begin_s = (handle.span.begin_s if handle.span is not None
                   else end_s)
        self._inflight_tokens -= handle.k
        out: Dict[int, List[int]] = {}
        n_emitted = 0
        for part_i, (toks, slots) in enumerate(fetched):
            # toks: [k, slots+1]
            part_emitted = 0
            part_reqs: List[Request] = []
            for slot in slots:
                req = handle.slot_req.get(slot)
                if req is None or req.done:
                    continue
                emitted = []
                for i in range(handle.k):
                    tok = int(toks[i, slot])
                    emitted.append(tok)
                    req.tokens.append(tok)
                    if self._req_finished(req, tok):
                        self._retire(req)
                        break
                out[req.rid] = emitted
                part_emitted += len(emitted)
                part_reqs.append(req)
            n_emitted += part_emitted
            self._record_flight(
                "decode", begin_s=begin_s, end_s=end_s,
                program={"k": handle.k,
                         "span": (handle.spans[part_i]
                                  if part_i < len(handle.spans)
                                  else None)},
                slots=slots, reqs=part_reqs, toks=part_emitted,
                dispatch_s=handle.dispatch_done_s,
                dev_keys=([handle.keys[part_i]]
                          if part_i < len(handle.keys) else None))
        if n_emitted:
            DECODE_TOKENS.inc(n_emitted)
        return out

    def step_decode_once(self) -> Dict[int, int]:
        """One single-token decode for all active slots (no admission).
        Runs at ONE span — the bucket covering the longest active slot
        (the single-step path is the classic-semantics fallback; the
        burst path is where regrouping pays)."""
        if not self.slot_req:
            return {}
        active = np.zeros((self.n_slots + 1,), bool)
        rows_max = 0
        for s, req in self.slot_req.items():
            if not self._ensure_headroom(s, req,
                                         self._slot_rows(req) + 1):
                continue            # lazy: pool dry — sits this out
            active[s] = True
            rows_max = max(rows_max, self._slot_rows(req))
        if not rows_max:
            # Lazy mode only (eager slots always have headroom): the
            # sync single-step path has no outstanding burst whose
            # completion could free blocks, so an all-slots-unbackable
            # round is a genuine wedge — raise like run_to_completion,
            # never spin silently.
            raise KvPoolWedgedError(
                "KV block pool exhausted: lazy growth cannot back any "
                "active slot — size SKYTPU_KV_BLOCKS for the live "
                "working set or disable SKYTPU_KV_LAZY")
        sarg = self._span_arg(self._span_for(rows_max))
        self.decode_programs.add(("decode1", 1, sarg))
        ev = timeline.Event("skytpu_decode_step_seconds",
                            histogram=DECODE_STEP_SECONDS)
        ev.begin()
        self.cache, self.rng, toks = self._decode_fn(
            self.params, self.cache, self.rng, jnp.asarray(active),
            self.table_device(), qweights=self.qweights, span=sarg,
            **self._lora_args())
        t_disp = time.time()
        step_key = self.compile_watch.last_key
        toks = np.asarray(toks)
        ev.end()
        out: Dict[int, int] = {}
        step_slots: List[int] = []
        step_reqs: List[Request] = []
        for slot, req in list(self.slot_req.items()):
            if not active[slot]:
                continue
            tok = int(toks[slot])
            req.tokens.append(tok)
            out[req.rid] = tok
            step_slots.append(slot)
            step_reqs.append(req)
            if self._req_finished(req, tok):
                self._retire(req)
        DECODE_TOKENS.inc(len(out))
        self._record_flight(
            "decode1", begin_s=ev.begin_s, end_s=time.time(),
            program={"k": 1, "span": sarg},
            slots=step_slots, reqs=step_reqs, toks=len(out),
            dispatch_s=t_disp, dev_keys=[step_key])
        return out

    def run_to_completion(self, max_burst: int = 8) -> List[Request]:
        """Drain all waiting + active requests; returns finished list.

        Lazy mode can genuinely wedge: every active slot needs blocks
        the pool cannot grow and nothing is left to retire. Eager
        admission makes that impossible by construction; here the
        stall is detected and raised instead of spinning forever."""
        stalled = 0
        while self.waiting or self.chunking or self.slot_req:
            had_chunks = bool(self.chunking)
            before = len(self.finished)
            out = self.step_burst(max_burst)
            progress = (bool(out) or had_chunks
                        or len(self.finished) > before)
            stalled = 0 if progress else stalled + 1
            if self.kv_lazy and self.slot_req and stalled > 2:
                raise KvPoolWedgedError(
                    "KV block pool exhausted: lazy growth cannot back "
                    "any active slot and nothing can retire — size "
                    "SKYTPU_KV_BLOCKS for the live working set or "
                    "disable SKYTPU_KV_LAZY")
        return self.finished

    # -- convenience -------------------------------------------------------

    def generate(self, prompts: List[List[int]],
                 max_new_tokens: int = 128) -> List[List[int]]:
        ids = [self.add_request(p, max_new_tokens) for p in prompts]
        self.run_to_completion()
        by_rid = {r.rid: r for r in self.finished}
        return [by_rid[i].tokens for i in ids]
