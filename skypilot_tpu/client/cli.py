"""The `skytpu` CLI.

Reference parity: sky/client/cli.py (launch/exec/status/stop/down/start/
autostop/queue/logs/cancel/check/show-gpus/cost-report, cli.py:1006-5131).
Invoke as ``python -m skypilot_tpu.client.cli`` (or the ``skytpu``
console script once installed).
"""

from __future__ import annotations

import os
import sys
from typing import Optional

import click
import yaml

import skypilot_tpu as sky
from skypilot_tpu import exceptions

# NOTE: skypilot_tpu.task / .resources pull the catalog layer (pandas,
# ~3s) — imported lazily inside the commands that build Tasks so
# metadata commands (lint, status, top, trace) start fast.


@click.group()
@click.version_option(sky.__version__, prog_name="skytpu")
def cli():
    """skypilot_tpu: run tasks on TPU slices (and VMs) in the sky."""


def _resource_overrides(accelerators: Optional[str],
                        cloud: Optional[str], use_spot: bool,
                        recovery: Optional[str] = None) -> dict:
    """CLI flags -> Resources.copy overrides (shared by launch, exec,
    and both jobs-launch forms)."""
    overrides = {}
    if accelerators:
        overrides["accelerators"] = accelerators
    if cloud:
        overrides["cloud"] = cloud
    if use_spot:
        overrides["use_spot"] = True
    if recovery:
        overrides["job_recovery"] = recovery
    return overrides


def _load_task(yaml_path: Optional[str], command: Optional[str],
               accelerators: Optional[str], cloud: Optional[str],
               num_nodes: Optional[int], use_spot: bool,
               name: Optional[str]) -> "sky.Task":
    from skypilot_tpu.task import Task
    if yaml_path:
        task = Task.from_yaml(yaml_path)
    else:
        task = Task(run=command)
    if name:
        task.name = name
    if num_nodes:
        task.num_nodes = num_nodes
    overrides = _resource_overrides(accelerators, cloud, use_spot)
    if overrides:
        task.set_resources(task.resources[0].copy(**overrides))
    return task


@cli.command()
@click.argument("yaml_or_command", required=False)
@click.option("--cluster", "-c", default=None, help="Cluster name.")
@click.option("--gpus", "--accelerators", "accelerators", default=None,
              help="e.g. tpu-v5e-8, A100:8")
@click.option("--cloud", default=None)
@click.option("--num-nodes", type=int, default=None)
@click.option("--use-spot", is_flag=True, default=False)
@click.option("--name", "-n", default=None)
@click.option("--retry-until-up", is_flag=True, default=False)
@click.option("--idle-minutes-to-autostop", "-i", type=int, default=None)
@click.option("--down", is_flag=True, default=False,
              help="Tear down after the job finishes.")
@click.option("--detach-run", "-d", is_flag=True, default=False)
@click.option("--dryrun", is_flag=True, default=False)
def launch(yaml_or_command, cluster, accelerators, cloud, num_nodes,
           use_spot, name, retry_until_up, idle_minutes_to_autostop, down,
           detach_run, dryrun):
    """Launch a task (YAML file or inline command)."""
    is_yaml = yaml_or_command and (
        yaml_or_command.endswith((".yaml", ".yml"))
        or os.path.exists(yaml_or_command))
    task = _load_task(yaml_or_command if is_yaml else None,
                      None if is_yaml else yaml_or_command,
                      accelerators, cloud, num_nodes, use_spot, name)
    job_id, handle = sky.launch(
        task, cluster_name=cluster, retry_until_up=retry_until_up,
        idle_minutes_to_autostop=idle_minutes_to_autostop, down=down,
        detach_run=True, dryrun=dryrun)
    if dryrun:
        return
    click.echo(f"Launched job {job_id} on cluster "
               f"{handle.cluster_name!r}.")
    if not detach_run and job_id is not None:
        sky.tail_logs(handle.cluster_name, job_id, follow=True)


@cli.command(name="exec")
@click.argument("cluster")
@click.argument("yaml_or_command")
@click.option("--name", "-n", default=None)
@click.option("--detach-run", "-d", is_flag=True, default=False)
def exec_cmd(cluster, yaml_or_command, name, detach_run):
    """Run a task on an existing cluster (skips provisioning)."""
    is_yaml = yaml_or_command.endswith((".yaml", ".yml")) or os.path.exists(
        yaml_or_command)
    task = _load_task(yaml_or_command if is_yaml else None,
                      None if is_yaml else yaml_or_command,
                      None, None, None, False, name)
    job_id, handle = sky.exec(task, cluster_name=cluster)
    click.echo(f"Job {job_id} submitted to {cluster!r}.")
    if not detach_run:
        sky.tail_logs(cluster, job_id, follow=True)


def _print_metrics_view(text: str, raw: bool) -> None:
    """Render /metrics exposition as a compact table (or raw)."""
    from skypilot_tpu.observability import metrics as metrics_lib
    if raw:
        click.echo(text.rstrip("\n"))
        return
    families = metrics_lib.parse_exposition(text)
    fmt = "{:<44}{:<10}{:>14}  {}"
    click.echo(fmt.format("METRIC", "TYPE", "VALUE", "LABELS"))
    for name in sorted(families):
        fam = families[name]
        if fam["type"] == "histogram":
            # One row per series: count and mean latency.
            by_series = {}
            for labels, value in fam["samples"]:
                sample = labels.pop("__name__", name)
                key = tuple(sorted(
                    (k, v) for k, v in labels.items() if k != "le"))
                agg = by_series.setdefault(key, {"count": 0.0, "sum": 0.0})
                if sample.endswith("_count"):
                    agg["count"] = value
                elif sample.endswith("_sum"):
                    agg["sum"] = value
            for key, agg in sorted(by_series.items()):
                mean = agg["sum"] / agg["count"] if agg["count"] else 0.0
                labels_s = ",".join(f"{k}={v}" for k, v in key)
                click.echo(fmt.format(
                    name, "histogram",
                    f"n={agg['count']:.0f} avg={mean:.4g}", labels_s))
            continue
        for labels, value in sorted(fam["samples"],
                                    key=lambda s: sorted(s[0].items())):
            labels_s = ",".join(f"{k}={v}"
                                for k, v in sorted(labels.items()))
            click.echo(fmt.format(name, fam["type"],
                                  f"{value:g}", labels_s))


def _fleet_fetch(need_metrics: bool = True):
    """Fetch the API server's federated fleet view: (families or None,
    health payload). Raises ClickException with an actionable message
    when the server is unreachable."""
    import json as json_lib
    import urllib.error
    import urllib.request

    from skypilot_tpu.client import sdk as sdk_mod
    from skypilot_tpu.observability import metrics as metrics_lib

    def fetch(path):
        req = urllib.request.Request(sdk_mod._url() + path,
                                     headers=sdk_mod._headers())
        try:
            with urllib.request.urlopen(req, timeout=20) as r:
                return r.read()
        except urllib.error.HTTPError as e:
            raise click.ClickException(
                f"GET {sdk_mod._url()}{path} failed: "
                f"HTTP {e.code} {e.reason}")
        except OSError:
            raise click.ClickException(
                f"API server at {sdk_mod._url()} is not reachable "
                f"(try `skytpu api start`)")

    families = None
    if need_metrics:
        families = metrics_lib.parse_exposition(
            fetch("/metrics/fleet").decode())
    payload = json_lib.loads(fetch("/api/fleet/health"))
    return families, payload


_HEALTH_MARK = {"healthy": "+", "draining": "-", "degraded": "~",
                "dead": "x"}


def _health_lines(payload) -> list:
    """Component table + alert lines shared by `status --health` and
    `skytpu top`."""
    lines = []
    comps = payload.get("components", [])
    counts = {}
    for c in comps:
        counts[c["status"]] = counts.get(c["status"], 0) + 1
    summary = ", ".join(f"{n} {s}" for s, n in sorted(counts.items()))
    alerts = payload.get("alerts", [])
    lines.append(f"fleet: {payload.get('status', '?').upper()} "
                 f"({summary or 'no components'}) — "
                 f"{len(alerts)} active alert(s)")
    import time as time_mod
    for a in alerts:
        age = max(time_mod.time() - a.get("since", time_mod.time()), 0)
        lines.append(f"  ALERT {a.get('rule')}: "
                     f"{a.get('attrs', {}).get('kind', '')} "
                     f"firing for {age:.0f}s")
    fmt = "{:<3}{:<18}{:<22}{:<10}{:>10}  {}"
    lines.append(fmt.format("", "COMPONENT", "INSTANCE", "HEALTH",
                            "SEEN(S)", "REASON"))
    for c in comps:
        seen = c.get("last_seen_s")
        lines.append(fmt.format(
            _HEALTH_MARK.get(c["status"], "?"), c["component"],
            c["instance"], c["status"],
            f"{seen:.0f}" if seen is not None else "-",
            c.get("reason") or ""))
    return lines


def _resolve_head_ip(cluster: str, refresh: bool = False) -> str:
    """An UP cluster's head host IP (external when it has one).
    Shared by `status --ip` and `skytpu flight <cluster>`; raises a
    clear ClickException on unknown/stopped clusters instead of
    letting callers time out against a stale handle."""
    from skypilot_tpu import provision
    records = sky.status([cluster], refresh=refresh)
    if not records:
        raise click.ClickException(f"no cluster {cluster!r}")
    if records[0]["status"].value != "UP":
        raise click.ClickException(
            f"cluster {cluster!r} is "
            f"{records[0]['status'].value}, not UP")
    h = records[0]["handle"]
    info = provision.get_cluster_info(h["provider"], cluster,
                                      h.get("zone"))
    if not info.hosts:
        raise click.ClickException(
            f"cluster {cluster!r} has no reachable hosts")
    head = info.hosts[0]
    return head.external_ip or head.internal_ip


@cli.command()
@click.option("--refresh", "-r", is_flag=True, default=False)
@click.option("--ip", "show_ip", is_flag=True, default=False,
              help="Print only the head host IP of ONE cluster "
                   "(external when it has one), for scripting.")
@click.option("--metrics", "show_metrics", is_flag=True, default=False,
              help="Show the API server's live metrics (scraped from "
                   "its GET /metrics) instead of the cluster table.")
@click.option("--health", "show_health", is_flag=True, default=False,
              help="Show fleet component health (API server's "
                   "/api/fleet/health) instead of the cluster table.")
@click.option("--raw", is_flag=True, default=False,
              help="With --metrics: print the Prometheus text "
                   "exposition verbatim.")
@click.argument("clusters", nargs=-1)
def status(refresh, show_ip, show_metrics, show_health, raw, clusters):
    """Show clusters (or live server metrics / fleet health)."""
    if raw and not show_metrics:
        raise click.ClickException("--raw only applies with --metrics")
    if show_health:
        if clusters or refresh or show_ip or show_metrics:
            raise click.ClickException(
                "--health shows the fleet component table and cannot "
                "be combined with cluster names or other modes")
        _, payload = _fleet_fetch(need_metrics=False)
        for line in _health_lines(payload):
            click.echo(line)
        # Draining within its deadline is a PLANNED state (rolling
        # update in progress), not an incident: exit 0. A replica
        # draining past its deadline self-reports degraded, which
        # rolls up here and exits 2.
        if payload.get("status") not in ("healthy", "draining"):
            sys.exit(2)
        return
    if show_metrics:
        if clusters or refresh or show_ip:
            raise click.ClickException(
                "--metrics shows the API server's registry and cannot "
                "be combined with cluster names, --refresh, or --ip")
        import urllib.error
        import urllib.request
        from skypilot_tpu.client import sdk as sdk_mod
        req = urllib.request.Request(sdk_mod._url() + "/metrics",
                                     headers=sdk_mod._headers())
        try:
            resp = urllib.request.urlopen(req, timeout=10)
        except urllib.error.HTTPError as e:
            # The server IS up — don't tell the user to restart it.
            raise click.ClickException(
                f"GET {sdk_mod._url()}/metrics failed: "
                f"HTTP {e.code} {e.reason}")
        except OSError:
            raise click.ClickException(
                f"API server at {sdk_mod._url()} is not reachable "
                f"(try `skytpu api start`)")
        try:
            with resp:
                text = resp.read().decode()
        except OSError as e:
            # Connected but the body read died: the server is up — a
            # "restart it" hint here would misdirect.
            raise click.ClickException(
                f"GET {sdk_mod._url()}/metrics failed mid-read: {e}")
        _print_metrics_view(text, raw)
        return
    if show_ip:
        # Reference parity: `sky status --ip` (sky/cli.py status).
        if len(clusters) != 1:
            raise click.UsageError("--ip requires exactly one cluster")
        click.echo(_resolve_head_ip(clusters[0], refresh=refresh))
        return
    records = sky.status(list(clusters) or None, refresh=refresh)
    if not records:
        click.echo("No existing clusters.")
        return
    fmt = "{:<16}{:<10}{:<28}{:<8}{:>10}"
    click.echo(fmt.format("NAME", "STATUS", "RESOURCES", "NODES", "$/HR"))
    for r in records:
        h = r["handle"]
        res = h.get("resources", {})
        desc = res.get("accelerators") or res.get("instance_type") or "-"
        click.echo(fmt.format(
            r["name"], r["status"].value,
            f"{h.get('provider')}:{desc}@{h.get('zone')}",
            h.get("num_nodes", 1), f"{r['price_per_hour']:.2f}"))


def _render_top_frame(prev, prev_ts, fams, now, payload) -> str:
    """One `skytpu top` frame as rendered text (the dict-first core is
    :func:`_top_frame`; this wrapper keeps the render-only callers and
    tests on the string)."""
    return _top_frame(prev, prev_ts, fams, now, payload)[0]


def _top_frame(prev, prev_ts, fams, now, payload):
    """One `skytpu top` frame: the health table plus fleet-wide rates
    and latencies. Counter rates need two snapshots — the first frame
    (and --once) shows '-' where a delta would go.

    Returns ``(rendered, data)``: the text frame AND its underlying
    values as one machine-readable dict (``skytpu top --json``) — the
    render is a VIEW over ``data``, so a dashboard scraping the JSON
    sees exactly the numbers the table shows."""
    from skypilot_tpu.observability import aggregate, slo

    span = (now - prev_ts) if prev_ts else None
    data = {
        "ts": now,
        "window_s": span,
        "fleet": {"status": payload.get("status"),
                  "alerts": payload.get("alerts", []),
                  "components": payload.get("components", [])},
    }

    def rate(name, match=None, sample_name=None):
        if prev is None or not span:
            return None
        d = aggregate.delta(prev, fams, name, match=match,
                            sample_name=sample_name)
        return d / span if d is not None else None

    def rate_prefix(name, label, prefix):
        if prev is None or not span:
            return None
        d = aggregate.filtered_delta(
            prev, fams, name,
            lambda labels: str(labels.get(label, "")).startswith(prefix))
        return d / span if d is not None else None

    def gauge(name, agg="sum"):
        return aggregate.sample_value(fams, name, agg=agg)

    def f_rate(v):
        return f"{v:6.2f}/s" if v is not None else "      -"

    def f_ms(v):
        return f"{v * 1e3:7.1f}ms" if v is not None else "        -"

    lines = _health_lines(payload)
    lines.append("")
    have = fams.keys()
    if "skytpu_http_requests_total" in have or \
            "skytpu_ttft_seconds" in have:
        serve = {}
        data["serve"] = serve
        ttft = aggregate.histogram_quantile(prev, fams,
                                            "skytpu_ttft_seconds", 0.95)
        slots = gauge("skytpu_slots_active")
        slots_total = gauge("skytpu_slots_total")
        req_rate = rate("skytpu_http_requests_total")
        err5_rate = rate_prefix("skytpu_http_requests_total",
                                "code", "5")
        serve["req_per_s"] = req_rate
        serve["err5xx_per_s"] = err5_rate
        serve["ttft_p95_s"] = ttft
        line = (
            f"serve   req {f_rate(req_rate)}"
            f"  5xx {f_rate(err5_rate)}"
            f"  ttft p95 {f_ms(ttft)}")
        if slots is not None and slots_total:
            serve["slots_active"] = slots
            serve["slots_total"] = slots_total
            line += f"  slots {slots:.0f}/{slots_total:.0f}"
        # Paged KV-cache block occupancy (docs/serving.md): how full
        # the shared block pool is across the fleet's engines.
        kv_used = gauge("skytpu_kv_blocks_used")
        kv_total = gauge("skytpu_kv_blocks_total")
        if kv_used is not None and kv_total:
            serve["kv_blocks_used"] = kv_used
            serve["kv_blocks_total"] = kv_total
            line += f"  kv {kv_used:.0f}/{kv_total:.0f}"
        # Span-bucketed decode attention (docs/serving.md): median KV
        # rows a decode/verify burst gathered between frames — decode
        # bandwidth tracks this, not the engines' max_len.
        span_rows = aggregate.histogram_quantile(
            prev, fams, "skytpu_decode_attn_rows", 0.5)
        if span_rows is not None:
            serve["attn_rows_p50"] = span_rows
            line += f"  span p50 {span_rows:.0f}"
        # Decode attention read path (docs/serving.md §Paged
        # decode-attention kernel): which big-cache path the fleet's
        # decode bursts rode — kernel (Pallas, SKYTPU_KV_KERNEL=1),
        # gather (the oracle/fallback), or mixed mid-rollout. Window
        # rates when bursts flowed between frames, lifetime totals
        # otherwise.
        if "skytpu_decode_attn_bursts_total" in have:
            def _path(p, window=True):
                if window:
                    v = rate("skytpu_decode_attn_bursts_total",
                             match={"path": p})
                else:
                    v = aggregate.sample_value(
                        fams, "skytpu_decode_attn_bursts_total",
                        match={"path": p})
                return v or 0
            kern, gath = _path("kernel"), _path("gather")
            if not kern and not gath:
                # Idle window or first frame: fall back to lifetime
                # totals so the indicator never vanishes mid-session.
                # Only when BOTH window rates are dry — one flowing
                # path means the fleet is on THAT path now, and the
                # other's stale lifetime total must not report
                # "mixed" forever after a rollout flip.
                kern = _path("kernel", window=False)
                gath = _path("gather", window=False)
            if kern or gath:
                attn = ("mixed" if kern and gath
                        else "kernel" if kern else "gather")
                serve["attn_path"] = attn
                line += "  attn " + attn
        # Speculative-decode drafter kind + acceptance (docs/
        # serving.md): which drafter rung the fleet's spec rounds rode
        # (model|ngram|mixed — the fallback ladder is observable at a
        # glance), the window acceptance rate when drafting happened
        # between frames (else the engines' lifetime gauge), and the
        # pipeline overlap ratio — draft-dispatch wall the rounds hid
        # inside the verify's dispatch->fetch window.
        if "skytpu_spec_drafted_total" in have:
            def _kind(k, window=True):
                if window:
                    v = rate("skytpu_spec_draft_tokens_total",
                             match={"drafter": k})
                else:
                    v = aggregate.sample_value(
                        fams, "skytpu_spec_draft_tokens_total",
                        match={"drafter": k})
                return v or 0
            model, ngram = _kind("model"), _kind("ngram")
            if not model and not ngram:
                # Idle window / first frame: lifetime totals, the
                # attn-indicator idiom — one flowing kind means the
                # fleet drafts THAT way now.
                model = _kind("model", window=False)
                ngram = _kind("ngram", window=False)
            kind = ("mixed" if model and ngram
                    else "model" if model
                    else "ngram" if ngram else None)
            d_dr = rate("skytpu_spec_drafted_total")
            d_ac = rate("skytpu_spec_accepted_total")
            acc = ((d_ac or 0) / d_dr if d_dr
                   else gauge("skytpu_spec_acceptance_rate", agg="max"))
            if kind is not None:
                serve["spec_drafter"] = kind
            if acc is not None:
                serve["spec_acceptance"] = acc
                line += (f"  spec {kind} acc {acc:4.0%}" if kind
                         else f"  spec acc {acc:4.0%}")
            ov = rate("skytpu_spec_overlap_wall_seconds_total")
            vw = rate("skytpu_spec_verify_wall_seconds_total")
            if ov is None or not vw:
                ov = gauge("skytpu_spec_overlap_wall_seconds_total")
                vw = gauge("skytpu_spec_verify_wall_seconds_total")
            if ov is not None and vw:
                serve["spec_overlap"] = min(ov / vw, 1.0)
                line += f"  ovl {min(ov / vw, 1.0):4.0%}"
        # Fleet prefix-cache hit rate (ROADMAP item 3 slice): the
        # federation already sums per-replica counters — the window
        # rate when traffic flowed between frames, else the lifetime
        # ratio (first frame / --once / idle).
        if "skytpu_prefix_cache_hits_total" in have:
            d_h = rate("skytpu_prefix_cache_hits_total")
            d_m = rate("skytpu_prefix_cache_misses_total")
            cache_rate = None
            if d_h is not None and d_m is not None and (d_h + d_m) > 0:
                cache_rate = d_h / (d_h + d_m)
            else:
                hits = gauge("skytpu_prefix_cache_hits_total")
                misses = gauge("skytpu_prefix_cache_misses_total") or 0
                if hits is not None and (hits + misses) > 0:
                    cache_rate = hits / (hits + misses)
            if cache_rate is not None:
                serve["prefix_cache_hit_rate"] = cache_rate
                line += f"  cache {cache_rate:4.0%}"
            # Per-replica spread of the lifetime hit ratio (the
            # skytpu_prefix_cache_hit_ratio GAUGE keeps instance
            # labels through federation, unlike the summed counters):
            # prefix-affinity routing is supposed to close this
            # spread — a wide one means families are landing on cold
            # replicas. Shown only when replicas actually disagree.
            lo = gauge("skytpu_prefix_cache_hit_ratio", agg="min")
            hi = gauge("skytpu_prefix_cache_hit_ratio", agg="max")
            if lo is not None and hi is not None:
                serve["prefix_cache_hit_min"] = lo
                serve["prefix_cache_hit_max"] = hi
                if hi - lo >= 0.01:
                    line += f" [{lo:.0%}..{hi:.0%}]"
        # Adapter catalog (docs/serving.md §Adapter catalog): resident
        # fine-tunes / pool capacity fleet-wide, plus the hot-load
        # rate when demand loads happened between frames — catalog
        # churn (thrashing) is visible at a glance.
        ad_active = gauge("skytpu_adapter_active")
        ad_slots = gauge("skytpu_adapter_slots")
        if ad_active is not None and ad_slots:
            serve["adapters_active"] = ad_active
            serve["adapter_slots"] = ad_slots
            line += f"  adapters {ad_active:.0f}/{ad_slots:.0f}"
            ld = rate("skytpu_adapter_loads_total")
            if ld:
                serve["adapter_loads_per_s"] = ld
                line += f" (ld {ld:.2f}/s)"
        # Compile watch (docs/observability.md §Flight recorder):
        # programs compiled fleet-wide, and — the alarm column — how
        # many compiled AFTER an engine declared warmup complete.
        comp = gauge("skytpu_programs_compiled_total")
        if comp is not None:
            unexp = gauge("skytpu_unexpected_compiles_total") or 0
            serve["programs_compiled"] = comp
            serve["unexpected_compiles"] = unexp
            line += f"  compiles {comp:.0f}"
            line += (f" (! {unexp:.0f} unexpected)" if unexp
                     else " (0 unexpected)")
        # Fault tolerance (docs/robustness.md §Replica loss & rolling
        # update): replicas mid-drain (summed per-replica gauge), the
        # engine crash-recovery rate, and the LB mid-stream failover
        # rate — a rolling update or a crash storm shows on the serve
        # line WHILE it happens, not in a postmortem. Columns appear
        # only when non-zero: steady state stays uncluttered.
        draining = gauge("skytpu_server_draining")
        if draining:
            serve["replicas_draining"] = draining
            line += f"  drain {draining:.0f}"
        rec_rate = rate("skytpu_engine_recoveries_total")
        if rec_rate:
            serve["recoveries_per_s"] = rec_rate
            line += f"  recov {rec_rate:.2f}/s"
        fo_rate = rate("skytpu_lb_failovers_total")
        if fo_rate:
            serve["failovers_per_s"] = fo_rate
            line += f"  failover {fo_rate:.2f}/s"
        # Device-truth roofline (docs/observability.md §Device-truth
        # attribution): windowed MFU and HBM-bandwidth utilization —
        # the fleet's analytical FLOPs/bytes rates over its summed
        # published peaks. Rates need two frames (first frame and
        # --once show nothing); lifetime totals are meaningless as a
        # utilization proxy, so no fallback.
        peak_f = gauge("skytpu_roofline_peak_flops")
        if peak_f:
            fl = rate("skytpu_device_flops_total")
            if fl is not None:
                serve["mfu"] = min(fl / peak_f, 1.0)
                line += f"  mfu {min(fl / peak_f, 1.0):5.1%}"
            peak_b = gauge("skytpu_roofline_peak_hbm_bytes_per_s")
            bw = rate("skytpu_device_hbm_moved_bytes_total")
            if peak_b and bw is not None:
                serve["hbm_bw_util"] = min(bw / peak_b, 1.0)
                line += f"  bw {min(bw / peak_b, 1.0):5.1%}"
        lines.append(line)
    # Per-tenant QoS columns (docs/serving.md §Multi-tenant QoS):
    # top-N tenants by request rate, each with its shed rate, plus the
    # fleet preemption rate — the hot-tenant story at a glance.
    if "skytpu_qos_requests_total" in have or \
            "skytpu_qos_shed_total" in have:
        def _tenant_values(name, where=None):
            # where="server" reads ONE admission tier: with QoS at
            # both the LB and the replicas, a proxied request is
            # admitted (and counted) twice — summing tiers would
            # double the req/s column. Sheds stay summed: a request
            # sheds at most once, at exactly one tier.
            vals = {}
            tiered = False
            for labels, value in fams.get(
                    name, {"samples": []})["samples"]:
                t = labels.get("tenant")
                if t is None or "__name__" in labels:
                    continue
                if where is not None and labels.get("where") == where:
                    if not tiered:
                        tiered, vals = True, {}
                    vals[t] = vals.get(t, 0.0) + value
                elif not tiered:
                    vals[t] = vals.get(t, 0.0) + value
            return vals

        req_life = _tenant_values("skytpu_qos_requests_total",
                                  where="server")
        shed_life = _tenant_values("skytpu_qos_shed_total")
        scored = []
        for t in sorted(set(req_life) | set(shed_life)):
            rr = rate("skytpu_qos_requests_total",
                      match={"tenant": t, "where": "server"})
            if rr is None:
                rr = rate("skytpu_qos_requests_total",
                          match={"tenant": t})
            sr = rate("skytpu_qos_shed_total", match={"tenant": t})
            score = rr if rr is not None else req_life.get(t, 0.0)
            scored.append((-(score or 0.0), t, rr, sr))
        scored.sort()
        cols = "  ".join(
            f"{t} {f_rate(rr).strip()} shed {f_rate(sr).strip()}"
            for _, t, rr, sr in scored[:3])
        pre = rate("skytpu_qos_preemptions_total")
        if pre is None:
            pre_life = gauge("skytpu_qos_preemptions_total")
            pre_txt = (f"{pre_life:.0f} total"
                       if pre_life is not None else "-")
        else:
            pre_txt = f_rate(pre).strip()
        data["qos"] = {
            "tenants": [{"tenant": t, "req_per_s": rr,
                         "shed_per_s": sr}
                        for _, t, rr, sr in scored[:3]],
            "preempt_per_s": pre,
            "preempt_total": (gauge("skytpu_qos_preemptions_total")
                              if pre is None else None),
        }
        lines.append(f"qos     {cols}  preempt {pre_txt}")
    if "skytpu_lb_proxied_total" in have:
        proxied = rate("skytpu_lb_proxied_total")
        retries = rate("skytpu_lb_retries_total")
        data["lb"] = {"proxied_per_s": proxied,
                      "retries_per_s": retries}
        lines.append(
            f"lb      proxied {f_rate(proxied)}"
            f"  retries {f_rate(retries)}")
    # Disaggregated serving tiers (docs/serving.md §Disaggregated
    # serving): per-tier request rates, the prefill->decode handoff
    # rate, and the handoff p95 — the line appears only once a
    # disaggregated service has routed traffic.
    if "skytpu_lb_tier_requests_total" in have:
        pf = rate("skytpu_lb_tier_requests_total",
                  match={"tier": "prefill"})
        dc = rate("skytpu_lb_tier_requests_total",
                  match={"tier": "decode"})
        ho = rate("skytpu_lb_handoffs_total", match={"result": "ok"})
        hp95 = aggregate.histogram_quantile(
            prev, fams, "skytpu_handoff_seconds", 0.95)
        data["tiers"] = {"prefill_per_s": pf, "decode_per_s": dc,
                         "handoff_per_s": ho, "handoff_p95_s": hp95}
        lines.append(
            f"tiers   prefill {f_rate(pf)}  decode {f_rate(dc)}"
            f"  handoff {f_rate(ho)}  p95 {f_ms(hp95)}")
    if "skytpu_api_requests_total" in have:
        busy = gauge("skytpu_api_workers_busy")
        api_rate = rate("skytpu_api_requests_total")
        data["api"] = {"req_per_s": api_rate, "workers_busy": busy}
        lines.append(
            f"api     req {f_rate(api_rate)}"
            f"  workers busy {busy:.0f}" if busy is not None else
            f"api     req {f_rate(api_rate)}")
    if "skytpu_train_step_last_seconds" in have:
        last = gauge("skytpu_train_step_last_seconds", agg="max")
        med = gauge("skytpu_train_step_median_seconds", agg="max")
        tps = gauge("skytpu_train_tokens_per_second")
        data["train"] = {"step_last_s": last, "step_median_s": med,
                         "tokens_per_s": tps}
        line = (f"train   step {f_ms(last)} (median {f_ms(med)})"
                f"  tokens {f_rate(tps)}")
        # Goodput/MFU/straggler columns (docs/observability.md
        # §Training goodput): the worst host's cumulative goodput
        # ratio (agg=min — the slice trains at the slowest host's
        # pace), windowed train MFU over the published roofline peak,
        # and the straggler spread of the federated per-host step
        # walls.
        gput = gauge("skytpu_train_goodput_ratio", agg="min")
        if gput is not None:
            data["train"]["goodput"] = gput
            line += f"  goodput {gput:5.1%}"
        peak_f = gauge("skytpu_roofline_peak_flops")
        fl = rate("skytpu_device_flops_total")
        if peak_f and fl is not None:
            data["train"]["mfu"] = min(fl / peak_f, 1.0)
            line += f"  mfu {min(fl / peak_f, 1.0):5.1%}"
        hosts = [(lab.get("host", "?"), v) for lab, v in
                 fams.get("skytpu_train_host_step_seconds",
                          {"samples": []})["samples"]]
        if len(hosts) > 1:
            worst = max(hosts, key=lambda h: h[1])
            lag_ms = (worst[1] - min(h[1] for h in hosts)) * 1e3
            data["train"]["straggler"] = {"host": worst[0],
                                          "lag_ms": lag_ms}
            line += f"  straggler host-{worst[0]} (+{lag_ms:.0f} ms)"
        lines.append(line)
    # Oldest heartbeat = worst skylet; the freshest would mask a
    # wedged sibling.
    hb = gauge("skytpu_skylet_last_tick_timestamp_seconds", agg="min")
    if hb:
        data["skylet_oldest_heartbeat_age_s"] = max(now - hb, 0)
        lines.append(f"skylet  oldest heartbeat age {max(now - hb, 0):.0f}s")
    down = [t for t in fams.get("skytpu_fleet_scrape_up",
                                {"samples": []})["samples"]
            if t[1] == 0]
    if down:
        names = [f"{lab.get('component')}/{lab.get('instance')}"
                 for lab, _ in down]
        data["scrape_down"] = names
        lines.append(f"scrape  DOWN: {', '.join(names)}")
    return "\n".join(lines), data


@cli.command(name="top")
@click.option("--interval", "-n", type=float, default=2.0,
              show_default=True, help="Seconds between refreshes.")
@click.option("--once", is_flag=True, default=False,
              help="Render a single frame and exit (scripting/tests; "
                   "rate columns need two frames and show '-').")
@click.option("--json", "as_json", is_flag=True, default=False,
              help="Emit ONE machine-readable frame (the table's "
                   "underlying dict: fleet health + serve/qos/attn "
                   "columns) and exit. Implies --once.")
def top(interval, once, as_json):
    """Live fleet overview: health, rates, latencies, per-tenant QoS.

    Data comes from the API server's federation tier (`GET
    /metrics/fleet` + `/api/fleet/health`), so one terminal covers the
    API server, every model-server replica, the load balancers, serve
    controllers, and local skylets. With QoS enabled the `qos` line
    shows the top tenants by request rate, each tenant's shed rate,
    and the fleet preemption rate.
    """
    import time as time_mod
    once = once or as_json
    prev, prev_ts = None, None
    try:
        while True:
            try:
                families, payload = _fleet_fetch()
            except click.ClickException:
                if once:
                    raise
                # The monitoring view must survive the outage it
                # exists to display: render a DOWN frame and retry
                # next interval instead of dying mid-incident.
                click.clear()
                click.echo(f"fleet: API SERVER UNREACHABLE "
                           f"(retrying every {max(interval, 0.1):g}s, "
                           f"Ctrl-C to exit)")
                prev, prev_ts = None, None
                time_mod.sleep(max(interval, 0.1))
                continue
            now = time_mod.time()
            frame, frame_data = _top_frame(prev, prev_ts, families,
                                           now, payload)
            if once:
                if as_json:
                    import json as json_lib
                    click.echo(json_lib.dumps(frame_data, indent=2,
                                              default=str))
                else:
                    click.echo(frame)
                return
            click.clear()
            click.echo(frame)
            prev, prev_ts = families, now
            time_mod.sleep(max(interval, 0.1))
    except KeyboardInterrupt:
        pass


@cli.command(name="trace")
@click.argument("request_id")
@click.option("--perfetto", "perfetto_path", default=None,
              help="Also write the assembled trace as Chrome "
                   "trace-format JSON (Perfetto/chrome://tracing "
                   "loadable) to this path.")
def trace_cmd(request_id, perfetto_path):
    """Reconstruct one request's cross-process span tree.

    REQUEST_ID is an API request id (as returned by every async
    endpoint and shown by `skytpu api status`) or a raw 32-hex trace
    id. Spans and lifecycle events are read from the structured event
    logs under ~/.skypilot_tpu/events/ (see docs/observability.md).
    """
    import json as json_lib
    import re as re_mod

    from skypilot_tpu.observability import trace_view, tracing
    from skypilot_tpu.server import requests_db

    trace_id = None
    rec = requests_db.get(request_id)
    if rec is not None:
        trace = rec.get("trace") or {}
        ctx = tracing.parse_traceparent(trace.get("tp"))
        if ctx is None:
            raise click.ClickException(
                f"request {request_id!r} predates tracing (no trace "
                f"context recorded)")
        trace_id = ctx.trace_id
    elif re_mod.fullmatch(r"[0-9a-f]{32}", request_id):
        trace_id = request_id
    else:
        raise click.ClickException(
            f"no request {request_id!r} (and not a 32-hex trace id)")
    records = trace_view.load_trace(trace_id)
    if not records:
        raise click.ClickException(
            f"no events recorded for trace {trace_id} (still in an "
            f"unflushed buffer, or logged under another home?)")
    if perfetto_path:
        with open(os.path.expanduser(perfetto_path), "w") as f:
            json_lib.dump(trace_view.to_perfetto(records), f)
        click.echo(f"perfetto trace written to {perfetto_path}")
    click.echo(trace_view.render(records, trace_id))


@cli.command(name="flight")
@click.argument("target", required=False)
@click.option("--local", "local", is_flag=True, default=False,
              help="Read the flushed flight logs under this machine's "
                   "events dir instead of querying a server.")
@click.option("-n", "--last", type=int, default=32, show_default=True,
              help="Burst records to show in the tail table.")
@click.option("--port", type=int, default=8080, show_default=True,
              help="Model-server port when TARGET is a cluster name.")
@click.option("--perfetto", "perfetto_path", default=None,
              help="Also write the burst records as Chrome "
                   "trace-format JSON (Perfetto loadable) to this "
                   "path.")
@click.option("--bubbles", "bubbles", is_flag=True, default=False,
              help="Append the bubble analysis: device-idle gaps "
                   "between bursts attributed to named host causes "
                   "(docs/observability.md §Device-truth attribution).")
@click.option("-f", "--follow", "follow", is_flag=True, default=False,
              help="Keep polling and print new bursts as they land. "
                   "Uses the /debug/flight?since=<seq> cursor so each "
                   "poll ships only the delta, not the whole ring. "
                   "Requires a server target; Ctrl-C to stop.")
@click.option("--interval", type=float, default=2.0, show_default=True,
              help="Poll interval in seconds for --follow.")
def flight_cmd(target, local, last, port, perfetto_path, bubbles,
               follow, interval):
    """Engine flight recorder: the last-N bursts and program summary.

    Burst-level serving introspection (docs/observability.md §Flight
    recorder): which compiled program ran each admission wave, prefill
    chunk, decode burst and speculative verify, with group
    composition, host timing, spec acceptance and — when the compile
    watch saw one — mid-traffic compiles.

    TARGET is a model-server URL (http://host:port) or a cluster name
    (resolved to its head IP); `--local` (or no target) reads the
    flushed per-process logs under ~/.skypilot_tpu/events/ instead.
    """
    import json as json_lib
    import urllib.request

    from skypilot_tpu.observability import attribution as attribution_lib
    from skypilot_tpu.observability import flight as flight_lib
    from skypilot_tpu.observability import trace_view

    programs = None
    if follow and (local or not target):
        raise click.ClickException(
            "--follow needs a live server TARGET (it tails the "
            "in-memory ring via /debug/flight?since=...); flushed "
            "--local logs don't grow.")
    if target and not local:
        if target.startswith(("http://", "https://")):
            url = target.rstrip("/")
        else:
            # Cluster name -> head IP (the `status --ip` resolution,
            # incl. its UP check — a stale handle would just time out).
            url = f"http://{_resolve_head_ip(target)}:{port}"
        # Fetch the whole ring (capped at its capacity), not just the
        # tail table's -n: the per-program summary and the --perfetto
        # export must cover the server's full history, exactly like
        # --local does over the flushed logs. -n only trims the table.
        try:
            with urllib.request.urlopen(
                    f"{url}/debug/flight?n={max(last, 8192)}",
                    timeout=10) as resp:
                payload = json_lib.loads(resp.read().decode())
        except OSError as e:
            raise click.ClickException(
                f"GET {url}/debug/flight failed: {e}")
        records = payload.get("records", [])
        programs = payload.get("programs") or None
        if not payload.get("enabled", True):
            click.echo("note: the server's flight recorder is "
                       "DISABLED (SKYTPU_FLIGHT=0)")
        if payload.get("unexpected"):
            click.echo(f"!! unexpected post-warmup compiles: "
                       f"{payload['unexpected']}")
    else:
        records = flight_lib.load_records()
    if perfetto_path:
        # Burst spans plus synthetic `bubble:<cause>` idle spans — the
        # perfetto timeline shows WHY the device sat idle between
        # bursts, not just that it did.
        spans = (flight_lib.as_spans(records)
                 + attribution_lib.idle_spans(records))
        with open(os.path.expanduser(perfetto_path), "w") as f:
            json_lib.dump(trace_view.to_perfetto(spans), f)
        click.echo(f"perfetto trace written to {perfetto_path}")
    click.echo(flight_lib.render_table(records, programs, last=last))
    if bubbles:
        click.echo("")
        click.echo(attribution_lib.render_bubbles(
            attribution_lib.analyze_bubbles(records)))
    if follow:
        # Tail the ring: re-send the server's returned "seq" cursor so
        # each poll transfers only records stamped after it. A dropped
        # poll just means the next one carries a bigger delta; records
        # that rolled out of the ring between polls are gone (the
        # cursor can't resurrect them — pin exemplars for that).
        import time as time_mod
        seq = int(payload.get("seq", 0))
        click.echo(f"-- following (every {interval:g}s, Ctrl-C to "
                   f"stop) --")
        try:
            while True:
                time_mod.sleep(max(interval, 0.1))
                try:
                    with urllib.request.urlopen(
                            f"{url}/debug/flight?since={seq}",
                            timeout=10) as resp:
                        delta = json_lib.loads(resp.read().decode())
                except OSError as e:
                    click.echo(f"poll failed ({e}); retrying")
                    continue
                seq = int(delta.get("seq", seq))
                new = delta.get("records", [])
                for r in new:
                    ts = r.get("ts_s", 0.0)
                    label = flight_lib.program_label(r)
                    click.echo(
                        f"{ts:>14.3f}  {label:<34} "
                        f"slots={len(r.get('slots', ()))} "
                        f"toks={r.get('toks', 0)} "
                        f"host={1e3 * r.get('dur_s', 0.0):.2f}ms")
        except KeyboardInterrupt:
            click.echo("-- stopped --")


@cli.command(name="why")
@click.argument("rid", type=int)
@click.argument("target", required=False)
@click.option("--local", "local", is_flag=True, default=False,
              help="Rebuild the ledger from this machine's flushed "
                   "flight logs instead of querying a server.")
@click.option("--port", type=int, default=8080, show_default=True,
              help="Model-server port when TARGET is a cluster name.")
@click.option("--json", "as_json", is_flag=True, default=False,
              help="Emit the raw ledger dict instead of the table.")
def why_cmd(rid, target, local, port, as_json):
    """Explain where one request's latency went, phase by phase.

    The forensics ledger (docs/observability.md §Request forensics)
    decomposes the request's measured submit->retire wall into named
    phases — queue wait, admission stalls by cause, prefill waves and
    chunks, decode device-vs-host, speculative draft/verify, delivery
    — that sum to the wall. Built entirely from flight records, so it
    works on any retired request still in the ring, and on tail
    exemplars pinned past ring rollover.

    RID is the request id (the "rid" in flight records, access logs
    and span attrs). TARGET is a model-server URL or cluster name;
    `--local` (or no target) replays the flushed flight logs instead.
    """
    import json as json_lib
    import urllib.error
    import urllib.request

    from skypilot_tpu.observability import flight as flight_lib
    from skypilot_tpu.observability import forensics as forensics_lib

    if target and not local:
        if target.startswith(("http://", "https://")):
            url = target.rstrip("/")
        else:
            url = f"http://{_resolve_head_ip(target)}:{port}"
        try:
            with urllib.request.urlopen(
                    f"{url}/debug/forensics?rid={rid}",
                    timeout=10) as resp:
                payload = json_lib.loads(resp.read().decode())
        except urllib.error.HTTPError as e:
            try:
                body = json_lib.loads(e.read().decode())
                msg = body.get("error", str(e))
            except Exception:
                msg = str(e)
            raise click.ClickException(f"{url}: {msg}")
        except OSError as e:
            raise click.ClickException(
                f"GET {url}/debug/forensics failed: {e}")
        ledger = payload.get("ledger")
        if payload.get("exemplar"):
            click.echo("(from a pinned tail exemplar — this request "
                       "rolled out of the live ring)")
    else:
        records = flight_lib.load_records()
        ledger = forensics_lib.ledger_from_records(rid, records)
        if ledger is None:
            raise click.ClickException(
                f"no retired request {rid} in the flushed flight "
                f"logs (not retired yet, or logs rolled/never "
                f"flushed — try a live TARGET)")
    if ledger is None:
        raise click.ClickException(f"no ledger for request {rid}")
    if as_json:
        click.echo(json_lib.dumps(ledger, indent=2, default=str))
    else:
        click.echo(forensics_lib.render_ledger(ledger))


@cli.command(name="train-why")
@click.option("--step", type=int, default=None,
              help="Render this step's ledger (default: the newest "
                   "recorded step).")
@click.option("--json", "as_json", is_flag=True, default=False,
              help="Emit the raw ledger dict(s) instead of tables.")
def train_why_cmd(step, as_json):
    """Explain where a training step's wall time went, phase by phase.

    The goodput ledger (docs/observability.md §Training goodput)
    decomposes each recorded step's wall into named phases —
    data_wait, compute, checkpoint save/wait, eval (the loss fetch),
    anomaly pause — that sum to the step wall exactly; the remainder
    is host_other, never silence. Built from flushed train_step
    flight records, so it works on any run that has flushed (the
    recorder flushes atexit and on its heartbeat).

    Without --step, renders the newest step's ledger plus the
    aggregate phase distribution over every recorded step — where the
    RUN's wall went, which is usually the question.
    """
    import json as json_lib

    from skypilot_tpu.observability import flight as flight_lib
    from skypilot_tpu.observability import goodput as goodput_lib

    records = flight_lib.load_records()
    ledger = goodput_lib.ledger_for_step(records, step=step)
    if ledger is None:
        what = f"step {step}" if step is not None else "train_step"
        raise click.ClickException(
            f"no {what} records in the flushed flight logs (run "
            f"still warming up, recorder off, or logs never flushed)")
    summary = goodput_lib.summarize_steps(records) \
        if step is None else None
    if as_json:
        out = {"ledger": ledger}
        if summary is not None:
            out["summary"] = summary
        click.echo(json_lib.dumps(out, indent=2, default=str))
        return
    click.echo(goodput_lib.render_step_ledger(ledger))
    if summary is not None:
        click.echo("")
        click.echo(goodput_lib.render_summary(summary))


@cli.group(name="incidents")
def incidents_group():
    """SLO incident snapshots captured at breach transitions.

    When a Watchdog rule crosses into breach, the server freezes an
    atomic forensics bundle — flight-ring tail, recent events, a
    metrics snapshot, fleet health and the pinned tail exemplars —
    into ~/.skypilot_tpu/incidents/<stamp>-<rule>/ (GC'd, newest
    SKYTPU_INCIDENTS_KEEP kept). `list` enumerates them, `show`
    renders one bundle's manifest and alert.
    """


@incidents_group.command(name="list")
def incidents_list():
    """List captured incident bundles, newest first."""
    import time as time_mod

    from skypilot_tpu.observability import forensics as forensics_lib

    rows = forensics_lib.list_incidents()
    if not rows:
        click.echo("no incidents captured (no breach transitions, or "
                   "SKYTPU_INCIDENTS=0)")
        return
    fmt = "{:<40} {:<20} {:>8}  {}"
    click.echo(fmt.format("INCIDENT", "RULE", "AGE", "ALERT"))
    now = time_mod.time()
    for row in rows:
        age_s = max(0.0, now - (row.get("ts_s") or now))
        if age_s >= 3600:
            age = f"{age_s / 3600:.1f}h"
        elif age_s >= 60:
            age = f"{age_s / 60:.1f}m"
        else:
            age = f"{age_s:.0f}s"
        attrs = row.get("attrs") or {}
        brief = " ".join(
            f"{k}={attrs[k]}" for k in ("value", "threshold", "window_s")
            if k in attrs)
        click.echo(fmt.format(row.get("name", "?"),
                              row.get("rule") or "?", age, brief))


@incidents_group.command(name="show")
@click.argument("name")
@click.option("--json", "as_json", is_flag=True, default=False,
              help="Emit the bundle manifest + alert as JSON.")
def incidents_show(name, as_json):
    """Show one incident bundle: manifest, alert and file inventory."""
    import json as json_lib
    import time as time_mod

    from skypilot_tpu.observability import forensics as forensics_lib

    bundle = forensics_lib.load_incident(name)
    if bundle is None:
        raise click.ClickException(
            f"no incident {name!r} (GC'd, or captured under another "
            f"home? — `skytpu incidents list`)")
    if as_json:
        click.echo(json_lib.dumps(bundle, indent=2, default=str))
        return
    meta = bundle.get("meta", {})
    click.echo(f"incident {name}")
    click.echo(f"  rule:     {meta.get('rule', '?')}")
    ts = meta.get("ts_s")
    if ts:
        stamp = time_mod.strftime("%Y-%m-%d %H:%M:%S",
                                  time_mod.localtime(ts))
        click.echo(f"  captured: {stamp}")
    attrs = meta.get("attrs") or {}
    if attrs:
        click.echo("  alert:")
        for k in sorted(attrs):
            click.echo(f"    {k}: {attrs[k]}")
    files = bundle.get("files") or []
    if files:
        click.echo("  files:")
        for row in files:
            lines = (f"  ({row['lines']} records)"
                     if row.get("lines") is not None else "")
            click.echo(f"    {row.get('file', '?'):<16} "
                       f"{row.get('bytes', 0):>10} bytes{lines}")
    click.echo(f"  path: {bundle.get('path', '?')}")


@cli.command()
@click.argument("cluster")
def queue(cluster):
    """Show the job queue of a cluster."""
    jobs = sky.queue(cluster)
    fmt = "{:<6}{:<18}{:<12}{:>10}"
    click.echo(fmt.format("ID", "NAME", "STATUS", "DUR(S)"))
    for j in jobs:
        dur = (j["ended_at"] or __import__("time").time()) - \
            (j["started_at"] or j["submitted_at"])
        click.echo(fmt.format(j["job_id"], j["name"] or "-",
                              j["status"].value, f"{dur:.1f}"))


@cli.command()
@click.argument("cluster")
@click.argument("job_id", type=int, required=False)
@click.option("--follow/--no-follow", default=True)
def logs(cluster, job_id, follow):
    """Tail job logs (all ranks, prefixed)."""
    sky.tail_logs(cluster, job_id, follow=follow)


@cli.command()
@click.argument("cluster")
@click.argument("job_ids", type=int, nargs=-1, required=True)
def cancel(cluster, job_ids):
    """Cancel job(s)."""
    for jid in job_ids:
        sky.cancel(cluster, jid)
        click.echo(f"Cancelled job {jid}.")


@cli.command()
@click.argument("clusters", nargs=-1, required=True)
def stop(clusters):
    """Stop cluster(s) (restartable with `start`)."""
    for c in clusters:
        sky.stop(c)
        click.echo(f"Stopped {c!r}.")


@cli.command()
@click.argument("clusters", nargs=-1, required=True)
def start(clusters):
    """Restart stopped cluster(s)."""
    for c in clusters:
        sky.start(c)
        click.echo(f"Started {c!r}.")


@cli.command()
@click.argument("clusters", nargs=-1, required=True)
@click.option("--purge", is_flag=True, default=False)
def down(clusters, purge):
    """Tear down cluster(s)."""
    for c in clusters:
        sky.down(c, purge=purge)
        click.echo(f"Terminated {c!r}.")


@cli.command()
@click.argument("cluster")
@click.option("--idle-minutes", "-i", type=int, required=True)
@click.option("--down", "down_", is_flag=True, default=False)
def autostop(cluster, idle_minutes, down_):
    """Schedule autostop/autodown after idle minutes (-1 cancels)."""
    sky.autostop(cluster, idle_minutes, down_)
    click.echo(f"Autostop set on {cluster!r}: {idle_minutes} min"
               f"{' (down)' if down_ else ''}.")


@cli.group()
def catalog():
    """Catalog maintenance (pricing data)."""


@catalog.command(name="fetch")
@click.option("--out", default=None,
              help="CSV path (default: the packaged gcp.csv)")
def catalog_fetch(out):
    """Refresh GCP prices from the Cloud Billing SKUs API.

    Regenerates the static catalog (topology: generations, slice sizes,
    zones) and overlays live on-demand/spot prices where the billing
    API carries them; offline environments keep the static snapshot.
    """
    from skypilot_tpu.catalog.fetchers import fetch_gcp
    try:
        path, updated, total = fetch_gcp.fetch_and_write(out)
    except Exception as e:  # noqa: BLE001 — network/auth surface
        raise click.ClickException(
            f"billing API fetch failed ({e}); the static catalog is "
            f"unchanged") from e
    click.echo(f"{path}: live prices on {updated}/{total} TPU rows")


@cli.command(name="show-gpus")
@click.argument("name_filter", required=False)
def show_gpus(name_filter):
    """List accelerators (TPU slices and GPUs) with prices."""
    from skypilot_tpu.catalog import catalog
    df = catalog.list_accelerators(name_filter)
    seen = set()
    fmt = "{:<16}{:<8}{:<8}{:>10}{:>12}  {}"
    click.echo(fmt.format("ACCELERATOR", "CHIPS", "HOSTS", "$/HR",
                          "SPOT $/HR", "REGIONS"))
    for _, row in df.iterrows():
        key = row["accelerator"]
        if key in seen:
            continue
        seen.add(key)
        sub = df[df["accelerator"] == key]
        regions = sorted(sub["region"].unique())
        click.echo(fmt.format(
            key, row["chips"] or row["accelerator_count"], row["hosts"],
            f"{sub['price'].min():.2f}", f"{sub['spot_price'].min():.2f}",
            ",".join(regions[:3]) + ("…" if len(regions) > 3 else "")))


@cli.command()
@click.argument("clouds", nargs=-1)
def check(clouds):
    """Check cloud credentials and cache the enabled-cloud list."""
    from skypilot_tpu import check as check_lib
    try:
        check_lib.check(clouds=list(clouds) or None)
    except exceptions.NoCloudAccessError as e:
        click.echo(f"Error: {e}", err=True)
        sys.exit(1)


@cli.group()
def config():
    """Inspect or edit the layered global config."""


@config.command(name="get")
@click.argument("key")
def config_get(key):
    """Print a config value; KEY is dot-separated (e.g. gcp.project)."""
    from skypilot_tpu import config as config_lib
    val = config_lib.get_nested(tuple(key.split(".")))
    if val is None:
        click.echo("(unset)")
    elif isinstance(val, (dict, list)):
        click.echo(yaml.safe_dump(val, sort_keys=False).strip())
    else:
        click.echo(val)


@config.command(name="set")
@click.argument("key")
@click.argument("value")
def config_set(key, value):
    """Set a config value in config.yaml (value parsed as YAML)."""
    from skypilot_tpu import config as config_lib
    try:
        config_lib.set_nested(tuple(key.split(".")), yaml.safe_load(value))
    except (ValueError, yaml.YAMLError) as e:
        click.echo(f"Error: {e}", err=True)
        sys.exit(1)
    click.echo(f"{key} = {value} -> {config_lib.config_path()}")


@config.command(name="list")
def config_list():
    """Dump the effective config."""
    from skypilot_tpu import config as config_lib
    cfg = config_lib.to_dict()
    click.echo(yaml.safe_dump(cfg, sort_keys=False).strip()
               if cfg else "(empty)")


@cli.group()
def jobs():
    """Managed jobs: auto-recovery for preemptible TPU slices."""


@jobs.command(name="launch")
@click.argument("yaml_or_command")
@click.option("--name", "-n", default=None)
@click.option("--gpus", "--accelerators", "accelerators", default=None)
@click.option("--cloud", default=None)
@click.option("--use-spot/--no-use-spot", default=True,
              help="Managed jobs default to spot slices.")
@click.option("--recovery", default=None,
              help="FAILOVER | EAGER_NEXT_ZONE (default)")
def jobs_launch(yaml_or_command, name, accelerators, cloud, use_spot,
                recovery):
    """Submit a managed job with slice-preemption auto-recovery."""
    from skypilot_tpu.jobs import core as jobs_core
    is_yaml = yaml_or_command.endswith((".yaml", ".yml")) or os.path.exists(
        yaml_or_command)
    from skypilot_tpu.task import Task
    tasks = (Task.from_yaml_all(yaml_or_command) if is_yaml
             else [Task(run=yaml_or_command)])
    over = _resource_overrides(accelerators, cloud, use_spot, recovery)
    # Flag overrides apply to EVERY task of a pipeline, same as the
    # single-task path (the reference's behavior for job-level flags).
    for t in tasks:
        if over:
            t.set_resources(t.resources[0].copy(**over))
    if len(tasks) > 1:
        job_id = jobs_core.launch(tasks, name=name)
        click.echo(f"Managed pipeline {job_id} submitted "
                   f"({len(tasks)} tasks; controller log: "
                   f"jobs-controller-{job_id}.log).")
        return
    task = tasks[0]
    if name:
        task.name = name
    job_id = jobs_core.launch(task, name=name)
    click.echo(f"Managed job {job_id} submitted "
               f"(controller log: jobs-controller-{job_id}.log).")


@jobs.command(name="queue")
def jobs_queue():
    """List managed jobs."""
    from skypilot_tpu.jobs import core as jobs_core
    rows = jobs_core.queue()
    fmt = "{:<6}{:<16}{:<20}{:<7}{:<10}{:<18}"
    click.echo(fmt.format("ID", "NAME", "STATUS", "TASK", "#RECOV",
                          "CLUSTER"))
    for r in rows:
        click.echo(fmt.format(r["job_id"], r["name"] or "-",
                              r["status"].value, r.get("task", "-"),
                              r["recovery_count"],
                              r["cluster_name"] or "-"))


@jobs.command(name="cancel")
@click.argument("job_ids", type=int, nargs=-1, required=True)
def jobs_cancel(job_ids):
    """Cancel managed job(s)."""
    from skypilot_tpu.jobs import core as jobs_core
    for jid in job_ids:
        jobs_core.cancel(jid)
        click.echo(f"Cancelling managed job {jid}.")


@jobs.command(name="logs")
@click.argument("job_id", type=int)
@click.option("--controller", is_flag=True, default=False)
def jobs_logs(job_id, controller):
    """Show a managed job's (controller) logs."""
    from skypilot_tpu.jobs import core as jobs_core
    if controller:
        jobs_core.tail_controller_log(job_id)
        return
    jobs_core.tail_job_output(job_id)


@cli.group()
def serve():
    """SkyServe: autoscaled serving behind a load balancer."""


@serve.command(name="up")
@click.argument("yaml_path")
@click.option("--service-name", "-n", required=True)
@click.option("--lb-port", type=int, default=None)
def serve_up(yaml_path, service_name, lb_port):
    """Bring up a service from a task YAML with a service: section."""
    from skypilot_tpu.serve import core as serve_core
    from skypilot_tpu.task import Task
    task = Task.from_yaml(yaml_path)
    info = serve_core.up(task, service_name, lb_port=lb_port)
    click.echo(f"Service {service_name!r} starting; endpoint "
               f"{info['endpoint']}")


@serve.command(name="status")
@click.argument("service_name", required=False)
def serve_status(service_name):
    """Show services and their replicas."""
    from skypilot_tpu.serve import core as serve_core
    services = serve_core.status(service_name)
    if not services:
        if service_name:
            click.echo(f"Service {service_name!r} not found.", err=True)
            sys.exit(1)
        click.echo("No services.")
        return
    from skypilot_tpu import controller_utils
    from skypilot_tpu.serve.core import _controller_handle
    try:
        host = controller_utils.controller_endpoint_host(
            _controller_handle())
    except Exception:  # noqa: BLE001 — controller may be unreachable
        # Never print a fabricated address (a wrong-but-plausible
        # loopback endpoint reads as "service down").
        host = None
    for s in services:
        ep = (f"endpoint http://{host}:{s['lb_port']}" if host
              else f"endpoint unknown (controller unreachable), "
                   f"lb port {s['lb_port']}")
        click.echo(f"{s['name']}: {s['status'].value} "
                   f"v{s.get('version', 1)} ({ep})")
        for r in s["replicas"]:
            click.echo(f"  replica {r['replica_id']} "
                       f"(v{r.get('version', 1)}): "
                       f"{r['status'].value} {r['url'] or ''}")


@serve.command(name="update")
@click.argument("yaml_path")
@click.argument("service_name")
def serve_update(yaml_path, service_name):
    """Rolling-update a running service to a new task/spec version."""
    from skypilot_tpu.serve import core as serve_core
    from skypilot_tpu.task import Task
    task = Task.from_yaml(yaml_path)
    info = serve_core.update(task, service_name)
    click.echo(f"Service {service_name!r} updating to "
               f"version {info['version']}.")


@serve.command(name="down")
@click.argument("service_name")
@click.option("--purge", is_flag=True, default=False)
def serve_down(service_name, purge):
    """Tear down a service (replicas, LB, controller)."""
    from skypilot_tpu.serve import core as serve_core
    serve_core.down(service_name, purge=purge)
    click.echo(f"Service {service_name!r} torn down.")


@cli.command(name="cost-report")
def cost_report():
    """Show accumulated cost of terminated clusters."""
    rows = sky.cost_report()
    if not rows:
        click.echo("No cost history.")
        return
    fmt = "{:<16}{:>12}{:>10}"
    click.echo(fmt.format("NAME", "DUR(MIN)", "COST($)"))
    for r in rows:
        click.echo(fmt.format(r["name"], f"{r['duration_s']/60:.1f}",
                              f"{r['cost']:.2f}"))


@cli.group()
def local():
    """Local kubernetes-in-docker (kind) cluster for real-k8s runs
    without any cloud credentials."""


@local.command(name="up")
@click.option("--name", default=None,
              help="kind cluster name (default skytpu-local)")
def local_up(name):
    """Create (or reuse) a kind cluster and enable the kubernetes
    cloud against it."""
    from skypilot_tpu import core as core_mod
    ctx = core_mod.local_up(name or core_mod.LOCAL_KIND_CLUSTER)
    click.echo(f"local kubernetes up (kubectl context {ctx}); "
               "launch with: skytpu launch --cloud kubernetes ...")


@local.command(name="down")
@click.option("--name", default=None)
def local_down(name):
    """Delete the local kind cluster."""
    from skypilot_tpu import core as core_mod
    core_mod.local_down(name or core_mod.LOCAL_KIND_CLUSTER)
    click.echo("local kubernetes deleted")


@cli.group()
def api():
    """The local API server (async request execution + dashboard)."""


def _api_url() -> str:
    from skypilot_tpu.client import sdk as sdk_mod
    return sdk_mod._url()


def _api_pid_file() -> str:
    from skypilot_tpu.utils import paths
    return os.path.join(paths.home(), "api_server.pid")


@api.command(name="start")
@click.option("--port", type=int, default=None)
@click.option("--host", default="127.0.0.1", show_default=True,
              help="Bind address; 0.0.0.0 shares the server on the "
                   "network — pair it with --auth.")
@click.option("--auth", is_flag=True, default=False,
              help="Require a bearer token (generated once at "
                   "~/.skypilot_tpu/api_token; clients on other "
                   "machines copy that file or set "
                   "SKYPILOT_TPU_API_TOKEN).")
def api_start(port, host, auth):
    """Start the API server (no-op if one is already running)."""
    from skypilot_tpu.client import sdk as sdk_mod
    info = sdk_mod.api_start(port, host=host, auth=auth)
    suffix = ""
    if auth:
        with open(sdk_mod._token_path()) as f:
            suffix = f"?token={f.read().strip()}"
    click.echo(f"API server healthy at {_api_url()} "
               f"(version {info.get('version', '?')}); dashboard at "
               f"{_api_url()}/dashboard{suffix}")
    if auth:
        click.echo(f"auth: bearer token at {sdk_mod._token_path()}")


@api.command(name="stop")
def api_stop():
    """Stop the background API server."""
    import signal
    pid = None
    try:
        with open(_api_pid_file()) as f:
            pid = int(f.read().strip())
    except (OSError, ValueError):
        click.echo("No running API server found.", err=True)
        return
    try:
        os.kill(pid, signal.SIGTERM)
        click.echo(f"Stopped API server (pid {pid}).")
    except ProcessLookupError:
        click.echo("API server already gone; cleaned up stale record.",
                   err=True)
    finally:
        try:
            os.remove(_api_pid_file())
        except OSError:
            pass


@api.command(name="info")
def api_info():
    """Health/version of the API server."""
    import json
    import urllib.request
    try:
        with urllib.request.urlopen(f"{_api_url()}/api/health",
                                    timeout=5) as r:
            info = json.loads(r.read())
        click.echo(f"API server at {_api_url()}: {info['status']} "
                   f"(version {info.get('version', '?')})")
    except OSError:
        click.echo(f"API server at {_api_url()} is not reachable.",
                   err=True)
        sys.exit(1)


def _api_unreachable() -> None:
    click.echo(f"API server at {_api_url()} is not reachable "
               f"(try `api start`).", err=True)
    sys.exit(1)


@api.command(name="status")
def api_status():
    """List recent API requests."""
    import json
    import urllib.request
    try:
        with urllib.request.urlopen(f"{_api_url()}/api/status",
                                    timeout=10) as r:
            rows = json.loads(r.read())
    except OSError:
        return _api_unreachable()
    fmt = "{:<14}{:<18}{:<12}"
    click.echo(fmt.format("REQUEST", "OP", "STATUS"))
    for row in rows[-30:]:
        click.echo(fmt.format(row["request_id"][:12], row["name"],
                              row["status"]))


@api.command(name="cancel")
@click.argument("request_id")
def api_cancel(request_id):
    """Cancel an in-flight API request."""
    import json
    import urllib.error
    import urllib.request
    req = urllib.request.Request(
        f"{_api_url()}/api/cancel",
        data=json.dumps({"request_id": request_id}).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            json.loads(r.read())
    except urllib.error.HTTPError as e:
        click.echo(f"Error: {e.read().decode()[:200]}", err=True)
        sys.exit(1)
    except OSError:
        return _api_unreachable()
    click.echo(f"Cancelled request {request_id}.")


@api.command(name="logs")
@click.argument("request_id")
def api_logs(request_id):
    """Stream a request's log."""
    import urllib.request
    try:
        with urllib.request.urlopen(
                f"{_api_url()}/api/stream?request_id={request_id}",
                timeout=30) as r:
            click.echo(r.read().decode())
    except OSError:
        return _api_unreachable()


@cli.group()
def storage():
    """Bucket storage objects created via storage_mounts."""


@storage.command(name="ls")
def storage_ls():
    """List tracked storage objects."""
    from skypilot_tpu import state as state_mod
    fmt = "{:<28}{:<10}{:<12}{:<12}"
    click.echo(fmt.format("NAME", "MODE", "PERSISTENT", "STORE"))
    for s in state_mod.list_storage():
        h = s["handle"]
        src = h.get("source") or ""
        scheme = src.split("://")[0] if "://" in src else "gs"
        click.echo(fmt.format(
            s["name"], h.get("mode", "-"),
            str(h.get("persistent", True)), scheme))


@storage.command(name="delete")
@click.argument("names", nargs=-1, required=True)
def storage_delete(names):
    """Delete storage object(s): removes the bucket and the record."""
    from skypilot_tpu import state as state_mod
    from skypilot_tpu.data import storage as storage_lib
    for name in names:
        rec = state_mod.get_storage(name)
        if rec is None:
            click.echo(f"Storage {name!r} not found.", err=True)
            continue
        h = dict(rec["handle"])
        h["persistent"] = False       # explicit delete overrides
        src = h.get("source") or ""
        if "://" in src:
            # External bucket: we didn't create it, we don't delete it.
            state_mod.remove_storage(name)
            click.echo(f"Removed record for {name!r} (external bucket "
                       f"{src} left intact).")
            continue
        try:
            storage_lib.Storage.from_yaml_config(h).delete()
        except Exception as e:  # noqa: BLE001
            click.echo(f"Warning: deleting bucket for {name!r} failed: "
                       f"{e}", err=True)
        state_mod.remove_storage(name)
        click.echo(f"Deleted storage {name!r}.")


@cli.group()
def bench():
    """Benchmark a task across candidate resources (cost/time)."""


@bench.command(name="launch")
@click.argument("yaml_path")
@click.option("--benchmark", "-b", required=True, help="Benchmark name.")
@click.option("-g", "--gpus", "--accelerators", "accelerators",
              multiple=True,
              help="Candidate accelerators; repeat for variants "
                   "(e.g. -g tpu-v5e-8 -g tpu-v6e-8).")
@click.option("--no-wait", is_flag=True, default=False)
@click.option("--keep-clusters", is_flag=True, default=False)
def bench_launch(yaml_path, benchmark, accelerators, no_wait,
                 keep_clusters):
    """Launch the task once per candidate resource set."""
    from skypilot_tpu.benchmark import benchmark_utils
    task = _load_task(yaml_path, None, None, None, None, False, None)
    candidates = ([{"accelerators": a} for a in accelerators]
                  or [{}])
    results = benchmark_utils.launch_benchmark(
        benchmark, task, candidates, wait=not no_wait,
        teardown=not keep_clusters)
    for r in results:
        extra = f" — {r['error']}" if r.get("error") else ""
        click.echo(f"{r['cluster']}: {r['status']} "
                   f"({r['duration_s']:.0f}s @ ${r['price_per_hour']}/hr)"
                   f"{extra}")


@bench.command(name="ls")
def bench_ls():
    """List benchmarks."""
    from skypilot_tpu.benchmark import benchmark_state
    fmt = "{:<24}{:<12}"
    click.echo(fmt.format("BENCHMARK", "STATUS"))
    for b in benchmark_state.list_benchmarks():
        click.echo(fmt.format(b["name"], b["status"]))


@bench.command(name="show")
@click.argument("benchmark")
def bench_show(benchmark):
    """Per-candidate cost/time comparison, cheapest first."""
    from skypilot_tpu.benchmark import benchmark_utils
    rows = benchmark_utils.summarize(benchmark)
    if not rows:
        click.echo(f"No results for benchmark {benchmark!r}.")
        return
    fmt = "{:<34}{:<34}{:>10}{:>12}{:>10}"
    click.echo(fmt.format("CLUSTER", "RESOURCES", "DUR(S)", "COST($)",
                          "STATUS"))
    for r in rows:
        click.echo(fmt.format(r["cluster"], r["resources"][:32],
                              f"{r['duration_s']:.0f}",
                              f"{r['cost']:.4f}", r["status"]))


@bench.command(name="delete")
@click.argument("benchmark")
def bench_delete(benchmark):
    """Delete a benchmark's records."""
    from skypilot_tpu.benchmark import benchmark_state
    benchmark_state.delete_benchmark(benchmark)
    click.echo(f"Deleted benchmark {benchmark!r}.")


@cli.group(name="chaos")
def chaos_group():
    """Deterministic fault injection (see docs/robustness.md)."""


@chaos_group.command(name="validate")
@click.argument("plan_path")
def chaos_validate(plan_path):
    """Parse a fault-plan JSON file and print the normalized schedule.

    Exits non-zero on a malformed plan; warns on rules bound to
    injection points the tree does not define (they inject nothing).
    """
    from skypilot_tpu import chaos as chaos_lib
    try:
        plan = chaos_lib.load_plan_file(plan_path)
    except (OSError, ValueError) as e:
        raise click.ClickException(f"invalid chaos plan: {e}")
    click.echo(f"seed: {plan.seed}")
    fmt = "{:<30}{:<28}{:<8}{:<7}{:<7}{:<9}{}"
    click.echo(fmt.format("POINT", "MATCH", "TIMES", "AFTER", "PROB",
                          "LATENCY", "EFFECT"))
    for r in plan.rules:
        match = ",".join(f"{k}={v}" for k, v in r.match.items()) or "-"
        click.echo(fmt.format(
            r.point, match[:26],
            "inf" if r.times is None else str(r.times), str(r.after),
            "-" if r.probability is None else f"{r.probability:g}",
            f"{r.latency_s:g}s" if r.latency_s else "-", r.effect()))
    unknown = chaos_lib.unknown_points(plan)
    if unknown:
        click.echo(f"WARNING: unknown injection point(s) — these rules "
                   f"inject nothing: {', '.join(unknown)}", err=True)


@chaos_group.command(name="points")
def chaos_points():
    """List the injection points a fault plan can target."""
    from skypilot_tpu import chaos as chaos_lib
    fmt = "{:<32}{}"
    click.echo(fmt.format("POINT", "WHERE / CONTEXT"))
    for name in sorted(chaos_lib.KNOWN_POINTS):
        click.echo(fmt.format(name, chaos_lib.KNOWN_POINTS[name]))


@cli.command(name="lint")
@click.argument("paths", nargs=-1)
@click.option("--changed", is_flag=True, default=False,
              help="Only files changed vs HEAD (plus untracked). "
                   "Skips stale-baseline detection; <2s on a warm "
                   "cache.")
@click.option("--baseline-update", "baseline_update", is_flag=True,
              default=False,
              help="Rewrite lint_baseline.json so the current tree is "
                   "exactly clean. Existing justifications are kept; "
                   "new entries get a TODO the tier-1 gate rejects "
                   "until a human writes the one-line reason.")
@click.option("--json", "as_json", is_flag=True, default=False,
              help="Machine-readable findings (one JSON object).")
@click.option("--checker", "checker_names", multiple=True,
              help="Run only these checkers (repeatable; see "
                   "docs/analysis.md for the catalog).")
@click.option("--no-cache", is_flag=True, default=False,
              help="Ignore and don't write the per-file result cache.")
def lint(paths, changed, baseline_update, as_json, checker_names,
         no_cache):
    """Static-analysis suite: retrace-safety, host-sync,
    lock-discipline, typed-errors, event/metric hygiene.

    Clean exit (0) means no findings beyond the checked-in baseline
    and no rotted baseline entries. See docs/analysis.md.
    """
    import json as json_lib

    from skypilot_tpu import analysis
    from skypilot_tpu.analysis import baseline as baseline_lib
    from skypilot_tpu.analysis import core as analysis_core

    root = analysis_core.repo_root()
    files = None
    if changed and paths:
        raise click.ClickException(
            "pass --changed or explicit paths, not both")
    if baseline_update and (changed or paths or checker_names):
        # A subset run sees a subset of findings; regenerating the
        # baseline from it would silently delete every other entry
        # (and its hand-written justification).
        raise click.ClickException(
            "--baseline-update requires a full run (no --changed, "
            "paths, or --checker)")
    if changed:
        files = analysis_core.changed_files(root)
        if not files:
            click.echo("lint: no changed files.")
            return
    elif paths:
        files = []
        for p in paths:
            ap = os.path.abspath(p)
            if os.path.isdir(ap):
                for dirpath, dirnames, names in os.walk(ap):
                    dirnames[:] = [d for d in dirnames
                                   if d != "__pycache__"]
                    files.extend(
                        os.path.relpath(os.path.join(dirpath, n), root)
                        for n in names if n.endswith(".py"))
            else:
                files.append(os.path.relpath(ap, root))
    try:
        res = analysis.run(root=root, files=files,
                           checkers=list(checker_names) or None,
                           use_cache=not no_cache)
    except ValueError as e:
        raise click.ClickException(str(e))
    if paths and res.files_scanned == 0:
        # A typo'd path in a hook must not make the gate pass
        # vacuously forever.
        raise click.ClickException(
            "none of the given paths resolve to lintable files "
            "(the suite scans skypilot_tpu/**/*.py)")

    if baseline_update:
        bp = baseline_lib.default_path(root)
        old = baseline_lib.load(bp)
        entries = baseline_lib.updated(res.findings, old)
        baseline_lib.save(bp, entries)
        todo = [k for k, e in entries.items()
                if e["justification"].startswith("TODO")]
        click.echo(f"lint: baseline rewritten with {len(entries)} "
                   f"entr{'y' if len(entries) == 1 else 'ies'} "
                   f"({len(res.findings)} findings).")
        if todo:
            click.echo("lint: entries needing a justification "
                       "(the tier-1 gate rejects TODOs):")
            for k in todo:
                click.echo(f"  {k}")
        return

    if as_json:
        click.echo(json_lib.dumps({
            "findings": [f.to_dict() for f in res.new],
            "baselined": len(res.findings) - len(res.new),
            "stale_baseline": res.stale,
            "unjustified_baseline": res.unjustified,
            "files_scanned": res.files_scanned,
            "files_from_cache": res.files_from_cache,
            "clean": res.clean,
        }, indent=1))
    else:
        for f in res.new:
            click.echo(f.format())
        for k in res.stale:
            click.echo(f"stale baseline entry (finding fixed or file "
                       f"renamed — remove it): {k}")
        for k in res.unjustified:
            click.echo(f"baseline entry without a justification: {k}")
        n_base = len(res.findings) - len(res.new)
        click.echo(f"lint: {res.files_scanned} files "
                   f"({res.files_from_cache} cached), "
                   f"{len(res.new)} finding"
                   f"{'' if len(res.new) == 1 else 's'}, "
                   f"{n_base} baselined"
                   + (f", {len(res.stale)} stale baseline"
                      if res.stale else "")
                   + (" [partial run]" if res.partial else ""))
    if not res.clean:
        sys.exit(1)


def main():
    try:
        cli()
    except exceptions.SkyTpuError as e:
        click.echo(f"Error: {e}", err=True)
        sys.exit(1)


if __name__ == "__main__":
    main()
