"""Client SDK over the API server: submit -> request id -> poll/stream.

Reference parity: sky/client/sdk.py (launch() posts /launch and returns
a request id; get()/stream_and_get() poll; api_start/api_stop/api_info
manage a local server). The CLI and Python API can run either direct
(library calls, default) or through a server via these functions.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.observability import tracing
from skypilot_tpu.task import Task
from skypilot_tpu.utils import paths

DEFAULT_URL = "http://127.0.0.1:46580"


def _url() -> str:
    return os.environ.get("SKYTPU_API_SERVER_URL", DEFAULT_URL)


def _token_path() -> str:
    return os.path.join(paths.home(), "api_token")


def _headers() -> Dict[str, str]:
    """Auth + identity headers on every SDK call. The bearer token
    comes from SKYPILOT_TPU_API_TOKEN or ~/.skypilot_tpu/api_token
    (written by `api start --auth`); identity rides as X-SkyTPU-User-*
    so the server's request workers run AS this client (ownership
    checks, users table)."""
    h = {"Content-Type": "application/json"}
    token = os.environ.get("SKYPILOT_TPU_API_TOKEN")
    if not token and os.path.exists(_token_path()):
        with open(_token_path()) as f:
            token = f.read().strip()
    if token:
        h["Authorization"] = f"Bearer {token}"
    from skypilot_tpu import authentication
    me = authentication.get_user_identity()
    h["X-SkyTPU-User-Id"] = me["id"]
    h["X-SkyTPU-User-Name"] = me["name"]
    return h


def _post(path: str, payload: Dict[str, Any]) -> Dict[str, Any]:
    # Every submission opens a client-side span and sends its context
    # as a W3C-style traceparent header; the server adopts the trace so
    # `skytpu trace <request_id>` shows the submit hop too. (Polling
    # GETs are deliberately unspanned — one request, not 300 polls.)
    with tracing.start_span(f"sdk.request:{path}") as span:
        headers = _headers()
        headers["traceparent"] = tracing.format_traceparent(span.ctx)
        req = urllib.request.Request(
            _url() + path, data=json.dumps(payload).encode(),
            headers=headers, method="POST")
        with urllib.request.urlopen(req, timeout=30) as resp:
            return json.loads(resp.read())


def _get_json(path: str) -> Any:
    req = urllib.request.Request(_url() + path, headers=_headers())
    with urllib.request.urlopen(req, timeout=30) as resp:
        return json.loads(resp.read())


# -- async request API ------------------------------------------------------

def get(request_id: str, timeout: float = 600) -> Any:
    """Block until the request finishes; return its result or raise."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        rec = _get_json(f"/api/get?request_id={request_id}")
        if rec["status"] in ("SUCCEEDED",):
            return rec["result"]
        if rec["status"] in ("FAILED", "CANCELLED"):
            raise exceptions.SkyTpuError(
                rec.get("error") or f"request {rec['status']}")
        time.sleep(0.2)
    raise TimeoutError(f"request {request_id} not finished in {timeout}s")


def stream_and_get(request_id: str, timeout: float = 600,
                   out=None) -> Any:
    out = out or sys.stdout
    offset = 0
    deadline = time.time() + timeout
    while True:
        content = _stream(request_id)
        if len(content) > offset:
            out.write(content[offset:])
            out.flush()
            offset = len(content)
        rec = _get_json(f"/api/get?request_id={request_id}")
        if rec["status"] == "SUCCEEDED":
            return rec["result"]
        if rec["status"] in ("FAILED", "CANCELLED"):
            raise exceptions.SkyTpuError(
                rec.get("error") or f"request {rec['status']}")
        if time.time() > deadline:
            raise TimeoutError(f"request {request_id} timed out")
        time.sleep(0.2)


def _stream(request_id: str) -> str:
    req = urllib.request.Request(
        _url() + f"/api/stream?request_id={request_id}",
        headers=_headers())
    with urllib.request.urlopen(req, timeout=30) as resp:
        return resp.read().decode(errors="replace")


def api_cancel(request_id: str) -> None:
    _post("/api/cancel", {"request_id": request_id})


def api_status() -> List[Dict[str, Any]]:
    return _get_json("/api/status")


# -- operations (all return request ids) ------------------------------------

def launch(task: Task, cluster_name: Optional[str] = None,
           retry_until_up: bool = False,
           idle_minutes_to_autostop: Optional[int] = None,
           down: bool = False) -> str:
    return _post("/launch", {
        "task": task.to_yaml_config(), "cluster_name": cluster_name,
        "retry_until_up": retry_until_up,
        "idle_minutes_to_autostop": idle_minutes_to_autostop,
        "down": down})["request_id"]


def exec(task: Task, cluster_name: str) -> str:  # noqa: A001
    return _post("/exec", {"task": task.to_yaml_config(),
                           "cluster_name": cluster_name})["request_id"]


def status(cluster_names: Optional[List[str]] = None,
           refresh: bool = False) -> str:
    return _post("/status", {"cluster_names": cluster_names,
                             "refresh": refresh})["request_id"]


def queue(cluster_name: str) -> str:
    return _post("/queue", {"cluster_name": cluster_name})["request_id"]


def stop(cluster_name: str) -> str:
    return _post("/stop", {"cluster_name": cluster_name})["request_id"]


def start(cluster_name: str) -> str:
    return _post("/start", {"cluster_name": cluster_name})["request_id"]


def down(cluster_name: str) -> str:
    return _post("/down", {"cluster_name": cluster_name})["request_id"]


def cancel(cluster_name: str, job_id: int) -> str:
    return _post("/cancel", {"cluster_name": cluster_name,
                             "job_id": job_id})["request_id"]


def jobs_launch(task: Task, name: Optional[str] = None) -> str:
    return _post("/jobs/launch", {"task": task.to_yaml_config(),
                                  "name": name})["request_id"]


def jobs_queue() -> str:
    return _post("/jobs/queue", {})["request_id"]


def serve_up(task: Task, service_name: str,
             lb_port: Optional[int] = None) -> str:
    return _post("/serve/up", {"task": task.to_yaml_config(),
                               "service_name": service_name,
                               "lb_port": lb_port})["request_id"]


def serve_down(service_name: str) -> str:
    return _post("/serve/down",
                 {"service_name": service_name})["request_id"]


# -- local server lifecycle --------------------------------------------------

def api_info() -> Optional[Dict[str, Any]]:
    try:
        return _get_json("/api/health")
    except Exception:  # noqa: BLE001
        return None


def api_start(port: Optional[int] = None, wait: float = 15,
              host: str = "127.0.0.1",
              auth: bool = False) -> Dict[str, Any]:
    """Start a local API server daemon if none is running. The port
    defaults to the one in SKYTPU_API_SERVER_URL (or 46580), and the
    readiness poll targets that same port.

    ``auth=True`` generates (once) a bearer token at
    ~/.skypilot_tpu/api_token (0600) and starts the server requiring
    it — the mode to use with a non-loopback ``host``. The SDK picks
    the token up from the same file automatically."""
    if port is None:
        port = urllib.parse.urlparse(_url()).port or 46580
    os.environ["SKYTPU_API_SERVER_URL"] = f"http://127.0.0.1:{port}"
    info = api_info()
    if info is not None:
        if auth:
            # A server is already up — refuse to silently "enable" auth
            # if that server accepts unauthenticated requests (the CLI
            # would otherwise report token auth on an open server).
            try:
                req = urllib.request.Request(_url() + "/api/status")
                urllib.request.urlopen(req, timeout=10)
                raise exceptions.SkyTpuError(
                    f"an API server is already running at {_url()} "
                    "WITHOUT auth; `api stop` it first, then "
                    "`api start --auth`")
            except urllib.error.HTTPError as e:
                if e.code != 401:
                    raise
        return info
    cmd = [sys.executable, "-m", "skypilot_tpu.server.server",
           "--host", host, "--port", str(port)]
    if auth:
        if not os.path.exists(_token_path()):
            import secrets
            fd = os.open(_token_path(),
                         os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o600)
            with os.fdopen(fd, "w") as f:
                f.write(secrets.token_hex(16))
        cmd += ["--auth-token-file", _token_path()]
    log = os.path.join(paths.logs_dir(), "api_server.log")
    with open(log, "ab") as f:
        proc = subprocess.Popen(
            cmd,
            stdout=f, stderr=subprocess.STDOUT, start_new_session=True,
            env={**os.environ, "SKYPILOT_TPU_HOME": paths.home()})
    with open(os.path.join(paths.home(), "api_server.pid"), "w") as f:
        f.write(str(proc.pid))
    deadline = time.time() + wait
    while time.time() < deadline:
        info = api_info()
        if info is not None:
            return info
        time.sleep(0.2)
    raise exceptions.SkyTpuError("API server failed to start; see " + log)
