"""Generate the CLI reference (docs/cli.md) from the click tree.

Reference parity: the reference's docs site generates its CLI page
from the click objects (docs/source/reference/cli.rst via
sphinx-click); this is the markdown equivalent, kept fresh by
tests/test_cli.py::test_cli_reference_up_to_date.

Run:  python -m skypilot_tpu.client.cli_docs > docs/cli.md
"""

from __future__ import annotations

import click

from skypilot_tpu.client import cli as cli_mod


def _params(cmd: click.Command) -> str:
    rows = []
    for p in cmd.params:
        if isinstance(p, click.Argument):
            rows.append(f"`{p.name.upper()}`"
                        + ("" if p.required else " (optional)"))
        elif isinstance(p, click.Option):
            names = "/".join(p.opts)
            rows.append(f"`{names}` — {p.help or ''}".rstrip(" —"))
    return "".join(f"\n  - {r}" for r in rows)


def _walk(cmd: click.Command, path: str, out: list, depth: int) -> None:
    help_line = (cmd.help or cmd.short_help or "").strip().split("\n\n")[0]
    help_line = " ".join(help_line.split())
    if isinstance(cmd, click.Group):
        if depth > 0:
            out.append(f"\n## `{path}`\n\n{help_line}\n")
        for name in sorted(cmd.commands):
            _walk(cmd.commands[name], f"{path} {name}".strip(), out,
                  depth + 1)
    else:
        out.append(f"\n### `{path}`\n\n{help_line}{_params(cmd)}\n")


def generate() -> str:
    out = [
        "# CLI reference",
        "",
        "Generated from the `skytpu` click tree — do not edit by hand",
        "(`python -m skypilot_tpu.client.cli_docs > docs/cli.md`).",
        "",
        "## Top-level commands",
    ]
    root = cli_mod.cli
    groups = []
    for name in sorted(root.commands):
        cmd = root.commands[name]
        if isinstance(cmd, click.Group):
            groups.append((name, cmd))
        else:
            _walk(cmd, f"skytpu {name}", out, 1)
    for name, grp in groups:
        _walk(grp, f"skytpu {name}", out, 1)
    return "\n".join(out) + "\n"


if __name__ == "__main__":
    print(generate(), end="")
