"""Task DAG (chains fully supported, mirroring the reference's actual
support surface — reference: sky/dag.py + execution.py:188 asserts one
task per launch; chains are consumed by the optimizer's DP)."""

from __future__ import annotations

import threading
from typing import List, Optional

import networkx as nx

_CURRENT = threading.local()


class Dag:
    def __init__(self, name: Optional[str] = None):
        self.name = name
        self.graph = nx.DiGraph()
        self._prev: Optional["Dag"] = None

    @property
    def tasks(self) -> List:
        return list(self.graph.nodes)

    def add(self, task) -> None:
        self.graph.add_node(task)

    def remove(self, task) -> None:
        self.graph.remove_node(task)

    def add_edge(self, a, b) -> None:
        self.graph.add_node(a)
        self.graph.add_node(b)
        self.graph.add_edge(a, b)

    def is_chain(self) -> bool:
        n = len(self.graph)
        if n <= 1:
            return True
        degrees_ok = all(self.graph.in_degree(v) <= 1
                         and self.graph.out_degree(v) <= 1
                         for v in self.graph)
        return (degrees_ok and nx.is_directed_acyclic_graph(self.graph)
                and nx.number_weakly_connected_components(self.graph) == 1)

    def topological_order(self) -> List:
        return list(nx.topological_sort(self.graph))

    def __enter__(self) -> "Dag":
        self._prev = getattr(_CURRENT, "dag", None)
        _CURRENT.dag = self
        return self

    def __exit__(self, *exc) -> None:
        _CURRENT.dag = self._prev

    def __len__(self) -> int:
        return len(self.graph)


def get_current_dag() -> Optional[Dag]:
    return getattr(_CURRENT, "dag", None)
