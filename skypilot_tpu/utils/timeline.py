"""Chrome trace-event tracing: ``@timeline.event`` + FileLockEvent.

Events are buffered in-process and flushed as Chrome trace-format JSON
(chrome://tracing / Perfetto loadable) to the path in
``SKYTPU_TIMELINE_FILE_PATH`` at process exit. Zero overhead when the
env var is unset.

Reference parity: sky/utils/timeline.py (Event/FileLockEvent, @event
decorator, SKYPILOT_TIMELINE_FILE_PATH; SURVEY.md §5 Tracing).
"""

from __future__ import annotations

import atexit
import functools
import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

ENV_VAR = "SKYTPU_TIMELINE_FILE_PATH"

_events: List[Dict[str, Any]] = []
_lock = threading.Lock()
_registered = False


def enabled() -> bool:
    return bool(os.environ.get(ENV_VAR))


def _save() -> None:
    path = os.environ.get(ENV_VAR)
    if not path or not _events:
        return
    with _lock:
        payload = {"traceEvents": list(_events),
                   "displayTimeUnit": "ms"}
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f)


def _ensure_atexit() -> None:
    global _registered
    if not _registered:
        atexit.register(_save)
        _registered = True


class Event:
    """Context manager emitting a complete ('X') trace event."""

    def __init__(self, name: str, message: Optional[str] = None):
        self._name = name
        self._message = message
        self._begin_us = 0.0

    def begin(self) -> None:
        self._begin_us = time.time() * 1e6

    def end(self) -> None:
        if not enabled():
            return
        _ensure_atexit()
        evt = {
            "name": self._name,
            "ph": "X",
            "ts": self._begin_us,
            "dur": time.time() * 1e6 - self._begin_us,
            "pid": os.getpid(),
            "tid": threading.get_ident() % 100_000,
        }
        if self._message:
            evt["args"] = {"message": self._message}
        with _lock:
            _events.append(evt)

    def __enter__(self) -> "Event":
        self.begin()
        return self

    def __exit__(self, *exc) -> None:
        self.end()


def event(fn: Optional[Callable] = None, name: Optional[str] = None):
    """Decorator tracing every call of ``fn`` (no-op when disabled)."""
    if fn is None:
        return functools.partial(event, name=name)

    evt_name = name or f"{fn.__module__}.{fn.__qualname__}"

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        if not enabled():
            return fn(*args, **kwargs)
        with Event(evt_name):
            return fn(*args, **kwargs)

    return wrapper


class FileLockEvent:
    """An exclusive cross-process file lock (stdlib ``fcntl.flock``)
    whose acquisition waits show up on the trace.

    flock serializes distinct open-file-descriptions, so two THREADS of
    one process exclude each other too (each acquire opens its own fd)
    — the per-cluster launch lock needs both. ``timeout`` < 0 blocks
    forever; otherwise TimeoutError after ~that many seconds.
    """

    def __init__(self, lockfile: str, timeout: float = -1):
        self._lockfile = os.path.abspath(lockfile)
        os.makedirs(os.path.dirname(self._lockfile), exist_ok=True)
        self._timeout = timeout
        self._fd = None

    def acquire(self):
        import fcntl
        with Event(f"filelock.acquire:{self._lockfile}"):
            fd = os.open(self._lockfile, os.O_RDWR | os.O_CREAT, 0o644)
            if self._timeout < 0:
                fcntl.flock(fd, fcntl.LOCK_EX)
            else:
                deadline = time.time() + self._timeout
                while True:
                    try:
                        fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                        break
                    except OSError:
                        if time.time() >= deadline:
                            os.close(fd)
                            raise TimeoutError(
                                f"lock {self._lockfile} not acquired "
                                f"within {self._timeout}s") from None
                        time.sleep(0.05)
            self._fd = fd

    def release(self):
        if self._fd is not None:
            os.close(self._fd)  # closing drops the flock
            self._fd = None

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()


def save_now() -> None:
    """Flush buffered events immediately (tests / long daemons)."""
    _save()
