"""Chrome trace-event tracing: ``@timeline.event`` + FileLockEvent.

Events are buffered in-process and flushed as Chrome trace-format JSON
(chrome://tracing / Perfetto loadable) to the path in
``SKYTPU_TIMELINE_FILE_PATH`` at process exit. Zero overhead when the
env var is unset.

Metrics bridge: an :class:`Event` (or ``@event`` decorator) given a
``histogram=`` — anything with ``observe(seconds)``, i.e. an
``observability.metrics`` histogram child — records its duration there
on EVERY call, traced or not. One instrumentation point yields both the
Perfetto span and the live latency histogram, under the same name, so
a spike on ``/metrics`` can be cross-examined in the trace.

Reference parity: sky/utils/timeline.py (Event/FileLockEvent, @event
decorator, SKYPILOT_TIMELINE_FILE_PATH; SURVEY.md §5 Tracing).
"""

from __future__ import annotations

import atexit
import functools
import json
import os
import tempfile
import threading
import time
from typing import Any, Callable, Dict, List, Optional

ENV_VAR = "SKYTPU_TIMELINE_FILE_PATH"

_events: List[Dict[str, Any]] = []   # guarded-by: _lock
_lock = threading.Lock()
_flush_lock = threading.Lock()   # serializes writers of the trace file
_registered = False
_named_tids: Dict[int, str] = {}     # guarded-by: _lock
_seq = 0                             # guarded-by: _lock
_flushed_seq = 0                     # guarded-by: _lock
_last_flush_s = 0.0                  # guarded-by: _lock
# Long-lived daemons flush every tick; without a cap the buffer (and
# each flush's serialization cost) grows for the life of the process.
_MAX_EVENTS = 200_000


def enabled() -> bool:
    return bool(os.environ.get(ENV_VAR))


def _save() -> None:
    global _flushed_seq, _last_flush_s
    path = os.environ.get(ENV_VAR)
    if not path:
        return
    with _lock:
        if not _events or _seq == _flushed_seq:
            return               # nothing new since the last flush
        seq_snapshot = _seq
        payload = {"traceEvents": list(_events),
                   "displayTimeUnit": "ms"}
    # Atomic flush: daemons call save_now() periodically and crash
    # whenever — a reader (or the atexit flush racing a mid-run
    # save_now) must never see a truncated JSON. Write a sibling temp
    # file and os.replace it over the target (same-filesystem rename is
    # atomic on POSIX). _flush_lock serializes writers so an older
    # snapshot can never land on top of a newer one.
    with _flush_lock:
        with _lock:
            if seq_snapshot <= _flushed_seq:
                return           # a newer flush already landed
        path = os.path.abspath(path)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path),
                                   prefix=os.path.basename(path) + ".")
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as f:
                json.dump(payload, f)
            os.replace(tmp, path)
            with _lock:
                _flushed_seq = seq_snapshot
                _last_flush_s = time.monotonic()
        except BaseException:
            try:
                os.remove(tmp)
            except OSError:
                pass
            raise


def _save_atexit() -> None:
    try:
        _save()
    except OSError:
        pass   # best-effort: exit must stay quiet on unwritable paths


def _ensure_atexit() -> None:
    global _registered
    if not _registered:
        atexit.register(_save_atexit)
        _registered = True


def _append(evt: Dict[str, Any]) -> None:
    """Append a trace event, emitting this thread's name metadata the
    first time the thread shows up (Perfetto renders the track name).
    Keyed by (tid, name), not tid alone: CPython reuses idents after a
    thread exits, and a recycled ident must not inherit the dead
    thread's track name."""
    global _seq
    tid = evt["tid"]
    name = threading.current_thread().name
    with _lock:
        if _named_tids.get(tid) != name:
            _named_tids[tid] = name
            _events.append({
                "name": "thread_name", "ph": "M",
                "pid": evt["pid"], "tid": tid,
                "args": {"name": name},
            })
        _events.append(evt)
        _seq += 1
        if len(_events) > _MAX_EVENTS:
            # Drop the oldest half of the spans, and with them the
            # name metadata of threads that no longer own any kept
            # span — under thread churn an every-metadata-survives trim
            # would grow the buffer the cap exists to bound. Dropped
            # names re-emit if their thread records again.
            spans = [e for e in _events if e.get("ph") != "M"]
            del spans[:len(spans) // 2]
            kept_tids = {e["tid"] for e in spans}
            meta = [e for e in _events
                    if e.get("ph") == "M" and e["tid"] in kept_tids]
            _events[:] = meta + spans
            for t in list(_named_tids):
                if t not in kept_tids:
                    del _named_tids[t]


class Event:
    """Context manager emitting a complete ('X') trace event, and —
    when constructed with ``histogram=`` — observing the duration into
    that histogram child regardless of tracing state."""

    def __init__(self, name: str, message: Optional[str] = None,
                 histogram: Optional[Any] = None):
        self._name = name
        self._message = message
        self._histogram = histogram
        self._begin_us = 0.0

    def begin(self) -> None:
        self._begin_us = time.time() * 1e6

    @property
    def begin_s(self) -> float:
        """Wall-clock begin time in seconds (0.0 before ``begin()``).
        Lets co-instrumented systems (the tracing event log) reuse this
        span's timestamps instead of re-reading the clock."""
        return self._begin_us / 1e6

    def end(self) -> None:
        dur_us = time.time() * 1e6 - self._begin_us
        if self._histogram is not None:
            self._histogram.observe(dur_us / 1e6)
        if not enabled():
            return
        _ensure_atexit()
        evt = {
            "name": self._name,
            "ph": "X",
            "ts": self._begin_us,
            "dur": dur_us,
            "pid": os.getpid(),
            # The REAL thread ident: the old ``% 100_000`` folding could
            # merge two threads onto one Perfetto track, interleaving
            # their spans into nonsense.
            "tid": threading.get_ident(),
        }
        if self._message:
            evt["args"] = {"message": self._message}
        _append(evt)

    def __enter__(self) -> "Event":
        self.begin()
        return self

    def __exit__(self, *exc) -> None:
        self.end()


def event(fn: Optional[Callable] = None, name: Optional[str] = None,
          histogram: Optional[Any] = None):
    """Decorator tracing every call of ``fn``. With ``histogram=`` it
    also observes every call's duration (metrics are always on); with
    neither tracing enabled nor a histogram it is a no-op passthrough."""
    if fn is None:
        return functools.partial(event, name=name, histogram=histogram)

    evt_name = name or f"{fn.__module__}.{fn.__qualname__}"

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        if not enabled() and histogram is None:
            return fn(*args, **kwargs)
        with Event(evt_name, histogram=histogram):
            return fn(*args, **kwargs)

    return wrapper


class FileLockEvent:
    """An exclusive cross-process file lock (stdlib ``fcntl.flock``)
    whose acquisition waits show up on the trace.

    flock serializes distinct open-file-descriptions, so two THREADS of
    one process exclude each other too (each acquire opens its own fd)
    — the per-cluster launch lock needs both. ``timeout`` < 0 blocks
    forever; otherwise TimeoutError after ~that many seconds.
    """

    def __init__(self, lockfile: str, timeout: float = -1):
        self._lockfile = os.path.abspath(lockfile)
        os.makedirs(os.path.dirname(self._lockfile), exist_ok=True)
        self._timeout = timeout
        self._fd = None

    def acquire(self):
        import fcntl
        with Event(f"filelock.acquire:{self._lockfile}"):
            fd = os.open(self._lockfile, os.O_RDWR | os.O_CREAT, 0o644)
            if self._timeout < 0:
                fcntl.flock(fd, fcntl.LOCK_EX)
            else:
                deadline = time.time() + self._timeout
                while True:
                    try:
                        fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                        break
                    except OSError:
                        if time.time() >= deadline:
                            os.close(fd)
                            raise TimeoutError(
                                f"lock {self._lockfile} not acquired "
                                f"within {self._timeout}s") from None
                        time.sleep(0.05)
            self._fd = fd

    def release(self):
        if self._fd is not None:
            os.close(self._fd)  # closing drops the flock
            self._fd = None

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()


def save_now() -> None:
    """Flush buffered events immediately. Idempotent and crash-safe:
    each call atomically replaces the trace file with the full buffer
    so far (no partial writes, no truncation window)."""
    _save()


def save_periodic(min_new_events: int = 512,
                  max_age_s: float = 60.0) -> None:
    """Throttled :func:`save_now` for per-tick daemon callers. Every
    flush re-serializes the WHOLE buffer (up to ``_MAX_EVENTS`` dicts),
    so flushing on each tick turns a short poll interval into a
    JSON-dump loop as the buffer fills. Flush only once at least
    ``min_new_events`` accumulated since the last flush, or the last
    flush is older than ``max_age_s`` — crash-safety with a bounded
    staleness window instead of per-event cost."""
    with _lock:
        if not _events or _seq == _flushed_seq:
            return               # clean buffer: nothing to flush
        pending = _seq - _flushed_seq
        fresh = time.monotonic() - _last_flush_s < max_age_s
    if pending < min_new_events and fresh:
        return
    _save()
