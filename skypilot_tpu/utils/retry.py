"""Unified retry/backoff/deadline/circuit-breaker policy.

Every retry loop in the tree routes through this module (enforced by
``tests/test_no_adhoc_retry.py``): one place owns the backoff math,
deadline accounting, and retry telemetry, so the chaos harness
(``skypilot_tpu/chaos``) can assert recovery behavior against a single
policy surface instead of N hand-rolled ``time.sleep`` loops — the
reference scatters retries across cloud adapters and the backend
(sky/backends/cloud_vm_ray_backend.py, sky/utils/common_utils.py's
``retry``), which is exactly what made its failover behavior hard to
test.

Stdlib-only: head-side runtime processes import this under
``python -S``.
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time
from typing import Any, Callable, Optional, Tuple, Type

from skypilot_tpu.observability import metrics, tracing

RETRIES = metrics.counter(
    "skytpu_retries_total",
    "Retry-policy attempt outcomes by policy name "
    "(retried | gave_up | deadline_exceeded | circuit_open)",
    labelnames=("name", "outcome"))

# Module-level RNG for backoff jitter. Deterministic tests (and the
# seeded chaos harness) pass their own ``random.Random(seed)``.
_rng = random.Random()


class RetryError(Exception):
    """Internal marker base; public failures re-raise the last cause."""


class DeadlineExceededError(Exception):
    """The overall deadline expired before an attempt succeeded. Carries
    the last attempt's exception as ``__cause__`` when one happened."""


class CircuitOpenError(Exception):
    """The circuit breaker is open: calls fail fast without attempting."""


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Declarative retry behavior: capped jittered exponential backoff.

    ``backoff(attempt)`` for attempt 0,1,2,... is
    ``min(base * multiplier**attempt, cap)`` scaled down by up to
    ``jitter`` (a fraction in [0, 1]) — jitter only ever *shortens* a
    sleep, so the cap is a hard upper bound and deadline math stays
    conservative. ``retry_on`` classifies retryable failures;
    ``give_up_on`` carves out subclasses that must fail immediately
    (e.g. a typed permanent refusal inside a broad transient class).
    """

    max_attempts: int = 3
    backoff_base_s: float = 0.5
    backoff_multiplier: float = 2.0
    backoff_max_s: float = 30.0
    jitter: float = 0.25
    retry_on: Tuple[Type[BaseException], ...] = (Exception,)
    give_up_on: Tuple[Type[BaseException], ...] = ()

    def backoff_s(self, attempt: int,
                  rng: Optional[random.Random] = None) -> float:
        base = min(self.backoff_base_s * self.backoff_multiplier ** attempt,
                   self.backoff_max_s)
        if self.jitter <= 0:
            return base
        return base * (1.0 - self.jitter * (rng or _rng).random())

    def retryable(self, exc: BaseException) -> bool:
        if isinstance(exc, self.give_up_on):
            return False
        return isinstance(exc, self.retry_on)


#: One attempt, no sleeping — for call sites that gate retrying on a
#: runtime condition (e.g. only idempotent RPC methods retry).
NO_RETRY = RetryPolicy(max_attempts=1)


class Deadline:
    """Overall wall-clock budget shared across attempts AND backoffs.

    ``Deadline(None)`` is unbounded. ``clamp(t)`` shrinks a per-attempt
    timeout to the remaining budget so attempts × timeout can never
    exceed the caller's intended total (the ClusterRpc bug this class
    exists to fix).
    """

    def __init__(self, seconds: Optional[float]):
        self.seconds = seconds
        self._t0 = time.monotonic()

    def remaining(self) -> Optional[float]:
        if self.seconds is None:
            return None
        return self.seconds - (time.monotonic() - self._t0)

    def expired(self) -> bool:
        rem = self.remaining()
        return rem is not None and rem <= 0

    def clamp(self, timeout: Optional[float]) -> Optional[float]:
        rem = self.remaining()
        if rem is None:
            return timeout
        rem = max(rem, 0.0)
        return rem if timeout is None else min(timeout, rem)


class CircuitBreaker:
    """Consecutive-failure circuit: after ``failure_threshold`` failures
    in a row the circuit opens and :func:`call` fails fast with
    ``CircuitOpenError`` (no attempt, no sleep) until ``reset_after_s``
    elapses; the next call then runs as a half-open probe — success
    closes the circuit, failure re-opens it for another window.

    Thread-safe, and the half-open probe is exclusive: granting it
    re-arms the window, so concurrent callers keep failing fast until
    the probe reports back — N handler threads must not all hammer the
    dependency the breaker exists to protect.
    """

    def __init__(self, name: str, failure_threshold: int = 5,
                 reset_after_s: float = 30.0):
        self.name = name
        self.failure_threshold = failure_threshold
        self.reset_after_s = reset_after_s
        self._failures = 0                    # guarded-by: _lock
        self._opened_at: Optional[float] = None  # guarded-by: _lock
        self._lock = threading.Lock()

    def allow(self) -> bool:
        with self._lock:
            if self._opened_at is None:
                return True
            if time.monotonic() - self._opened_at < self.reset_after_s:
                return False
            # Claim the half-open probe: re-arm the window so only THIS
            # caller probes; a success will close the circuit.
            self._opened_at = time.monotonic()
            return True

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._opened_at = None

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            if self._failures >= self.failure_threshold:
                self._opened_at = time.monotonic()


def call(fn: Callable[[], Any], *,
         policy: RetryPolicy = RetryPolicy(),
         name: Optional[str] = None,
         deadline: Optional[Deadline] = None,
         breaker: Optional[CircuitBreaker] = None,
         on_retry: Optional[Callable[[int, BaseException, float],
                                     None]] = None,
         sleep: Callable[[float], None] = time.sleep,
         rng: Optional[random.Random] = None) -> Any:
    """Run ``fn()`` under ``policy``. THE retry loop.

    * Retries only failures ``policy`` classifies retryable; anything
      else re-raises immediately.
    * Never sleeps past ``deadline``: when the remaining budget cannot
      cover the next backoff (or is already spent), the last failure
      re-raises now instead of burning budget asleep — a caller's
      deadline bounds the WHOLE call, not just the attempts.
    * ``on_retry(attempt, exc, backoff_s)`` fires before each backoff
      (telemetry, blocklist resets); ``name`` additionally records
      ``skytpu_retries_total`` and a typed ``retry.backoff`` event so
      traces show every recovery pause.
    * ``breaker``: consult/record a :class:`CircuitBreaker`; an open
      circuit raises ``CircuitOpenError`` without attempting.
    """
    if breaker is not None and not breaker.allow():
        if name:
            RETRIES.labels(name=name, outcome="circuit_open").inc()
        raise CircuitOpenError(
            f"circuit {breaker.name!r} open after "
            f"{breaker.failure_threshold} consecutive failures")
    attempt = 0
    while True:
        if deadline is not None and deadline.expired():
            if name:
                RETRIES.labels(name=name,
                               outcome="deadline_exceeded").inc()
            raise DeadlineExceededError(
                f"deadline ({deadline.seconds}s) expired before attempt "
                f"{attempt + 1}")
        try:
            result = fn()
        except BaseException as e:  # noqa: BLE001 — classified below
            if breaker is not None:
                breaker.record_failure()
            if not policy.retryable(e) or attempt + 1 >= policy.max_attempts:
                if name:
                    RETRIES.labels(name=name, outcome="gave_up").inc()
                raise
            pause = policy.backoff_s(attempt, rng=rng)
            if deadline is not None:
                rem = deadline.remaining()
                if rem is not None and pause >= rem:
                    # Sleeping would eat the rest of the budget: fail
                    # with the real cause now, not a late timeout.
                    if name:
                        RETRIES.labels(name=name,
                                       outcome="deadline_exceeded").inc()
                    raise
            if on_retry is not None:
                on_retry(attempt, e, pause)
            if name:
                RETRIES.labels(name=name, outcome="retried").inc()
                tracing.add_event(
                    "retry.backoff",
                    attrs={"policy": name, "attempt": attempt,
                           "backoff_s": round(pause, 3),
                           "error_type": type(e).__name__,
                           "message": str(e)[:200]})
            if pause > 0:
                sleep(pause)
            attempt += 1
            continue
        if breaker is not None:
            breaker.record_success()
        return result


def pause(policy: RetryPolicy, attempt: int, *,
          sleep: Callable[[float], None] = time.sleep,
          rng: Optional[random.Random] = None) -> float:
    """Sleep one policy backoff and return the pause taken — for loops
    whose retry decision lives elsewhere (e.g. the managed-job monitor,
    where "retry" means a full recovery launch driven by job state, not
    re-calling a function)."""
    t = policy.backoff_s(attempt, rng=rng)
    if t > 0:
        sleep(t)
    return t
