"""Command runners: the single funnel for running commands on cluster
hosts, local or over SSH.

Reference parity: sky/utils/command_runner.py (CommandRunner ABC :165,
SSHCommandRunner :435 with ControlMaster multiplexing). Additions beyond
the reference: ``stdin`` support (the typed cluster RPC sends one JSON
request per call on stdin — no string codegen), and ``FakeSSHRunner``,
which emulates a remote host rooted at a local directory so the entire
remote code path (rsynced framework, $HOME-relative layout, log
mirroring) runs in offline tests.

Stdlib-only: head-side runtime processes import this under ``python -S``.
"""

from __future__ import annotations

import os
import shlex
import subprocess
import sys
from typing import Dict, List, Optional, Tuple

# Parent directory of the skypilot_tpu package on THIS machine — what a
# child python needs on PYTHONPATH to import the framework.
PKG_PARENT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Where instance_setup rsyncs the framework on remote hosts, relative to
# the remote $HOME (reference: the wheel installed by
# sky/backends/wheel_utils.py:140; here it is a plain package dir added
# to PYTHONPATH).
REMOTE_PKG_DIR = ".skypilot_tpu/pkg"

# Default port of the per-host exec agent (runtime/hostd.py).
AGENT_PORT = 8477


class CommandRunner:
    """Runs shell commands on one host."""

    is_local = False

    def __init__(self, host_id: int = 0, ip: str = "127.0.0.1"):
        self.host_id = host_id
        self.ip = ip

    def run(self, cmd: str, env: Optional[Dict[str, str]] = None,
            cwd: Optional[str] = None, timeout: Optional[float] = None,
            log_path: Optional[str] = None,
            stdin: Optional[str] = None) -> Tuple[int, str, str]:
        """Run to completion. Returns (rc, stdout, stderr); when
        ``log_path`` is given, output is tee'd there instead."""
        raise NotImplementedError

    def run_detached(self, cmd: str, env: Optional[Dict[str, str]],
                     cwd: Optional[str], log_path: str) -> int:
        """Start without waiting; returns a PID (new process group so the
        whole tree can be killed for gang-cancel)."""
        raise NotImplementedError

    def rsync(self, src: str, dst: str, up: bool = True,
              excludes: Optional[List[str]] = None) -> None:
        """Copy src -> dst. Directory sources copy their CONTENTS into
        dst (rsync `src/` semantics) on every transport. ``excludes``:
        rsync-style patterns to skip (ignored by fallback copy paths)."""
        raise NotImplementedError

    def kill(self, pid: int) -> None:
        """Terminate the process group started by ``run_detached``."""
        raise NotImplementedError

    def read_file(self, path: str) -> Optional[str]:
        """Contents of a file on the host, or None if absent. Used by the
        gang driver to poll per-host rc files uniformly (local FS read or
        a `cat` over SSH)."""
        raise NotImplementedError

    def framework_invocation(self, module: str) -> str:
        """Shell command that runs ``python -m <module>`` on this host
        with the framework importable and site-packages skipped (-S: the
        runtime layer is stdlib-only, and skipping site avoids paying the
        multi-second jax/TPU-plugin import on every RPC). Default is the
        remote contract (rsynced package under $HOME); LocalRunner
        overrides with the in-tree package."""
        return (f'PYTHONPATH="$HOME/{REMOTE_PKG_DIR}:$PYTHONPATH" '
                f"python3 -S -m {module}")


class LocalRunner(CommandRunner):
    """Executes on the local machine (fake-cloud hosts = directories).

    ``env_overrides`` lets the local provider give each "host" its own
    $HOME (the host directory), so `~`-relative layout behaves per-host
    exactly as on a real multi-VM cluster. A value of None unsets the
    variable.
    """

    is_local = True

    def __init__(self, host_id: int = 0, ip: str = "127.0.0.1",
                 workspace: Optional[str] = None,
                 env_overrides: Optional[Dict[str, Optional[str]]] = None):
        super().__init__(host_id, ip)
        self.workspace = workspace
        self.env_overrides = env_overrides or {}

    def _env(self, env):
        full = dict(os.environ)
        for k, v in self.env_overrides.items():
            if v is None:
                full.pop(k, None)
            else:
                full[k] = v
        if env:
            full.update(env)
        return full

    def _expand(self, path: str) -> str:
        """Resolve a path the way the remote host's shell would: `~` and
        relative paths anchor at the HOST's home (the override dir),
        never at the calling process's cwd."""
        home = self.env_overrides.get("HOME")
        if path == "~" or path.startswith("~/"):
            return (home + path[1:]) if home else os.path.expanduser(path)
        if home and not os.path.isabs(path):
            return os.path.join(home, path)
        return os.path.expanduser(path)

    def run(self, cmd, env=None, cwd=None, timeout=None, log_path=None,
            stdin=None):
        cwd = cwd or self.workspace
        if log_path:
            os.makedirs(os.path.dirname(log_path), exist_ok=True)
            with open(log_path, "ab") as f:
                proc = subprocess.run(
                    ["bash", "-c", cmd], env=self._env(env), cwd=cwd,
                    stdout=f, stderr=subprocess.STDOUT, timeout=timeout,
                    input=stdin.encode() if stdin is not None else None)
            return proc.returncode, "", ""
        proc = subprocess.run(
            ["bash", "-c", cmd], env=self._env(env), cwd=cwd,
            capture_output=True, text=True, timeout=timeout, input=stdin)
        return proc.returncode, proc.stdout, proc.stderr

    def run_detached(self, cmd, env=None, cwd=None, log_path="/dev/null"):
        log_path = self._expand(log_path)
        os.makedirs(os.path.dirname(log_path) or ".", exist_ok=True)
        with open(log_path, "ab") as f:
            proc = subprocess.Popen(
                ["bash", "-c", cmd], env=self._env(env),
                cwd=cwd or self.workspace, stdout=f,
                stderr=subprocess.STDOUT, start_new_session=True)
        return proc.pid

    def read_file(self, path: str) -> Optional[str]:
        try:
            with open(self._expand(path)) as f:
                return f.read()
        except OSError:
            return None

    def kill(self, pid: int) -> None:
        import signal
        try:
            os.killpg(pid, signal.SIGTERM)
        except (ProcessLookupError, PermissionError, OSError):
            try:
                os.kill(pid, signal.SIGTERM)
            except OSError:
                pass

    def rsync(self, src: str, dst: str, up: bool = True,
              excludes: Optional[List[str]] = None) -> None:
        src = self._expand(src)
        dst = self._expand(dst)
        os.makedirs(dst if os.path.isdir(src) else os.path.dirname(dst),
                    exist_ok=True)
        # rsync if available, else cp (keeps the zero-dep property).
        # Both paths copy a directory's *contents* into dst (src/. form).
        excl = " ".join(f"--exclude {shlex.quote(e)}"
                        for e in (excludes or []))
        if os.path.isdir(src):
            copy = (f"command -v rsync >/dev/null && "
                    f"rsync -a {excl} {shlex.quote(src.rstrip('/') + '/')} "
                    f"{shlex.quote(dst)} || "
                    f"cp -r {shlex.quote(os.path.join(src, '.'))} "
                    f"{shlex.quote(dst)}")
        else:
            copy = (f"command -v rsync >/dev/null && "
                    f"rsync -a {shlex.quote(src)} {shlex.quote(dst)} || "
                    f"cp {shlex.quote(src)} {shlex.quote(dst)}")
        rc = subprocess.run(["bash", "-c", copy],
                            capture_output=True).returncode
        if rc != 0:
            raise RuntimeError(f"rsync {src} -> {dst} failed")

    def framework_invocation(self, module: str) -> str:
        return (f"PYTHONPATH={shlex.quote(PKG_PARENT)}:$PYTHONPATH "
                f"{shlex.quote(sys.executable)} -S -m {module}")


class FakeSSHRunner(LocalRunner):
    """A "remote" host rooted at a local directory (its $HOME).

    The client's SKYPILOT_TPU_HOME and PYTHONPATH are scrubbed from the
    environment, so anything that works through this runner provably
    works through the rsynced-package + $HOME-relative layout — the same
    contract a real SSH host gets. Test seam for the on-cluster runtime
    (reference analog: the codegen-boundary mocks at
    tests/common_test_fixtures.py:203-227, made executable).
    """

    is_local = False

    def __init__(self, root: str, host_id: int = 0, ip: str = "127.0.0.1"):
        os.makedirs(root, exist_ok=True)
        super().__init__(
            host_id, ip, workspace=root,
            env_overrides={
                "HOME": root,
                "SKYPILOT_TPU_HOME": None,
                "PYTHONPATH": None,
                # remote "python3" resolves to this interpreter
                "PATH": (os.path.dirname(sys.executable) + os.pathsep +
                         os.environ.get("PATH", "")),
            })
        self.root = root

    framework_invocation = CommandRunner.framework_invocation


class TcpAgentRunner(CommandRunner):
    """Reaches a host through its runtime/hostd.py agent (line-delimited
    JSON over TCP). The gang driver's transport on kubernetes pods,
    where there is no sshd — same CommandRunner contract, so the driver
    code path is identical to SSH clusters."""

    def __init__(self, ip: str, port: int, token: str, host_id: int = 0,
                 connect_timeout: float = 10.0):
        super().__init__(host_id, ip)
        self.port = port
        self.token = token
        self.connect_timeout = connect_timeout
        self._sock = None  # persistent connection (hostd loops per line)

    def _connect(self):
        import socket
        self._sock = socket.create_connection(
            (self.ip, self.port), timeout=self.connect_timeout)
        return self._sock

    def _exchange(self, payload: bytes, timeout) -> bytes:
        s = self._sock or self._connect()
        # None = block until the agent answers (the CommandRunner
        # contract: timeout=None runs to completion).
        s.settimeout(timeout + 10 if timeout else None)
        s.sendall(payload)
        buf = b""
        while not buf.endswith(b"\n"):
            chunk = s.recv(65536)
            if not chunk:
                raise ConnectionError("agent closed connection")
            buf += chunk
        return buf

    def _call(self, req: Dict, timeout: Optional[float] = None) -> Dict:
        import json
        payload = (json.dumps(dict(req, token=self.token)) + "\n").encode()
        try:
            buf = self._exchange(payload, timeout)
        except (OSError, ConnectionError):
            # Stale persistent socket (agent restart, idle teardown):
            # one fresh-connection retry.
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None
            buf = self._exchange(payload, timeout)
        resp = json.loads(buf or b"{}")
        if not resp.get("ok"):
            raise RuntimeError(
                f"host agent {self.ip}:{self.port} error: "
                f"{resp.get('error')}")
        return resp

    def _agent_protocol(self) -> int:
        """Protocol version of the live agent (probed once). Agents
        predating the version field are v1."""
        if getattr(self, "_protocol", None) is None:
            self._protocol = int(
                self._call({"op": "ping"}).get("protocol", 1))
        return self._protocol

    def run(self, cmd, env=None, cwd=None, timeout=None, log_path=None,
            stdin=None):
        if stdin is not None and self._agent_protocol() < 2:
            # v1 agents (still running from before a client upgrade)
            # don't know the "stdin" field. Base64 keeps the payload
            # data-safe inside the shell line (a raw heredoc would let
            # stdin content execute as shell).
            import base64
            b64 = base64.b64encode(stdin.encode()).decode()
            cmd = f"printf %s {b64} | base64 -d | {{ {cmd} ; }}"
            stdin = None
        resp = self._call({"op": "run", "cmd": cmd, "env": env,
                           "cwd": cwd, "timeout": timeout,
                           "stdin": stdin},
                          timeout=timeout)
        if log_path:
            os.makedirs(os.path.dirname(log_path), exist_ok=True)
            with open(log_path, "ab") as f:
                f.write((resp["out"] + resp["err"]).encode())
            return resp["rc"], "", ""
        return resp["rc"], resp["out"], resp["err"]

    def run_detached(self, cmd, env=None, cwd=None, log_path="/dev/null"):
        return self._call({"op": "run_detached", "cmd": cmd, "env": env,
                           "cwd": cwd, "log_path": log_path})["pid"]

    def read_file(self, path: str) -> Optional[str]:
        return self._call({"op": "read_file", "path": path})["content"]

    def kill(self, pid: int) -> None:
        self._call({"op": "kill", "pid": pid})

    def rsync(self, src, dst, up=True, excludes=None):
        raise NotImplementedError(
            "TcpAgentRunner is an exec transport; file sync to pods goes "
            "through the kubernetes runner (tar-over-exec)")


class SSHRunner(CommandRunner):
    """SSH with ControlMaster multiplexing (one handshake per host)."""

    def __init__(self, ip: str, user: str, key_path: str, host_id: int = 0,
                 port: int = 22, proxy_command: Optional[str] = None):
        super().__init__(host_id, ip)
        self.user = user
        self.key_path = key_path
        self.port = port
        self.proxy_command = proxy_command

    def _ssh_base(self) -> List[str]:
        ctrl = os.path.expanduser("~/.skypilot_tpu/ssh_control")
        os.makedirs(ctrl, exist_ok=True)
        base = [
            "ssh", "-i", os.path.expanduser(self.key_path),
            "-o", "StrictHostKeyChecking=no",
            "-o", "UserKnownHostsFile=/dev/null",
            "-o", "IdentitiesOnly=yes",
            "-o", "ConnectTimeout=30",
            "-o", f"ControlPath={ctrl}/%C",
            "-o", "ControlMaster=auto",
            "-o", "ControlPersist=120s",
            "-p", str(self.port),
        ]
        if self.proxy_command:
            base += ["-o", f"ProxyCommand={self.proxy_command}"]
        return base + [f"{self.user}@{self.ip}"]

    def run(self, cmd, env=None, cwd=None, timeout=None, log_path=None,
            stdin=None):
        env_prefix = "".join(
            f"export {k}={shlex.quote(v)}; " for k, v in (env or {}).items())
        cd = f"cd {shlex.quote(cwd)} && " if cwd else ""
        full = self._ssh_base() + [f"{env_prefix}{cd}{cmd}"]
        if log_path:
            os.makedirs(os.path.dirname(log_path), exist_ok=True)
            with open(log_path, "ab") as f:
                proc = subprocess.run(
                    full, stdout=f, stderr=subprocess.STDOUT, timeout=timeout,
                    input=stdin.encode() if stdin is not None else None)
            return proc.returncode, "", ""
        proc = subprocess.run(full, capture_output=True, text=True,
                              timeout=timeout, input=stdin)
        return proc.returncode, proc.stdout, proc.stderr

    def run_detached(self, cmd, env=None, cwd=None, log_path="/dev/null"):
        env_prefix = "".join(
            f"export {k}={shlex.quote(v)}; " for k, v in (env or {}).items())
        cd = f"cd {shlex.quote(cwd)} && " if cwd else ""
        # setsid makes the remote bash a process-group leader so kill()
        # can take down the whole tree (children included); nohup alone
        # leaves children orphaned on cancel.
        remote = (f"nohup setsid bash -c {shlex.quote(env_prefix + cd + cmd)} "
                  f">> {shlex.quote(log_path)} 2>&1 & echo $!")
        rc, out, err = LocalRunner().run(
            " ".join(shlex.quote(a) for a in self._ssh_base())
            + " " + shlex.quote(f"mkdir -p $(dirname {shlex.quote(log_path)}); {remote}"))
        if rc != 0:
            raise RuntimeError(f"ssh detach failed: {err}")
        return int(out.strip().splitlines()[-1])

    def read_file(self, path: str) -> Optional[str]:
        # `~` must expand host-side; shlex.quote would make it literal.
        quoted = ('"$HOME"' + shlex.quote(path[1:])
                  if path.startswith("~") else shlex.quote(path))
        rc, out, _ = self.run(f"cat {quoted} 2>/dev/null")
        return out if rc == 0 else None

    def kill(self, pid: int) -> None:
        # Kill the remote process group (run_detached used setsid).
        self.run(f"kill -TERM -- -{pid} 2>/dev/null || "
                 f"kill -TERM {pid} 2>/dev/null || true")

    def rsync(self, src: str, dst: str, up: bool = True,
              excludes: Optional[List[str]] = None) -> None:
        if up and os.path.isdir(os.path.expanduser(src)):
            # Contents-into-dst contract (matches LocalRunner.rsync).
            src = src.rstrip("/") + "/"
        ssh_cmd = " ".join(self._ssh_base()[:-1])
        remote = f"{self.user}@{self.ip}"
        pair = ([src, f"{remote}:{dst}"] if up else [f"{remote}:{src}", dst])
        excl = [a for e in (excludes or []) for a in ("--exclude", e)]
        proc = subprocess.run(
            ["rsync", "-az", *excl, "-e", ssh_cmd, "--mkpath", *pair],
            capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(f"rsync failed: {proc.stderr}")
