"""Command runners: the single funnel for running commands on cluster
hosts, local or over SSH.

Reference parity: sky/utils/command_runner.py (CommandRunner ABC :165,
SSHCommandRunner :435 with ControlMaster multiplexing). The local runner
doubles as the fake-cloud execution path so the whole stack is testable
on one machine.
"""

from __future__ import annotations

import os
import shlex
import subprocess
import sys
from typing import Dict, List, Optional, Tuple


class CommandRunner:
    """Runs shell commands on one host."""

    def __init__(self, host_id: int = 0, ip: str = "127.0.0.1"):
        self.host_id = host_id
        self.ip = ip

    def run(self, cmd: str, env: Optional[Dict[str, str]] = None,
            cwd: Optional[str] = None, timeout: Optional[float] = None,
            log_path: Optional[str] = None) -> Tuple[int, str, str]:
        """Run to completion. Returns (rc, stdout, stderr); when
        ``log_path`` is given, output is tee'd there instead."""
        raise NotImplementedError

    def run_detached(self, cmd: str, env: Optional[Dict[str, str]],
                     cwd: Optional[str], log_path: str) -> int:
        """Start without waiting; returns a PID (new process group so the
        whole tree can be killed for gang-cancel)."""
        raise NotImplementedError

    def rsync(self, src: str, dst: str, up: bool = True,
              excludes: Optional[List[str]] = None) -> None:
        """Copy src -> dst. ``excludes``: rsync-style patterns to skip
        (ignored by fallback copy paths)."""
        raise NotImplementedError

    def kill(self, pid: int) -> None:
        """Terminate the process group started by ``run_detached``."""
        raise NotImplementedError

    def read_file(self, path: str) -> Optional[str]:
        """Contents of a file on the host, or None if absent. Used by the
        gang driver to poll per-host rc files uniformly (local FS read or
        a `cat` over SSH)."""
        raise NotImplementedError

    @property
    def is_local(self) -> bool:
        return isinstance(self, LocalRunner)


class LocalRunner(CommandRunner):
    """Executes on the local machine (fake-cloud hosts = directories)."""

    def __init__(self, host_id: int = 0, ip: str = "127.0.0.1",
                 workspace: Optional[str] = None):
        super().__init__(host_id, ip)
        self.workspace = workspace

    def _env(self, env):
        full = dict(os.environ)
        if env:
            full.update(env)
        return full

    def run(self, cmd, env=None, cwd=None, timeout=None, log_path=None):
        cwd = cwd or self.workspace
        if log_path:
            os.makedirs(os.path.dirname(log_path), exist_ok=True)
            with open(log_path, "ab") as f:
                proc = subprocess.run(
                    ["bash", "-c", cmd], env=self._env(env), cwd=cwd,
                    stdout=f, stderr=subprocess.STDOUT, timeout=timeout)
            return proc.returncode, "", ""
        proc = subprocess.run(
            ["bash", "-c", cmd], env=self._env(env), cwd=cwd,
            capture_output=True, text=True, timeout=timeout)
        return proc.returncode, proc.stdout, proc.stderr

    def run_detached(self, cmd, env=None, cwd=None, log_path="/dev/null"):
        os.makedirs(os.path.dirname(log_path) or ".", exist_ok=True)
        with open(log_path, "ab") as f:
            proc = subprocess.Popen(
                ["bash", "-c", cmd], env=self._env(env),
                cwd=cwd or self.workspace, stdout=f,
                stderr=subprocess.STDOUT, start_new_session=True)
        return proc.pid

    def read_file(self, path: str) -> Optional[str]:
        try:
            with open(os.path.expanduser(path)) as f:
                return f.read()
        except OSError:
            return None

    def kill(self, pid: int) -> None:
        import signal
        try:
            os.killpg(pid, signal.SIGTERM)
        except (ProcessLookupError, PermissionError, OSError):
            try:
                os.kill(pid, signal.SIGTERM)
            except OSError:
                pass

    def rsync(self, src: str, dst: str, up: bool = True,
              excludes: Optional[List[str]] = None) -> None:
        src = os.path.expanduser(src)
        dst = os.path.expanduser(dst)
        os.makedirs(dst if os.path.isdir(src) else os.path.dirname(dst),
                    exist_ok=True)
        # rsync if available, else cp (keeps the zero-dep property).
        # Both paths copy a directory's *contents* into dst (src/. form).
        excl = " ".join(f"--exclude {shlex.quote(e)}"
                        for e in (excludes or []))
        if os.path.isdir(src):
            copy = (f"command -v rsync >/dev/null && "
                    f"rsync -a {excl} {shlex.quote(src.rstrip('/') + '/')} "
                    f"{shlex.quote(dst)} || "
                    f"cp -r {shlex.quote(os.path.join(src, '.'))} "
                    f"{shlex.quote(dst)}")
        else:
            copy = (f"command -v rsync >/dev/null && "
                    f"rsync -a {shlex.quote(src)} {shlex.quote(dst)} || "
                    f"cp {shlex.quote(src)} {shlex.quote(dst)}")
        rc = subprocess.run(["bash", "-c", copy],
                            capture_output=True).returncode
        if rc != 0:
            raise RuntimeError(f"rsync {src} -> {dst} failed")


class SSHRunner(CommandRunner):
    """SSH with ControlMaster multiplexing (one handshake per host)."""

    def __init__(self, ip: str, user: str, key_path: str, host_id: int = 0,
                 port: int = 22, proxy_command: Optional[str] = None):
        super().__init__(host_id, ip)
        self.user = user
        self.key_path = key_path
        self.port = port
        self.proxy_command = proxy_command

    def _ssh_base(self) -> List[str]:
        ctrl = os.path.expanduser("~/.skypilot_tpu/ssh_control")
        os.makedirs(ctrl, exist_ok=True)
        base = [
            "ssh", "-i", os.path.expanduser(self.key_path),
            "-o", "StrictHostKeyChecking=no",
            "-o", "UserKnownHostsFile=/dev/null",
            "-o", "IdentitiesOnly=yes",
            "-o", "ConnectTimeout=30",
            "-o", f"ControlPath={ctrl}/%C",
            "-o", "ControlMaster=auto",
            "-o", "ControlPersist=120s",
            "-p", str(self.port),
        ]
        if self.proxy_command:
            base += ["-o", f"ProxyCommand={self.proxy_command}"]
        return base + [f"{self.user}@{self.ip}"]

    def run(self, cmd, env=None, cwd=None, timeout=None, log_path=None):
        env_prefix = "".join(
            f"export {k}={shlex.quote(v)}; " for k, v in (env or {}).items())
        cd = f"cd {shlex.quote(cwd)} && " if cwd else ""
        full = self._ssh_base() + [f"{env_prefix}{cd}{cmd}"]
        if log_path:
            os.makedirs(os.path.dirname(log_path), exist_ok=True)
            with open(log_path, "ab") as f:
                proc = subprocess.run(full, stdout=f,
                                      stderr=subprocess.STDOUT,
                                      timeout=timeout)
            return proc.returncode, "", ""
        proc = subprocess.run(full, capture_output=True, text=True,
                              timeout=timeout)
        return proc.returncode, proc.stdout, proc.stderr

    def run_detached(self, cmd, env=None, cwd=None, log_path="/dev/null"):
        env_prefix = "".join(
            f"export {k}={shlex.quote(v)}; " for k, v in (env or {}).items())
        cd = f"cd {shlex.quote(cwd)} && " if cwd else ""
        # setsid makes the remote bash a process-group leader so kill()
        # can take down the whole tree (children included); nohup alone
        # leaves children orphaned on cancel.
        remote = (f"nohup setsid bash -c {shlex.quote(env_prefix + cd + cmd)} "
                  f">> {shlex.quote(log_path)} 2>&1 & echo $!")
        rc, out, err = LocalRunner().run(
            " ".join(shlex.quote(a) for a in self._ssh_base())
            + " " + shlex.quote(f"mkdir -p $(dirname {shlex.quote(log_path)}); {remote}"))
        if rc != 0:
            raise RuntimeError(f"ssh detach failed: {err}")
        return int(out.strip().splitlines()[-1])

    def read_file(self, path: str) -> Optional[str]:
        rc, out, _ = self.run(f"cat {shlex.quote(path)} 2>/dev/null")
        return out if rc == 0 else None

    def kill(self, pid: int) -> None:
        # Kill the remote process group (run_detached used setsid).
        self.run(f"kill -TERM -- -{pid} 2>/dev/null || "
                 f"kill -TERM {pid} 2>/dev/null || true")

    def rsync(self, src: str, dst: str, up: bool = True,
              excludes: Optional[List[str]] = None) -> None:
        ssh_cmd = " ".join(self._ssh_base()[:-1])
        remote = f"{self.user}@{self.ip}"
        pair = ([src, f"{remote}:{dst}"] if up else [f"{remote}:{src}", dst])
        excl = [a for e in (excludes or []) for a in ("--exclude", e)]
        proc = subprocess.run(
            ["rsync", "-az", *excl, "-e", ssh_cmd, "--mkpath", *pair],
            capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(f"rsync failed: {proc.stderr}")
