"""Shared sqlite connection discipline.

WAL journaling (readers never block the single writer — controllers and
RPC handlers share these DBs concurrently) + a busy handler matched to
the caller's timeout. One helper so tuning changes hit every DB at once.
Stdlib-only: imported by head-side runtime modules under ``python -S``.
"""

from __future__ import annotations

import sqlite3


def connect(path: str, timeout: float = 10) -> sqlite3.Connection:
    conn = sqlite3.connect(path, timeout=timeout)
    conn.execute("PRAGMA journal_mode=WAL")
    conn.execute(f"PRAGMA busy_timeout={int(timeout * 1000)}")
    return conn
