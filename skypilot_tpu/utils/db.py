"""Shared sqlite connection discipline + schema versioning.

WAL journaling (readers never block the single writer — controllers and
RPC handlers share these DBs concurrently) + a busy handler matched to
the caller's timeout. One helper so tuning changes hit every DB at once.
Stdlib-only: imported by head-side runtime modules under ``python -S``.

Schema versioning (reference analog:
tests/backward_compatibility_tests.sh — new client code meeting an old
``~/.skypilot_tpu`` state dir must upgrade it or fail LOUDLY, never
misread it): every DB stamps ``PRAGMA user_version``. ``open_versioned``
creates fresh DBs at the current version, runs registered migrations on
older ones (in order, committed per step), and refuses DBs written by a
NEWER client.
"""

from __future__ import annotations

import collections
import os
import sqlite3
import threading
from typing import Callable, Dict, Union

Migration = Union[str, Callable[[sqlite3.Connection], None]]


class SchemaVersionError(RuntimeError):
    """DB schema can't be used: newer than this client, or a migration
    step is missing."""


# WAL keepers: one idle connection per DB path, held for the life of
# the process. Every caller here opens a connection per operation (the
# multi-process-safe discipline), but in WAL mode the LAST connection
# to close runs a full checkpoint + fsync — so connection-per-op turns
# every state write into a checkpoint, ~10x the cost on slow disks.
# With a keeper holding the DB open, per-op connections are never the
# last one; checkpoints amortize over the WAL's auto-checkpoint
# threshold instead. The keeper holds no transaction (it never reads
# after the opening pragma), so it blocks neither writers nor
# checkpointers. Bounded LRU: a process touches a handful of DBs; test
# suites churn through tmp homes and must not leak fds.
_MAX_KEEPERS = 8
_keeper_lock = threading.Lock()
# guarded-by: _keeper_lock
_keepers: "collections.OrderedDict[str, sqlite3.Connection]" = \
    collections.OrderedDict()


def _ensure_keeper(path: str) -> None:
    key = os.path.abspath(path)
    with _keeper_lock:
        if key in _keepers:
            _keepers.move_to_end(key)
            return
        try:
            keeper = sqlite3.connect(path, timeout=1,
                                     check_same_thread=False)
            keeper.execute("PRAGMA journal_mode=WAL")
        except sqlite3.Error:
            return                 # best-effort: never fail a caller
        _keepers[key] = keeper
        while len(_keepers) > _MAX_KEEPERS:
            _, evicted = _keepers.popitem(last=False)
            try:
                evicted.close()
            except sqlite3.Error:
                pass


def connect(path: str, timeout: float = 10) -> sqlite3.Connection:
    conn = sqlite3.connect(path, timeout=timeout)
    conn.execute("PRAGMA journal_mode=WAL")
    # WAL's recommended durability level: commits append to the WAL
    # without an fsync each (checkpoints still sync), which is the
    # difference between ~2ms and ~50ms per write transaction on slow
    # disks — these DBs take one commit per job/request state change.
    # Consistency is unaffected (a crash never corrupts); only an OS/
    # power loss can drop the last commits, and every writer here
    # re-derives state from the cluster/provider on restart anyway.
    conn.execute("PRAGMA synchronous=NORMAL")
    conn.execute(f"PRAGMA busy_timeout={int(timeout * 1000)}")
    _ensure_keeper(path)
    return conn


def open_versioned(path: str, schema: str, version: int,
                   migrations: Dict[int, Migration] | None = None,
                   timeout: float = 10) -> sqlite3.Connection:
    """Connect + create-or-migrate.

    ``schema`` is the CURRENT-version DDL (executed only on fresh DBs).
    ``migrations[v]`` upgrades v-1 -> v (SQL script or callable); a DB
    at an older version replays them in order. DBs created before
    versioning existed (user_version 0 but tables present) count as
    version 1. A DB stamped NEWER than ``version`` raises
    SchemaVersionError — old code must never scribble on a new schema.
    """
    conn = connect(path, timeout=timeout)
    try:
        cur = conn.execute("PRAGMA user_version").fetchone()[0]
        if cur == version:
            return conn           # fast path: no write lock taken
        # Creation/migration runs under ONE exclusive transaction
        # (BEGIN IMMEDIATE; concurrent openers block on busy_timeout
        # then re-read the version). Without it, a second connection
        # can observe a mid-creation DB — tables present, version not
        # yet stamped — misread it as "pre-versioning v1" and re-run
        # migrations into a duplicate-column error. Not executescript:
        # that helper force-commits first, which would break the
        # atomicity this exists for. PRAGMA user_version is part of
        # the DB header and IS transactional.
        conn.execute("BEGIN IMMEDIATE")
        try:
            cur = conn.execute("PRAGMA user_version").fetchone()[0]
            if cur == 0:
                tables = conn.execute(
                    "SELECT count(*) FROM sqlite_master"
                    " WHERE type='table'").fetchone()[0]
                if tables == 0:
                    for stmt in schema.split(";"):
                        if stmt.strip():
                            conn.execute(stmt)
                    conn.execute(f"PRAGMA user_version={int(version)}")
                    conn.commit()
                    return conn
                cur = 1           # pre-versioning DB
            if cur > version:
                raise SchemaVersionError(
                    f"{path} is schema v{cur}, but this client only "
                    f"knows v{version} — upgrade the client (refusing "
                    "to touch a newer on-disk state)")
            for v in range(cur + 1, version + 1):
                step = (migrations or {}).get(v)
                if step is None:
                    raise SchemaVersionError(
                        f"{path} is schema v{cur} and no migration to "
                        f"v{v} is registered")
                if callable(step):
                    step(conn)    # must not commit mid-step
                else:
                    for stmt in step.split(";"):
                        if stmt.strip():
                            conn.execute(stmt)
                conn.execute(f"PRAGMA user_version={v}")
            conn.commit()
        except BaseException:
            conn.rollback()
            raise
        return conn
    except BaseException:
        conn.close()
        raise
