"""Filesystem layout. Everything lives under $SKYPILOT_TPU_HOME
(default ~/.skypilot_tpu), overridable so tests run in tmp dirs."""

from __future__ import annotations

import os


def home() -> str:
    root = os.environ.get("SKYPILOT_TPU_HOME",
                          os.path.expanduser("~/.skypilot_tpu"))
    os.makedirs(root, exist_ok=True)
    return root


def state_db() -> str:
    return os.path.join(home(), "state.db")


def cluster_dir(cluster_name: str) -> str:
    d = os.path.join(home(), "clusters", cluster_name)
    os.makedirs(d, exist_ok=True)
    return d


def logs_dir() -> str:
    d = os.path.join(home(), "logs")
    os.makedirs(d, exist_ok=True)
    return d


def requests_db() -> str:
    return os.path.join(home(), "requests.db")
