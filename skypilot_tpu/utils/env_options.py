"""Boolean env-var flags.

Reference parity: sky/utils/env_options.py (SKYPILOT_DEBUG,
SKYPILOT_DISABLE_USAGE_COLLECTION, SKYPILOT_MINIMIZE_LOGGING).
"""

from __future__ import annotations

import enum
import os


class Options(enum.Enum):
    IS_DEVELOPER = "SKYPILOT_TPU_DEV"
    SHOW_DEBUG_INFO = "SKYPILOT_TPU_DEBUG"
    DISABLE_USAGE_COLLECTION = "SKYPILOT_TPU_DISABLE_USAGE_COLLECTION"
    MINIMIZE_LOGGING = "SKYPILOT_TPU_MINIMIZE_LOGGING"

    def get(self) -> bool:
        return os.environ.get(self.value, "0").lower() in ("1", "true", "yes")

    def __bool__(self) -> bool:
        return self.get()
