"""JSON schemas for task YAML / config validation.

Reference parity: sky/utils/schemas.py (985 LoC of jsonschema dicts —
the task-YAML spec lives there and is enforced at Task.from_yaml_config
time). Scope here is the TPU-native surface: task, resources, service,
and global config.
"""

from __future__ import annotations

from typing import Any, Dict

import jsonschema

from skypilot_tpu import exceptions

_RESOURCES_SCHEMA: Dict[str, Any] = {
    "type": "object",
    "additionalProperties": False,
    "properties": {
        "cloud": {"type": ["string", "null"]},
        "region": {"type": ["string", "null"]},
        "zone": {"type": ["string", "null"]},
        "accelerators": {"type": ["string", "object", "null"]},
        "runtime_version": {"type": ["string", "null"]},
        "accelerator_args": {"type": ["object", "null"]},
        "job_recovery": {"type": ["string", "object", "null"]},
        "cpus": {"type": ["string", "number", "null"]},
        "memory": {"type": ["string", "number", "null"]},
        "instance_type": {"type": ["string", "null"]},
        "use_spot": {"type": "boolean"},
        "disk_size": {"type": ["integer", "null"]},
        "ports": {"type": ["array", "integer", "string", "null"],
                  "items": {"type": ["integer", "string"]}},
        "labels": {"type": ["object", "null"]},
        "image_id": {"type": ["string", "null"]},
        "any_of": {"type": "array"},
    },
}

_SERVICE_SCHEMA: Dict[str, Any] = {
    "type": "object",
    "additionalProperties": False,
    "properties": {
        "readiness_probe": {
            "anyOf": [
                {"type": "string"},
                {
                    "type": "object",
                    "additionalProperties": False,
                    "properties": {
                        "path": {"type": "string"},
                        "initial_delay_seconds": {"type": "number"},
                        "timeout_seconds": {"type": "number"},
                        "post_data": {"type": ["object", "string"]},
                    },
                },
            ],
        },
        "replica_policy": {
            "type": "object",
            "additionalProperties": False,
            "properties": {
                "min_replicas": {"type": "integer"},
                "max_replicas": {"type": ["integer", "null"]},
                "target_qps_per_replica": {"type": ["number", "null"]},
                "target_ttft_p95_seconds": {"type": ["number", "null"]},
                "upscale_delay_seconds": {"type": "number"},
                "downscale_delay_seconds": {"type": "number"},
                "base_ondemand_fallback_replicas": {"type": "integer"},
                "dynamic_ondemand_fallback": {"type": "boolean"},
            },
        },
        "replicas": {"type": "integer"},
        "port": {"type": "integer"},
        "load_balancing_policy": {"type": "string"},
        "tls": {
            "type": "object",
            "additionalProperties": False,
            "properties": {
                "keyfile": {"type": "string"},
                "certfile": {"type": "string"},
            },
        },
    },
}

TASK_SCHEMA: Dict[str, Any] = {
    "$schema": "http://json-schema.org/draft-07/schema#",
    "type": "object",
    "additionalProperties": False,
    "properties": {
        "name": {"type": ["string", "null"]},
        "workdir": {"type": ["string", "null"]},
        "num_nodes": {"type": ["integer", "null"], "minimum": 1},
        "estimated_runtime_seconds": {"type": ["number", "null"],
                                      "exclusiveMinimum": 0},
        "estimated_outputs_gb": {"type": ["number", "null"],
                                 "minimum": 0},
        "setup": {"type": ["string", "null"]},
        "run": {"type": ["string", "null"]},
        "envs": {
            "type": ["object", "null"],
            "patternProperties": {
                "^[A-Za-z_][A-Za-z0-9_]*$": {
                    "type": ["string", "number", "boolean", "null"]},
            },
            "additionalProperties": False,
        },
        "file_mounts": {"type": ["object", "null"]},
        "storage_mounts": {"type": ["object", "null"]},
        "resources": {
            "anyOf": [
                _RESOURCES_SCHEMA,
                {"type": "array", "items": _RESOURCES_SCHEMA},
                {"type": "null"},
            ],
        },
        "service": _SERVICE_SCHEMA,
        "config_overrides": {"type": "object"},
    },
}

CONFIG_SCHEMA: Dict[str, Any] = {
    "$schema": "http://json-schema.org/draft-07/schema#",
    "type": "object",
    "additionalProperties": True,
    "properties": {
        "admin_policy": {"type": "string"},
        "gcp": {
            "type": "object",
            "properties": {
                "project": {"type": "string"},
                "specific_reservations": {"type": "array",
                                          "items": {"type": "string"}},
                "use_reserved_tpu_capacity": {"type": "boolean"},
            },
        },
        "provisioner": {
            "type": "object",
            "properties": {
                "ssh_timeout": {"type": "number"},
            },
        },
        "jobs": {"type": "object"},
        "serve": {"type": "object"},
        "usage": {
            "type": "object",
            "properties": {"disabled": {"type": "boolean"}},
        },
    },
}


def _validate(config: Dict[str, Any], schema: Dict[str, Any],
              what: str) -> None:
    try:
        jsonschema.validate(instance=config, schema=schema)
    except jsonschema.ValidationError as e:
        path = ".".join(str(p) for p in e.absolute_path) or "<root>"
        raise exceptions.InvalidTaskError(
            f"invalid {what}: {path}: {e.message}") from None


def validate_task_config(config: Dict[str, Any]) -> None:
    _validate(config, TASK_SCHEMA, "task YAML")


def validate_global_config(config: Dict[str, Any]) -> None:
    _validate(config, CONFIG_SCHEMA, "config.yaml")
