"""Simple name->class registries (reference: sky/utils/registry.py:16)."""

from __future__ import annotations

from typing import Dict, Generic, Optional, TypeVar

T = TypeVar("T")


class Registry(Generic[T]):
    def __init__(self, name: str):
        self.name = name
        self._items: Dict[str, T] = {}

    def register(self, key: str, value: T) -> T:
        self._items[key.upper()] = value
        return value

    def get(self, key: str) -> Optional[T]:
        return self._items.get(key.upper())

    def __iter__(self):
        return iter(self._items)

    def __contains__(self, key: str) -> bool:
        return key.upper() in self._items


JOBS_RECOVERY_STRATEGY_REGISTRY: Registry = Registry("jobs_recovery_strategy")
