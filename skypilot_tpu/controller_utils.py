"""Controller-as-task plumbing: managed-jobs and serve controllers run
as processes ON a controller cluster launched through the framework's
own stack — the reference's recursion (sky/utils/controller_utils.py:87
controller registry; jobs-controller.yaml.j2 / sky-serve-controller
templates), minus the templates: the controller cluster is provisioned
by execution.launch and controller processes are spawned by the typed
cluster RPC.

Consequences (the properties VERDICT r1 #2/#3 demanded): controllers
survive the submitting client, are shared between clients, and the
serve load balancer binds on the controller cluster head — the service
endpoint is the head's address, not a client loopback.
"""

from __future__ import annotations

import os
from typing import Optional

from skypilot_tpu import config as config_lib
from skypilot_tpu import exceptions, state
from skypilot_tpu.backend import ClusterHandle, TpuVmBackend
from skypilot_tpu.runtime.rpc_client import ClusterRpc
from skypilot_tpu.task import Task

JOBS_CONTROLLER_CLUSTER = "sky-jobs-controller"
SERVE_CONTROLLER_CLUSTER = "sky-serve-controller"

# Default VM for controller clusters on real clouds (reference:
# controller_utils.get_controller_resources:443 — small CPU VM).
_DEFAULT_CONTROLLER_VM = {"cloud": "gcp", "instance_type": "n2-standard-4"}


def controller_resources_config(task: Task, kind: str) -> dict:
    """Resources for the controller cluster. Order: explicit config
    (``jobs.controller_resources`` / ``serve.controller_resources``) >
    same-cloud-as-task default (local tasks get a local controller;
    cloud tasks get a small CPU VM)."""
    cfg = config_lib.get_nested((kind, "controller_resources"))
    if cfg:
        return dict(cfg)
    for r in task.resources:
        if r.cloud == "local":
            return {"cloud": "local"}
        if r.cloud == "kubernetes":
            return {"cloud": "kubernetes"}
    return dict(_DEFAULT_CONTROLLER_VM)


def ensure_controller_cluster(cluster_name: str, task: Task,
                              kind: str) -> ClusterHandle:
    """Provision (or reuse) the controller cluster via the framework's
    own launch path. Idempotent: an UP cluster is returned as-is."""
    from skypilot_tpu import provision
    from skypilot_tpu.resources import Resources
    backend = TpuVmBackend()
    rec = state.get_cluster(cluster_name)
    if rec is not None and rec["status"] == state.ClusterStatus.UP:
        return ClusterHandle(rec["handle"])
    cfg = controller_resources_config(task, kind)
    provider = cfg.get("cloud") or "gcp"
    if not provision.supports(provider,
                              provision.Feature.HOST_CONTROLLERS):
        raise exceptions.NotSupportedError(
            f"{provider} cannot host {kind} controllers "
            f"(Feature.HOST_CONTROLLERS); set "
            f"{kind}.controller_resources in config")
    ctrl_task = Task(name=f"{kind}-controller", run=None)
    ctrl_task.set_resources(Resources.from_yaml_config(cfg))
    return backend.provision(ctrl_task, cluster_name)


def controller_rpc(handle: ClusterHandle) -> ClusterRpc:
    return TpuVmBackend()._rpc(handle)


def controller_endpoint_host(handle: ClusterHandle) -> str:
    """The address clients (and end users, for serve) reach the
    controller cluster head on."""
    from skypilot_tpu import provision
    info = provision.get_cluster_info(handle.provider, handle.cluster_name,
                                      handle.zone)
    return info.head.external_ip or info.head.internal_ip


def _owner_suffix() -> str:
    """A stable per-owner suffix: GCS bucket names are GLOBALLY unique,
    so a fixed name would collide across every deployment worldwide."""
    import getpass
    import hashlib
    import socket
    try:
        from skypilot_tpu.provision import gcp_auth
        seed = gcp_auth.get_project() or ""
    except Exception:  # noqa: BLE001 — any auth failure: fall through
        seed = ""
    if not seed:
        seed = f"{getpass.getuser()}@{socket.gethostname()}"
    return hashlib.sha1(seed.encode()).hexdigest()[:8]


def get_or_create_controller(cluster_name: str, kind: str,
                             missing_exc: type,
                             create_for: Optional[Task] = None
                             ) -> ClusterHandle:
    """Shared jobs/serve lookup: return the controller cluster handle,
    provisioning it when ``create_for`` is given, else raising
    ``missing_exc`` if it does not exist."""
    if create_for is not None:
        return ensure_controller_cluster(cluster_name, create_for, kind)
    rec = state.get_cluster(cluster_name)
    if rec is None:
        raise missing_exc(
            f"no {kind} controller cluster; launch through `{kind}` "
            f"first")
    return ClusterHandle(rec["handle"])


def translate_local_file_mounts(task: Task, handle: ClusterHandle) -> Task:
    """Make client-local file sources reachable from the controller
    cluster (reference: maybe_translate_local_file_mounts_and_sync_up,
    controller_utils.py:696 — local files -> bucket).

    Local-provider controller clusters share the client filesystem, so
    translation is a no-op there. For cloud controllers, local workdir/
    file_mounts are uploaded to a GCS bucket and the task is rewritten
    to gs:// sources."""
    from skypilot_tpu.data import cloud_stores
    needs_translation = bool(task.workdir) or any(
        not src.startswith(cloud_stores.REMOTE_URL_PREFIXES)
        for src in (task.file_mounts or {}).values())
    if handle.provider == "local" or not needs_translation:
        return task

    import uuid

    from skypilot_tpu.data import storage as storage_lib
    bucket_name = (f"skytpu-controller-{handle.cluster_name}-"
                   f"{_owner_suffix()}").replace("_", "-")
    # Per-submission prefix: concurrent/successive submissions must not
    # clobber each other's files in the shared controller bucket.
    run_prefix = f"run-{uuid.uuid4().hex[:10]}"
    cfg = task.to_yaml_config()
    mounts = dict(cfg.get("file_mounts") or {})
    uploads = {}  # bucket subpath -> local path
    if task.workdir:
        uploads[f"{run_prefix}/workdir"] = task.workdir
        cfg["workdir"] = None
    for dst, src in list(mounts.items()):
        if not src.startswith(cloud_stores.REMOTE_URL_PREFIXES):
            sub = f"{run_prefix}/mount{len(uploads)}"
            uploads[sub] = src
            if os.path.isfile(os.path.expanduser(src)):
                # Single-file mounts upload as {sub}/{basename} (see
                # GcsStore.upload); the rewritten URL must carry the
                # basename so the cluster-side file/dir heuristic
                # (data/storage.py materialize) picks a cp, not rsync.
                base = os.path.basename(os.path.expanduser(src).rstrip("/"))
                mounts[dst] = f"gs://{bucket_name}/{sub}/{base}"
            else:
                mounts[dst] = f"gs://{bucket_name}/{sub}"
    if not uploads:
        return task
    store = storage_lib.Storage(name=bucket_name, source=None,
                                persistent=False)
    for sub, local in uploads.items():
        store.upload_subpath(os.path.expanduser(local), sub)
    if task.workdir:
        mounts["~/sky_workdir"] = f"gs://{bucket_name}/{run_prefix}/workdir"
    cfg["file_mounts"] = mounts
    return Task.from_yaml_config(cfg)
