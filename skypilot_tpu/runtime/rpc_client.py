"""Client half of the typed cluster RPC (see runtime/rpc.py).

Wraps a head-host command runner; every method is one JSON round trip.
Remote error types re-raise as the matching ``skypilot_tpu.exceptions``
class when one exists, so callers handle cluster-side failures exactly
like local ones (the reference's codegen RPC loses this typing —
sky/skylet/job_lib.py returns encoded strings the caller must parse).
"""

from __future__ import annotations

import json
import shlex
import subprocess
import time
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu import chaos, exceptions
from skypilot_tpu.observability import metrics, tracing
from skypilot_tpu.runtime import job_queue
from skypilot_tpu.runtime.rpc import MARKER
from skypilot_tpu.utils import retry
from skypilot_tpu.utils.command_runner import CommandRunner

# Skylet-transport health on /metrics: every cluster RPC records its
# round-trip latency (one observation per transport attempt) and
# failures by kind — "transport" (runner rc != 0), "protocol" (no
# response frame in the output), "remote" (the method raised on the
# head).
RPC_SECONDS = metrics.histogram(
    "skytpu_rpc_seconds",
    "Cluster RPC round-trip latency per transport attempt, by method",
    labelnames=("method",))
RPC_FAILURES = metrics.counter(
    "skytpu_rpc_failures_total",
    "Cluster RPC failures by method and kind "
    "(transport | protocol | remote)",
    labelnames=("method", "kind"))


class ClusterRpcError(exceptions.SkyTpuError):
    pass


# Read-only methods are safe to retry on transport failure (one dropped
# SSH connection mid-poll must not crash wait_job/tail_logs while the
# job keeps running on the head).
_IDEMPOTENT = frozenset(
    {"ping", "get_job", "list_jobs", "read_logs", "is_idle",
     "jobs_get", "jobs_list", "jobs_log", "jobs_tail", "serve_status",
     "get_metrics", "healthz"})
_TRANSPORT_RETRIES = 3
DEFAULT_TIMEOUT_SECONDS = 120.0


class _TransportFailure(Exception):
    """One failed transport attempt (rc != 0 / timeout / OSError)."""

    def __init__(self, rc: int, detail: str):
        super().__init__(detail)
        self.rc = rc
        self.detail = detail


# Jittered exponential backoff between transport attempts, capped well
# below any sane timeout; attempts AND backoffs share one overall
# deadline (default: the caller's ``timeout`` — so attempts × timeout
# can never stretch a 120s budget into 6 minutes of hang).
_TRANSPORT_POLICY = retry.RetryPolicy(
    max_attempts=_TRANSPORT_RETRIES, backoff_base_s=1.0,
    backoff_multiplier=2.0, backoff_max_s=8.0,
    retry_on=(_TransportFailure,))
_SINGLE_ATTEMPT = retry.RetryPolicy(max_attempts=1,
                                    retry_on=(_TransportFailure,))


class ClusterRpc:
    def __init__(self, head_runner: CommandRunner, cluster_name: str):
        self.runner = head_runner
        self.cluster_name = cluster_name

    def call(self, method: str, *,
             timeout: float = DEFAULT_TIMEOUT_SECONDS,
             deadline_s: Optional[float] = None,
             **params: Any) -> Any:
        """One RPC round trip. ``timeout`` bounds each transport
        attempt; ``deadline_s`` bounds the WHOLE call including retries
        and backoffs (default: ``timeout`` — the caller's budget is a
        total, not a per-attempt multiplier)."""
        with tracing.start_span(
                f"rpc.{method}",
                attrs={"cluster": self.cluster_name}) as span:
            return self._call(method, span, timeout,
                              deadline_s if deadline_s is not None
                              else timeout, params)

    def _call(self, method: str, span, timeout: float,
              deadline_s: float, params: Dict[str, Any]) -> Any:
        cmd = (self.runner.framework_invocation("skypilot_tpu.runtime.rpc")
               + f" --cluster {shlex.quote(self.cluster_name)}")
        # The trace context rides IN the request: the head-side rpc
        # process parents its dispatch span (and anything it spawns —
        # skylet, driver) to this client-side span.
        payload = json.dumps({"method": method, "params": params,
                              "trace": tracing.format_traceparent(
                                  span.ctx)})
        deadline = retry.Deadline(deadline_s)
        attempts_made = [0]

        def attempt() -> str:
            # The first attempt gets the caller's per-attempt timeout
            # verbatim (the accounting overhead between Deadline() and
            # here must not shave it); RETRIES are clamped to the
            # remaining overall budget.
            first = attempts_made[0] == 0
            attempts_made[0] += 1
            per_timeout = (timeout if first and deadline_s >= timeout
                           else deadline.clamp(timeout))
            t0 = time.monotonic()
            try:
                # The chaos point rides INSIDE the transport-failure
                # classification: an injected ConnectionError/OSError is
                # counted, retried (idempotent methods), and typed
                # exactly like a real dropped SSH pipe.
                chaos.point("rpc.transport", method=method,
                            cluster=self.cluster_name)
                rc, out, err = self.runner.run(
                    cmd, stdin=payload, timeout=per_timeout)
            except subprocess.TimeoutExpired:
                # A timeout IS a transport failure — the exact failure
                # mode the timeout parameter exists for must show up in
                # the latency histogram and the failure counter, and
                # surface as the typed RPC error, not a raw
                # TimeoutExpired.
                rc, out = -1, ""
                err = f"timed out after {per_timeout:.6g}s"
            except OSError as e:
                # Socket/exec-level transport failures (the agent
                # runner's ConnectionRefusedError during a head outage,
                # a dropped SSH pipe — and TimeoutError, an OSError
                # subclass) take the same path: counted as
                # kind=transport, retried when idempotent, surfaced as
                # the typed RPC error.
                rc, out = -1, ""
                err = f"{type(e).__name__}: {e}"
            finally:
                RPC_SECONDS.labels(method=method).observe(
                    time.monotonic() - t0)
            if rc != 0:
                RPC_FAILURES.labels(method=method, kind="transport").inc()
                raise _TransportFailure(
                    rc, err.strip() or out.strip())
            return out

        policy = (_TRANSPORT_POLICY if method in _IDEMPOTENT
                  else _SINGLE_ATTEMPT)
        try:
            out = retry.call(attempt, name=f"rpc.{method}",
                             deadline=deadline, policy=policy)
        except _TransportFailure as e:
            raise ClusterRpcError(
                f"cluster rpc {method!r} on {self.cluster_name!r} failed "
                f"(rc={e.rc}): {e.detail}") from None
        except retry.DeadlineExceededError as e:
            raise ClusterRpcError(
                f"cluster rpc {method!r} on {self.cluster_name!r} failed: "
                f"deadline ({deadline_s}s) exceeded: {e}") from None
        resp = None
        for line in reversed(out.splitlines()):
            if line.startswith(MARKER):
                resp = json.loads(line[len(MARKER):])
                break
        if resp is None:
            RPC_FAILURES.labels(method=method, kind="protocol").inc()
            raise ClusterRpcError(
                f"cluster rpc {method!r}: no response frame in output: "
                f"{out[-500:]!r}")
        if not resp["ok"]:
            RPC_FAILURES.labels(method=method, kind="remote").inc()
            exc_cls = getattr(exceptions, resp.get("etype", ""), None)
            if isinstance(exc_cls, type) and issubclass(exc_cls, Exception):
                raise exc_cls(resp["error"])
            raise ClusterRpcError(f"{resp.get('etype')}: {resp['error']}")
        return resp["result"]

    # -- typed wrappers ----------------------------------------------------
    def ping(self) -> Dict[str, Any]:
        return self.call("ping")

    def init_cluster(self, meta: Dict[str, Any]) -> None:
        self.call("init_cluster", meta=meta)

    def submit(self, name: Optional[str], script: str, num_nodes: int,
               workdir: bool = False) -> int:
        return self.call("submit", name=name, script=script,
                         num_nodes=num_nodes, workdir=workdir)["job_id"]

    def get_job(self, job_id: int) -> Optional[Dict[str, Any]]:
        job = self.call("get_job", job_id=job_id)
        return _rehydrate(job) if job else None

    def list_jobs(self) -> List[Dict[str, Any]]:
        return [_rehydrate(j) for j in self.call("list_jobs")]

    def cancel(self, job_id: int) -> None:
        self.call("cancel", job_id=job_id)

    def read_logs(self, job_id: int, offsets: Dict[str, int]
                  ) -> Tuple[job_queue.JobStatus, Dict[str, str],
                             Dict[str, int]]:
        r = self.call("read_logs", job_id=job_id, offsets=offsets)
        return (job_queue.JobStatus(r["status"]), r["chunks"], r["offsets"])

    def set_autostop(self, idle_minutes: Optional[int], down: bool) -> None:
        self.call("set_autostop", idle_minutes=idle_minutes, down=down)

    def is_idle(self) -> bool:
        return self.call("is_idle")["idle"]

    def get_metrics(self, timeout: float = 20.0) -> Dict[str, Any]:
        """The head's persisted exposition ({"exposition", "mtime"});
        empty exposition when no daemon has published yet."""
        return self.call("get_metrics", timeout=timeout)

    def healthz(self, timeout: float = 20.0) -> Dict[str, Any]:
        """Skylet component health: {status, reason, last_seen_s}."""
        return self.call("healthz", timeout=timeout)


def _rehydrate(job: Dict[str, Any]) -> Dict[str, Any]:
    job = dict(job)
    job["status"] = job_queue.JobStatus(job["status"])
    return job
