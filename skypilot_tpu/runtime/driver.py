"""Gang-execution driver: runs one job across all hosts of a cluster.

TPU-native replacement for the reference's generated Ray driver program
(reference: sky/backends/cloud_vm_ray_backend.py:225-714 — RayCodeGen
emits a per-job python file that gang-schedules via a STRICT_SPREAD
placement group). A TPU slice is *already* a gang: every host must run
the same program simultaneously, so no placement-group machinery is
needed — the driver simply

  1. starts the job script on every host (detached, own process group),
     with the rank/coordinator env contract injected,
  2. polls per-host rc files,
  3. on any nonzero rc kills all other hosts (fail-one-kill-all — the
     gang semantics of get_or_fail at reference :318-355),
  4. records the final JobStatus in the cluster job queue.

The driver runs ON THE CLUSTER HEAD (spawned detached by the rpc
``submit`` method — the role the skylet FIFOScheduler plays at
reference sky/skylet/job_lib.py:276), reads the cluster's own
cluster.json for topology, and reaches peer hosts with intra-cluster
runners. No client state is touched: the job completes even if every
client disappears. Runs under ``python -S``; stdlib-only imports.
"""

from __future__ import annotations

import argparse
import os
import shlex
import sys
import time
from typing import Dict, List

from skypilot_tpu.runtime import constants, job_queue, topology
from skypilot_tpu.utils import command_runner

# How often to double-check the cloud that the slice still exists
# (preemption / out-of-band teardown detection). Guarded: head-side
# credentials may not allow it, and that must not break the job.
_PROVIDER_CHECK_INTERVAL = 5.0


def build_job_env(meta: dict, job_id: int, host: dict) -> Dict[str, str]:
    """The full injected env for one host's job process."""
    node_heads: Dict[int, str] = {}
    for h in meta["hosts"]:
        node_heads.setdefault(h["node_id"], h["internal_ip"])
    node_ips = [node_heads[n] for n in sorted(node_heads)]
    coordinator = (f"{meta['hosts'][0]['internal_ip']}:"
                   f"{constants.COORDINATOR_PORT}")
    n_hosts = len(meta["hosts"])
    env = {
        constants.ENV_CLUSTER: meta["cluster_name"],
        constants.ENV_JOB_ID: str(job_id),
        constants.ENV_NODE_RANK: str(host["node_id"]),
        constants.ENV_NUM_NODES: str(len(node_ips)),
        constants.ENV_NODE_IPS: "\n".join(node_ips),
        constants.ENV_HOST_ID: str(host["host_id"]),
        constants.ENV_NUM_HOSTS: str(n_hosts),
        constants.ENV_WORKER_ID: str(host["worker_id"]),
        constants.ENV_COORDINATOR: coordinator,
        constants.ENV_NUM_PROCESSES: str(n_hosts),
        constants.ENV_PROCESS_ID: str(host["host_id"]),
    }
    if len(node_ips) > 1:
        # Multislice: one logical node == one slice; libtpu reads the
        # MEGASCALE_* contract to bring up DCN between slices.
        env[constants.ENV_MEGASCALE_COORDINATOR] = (
            f"{node_ips[0]}:{constants.MEGASCALE_PORT}")
        env[constants.ENV_MEGASCALE_NUM_SLICES] = str(len(node_ips))
        env[constants.ENV_MEGASCALE_SLICE_ID] = str(host["node_id"])
    return env


def _wrap_script(run_cmd: str, rc_file: str, runner, workdir: bool,
                 docker_image: str = None,
                 env: Dict[str, str] = None) -> str:
    """Wrap the job command: make the framework importable on this host,
    optionally enter the synced workdir, and record the exit code
    atomically (tmp+mv) so the poll loop never reads a partial write.

    With ``docker_image`` the command itself runs inside the cluster's
    task container (docker exec propagating the rank env — the
    container does not inherit the detached process env); the rc file
    is still written HOST-side so the poll loop and gang-kill work
    unchanged. The container bind-mounts the host $HOME at /root, so
    the synced pkg and sky_workdir resolve at the same relative paths."""
    if runner.is_local:
        pythonpath = (f"export PYTHONPATH="
                      f"{shlex.quote(command_runner.PKG_PARENT)}"
                      f":$PYTHONPATH; ")
        if docker_image:
            # The head's PKG_PARENT is a host-absolute path that may
            # not exist inside the container; the synced pkg dir under
            # $HOME does (the container bind-mounts host $HOME at
            # /root) — export both so head-rank docker jobs can import
            # the framework like the SSH ranks do.
            pythonpath += (f'export PYTHONPATH="$HOME/'
                           f'{command_runner.REMOTE_PKG_DIR}'
                           f':$PYTHONPATH"; ')
    else:
        pythonpath = (f'export PYTHONPATH="$HOME/'
                      f'{command_runner.REMOTE_PKG_DIR}:$PYTHONPATH"; ')
    # `&&`: a missing synced workdir must fail loudly (cd's error lands
    # in the rank log), not silently run the job in $HOME.
    cd = "cd sky_workdir && " if workdir else ""
    q = shlex.quote
    body = f"{pythonpath}{cd}{run_cmd}"
    if docker_image:
        from skypilot_tpu.provision import instance_setup
        body = instance_setup.docker_exec_command(
            f"cd \"$HOME\" && {body}", env=env)
    return (f"{body}; rc=$?; "
            f"echo $rc > {q(rc_file + '.tmp')} && "
            f"mv {q(rc_file + '.tmp')} {q(rc_file)}; exit $rc")


def run_job(cluster_name: str, job_id: int,
            poll_interval: float = 0.2) -> int:
    cdir = topology.cluster_dir(cluster_name)
    meta = topology.load(cdir)
    topology.apply_provider_env(meta)
    db = os.path.join(cdir, constants.JOB_DB)
    job = job_queue.get_job(db, job_id)
    if job is None:
        print(f"job {job_id} not found", file=sys.stderr)
        return 1
    if job["status"] == job_queue.JobStatus.CANCELLED:
        return 0

    # FIFO gate (the reference's skylet FIFOScheduler role, job_lib.py:276):
    # proceed only when nothing is active and this job is the oldest
    # pending. Only the driver whose id matches next_pending advances, so
    # concurrent drivers serialize — one job at a time on the slice.
    while True:
        nxt = job_queue.next_pending(db)
        if nxt is not None and nxt["job_id"] == job_id:
            break
        cur = job_queue.get_job(db, job_id)
        if cur is None or cur["status"] != job_queue.JobStatus.PENDING:
            return 0  # cancelled (or externally transitioned) while queued
        time.sleep(poll_interval)

    hosts = meta["hosts"]
    runners = topology.build_runners(meta)
    log_dir = os.path.join(cdir, "logs",
                           constants.LOG_DIR.format(job_id=job_id))
    os.makedirs(log_dir, exist_ok=True)
    workdir = bool(job["metadata"].get("workdir"))

    job_queue.set_status(db, job_id, job_queue.JobStatus.RUNNING)

    pids: List[int] = []
    started = []   # (runner, pid) pairs for gang-kill
    hostpaths = {}  # host_id -> (runner, rc path, remote log, local log)
    offsets: Dict[int, int] = {}  # per-host mirrored-log byte offsets
    try:
        for host, runner in zip(hosts, runners):
            env = build_job_env(meta, job_id, host)
            hid = host["host_id"]
            local_log = os.path.join(log_dir, f"rank-{hid}.log")
            if runner.is_local:
                # Head / same-machine host: rc + log written straight
                # into the head log dir.
                rc_file = os.path.join(log_dir, f"rc-{hid}")
                log_path = local_log
            else:
                # Remote slice worker: rc + log live on the worker under
                # its $HOME (relative paths — remote commands start in
                # $HOME, and quoting keeps `~` from expanding); the poll
                # loop reads rc and mirrors log bytes via the runner.
                scratch = f".skypilot_tpu/job_{job_id}"
                runner.run(f"mkdir -p {scratch}")
                rc_file = f"{scratch}/rc"
                log_path = f"{scratch}/out.log"
            wrapped = _wrap_script(job["run_cmd"], rc_file, runner, workdir,
                                   docker_image=meta.get("docker_image"),
                                   env=env)
            pid = runner.run_detached(wrapped, env=env,
                                      cwd=host.get("workspace"),
                                      log_path=log_path)
            pids.append(pid)
            started.append((runner, pid))
            hostpaths[hid] = (runner, rc_file, log_path, local_log)
        job_queue.set_pids(db, job_id, pids)

        # Poll rc files (via runner: local read or `cat` over SSH) and
        # mirror remote logs head-local; fail-one-kill-all.
        done: Dict[int, int] = {}
        last_provider_check = time.time()
        while len(done) < len(hosts):
            for host in hosts:
                hid = host["host_id"]
                runner, rc_file, log_path, local_log = hostpaths[hid]
                if not runner.is_local:
                    _mirror_log(runner, log_path, local_log, offsets, hid)
                if hid in done:
                    continue
                content = runner.read_file(rc_file)
                if content is not None and content.strip():
                    done[hid] = int(content.strip())
            cur = job_queue.get_job(db, job_id)
            if cur and cur["status"] == job_queue.JobStatus.CANCELLED:
                _kill_all(started)
                return 0
            if any(rc != 0 for rc in done.values()):
                break
            # Slice preempted / terminated out-of-band? rc files will
            # never appear — ask the cloud occasionally and fail the
            # gang. Best-effort: head-side credentials may be absent.
            if time.time() - last_provider_check > _PROVIDER_CHECK_INTERVAL:
                last_provider_check = time.time()
                if _cluster_gone(meta):
                    raise RuntimeError(
                        "cluster disappeared while job was running "
                        "(slice preempted or externally terminated)")
            time.sleep(poll_interval)

        # Final log drain for remote hosts.
        for host in hosts:
            runner, _, log_path, local_log = hostpaths[host["host_id"]]
            if not runner.is_local:
                _mirror_log(runner, log_path, local_log, offsets,
                            host["host_id"])

        failed = [h for h, rc in done.items() if rc != 0]
        if failed:
            _kill_all(started)
            job_queue.set_status(db, job_id, job_queue.JobStatus.FAILED)
            return 1
        job_queue.set_status(db, job_id, job_queue.JobStatus.SUCCEEDED)
        return 0
    except Exception as e:  # noqa: BLE001 — driver must record failure
        print(f"driver error: {e}", file=sys.stderr)
        _kill_all(started)
        # Drain remote logs before the terminal status write: tail_logs'
        # bounded-read contract is that a read observing terminal status
        # already carries every mirrored byte — the bytes explaining
        # THIS failure most of all.
        for host in hosts:
            entry = hostpaths.get(host["host_id"])
            if entry and not entry[0].is_local:
                try:
                    _mirror_log(entry[0], entry[2], entry[3], offsets,
                                host["host_id"])
                except Exception:  # noqa: BLE001 — hosts may be gone
                    pass
        job_queue.set_status(db, job_id, job_queue.JobStatus.FAILED)
        return 1


def _cluster_gone(meta: dict) -> bool:
    try:
        from skypilot_tpu import provision
        return provision.query_instances(
            meta["provider"], meta["cluster_name"],
            meta["zone"]) == "NOT_FOUND"
    except Exception:  # noqa: BLE001 — best-effort check only
        return False


def _mirror_log(runner, remote_path: str, local_path: str,
                offsets: Dict[int, int], host_id: int) -> None:
    """Append new remote log bytes to the head-local rank log."""
    off = offsets.get(host_id, 0)
    rc, out, _ = runner.run(
        f"tail -c +{off + 1} {shlex.quote(remote_path)} 2>/dev/null")
    if rc == 0 and out:
        offsets[host_id] = off + len(out.encode())
        with open(local_path, "a") as f:
            f.write(out)


def _kill_all(started) -> None:
    for runner, pid in started:
        runner.kill(pid)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--cluster-name", required=True)
    ap.add_argument("--job-id", type=int, required=True)
    args = ap.parse_args()
    sys.exit(run_job(args.cluster_name, args.job_id))


if __name__ == "__main__":
    main()
