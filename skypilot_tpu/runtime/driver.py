"""Gang-execution driver: runs one job across all hosts of a cluster.

TPU-native replacement for the reference's generated Ray driver program
(reference: sky/backends/cloud_vm_ray_backend.py:225-714 — RayCodeGen
emits a per-job python file that gang-schedules via a STRICT_SPREAD
placement group). A TPU slice is *already* a gang: every host must run
the same program simultaneously, so no placement-group machinery is
needed — the driver simply

  1. starts the job script on every host (detached, own process group),
     with the rank/coordinator env contract injected,
  2. polls per-host rc files,
  3. on any nonzero rc kills all other hosts (fail-one-kill-all — the
     gang semantics of get_or_fail at reference :318-355),
  4. records the final JobStatus in the cluster job queue.

One driver process per job, spawned detached by the backend (the role
the skylet FIFOScheduler plays at reference sky/skylet/job_lib.py:276).
"""

from __future__ import annotations

import argparse
import json
import os
import shlex
import sys
import time
from typing import Dict, List

from skypilot_tpu import exceptions, provision
from skypilot_tpu.runtime import constants, job_queue


def _load_cluster_meta(cluster_dir: str) -> dict:
    with open(os.path.join(cluster_dir, "cluster.json")) as f:
        return json.load(f)


def build_job_env(cluster_name: str, job_id: int, info,
                  host) -> Dict[str, str]:
    """The full injected env for one host's job process."""
    node_heads = {}
    for h in info.hosts:
        node_heads.setdefault(h.node_id, h.internal_ip)
    node_ips = [node_heads[n] for n in sorted(node_heads)]
    coordinator = f"{info.hosts[0].internal_ip}:{constants.COORDINATOR_PORT}"
    return {
        constants.ENV_CLUSTER: cluster_name,
        constants.ENV_JOB_ID: str(job_id),
        constants.ENV_NODE_RANK: str(host.node_id),
        constants.ENV_NUM_NODES: str(len(node_ips)),
        constants.ENV_NODE_IPS: "\n".join(node_ips),
        constants.ENV_HOST_ID: str(host.host_id),
        constants.ENV_NUM_HOSTS: str(len(info.hosts)),
        constants.ENV_WORKER_ID: str(host.worker_id),
        constants.ENV_COORDINATOR: coordinator,
        constants.ENV_NUM_PROCESSES: str(len(info.hosts)),
        constants.ENV_PROCESS_ID: str(host.host_id),
    }


def run_job(cluster_dir: str, job_id: int, poll_interval: float = 0.2) -> int:
    meta = _load_cluster_meta(cluster_dir)
    db = os.path.join(cluster_dir, constants.JOB_DB)
    job = job_queue.get_job(db, job_id)
    if job is None:
        print(f"job {job_id} not found", file=sys.stderr)
        return 1
    if job["status"] == job_queue.JobStatus.CANCELLED:
        return 0

    # FIFO gate (the reference's skylet FIFOScheduler role, job_lib.py:276):
    # proceed only when nothing is active and this job is the oldest
    # pending. Only the driver whose id matches next_pending advances, so
    # concurrent drivers serialize — one job at a time on the slice.
    while True:
        nxt = job_queue.next_pending(db)
        if nxt is not None and nxt["job_id"] == job_id:
            break
        cur = job_queue.get_job(db, job_id)
        if cur is None or cur["status"] != job_queue.JobStatus.PENDING:
            return 0  # cancelled (or externally transitioned) while queued
        time.sleep(poll_interval)

    info = provision.get_cluster_info(meta["provider"], meta["cluster_name"],
                                      meta["zone"])
    runners = provision.get_command_runners(info)
    log_dir = os.path.join(cluster_dir, "logs",
                           constants.LOG_DIR.format(job_id=job_id))
    os.makedirs(log_dir, exist_ok=True)

    job_queue.set_status(db, job_id, job_queue.JobStatus.RUNNING)

    pids: List[int] = []
    started = []   # (runner, pid) pairs for gang-kill
    hostpaths = {}  # host_id -> (runner, remote rc path, remote log path)
    try:
        for host, runner in zip(info.hosts, runners):
            env = build_job_env(meta["cluster_name"], job_id, info, host)
            local_log = os.path.join(log_dir, f"rank-{host.host_id}.log")
            if runner.is_local:
                # Head-local host: rc + log written straight into log_dir.
                scratch = log_dir
                rc_file = os.path.join(scratch, f"rc-{host.host_id}")
                log_path = local_log
            else:
                # Remote slice worker: rc + log live on the worker; the
                # poll loop reads rc and mirrors log bytes via the runner.
                scratch = f"~/.skypilot_tpu/job_{job_id}"
                runner.run(f"mkdir -p {scratch}")
                rc_file = f"{scratch}/rc"
                log_path = f"{scratch}/out.log"
            # Wrap: run the script, then record its rc atomically.
            wrapped = (f"{job['run_cmd']}; rc=$?; "
                       f"echo $rc > {shlex.quote(rc_file + '.tmp')} && "
                       f"mv {shlex.quote(rc_file + '.tmp')} "
                       f"{shlex.quote(rc_file)}; exit $rc")
            pid = runner.run_detached(wrapped, env=env, cwd=host.workspace,
                                      log_path=log_path)
            pids.append(pid)
            started.append((runner, pid))
            hostpaths[host.host_id] = (runner, rc_file, log_path, local_log)
        job_queue.set_pids(db, job_id, pids)

        # Poll rc files (via runner: local read or `cat` over SSH) and
        # mirror remote logs head-local; fail-one-kill-all.
        done: Dict[int, int] = {}
        offsets: Dict[int, int] = {}
        while len(done) < len(info.hosts):
            for host in info.hosts:
                hid = host.host_id
                runner, rc_file, log_path, local_log = hostpaths[hid]
                if not runner.is_local:
                    _mirror_log(runner, log_path, local_log, offsets, hid)
                if hid in done:
                    continue
                content = runner.read_file(rc_file)
                if content is not None and content.strip():
                    done[hid] = int(content.strip())
            cur = job_queue.get_job(db, job_id)
            if cur and cur["status"] == job_queue.JobStatus.CANCELLED:
                _kill_all(started)
                return 0
            if any(rc != 0 for rc in done.values()):
                break
            # Slice preempted / terminated out-of-band? rc files will
            # never appear — detect and fail the gang.
            if provision.query_instances(
                    meta["provider"], meta["cluster_name"],
                    meta["zone"]) == "NOT_FOUND":
                raise exceptions.ClusterNotUpError(
                    "cluster disappeared while job was running "
                    "(slice preempted or externally terminated)")
            time.sleep(poll_interval)

        # Final log drain for remote hosts.
        for host in info.hosts:
            runner, _, log_path, local_log = hostpaths[host.host_id]
            if not runner.is_local:
                _mirror_log(runner, log_path, local_log, offsets,
                            host.host_id)

        failed = [h for h, rc in done.items() if rc != 0]
        if failed:
            _kill_all(started)
            job_queue.set_status(db, job_id, job_queue.JobStatus.FAILED)
            return 1
        job_queue.set_status(db, job_id, job_queue.JobStatus.SUCCEEDED)
        return 0
    except Exception as e:  # noqa: BLE001 — driver must record failure
        print(f"driver error: {e}", file=sys.stderr)
        _kill_all(started)
        job_queue.set_status(db, job_id, job_queue.JobStatus.FAILED)
        return 1


def _mirror_log(runner, remote_path: str, local_path: str,
                offsets: Dict[int, int], host_id: int) -> None:
    """Append new remote log bytes to the head-local rank log."""
    off = offsets.get(host_id, 0)
    rc, out, _ = runner.run(
        f"tail -c +{off + 1} {shlex.quote(remote_path)} 2>/dev/null")
    if rc == 0 and out:
        offsets[host_id] = off + len(out.encode())
        with open(local_path, "a") as f:
            f.write(out)


def _kill_all(started) -> None:
    for runner, pid in started:
        runner.kill(pid)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--cluster-dir", required=True)
    ap.add_argument("--job-id", type=int, required=True)
    args = ap.parse_args()
    sys.exit(run_job(args.cluster_dir, args.job_id))


if __name__ == "__main__":
    main()
