"""Head-side cluster topology: cluster.json + intra-cluster runners.

The cluster's own record of itself, written once by the client at
provision time (rpc ``init_cluster``) and read by every head-side
component (rpc, driver, skylet). After launch, no client state is
consulted — the cluster is autonomous (the property the reference gets
from the on-head Ray cluster + sqlite job DB, sky/skylet/job_lib.py).

Stdlib-only: head-side processes run under ``python -S``.

Schema of cluster.json::

    {
      "provider": "local" | "gcp" | "kubernetes",
      "cluster_name": ..., "zone": ..., "region": ...,
      "num_nodes": N, "hosts_per_node": H,
      "launched_at": <epoch seconds>,
      "head_host_id": 0,
      "ssh_key_path": "~/.skypilot_tpu/ssh/sky-key",   # head-side path
      "provider_env": {"SKYTPU_LOCAL_CLUSTERS_ROOT": ...},
      "hosts": [
        {"host_id": 0, "node_id": 0, "worker_id": 0,
         "internal_ip": "...", "ssh_user": ..., "ssh_port": 22,
         "workspace": <dir or null>, "kind": "local"|"fake"|"ssh"|"k8s"}
      ]
    }
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List

from skypilot_tpu.utils import command_runner, paths

CLUSTER_META = "cluster.json"
AUTOSTOP_CONFIG = "autostop.json"


def cluster_dir(cluster_name: str) -> str:
    """Head-side per-cluster dir (under the head's own home)."""
    d = os.path.join(paths.home(), "clusters", cluster_name)
    os.makedirs(d, exist_ok=True)
    return d


def save(cdir: str, meta: Dict[str, Any]) -> None:
    tmp = os.path.join(cdir, CLUSTER_META + ".tmp")
    with open(tmp, "w") as f:
        json.dump(meta, f, indent=1)
    os.replace(tmp, os.path.join(cdir, CLUSTER_META))


def load(cdir: str) -> Dict[str, Any]:
    with open(os.path.join(cdir, CLUSTER_META)) as f:
        return json.load(f)


def apply_provider_env(meta: Dict[str, Any]) -> None:
    """Make provider API calls work from the cluster side (e.g. the
    local fake cloud's clusters root, which must not depend on any
    client's home)."""
    os.environ.update(meta.get("provider_env") or {})


def build_runners(
        meta: Dict[str, Any]) -> List[command_runner.CommandRunner]:
    """Intra-cluster runners for the gang driver / rpc, aligned with
    meta["hosts"]. Must be called ON the head host."""
    head_id = meta.get("head_host_id", 0)
    runners: List[command_runner.CommandRunner] = []
    for h in meta["hosts"]:
        kind = h.get("kind", "ssh")
        ws = h.get("workspace")
        if h["host_id"] == head_id:
            # The head itself: plain local execution. When the host has a
            # workspace dir (local fake cloud), pin $HOME to it so
            # `~`-relative layout matches a real VM.
            runners.append(command_runner.LocalRunner(
                h["host_id"], h.get("internal_ip", "127.0.0.1"), ws,
                env_overrides={"HOME": ws} if ws else None))
        elif kind == "fake":
            runners.append(command_runner.FakeSSHRunner(
                root=ws, host_id=h["host_id"],
                ip=h.get("internal_ip", "127.0.0.1")))
        elif kind == "local":
            runners.append(command_runner.LocalRunner(
                h["host_id"], h.get("internal_ip", "127.0.0.1"), ws,
                env_overrides={"HOME": ws} if ws else None))
        elif kind == "ssh":
            runners.append(command_runner.SSHRunner(
                ip=h["internal_ip"], user=h.get("ssh_user") or "skypilot",
                key_path=meta.get("ssh_key_path")
                or "~/.skypilot_tpu/ssh/sky-key",
                host_id=h["host_id"], port=h.get("ssh_port", 22)))
        elif kind == "k8s":
            # Pods have no sshd; the per-pod hostd agent (started at
            # provision) is the exec transport.
            token = meta.get("agent_token")
            if not token:
                raise RuntimeError(
                    "k8s host without an agent token in cluster.json — "
                    "was start_host_agents skipped at provision?")
            # `or`, not a dict default: the key is serialized as null
            # when unset.
            port = meta.get("agent_port") or command_runner.AGENT_PORT
            runners.append(command_runner.TcpAgentRunner(
                ip=h["internal_ip"], port=port,
                token=token, host_id=h["host_id"]))
        else:
            raise NotImplementedError(
                f"intra-cluster runner kind {kind!r} (host "
                f"{h['host_id']})")
    return runners


def from_cluster_info(info, provider_env: Dict[str, str] | None = None,
                      ssh_key_path: str | None = None,
                      launched_at: float | None = None,
                      agent_token: str | None = None,
                      agent_port: int | None = None,
                      docker_image: str | None = None) -> Dict[str, Any]:
    """Client-side: build the cluster.json payload from a provision
    ClusterInfo (each HostInfo carries its runner kind)."""
    hosts = []
    for h in info.hosts:
        hosts.append({
            "host_id": h.host_id,
            "node_id": h.node_id,
            "worker_id": h.worker_id,
            "internal_ip": h.internal_ip,
            "ssh_user": h.ssh_user,
            "ssh_port": h.ssh_port,
            "workspace": h.workspace,
            "kind": getattr(h, "runner_kind", "ssh"),
        })
    return {
        "provider": info.provider,
        "cluster_name": info.cluster_name,
        "zone": info.zone,
        "num_nodes": max((h["node_id"] for h in hosts), default=0) + 1,
        "hosts_per_node": (len(hosts) //
                           (max((h["node_id"] for h in hosts),
                                default=0) + 1)) if hosts else 1,
        "launched_at": launched_at,
        "head_host_id": hosts[0]["host_id"] if hosts else 0,
        "ssh_key_path": ssh_key_path,
        "agent_token": agent_token,
        "agent_port": agent_port,
        "docker_image": docker_image,
        "provider_env": provider_env or {},
        "hosts": hosts,
    }
