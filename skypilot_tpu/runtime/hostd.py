"""Per-host exec agent: the gang driver's transport where SSH does not
exist (kubernetes pods).

A ~150-line TCP server speaking line-delimited JSON, started on every
pod at provision time. The head pod's driver reaches peers through
``TcpAgentRunner`` exactly like it reaches SSH hosts — run /
run_detached / read_file / kill — so multi-pod gang execution uses the
identical driver code path. This replaces the role Ray's on-cluster
actor transport plays in the reference (sky/provision/instance_setup.py
starts Ray workers; here the agent is ~two orders of magnitude smaller
and stdlib-only, run under ``python -S``).

Security: requests must carry the cluster's shared token (pushed to
every pod at provision). Pod networks are cluster-internal; the token
is defense in depth, not a perimeter.

Protocol: one JSON object per line in, one per line out.

  {"token": T, "op": "run", "cmd": ..., "env": {..}, "cwd": ...,
   "timeout": N, "stdin": S|null} -> {"ok": true, "rc", "out", "err"}
  {"token": T, "op": "run_detached", "cmd", "env", "cwd", "log_path"}
                                  -> {"ok": true, "pid": N}
  {"token": T, "op": "read_file", "path": P} -> {"ok": true,
                                                 "content": str|null}
  {"token": T, "op": "kill", "pid": N}       -> {"ok": true}
  {"token": T, "op": "ping"}                 -> {"ok": true}
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import socketserver
import subprocess
import sys

DEFAULT_PORT = 8477


def _expand(path: str) -> str:
    return os.path.expanduser(path)


def _full_env(env):
    full = dict(os.environ)
    if env:
        full.update(env)
    return full


def handle_request(req: dict) -> dict:
    op = req.get("op")
    if op == "ping":
        return {"ok": True, "home": os.path.expanduser("~"),
                "protocol": PROTOCOL_VERSION}
    if op == "run":
        # stdin rides the protocol as data (never spliced into the
        # shell line — a heredoc wrapper would let stdin content
        # execute as shell on the pod).
        proc = subprocess.run(
            ["bash", "-c", req["cmd"]], env=_full_env(req.get("env")),
            cwd=req.get("cwd") or os.path.expanduser("~"),
            input=req.get("stdin"),
            capture_output=True, text=True, timeout=req.get("timeout"))
        return {"ok": True, "rc": proc.returncode, "out": proc.stdout,
                "err": proc.stderr}
    if op == "run_detached":
        log_path = _expand(req.get("log_path") or "/dev/null")
        if not os.path.isabs(log_path):
            log_path = os.path.join(os.path.expanduser("~"), log_path)
        os.makedirs(os.path.dirname(log_path) or ".", exist_ok=True)
        with open(log_path, "ab") as f:
            proc = subprocess.Popen(
                ["bash", "-c", req["cmd"]], env=_full_env(req.get("env")),
                cwd=req.get("cwd") or os.path.expanduser("~"),
                stdout=f, stderr=subprocess.STDOUT,
                start_new_session=True)
        return {"ok": True, "pid": proc.pid}
    if op == "read_file":
        path = _expand(req["path"])
        if not os.path.isabs(path):
            path = os.path.join(os.path.expanduser("~"), path)
        try:
            with open(path) as f:
                return {"ok": True, "content": f.read()}
        except OSError:
            return {"ok": True, "content": None}
    if op == "kill":
        pid = int(req["pid"])
        try:
            os.killpg(pid, signal.SIGTERM)
        except (ProcessLookupError, PermissionError, OSError):
            try:
                os.kill(pid, signal.SIGTERM)
            except OSError:
                pass
        return {"ok": True}
    return {"ok": False, "error": f"unknown op {op!r}"}


class _Handler(socketserver.StreamRequestHandler):
    def handle(self):
        for raw in self.rfile:
            try:
                req = json.loads(raw)
                if req.get("token") != self.server.token:  # type: ignore
                    resp = {"ok": False, "error": "bad token"}
                else:
                    resp = handle_request(req)
            except Exception as e:  # noqa: BLE001 — agent must answer
                resp = {"ok": False,
                        "error": f"{type(e).__name__}: {e}"}
            self.wfile.write((json.dumps(resp) + "\n").encode())
            self.wfile.flush()


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


# Bumped on wire-protocol changes (v2: dedicated "stdin" field on "run").
# The running agent records its version so instance_setup can detect a
# stale daemon after a re-provision and restart it — the launch guard
# alone would keep an old-protocol agent alive forever.
PROTOCOL_VERSION = 2


def _record_protocol_version() -> None:
    try:
        d = os.path.expanduser("~/.skypilot_tpu")
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, "hostd.protocol"), "w") as f:
            f.write(str(PROTOCOL_VERSION))
    except OSError:
        pass  # advisory only; worst case setup restarts the agent


def serve(port: int, token: str, host: str = "0.0.0.0") -> None:
    srv = _Server((host, port), _Handler)
    srv.token = token  # type: ignore[attr-defined]
    _record_protocol_version()
    srv.serve_forever()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=DEFAULT_PORT)
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--token-file",
                    default="~/.skypilot_tpu/agent_token")
    args = ap.parse_args()
    try:
        with open(os.path.expanduser(args.token_file)) as f:
            token = f.read().strip()
    except OSError:
        print(f"no token file at {args.token_file}", file=sys.stderr)
        sys.exit(1)
    serve(args.port, token, args.host)


if __name__ == "__main__":
    main()
