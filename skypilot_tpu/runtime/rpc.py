"""Typed cluster RPC: the single client <-> cluster seam.

One call = one JSON request on stdin, one framed JSON response on
stdout, executed on the cluster head through the command runner as
``python -S -m skypilot_tpu.runtime.rpc --cluster <name>``. This
replaces the reference's string-codegen-over-SSH protocol
(sky/skylet/job_lib.py:930-1077 JobLibCodeGen emits `python -c`
snippets) with plain data — no generated source, stable wire format,
symmetrical client in runtime/rpc_client.py.

Everything here is stdlib-only and runs under ``python -S`` (~20ms per
call vs multi-second site/jax imports), so polling RPCs are cheap.

The job DB, run scripts, logs, autostop config, and the driver/skylet
processes all live under the HEAD's home — the cluster survives client
death, serves any number of clients, and autostops by itself
(reference: sky/skylet/skylet.py + events.py:102).
"""

from __future__ import annotations

import argparse
import json
import os
import shlex
import subprocess
import sys
import time
from typing import Any, Callable, Dict

from skypilot_tpu.observability import tracing
from skypilot_tpu.runtime import constants, job_queue, topology
from skypilot_tpu.utils import command_runner

MARKER = "SKYTPU-RPC1 "


def _db(cdir: str) -> str:
    return os.path.join(cdir, constants.JOB_DB)


def _serialize_job(job: Dict[str, Any]) -> Dict[str, Any]:
    out = dict(job)
    out["status"] = job["status"].value
    return out


def _child_env() -> Dict[str, str]:
    """Env for head-side daemons (driver, skylet): framework importable,
    head home pinned."""
    from skypilot_tpu.utils import paths
    env = dict(os.environ)
    env["PYTHONPATH"] = (command_runner.PKG_PARENT + os.pathsep +
                         env.get("PYTHONPATH", ""))
    env["SKYPILOT_TPU_HOME"] = paths.home()
    return env


def _spawn_detached(argv, log_path: str) -> int:
    os.makedirs(os.path.dirname(log_path), exist_ok=True)
    with open(log_path, "ab") as f:
        proc = subprocess.Popen(argv, stdout=f, stderr=subprocess.STDOUT,
                                start_new_session=True, env=_child_env())
    return proc.pid


def _pid_alive(pidfile: str) -> bool:
    if not os.path.exists(pidfile):
        return False
    try:
        os.kill(int(open(pidfile).read().strip()), 0)
        return True
    except (OSError, ValueError):
        return False


def _ensure_skylet(cluster_name: str, cdir: str) -> None:
    pidfile = os.path.join(cdir, "skylet.pid")
    if _pid_alive(pidfile):
        return
    pid = _spawn_detached(
        [sys.executable, "-S", "-m", "skypilot_tpu.runtime.skylet",
         "--cluster-name", cluster_name],
        os.path.join(cdir, "skylet.log"))
    with open(pidfile, "w") as f:
        f.write(str(pid))


# ---------------------------------------------------------------------------
# Methods. Each takes (cluster_name, cdir, params) and returns a
# JSON-serializable result.

def _m_ping(cluster_name, cdir, p):
    return {"pong": True, "home": os.path.dirname(os.path.dirname(cdir))}


def _m_init_cluster(cluster_name, cdir, p):
    meta = p["meta"]
    if meta.get("launched_at") is None:
        meta["launched_at"] = time.time()
    topology.save(cdir, meta)
    os.makedirs(os.path.join(cdir, "logs"), exist_ok=True)
    # The skylet is spawned lazily by set_autostop; on re-init (cluster
    # restart) a persisted autostop config must get its skylet back.
    if os.path.exists(os.path.join(cdir, topology.AUTOSTOP_CONFIG)):
        _ensure_skylet(cluster_name, cdir)
    return {"initialized": True}


def _m_submit(cluster_name, cdir, p):
    job_id = job_queue.add_job(
        _db(cdir), p.get("name"), "",
        metadata={"num_nodes": p.get("num_nodes", 1),
                  "workdir": bool(p.get("workdir", False))})
    script_path = os.path.join(cdir,
                               constants.RUN_SCRIPT.format(job_id=job_id))
    with open(script_path, "w") as f:
        f.write(p["script"])
    job_queue.set_run_cmd(_db(cdir), job_id,
                          f"bash {shlex.quote(script_path)}")
    pid = _spawn_detached(
        [sys.executable, "-S", "-m", "skypilot_tpu.runtime.driver",
         "--cluster-name", cluster_name, "--job-id", str(job_id)],
        os.path.join(cdir, "logs", f"driver-{job_id}.log"))
    return {"job_id": job_id, "driver_pid": pid}


def _m_get_job(cluster_name, cdir, p):
    job = job_queue.get_job(_db(cdir), int(p["job_id"]))
    return _serialize_job(job) if job else None


def _m_list_jobs(cluster_name, cdir, p):
    return [_serialize_job(j) for j in job_queue.list_jobs(_db(cdir))]


def _m_cancel(cluster_name, cdir, p):
    job_id = int(p["job_id"])
    job = job_queue.get_job(_db(cdir), job_id)
    if job is None:
        raise _err("JobNotFoundError", f"no job {job_id}")
    job_queue.set_status(_db(cdir), job_id, job_queue.JobStatus.CANCELLED)
    # The driver notices CANCELLED within one poll; also kill the job
    # processes directly in case the driver itself died.
    if job["pids"]:
        try:
            meta = topology.load(cdir)
            runners = topology.build_runners(meta)
            for runner, pid in zip(runners, job["pids"]):
                runner.kill(pid)
        except (OSError, NotImplementedError):
            pass
    return {"cancelled": job_id}


def _m_read_logs(cluster_name, cdir, p):
    job_id = int(p["job_id"])
    job = job_queue.get_job(_db(cdir), job_id)
    if job is None:
        raise _err("JobNotFoundError", f"no job {job_id}")
    log_dir = os.path.join(cdir, "logs",
                           constants.LOG_DIR.format(job_id=job_id))
    offsets = {str(k): int(v) for k, v in (p.get("offsets") or {}).items()}
    chunks: Dict[str, str] = {}
    if os.path.isdir(log_dir):
        for fname in sorted(os.listdir(log_dir)):
            if not fname.startswith("rank-"):
                continue
            fpath = os.path.join(log_dir, fname)
            off = offsets.get(fname, 0)
            try:
                with open(fpath, "rb") as f:
                    f.seek(off)
                    data = f.read()
            except OSError:
                continue
            if data:
                # Hold back a trailing partial UTF-8 sequence so a
                # multi-byte char split across two polls is never
                # corrupted; the held bytes re-read on the next call.
                data = _trim_partial_utf8(data)
            if data:
                chunks[fname] = data.decode("utf-8", errors="replace")
                offsets[fname] = off + len(data)
            else:
                offsets.setdefault(fname, off)
    return {"status": job["status"].value, "chunks": chunks,
            "offsets": offsets}


def _trim_partial_utf8(data: bytes) -> bytes:
    """Drop a trailing incomplete UTF-8 sequence (at most 3 bytes)."""
    for back in range(1, min(4, len(data) + 1)):
        b = data[-back]
        if b < 0x80:        # ASCII: complete
            return data
        if b >= 0xC0:       # lead byte: complete iff sequence fits
            need = 2 if b < 0xE0 else 3 if b < 0xF0 else 4
            return data if back >= need else data[:-back]
        # else continuation byte: keep looking back
    return data


def _m_set_autostop(cluster_name, cdir, p):
    cfg_path = os.path.join(cdir, topology.AUTOSTOP_CONFIG)
    idle = p.get("idle_minutes")
    if idle is None or idle < 0:
        try:
            os.remove(cfg_path)
        except OSError:
            pass
    else:
        tmp = cfg_path + ".tmp"
        with open(tmp, "w") as f:
            # "trace": the arming request's context, persisted so the
            # skylet attributes autostop outcomes (fired/retry/disarm —
            # possibly days later, long after this rpc process died) to
            # the request that ARMED autostop, not to whichever request
            # originally spawned the skylet.
            json.dump({"idle_minutes": idle, "down": bool(p.get("down")),
                       "set_at": time.time(),
                       "trace": tracing.traceparent()}, f)
        os.replace(tmp, cfg_path)
        # Arming anew invalidates a previous fire's outcome marker —
        # left behind, a later skylet crash would read as "exited by
        # design" to the health model instead of dead.
        try:
            os.remove(os.path.join(cdir, "autostop_fired"))
        except OSError:
            pass
        _ensure_skylet(cluster_name, cdir)
    return {"autostop": idle}


def _m_is_idle(cluster_name, cdir, p):
    return {"idle": job_queue.is_idle(_db(cdir))}


def _m_get_metrics(cluster_name, cdir, p):
    """The head's daemon registries for the federation tier: the
    skylet publishes its registry to ``metrics.prom`` every tick (it
    has no HTTP surface), so this method is a file read — cheap enough
    for a scrape loop even over SSH."""
    from skypilot_tpu.observability import aggregate
    path = os.path.join(cdir, aggregate.METRICS_FILENAME)
    try:
        with open(path, encoding="utf-8") as f:
            text = f.read()
        return {"exposition": text, "mtime": os.path.getmtime(path)}
    except OSError:
        return {"exposition": "", "mtime": None}


def _m_healthz(cluster_name, cdir, p):
    """Cheap component-health probe of the head's skylet: pidfile
    liveness + the heartbeat gauge persisted in ``metrics.prom``,
    answered in the common {status, reason, last_seen_s} shape."""
    from skypilot_tpu.observability import health
    h = health.skylet_health(cdir)
    return {"status": h["status"], "reason": h["reason"],
            "last_seen_s": h["last_seen_s"]}


# -- controller-as-task methods --------------------------------------------
# Managed-jobs and serve controllers run as processes on THIS host (the
# controller cluster head, reference: jobs-controller.yaml.j2 /
# sky-serve-controller templates); their state DBs live in this host's
# home. Imports are lazy so the plain job-queue RPC stays stdlib-light.

def _controller_env(cdir: str) -> Dict[str, str]:
    env = _child_env()
    try:
        env.update(topology.load(cdir).get("provider_env") or {})
    except (OSError, ValueError):
        pass
    return env


def _serialize_enum_rec(rec):
    out = dict(rec)
    for k, v in out.items():
        if hasattr(v, "value") and not isinstance(v, (int, float, str)):
            out[k] = v.value
    return out


def _m_jobs_submit(cluster_name, cdir, p):
    from skypilot_tpu.jobs import state as jstate
    limit = jstate.alive_limit()
    if jstate.count_alive() >= limit:
        raise _err("ManagedJobError",
                   f"managed-job limit reached ({limit}); wait for "
                   f"running jobs to finish")
    job_id = jstate.add(p.get("name"), p["task_config"],
                        p.get("strategy") or "EAGER_NEXT_ZONE")
    from skypilot_tpu.utils import paths
    log = os.path.join(paths.logs_dir(), f"jobs-controller-{job_id}.log")
    os.makedirs(os.path.dirname(log), exist_ok=True)
    with open(log, "ab") as f:
        proc = subprocess.Popen(
            [sys.executable, "-m", "skypilot_tpu.jobs.controller",
             "--job-id", str(job_id)],
            stdout=f, stderr=subprocess.STDOUT, start_new_session=True,
            env=_controller_env(cdir))
    jstate.set_controller_pid(job_id, proc.pid)
    jstate.set_status(job_id, jstate.ManagedJobStatus.SUBMITTED)
    return {"job_id": job_id}


def _m_jobs_list(cluster_name, cdir, p):
    from skypilot_tpu.jobs import state as jstate
    jstate.reap_dead_controllers()
    return [_serialize_enum_rec(r) for r in jstate.list_jobs()]


def _m_jobs_get(cluster_name, cdir, p):
    from skypilot_tpu.jobs import state as jstate
    jstate.reap_dead_controllers()
    rec = jstate.get(int(p["job_id"]))
    return _serialize_enum_rec(rec) if rec else None


def _m_jobs_cancel(cluster_name, cdir, p):
    from skypilot_tpu.jobs import state as jstate
    job_id = int(p["job_id"])
    rec = jstate.get(job_id)
    if rec is None:
        raise _err("ManagedJobError", f"no managed job {job_id}")
    if rec["status"].is_terminal():
        return {"cancelled": job_id}
    jstate.set_status(job_id, jstate.ManagedJobStatus.CANCELLING)
    pid = rec["controller_pid"]
    if pid is not None:
        try:
            os.kill(pid, 0)
            return {"cancelled": job_id}  # controller will finish it
        except OSError:
            pass
    jstate.set_status(job_id, jstate.ManagedJobStatus.CANCELLED)
    return {"cancelled": job_id}


def _m_jobs_log(cluster_name, cdir, p):
    from skypilot_tpu.utils import paths
    job_id = int(p["job_id"])
    path = os.path.join(paths.logs_dir(),
                        f"jobs-controller-{job_id}.log")
    try:
        with open(path, "rb") as f:
            f.seek(int(p.get("offset", 0)))
            data = f.read()
    except OSError:
        return {"text": "", "offset": int(p.get("offset", 0))}
    data = _trim_partial_utf8(data)
    return {"text": data.decode("utf-8", errors="replace"),
            "offset": int(p.get("offset", 0)) + len(data)}


def _m_jobs_tail(cluster_name, cdir, p):
    """Fetch a managed job's OUTPUT logs. The per-job cluster handle
    lives in this host's cluster state, so the fetch runs here — in a
    full (non -S) python, since it needs the orchestration stack."""
    from skypilot_tpu.jobs import state as jstate
    rec = jstate.get(int(p["job_id"]))
    if rec is None:
        raise _err("ManagedJobError", f"no managed job {p['job_id']}")
    if not rec["cluster_name"]:
        return {"text": "", "note": "no cluster yet"}
    if rec["status"].is_terminal():
        # The per-job cluster is (being) torn down; serve the snapshot
        # the controller saved before cleanup.
        from skypilot_tpu.utils import paths
        snap = os.path.join(paths.logs_dir(),
                            f"jobs-output-{rec['job_id']}.log")
        try:
            with open(snap) as f:
                return {"text": f.read(), "note": None}
        except OSError:
            pass  # no snapshot (e.g. failed before running): live path
    code = ("from skypilot_tpu import core\n"
            f"core.tail_logs({rec['cluster_name']!r}, None, follow=False)\n")
    out = subprocess.run([sys.executable, "-c", code],
                         env=_controller_env(cdir), capture_output=True,
                         text=True, timeout=120)
    if out.returncode != 0:
        lines = out.stderr.strip().splitlines()
        reason = lines[-1] if lines else "unknown error"
        return {"text": out.stdout,
                "note": f"log fetch failed (cluster may be cleaned up): "
                        f"{reason}"}
    return {"text": out.stdout, "note": None}


def _m_serve_up(cluster_name, cdir, p):
    from skypilot_tpu.serve import serve_state
    from skypilot_tpu.utils import paths
    name = p["service_name"]
    if serve_state.get_service(name) is not None:
        raise _err("ServeError", f"service {name!r} already exists")
    import socket
    with socket.socket() as s:
        s.bind(("", int(p.get("lb_port") or 0)))
        lb_port = s.getsockname()[1]
    serve_state.add_service(name, p["spec"], p["task_config"], lb_port)
    log = os.path.join(paths.logs_dir(), f"serve-controller-{name}.log")
    os.makedirs(os.path.dirname(log), exist_ok=True)
    with open(log, "ab") as f:
        proc = subprocess.Popen(
            [sys.executable, "-m", "skypilot_tpu.serve.controller",
             "--service", name],
            stdout=f, stderr=subprocess.STDOUT, start_new_session=True,
            env=_controller_env(cdir))
    serve_state.set_controller_pid(name, proc.pid)
    return {"lb_port": lb_port}


def _m_serve_update(cluster_name, cdir, p):
    from skypilot_tpu.serve import serve_state
    name = p["service_name"]
    if serve_state.get_service(name) is None:
        raise _err("ServeError", f"no service {name!r}")
    version = serve_state.update_service(name, p["spec"], p["task_config"])
    return {"version": version}


def _m_serve_status(cluster_name, cdir, p):
    from skypilot_tpu.serve import serve_state
    name = p.get("service_name")
    services = ([serve_state.get_service(name)] if name
                else serve_state.list_services())
    out = []
    for s in services:
        if s is None:
            continue
        alive = False
        if s.get("controller_pid") is not None:
            try:
                os.kill(s["controller_pid"], 0)
                alive = True
            except OSError:
                pass
        replicas = [_serialize_enum_rec(r)
                    for r in serve_state.list_replicas(s["name"])]
        out.append(dict(_serialize_enum_rec(s), replicas=replicas,
                        controller_alive=alive))
    return out


def _m_serve_down(cluster_name, cdir, p):
    from skypilot_tpu.serve import serve_state
    name = p["service_name"]
    rec = serve_state.get_service(name)
    if rec is None:
        return {"down": name, "missing": True}
    serve_state.set_service_status(
        name, serve_state.ServiceStatus.SHUTTING_DOWN)
    pid = rec["controller_pid"]
    alive = False
    if pid is not None:
        try:
            os.kill(pid, 0)
            alive = True
        except OSError:
            pass
    return {"down": name, "controller_alive": alive}


def _m_serve_remove(cluster_name, cdir, p):
    from skypilot_tpu.serve import serve_state
    serve_state.remove_service(p["service_name"])
    return {"removed": p["service_name"]}


_METHODS: Dict[str, Callable] = {
    "ping": _m_ping,
    "init_cluster": _m_init_cluster,
    "submit": _m_submit,
    "get_job": _m_get_job,
    "list_jobs": _m_list_jobs,
    "cancel": _m_cancel,
    "read_logs": _m_read_logs,
    "set_autostop": _m_set_autostop,
    "is_idle": _m_is_idle,
    "get_metrics": _m_get_metrics,
    "healthz": _m_healthz,
    "jobs_submit": _m_jobs_submit,
    "jobs_list": _m_jobs_list,
    "jobs_get": _m_jobs_get,
    "jobs_cancel": _m_jobs_cancel,
    "jobs_log": _m_jobs_log,
    "jobs_tail": _m_jobs_tail,
    "serve_up": _m_serve_up,
    "serve_update": _m_serve_update,
    "serve_status": _m_serve_status,
    "serve_down": _m_serve_down,
    "serve_remove": _m_serve_remove,
}


class RpcMethodError(Exception):
    """Carries a symbolic error type back over the wire."""

    def __init__(self, etype: str, message: str):
        super().__init__(message)
        self.etype = etype


def _err(etype: str, message: str) -> RpcMethodError:
    return RpcMethodError(etype, message)


def dispatch(cluster_name: str, method: str,
             params: Dict[str, Any]) -> Any:
    fn = _METHODS.get(method)
    if fn is None:
        raise _err("RpcError", f"unknown method {method!r}")
    cdir = topology.cluster_dir(cluster_name)
    return fn(cluster_name, cdir, params)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--cluster", required=True)
    args = ap.parse_args()
    tracing.set_process_name("rpc")
    method = "?"
    try:
        req = json.loads(sys.stdin.read() or "{}")
        method = req.get("method", "ping")
        # Install the caller's trace context as this process's root —
        # via the env so daemons spawned here (skylet, driver,
        # controllers: _child_env copies os.environ) inherit it and
        # their lifecycle events join the originating request's trace.
        if tracing.parse_traceparent(req.get("trace")) is not None:
            os.environ[tracing.ENV_VAR] = req["trace"]
        with tracing.start_span(f"rpc.dispatch:{method}",
                                attrs={"cluster": args.cluster}):
            result = dispatch(args.cluster, method,
                              req.get("params") or {})
        resp = {"ok": True, "result": result}
    except RpcMethodError as e:
        tracing.add_event("rpc.error",
                          attrs={"method": method, "etype": e.etype,
                                 "message": str(e)[:500]})
        resp = {"ok": False, "error": str(e), "etype": e.etype}
    except Exception as e:  # noqa: BLE001 — the wire must always answer
        tracing.add_event("rpc.error",
                          attrs={"method": method,
                                 "etype": type(e).__name__,
                                 "message": str(e)[:500]})
        resp = {"ok": False, "error": f"{type(e).__name__}: {e}",
                "etype": type(e).__name__}
    sys.stdout.write(MARKER + json.dumps(resp) + "\n")
    sys.stdout.flush()


if __name__ == "__main__":
    main()
