"""Typed cluster RPC: the single client <-> cluster seam.

One call = one JSON request on stdin, one framed JSON response on
stdout, executed on the cluster head through the command runner as
``python -S -m skypilot_tpu.runtime.rpc --cluster <name>``. This
replaces the reference's string-codegen-over-SSH protocol
(sky/skylet/job_lib.py:930-1077 JobLibCodeGen emits `python -c`
snippets) with plain data — no generated source, stable wire format,
symmetrical client in runtime/rpc_client.py.

Everything here is stdlib-only and runs under ``python -S`` (~20ms per
call vs multi-second site/jax imports), so polling RPCs are cheap.

The job DB, run scripts, logs, autostop config, and the driver/skylet
processes all live under the HEAD's home — the cluster survives client
death, serves any number of clients, and autostops by itself
(reference: sky/skylet/skylet.py + events.py:102).
"""

from __future__ import annotations

import argparse
import json
import os
import shlex
import subprocess
import sys
import time
from typing import Any, Callable, Dict

from skypilot_tpu.runtime import constants, job_queue, topology
from skypilot_tpu.utils import command_runner

MARKER = "SKYTPU-RPC1 "


def _db(cdir: str) -> str:
    return os.path.join(cdir, constants.JOB_DB)


def _serialize_job(job: Dict[str, Any]) -> Dict[str, Any]:
    out = dict(job)
    out["status"] = job["status"].value
    return out


def _child_env() -> Dict[str, str]:
    """Env for head-side daemons (driver, skylet): framework importable,
    head home pinned."""
    from skypilot_tpu.utils import paths
    env = dict(os.environ)
    env["PYTHONPATH"] = (command_runner.PKG_PARENT + os.pathsep +
                         env.get("PYTHONPATH", ""))
    env["SKYPILOT_TPU_HOME"] = paths.home()
    return env


def _spawn_detached(argv, log_path: str) -> int:
    os.makedirs(os.path.dirname(log_path), exist_ok=True)
    with open(log_path, "ab") as f:
        proc = subprocess.Popen(argv, stdout=f, stderr=subprocess.STDOUT,
                                start_new_session=True, env=_child_env())
    return proc.pid


def _pid_alive(pidfile: str) -> bool:
    if not os.path.exists(pidfile):
        return False
    try:
        os.kill(int(open(pidfile).read().strip()), 0)
        return True
    except (OSError, ValueError):
        return False


def _ensure_skylet(cluster_name: str, cdir: str) -> None:
    pidfile = os.path.join(cdir, "skylet.pid")
    if _pid_alive(pidfile):
        return
    pid = _spawn_detached(
        [sys.executable, "-S", "-m", "skypilot_tpu.runtime.skylet",
         "--cluster-name", cluster_name],
        os.path.join(cdir, "skylet.log"))
    with open(pidfile, "w") as f:
        f.write(str(pid))


# ---------------------------------------------------------------------------
# Methods. Each takes (cluster_name, cdir, params) and returns a
# JSON-serializable result.

def _m_ping(cluster_name, cdir, p):
    return {"pong": True, "home": os.path.dirname(os.path.dirname(cdir))}


def _m_init_cluster(cluster_name, cdir, p):
    meta = p["meta"]
    if meta.get("launched_at") is None:
        meta["launched_at"] = time.time()
    topology.save(cdir, meta)
    os.makedirs(os.path.join(cdir, "logs"), exist_ok=True)
    # The skylet is spawned lazily by set_autostop; on re-init (cluster
    # restart) a persisted autostop config must get its skylet back.
    if os.path.exists(os.path.join(cdir, topology.AUTOSTOP_CONFIG)):
        _ensure_skylet(cluster_name, cdir)
    return {"initialized": True}


def _m_submit(cluster_name, cdir, p):
    job_id = job_queue.add_job(
        _db(cdir), p.get("name"), "",
        metadata={"num_nodes": p.get("num_nodes", 1),
                  "workdir": bool(p.get("workdir", False))})
    script_path = os.path.join(cdir,
                               constants.RUN_SCRIPT.format(job_id=job_id))
    with open(script_path, "w") as f:
        f.write(p["script"])
    job_queue.set_run_cmd(_db(cdir), job_id,
                          f"bash {shlex.quote(script_path)}")
    pid = _spawn_detached(
        [sys.executable, "-S", "-m", "skypilot_tpu.runtime.driver",
         "--cluster-name", cluster_name, "--job-id", str(job_id)],
        os.path.join(cdir, "logs", f"driver-{job_id}.log"))
    return {"job_id": job_id, "driver_pid": pid}


def _m_get_job(cluster_name, cdir, p):
    job = job_queue.get_job(_db(cdir), int(p["job_id"]))
    return _serialize_job(job) if job else None


def _m_list_jobs(cluster_name, cdir, p):
    return [_serialize_job(j) for j in job_queue.list_jobs(_db(cdir))]


def _m_cancel(cluster_name, cdir, p):
    job_id = int(p["job_id"])
    job = job_queue.get_job(_db(cdir), job_id)
    if job is None:
        raise _err("JobNotFoundError", f"no job {job_id}")
    job_queue.set_status(_db(cdir), job_id, job_queue.JobStatus.CANCELLED)
    # The driver notices CANCELLED within one poll; also kill the job
    # processes directly in case the driver itself died.
    if job["pids"]:
        try:
            meta = topology.load(cdir)
            runners = topology.build_runners(meta)
            for runner, pid in zip(runners, job["pids"]):
                runner.kill(pid)
        except (OSError, NotImplementedError):
            pass
    return {"cancelled": job_id}


def _m_read_logs(cluster_name, cdir, p):
    job_id = int(p["job_id"])
    job = job_queue.get_job(_db(cdir), job_id)
    if job is None:
        raise _err("JobNotFoundError", f"no job {job_id}")
    log_dir = os.path.join(cdir, "logs",
                           constants.LOG_DIR.format(job_id=job_id))
    offsets = {str(k): int(v) for k, v in (p.get("offsets") or {}).items()}
    chunks: Dict[str, str] = {}
    if os.path.isdir(log_dir):
        for fname in sorted(os.listdir(log_dir)):
            if not fname.startswith("rank-"):
                continue
            fpath = os.path.join(log_dir, fname)
            off = offsets.get(fname, 0)
            try:
                with open(fpath, "rb") as f:
                    f.seek(off)
                    data = f.read()
            except OSError:
                continue
            if data:
                # Hold back a trailing partial UTF-8 sequence so a
                # multi-byte char split across two polls is never
                # corrupted; the held bytes re-read on the next call.
                data = _trim_partial_utf8(data)
            if data:
                chunks[fname] = data.decode("utf-8", errors="replace")
                offsets[fname] = off + len(data)
            else:
                offsets.setdefault(fname, off)
    return {"status": job["status"].value, "chunks": chunks,
            "offsets": offsets}


def _trim_partial_utf8(data: bytes) -> bytes:
    """Drop a trailing incomplete UTF-8 sequence (at most 3 bytes)."""
    for back in range(1, min(4, len(data) + 1)):
        b = data[-back]
        if b < 0x80:        # ASCII: complete
            return data
        if b >= 0xC0:       # lead byte: complete iff sequence fits
            need = 2 if b < 0xE0 else 3 if b < 0xF0 else 4
            return data if back >= need else data[:-back]
        # else continuation byte: keep looking back
    return data


def _m_set_autostop(cluster_name, cdir, p):
    cfg_path = os.path.join(cdir, topology.AUTOSTOP_CONFIG)
    idle = p.get("idle_minutes")
    if idle is None or idle < 0:
        try:
            os.remove(cfg_path)
        except OSError:
            pass
    else:
        tmp = cfg_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"idle_minutes": idle, "down": bool(p.get("down")),
                       "set_at": time.time()}, f)
        os.replace(tmp, cfg_path)
        _ensure_skylet(cluster_name, cdir)
    return {"autostop": idle}


def _m_is_idle(cluster_name, cdir, p):
    return {"idle": job_queue.is_idle(_db(cdir))}


_METHODS: Dict[str, Callable] = {
    "ping": _m_ping,
    "init_cluster": _m_init_cluster,
    "submit": _m_submit,
    "get_job": _m_get_job,
    "list_jobs": _m_list_jobs,
    "cancel": _m_cancel,
    "read_logs": _m_read_logs,
    "set_autostop": _m_set_autostop,
    "is_idle": _m_is_idle,
}


class RpcMethodError(Exception):
    """Carries a symbolic error type back over the wire."""

    def __init__(self, etype: str, message: str):
        super().__init__(message)
        self.etype = etype


def _err(etype: str, message: str) -> RpcMethodError:
    return RpcMethodError(etype, message)


def dispatch(cluster_name: str, method: str,
             params: Dict[str, Any]) -> Any:
    fn = _METHODS.get(method)
    if fn is None:
        raise _err("RpcError", f"unknown method {method!r}")
    cdir = topology.cluster_dir(cluster_name)
    return fn(cluster_name, cdir, params)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--cluster", required=True)
    args = ap.parse_args()
    try:
        req = json.loads(sys.stdin.read() or "{}")
        result = dispatch(args.cluster, req.get("method", "ping"),
                          req.get("params") or {})
        resp = {"ok": True, "result": result}
    except RpcMethodError as e:
        resp = {"ok": False, "error": str(e), "etype": e.etype}
    except Exception as e:  # noqa: BLE001 — the wire must always answer
        resp = {"ok": False, "error": f"{type(e).__name__}: {e}",
                "etype": type(e).__name__}
    sys.stdout.write(MARKER + json.dumps(resp) + "\n")
    sys.stdout.flush()


if __name__ == "__main__":
    main()
