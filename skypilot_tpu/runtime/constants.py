"""The env contract injected into every job process.

Reference parity: the torchrun-oriented contract at
sky/skylet/constants.py:319-322 (SKYPILOT_NODE_RANK / NODE_IPS /
NUM_NODES / NUM_GPUS_PER_NODE). TPU-native replacement: the variables a
JAX program needs for ``jax.distributed.initialize`` — coordinator
address, process count, process id — are injected directly, so user code
can simply call ``jax.distributed.initialize()`` with no arguments.
"""

# Framework-level contract (node = logical node; host = slice worker VM).
ENV_NODE_RANK = "SKYTPU_NODE_RANK"
ENV_NODE_IPS = "SKYTPU_NODE_IPS"          # newline-separated head IPs
ENV_NUM_NODES = "SKYTPU_NUM_NODES"
ENV_HOST_ID = "SKYTPU_HOST_ID"            # global host index
ENV_NUM_HOSTS = "SKYTPU_NUM_HOSTS"
ENV_WORKER_ID = "SKYTPU_WORKER_ID"        # index within the slice
ENV_CLUSTER = "SKYTPU_CLUSTER_NAME"
ENV_JOB_ID = "SKYTPU_INTERNAL_JOB_ID"

# jax.distributed contract — JAX_COORDINATOR_ADDRESS is read natively
# by jax.distributed.initialize; the process count/id pair is consumed
# by parallel/distributed.initialize_from_env().
ENV_COORDINATOR = "JAX_COORDINATOR_ADDRESS"
ENV_NUM_PROCESSES = "JAX_NUM_PROCESSES"
ENV_PROCESS_ID = "JAX_PROCESS_ID"

# Multislice (DCN) contract — read by libtpu on real multislice TPU
# hardware; one logical node == one slice, so slice id == node rank.
# Reference parity: none (the reference never wired multislice).
ENV_MEGASCALE_COORDINATOR = "MEGASCALE_COORDINATOR_ADDRESS"
ENV_MEGASCALE_NUM_SLICES = "MEGASCALE_NUM_SLICES"
ENV_MEGASCALE_SLICE_ID = "MEGASCALE_SLICE_ID"

COORDINATOR_PORT = 8476
MEGASCALE_PORT = 8080

JOB_DB = "jobs.db"            # per-cluster job queue (head host)
RUN_SCRIPT = "job_{job_id}.sh"
LOG_DIR = "job_{job_id}"      # per-job log dir, rank-<host>.log inside
