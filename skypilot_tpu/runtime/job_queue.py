"""Per-cluster job queue (sqlite), FIFO-scheduled.

Reference parity: sky/skylet/job_lib.py (JobStatus :121, FIFOScheduler
:276, sqlite jobs.db). Differences: no codegen-over-SSH RPC — the
client talks to this module through the backend's typed calls, and the
DB lives in the cluster dir (local provider) or on the head host (gcp),
accessed via the command runner.
"""

from __future__ import annotations

import contextlib
import enum
import json
import os
import sqlite3

from skypilot_tpu.utils import db
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import chaos
from skypilot_tpu.observability import metrics as obs_metrics

JOB_TRANSITIONS = obs_metrics.counter(
    "skytpu_jobs_transitions_total",
    "Job status transitions recorded in this process, by new status",
    labelnames=("status",))
JOBS_BY_STATE = obs_metrics.gauge(
    "skytpu_jobs_by_state",
    "Jobs in the cluster job DB by status (refreshed by "
    "update_state_gauges — the skylet tick and /metrics scrapes)",
    labelnames=("status",))


class JobStatus(enum.Enum):
    INIT = "INIT"
    PENDING = "PENDING"
    SETTING_UP = "SETTING_UP"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    FAILED_SETUP = "FAILED_SETUP"
    CANCELLED = "CANCELLED"

    def is_terminal(self) -> bool:
        return self in (JobStatus.SUCCEEDED, JobStatus.FAILED,
                        JobStatus.FAILED_SETUP, JobStatus.CANCELLED)


_SCHEMA = """
CREATE TABLE IF NOT EXISTS jobs (
    job_id INTEGER PRIMARY KEY AUTOINCREMENT,
    name TEXT,
    submitted_at REAL,
    started_at REAL,
    ended_at REAL,
    status TEXT,
    run_cmd TEXT,
    metadata TEXT,
    pids TEXT
);
"""


@contextlib.contextmanager
def _db(db_path: str):
    os.makedirs(os.path.dirname(db_path), exist_ok=True)
    conn = db.connect(db_path, timeout=10)
    conn.executescript(_SCHEMA)
    try:
        yield conn
        conn.commit()
    finally:
        conn.close()


def add_job(db_path: str, name: Optional[str], run_cmd: str,
            metadata: Optional[Dict[str, Any]] = None) -> int:
    with _db(db_path) as c:
        cur = c.execute(
            "INSERT INTO jobs (name, submitted_at, status, run_cmd, metadata)"
            " VALUES (?,?,?,?,?)",
            (name, time.time(), JobStatus.PENDING.value, run_cmd,
             json.dumps(metadata or {})))
        job_id = int(cur.lastrowid)
    # Count only after the INSERT committed: the metric must not claim
    # transitions the DB never saw.
    JOB_TRANSITIONS.labels(status=JobStatus.PENDING.value).inc()
    return job_id


def set_status(db_path: str, job_id: int, status: JobStatus) -> None:
    # Before the write: an injected fault means the transition never
    # reached the DB, exactly like a crash between decide and commit.
    chaos.point("jobs.transition", status=status.value, job_id=job_id)
    now = time.time()
    with _db(db_path) as c:
        if status == JobStatus.RUNNING:
            cur = c.execute(
                "UPDATE jobs SET status=?, started_at=? WHERE job_id=?",
                (status.value, now, job_id))
        elif status.is_terminal():
            cur = c.execute(
                "UPDATE jobs SET status=?, ended_at=? WHERE job_id=?",
                (status.value, now, job_id))
        else:
            cur = c.execute("UPDATE jobs SET status=? WHERE job_id=?",
                            (status.value, job_id))
        applied = cur.rowcount > 0
    if applied:
        JOB_TRANSITIONS.labels(status=status.value).inc()


def set_run_cmd(db_path: str, job_id: int, run_cmd: str) -> None:
    with _db(db_path) as c:
        c.execute("UPDATE jobs SET run_cmd=? WHERE job_id=?",
                  (run_cmd, job_id))


def set_pids(db_path: str, job_id: int, pids: List[int]) -> None:
    with _db(db_path) as c:
        c.execute("UPDATE jobs SET pids=? WHERE job_id=?",
                  (json.dumps(pids), job_id))


def get_job(db_path: str, job_id: int) -> Optional[Dict[str, Any]]:
    with _db(db_path) as c:
        row = c.execute(
            "SELECT job_id, name, submitted_at, started_at, ended_at, status,"
            " run_cmd, metadata, pids FROM jobs WHERE job_id=?",
            (job_id,)).fetchone()
    return _to_rec(row) if row else None


def list_jobs(db_path: str) -> List[Dict[str, Any]]:
    with _db(db_path) as c:
        rows = c.execute(
            "SELECT job_id, name, submitted_at, started_at, ended_at, status,"
            " run_cmd, metadata, pids FROM jobs ORDER BY job_id DESC"
        ).fetchall()
    return [_to_rec(r) for r in rows]


def next_pending(db_path: str) -> Optional[Dict[str, Any]]:
    """FIFO: oldest PENDING job, only if nothing is currently active."""
    with _db(db_path) as c:
        active = c.execute(
            "SELECT COUNT(*) FROM jobs WHERE status IN (?,?)",
            (JobStatus.RUNNING.value, JobStatus.SETTING_UP.value)).fetchone()[0]
        if active:
            return None
        row = c.execute(
            "SELECT job_id, name, submitted_at, started_at, ended_at, status,"
            " run_cmd, metadata, pids FROM jobs WHERE status=?"
            " ORDER BY job_id ASC LIMIT 1",
            (JobStatus.PENDING.value,)).fetchone()
    return _to_rec(row) if row else None


def is_idle(db_path: str) -> bool:
    with _db(db_path) as c:
        n = c.execute(
            "SELECT COUNT(*) FROM jobs WHERE status IN (?,?,?)",
            (JobStatus.PENDING.value, JobStatus.SETTING_UP.value,
             JobStatus.RUNNING.value)).fetchone()[0]
    return n == 0


def last_activity_time(db_path: str) -> float:
    with _db(db_path) as c:
        row = c.execute(
            "SELECT MAX(COALESCE(ended_at, started_at, submitted_at))"
            " FROM jobs").fetchone()
    return float(row[0]) if row and row[0] else 0.0


def update_state_gauges(db_path: str) -> Dict[str, int]:
    """Refresh ``skytpu_jobs_by_state`` from the DB (every status gets
    a sample, zeroed when empty, so scrapes see transitions back to
    zero). Returns the counts for callers that want them."""
    counts = {s.value: 0 for s in JobStatus}
    try:
        with _db(db_path) as c:
            for status, n in c.execute(
                    "SELECT status, COUNT(*) FROM jobs GROUP BY status"):
                if status in counts:
                    counts[status] = n
    except (sqlite3.Error, OSError):
        return counts   # daemon metrics must never take the tick down
    for status, n in counts.items():
        JOBS_BY_STATE.labels(status=status).set(n)
    return counts


def _to_rec(row) -> Dict[str, Any]:
    (job_id, name, sub, start, end, status, run_cmd, meta, pids) = row
    return {
        "job_id": job_id, "name": name, "submitted_at": sub,
        "started_at": start, "ended_at": end,
        "status": JobStatus(status), "run_cmd": run_cmd,
        "metadata": json.loads(meta or "{}"),
        "pids": json.loads(pids) if pids else [],
    }
