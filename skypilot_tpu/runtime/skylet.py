"""Per-cluster skylet daemon: the autostop event loop.

Reference parity: sky/skylet/skylet.py + events.py (AutostopEvent :102 —
idle-minutes tracking, invoking stop/down from the cluster itself).
Spawned detached by the backend at provision/start time, one per
cluster; exits when the cluster record disappears or stops.

Currently runs client-side next to the state DB (correct for the local
provider and for client-managed GCP clusters); moving it onto the head
host alongside a synced config is the multi-host hardening step tracked
for the GCP runtime milestone.
"""

from __future__ import annotations

import argparse
import os
import sys
import time


def run(cluster_name: str, poll_interval: float) -> int:
    from skypilot_tpu import core, state
    from skypilot_tpu.runtime import constants, job_queue
    from skypilot_tpu.utils import paths

    while True:
        rec = state.get_cluster(cluster_name)
        if rec is None or rec["status"] != state.ClusterStatus.UP:
            return 0
        idle_minutes = rec["autostop_minutes"]
        if idle_minutes is not None and idle_minutes >= 0:
            db = os.path.join(paths.cluster_dir(cluster_name),
                              constants.JOB_DB)
            last = max(job_queue.last_activity_time(db), rec["launched_at"])
            if job_queue.is_idle(db) and \
                    time.time() - last > idle_minutes * 60:
                try:
                    if rec["autostop_down"]:
                        core.down(cluster_name)
                    else:
                        core.stop(cluster_name)
                except Exception as e:  # noqa: BLE001
                    print(f"autostop failed: {e}", file=sys.stderr)
                return 0
        time.sleep(poll_interval)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--cluster-name", required=True)
    ap.add_argument("--poll-interval", type=float,
                    default=float(os.environ.get("SKYTPU_SKYLET_POLL", "10")))
    args = ap.parse_args()
    sys.exit(run(args.cluster_name, args.poll_interval))


if __name__ == "__main__":
    main()
