"""Cluster-side skylet daemon: the autostop event loop.

Reference parity: sky/skylet/skylet.py + events.py (AutostopEvent :102 —
idle-minutes tracking, invoking stop/down from the cluster itself).
Runs ON THE CLUSTER HEAD (spawned by rpc ``init_cluster`` /
``set_autostop``), reads only cluster-side state (cluster.json,
autostop.json, jobs.db), and calls the provider API from the cluster —
so autostop fires with every client laptop closed, exactly like the
reference's on-VM AutostopEvent. Runs under ``python -S``;
stdlib-only imports (the zero-SDK REST providers keep that true even
for the cloud call).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from skypilot_tpu import chaos
from skypilot_tpu.observability import metrics as obs_metrics
from skypilot_tpu.observability import tracing
from skypilot_tpu.runtime import constants, job_queue, topology
from skypilot_tpu.utils import timeline

SKYLET_TICKS = obs_metrics.counter(
    "skytpu_skylet_ticks_total", "Skylet poll-loop iterations")
SKYLET_HEARTBEAT = obs_metrics.gauge(
    "skytpu_skylet_last_tick_timestamp_seconds",
    "Unix time of the skylet's last poll tick; scrape-side heartbeat "
    "age = now - this")
AUTOSTOP_FIRED = obs_metrics.counter(
    "skytpu_autostop_fired_total",
    "Autostop stop/terminate actions taken", labelnames=("down",))


def _read_autostop(cdir: str):
    try:
        with open(os.path.join(cdir, topology.AUTOSTOP_CONFIG)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def observe_tick(db: str) -> None:
    """Per-tick observability: liveness + job-state gauges for scrapers
    of this daemon's registry, an atomic exposition-file write (the
    skylet has no HTTP surface — the rpc ``get_metrics``/``healthz``
    methods and the fleet federation tier read ``metrics.prom``, and
    its heartbeat gauge is what the health model derives staleness
    from), and a throttled atomic trace flush (save_periodic skips
    ticks with little news — re-serializing the whole buffer every poll
    would eat short poll intervals alive)."""
    SKYLET_TICKS.inc()
    SKYLET_HEARTBEAT.set(time.time())
    job_queue.update_state_gauges(db)
    try:
        from skypilot_tpu.observability import aggregate
        obs_metrics.write_exposition_file(
            os.path.join(os.path.dirname(db), aggregate.METRICS_FILENAME))
        timeline.save_periodic()
        tracing.flush_periodic()
    except OSError:
        pass    # an unwritable trace path must not take the tick down


def run(cluster_name: str, poll_interval: float) -> int:
    cdir = topology.cluster_dir(cluster_name)
    db = os.path.join(cdir, constants.JOB_DB)
    while True:
        # A fault here kills the tick before any observation/autostop
        # work — the chaos stand-in for a wedged/crashed skylet (the
        # heartbeat-staleness SLO is what must catch it).
        chaos.point("skylet.tick", cluster=cluster_name)
        observe_tick(db)
        try:
            meta = topology.load(cdir)
        except (OSError, ValueError):
            return 0  # cluster record gone: torn down
        cfg = _read_autostop(cdir)
        if cfg is None:
            # Autostop unset (or cancelled): nothing to supervise. The
            # rpc set_autostop method respawns us when a config appears.
            return 0
        if cfg.get("idle_minutes", -1) >= 0:
            # Attribute autostop outcomes to the request that ARMED
            # autostop (context persisted in the config by the
            # set_autostop rpc), never this daemon's spawn-time root —
            # a pre-upgrade config without the field records the events
            # unattributed (DETACHED) rather than misattributed.
            arm_ctx = (tracing.parse_traceparent(cfg.get("trace"))
                       or tracing.DETACHED)
            last = max(job_queue.last_activity_time(db),
                       meta.get("launched_at") or 0.0,
                       cfg.get("set_at") or 0.0)
            if (job_queue.is_idle(db)
                    and time.time() - last > cfg["idle_minutes"] * 60):
                topology.apply_provider_env(meta)
                try:
                    from skypilot_tpu import provision
                    if cfg.get("down"):
                        provision.terminate_instances(
                            meta["provider"], cluster_name, meta["zone"])
                    else:
                        provision.stop_instances(
                            meta["provider"], cluster_name, meta["zone"])
                    AUTOSTOP_FIRED.labels(
                        down=str(bool(cfg.get("down")))).inc()
                    tracing.add_event(
                        "skylet.autostop_fired",
                        attrs={"cluster": cluster_name,
                               "down": bool(cfg.get("down"))},
                        ctx=arm_ctx, echo=True)
                    with open(os.path.join(cdir, "autostop_fired"),
                              "w") as f:
                        f.write(json.dumps(
                            {"at": time.time(), "down": cfg.get("down")}))
                    timeline.save_now()
                    return 0
                except Exception as e:  # noqa: BLE001
                    if getattr(e, "no_failover", False):
                        # Permanent refusal (e.g. multislice/multi-host
                        # TPU cannot stop): retrying forever would spam
                        # the cloud API while the user believes autostop
                        # is armed. Disarm loudly — a typed event record
                        # (echoed to skylet.log) instead of a bare
                        # print, so the failure shows up in `skytpu
                        # trace` for the request that armed autostop.
                        tracing.add_event(
                            "skylet.autostop_disarmed",
                            attrs={"cluster": cluster_name,
                                   "error_type": type(e).__name__,
                                   "message": str(e)[:500]},
                            ctx=arm_ctx, echo=True)
                        with open(os.path.join(cdir, "autostop_failed"),
                                  "w") as f:
                            f.write(str(e))
                        try:
                            os.remove(os.path.join(
                                cdir, topology.AUTOSTOP_CONFIG))
                        except OSError:
                            pass
                        return 1
                    # Transient cloud error: stay alive and retry next
                    # tick — exiting here would permanently disarm
                    # autostop and let an idle cluster bill forever.
                    tracing.add_event(
                        "skylet.autostop_retry",
                        attrs={"cluster": cluster_name,
                               "error_type": type(e).__name__,
                               "message": str(e)[:500]},
                        ctx=arm_ctx, echo=True)
        time.sleep(poll_interval)


def main() -> None:
    tracing.set_process_name("skylet")
    ap = argparse.ArgumentParser()
    ap.add_argument("--cluster-name", required=True)
    ap.add_argument("--poll-interval", type=float,
                    default=float(os.environ.get("SKYTPU_SKYLET_POLL", "10")))
    args = ap.parse_args()
    sys.exit(run(args.cluster_name, args.poll_interval))


if __name__ == "__main__":
    main()
