"""Task: the user-facing workload declaration (YAML or Python).

Reference parity: sky/task.py (Task:192, from_yaml_config:432,
set_resources:717, file_mounts :798, storage mounts :1004, ``>>``
chaining :1263). TPU-first deltas: ``num_nodes`` counts *logical* nodes
(a whole TPU slice is one node; the runtime fans out to its hosts), and
the run command receives the ``jax.distributed`` env contract instead of
the torchrun MASTER_ADDR one.
"""

from __future__ import annotations

import os
import re
from typing import Any, Callable, Dict, List, Optional, Union

import yaml

from skypilot_tpu import exceptions
from skypilot_tpu.resources import Resources

_NAME_RE = re.compile(r"^[a-zA-Z0-9][a-zA-Z0-9._-]*$")

RunCmd = Union[str, Callable[[int, List[str]], Optional[str]], None]


class Task:
    def __init__(self,
                 name: Optional[str] = None,
                 *,
                 setup: Optional[str] = None,
                 run: RunCmd = None,
                 envs: Optional[Dict[str, str]] = None,
                 workdir: Optional[str] = None,
                 num_nodes: int = 1,
                 file_mounts: Optional[Dict[str, str]] = None,
                 storage_mounts: Optional[Dict[str, Any]] = None):
        if name is not None and not _NAME_RE.match(name):
            raise exceptions.InvalidTaskError(f"invalid task name {name!r}")
        self.name = name
        self.setup = setup
        self.run = run
        self.envs = dict(envs or {})
        self.workdir = workdir
        self.num_nodes = num_nodes
        self.file_mounts = dict(file_mounts or {})
        self.storage_mounts = dict(storage_mounts or {})
        self.resources: List[Resources] = [Resources()]
        self.service: Optional[Any] = None  # serve.SkyServiceSpec
        # Wall seconds on ONE v5e-chip-equivalent (the optimizer
        # scales it by each candidate's compute units); None = unknown
        # (flat default, no cross-accelerator scaling).
        self.estimated_runtime_seconds: Optional[float] = None
        # Output data this task hands to its DAG successor, in GB —
        # feeds the optimizer's cross-region egress term.
        self.estimated_outputs_gb: Optional[float] = None
        # Per-task global-config overrides (reference:
        # experimental.config_overrides, sky/skypilot_config.py).
        self.config_overrides: Optional[Dict[str, Any]] = None

    # -- builder API -------------------------------------------------------
    def set_resources(self, resources: Union[Resources, List[Resources]]):
        self.resources = ([resources] if isinstance(resources, Resources)
                          else list(resources))
        return self

    def set_file_mounts(self, mounts: Optional[Dict[str, str]]):
        self.file_mounts = dict(mounts or {})
        return self

    def update_envs(self, envs: Dict[str, str]):
        self.envs.update(envs)
        return self

    def set_service(self, service):
        self.service = service
        return self

    # -- yaml --------------------------------------------------------------
    @classmethod
    def from_yaml_config(cls, config: Dict[str, Any]) -> "Task":
        config = dict(config or {})
        from skypilot_tpu.utils import schemas
        schemas.validate_task_config(config)
        resources_cfg = config.pop("resources", None)
        service_cfg = config.pop("service", None)
        config_overrides = config.pop("config_overrides", None)
        storage_mounts = config.pop("storage_mounts", None)
        task = cls(
            name=config.pop("name", None),
            setup=config.pop("setup", None),
            run=config.pop("run", None),
            envs={k: "" if v is None else str(v)
                  for k, v in (config.pop("envs", None) or {}).items()},
            workdir=config.pop("workdir", None),
            num_nodes=int(config.pop("num_nodes", 1) or 1),
            file_mounts=config.pop("file_mounts", None),
            storage_mounts=storage_mounts,
        )
        task.config_overrides = config_overrides
        ert = config.pop("estimated_runtime_seconds", None)
        if ert is not None:
            task.estimated_runtime_seconds = float(ert)
        eog = config.pop("estimated_outputs_gb", None)
        if eog is not None:
            task.estimated_outputs_gb = float(eog)
        if config:
            raise exceptions.InvalidTaskError(
                f"unknown task fields: {sorted(config)}")
        if resources_cfg is not None:
            if isinstance(resources_cfg, list):
                task.set_resources(
                    [Resources.from_yaml_config(r) for r in resources_cfg])
            else:
                task.set_resources(Resources.from_yaml_config(resources_cfg))
        if service_cfg is not None:
            from skypilot_tpu.serve import service_spec
            task.set_service(
                service_spec.SkyServiceSpec.from_yaml_config(service_cfg))
        return task

    @classmethod
    def from_yaml(cls, path: str) -> "Task":
        with open(os.path.expanduser(path)) as f:
            config = yaml.safe_load(f)
        if not isinstance(config, dict):
            raise exceptions.InvalidTaskError(
                f"{path} did not parse to a task dict")
        return cls.from_yaml_config(config)

    @classmethod
    def from_yaml_all(cls, path: str) -> List["Task"]:
        """Every task in a (possibly multi-document) YAML — the
        reference's managed-job PIPELINE form: tasks separated by
        ``---`` run sequentially under one job (reference:
        sky/jobs/controller.py:68 iterates dag.tasks)."""
        with open(os.path.expanduser(path)) as f:
            docs = [d for d in yaml.safe_load_all(f) if d is not None]
        if not docs:
            raise exceptions.InvalidTaskError(f"{path} is empty")
        for d in docs:
            if not isinstance(d, dict):
                raise exceptions.InvalidTaskError(
                    f"{path}: document {d!r} is not a task dict")
        return [cls.from_yaml_config(d) for d in docs]

    def to_yaml_config(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        if self.name:
            out["name"] = self.name
        if self.workdir:
            out["workdir"] = self.workdir
        if self.num_nodes != 1:
            out["num_nodes"] = self.num_nodes
        if len(self.resources) == 1:
            out["resources"] = self.resources[0].to_yaml_config()
        else:
            out["resources"] = [r.to_yaml_config() for r in self.resources]
        if self.envs:
            out["envs"] = dict(self.envs)
        if self.setup:
            out["setup"] = self.setup
        if isinstance(self.run, str):
            out["run"] = self.run
        if self.file_mounts:
            out["file_mounts"] = dict(self.file_mounts)
        if self.storage_mounts:
            out["storage_mounts"] = {
                dst: (s.to_yaml_config() if hasattr(s, "to_yaml_config")
                      else s)
                for dst, s in self.storage_mounts.items()}
        if self.service is not None:
            out["service"] = self.service.to_yaml_config()
        if self.config_overrides:
            out["config_overrides"] = dict(self.config_overrides)
        if self.estimated_runtime_seconds is not None:
            out["estimated_runtime_seconds"] = self.estimated_runtime_seconds
        if self.estimated_outputs_gb is not None:
            out["estimated_outputs_gb"] = self.estimated_outputs_gb
        return out

    def to_yaml(self, path: str) -> None:
        with open(os.path.expanduser(path), "w") as f:
            yaml.safe_dump(self.to_yaml_config(), f, sort_keys=False)

    # -- dag chaining ------------------------------------------------------
    def __rshift__(self, other: "Task") -> "Task":
        from skypilot_tpu import dag as dag_lib
        dag = dag_lib.get_current_dag()
        if dag is not None:
            dag.add_edge(self, other)
        return other

    def __repr__(self) -> str:
        r = self.resources[0] if self.resources else None
        return f"Task({self.name or '<unnamed>'}, {r}, nodes={self.num_nodes})"
