"""Declarative resource spec — immutable, catalog-backed.

Reference parity: sky/resources.py (Resources:31 — accelerator
canonicalization :563, TPU defaults :605-629, feasibility via catalog,
cost :1040, less_demanding_than :1146, yaml io :1348). TPU-first deltas:
a TPU *slice* (``tpu-v5p-128``) is one logical resource whose host count
comes from the catalog (the reference bolts this on via
``num_ips_per_node``); topology-aware placement is native, not an
accelerator_args dict.
"""

from __future__ import annotations

import dataclasses
import os
import re
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu import exceptions
from skypilot_tpu.catalog import catalog

_COUNT_RE = re.compile(r"^(\d+(?:\.\d+)?)(\+?)$")


def parse_count(value, what: str) -> Tuple[Optional[float], bool]:
    """'8' -> (8, False); '8+' -> (8, True); None -> (None, False)."""
    if value is None:
        return None, False
    m = _COUNT_RE.match(str(value).strip())
    if not m:
        raise ValueError(f"invalid {what} spec: {value!r} "
                         f"(expected e.g. '8' or '8+')")
    return float(m.group(1)), m.group(2) == "+"


@dataclasses.dataclass(frozen=True)
class Resources:
    """Partial or concrete resource requirement. Immutable; use ``copy``."""

    cloud: Optional[str] = None          # "gcp" | "local"
    region: Optional[str] = None
    zone: Optional[str] = None
    accelerators: Optional[str] = None   # "tpu-v5e-8" | "A100:8"
    cpus: Optional[str] = None           # "8" | "8+"
    memory: Optional[str] = None         # GB, "32" | "32+"
    instance_type: Optional[str] = None
    use_spot: bool = False
    disk_size: int = 256
    image_id: Optional[str] = None
    ports: Optional[Tuple[int, ...]] = None
    labels: Optional[Tuple[Tuple[str, str], ...]] = None
    job_recovery: Optional[str] = None   # managed-jobs strategy name
    # TPU-specific: software version for the runtime (None = per-gen default)
    runtime_version: Optional[str] = None
    _price: Optional[float] = None       # filled on launchable resources

    def __post_init__(self):
        if self.accelerators is not None:
            catalog.parse_accelerator(self.accelerators)  # validate
        parse_count(self.cpus, "cpus")
        parse_count(self.memory, "memory")
        from skypilot_tpu import check as _check
        if self.cloud not in (None, *_check.CLOUDS):
            raise ValueError(f"unknown cloud {self.cloud!r}")
        if self.is_tpu() and self.runtime_version is None:
            object.__setattr__(self, "runtime_version",
                               default_tpu_runtime(self.accelerators))

    # -- classification ----------------------------------------------------
    def is_tpu(self) -> bool:
        return catalog.is_tpu(self.accelerators)

    @property
    def docker_image(self) -> Optional[str]:
        """Container image when ``image_id: docker:<image>`` — the task
        runs inside that container on the VM/TPU-VM (reference:
        sky/resources.py:885 extract_docker_image; provisioning still
        boots the stock VM image underneath)."""
        return extract_docker_image(self.image_id)

    @property
    def accelerator_name(self) -> Optional[str]:
        if self.accelerators is None:
            return None
        return catalog.parse_accelerator(self.accelerators)[0]

    @property
    def accelerator_count(self) -> int:
        if self.accelerators is None:
            return 0
        return catalog.parse_accelerator(self.accelerators)[1]

    def tpu_info(self) -> Dict[str, int]:
        """{'chips', 'hosts'} for a TPU slice."""
        if not self.is_tpu():
            raise ValueError(f"{self} is not a TPU resource")
        return catalog.tpu_slice_info(self.accelerator_name)

    @property
    def hosts_per_node(self) -> int:
        """Physical hosts behind one logical node (TPU pods: >1)."""
        if self.is_tpu() and self.cloud != "local":
            return self.tpu_info()["hosts"]
        return 1

    # -- lifecycle ---------------------------------------------------------
    def copy(self, **overrides) -> "Resources":
        return dataclasses.replace(self, **overrides)

    # -- feasibility / cost -------------------------------------------------
    def launchables(self, blocked: Optional[set] = None) -> List["Resources"]:
        """Concrete per-zone candidates, cheapest first.

        Each returned Resources has cloud/region/zone/instance_type/_price
        filled. ``blocked`` is a set of (cloud, region, zone) triples (zone
        or region may be None = whole region/cloud blocked).
        """
        blocked = blocked or set()
        if self.cloud in ("local", "kubernetes"):
            # Catalog-less clouds: capacity is whatever the machine/
            # cluster has, so the sole candidate is the spec itself
            # (price 0 — kubernetes nodes are owned capacity; the
            # reference prices k8s at 0 too). The local fake cloud can
            # present MULTIPLE zones (SKYTPU_LOCAL_ZONES="zone-a,
            # zone-b") so zone-scoped failover/blocklist paths — the
            # chaos harness's stockout scenarios — run offline.
            if self.cloud == "local":
                zones = [z.strip() for z in os.environ.get(
                    "SKYTPU_LOCAL_ZONES", "local").split(",") if z.strip()]
            else:
                zones = ["default"]
            return [self.copy(region=z, zone=z, _price=0.0)
                    for z in zones
                    if not _is_blocked(self.cloud, z, z, blocked)]
        out = []
        min_cpus, cpus_plus = parse_count(self.cpus, "cpus")
        min_mem, mem_plus = parse_count(self.memory, "memory")
        # None = arbitrage across every catalog cloud (the reference's
        # core value prop: sky/optimizer.py candidates span all enabled
        # clouds); a set cloud restricts the search to it. Once a
        # credential check has run, disabled clouds drop out of the
        # candidate set (no cache -> no restriction: offline dryruns
        # stay credential-free).
        cloud = self.cloud if self.cloud in catalog.CATALOG_CLOUDS else None
        from skypilot_tpu import check as check_lib
        enabled = check_lib.cached_enabled_clouds()
        allowed = None
        if enabled is not None:
            if cloud is not None and cloud not in enabled:
                # No candidates, not an exception: this Resources may be
                # one option of an any-of list whose other entries are
                # feasible (the optimizer's no-feasible-resources error
                # carries the enabled-clouds hint when everything
                # drops out).
                return []
            if cloud is None:
                allowed = [c for c in catalog.CATALOG_CLOUDS
                           if c in enabled]
                if not allowed:
                    return []
        if self.accelerators is None and self.instance_type is None:
            df = catalog.cpu_instance_types(min_cpus or 0, min_mem or 0,
                                            cloud=cloud)
        else:
            name, count = (catalog.parse_accelerator(self.accelerators)
                           if self.accelerators else (None, None))
            df = catalog.offerings(name, count, self.instance_type,
                                   self.region, self.zone, cloud=cloud)
            if min_cpus is not None:
                df = df[df["vcpus"] >= min_cpus] if cpus_plus else \
                    df[df["vcpus"] == min_cpus]
            if min_mem is not None:
                df = df[df["memory_gb"] >= min_mem] if mem_plus else df
        if allowed is not None:
            df = df[df["cloud"].isin(allowed)]
        if self.region is not None:
            df = df[df["region"] == self.region]
        if self.zone is not None:
            df = df[df["zone"] == self.zone]
        price_col = "spot_price" if self.use_spot else "price"
        for _, row in df.sort_values(price_col).iterrows():
            if _is_blocked(row["cloud"], row["region"], row["zone"],
                           blocked):
                continue
            out.append(self.copy(
                cloud=row["cloud"], region=row["region"], zone=row["zone"],
                instance_type=row["instance_type"],
                _price=float(row[price_col])))
        return out

    def get_cost(self, seconds: float) -> float:
        if self._price is None:
            raise ValueError("cost is only defined on launchable resources")
        return self._price * seconds / 3600.0

    @property
    def price(self) -> Optional[float]:
        return self._price

    def less_demanding_than(self, other: "Resources") -> bool:
        """Can a cluster with ``other`` run a task asking for ``self``?"""
        if self.cloud is not None and self.cloud != other.cloud:
            return False
        if self.region is not None and self.region != other.region:
            return False
        if self.zone is not None and self.zone != other.zone:
            return False
        if self.accelerators is not None:
            if other.accelerators is None:
                return False
            sn, sc = catalog.parse_accelerator(self.accelerators)
            on, oc = catalog.parse_accelerator(other.accelerators)
            if sn.lower() != on.lower() or sc > oc:
                return False
        if self.use_spot and not other.use_spot:
            return False
        return True

    # -- serialization -----------------------------------------------------
    def to_yaml_config(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for f in ("cloud", "region", "zone", "accelerators", "cpus",
                  "memory", "instance_type", "image_id", "runtime_version",
                  "job_recovery"):
            v = getattr(self, f)
            if v is not None:
                out[f] = v
        if self.use_spot:
            out["use_spot"] = True
        if self.disk_size != 256:
            out["disk_size"] = self.disk_size
        if self.ports:
            out["ports"] = list(self.ports)
        if self.labels:
            out["labels"] = dict(self.labels)
        return out

    @classmethod
    def from_yaml_config(cls, config: Optional[Dict[str, Any]]) -> "Resources":
        config = dict(config or {})
        ports = config.pop("ports", None)
        labels = config.pop("labels", None)
        accel = config.pop("accelerators", None)
        if isinstance(accel, dict):  # {"A100": 8} form
            (name, cnt), = accel.items()
            accel = f"{name}:{cnt}"
        # Reference-YAML compat: accelerator_args: {runtime_version: X}
        # (sky/resources.py:605-629) maps onto the first-class
        # runtime_version field; other args have no TPU-VM meaning.
        args = config.pop("accelerator_args", None)
        if args:
            extra = set(args) - {"runtime_version"}
            if extra:
                raise exceptions.InvalidTaskError(
                    f"unsupported accelerator_args: {sorted(extra)} "
                    f"(TPU-VM supports runtime_version)")
            config.setdefault("runtime_version",
                              args["runtime_version"])
        known = {f.name for f in dataclasses.fields(cls) if f.name != "_price"}
        unknown = set(config) - known
        if unknown:
            raise exceptions.InvalidTaskError(
                f"unknown resources fields: {sorted(unknown)}")
        for k in ("cpus", "memory"):
            if k in config and config[k] is not None:
                config[k] = str(config[k])
        return cls(
            accelerators=accel,
            ports=tuple(ports) if ports else None,
            labels=tuple(sorted(labels.items())) if labels else None,
            **config)

    def __repr__(self) -> str:
        bits = [self.cloud or "any"]
        if self.accelerators:
            bits.append(self.accelerators)
        if self.instance_type:
            bits.append(self.instance_type)
        if self.zone:
            bits.append(self.zone)
        elif self.region:
            bits.append(self.region)
        if self.use_spot:
            bits.append("[spot]")
        if self._price is not None:
            bits.append(f"${self._price:.2f}/h")
        return f"Resources({', '.join(bits)})"


def extract_docker_image(image_id: Optional[str]) -> Optional[str]:
    """The single owner of the ``docker:`` image_id scheme: returns the
    container image, or None for VM images / unset."""
    if image_id and image_id.startswith("docker:"):
        return image_id[len("docker:"):]
    return None


def _is_blocked(cloud: str, region: str, zone: str, blocked: set) -> bool:
    return ((cloud, None, None) in blocked
            or (cloud, region, None) in blocked
            or (cloud, region, zone) in blocked)


def default_tpu_runtime(accelerator: Optional[str]) -> str:
    """Per-generation TPU VM runtime version (reference:
    sky/resources.py:605-629 fills v2-alpha-tpuv5 etc.)."""
    a = (accelerator or "").lower()
    if "v6e" in a:
        return "v2-alpha-tpuv6e"
    if "v5p" in a:
        return "v2-alpha-tpuv5"
    if "v5e" in a:
        return "v2-alpha-tpuv5-lite"
    return "tpu-ubuntu2204-base"
