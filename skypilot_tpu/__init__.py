"""skypilot_tpu: a TPU-native sky orchestration + compute framework.

Public API surface mirrors the reference's (reference:
sky/__init__.py:83-220) with the TPU-first additions (mesh/sharding,
in-tree models and trainers).

Exports resolve lazily (PEP 562) so that head-side runtime processes —
which run under ``python -S`` with stdlib only — can import
``skypilot_tpu.runtime.*`` without dragging in the orchestration stack,
and so the CLI starts fast (the reference solves the same problem with
sky/adaptors LazyImport shims).
"""

import importlib
import typing

__version__ = "0.2.0"

_EXPORTS = {
    "Dag": ("skypilot_tpu.dag", "Dag"),
    "Task": ("skypilot_tpu.task", "Task"),
    "Resources": ("skypilot_tpu.resources", "Resources"),
    "launch": ("skypilot_tpu.execution", "launch"),
    "exec": ("skypilot_tpu.execution", "exec"),
    "status": ("skypilot_tpu.core", "status"),
    "start": ("skypilot_tpu.core", "start"),
    "stop": ("skypilot_tpu.core", "stop"),
    "down": ("skypilot_tpu.core", "down"),
    "autostop": ("skypilot_tpu.core", "autostop"),
    "queue": ("skypilot_tpu.core", "queue"),
    "cancel": ("skypilot_tpu.core", "cancel"),
    "tail_logs": ("skypilot_tpu.core", "tail_logs"),
    "job_status": ("skypilot_tpu.core", "job_status"),
    "cost_report": ("skypilot_tpu.core", "cost_report"),
}

__all__ = ["__version__"] + sorted(_EXPORTS)


def __getattr__(name: str):
    try:
        module_name, attr = _EXPORTS[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}") from None
    value = getattr(importlib.import_module(module_name), attr)
    globals()[name] = value
    return value


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))


if typing.TYPE_CHECKING:  # static-analysis visibility for the lazy names
    from skypilot_tpu.core import (autostop, cancel, cost_report, down,
                                   job_status, queue, start, status, stop,
                                   tail_logs)
    from skypilot_tpu.dag import Dag
    from skypilot_tpu.execution import exec, launch  # noqa: A004
    from skypilot_tpu.resources import Resources
    from skypilot_tpu.task import Task
