"""skypilot_tpu: a TPU-native sky orchestration + compute framework.

Public API surface mirrors the reference's (reference:
sky/__init__.py:83-220) with the TPU-first additions (mesh/sharding,
in-tree models and trainers).
"""

from skypilot_tpu.dag import Dag
from skypilot_tpu.execution import exec, launch  # noqa: A004
from skypilot_tpu.core import (autostop, cancel, cost_report, down,
                               job_status, queue, start, status, stop,
                               tail_logs)
from skypilot_tpu.resources import Resources
from skypilot_tpu.task import Task

__version__ = "0.1.0"

__all__ = [
    "Dag", "Resources", "Task",
    "launch", "exec",
    "status", "start", "stop", "down", "autostop",
    "queue", "cancel", "tail_logs", "job_status", "cost_report",
    "__version__",
]
