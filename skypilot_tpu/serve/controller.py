"""Serve controller process: autoscaler loop + replica management + the
load-balancer child process.

Reference parity: sky/serve/service.py (_start_service forks controller
+ LB) and sky/serve/controller.py (SkyServeController:36,
_run_autoscaler:64). Teardown handshake is DB-status based (the
reference uses signal files, service.py:38).
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time

from skypilot_tpu.observability import metrics, tracing
from skypilot_tpu.serve import autoscalers, replica_managers, serve_state
from skypilot_tpu.serve.serve_state import ReplicaStatus, ServiceStatus
from skypilot_tpu.serve.service_spec import SkyServiceSpec
from skypilot_tpu.utils import paths

POLL_SECONDS = float(os.environ.get("SKYTPU_SERVE_POLL", "2"))

READY_REPLICAS = metrics.gauge(
    "skytpu_serve_ready_replicas",
    "Replicas currently READY, per service", labelnames=("service",))
TARGET_REPLICAS = metrics.gauge(
    "skytpu_serve_target_replicas",
    "Autoscaler's current overall replica target, per service",
    labelnames=("service",))
READY_TIER_REPLICAS = metrics.gauge(
    "skytpu_serve_ready_tier_replicas",
    "Replicas currently READY per disaggregation tier (prefill/"
    "decode); only published for services with a disaggregation "
    "spec", labelnames=("service", "tier"))


def _publish_metrics(service_name: str) -> None:
    """The controller has no HTTP surface; its registry (probe
    failures, per-replica probe gauges, ready/target) is published as
    an atomic exposition file the federation tier reads. Never lets an
    unwritable home kill the control loop."""
    try:
        metrics.write_exposition_file(os.path.join(
            paths.home(), f"serve-metrics-{service_name}.prom"))
    except OSError:
        pass


def run(service_name: str) -> int:
    rec = serve_state.get_service(service_name)
    if rec is None:
        tracing.add_event("serve.controller_no_service",
                          {"service": service_name}, echo=True)
        return 1
    spec = SkyServiceSpec.from_yaml_config(rec["spec"])
    manager = replica_managers.ReplicaManager(
        service_name, spec, rec["task_config"],
        version=rec.get("version", 1))
    autoscaler = autoscalers.Autoscaler.from_spec(spec)

    # Start the LB as a child; it dies with us.
    lb_log = os.path.join(paths.logs_dir(),
                          f"serve-lb-{service_name}.log")
    lb_argv = [sys.executable, "-m", "skypilot_tpu.serve.load_balancer",
               "--service", service_name, "--port", str(rec["lb_port"])]
    if spec.tls_certfile:
        lb_argv += ["--tls-certfile", spec.tls_certfile,
                    "--tls-keyfile", spec.tls_keyfile]
    with open(lb_log, "ab") as f:
        lb = subprocess.Popen(
            lb_argv, stdout=f, stderr=subprocess.STDOUT,
            env={**os.environ, "SKYPILOT_TPU_HOME": paths.home()})

    def apply_scaling(autoscaler, manager, qps, ready, alive,
                      cur_version_live):
        """One scaling tick; returns the overall target (for draining).
        Mixed-fleet autoscalers own preemption replacement, so the
        probe loop's auto-replace is off for them."""
        if isinstance(autoscaler, autoscalers.FallbackRequestRateAutoscaler):
            manager.auto_replace = False
            d = autoscaler.decide_mixed(qps, cur_version_live)
            manager.scale_mixed(d.spot_target, d.ondemand_target)
            return d.target
        manager.auto_replace = True
        d = autoscaler.decide(qps, ready, alive)
        manager.scale_to(d.target)
        return d.target

    serve_state.set_service_status(service_name, ServiceStatus.REPLICA_INIT)
    # Initial provision bypasses hysteresis (decide() at t=0 would
    # propose-and-wait, delaying the first launch by upscale_delay).
    if isinstance(autoscaler, autoscalers.FallbackRequestRateAutoscaler):
        manager.auto_replace = False
        d0 = autoscaler.split(spec.target_num_replicas, [])
        manager.scale_mixed(d0.spot_target, d0.ondemand_target)
    else:
        manager.scale_to(spec.target_num_replicas)
    try:
        while True:
            time.sleep(POLL_SECONDS)
            rec = serve_state.get_service(service_name)
            if rec is None or rec["status"] == ServiceStatus.SHUTTING_DOWN:
                break
            if rec.get("version", 1) != manager.version:
                # Rolling update: new version launches fresh replicas;
                # old ones keep serving until drained below.
                spec = SkyServiceSpec.from_yaml_config(rec["spec"])
                autoscaler = autoscalers.Autoscaler.from_spec(spec)
                manager.apply_update(spec, rec["task_config"],
                                     rec["version"])
                tracing.add_event(
                    "serve.rolling_update",
                    {"service": service_name,
                     "version": rec["version"]}, echo=True)
            manager.probe_all()
            replicas = serve_state.list_replicas(service_name)
            ready = [r for r in replicas
                     if r["status"] == ReplicaStatus.READY]
            alive = [r for r in replicas
                     if r["status"] not in (ReplicaStatus.FAILED,
                                            ReplicaStatus.SHUTDOWN,
                                            ReplicaStatus.PREEMPTED)]
            status = (ServiceStatus.READY if ready
                      else ServiceStatus.REPLICA_INIT)
            if not alive and replicas:
                status = ServiceStatus.FAILED
            serve_state.set_service_status(service_name, status)
            if status == ServiceStatus.FAILED:
                break
            cur_live = [r for r in replicas
                        if r.get("version", 1) == manager.version
                        and r["status"] not in (ReplicaStatus.FAILED,
                                                ReplicaStatus.SHUTDOWN,
                                                ReplicaStatus.PREEMPTED,
                                                ReplicaStatus.SHUTTING_DOWN,
                                                ReplicaStatus.DRAINING)]
            target = apply_scaling(autoscaler, manager,
                                   serve_state.qps(service_name),
                                   len(ready), len(alive), cur_live)
            manager.drain_old_versions(target)
            READY_REPLICAS.labels(service=service_name).set(len(ready))
            TARGET_REPLICAS.labels(service=service_name).set(target)
            if getattr(spec, "disaggregation", None):
                for tier in ("prefill", "decode"):
                    READY_TIER_REPLICAS.labels(
                        service=service_name, tier=tier).set(
                            sum(1 for r in ready
                                if r.get("tier") == tier))
            _publish_metrics(service_name)
    finally:
        lb.terminate()
        manager.terminate_all()
        final = serve_state.get_service(service_name)
        if final is not None and final["status"] != ServiceStatus.FAILED:
            serve_state.set_service_status(service_name,
                                           ServiceStatus.SHUTDOWN)
        try:
            os.remove(os.path.join(paths.home(),
                                   f"serve-metrics-{service_name}.prom"))
        except OSError:
            pass
    return 0


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--service", required=True)
    args = ap.parse_args()
    sys.exit(run(args.service))


if __name__ == "__main__":
    main()
