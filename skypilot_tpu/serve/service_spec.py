"""Service spec: the `service:` section of a task YAML.

Reference parity: sky/serve/service_spec.py (SkyServiceSpec —
readiness probe, replica counts, target qps, autoscaler knobs).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

from skypilot_tpu import exceptions


@dataclasses.dataclass
class SkyServiceSpec:
    readiness_path: str = "/"
    initial_delay_seconds: float = 60.0
    readiness_timeout_seconds: float = 5.0
    min_replicas: int = 1
    max_replicas: Optional[int] = None
    target_num_replicas: Optional[int] = None
    target_qps_per_replica: Optional[float] = None
    # SLO-driven autoscaling: scale on the fleet's multi-window TTFT
    # p95 burn rate (observability/slo.py machinery) instead of raw
    # QPS — set this to the p95 objective in seconds and the
    # BurnRateAutoscaler takes over (docs/serving.md §Multi-tenant
    # QoS). Mutually composable with min/max replicas and the
    # upscale/downscale delays (cooldowns).
    target_ttft_p95_seconds: Optional[float] = None
    replica_port: int = 8080
    upscale_delay_seconds: float = 30.0
    downscale_delay_seconds: float = 60.0
    post_data: Optional[str] = None
    # TLS termination at the load balancer (reference:
    # sky/serve/service_spec.py tls fields): PEM paths valid on the
    # controller (push them via file_mounts for cloud controllers).
    tls_keyfile: Optional[str] = None
    tls_certfile: Optional[str] = None
    # Multi-LoRA adapter catalog (docs/serving.md §Adapter catalog):
    # {fine-tune name: checkpoint path} — the serve controller hands
    # each replica the catalog (SKYTPU_ADAPTERS env; the paths are
    # ordinary small checkpoints valid on the replica, pushed via
    # file_mounts or shared storage), the model server hot-loads on
    # demand, and the LB routes `model=` names (unknown -> typed 404
    # at BOTH tiers, affinity for known names).
    adapters: Optional[Dict[str, str]] = None
    # Disaggregated prefill/decode serving (docs/serving.md
    # §Disaggregated serving): {"prefill_replicas": P,
    # "decode_replicas": D} splits the fleet into a prefill tier
    # (chunked admission to one committed token, then a paged-KV
    # handoff) and a decode tier (imports the blocks and resumes
    # through the ordinary prefix-resume path). P + D must equal the
    # replica count, and autoscaling is fixed-count only — moving a
    # replica between tiers is a relaunch, not a probe flip.
    disaggregation: Optional[Dict[str, int]] = None
    # Spot/on-demand mixed fleet (reference: sky/serve/autoscalers.py
    # FallbackRequestRateAutoscaler:546): keep this many always-on
    # on-demand replicas under the spot fleet...
    base_ondemand_fallback_replicas: Optional[int] = None
    # ...and/or dynamically backfill on-demand for every spot replica
    # that is provisioned-but-not-READY (preempted or stockout).
    dynamic_ondemand_fallback: Optional[bool] = None

    @property
    def use_ondemand_fallback(self) -> bool:
        return (self.base_ondemand_fallback_replicas is not None
                or bool(self.dynamic_ondemand_fallback))

    def __post_init__(self):
        if self.max_replicas is None:
            self.max_replicas = max(self.min_replicas,
                                    self.target_num_replicas or
                                    self.min_replicas)
        if self.target_num_replicas is None:
            self.target_num_replicas = self.min_replicas
        if not (self.min_replicas <= self.target_num_replicas
                <= self.max_replicas):
            raise exceptions.ServeError(
                f"need min <= target <= max replicas, got "
                f"{self.min_replicas}/{self.target_num_replicas}/"
                f"{self.max_replicas}")
        base = self.base_ondemand_fallback_replicas
        if base is not None and not 0 <= base <= self.max_replicas:
            raise exceptions.ServeError(
                f"need 0 <= base_ondemand_fallback_replicas <= "
                f"max_replicas, got {base}/{self.max_replicas}")
        # Enforced at the dataclass so every construction path (YAML,
        # programmatic) agrees — the controller, core.up's endpoint
        # scheme, and to_yaml_config all gate on tls_certfile.
        if bool(self.tls_keyfile) != bool(self.tls_certfile):
            raise exceptions.ServeError(
                "service.tls needs both keyfile and certfile")
        if self.adapters is not None:
            if not isinstance(self.adapters, dict) or not all(
                    isinstance(k, str) and k and isinstance(v, str)
                    and v for k, v in self.adapters.items()):
                raise exceptions.ServeError(
                    "service.adapters must map non-empty adapter "
                    "names to checkpoint paths")
        if self.disaggregation is not None:
            d = self.disaggregation
            if (not isinstance(d, dict)
                    or set(d) != {"prefill_replicas", "decode_replicas"}
                    or not all(isinstance(v, int) and v >= 1
                               for v in d.values())):
                raise exceptions.ServeError(
                    "service.disaggregation needs integer "
                    "prefill_replicas >= 1 and decode_replicas >= 1")
            total = d["prefill_replicas"] + d["decode_replicas"]
            if self.min_replicas != self.max_replicas:
                raise exceptions.ServeError(
                    "service.disaggregation requires a fixed replica "
                    "count (replicas: N, no autoscaling policy) — "
                    "tier membership is assigned at launch")
            if total != self.min_replicas:
                raise exceptions.ServeError(
                    f"disaggregation tiers must cover the fleet: "
                    f"prefill_replicas + decode_replicas = {total} "
                    f"!= replicas = {self.min_replicas}")

    @classmethod
    def from_yaml_config(cls, config: Dict[str, Any]) -> "SkyServiceSpec":
        config = dict(config or {})
        readiness = config.pop("readiness_probe", "/")
        kwargs: Dict[str, Any] = {}
        if isinstance(readiness, str):
            kwargs["readiness_path"] = readiness
        else:
            kwargs["readiness_path"] = readiness.get("path", "/")
            if "initial_delay_seconds" in readiness:
                kwargs["initial_delay_seconds"] = float(
                    readiness["initial_delay_seconds"])
            if "post_data" in readiness:
                kwargs["post_data"] = readiness["post_data"]
        replicas = config.pop("replicas", None)
        policy = config.pop("replica_policy", None) or {}
        if replicas is not None and policy:
            raise exceptions.ServeError(
                "specify either `replicas` or `replica_policy`, not both")
        if replicas is not None:
            kwargs["min_replicas"] = kwargs["target_num_replicas"] = \
                int(replicas)
            kwargs["max_replicas"] = int(replicas)
        for key in ("min_replicas", "max_replicas",
                    "target_qps_per_replica",
                    "target_ttft_p95_seconds", "upscale_delay_seconds",
                    "downscale_delay_seconds",
                    "base_ondemand_fallback_replicas",
                    "dynamic_ondemand_fallback"):
            if key in policy:
                kwargs[key] = policy[key]
        if "port" in config:
            kwargs["replica_port"] = int(config.pop("port"))
        adapters = config.pop("adapters", None)
        if adapters is not None:
            kwargs["adapters"] = {str(k): str(v)
                                  for k, v in dict(adapters).items()}
        disagg = config.pop("disaggregation", None)
        if disagg is not None:
            try:
                kwargs["disaggregation"] = {
                    str(k): int(v) for k, v in dict(disagg).items()}
            except (TypeError, ValueError):
                raise exceptions.ServeError(
                    "service.disaggregation must map tier names to "
                    "integer replica counts")
        tls = config.pop("tls", None) or {}
        if tls:
            if not (tls.get("keyfile") and tls.get("certfile")):
                raise exceptions.ServeError(
                    "service.tls needs both keyfile and certfile")
            kwargs["tls_keyfile"] = tls["keyfile"]
            kwargs["tls_certfile"] = tls["certfile"]
        if config:
            raise exceptions.ServeError(
                f"unknown service fields: {sorted(config)}")
        return cls(**kwargs)

    def to_yaml_config(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "readiness_probe": {
                "path": self.readiness_path,
                "initial_delay_seconds": self.initial_delay_seconds,
            },
            "port": self.replica_port,
        }
        if self.post_data:
            out["readiness_probe"]["post_data"] = self.post_data
        if self.adapters:
            out["adapters"] = dict(self.adapters)
        if self.disaggregation:
            out["disaggregation"] = dict(self.disaggregation)
        if self.tls_certfile:
            out["tls"] = {"keyfile": self.tls_keyfile,
                          "certfile": self.tls_certfile}
        if self.min_replicas == self.max_replicas and \
                self.target_qps_per_replica is None and \
                self.target_ttft_p95_seconds is None and \
                not self.use_ondemand_fallback:
            out["replicas"] = self.min_replicas
        else:
            out["replica_policy"] = {
                "min_replicas": self.min_replicas,
                "max_replicas": self.max_replicas,
                "target_qps_per_replica": self.target_qps_per_replica,
                "upscale_delay_seconds": self.upscale_delay_seconds,
                "downscale_delay_seconds": self.downscale_delay_seconds,
            }
            if self.target_ttft_p95_seconds is not None:
                out["replica_policy"]["target_ttft_p95_seconds"] \
                    = self.target_ttft_p95_seconds
            if self.base_ondemand_fallback_replicas is not None:
                out["replica_policy"]["base_ondemand_fallback_replicas"] \
                    = self.base_ondemand_fallback_replicas
            if self.dynamic_ondemand_fallback is not None:
                out["replica_policy"]["dynamic_ondemand_fallback"] \
                    = self.dynamic_ondemand_fallback
        return out
