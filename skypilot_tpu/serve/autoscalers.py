"""Autoscalers: fixed-count and request-rate with hysteresis.

Reference parity: sky/serve/autoscalers.py (Autoscaler:115,
_AutoscalerWithHysteresis:348, RequestRateAutoscaler:431).
"""

from __future__ import annotations

import dataclasses
import math
import time
from typing import Optional

from skypilot_tpu.serve.service_spec import SkyServiceSpec


@dataclasses.dataclass
class ScalingDecision:
    target: int
    # Mixed-fleet split (spot + on-demand sum may exceed ``target``
    # while dynamic fallback is backfilling). None = unmixed.
    spot_target: Optional[int] = None
    ondemand_target: Optional[int] = None

    @property
    def mixed(self) -> bool:
        return self.spot_target is not None


class Autoscaler:
    def __init__(self, spec: SkyServiceSpec):
        self.spec = spec

    @classmethod
    def from_spec(cls, spec: SkyServiceSpec) -> "Autoscaler":
        if spec.use_ondemand_fallback:
            return FallbackRequestRateAutoscaler(spec)
        if spec.target_ttft_p95_seconds is not None:
            return BurnRateAutoscaler(
                spec,
                snapshot_fn=BurnRateAutoscaler.federated_snapshot)
        if spec.target_qps_per_replica is not None:
            return RequestRateAutoscaler(spec)
        return FixedAutoscaler(spec)

    def decide(self, current_qps: float, num_ready: int,
               num_total: int) -> ScalingDecision:
        raise NotImplementedError


class FixedAutoscaler(Autoscaler):
    def decide(self, current_qps, num_ready, num_total) -> ScalingDecision:
        return ScalingDecision(self.spec.target_num_replicas)


class RequestRateAutoscaler(Autoscaler):
    """target = ceil(qps / target_qps_per_replica), with upscale/downscale
    delays so transient spikes don't thrash replicas."""

    def __init__(self, spec: SkyServiceSpec):
        super().__init__(spec)
        self._proposal_since: Optional[float] = None
        self._proposal: Optional[int] = None

    def decide(self, current_qps, num_ready, num_total) -> ScalingDecision:
        raw = math.ceil(current_qps / self.spec.target_qps_per_replica) \
            if self.spec.target_qps_per_replica else self.spec.min_replicas
        desired = max(self.spec.min_replicas,
                      min(raw, self.spec.max_replicas))
        now = time.time()
        if desired == num_total:
            self._proposal = None
            self._proposal_since = None
            return ScalingDecision(num_total)
        if desired != self._proposal:
            self._proposal = desired
            self._proposal_since = now
            return ScalingDecision(num_total)
        delay = (self.spec.upscale_delay_seconds if desired > num_total
                 else self.spec.downscale_delay_seconds)
        if now - self._proposal_since >= delay:
            self._proposal = None
            self._proposal_since = None
            return ScalingDecision(desired)
        return ScalingDecision(num_total)


class BurnRateAutoscaler(Autoscaler):
    """SLO-driven scaling: the multi-window TTFT-p95 burn rate decides,
    not raw QPS (ROADMAP item 4 / docs/serving.md §Multi-tenant QoS).

    QPS is a proxy; the objective is latency. This autoscaler reuses
    the SLO watchdog's rule machinery verbatim — one
    ``histogram_quantile`` rule over ``skytpu_ttft_seconds``, evaluated
    over a short window (responsiveness) AND a long window (confidence)
    — and scales out one replica per upscale-delay cooldown while BOTH
    windows breach the objective. That multi-window gate is the
    hysteresis: a single slow request or scrape blip cannot launch a
    replica, exactly as it cannot page. Downscale is the mirror image:
    one replica per downscale delay while both windows sit below
    ``downscale_factor`` x the objective (comfortably inside SLO), so
    the fleet drains only when latency says the capacity is surplus.

    Snapshots come from ``snapshot_fn`` (the controller wires the
    federation tier via :meth:`federated_snapshot`); tests feed
    :meth:`observe` directly, like the watchdog's own tests.
    """

    def __init__(self, spec: SkyServiceSpec, snapshot_fn=None,
                 short_window_s: float = 60.0,
                 long_window_s: float = 300.0,
                 downscale_factor: float = 0.5):
        super().__init__(spec)
        from skypilot_tpu.observability import slo as slo_lib
        self._slo = slo_lib
        self.rule = slo_lib.SloRule(
            "ttft-burn", "histogram_quantile",
            threshold=float(spec.target_ttft_p95_seconds),
            metric="skytpu_ttft_seconds", quantile=0.95,
            short_window_s=short_window_s,
            long_window_s=long_window_s)
        self.downscale_factor = downscale_factor
        self._snapshot_fn = snapshot_fn
        self._history: list = []
        self._last_upscale_s: Optional[float] = None
        self._calm_since: Optional[float] = None

    @staticmethod
    def federated_snapshot():
        """Fleet-wide metric families from the federation tier (what
        the controller process scrapes anyway)."""
        from skypilot_tpu.observability import aggregate
        return aggregate.federate(aggregate.discover_endpoints()).families

    def observe(self, families, ts: Optional[float] = None) -> None:
        """Feed one metrics snapshot (the watchdog's Snapshot shape,
        components unused)."""
        ts = time.time() if ts is None else ts
        self._history.append((ts, families, []))
        cutoff = ts - 2 * self.rule.long_window_s
        while len(self._history) > 2 and self._history[0][0] < cutoff:
            self._history.pop(0)

    def decide(self, current_qps, num_ready, num_total) -> ScalingDecision:
        if self._snapshot_fn is not None:
            try:
                self.observe(self._snapshot_fn())
            except Exception as e:  # noqa: BLE001 — a dead federation
                # tier must not kill the controller loop; scaling just
                # freezes at the current target until scrapes return.
                from skypilot_tpu.observability import tracing
                tracing.add_event(
                    "autoscaler.snapshot_failed",
                    {"error_type": type(e).__name__,
                     "message": str(e)[:200]}, echo=True)
        breached, short, long_ = self._slo.evaluate_rule(
            self.rule, self._history)
        now = self._history[-1][0] if self._history else time.time()
        lo, hi = self.spec.min_replicas, self.spec.max_replicas
        target = min(max(num_total, lo), hi)
        if breached:
            self._calm_since = None
            cooled = (self._last_upscale_s is None
                      or now - self._last_upscale_s
                      >= self.spec.upscale_delay_seconds)
            if cooled and target < hi:
                self._last_upscale_s = now
                return ScalingDecision(target + 1)
            return ScalingDecision(target)
        calm_bar = self.rule.threshold * self.downscale_factor
        calm = (short is not None and long_ is not None
                and short <= calm_bar and long_ <= calm_bar)
        if calm and target > lo:
            if self._calm_since is None:
                self._calm_since = now
            elif now - self._calm_since \
                    >= self.spec.downscale_delay_seconds:
                self._calm_since = now
                return ScalingDecision(target - 1)
        elif not calm:
            self._calm_since = None
        return ScalingDecision(target)


class FallbackRequestRateAutoscaler(RequestRateAutoscaler):
    """Spot fleet with an on-demand floor + preemption-aware backfill.

    Reference parity: sky/serve/autoscalers.py
    FallbackRequestRateAutoscaler:546 — ``base`` on-demand replicas are
    always kept (availability floor); with ``dynamic_ondemand_fallback``
    every spot replica that is wanted-but-not-READY (preempted, spot
    stockout, still provisioning) is covered by an extra on-demand
    replica, drained again once the spot fleet recovers. Serving cost
    approaches all-spot while availability approaches all-on-demand.

    Works over fixed-count specs too (no target_qps -> the request-rate
    parent degrades to min_replicas, which equals the fixed count).
    """

    def split(self, overall: int, replicas) -> ScalingDecision:
        """Split an overall target into (spot, on-demand) sub-targets.

        ``overall`` is clamped to [min, max] replicas FIRST: the
        hysteresis parent echoes the live count while a proposal
        settles, and the live count includes backfill overage — an
        unclamped echo would feed the overage back into the spot
        target, a geometric launch runaway until the downscale delay
        elapsed.
        """
        from skypilot_tpu.serve.serve_state import ReplicaStatus
        overall = min(max(overall, self.spec.min_replicas),
                      self.spec.max_replicas)
        base = self.spec.base_ondemand_fallback_replicas or 0
        base = min(base, overall)
        spot_target = overall - base
        ready_spot = sum(1 for r in replicas if r["is_spot"]
                         and r["status"] == ReplicaStatus.READY)
        ondemand_target = base
        if self.spec.dynamic_ondemand_fallback:
            ondemand_target += max(spot_target - ready_spot, 0)
        return ScalingDecision(overall, spot_target=spot_target,
                               ondemand_target=ondemand_target)

    def decide_mixed(self, current_qps: float,
                     replicas) -> ScalingDecision:
        """``replicas``: current-version live replica rows (dicts with
        "status" and "is_spot")."""
        from skypilot_tpu.serve.serve_state import ReplicaStatus
        num_ready = sum(1 for r in replicas
                        if r["status"] == ReplicaStatus.READY)
        overall = self.decide(current_qps, num_ready,
                              len(replicas)).target
        return self.split(overall, replicas)
