"""Autoscalers: fixed-count and request-rate with hysteresis.

Reference parity: sky/serve/autoscalers.py (Autoscaler:115,
_AutoscalerWithHysteresis:348, RequestRateAutoscaler:431).
"""

from __future__ import annotations

import dataclasses
import math
import time
from typing import Optional

from skypilot_tpu.serve.service_spec import SkyServiceSpec


@dataclasses.dataclass
class ScalingDecision:
    target: int


class Autoscaler:
    def __init__(self, spec: SkyServiceSpec):
        self.spec = spec

    @classmethod
    def from_spec(cls, spec: SkyServiceSpec) -> "Autoscaler":
        if spec.target_qps_per_replica is not None:
            return RequestRateAutoscaler(spec)
        return FixedAutoscaler(spec)

    def decide(self, current_qps: float, num_ready: int,
               num_total: int) -> ScalingDecision:
        raise NotImplementedError


class FixedAutoscaler(Autoscaler):
    def decide(self, current_qps, num_ready, num_total) -> ScalingDecision:
        return ScalingDecision(self.spec.target_num_replicas)


class RequestRateAutoscaler(Autoscaler):
    """target = ceil(qps / target_qps_per_replica), with upscale/downscale
    delays so transient spikes don't thrash replicas."""

    def __init__(self, spec: SkyServiceSpec):
        super().__init__(spec)
        self._proposal_since: Optional[float] = None
        self._proposal: Optional[int] = None

    def decide(self, current_qps, num_ready, num_total) -> ScalingDecision:
        raw = math.ceil(current_qps / self.spec.target_qps_per_replica) \
            if self.spec.target_qps_per_replica else self.spec.min_replicas
        desired = max(self.spec.min_replicas,
                      min(raw, self.spec.max_replicas))
        now = time.time()
        if desired == num_total:
            self._proposal = None
            self._proposal_since = None
            return ScalingDecision(num_total)
        if desired != self._proposal:
            self._proposal = desired
            self._proposal_since = now
            return ScalingDecision(num_total)
        delay = (self.spec.upscale_delay_seconds if desired > num_total
                 else self.spec.downscale_delay_seconds)
        if now - self._proposal_since >= delay:
            self._proposal = None
            self._proposal_since = None
            return ScalingDecision(desired)
        return ScalingDecision(num_total)
