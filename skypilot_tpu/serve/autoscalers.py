"""Autoscalers: fixed-count and request-rate with hysteresis.

Reference parity: sky/serve/autoscalers.py (Autoscaler:115,
_AutoscalerWithHysteresis:348, RequestRateAutoscaler:431).
"""

from __future__ import annotations

import dataclasses
import math
import time
from typing import Optional

from skypilot_tpu.serve.service_spec import SkyServiceSpec


@dataclasses.dataclass
class ScalingDecision:
    target: int
    # Mixed-fleet split (spot + on-demand sum may exceed ``target``
    # while dynamic fallback is backfilling). None = unmixed.
    spot_target: Optional[int] = None
    ondemand_target: Optional[int] = None

    @property
    def mixed(self) -> bool:
        return self.spot_target is not None


class Autoscaler:
    def __init__(self, spec: SkyServiceSpec):
        self.spec = spec

    @classmethod
    def from_spec(cls, spec: SkyServiceSpec) -> "Autoscaler":
        if spec.use_ondemand_fallback:
            return FallbackRequestRateAutoscaler(spec)
        if spec.target_qps_per_replica is not None:
            return RequestRateAutoscaler(spec)
        return FixedAutoscaler(spec)

    def decide(self, current_qps: float, num_ready: int,
               num_total: int) -> ScalingDecision:
        raise NotImplementedError


class FixedAutoscaler(Autoscaler):
    def decide(self, current_qps, num_ready, num_total) -> ScalingDecision:
        return ScalingDecision(self.spec.target_num_replicas)


class RequestRateAutoscaler(Autoscaler):
    """target = ceil(qps / target_qps_per_replica), with upscale/downscale
    delays so transient spikes don't thrash replicas."""

    def __init__(self, spec: SkyServiceSpec):
        super().__init__(spec)
        self._proposal_since: Optional[float] = None
        self._proposal: Optional[int] = None

    def decide(self, current_qps, num_ready, num_total) -> ScalingDecision:
        raw = math.ceil(current_qps / self.spec.target_qps_per_replica) \
            if self.spec.target_qps_per_replica else self.spec.min_replicas
        desired = max(self.spec.min_replicas,
                      min(raw, self.spec.max_replicas))
        now = time.time()
        if desired == num_total:
            self._proposal = None
            self._proposal_since = None
            return ScalingDecision(num_total)
        if desired != self._proposal:
            self._proposal = desired
            self._proposal_since = now
            return ScalingDecision(num_total)
        delay = (self.spec.upscale_delay_seconds if desired > num_total
                 else self.spec.downscale_delay_seconds)
        if now - self._proposal_since >= delay:
            self._proposal = None
            self._proposal_since = None
            return ScalingDecision(desired)
        return ScalingDecision(num_total)


class FallbackRequestRateAutoscaler(RequestRateAutoscaler):
    """Spot fleet with an on-demand floor + preemption-aware backfill.

    Reference parity: sky/serve/autoscalers.py
    FallbackRequestRateAutoscaler:546 — ``base`` on-demand replicas are
    always kept (availability floor); with ``dynamic_ondemand_fallback``
    every spot replica that is wanted-but-not-READY (preempted, spot
    stockout, still provisioning) is covered by an extra on-demand
    replica, drained again once the spot fleet recovers. Serving cost
    approaches all-spot while availability approaches all-on-demand.

    Works over fixed-count specs too (no target_qps -> the request-rate
    parent degrades to min_replicas, which equals the fixed count).
    """

    def split(self, overall: int, replicas) -> ScalingDecision:
        """Split an overall target into (spot, on-demand) sub-targets.

        ``overall`` is clamped to [min, max] replicas FIRST: the
        hysteresis parent echoes the live count while a proposal
        settles, and the live count includes backfill overage — an
        unclamped echo would feed the overage back into the spot
        target, a geometric launch runaway until the downscale delay
        elapsed.
        """
        from skypilot_tpu.serve.serve_state import ReplicaStatus
        overall = min(max(overall, self.spec.min_replicas),
                      self.spec.max_replicas)
        base = self.spec.base_ondemand_fallback_replicas or 0
        base = min(base, overall)
        spot_target = overall - base
        ready_spot = sum(1 for r in replicas if r["is_spot"]
                         and r["status"] == ReplicaStatus.READY)
        ondemand_target = base
        if self.spec.dynamic_ondemand_fallback:
            ondemand_target += max(spot_target - ready_spot, 0)
        return ScalingDecision(overall, spot_target=spot_target,
                               ondemand_target=ondemand_target)

    def decide_mixed(self, current_qps: float,
                     replicas) -> ScalingDecision:
        """``replicas``: current-version live replica rows (dicts with
        "status" and "is_spot")."""
        from skypilot_tpu.serve.serve_state import ReplicaStatus
        num_ready = sum(1 for r in replicas
                        if r["status"] == ReplicaStatus.READY)
        overall = self.decide(current_qps, num_ready,
                              len(replicas)).target
        return self.split(overall, replicas)
