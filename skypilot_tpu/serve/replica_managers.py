"""Replica manager: launches/terminates/probes replica clusters.

Reference parity: sky/serve/replica_managers.py (ReplicaInfo status
machine :224-383, SkyPilotReplicaManager :607 — _launch_replica=
sky.launch of a replica cluster, _terminate_replica, _handle_preemption,
readiness prober :1026).
"""

from __future__ import annotations

import concurrent.futures
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional

from skypilot_tpu import exceptions, execution
from skypilot_tpu import state as cluster_state
from skypilot_tpu.backend import ClusterHandle, TpuVmBackend
from skypilot_tpu.serve import serve_state
from skypilot_tpu.serve.serve_state import ReplicaStatus
from skypilot_tpu.serve.service_spec import SkyServiceSpec
from skypilot_tpu.task import Task

PROBE_FAILURES_BEFORE_NOT_READY = 3


class ReplicaManager:
    def __init__(self, service_name: str, spec: SkyServiceSpec,
                 task_config: dict, version: int = 1):
        self.service = service_name
        self.spec = spec
        self.task_config = task_config
        self.version = version
        self.backend = TpuVmBackend()
        self._next_replica_id = 1 + max(
            [r["replica_id"] for r in serve_state.list_replicas(service_name)]
            or [0])
        self._probe_failures: Dict[int, int] = {}
        self._pool = concurrent.futures.ThreadPoolExecutor(max_workers=8)
        self._launching: set = set()
        self._lock = threading.Lock()

    # -- rolling updates ---------------------------------------------------
    def apply_update(self, spec: SkyServiceSpec, task_config: dict,
                     version: int) -> None:
        """Switch to a new service version: subsequent launches use the
        new task/spec; old-version replicas are drained by
        drain_old_versions once enough new ones are READY (reference:
        sky/serve/serve_utils.py version machinery)."""
        self.spec = spec
        self.task_config = task_config
        self.version = version

    def drain_old_versions(self, target: int) -> None:
        """Terminate old-version replicas only after the current version
        can carry the load — zero-downtime rollover."""
        live = self._live_replicas()
        old = [r for r in live if r.get("version", 1) != self.version]
        if not old:
            return
        ready_cur = [r for r in live
                     if r.get("version", 1) == self.version
                     and r["status"] == ReplicaStatus.READY]
        if len(ready_cur) >= max(1, target):
            for r in old:
                self._terminate_replica(r["replica_id"])

    # -- scaling -----------------------------------------------------------
    def _live_replicas(self):
        return [r for r in serve_state.list_replicas(self.service)
                if r["status"] not in (ReplicaStatus.SHUTTING_DOWN,
                                       ReplicaStatus.SHUTDOWN,
                                       ReplicaStatus.FAILED,
                                       ReplicaStatus.PREEMPTED)]

    def scale_to(self, target: int) -> None:
        # Launch decisions count only CURRENT-version replicas, so an
        # update immediately provisions the new version while the old
        # one keeps serving (drained separately).
        cur = [r for r in self._live_replicas()
               if r.get("version", 1) == self.version]
        with self._lock:
            n_current = len(cur) + len(self._launching)
        if target > n_current:
            for _ in range(target - n_current):
                self._launch_replica()
        elif target < len(cur):
            # Scale down the newest non-ready first, then newest ready.
            order = sorted(
                cur,
                key=lambda r: (r["status"] == ReplicaStatus.READY,
                               -r["replica_id"]))
            for r in order[:len(cur) - target]:
                self._terminate_replica(r["replica_id"])

    def _launch_replica(self) -> None:
        with self._lock:
            rid = self._next_replica_id
            self._next_replica_id += 1
            self._launching.add(rid)
        cluster = f"sky-serve-{self.service}-{rid}"
        version = self.version
        serve_state.upsert_replica(self.service, rid, cluster,
                                   ReplicaStatus.PROVISIONING, None,
                                   version=version)
        self._pool.submit(self._launch_replica_blocking, rid, cluster,
                          version, dict(self.task_config))

    def _launch_replica_blocking(self, rid: int, cluster: str,
                                 version: int, task_config: dict) -> None:
        try:
            task = Task.from_yaml_config(task_config)
            task.update_envs({"SKYTPU_REPLICA_ID": str(rid),
                              "SKYTPU_REPLICA_PORT": str(self._port(rid))})
            job_id, handle = execution.launch(task, cluster_name=cluster,
                                              retry_until_up=True)
            url = self._replica_url(handle, rid)
            serve_state.upsert_replica(self.service, rid, cluster,
                                       ReplicaStatus.STARTING, url,
                                       version=version)
        except Exception as e:  # noqa: BLE001 — replica failure is a state
            print(f"replica {rid} launch failed: {e}", flush=True)
            serve_state.upsert_replica(self.service, rid, cluster,
                                       ReplicaStatus.FAILED, None,
                                       version=version)
        finally:
            with self._lock:
                self._launching.discard(rid)

    def _port(self, rid: int) -> int:
        # Local replicas share one machine: unique port per replica.
        first = (self.task_config.get("resources") or {})
        if isinstance(first, list):
            first = first[0] if first else {}
        if first.get("cloud") == "local":
            return self.spec.replica_port + rid
        return self.spec.replica_port

    def _replica_url(self, handle: ClusterHandle, rid: int) -> str:
        from skypilot_tpu import provision
        info = provision.get_cluster_info(handle.provider,
                                          handle.cluster_name, handle.zone)
        ip = info.head.external_ip or info.head.internal_ip
        return f"http://{ip}:{self._port(rid)}"

    def _terminate_replica(self, rid: int) -> None:
        serve_state.set_replica_status(self.service, rid,
                                       ReplicaStatus.SHUTTING_DOWN)

        def do():
            cluster = f"sky-serve-{self.service}-{rid}"
            rec = cluster_state.get_cluster(cluster)
            if rec is not None:
                try:
                    self.backend.teardown(ClusterHandle(rec["handle"]))
                except exceptions.SkyTpuError:
                    cluster_state.remove_cluster(cluster)
            serve_state.remove_replica(self.service, rid)

        self._pool.submit(do)

    def terminate_all(self) -> None:
        for r in serve_state.list_replicas(self.service):
            self._terminate_replica(r["replica_id"])
        self._pool.shutdown(wait=True)

    # -- probing -----------------------------------------------------------
    def probe_all(self) -> None:
        for r in serve_state.list_replicas(self.service):
            if r["status"] in (ReplicaStatus.PROVISIONING,
                               ReplicaStatus.SHUTTING_DOWN,
                               ReplicaStatus.SHUTDOWN,
                               ReplicaStatus.FAILED):
                continue
            rid = r["replica_id"]
            if self._cluster_gone(r["cluster_name"]):
                # Slice preempted: replace the replica entirely.
                serve_state.set_replica_status(self.service, rid,
                                               ReplicaStatus.PREEMPTED)
                self._terminate_replica(rid)
                self._launch_replica()
                continue
            ok = self._probe_one(r)
            if ok:
                self._probe_failures[rid] = 0
                if r["status"] != ReplicaStatus.READY:
                    serve_state.set_replica_status(self.service, rid,
                                                   ReplicaStatus.READY)
            else:
                # STARTING grace period: initial_delay before failures count.
                if r["status"] == ReplicaStatus.STARTING and \
                        time.time() - r["launched_at"] < \
                        self.spec.initial_delay_seconds:
                    continue
                n = self._probe_failures.get(rid, 0) + 1
                self._probe_failures[rid] = n
                if n >= PROBE_FAILURES_BEFORE_NOT_READY and \
                        r["status"] == ReplicaStatus.READY:
                    serve_state.set_replica_status(self.service, rid,
                                                   ReplicaStatus.NOT_READY)

    def _probe_one(self, r: dict) -> bool:
        if not r["url"]:
            return False
        url = r["url"] + self.spec.readiness_path
        try:
            data = (self.spec.post_data.encode()
                    if self.spec.post_data else None)
            req = urllib.request.Request(url, data=data)
            with urllib.request.urlopen(
                    req, timeout=self.spec.readiness_timeout_seconds) as resp:
                return 200 <= resp.status < 300
        except Exception:  # noqa: BLE001 — any probe error = not ready
            return False

    def _cluster_gone(self, cluster_name: str) -> bool:
        from skypilot_tpu import provision
        rec = cluster_state.get_cluster(cluster_name)
        if rec is None:
            return True
        try:
            return provision.query_instances(
                rec["handle"]["provider"], cluster_name,
                rec["handle"]["zone"]) == "NOT_FOUND"
        except exceptions.SkyTpuError:
            return True
