"""Replica manager: launches/terminates/probes replica clusters.

Reference parity: sky/serve/replica_managers.py (ReplicaInfo status
machine :224-383, SkyPilotReplicaManager :607 — _launch_replica=
sky.launch of a replica cluster, _terminate_replica, _handle_preemption,
readiness prober :1026).
"""

from __future__ import annotations

import concurrent.futures
import json
import os
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional

from skypilot_tpu import chaos, exceptions, execution
from skypilot_tpu import state as cluster_state
from skypilot_tpu.backend import ClusterHandle, TpuVmBackend
from skypilot_tpu.observability import metrics, tracing
from skypilot_tpu.serve import serve_state
from skypilot_tpu.serve.serve_state import ReplicaStatus
from skypilot_tpu.serve.service_spec import SkyServiceSpec
from skypilot_tpu.task import Task

PROBE_FAILURES_BEFORE_NOT_READY = 3

# Probe outcomes used to live only in serve-DB status flips; the
# counters/gauges below make them scrapeable (the controller publishes
# its registry per tick; the health model reads the last-probe-ok
# gauge to tell "degraded replica" from "never probed").
PROBE_FAILURES = metrics.counter(
    "skytpu_serve_probe_failures_total",
    "Readiness-probe failures observed by the controller's prober, "
    "by service", labelnames=("service",))
REPLICA_PROBE_OK = metrics.gauge(
    "skytpu_serve_replica_probe_ok",
    "1 when the replica's most recent readiness probe succeeded, 0 "
    "after a failure (a terminated replica's series keeps its last "
    "value — pair with the last-probe-ok timestamp for staleness)",
    labelnames=("service", "replica"))
REPLICA_PROBE_OK_TS = metrics.gauge(
    "skytpu_serve_replica_last_probe_ok_timestamp_seconds",
    "Unix time of the replica's last successful readiness probe "
    "(staleness source for the component health model)",
    labelnames=("service", "replica"))


def _apply_resource_overrides(task_config: dict,
                              use_spot: Optional[bool],
                              port: int) -> dict:
    """Per-replica resource rewrites: the mixed-fleet spot override and
    the replica port (so providers with explicit port exposure —
    kubernetes NodePort Services — open it at provision time). The
    schema allows scalar/string port forms; normalize to ints before
    merging or sorted() raises mid-launch and the replica FAILs."""
    task_config = dict(task_config)
    res = task_config.get("resources") or {}

    def override(r: dict) -> dict:
        r = dict(r)
        if use_spot is not None:
            r["use_spot"] = use_spot
        raw = r.get("ports")
        if raw is None:
            raw = []
        elif not isinstance(raw, (list, tuple)):
            raw = [raw]
        ports = {int(p) for p in raw}
        ports.add(int(port))
        r["ports"] = sorted(ports)
        return r

    task_config["resources"] = ([override(r) for r in res]
                                if isinstance(res, list) else override(res))
    return task_config


class ReplicaManager:
    def __init__(self, service_name: str, spec: SkyServiceSpec,
                 task_config: dict, version: int = 1):
        self.service = service_name
        self.spec = spec
        self.task_config = task_config
        self.version = version
        self.backend = TpuVmBackend()
        self._next_replica_id = 1 + max(
            [r["replica_id"] for r in serve_state.list_replicas(service_name)]
            or [0])
        self._probe_failures: Dict[int, int] = {}
        self._pool = concurrent.futures.ThreadPoolExecutor(max_workers=8)
        self._launching: Dict[int, bool] = {}   # rid -> is_spot; guarded-by: _lock
        self._launch_tier: Dict[int, str] = {}  # rid -> tier; guarded-by: _lock
        self._lock = threading.Lock()
        # With a mixed-fleet autoscaler the controller owns replacement
        # decisions (preempted spot may come back as on-demand); the
        # probe loop then only marks/terminates, never relaunches.
        self.auto_replace = True
        # Graceful-drain budget per terminated replica: how long the
        # manager waits for in-flight requests to finish after the
        # replica goes unroutable, before the actual kill.
        self.drain_grace_s = float(
            os.environ.get("SKYTPU_SERVE_DRAIN_GRACE_S", "30"))

    # -- rolling updates ---------------------------------------------------
    def apply_update(self, spec: SkyServiceSpec, task_config: dict,
                     version: int) -> None:
        """Switch to a new service version: subsequent launches use the
        new task/spec; old-version replicas are drained by
        drain_old_versions once enough new ones are READY (reference:
        sky/serve/serve_utils.py version machinery)."""
        self.spec = spec
        self.task_config = task_config
        self.version = version

    def drain_old_versions(self, target: int) -> None:
        """Terminate old-version replicas only after the current version
        can carry the load — zero-downtime rollover."""
        live = self._live_replicas()
        old = [r for r in live if r.get("version", 1) != self.version]
        if not old:
            return
        ready_cur = [r for r in live
                     if r.get("version", 1) == self.version
                     and r["status"] == ReplicaStatus.READY]
        if len(ready_cur) >= max(1, target):
            for r in old:
                self._terminate_replica(r["replica_id"])

    # -- scaling -----------------------------------------------------------
    def _live_replicas(self):
        # DRAINING is already on its way out: it must not count toward
        # capacity (scale decisions) nor be re-terminated every tick.
        return [r for r in serve_state.list_replicas(self.service)
                if r["status"] not in (ReplicaStatus.SHUTTING_DOWN,
                                       ReplicaStatus.SHUTDOWN,
                                       ReplicaStatus.FAILED,
                                       ReplicaStatus.PREEMPTED,
                                       ReplicaStatus.DRAINING)]

    def scale_to(self, target: int) -> None:
        # Launch decisions count only CURRENT-version replicas, so an
        # update immediately provisions the new version while the old
        # one keeps serving (drained separately).
        cur = [r for r in self._live_replicas()
               if r.get("version", 1) == self.version]
        with self._lock:
            n_current = len(cur) + len(self._launching)
        if target > n_current:
            for _ in range(target - n_current):
                self._launch_replica()
        elif target < len(cur):
            for r in self._scale_down_order(cur)[:len(cur) - target]:
                self._terminate_replica(r["replica_id"])

    @staticmethod
    def _scale_down_order(replicas):
        """Newest non-ready first, then newest ready."""
        return sorted(replicas,
                      key=lambda r: (r["status"] == ReplicaStatus.READY,
                                     -r["replica_id"]))

    def scale_mixed(self, spot_target: int, ondemand_target: int) -> None:
        """Reconcile the spot and on-demand sub-fleets independently
        (reference: FallbackRequestRateAutoscaler's per-type decisions,
        sky/serve/autoscalers.py:640-700)."""
        cur = [r for r in self._live_replicas()
               if r.get("version", 1) == self.version]
        for is_spot, target in ((True, spot_target),
                                (False, ondemand_target)):
            sub = [r for r in cur if bool(r.get("is_spot")) == is_spot]
            with self._lock:
                n = len(sub) + sum(1 for s in self._launching.values()
                                   if s == is_spot)
            if target > n:
                for _ in range(target - n):
                    self._launch_replica(use_spot=is_spot)
            elif target < len(sub):
                for r in self._scale_down_order(sub)[:len(sub) - target]:
                    self._terminate_replica(r["replica_id"])

    def _tier_counts(self) -> Dict[str, int]:
        """Live + in-flight-launch replicas per disaggregation tier.
        Guarded-by: _lock (reads _launch_tier)."""
        counts: Dict[str, int] = {}
        for r in self._live_replicas():
            t = r.get("tier") or ""
            counts[t] = counts.get(t, 0) + 1
        for t in self._launch_tier.values():
            counts[t] = counts.get(t, 0) + 1
        return counts

    def _launch_replica(self, use_spot: Optional[bool] = None) -> None:
        with self._lock:
            rid = self._next_replica_id
            self._next_replica_id += 1
            self._launching[rid] = bool(use_spot)
            # Disaggregated tier assignment (docs/serving.md
            # §Disaggregated serving): fill the prefill tier to its
            # spec'd count first, decode gets the rest. A replaced
            # replica (preemption relaunch) lands back in whichever
            # tier is short, so the split self-heals.
            tier = ""
            disagg = getattr(self.spec, "disaggregation", None)
            if disagg:
                counts = self._tier_counts()
                tier = ("prefill"
                        if counts.get("prefill", 0)
                        < int(disagg.get("prefill_replicas", 0))
                        else "decode")
            self._launch_tier[rid] = tier
        cluster = f"sky-serve-{self.service}-{rid}"
        version = self.version
        serve_state.upsert_replica(self.service, rid, cluster,
                                   ReplicaStatus.PROVISIONING, None,
                                   version=version,
                                   is_spot=bool(use_spot), tier=tier)
        self._pool.submit(self._launch_replica_blocking, rid, cluster,
                          version, dict(self.task_config), use_spot,
                          tier)

    def _launch_replica_blocking(self, rid: int, cluster: str,
                                 version: int, task_config: dict,
                                 use_spot: Optional[bool] = None,
                                 tier: str = "") -> None:
        try:
            task_config = _apply_resource_overrides(
                task_config, use_spot, self._port(rid))
            task = Task.from_yaml_config(task_config)
            task.update_envs({"SKYTPU_REPLICA_ID": str(rid),
                              "SKYTPU_REPLICA_PORT": str(self._port(rid))})
            if tier:
                # The replica's own processes see their tier (ops
                # tooling, logs); routing stays LB-side off the serve
                # DB — a replica serves whatever endpoint is asked of
                # it, so a tier flip is a relaunch, not a config skew.
                task.update_envs({"SKYTPU_TIER": tier})
            if getattr(self.spec, "adapters", None):
                # Adapter-catalog distribution: each replica's model
                # server registers the service's fine-tunes from this
                # env (checkpoints are ordinary small files the task's
                # file_mounts/shared storage put in place; loading to
                # device stays demand-driven on the replica).
                task.update_envs({
                    "SKYTPU_ADAPTERS": json.dumps(self.spec.adapters)})
            job_id, handle = execution.launch(task, cluster_name=cluster,
                                              retry_until_up=True)
            # The controller may have terminated this replica while the
            # launch was in flight (mixed-fleet backfill drains as soon
            # as spot recovers): an unconditional STARTING upsert would
            # resurrect the deleted row and leak the cluster.
            row = [r for r in serve_state.list_replicas(self.service)
                   if r["replica_id"] == rid]
            if not row or row[0]["status"] in (ReplicaStatus.SHUTTING_DOWN,
                                               ReplicaStatus.SHUTDOWN):
                try:
                    self.backend.teardown(handle)
                except exceptions.SkyTpuError:
                    cluster_state.remove_cluster(cluster)
                return
            url = self._replica_url(handle, rid)
            serve_state.upsert_replica(self.service, rid, cluster,
                                       ReplicaStatus.STARTING, url,
                                       version=version,
                                       is_spot=bool(use_spot), tier=tier)
        except Exception as e:  # noqa: BLE001 — replica failure is a state
            tracing.add_event(
                "serve.replica_launch_failed",
                {"service": self.service, "replica": rid,
                 "error": str(e)}, echo=True)
            serve_state.upsert_replica(self.service, rid, cluster,
                                       ReplicaStatus.FAILED, None,
                                       version=version,
                                       is_spot=bool(use_spot), tier=tier)
        finally:
            with self._lock:
                self._launching.pop(rid, None)
                self._launch_tier.pop(rid, None)

    def _port(self, rid: int) -> int:
        # Local replicas share one machine: unique port per replica.
        first = (self.task_config.get("resources") or {})
        if isinstance(first, list):
            first = first[0] if first else {}
        if first.get("cloud") == "local":
            return self.spec.replica_port + rid
        return self.spec.replica_port

    def _replica_url(self, handle: ClusterHandle, rid: int) -> str:
        from skypilot_tpu import provision
        port = self._port(rid)
        # Providers with explicit port exposure (kubernetes NodePort
        # Service) publish remapped endpoints; pod/VM addresses
        # otherwise.
        ep = provision.query_ports(handle.provider,
                                   handle.cluster_name).get(port)
        if ep:
            return f"http://{ep}"
        info = provision.get_cluster_info(handle.provider,
                                          handle.cluster_name, handle.zone)
        ip = info.head.external_ip or info.head.internal_ip
        return f"http://{ip}:{port}"

    def _terminate_replica(self, rid: int, drain: bool = True) -> None:
        """Drain-before-kill: a routable replica flips to DRAINING
        first (instantly out of ``ready_urls``, so the LB stops
        sending work BEFORE the kill), finishes its in-flight requests
        via ``POST /drain`` polling, and only then tears down. Callers
        whose replica cannot usefully drain (preempted — the cluster
        is already gone; service teardown — the endpoint is going
        away) pass ``drain=False`` for the immediate kill."""
        row = [r for r in serve_state.list_replicas(self.service)
               if r["replica_id"] == rid]
        url = row[0]["url"] if row else None
        do_drain = (drain and bool(url)
                    and row[0]["status"] in (ReplicaStatus.READY,
                                             ReplicaStatus.DRAINING))
        serve_state.set_replica_status(
            self.service, rid,
            ReplicaStatus.DRAINING if do_drain
            else ReplicaStatus.SHUTTING_DOWN)

        def do():
            if do_drain:
                self._drain_replica(url)
                serve_state.set_replica_status(
                    self.service, rid, ReplicaStatus.SHUTTING_DOWN)
            cluster = f"sky-serve-{self.service}-{rid}"
            rec = cluster_state.get_cluster(cluster)
            if rec is not None:
                try:
                    self.backend.teardown(ClusterHandle(rec["handle"]))
                except exceptions.SkyTpuError:
                    cluster_state.remove_cluster(cluster)
            serve_state.remove_replica(self.service, rid)

        self._pool.submit(do)

    def _drain_replica(self, url: str) -> bool:
        """``POST /drain`` and poll until the replica reports drained
        or the grace budget runs out. Any transport/endpoint failure
        returns False immediately — a replica that cannot answer
        ``/drain`` gains nothing from the manager waiting on it."""
        deadline = time.monotonic() + self.drain_grace_s

        def poll() -> Optional[dict]:
            try:
                req = urllib.request.Request(
                    url + "/drain",
                    data=json.dumps(
                        {"grace_s": self.drain_grace_s}).encode(),
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=5) as resp:
                    return json.loads(resp.read() or b"{}")
            except Exception:  # noqa: BLE001 — no drain answer: kill
                return None

        st = poll()
        if st is None:
            return False
        while not st.get("drained") and time.monotonic() < deadline:
            time.sleep(0.2)
            st = poll()
            if st is None:
                return False
        return bool(st.get("drained"))

    def terminate_all(self) -> None:
        for r in serve_state.list_replicas(self.service):
            self._terminate_replica(r["replica_id"], drain=False)
        self._pool.shutdown(wait=True)

    # -- probing -----------------------------------------------------------
    def probe_all(self) -> None:
        for r in serve_state.list_replicas(self.service):
            if r["status"] in (ReplicaStatus.PROVISIONING,
                               ReplicaStatus.DRAINING,
                               ReplicaStatus.SHUTTING_DOWN,
                               ReplicaStatus.SHUTDOWN,
                               ReplicaStatus.FAILED):
                continue
            rid = r["replica_id"]
            if self._cluster_gone(r["cluster_name"]):
                # Slice preempted: replace the replica entirely. Under
                # a mixed-fleet autoscaler the controller decides the
                # replacement's type instead (on-demand backfill).
                serve_state.set_replica_status(self.service, rid,
                                               ReplicaStatus.PREEMPTED)
                self._terminate_replica(rid, drain=False)
                if self.auto_replace:
                    self._launch_replica(
                        use_spot=r.get("is_spot") or None)
                continue
            ok = self._probe_one(r)
            REPLICA_PROBE_OK.labels(service=self.service,
                                    replica=str(rid)).set(1 if ok else 0)
            if ok:
                REPLICA_PROBE_OK_TS.labels(
                    service=self.service, replica=str(rid)).set(
                        time.time())
                self._probe_failures[rid] = 0
                if r["status"] != ReplicaStatus.READY:
                    serve_state.set_replica_status(self.service, rid,
                                                   ReplicaStatus.READY)
            else:
                PROBE_FAILURES.labels(service=self.service).inc()
                # STARTING grace period: initial_delay before failures count.
                if r["status"] == ReplicaStatus.STARTING and \
                        time.time() - r["launched_at"] < \
                        self.spec.initial_delay_seconds:
                    continue
                n = self._probe_failures.get(rid, 0) + 1
                self._probe_failures[rid] = n
                if n >= PROBE_FAILURES_BEFORE_NOT_READY and \
                        r["status"] == ReplicaStatus.READY:
                    serve_state.set_replica_status(self.service, rid,
                                                   ReplicaStatus.NOT_READY)

    def _probe_one(self, r: dict) -> bool:
        if not r["url"]:
            return False
        url = r["url"] + self.spec.readiness_path
        try:
            # Inside the any-error-is-not-ready classification: an
            # injected fault counts as exactly one failed probe.
            chaos.point("serve.probe", service=self.service,
                        replica=str(r["replica_id"]))
            data = (self.spec.post_data.encode()
                    if self.spec.post_data else None)
            req = urllib.request.Request(url, data=data)
            with urllib.request.urlopen(
                    req, timeout=self.spec.readiness_timeout_seconds) as resp:
                return 200 <= resp.status < 300
        except Exception:  # noqa: BLE001 — any probe error = not ready
            return False

    def _cluster_gone(self, cluster_name: str) -> bool:
        from skypilot_tpu import provision
        rec = cluster_state.get_cluster(cluster_name)
        if rec is None:
            return True
        try:
            return provision.query_instances(
                rec["handle"]["provider"], cluster_name,
                rec["handle"]["zone"]) == "NOT_FOUND"
        except exceptions.SkyTpuError:
            return True
