"""Serve state DB (sqlite): services, replicas, request stats.

Reference parity: sky/serve/serve_state.py. The request-stat table
doubles as the LB -> controller sync channel (the reference uses an HTTP
endpoint, serve/controller.py:103; a shared DB removes a failure mode on
the co-located controller VM and stays testable).
"""

from __future__ import annotations

import contextlib
import enum
import json
import os
import sqlite3
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu.utils import db, paths


class ServiceStatus(enum.Enum):
    CONTROLLER_INIT = "CONTROLLER_INIT"
    REPLICA_INIT = "REPLICA_INIT"
    READY = "READY"
    SHUTTING_DOWN = "SHUTTING_DOWN"
    FAILED = "FAILED"
    SHUTDOWN = "SHUTDOWN"

    def is_terminal(self) -> bool:
        return self in (ServiceStatus.FAILED, ServiceStatus.SHUTDOWN)


class ReplicaStatus(enum.Enum):
    PROVISIONING = "PROVISIONING"
    STARTING = "STARTING"
    READY = "READY"
    NOT_READY = "NOT_READY"
    # Graceful drain ahead of a rolling-update / scale-down kill: the
    # replica finishes its in-flight requests while the LB no longer
    # routes to it (ready_urls is READY-only, so the flip to DRAINING
    # is instantly unroutable — before the kill, not after).
    DRAINING = "DRAINING"
    FAILED = "FAILED"
    PREEMPTED = "PREEMPTED"
    SHUTTING_DOWN = "SHUTTING_DOWN"
    SHUTDOWN = "SHUTDOWN"


_SCHEMA = """
CREATE TABLE IF NOT EXISTS services (
    name TEXT PRIMARY KEY,
    spec TEXT,
    task_config TEXT,
    status TEXT,
    controller_pid INTEGER,
    lb_port INTEGER,
    created_at REAL,
    version INTEGER DEFAULT 1
);
CREATE TABLE IF NOT EXISTS replicas (
    service TEXT,
    replica_id INTEGER,
    cluster_name TEXT,
    status TEXT,
    url TEXT,
    launched_at REAL,
    version INTEGER DEFAULT 1,
    is_spot INTEGER DEFAULT 0,
    tier TEXT DEFAULT '',
    PRIMARY KEY (service, replica_id)
);
CREATE TABLE IF NOT EXISTS lb_requests (
    service TEXT,
    ts REAL
);
"""


def _db_path() -> str:
    return os.path.join(paths.home(), "serve.db")


# Columns added after the first release: existing DBs need explicit
# idempotent ALTERs (CREATE TABLE IF NOT EXISTS won't add them).
_MIGRATIONS = (
    "ALTER TABLE services ADD COLUMN version INTEGER DEFAULT 1",
    "ALTER TABLE replicas ADD COLUMN version INTEGER DEFAULT 1",
    "ALTER TABLE replicas ADD COLUMN is_spot INTEGER DEFAULT 0",
    "ALTER TABLE replicas ADD COLUMN tier TEXT DEFAULT ''",
)


@contextlib.contextmanager
def _db():
    conn = db.connect(_db_path(), timeout=10)
    conn.executescript(_SCHEMA)
    for mig in _MIGRATIONS:
        try:
            conn.execute(mig)
        except sqlite3.OperationalError:
            pass  # column already exists
    try:
        yield conn
        conn.commit()
    finally:
        conn.close()


# -- services ---------------------------------------------------------------

def add_service(name: str, spec: Dict[str, Any], task_config: Dict[str, Any],
                lb_port: int) -> None:
    with _db() as c:
        c.execute(
            "INSERT INTO services (name, spec, task_config, status, lb_port,"
            " created_at) VALUES (?,?,?,?,?,?)",
            (name, json.dumps(spec), json.dumps(task_config),
             ServiceStatus.CONTROLLER_INIT.value, lb_port, time.time()))


def update_service(name: str, spec: Dict[str, Any],
                   task_config: Dict[str, Any]) -> int:
    """Record a new service version (rolling update, reference:
    sky/serve/serve_utils.py version machinery). Returns the version."""
    with _db() as c:
        c.execute(
            "UPDATE services SET spec=?, task_config=?,"
            " version=version+1 WHERE name=?",
            (json.dumps(spec), json.dumps(task_config), name))
        row = c.execute("SELECT version FROM services WHERE name=?",
                        (name,)).fetchone()
    if row is None:
        raise KeyError(f"no service {name!r}")
    return int(row[0])


def set_service_status(name: str, status: ServiceStatus) -> None:
    with _db() as c:
        c.execute("UPDATE services SET status=? WHERE name=?",
                  (status.value, name))


def set_controller_pid(name: str, pid: int) -> None:
    with _db() as c:
        c.execute("UPDATE services SET controller_pid=? WHERE name=?",
                  (pid, name))


def get_service(name: str) -> Optional[Dict[str, Any]]:
    with _db() as c:
        row = c.execute(
            "SELECT name, spec, task_config, status, controller_pid, lb_port,"
            " created_at, version FROM services WHERE name=?",
            (name,)).fetchone()
    if row is None:
        return None
    return {"name": row[0], "spec": json.loads(row[1]),
            "task_config": json.loads(row[2]),
            "status": ServiceStatus(row[3]), "controller_pid": row[4],
            "lb_port": row[5], "created_at": row[6], "version": row[7]}


def list_services() -> List[Dict[str, Any]]:
    with _db() as c:
        names = [r[0] for r in c.execute("SELECT name FROM services")]
    return [s for n in names if (s := get_service(n)) is not None]


def remove_service(name: str) -> None:
    with _db() as c:
        c.execute("DELETE FROM services WHERE name=?", (name,))
        c.execute("DELETE FROM replicas WHERE service=?", (name,))
        c.execute("DELETE FROM lb_requests WHERE service=?", (name,))


# -- replicas ---------------------------------------------------------------

def upsert_replica(service: str, replica_id: int, cluster_name: str,
                   status: ReplicaStatus, url: Optional[str],
                   version: int = 1, is_spot: bool = False,
                   tier: str = "") -> None:
    with _db() as c:
        c.execute(
            "INSERT INTO replicas (service, replica_id, cluster_name,"
            " status, url, launched_at, version, is_spot, tier)"
            " VALUES (?,?,?,?,?,?,?,?,?)"
            " ON CONFLICT(service, replica_id) DO UPDATE SET"
            " cluster_name=excluded.cluster_name, status=excluded.status,"
            " url=excluded.url, version=excluded.version,"
            " is_spot=excluded.is_spot, tier=excluded.tier",
            (service, replica_id, cluster_name, status.value, url,
             time.time(), version, int(is_spot), tier or ""))


def set_replica_status(service: str, replica_id: int,
                       status: ReplicaStatus) -> None:
    with _db() as c:
        c.execute("UPDATE replicas SET status=? WHERE service=? AND"
                  " replica_id=?", (status.value, service, replica_id))


def remove_replica(service: str, replica_id: int) -> None:
    with _db() as c:
        c.execute("DELETE FROM replicas WHERE service=? AND replica_id=?",
                  (service, replica_id))


def list_replicas(service: str) -> List[Dict[str, Any]]:
    with _db() as c:
        rows = c.execute(
            "SELECT replica_id, cluster_name, status, url, launched_at,"
            " version, is_spot, tier FROM replicas WHERE service=?"
            " ORDER BY replica_id",
            (service,)).fetchall()
    return [{"replica_id": r[0], "cluster_name": r[1],
             "status": ReplicaStatus(r[2]), "url": r[3],
             "launched_at": r[4], "version": r[5],
             "is_spot": bool(r[6]), "tier": r[7] or ""} for r in rows]


def ready_urls(service: str, tier: Optional[str] = None) -> List[str]:
    """READY replica URLs; ``tier`` filters to one disaggregation tier
    ("prefill"/"decode"). None returns every tier — the single-tier
    path and the disagg fallback both route over the whole fleet."""
    return [r["url"] for r in list_replicas(service)
            if r["status"] == ReplicaStatus.READY and r["url"]
            and (tier is None or r["tier"] == tier)]


# -- request stats (LB -> autoscaler channel) -------------------------------

def record_request(service: str) -> None:
    with _db() as c:
        c.execute("INSERT INTO lb_requests (service, ts) VALUES (?,?)",
                  (service, time.time()))


def qps(service: str, window_seconds: float = 30.0) -> float:
    cutoff = time.time() - window_seconds
    with _db() as c:
        n = c.execute("SELECT COUNT(*) FROM lb_requests WHERE service=?"
                      " AND ts>?", (service, cutoff)).fetchone()[0]
        c.execute("DELETE FROM lb_requests WHERE service=? AND ts<=?",
                  (service, cutoff))
    return n / window_seconds
